"""Scheduler-extender HTTP sidecar: the integration seam into a real
kube-scheduler.

Implements the reference's extender wire contract verbatim so an unmodified
kube-scheduler with `--policy-config-file` pointing at an ExtenderConfig
(api/types.go:129) offloads findNodesThatFit / PrioritizeNodes here
(generic_scheduler.go:211-228,381-399 -> core/extender.go:100 Filter,
:157 Prioritize, :199 Bind, :226 send):

  POST {prefix}/filter      ExtenderArgs -> ExtenderFilterResult
  POST {prefix}/prioritize  ExtenderArgs -> HostPriorityList
  POST {prefix}/bind        ExtenderBindingArgs -> ExtenderBindingResult
  GET  /healthz, /metrics
  GET  /debug/vars          unified telemetry-registry snapshot (ISSUE 13
                            — identical content to the binary STATS verb
                            and the embedded debug_snapshot)
  GET  /debug/trace?last=N  the flight recorder's event tail
  GET  /debug/pods          pod-level black box (ISSUE 15): the tracer's
                            per-window critical-path aggregate + the
                            slowest-K tail-exemplar timelines
  GET  /debug/slo           the SLO engine's burn-rate/alert snapshot

Trace context (ISSUE 15): a POST /filter or /bind carrying an
``X-Pod-Trace: <id>`` header stamps one WIRE_HOP on that pod's podtrace
timeline — the HTTP twin of the binary wire's FLAG_TRACE field and the
embedded API's ``trace_ctx=``; header presence IS the sample decision.

JSON keys: the reference posts the *internal* structs (no json tags ->
capitalized keys: "Pod", "Nodes", "NodeNames"); Go's json.Unmarshal is
case-insensitive, so we accept either case and respond capitalized.

nodeCacheCapable mode (extender.go:113-124): only candidate node NAMES cross
the wire; the sidecar keeps full node/pod state in its own cache, synced via
the bulk endpoints POST /cache/nodes and /cache/pods (the "snapshot POSTs"
variant of SURVEY.md §7 step 3) and updated optimistically by bind calls.

Multi-frontend service (ISSUE 9) — the same verbs, hardened for a FLEET of
concurrent schedulers sharing one sidecar:

  - COALESCED DISPATCH: concurrent /filter + /prioritize evaluations ride
    a micro-batch window (server/coalescer.py) into ONE fused [C, N]
    kernel dispatch over the shared device-resident snapshot.
  - OPTIMISTIC CONCURRENCY (PAPERS.md §Omega): verdicts carry a
    "SnapshotGen"; each frontend evaluates against a possibly-stale
    snapshot (bounded by ``stale_window_s``) and /bind commits through a
    FENCE that re-validates capacity/ports/liveness/topology against
    current cache truth, answering a typed HTTP 409 CONFLICT (body carries
    "RetryAfterMs") the client retries with jittered backoff.
  - EXACTLY-ONCE BINDS: /bind accepts an "IdempotencyKey"; a timed-out-
    but-landed bind replays safely through the BindLedger (state/cache.py)
    — the retry converges on the recorded node instead of double-booking.
  - BACKPRESSURE: bounded coalescer queue + per-verb in-flight cap answer
    HTTP 429 + Retry-After past the dispatch budget; a request whose
    client deadline ("DeadlineMs") elapsed while queued is shed (504).

Optional request fields (ignored by a stock kube-scheduler, used by our
multi-frontend clients): /filter {"Compact": true} elides the echo of an
all-passed candidate list; /prioritize {"TopK": k} returns only the k
top-scored hosts (still a valid HostPriorityList); /bind {"SnapshotGen",
"IdempotencyKey", "DeadlineMs", "Pod": <spec>} — shipping the spec lets
the fence do exact capacity math instead of the identifiers-only wire's
zero-resource assume.
"""

from __future__ import annotations

import json
import random
import threading
from kubernetes_tpu.analysis import lockcheck
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Protocol, Tuple

from kubernetes_tpu.api import serde
from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.server.coalescer import (
    DeadlineExceeded,
    EvalCoalescer,
    Overloaded,
)


class ExtenderBackend(Protocol):
    def filter(self, pod: Pod, nodes: Optional[List[Node]],
               node_names: Optional[List[str]]
               ) -> Tuple[List[str], Dict[str, str]]: ...

    def prioritize(self, pod: Pod, nodes: Optional[List[Node]],
                   node_names: Optional[List[str]]
                   ) -> List[Tuple[str, int]]: ...

    def bind(self, pod_name: str, pod_namespace: str, pod_uid: str,
             node: str) -> str: ...

    def sync_nodes(self, nodes: List[Node]) -> None: ...

    def sync_pods(self, pods: List[Pod]) -> None: ...

    def metrics_text(self) -> str: ...


class _FleetHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer tuned for a fleet of keep-alive frontends
    (ISSUE 9 satellite): the stock accept backlog of 5 refuses connections
    the moment ~100 clients dial in together, and a non-daemon handler
    thread wedged on a dead client would block shutdown."""

    request_queue_size = 256
    daemon_threads = True


class ExtenderHTTPServer:
    def __init__(self, backend: ExtenderBackend, host: str = "127.0.0.1",
                 port: int = 0, prefix: str = "", max_inflight: int = 256):
        self.backend = backend
        self.prefix = prefix.rstrip("/")
        # per-verb in-flight admission (the HTTP half of the backpressure
        # story; the coalescer bounds its own queue below this)
        self.max_inflight = max_inflight
        self._inflight = 0
        self._adm_lock = lockcheck.make_lock("ExtenderHTTPServer._adm_lock")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # a dead client's half-open socket must not pin a handler
            # thread forever (daemon_threads bounds shutdown, this bounds
            # the thread count)
            timeout = 120

            def log_message(self, *a):  # quiet
                pass

            def _read_raw(self):
                length = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(length) if length else b""

            def _write_json(self, obj, code: int = 200, headers=None):
                # compact separators: a 5k-node HostPriorityList is ~230KB
                # of response; the default ", " padding costs measurable
                # serialize+wire time at compat-mode request rates
                body = json.dumps(obj, separators=(",", ":")).encode()
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    for k, v in (headers or {}).items():
                        self.send_header(k, v)
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    # the client gave up (its timeout elapsed) — a fleet
                    # norm, not a server error: drop the socket quietly
                    # instead of letting ThreadingHTTPServer print a
                    # traceback per dead peer
                    self.close_connection = True

            def do_GET(self):
                if self.path == "/healthz":
                    body = b"ok"
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/metrics":
                    body = outer.backend.metrics_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/debug/vars":
                    # live introspection (ISSUE 13): the unified registry
                    # snapshot — identical content to the binary STATS
                    # verb and the embedded debug_snapshot, test-pinned
                    dv = getattr(outer.backend, "debug_vars", None)
                    if dv is None:
                        self._write_json({"error": "not found"}, 404)
                    else:
                        self._write_json(dv())
                elif self.path.split("?", 1)[0] == "/debug/trace":
                    dt = getattr(outer.backend, "debug_trace", None)
                    if dt is None:
                        self._write_json({"error": "not found"}, 404)
                    else:
                        from urllib.parse import parse_qs, urlsplit
                        q = parse_qs(urlsplit(self.path).query)
                        try:
                            # absent param -> a BOUNDED default tail (a
                            # full 65k-event ring is a multi-MB body);
                            # an explicit last (0 included) means
                            # exactly what it means on the other
                            # transports
                            last = int(q.get("last", ["256"])[0])
                        except ValueError:
                            last = 256
                        self._write_json(dt(last))
                elif self.path == "/debug/pods":
                    # pod-level black box (ISSUE 15) — identical content
                    # to the binary STATS verb's "pods" key and the
                    # embedded debug_snapshot, test-pinned
                    dp = getattr(outer.backend, "debug_pods", None)
                    if dp is None:
                        self._write_json({"error": "not found"}, 404)
                    else:
                        self._write_json(dp())
                elif self.path == "/debug/slo":
                    ds = getattr(outer.backend, "debug_slo", None)
                    if ds is None:
                        self._write_json({"error": "not found"}, 404)
                    else:
                        self._write_json(ds())
                else:
                    self._write_json({"error": "not found"}, 404)

            def do_POST(self):
                path = self.path
                if outer.prefix and path.startswith(outer.prefix):
                    path = path[len(outer.prefix):]
                # read the body FIRST, unconditionally: on a keep-alive
                # connection an unread body (unknown path, early error)
                # would desync every later request on the socket — the
                # head-of-line audit of the ISSUE 9 satellite
                raw = self._read_raw()
                try:
                    if path in ("/cache/nodes", "/cache/pods"):
                        # bulk sync: binary fast path (protobuf, SURVEY
                        # §5.8 — the --kube-api-content-type analog) or
                        # the JSON contract, picked by Content-Type
                        from kubernetes_tpu.api import protowire
                        ctype = self.headers.get("Content-Type", "")
                        is_nodes = path == "/cache/nodes"
                        if ctype == protowire.CONTENT_TYPE:
                            if not protowire.available():
                                # negotiable failure: tell the client to
                                # fall back to the JSON contract
                                self._write_json(
                                    {"Error": "protobuf unavailable; use "
                                     "application/json"}, 415)
                                return
                            items = (protowire.decode_nodes(raw) if is_nodes
                                     else protowire.decode_pods(raw))
                        else:
                            raw_items = json.loads(raw or b"{}").get(
                                "items", [])
                            items = [(serde.decode_node(o) if is_nodes
                                      else serde.decode_pod(o))
                                     for o in raw_items]
                        if is_nodes:
                            outer.backend.sync_nodes(items)
                        else:
                            outer.backend.sync_pods(items)
                        self._write_json({"synced": len(items)})
                        return
                    if path not in ("/filter", "/prioritize", "/bind"):
                        self._write_json(
                            {"error": f"unknown path {self.path}"}, 404)
                        return
                    if not outer._admit():
                        # jittered Retry-After: a fleet shed together must
                        # not return together (thundering-herd starvation
                        # of the same unlucky clients every window)
                        self._write_json(
                            {"Error": "overloaded",
                             "RetryAfterMs": random.randint(10, 80)},
                            429, headers={"Retry-After": "1"})
                        return
                    tid = self.headers.get("X-Pod-Trace")
                    if tid and path in ("/filter", "/bind"):
                        # trace-context hop (ISSUE 15): header presence
                        # is the client's head decision — honor it
                        from kubernetes_tpu.observability import podtrace
                        if podtrace.TRACER.enabled:
                            podtrace.TRACER.wire_hop(
                                tid, podtrace.WIRE_HTTP,
                                podtrace.HOP_FILTER if path == "/filter"
                                else podtrace.HOP_BIND)
                    try:
                        payload = json.loads(raw or b"{}")
                        if path == "/filter":
                            out, code = outer.handle_filter(payload), 200
                        elif path == "/prioritize":
                            out, code = outer.handle_prioritize(payload), 200
                        else:
                            out, code = outer.handle_bind(payload)
                            if tid and code == 200 \
                                    and not out.get("Error"):
                                # complete the wire-path trace: the
                                # sidecar has no scheduler bind path to
                                # terminate the timeline (embedded.py
                                # trace_bound docstring)
                                from kubernetes_tpu.server.embedded \
                                    import VerdictService
                                VerdictService.trace_bound(tid)
                        self._write_json(out, code)
                    finally:
                        outer._release()
                except Overloaded as e:
                    self._write_json(
                        {"Error": "overloaded",
                         "RetryAfterMs": int(e.retry_after_s * 1e3)},
                        429, headers={"Retry-After": "1"})
                except DeadlineExceeded:
                    self._write_json({"Error": "DEADLINE_EXCEEDED"}, 504)
                except Exception as e:  # wire errors surface in-band, like the
                    # reference's ExtenderFilterResult.Error (types.go:177)
                    self._write_json({"Error": f"{type(e).__name__}: {e}"}, 500)

        self.httpd = _FleetHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None
        # transport-agnostic service core (ISSUE 11): the verdict-capable
        # paths below delegate here, the SAME core the async binary wire
        # (server/asyncwire.py) and the embedded mode (server/embedded.py)
        # serve — no transport owns a semantic. Local import: embedded.py
        # imports this module for TPUExtenderBackend.
        self.service = None
        if getattr(backend, "fused_verdict", None) is not None \
                and getattr(backend, "filter_verdict", None) is not None:
            from kubernetes_tpu.server.embedded import VerdictService
            self.service = VerdictService(backend)

    # ------------------------------------------------------- admission gate

    def _admit(self) -> bool:
        with self._adm_lock:
            if self._inflight >= self.max_inflight:
                count = getattr(self.backend, "_count", None)
                if count is not None:
                    count("admission_shed")
                return False
            self._inflight += 1
            return True

    def _release(self) -> None:
        with self._adm_lock:
            self._inflight -= 1

    # -------------------------------------------------------------- handlers

    @staticmethod
    def _get(payload: Dict, *names):
        for n in names:
            if n in payload:
                return payload[n]
        return None

    def _parse_args(self, payload: Dict) -> Tuple[Pod, Optional[List[Node]],
                                                  Optional[List[str]]]:
        pod_obj = self._get(payload, "Pod", "pod") or {}
        pod = serde.decode_pod(pod_obj)
        nodes_obj = self._get(payload, "Nodes", "nodes")
        nodes = None
        if nodes_obj:
            nodes = [serde.decode_node(n)
                     for n in (nodes_obj.get("Items")
                               or nodes_obj.get("items") or [])]
        names = self._get(payload, "NodeNames", "nodenames", "nodeNames")
        return pod, nodes, names

    @staticmethod
    def _deadline_of(payload: Dict) -> Optional[float]:
        ms = payload.get("DeadlineMs")
        return float(ms) / 1e3 if ms else None

    def handle_filter(self, payload: Dict) -> Dict:
        pod, nodes, names = self._parse_args(payload)
        top_k = int(payload.get("TopK") or 0)
        if self.service is None or nodes is not None:
            passed, failed = self.backend.filter(pod, nodes, names)
            if nodes is not None:
                by_name = {n.name: n for n in nodes}
                return {
                    "Nodes": {"Items": [serde.encode_node(by_name[nm])
                                        for nm in passed if nm in by_name]},
                    "FailedNodes": failed,
                    "Error": "",
                }
            return {"NodeNames": passed, "FailedNodes": failed, "Error": ""}
        # verdict-capable cache mode: ONE service-core call answers the
        # verb (and, with TopK, the fused top scores of the same window
        # ticket — a fleet scheduleOne skips /prioritize entirely); this
        # JSON shaping is all that stays transport-specific
        v = self.service.filter(
            pod, node_names=names, top_k=top_k,
            deadline_s=self._deadline_of(payload),
            compact=bool(payload.get("Compact")))
        out = {"NodeNames": v.passed, "FailedNodes": v.failed, "Error": ""}
        if v.snapshot_gen is not None:
            out["SnapshotGen"] = v.snapshot_gen
        if v.top_scores is not None:
            out["TopScores"] = [{"Host": h, "Score": int(s)}
                                for h, s in v.top_scores]
        if v.passed is None:
            # multi-frontend compact mode: the echo of an all-passed 5k-
            # name candidate list costs more wire time than the verdict —
            # "everything passed" is one bit + a count
            out["AllPassed"] = True
            out["PassedCount"] = v.passed_count
        return out

    def handle_prioritize(self, payload: Dict) -> List[Dict]:
        pod, nodes, names = self._parse_args(payload)
        top_k = int(payload.get("TopK") or 0)
        pv = getattr(self.backend, "prioritize_verdict", None)
        if pv is None or nodes is not None:
            scores = self.backend.prioritize(pod, nodes, names)
        else:
            # TopK resolves server-side, vectorized (prioritize_verdict):
            # truncation stays a valid HostPriorityList; our frontends
            # pick among the max-score entries, so shipping the tail is
            # pure wire cost (PAPERS.md §Sparrow: sample, don't census)
            scores, _gen = pv(
                pod, names, deadline_s=self._deadline_of(payload),
                top_k=top_k if names is None else 0)
        if top_k and len(scores) > top_k:
            import heapq
            scores = heapq.nlargest(top_k, scores, key=lambda e: e[1])
        return [{"Host": h, "Score": int(s)} for h, s in scores]

    def handle_bind(self, payload: Dict) -> Tuple[Dict, int]:
        pod_name = self._get(payload, "PodName", "podName") or ""
        pod_ns = self._get(payload, "PodNamespace", "podNamespace") or ""
        pod_uid = str(self._get(payload, "PodUID", "podUID") or "")
        node = self._get(payload, "Node", "node") or ""
        if self.service is None \
                or getattr(self.backend, "bind_verdict", None) is None:
            return {"Error": self.backend.bind(
                pod_name, pod_ns, pod_uid, node)}, 200
        spec_obj = self._get(payload, "Pod", "pod")
        spec = serde.decode_pod(spec_obj) if spec_obj else None
        gen = payload.get("SnapshotGen")
        res = self.service.bind(
            pod_name, pod_ns, pod_uid, node,
            snapshot_gen=int(gen) if gen is not None else None,
            idem_key=payload.get("IdempotencyKey") or None,
            deadline_s=self._deadline_of(payload), pod=spec)
        out: Dict = {"Error": res.error}
        if res.retryable:
            out["Conflict"] = True
            out["RetryAfterMs"] = max(int(res.retry_after_s * 1e3), 1)
            return out, 409
        if res.kind == "shed":
            return out, 504
        return out, 200

    # ------------------------------------------------------------- lifecycle

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)


class _Verdict:
    """One pod's evaluation against the shared snapshot, captured with the
    node order / index / generation of the SAME critical section — so the
    HTTP response builds outside every lock without torn state."""

    __slots__ = ("m", "s", "names", "idx", "gen")

    def __init__(self, m, s, names, idx, gen):
        self.m = m
        self.s = s
        self.names = names
        self.idx = idx
        self.gen = gen


class TPUExtenderBackend:
    """The TPU-offload backend: sidecar-owned SchedulerCache + fused kernels.

    Filter/prioritize evaluate the pod against the sidecar's cached cluster
    state (or against the Nodes shipped in the args when not cache-capable),
    restricted to the candidate set the scheduler sent — exactly the
    contract of extender.go:100-198. Bind assumes into the local cache and
    delegates the apiserver write to `binder` (None = extender not configured
    with BindVerb).

    Warm fast lane (the cache-capable path): cluster state lives DEVICE-
    resident between requests. The backend owns its SchedulerCache
    exclusively — every mutation arrives through sync_nodes / sync_pods /
    bind — so it tracks staleness itself instead of re-deriving it per
    request:

      - sync_* marks a FULL refresh (membership/spec may have moved) and
        invalidates the EvalCache (on_sync);
      - bind marks a TARGETED refresh of just the bound node
        (snapshot.refresh changed_hint — one dynamic row, not an N-node
        generation walk);
      - a request with nothing dirty touches no cluster state at all: the
        snapshot, the uploaded node arrays, the encoded classes and the
        (fits, scores) result memo are all valid, so /prioritize after
        /filter is a dict hit.

    Node arrays ride SchedulingEngine._nodes_on_device (incremental
    dirty-only host->HBM sync), so a bind re-uploads three small dynamic
    arrays, not the 40MB+ snapshot."""

    def __init__(self, binder=None, stale_window_s: float = 0.0,
                 coalesce_window_s: float = 0.0, coalesce_max_batch: int = 64,
                 coalesce_max_depth: int = 512):
        # jax-dependent imports are local so the wire layer stays importable
        # without a TPU runtime
        from kubernetes_tpu.state.cache import BindLedger, SchedulerCache
        from kubernetes_tpu.engine.scheduler_engine import (
            EvalCache,
            SchedulingEngine,
        )
        from kubernetes_tpu.utils.metrics import SchedulerMetrics

        self.cache = SchedulerCache()
        self.engine = SchedulingEngine(self.cache)
        self.metrics = SchedulerMetrics()
        self.binder = binder
        self._known_pods: Dict[str, Pod] = {}
        # per-request amortization + vocab-growth isolation (EvalCache
        # docstring; the reference amortizes the same work through its
        # scheduler cache + equivalence LRU)
        self.eval_cache = EvalCache()
        # staleness ledger for the warm lane (class docstring); guarded by
        # _lock — ThreadingHTTPServer serves each request on its own thread
        self._lock = lockcheck.make_rlock("TPUExtenderBackend._lock")
        self._state_dirty = True          # full refresh needed
        self._bind_hint: set = set()      # targeted refresh of these nodes
        self._infos = None                # cached node_infos() view
        self._aff_pod_count = 0           # cached pods carrying pod affinity
        # pods assumed by bind BEFORE any sync shipped their spec: /bind
        # carries only identifiers, so their accounting is spec-less until
        # the bulk cache sync delivers the real object (and replaces it)
        self._assumed_bare: Dict[str, Pod] = {}
        self._last_cleanup = 0.0
        self.eval_cache.cluster_aff_free = True
        # ---- multi-frontend service state (ISSUE 9) ----
        # Omega-style bounded staleness: within this window, bind-hinted
        # snapshot refreshes are DEFERRED, so verdicts serve from the memo
        # while commits advance — the bind fence re-validates every commit
        # against live cache truth, so staleness costs conflicts (reported),
        # never correctness. 0.0 = always fresh (the PR 1-8 behavior).
        self.stale_window_s = stale_window_s
        self._last_refresh = 0.0
        # commit_gen: bumped per committed mutation (bind assume/rollback,
        # bulk sync). _snap_gen: the commit_gen the snapshot reflects —
        # what verdicts report as "SnapshotGen"; a /bind whose verdict gen
        # equals the CURRENT commit_gen provably re-validated nothing away
        # and may skip the fence.
        self.commit_gen = 0
        self._snap_gen = 0
        self.ledger = BindLedger()
        # service counters: own lock, so /metrics scrapes and coalescer
        # increments never contend with (or tear against) the eval lock —
        # the ISSUE 9 torn-read audit
        self._counters_lock = lockcheck.make_lock("TPUExtenderBackend._counters_lock")
        self._counters: Dict[str, int] = {}
        self._rng = random.Random(0xB19D)
        self.coalescer = EvalCoalescer(self, window_s=coalesce_window_s,
                                       max_batch=coalesce_max_batch,
                                       max_depth=coalesce_max_depth)
        # unified telemetry registry (ISSUE 13): the ONE namespace every
        # introspection transport serves — HTTP /debug/vars, the binary
        # STATS verb, VerdictService.debug_snapshot and /metrics all read
        # THIS (transport parity is a dict equality, test-pinned). Each
        # source snapshots under its own lock, in sequence, never nested
        # — the r12 torn-read discipline carried over.
        from kubernetes_tpu.observability.registry import TelemetryRegistry
        self.telemetry = TelemetryRegistry()
        self.telemetry.register_metrics("extender", self.metrics)
        self.telemetry.register_counters("extender", self._counters_snapshot,
                                         prom_prefix="tpu_extender")
        self.telemetry.register_gauges("extender", self._gen_gauges)

    def _count(self, name: str, n: int = 1) -> None:
        with self._counters_lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def _counters_snapshot(self) -> Dict[str, int]:
        with self._counters_lock:
            return dict(self._counters)

    def _gen_gauges(self) -> Dict[str, int]:
        with self._lock:
            return {"tpu_extender_commit_gen": self.commit_gen,
                    "tpu_extender_snapshot_gen": self._snap_gen}

    def debug_vars(self) -> Dict:
        """The registry snapshot /debug/vars (and every other transport)
        serves."""
        return self.telemetry.snapshot()

    def debug_trace(self, last: int = 0):
        """The flight recorder's event tail for /debug/trace?last=N.
        ``last <= 0`` returns NOTHING — identical semantics on every
        transport (binary STATS, embedded debug_snapshot), so parity
        holds for every literal ``last`` value; a full-ring dump is an
        explicit ``last >= recorder.capacity`` (the capacity travels in
        /debug/vars as ``recorder.capacity``)."""
        from kubernetes_tpu.observability.recorder import RECORDER
        return RECORDER.snapshot(last) if last > 0 else []

    def debug_pods(self):
        """The pod tracer's /debug/pods payload (ISSUE 15) — per-window
        critical-path aggregate + slowest-K exemplar timelines,
        identical on every transport."""
        from kubernetes_tpu.observability.podtrace import TRACER
        return TRACER.snapshot()

    def debug_slo(self):
        """The SLO engine's /debug/slo payload (ISSUE 15), identical on
        every transport. The fast tier's 10 ms objective (ISSUE 17)
        rides under the "fast" key so both tiers land in one scrape."""
        from kubernetes_tpu.observability.slo import SLO, SLO_FAST
        return {**SLO.snapshot(), "fast": SLO_FAST.snapshot()}

    # -- cache sync ---------------------------------------------------------

    # assumed-pod TTL sweep cadence: the sidecar has no informer confirm
    # loop — the bulk cache sync IS the confirmation — so a bind whose pod
    # never reappears in a sync (deleted at the apiserver, write lost)
    # must expire via the cache's own TTL or its phantom pod_count/capacity
    # leaks for the process lifetime
    CLEANUP_INTERVAL_S = 5.0

    def _maybe_cleanup_assumed_locked(self) -> None:
        """Time-gated cleanup_assumed (cache.go:355 analog) — called with
        the lock held from the sync/refresh paths."""
        lockcheck.assert_held(self._lock, "_maybe_cleanup_assumed_locked")
        import time as _time
        now = _time.monotonic()
        if now - self._last_cleanup < self.CLEANUP_INTERVAL_S:
            return
        self._last_cleanup = now
        expired = self.cache.cleanup_assumed()
        if expired:
            for k in expired:
                self._assumed_bare.pop(k, None)
            self._state_dirty = True  # released capacity: full re-walk
            # cache truth moved like any other mutation: a verdict issued
            # before the expiry must NOT satisfy the fence-skip gen check
            # against the post-expiry state
            self.commit_gen += 1

    def sync_nodes(self, nodes: List[Node]) -> None:
        with self._lock:
            self.eval_cache.on_sync()
            self._state_dirty = True
            self.commit_gen += 1
            self._bind_hint.clear()
            self._maybe_cleanup_assumed_locked()
            seen = set()
            for n in nodes:
                self.cache.update_node(n)
                seen.add(n.name)
            removed = False
            for name in list(self.cache.node_infos().keys()):
                if name not in seen:
                    self.cache.remove_node(name)
                    removed = True
            if removed:
                # the sidecar's sync is a wholesale reconcile that already
                # escalates to a full refresh — compact the ISSUE 8
                # tombstones right away instead of accruing dead rows
                self.cache.purge_tombstones()

    def sync_pods(self, pods: List[Pod]) -> None:
        from kubernetes_tpu.ops.affinity import _has_affinity
        with self._lock:
            self.eval_cache.on_sync()
            self._state_dirty = True
            self.commit_gen += 1
            self._bind_hint.clear()
            self._maybe_cleanup_assumed_locked()
            seen = set()
            for p in pods:
                if not p.node_name:
                    continue
                seen.add(p.key())
                prev = self._known_pods.get(p.key())
                if prev is None:
                    bare = self._assumed_bare.pop(p.key(), None)
                    if bare is not None:
                        # bind assumed this pod WITHOUT its spec (wire
                        # carries identifiers only): swap the spec-less
                        # accounting for the real object — the confirm
                        # path alone would keep the zero-resource rows
                        self.cache.remove_pod(bare)
                    self.cache.add_pod(p)
                else:
                    self.cache.update_pod(prev, p)
                self._known_pods[p.key()] = p
            # full-state semantics, like sync_nodes: pods absent from the
            # snapshot were deleted — release their capacity
            for key in list(self._known_pods):
                if key not in seen:
                    self.cache.remove_pod(self._known_pods.pop(key))
            self._aff_pod_count = sum(
                1 for p in self._known_pods.values() if _has_affinity(p))
            self.eval_cache.cluster_aff_free = self._aff_pod_count == 0

    # -- extender verbs -----------------------------------------------------

    def _refresh_warm_locked(self):
        """Bring the persistent snapshot up to date with the cache, paying
        only for what actually moved (class docstring). Returns the live
        infos view.

        Bounded staleness (ISSUE 9, PAPERS.md §Omega): when a stale_window
        is configured, BIND-hinted refreshes are deferred inside it —
        verdicts keep serving from the current snapshot version (memo
        hits, zero device work) while commits advance, and the bind fence
        re-validates every commit against live cache truth. Sync-driven
        dirtiness always refreshes immediately: membership/spec changes
        are not a staleness the fence is allowed to absorb."""
        lockcheck.assert_held(self._lock, "_refresh_warm_locked")
        import time as _time

        from kubernetes_tpu.utils.trace import COUNTERS, timed_span
        snap = self.engine.snapshot
        self._maybe_cleanup_assumed_locked()  # time-gated; a bind-only deployment
        # (no syncs ever) must still expire unconfirmed assumptions
        if self._state_dirty or self._infos is None:
            with timed_span("extender.refresh_full"):
                self._infos = self.cache.node_infos()
                snap.refresh(self._infos)
            self._state_dirty = False
            self._bind_hint.clear()
            self._snap_gen = self.commit_gen
            self._last_refresh = _time.monotonic()
        elif self._bind_hint:
            if self.stale_window_s > 0 and (
                    _time.monotonic() - self._last_refresh
                    < self.stale_window_s):
                COUNTERS.inc("extender.stale_served")
                return self._infos
            with timed_span("extender.refresh_hint"):
                hint = tuple(self._bind_hint)
                self._bind_hint.clear()
                snap.refresh(self._infos, changed_hint=hint)
            self._snap_gen = self.commit_gen
            self._last_refresh = _time.monotonic()
        return self._infos

    def _port_words_for(self, pod: Pod) -> int:
        from kubernetes_tpu.ops.predicates import bucket
        snap = self.engine.snapshot
        words = snap.port_words_used()
        for c in pod.containers:
            for p in c.ports:
                if p.host_port > 0:
                    words = max(words, p.host_port // 32 + 1)
        return bucket(max(words, 1), lo=1)

    def _eval_locked(self, pod: Pod, nodes: Optional[List[Node]]):
        lockcheck.assert_held(self._lock, "_eval_locked")
        from kubernetes_tpu.engine.scheduler_engine import evaluate_pod
        from kubernetes_tpu.state.snapshot import ClusterSnapshot

        if nodes is not None:
            # non-cache-capable: full node state ships in every request, so
            # evaluate against a FRESH snapshot — reusing the persistent one
            # would diff generation counters of unrelated NodeInfo objects
            # and silently serve stale rows
            from kubernetes_tpu.state.node_info import node_info_map
            infos = node_info_map(nodes, [p for p in self._known_pods.values()])
            snap = ClusterSnapshot()
            snap.refresh(infos)
            m, s = evaluate_pod(
                pod, infos, snap, self.engine.priorities,
                workloads=self.engine.workloads_provider(),
                hard_weight=self.engine.hard_pod_affinity_weight,
                volume_ctx=self.engine.volume_ctx, eval_cache=None)
            return snap, m, s
        snap = self.engine.snapshot
        infos = self._refresh_warm_locked()
        # deferred: evaluate_pod invokes this only after vocab flushes, so
        # a label-matrix rebuild can never race a stale device upload
        provider = (lambda: self.engine._nodes_on_device(
            port_words=self._port_words_for(pod)))
        m, s = evaluate_pod(
            pod, infos, snap, self.engine.priorities,
            workloads=self.engine.workloads_provider(),
            hard_weight=self.engine.hard_pod_affinity_weight,
            volume_ctx=self.engine.volume_ctx,
            eval_cache=self.eval_cache, device_nodes_provider=provider)
        return snap, m, s

    FAIL_REASON = "node(s) didn't satisfy TPU predicate kernel"

    # ---- coalescer seams (ISSUE 9): the leader evaluates whole batches
    # under ONE lock acquisition; verdict objects capture names/index/gen
    # from the same critical section so responses build outside it -------

    def _eval_many(self, pods):
        """Leader-side batch evaluation: one fused [C, N] dispatch for the
        batch's unique classes (engine.evaluate_pods_batch). Returns one
        _Verdict per pod, in order."""
        from kubernetes_tpu.engine.scheduler_engine import evaluate_pods_batch
        with self._lock:
            infos = self._refresh_warm_locked()
            snap = self.engine.snapshot
            port_words = max(self._port_words_for(p) for p in pods)
            provider = (lambda: self.engine._nodes_on_device(
                port_words=port_words))
            outs = evaluate_pods_batch(
                pods, infos, snap, self.engine.priorities,
                workloads=self.engine.workloads_provider(),
                hard_weight=self.engine.hard_pod_affinity_weight,
                volume_ctx=self.engine.volume_ctx,
                eval_cache=self.eval_cache, device_nodes_provider=provider)
            names = snap.node_names
            idx = snap.node_index
            gen = self._snap_gen
        return [_Verdict(m, s, names, idx, gen) for (m, s) in outs]

    def _eval_one(self, pod):
        """Degraded per-request fallback (coalescer fault path)."""
        with self._lock:
            snap, m, s = self._eval_locked(pod, None)
            return _Verdict(m, s, snap.node_names, snap.node_index,
                            self._snap_gen)

    def _split_passed(self, m, names, idx, node_names):
        """Shared /filter response split (verdict mask -> passed/failed)."""
        if node_names is None:
            # whole-cluster candidate set: vectorized split instead of
            # a per-name dict-lookup loop over N nodes
            import numpy as np
            mask = m[:len(names)]
            if mask.all():
                return list(names), {}
            passed = [names[i] for i in np.nonzero(mask)[0]]
            failed = {names[i]: self.FAIL_REASON
                      for i in np.nonzero(~mask)[0]}
            return passed, failed
        passed, failed = [], {}
        for nm in node_names:
            i = idx.get(nm, -1)
            if i >= 0 and m[i]:
                passed.append(nm)
            else:
                failed[nm] = self.FAIL_REASON
        return passed, failed

    def filter_verdict(self, pod, node_names=None, deadline_s=None):
        """/filter through the coalescing window: (passed, failed, gen)."""
        v = self.coalescer.submit(pod, deadline_s)
        passed, failed = self._split_passed(v.m, v.names, v.idx, node_names)
        return passed, failed, v.gen

    @staticmethod
    def _top_scores(v: "_Verdict", top_k: int):
        """Vectorized top-k (host, score) over a verdict's FITTING nodes —
        argpartition, not a 5k-tuple Python sort (at fleet request rates
        the marshalling would cost more than the evaluation)."""
        import numpy as np
        n = len(v.names)
        if not (top_k and n):
            return []
        # widen BEFORE masking: the verdict's scores are int32 on the
        # production config, and np.where(int32, int64-min) wraps the
        # sentinel to 0 — a non-fitting node would ride TopScores with
        # score 0 whenever fewer than k nodes fit, steering the frontend
        # into a guaranteed fence conflict
        s = np.asarray(v.s[:n]).astype(np.int64, copy=True)
        s[~np.asarray(v.m[:n])] = np.iinfo(np.int64).min
        k = min(int(top_k), n)
        part = np.argpartition(s, n - k)[n - k:]
        order = part[np.argsort(-s[part], kind="stable")]
        sl = s[order].tolist()
        return [(v.names[i], sl[j])
                for j, i in enumerate(order.tolist())
                if sl[j] != np.iinfo(np.int64).min]

    def fused_verdict(self, pod, node_names=None, deadline_s=None,
                      top_k: int = 0):
        """ONE coalescer submit answering both verbs (the wire mirror of
        the PR 1 fused-verb memo): (passed, failed, top_scores, gen).
        A fleet scheduleOne becomes two round trips (filter+, bind)
        instead of three, and one window ticket instead of two.
        top_scores honors the caller's candidate restriction: a fused
        verdict must never steer a frontend to a node its own scheduler
        already excluded."""
        v = self.coalescer.submit(pod, deadline_s)
        passed, failed = self._split_passed(v.m, v.names, v.idx, node_names)
        if node_names is None:
            top = self._top_scores(v, top_k)
        else:
            # restricted candidate set: rank only the PASSED subset
            sl = [(nm, int(v.s[v.idx[nm]])) for nm in passed]
            sl.sort(key=lambda e: -e[1])
            top = sl[:max(int(top_k), 0)]
        return passed, failed, top, v.gen

    def prioritize_verdict(self, pod, node_names=None, deadline_s=None,
                           top_k: int = 0):
        """/prioritize through the coalescing window: (scores, gen).
        ``top_k`` > 0 returns only the k top-scored hosts, selected
        VECTORIZED (argpartition over the score row) — at fleet request
        rates, materializing 5k (host, score) Python tuples per request
        just to pick a winner costs more than the evaluation did."""
        v = self.coalescer.submit(pod, deadline_s)
        if top_k and node_names is None:
            # whole-cluster TopK masks to FITTING nodes (the verbs are
            # fused on one verdict; a top score on a failed node would
            # send the frontend into a guaranteed fence conflict)
            return self._top_scores(v, top_k), v.gen
        sl = v.s.tolist()  # one bulk convert beats N np-scalar __int__s
        if node_names is None:
            return list(zip(v.names, sl[:len(v.names)])), v.gen
        idx = v.idx
        return [(nm, sl[idx[nm]]) for nm in node_names if nm in idx], v.gen

    def filter(self, pod, nodes, node_names):
        if nodes is not None:
            # non-cache-capable args-mode: full state ships per request —
            # nothing to coalesce against, evaluate directly
            with self._lock:
                snap, m, _ = self._eval_locked(pod, nodes)
                names = snap.node_names
                idx = snap.node_index
            cand = node_names if node_names is not None \
                else [n.name for n in nodes]
            return self._split_passed(m, names, idx, cand)
        passed, failed, _gen = self.filter_verdict(pod, node_names)
        return passed, failed

    def prioritize(self, pod, nodes, node_names):
        if nodes is not None:
            with self._lock:
                snap, _, s = self._eval_locked(pod, nodes)
                names = snap.node_names
                idx = snap.node_index
            sl = s.tolist()
            cand = node_names if node_names is not None \
                else [n.name for n in nodes]
            return [(nm, sl[idx[nm]]) for nm in cand if nm in idx]
        scores, _gen = self.prioritize_verdict(pod, node_names)
        return scores

    def _bind_fence_locked(self, pod: Pod, node: str):
        """Single-commit mirror of the engine's harvest fence (ISSUE 9):
        re-validate capacity / pod count / host ports / liveness — and,
        when affinity is in play, the full topology verdict via a FRESH
        evaluation — for one (pod, node) commit against CURRENT cache
        truth. This is the Omega transaction re-validator at the wire:
        verdicts may be stale (stale_window_s), commits never are. Called
        with the lock held, BEFORE the assume. Returns the typed conflict
        as ``(reason_code, message)`` — reason_code indexes
        podtrace.REASON_NAMES, the SAME vocabulary the wave engine's
        fence_reason_* requeues use (ISSUE 16: the per-reason
        bind_conflict counters partition the total with names the
        existing requeue attribution already established) — or None to
        admit."""
        lockcheck.assert_held(self._lock, "_bind_fence_locked")
        from kubernetes_tpu.observability import podtrace
        from kubernetes_tpu.ops import oracle
        from kubernetes_tpu.ops.affinity import _has_affinity
        infos = self._infos if self._infos is not None \
            else self.cache.node_infos()
        info = infos.get(node)
        if info is None:
            return podtrace.REASON_LIVENESS, f"node {node} unknown"
        if info.node is None:
            return podtrace.REASON_LIVENESS, f"node {node} gone"
        if info.node.unschedulable:
            return podtrace.REASON_LIVENESS, f"node {node} cordoned"
        if not oracle.check_node_condition(info.node):
            return podtrace.REASON_LIVENESS, f"node {node} not ready"
        # NodeInfo.requested includes every assume committed so far —
        # exactly the occupancy the harvest fence's prefix math re-checks
        ok, fails = oracle.pod_fits_resources(pod, info)
        if not ok:
            return (podtrace.REASON_CAPACITY,
                    f"insufficient capacity on {node}: {','.join(fails)}")
        if not oracle.pod_fits_host_ports(pod, info):
            return (podtrace.REASON_CAPACITY,
                    f"host port conflict on {node}")
        if _has_affinity(pod) or not self.eval_cache.cluster_aff_free:
            # topology mirror: an affinity verdict can be invalidated by
            # ANY foreign commit — force the deferred hint refresh past
            # the staleness window and re-check the chosen node against
            # the fresh evaluation
            self._last_refresh = 0.0
            snap, m, _s = self._eval_locked(pod, None)
            i = snap.node_index.get(node, -1)
            if i < 0 or not m[i]:
                return (podtrace.REASON_AFFINITY,
                        f"topology re-validation failed on {node}")
        return None

    def _fence_conflict(self, code: int, reason: str,
                        idem_key: Optional[str]):
        """One typed fence refusal (lock held): fold the total, attribute
        the per-reason counter — the partition invariant
        sum(bind_conflict_reason_*) == bind_conflicts is test-pinned on
        every transport — stamp a ring instant for the perfetto fence
        lane (wave=-1 marks a WIRE conflict; b carries the reason code),
        and answer the retryable CONFLICT."""
        import time as _time

        from kubernetes_tpu.observability import podtrace
        from kubernetes_tpu.observability.recorder import RECORDER
        from kubernetes_tpu.observability import recorder as flightrec
        self._count("bind_conflicts")
        self._count("bind_conflict_reason_" + podtrace.REASON_NAMES[code])
        if RECORDER.enabled:
            RECORDER.record(flightrec.FENCE_REQUEUE, wave=-1,
                            t0=_time.monotonic(), a=1, b=code)
        err = f"CONFLICT: {reason}"
        if idem_key:
            self.ledger.finish(idem_key, "conflict", err)
        return err, "conflict", self._retry_jitter()

    def list_state(self):
        """``(nodes, bound_pods)`` — cell truth for a relisting scheduler
        process (ISSUE 16): every live node plus every pod the cache
        currently charges to a node (assumed AND confirmed — exactly the
        occupancy the bind fence validates commits against). This is the
        RELIST half of a per-process watch/relist snapshot refresh: a
        worker process syncs this into ITS OWN backend and schedules
        against bounded-stale local truth while commits race through the
        shared fence."""
        with self._lock:
            infos = self._infos if self._infos is not None \
                else self.cache.node_infos()
            nodes = [i.node for i in infos.values() if i.node is not None]
            pods = [p for i in infos.values() for p in list(i.pods)]
            return nodes, pods

    def bind(self, pod_name, pod_namespace, pod_uid, node):
        """Legacy single-scheduler wire shape: error string, "" = bound."""
        err, _kind, _retry = self.bind_verdict(pod_name, pod_namespace,
                                               pod_uid, node)
        return err

    def bind_verdict(self, pod_name, pod_namespace, pod_uid, node,
                     snapshot_gen: Optional[int] = None,
                     idem_key: Optional[str] = None,
                     deadline_s: Optional[float] = None,
                     pod_spec: Optional[Pod] = None):
        """The multi-frontend /bind commit (ISSUE 9). Returns
        (error, kind, retry_after_s) with kind in:

          ok       — committed (or a replayed success);
          conflict — the fence refused; RETRYABLE: re-run scheduleOne
                     against a fresh verdict after the jittered backoff;
          pending  — a twin with the same idempotency key is in flight;
                     retryable exactly like a conflict;
          shed     — the request outlived its own deadline; nothing
                     happened (a same-key retry starts fresh);
          error    — the downstream apiserver write failed; AMBIGUOUS
                     (may have landed) — retry with the SAME key and the
                     ledger replays it to exactly-once.

        NOTE on affinity: the /bind wire carries identifiers only
        (ExtenderBindingArgs), so without a shipped "Pod" spec a freshly
        bound pod's affinity stays unknown until the bulk cache sync —
        cluster_aff_free changes only at sync boundaries, so no evaluation
        path can see the unknown affinity (fast lane == oracle)."""
        import dataclasses
        import time as _time
        t0 = _time.monotonic()
        key = f"{pod_namespace}/{pod_name}"
        replaying = False
        replay_err = ""
        if idem_key:
            verdict, lnode, lerr = self.ledger.begin(idem_key, node)
            if verdict == "done":
                # completed attempt: answer from the record — no second
                # assume, no second apiserver write (exactly-once)
                self._count("bind_replays")
                kind = "conflict" if lerr.startswith("CONFLICT") else \
                    ("ok" if not lerr else "error")
                return lerr, kind, self._retry_jitter()
            if verdict == "pending":
                self._count("bind_replays")
                return ("CONFLICT: bind attempt in flight", "pending",
                        self._retry_jitter())
            if verdict == "replay":
                # ambiguous prior attempt: converge on ITS node choice
                # (BindLedger docstring), never a fresh one
                self._count("bind_replays")
                node = lnode
                replaying = True
                replay_err = lerr
        try:
            return self._bind_attempt(key, pod_name, pod_namespace,
                                      pod_uid, node, snapshot_gen,
                                      idem_key, deadline_s, pod_spec, t0,
                                      replaying, replay_err)
        except BaseException:
            # an unexpected escape (device error in the fence's re-eval,
            # cache invariant trip) must not pin a PENDING ledger entry —
            # that would answer every same-key retry "in flight" forever
            if idem_key:
                if replaying:
                    self.ledger.finish(idem_key, "uncertain", replay_err)
                else:
                    self.ledger.abandon(idem_key)
            raise

    def _bind_attempt(self, key, pod_name, pod_namespace, pod_uid, node,
                      snapshot_gen, idem_key, deadline_s, pod_spec, t0,
                      replaying, replay_err):
        """The fence + assume + downstream-write body of bind_verdict,
        after the ledger prologue resolved what to attempt."""
        import dataclasses
        import time as _time
        assumed_now = False
        with self._lock:
            if deadline_s is not None \
                    and _time.monotonic() - t0 > deadline_s:
                self._count("deadline_shed")
                if idem_key:
                    if replaying:  # restore the ambiguity record
                        self.ledger.finish(idem_key, "uncertain", replay_err)
                    else:
                        self.ledger.abandon(idem_key)
                return "DEADLINE_EXCEEDED", "shed", 0.0
            base = self._known_pods.get(key)
            if base is None and pod_spec is not None:
                base = pod_spec  # wire-shipped spec: exact fence math +
                # resource-true assume instead of the zero-resource bare
            if base is None:
                base = Pod(name=pod_name, namespace=pod_namespace,
                           uid=pod_uid)
            # DOUBLE-CLAIM (ISSUE 16): a pod already charged to a
            # DIFFERENT node was committed by another scheduler racing
            # this cell — refuse typed BEFORE the capacity fence (and
            # regardless of the generation skip below: a current-gen
            # verdict attests the snapshot, not pod ownership). Same-node
            # re-binds fall through untouched: that is the client-retry-
            # of-a-landed-bind shape the assume's KeyError tolerance and
            # the store's idempotent refusal already heal.
            from kubernetes_tpu.observability import podtrace
            claimed = self.cache.claimed_node(key)
            if claimed is not None and claimed != node:
                return self._fence_conflict(
                    podtrace.REASON_DOUBLE_CLAIM,
                    f"double-claim: pod {key} already claimed on "
                    f"{claimed}", idem_key)
            # FENCE (optimistic concurrency): skip only when the verdict's
            # generation is provably current — nothing was committed since
            # the snapshot it read, so its own /filter pass IS the fence
            if snapshot_gen is None or snapshot_gen != self.commit_gen:
                self._refresh_warm_locked()  # liveness truth for _infos
                fenced = self._bind_fence_locked(base, node)
                if fenced is not None:
                    return self._fence_conflict(fenced[0], fenced[1],
                                                idem_key)
            else:
                self._count("bind_fence_skipped")
            pod = dataclasses.replace(base, node_name=node)
            try:
                self.cache.assume_pod(pod)
                self.cache.finish_binding(pod)
                assumed_now = True
                if key not in self._known_pods:
                    self._assumed_bare[key] = pod
                # the warm lane's staleness ledger: exactly one node's
                # dynamic row moved
                self._bind_hint.add(node)
                self.commit_gen += 1
            except KeyError:
                pass  # already known (e.g. a client retry of a bind that
                # succeeded) — do NOT treat the existing assumption as ours
        # the apiserver write runs OUTSIDE the lock: a slow apiserver must
        # not stall every concurrent /filter//prioritize for the duration
        # of an external HTTP call. Concurrent evaluations meanwhile see
        # the optimistic assume — exactly the reference's semantics
        # (scheduler.go:224-250: assume first, bind async, forget on
        # failure), compensated below.
        if self.binder is not None:
            try:
                self.binder(pod_name, pod_namespace, pod_uid, node)
            except Exception as e:
                if assumed_now:
                    # undo ONLY what this call assumed: a duplicate /bind
                    # whose write fails must not forget a legitimately
                    # bound pod (that would leak its capacity until the
                    # next sync)
                    with self._lock:
                        self.cache.forget_pod(pod)
                        self._assumed_bare.pop(key, None)
                        self._bind_hint.add(node)
                        self.commit_gen += 1
                self._count("bind_errors")
                if idem_key:
                    # AMBIGUOUS: the write may have landed (bind-API
                    # timeout shape) — record it so a same-key retry
                    # replays to the same node instead of double-booking
                    self.ledger.finish(idem_key, "uncertain", str(e))
                return str(e), "error", 0.0
        if idem_key:
            self.ledger.finish(idem_key, "ok", "")
        return "", "ok", 0.0

    def _retry_jitter(self) -> float:
        """Server-suggested conflict backoff: jittered so a fleet that
        conflicted together doesn't retry in lockstep."""
        with self._counters_lock:
            return 0.002 + self._rng.random() * 0.01

    def metrics_text(self) -> str:
        # the single Prometheus render of the unified registry (ISSUE 13):
        # same families as the pre-r15 hand-rolled fold (scheduler
        # histograms, tpu_extender_*_total counters, gen gauges) plus the
        # span and flight-recorder families. Lock discipline unchanged:
        # each source snapshots under ITS lock, in sequence, never nested.
        return self.telemetry.render_prometheus()
