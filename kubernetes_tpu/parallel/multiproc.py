"""Omega for real (ISSUE 16): M independent scheduler PROCESSES over one
shared cell, racing through the bind fence.

The multi-frontend benches (ISSUE 9/11) already run many scheduler
*threads* against one backend — but every thread shares the parent's
GIL, device context and cache, so "N schedulers" was really one
interpreter time-slicing. This module runs the paper's actual shape
(PAPERS.md §Omega): each scheduler is a FULL OS process with its own
interpreter, its own jax context, its own TPUExtenderBackend evaluator
(driving engine/scheduler_engine's fused kernels locally) and its own
bounded-stale snapshot — all sharing ONE cell through the binary wire.

The concurrency contract is exactly Omega's:

  - each worker hydrates from the shared cell with RELIST (one round
    trip: nodes + bound pods from commit truth) and re-pulls
    periodically — that pull cadence IS its staleness window;
  - placement decisions run on the worker's LOCAL evaluator against its
    possibly-stale view (zero shared locks on the decision path);
  - the only shared-state touch is the fenced BIND commit: the shared
    backend re-validates every commit against live cache truth
    (extender.py _bind_fence) and refuses with a TYPED conflict —
    capacity/affinity (stale-snapshot shapes), liveness, or
    double_claim (another process already placed this pod);
  - a refused worker refreshes (relist) and retries — optimistic
    concurrency, no pessimistic cell lock anywhere.

Exactly-once is audited against STORE truth (audit_duplicate_binds):
with W workers racing overlapping pending pools, every pod must land on
exactly one node, duplicates hard-zero — the fence plus the double-claim
probe plus the idempotency ledger carry that bar across process
boundaries.

This module is pure HOST-side orchestration: it imports no jax in the
parent (workers import the evaluator stack inside their own process).
"""

from __future__ import annotations

import multiprocessing
import os
import re
import time
from typing import Dict, List, Optional

_OWNER_RE = re.compile(r"already (?:claimed on|assigned to node) (\S+)")

# events kept per worker for perfetto lanes / debugging; the counters
# are exact regardless — this only bounds the queue payload
MAX_EVENTS_PER_WORKER = 4096


def audit_duplicate_binds(api, prefix: str = "") -> int:
    """STORE-TRUTH exactly-once audit over the full event log: a pod
    whose MODIFIED events ever name two different nodes was double-
    booked. This is the hard-zero acceptance bar for every multiproc
    scenario (ISSUE 16) — same audit the thread fleets use."""
    first_node, dups = {}, 0
    for e in api._log:
        if e.kind == "Pod" and e.type == "MODIFIED" and e.obj.node_name \
                and e.obj.name.startswith(prefix):
            prev = first_node.setdefault(e.obj.name, e.obj.node_name)
            if prev != e.obj.node_name:
                dups += 1
    return dups


def _worker_main(cfg: Dict, out_q) -> None:
    """One scheduler process (spawn target — module level, import-safe).

    Owns a full local evaluator: TPUExtenderBackend(binder=None) is the
    fused-kernel scheduler_engine front (its fused_verdict/bind_verdict
    are the same seams the wave engine drives), hydrated by RELIST and
    committed-to only AFTER the shared cell accepted the fenced bind.
    """
    # before any kubernetes_tpu import: the evaluator pulls in jax, and
    # a CI worker must never grab an accelerator the parent owns
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import random

    from kubernetes_tpu.client.binarywire import (
        BinaryWireClient, WireDeadline, WireError, WireOverloaded)
    from kubernetes_tpu.server import framing
    from kubernetes_tpu.server.extender import TPUExtenderBackend

    wid = cfg["worker_id"]
    rng = random.Random((0xED6A << 4) ^ (wid * 7919))
    pods = framing.decode_items_blob(cfg["pods_blob"], "pods")
    local = TPUExtenderBackend(
        binder=None,
        stale_window_s=cfg.get("stale_window_ms", 0) / 1e3,
        coalesce_window_s=0.0005)
    cli = BinaryWireClient(cfg["host"], cfg["port"],
                           timeout=cfg.get("wire_timeout_s", 60.0))
    cli.connect()

    counts = {"binds": 0, "conflicts": 0, "double_claim": 0,
              "stale_snapshot": 0, "pending": 0, "relists": 0,
              "attempts": 0, "overloaded": 0, "gave_up": 0,
              "wire_replays": 0}
    events: List[Dict] = []
    bound: Dict[str, str] = {}

    def ev(kind: str, t0: float, **kw) -> None:
        if len(events) < MAX_EVENTS_PER_WORKER:
            e = {"kind": kind, "t": t0,
                 "dur": time.monotonic() - t0}
            e.update(kw)
            events.append(e)

    def relist() -> None:
        t0 = time.monotonic()
        nodes, bound_pods = cli.relist()
        local.sync_nodes(nodes)
        local.sync_pods(bound_pods)
        counts["relists"] += 1
        ev("relist", t0, n=len(bound_pods))

    try:
        relist()  # hydrate: the per-process snapshot
        relist_every = max(int(cfg.get("relist_every", 16)), 1)
        top_k = int(cfg.get("top_k", 32))
        since_relist = 0
        t_start = time.monotonic()
        for pod in pods:
            key = pod.key()
            blob = framing.encode_pod_blob(pod)
            placed = None
            for attempt in range(80):
                counts["attempts"] += 1
                # DECIDE locally: the fused verdict runs on THIS
                # process's evaluator against its bounded-stale view —
                # no shared lock, no wire round trip
                _passed, _failed, top, _gen = local.fused_verdict(
                    pod, None, top_k=top_k)
                if not top:
                    relist()
                    time.sleep(0.002 * rng.uniform(0.5, 1.5))
                    continue
                best = top[0][1]
                host = rng.choice([n for n, s in top if s == best])
                # COMMIT remotely: gen=None forces the shared fence —
                # a local generation can never attest the shared cell
                idem = f"{key}:w{wid}:{attempt}"
                t0 = time.monotonic()
                try:
                    r = cli.bind(pod.name, pod.namespace, pod.uid, host,
                                 snapshot_gen=None, idem_key=idem,
                                 pod_blob=blob)
                except WireOverloaded as e:
                    counts["overloaded"] += 1
                    time.sleep(e.retry_after_s * rng.uniform(0.5, 1.5))
                    continue
                except WireDeadline:
                    continue
                except (WireError, ConnectionError, OSError):
                    # ambiguous wire fault: reconnect and replay the
                    # SAME ledger key — the service converges it
                    counts["wire_replays"] += 1
                    try:
                        cli.connect()
                        r = cli.bind(pod.name, pod.namespace, pod.uid,
                                     host, snapshot_gen=None,
                                     idem_key=idem, pod_blob=blob)
                    except Exception:
                        time.sleep(0.01)
                        continue
                if r.kind == "ok":
                    placed = host
                    counts["binds"] += 1
                    ev("bind", t0, pod=key, node=host,
                       attempt=attempt)
                    # local commit mirrors the accepted placement so
                    # subsequent verdicts see the capacity charge now,
                    # not at the next relist
                    local.bind_verdict(pod.name, pod.namespace,
                                       pod.uid, host, pod_spec=pod)
                    break
                if r.kind == "conflict":
                    counts["conflicts"] += 1
                    m = _OWNER_RE.search(r.error)
                    if "double-claim" in r.error and m:
                        # another PROCESS placed this pod: store truth
                        # wins — converge, don't fight
                        counts["double_claim"] += 1
                        ev("conflict", t0, pod=key,
                           reason="double_claim", owner=m.group(1))
                        placed = m.group(1)
                        break
                    counts["stale_snapshot"] += 1
                    ev("conflict", t0, pod=key, reason="stale_snapshot")
                    time.sleep(max(r.retry_after_s, 0.001)
                               * rng.uniform(0.5, 1.5))
                    relist()
                    continue
                if r.kind == "pending":
                    counts["pending"] += 1
                    time.sleep(max(r.retry_after_s, 0.001))
                    continue
                if r.kind == "shed":
                    continue
                # kind == "error": the store write failed. A different-
                # node refusal means a racing process landed first at
                # the STORE (fence raced the same microsecond) —
                # converge on the store's owner like a double-claim.
                m = _OWNER_RE.search(r.error or "")
                if m and m.group(1) != host:
                    counts["conflicts"] += 1
                    counts["double_claim"] += 1
                    ev("conflict", t0, pod=key, reason="double_claim",
                       owner=m.group(1))
                    placed = m.group(1)
                    break
                # ambiguous store fault: same-key replay next round
                time.sleep(0.005 * rng.uniform(0.5, 1.5))
            else:
                counts["gave_up"] += 1
            if placed is not None:
                bound[key] = placed
            since_relist += 1
            if since_relist >= relist_every:
                since_relist = 0
                relist()  # the watch cadence: bounded staleness
        t_end = time.monotonic()
        out_q.put({"worker": wid, "ok": True, "counts": counts,
                   "bound": bound, "events": events,
                   "t0": t_start, "t1": t_end,
                   "elapsed_s": t_end - t_start})
    except Exception as e:  # noqa: BLE001 — report, never hang the join
        out_q.put({"worker": wid, "ok": False,
                   "error": f"{type(e).__name__}: {e}",
                   "counts": counts, "bound": bound, "events": events,
                   "t0": 0.0, "t1": 0.0, "elapsed_s": 0.0})
    finally:
        cli.close()


def run_process_fleet(n_workers: int, pods_per_worker: int = 64,
                      overlap: float = 0.0, n_nodes: int = 64,
                      stale_window_ms: float = 0.0,
                      bind_fail_rate: float = 0.0,
                      bind_timeout_rate: float = 0.0,
                      relist_every: int = 16, top_k: int = 32,
                      seed: int = 0, pod_prefix: str = "mp",
                      durable_dir: Optional[str] = None,
                      timeout_s: float = 300.0) -> Dict:
    """Spawn ``n_workers`` full scheduler processes over one shared cell
    and drain their pending pools through the fenced wire.

    ``overlap`` is the fraction of each worker's pool that is SHARED
    with every other worker (the same pod objects, raced): overlap 0.0
    partitions the pending pool (Omega's happy case — conflicts only
    from capacity races), overlap 1.0 makes every pod contested
    (worst case — W-1 of every W claims must lose typed).

    Returns {"workers": [...], "agg": {...}} — per-worker raw results
    (counts/events/bound, perfetto-lane ready) plus the aggregate:
    scheduleOnes/s over the fleet wall-clock, conflict totals split by
    typed reason, the server's fence-conflict counter snapshot and the
    store-truth duplicate audit (must be 0).
    """
    from kubernetes_tpu.api.types import make_pod
    from kubernetes_tpu.models.hollow import hollow_nodes
    from kubernetes_tpu.server import framing
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite
    from kubernetes_tpu.server.asyncwire import AsyncBinaryServer
    from kubernetes_tpu.server.embedded import VerdictService
    from kubernetes_tpu.server.extender import TPUExtenderBackend
    from kubernetes_tpu.testing.churn import (FaultyBindApi,
                                              extender_store_binder)

    n_workers = max(int(n_workers), 1)
    overlap = min(max(float(overlap), 0.0), 1.0)
    total_pods = n_workers * pods_per_worker
    api = ApiServerLite(max_log=max(200_000, 8 * (n_nodes + total_pods)),
                        data_dir=durable_dir)
    nodes = hollow_nodes(n_nodes, seed=seed)
    for i, n in enumerate(nodes):
        n.labels["zone"] = f"z{i % 16}"
        api.create("Node", n)
    faulty = FaultyBindApi(api, fail_rate=bind_fail_rate,
                           timeout_rate=bind_timeout_rate, seed=seed)
    backend = TPUExtenderBackend(binder=extender_store_binder(faulty),
                                 stale_window_s=stale_window_ms / 1e3,
                                 coalesce_window_s=0.0005)
    backend.sync_nodes(nodes)
    backend.filter(make_pod(f"{pod_prefix}-warm", cpu=100,
                            memory=256 << 20), None, None)
    service = VerdictService(backend)
    srv = AsyncBinaryServer(service, max_inflight=max(64, 4 * n_workers))
    srv.start()

    # pending pools: a per-worker OWN slice plus a SHARED slice every
    # worker races (the overlap knob). All pods exist in the store
    # first, like a real pending queue.
    n_shared = int(round(overlap * pods_per_worker))
    n_own = pods_per_worker - n_shared
    shared = [make_pod(f"{pod_prefix}-sh-{i}", cpu=100,
                       memory=256 << 20) for i in range(n_shared)]
    own = {w: [make_pod(f"{pod_prefix}-w{w}-{i}", cpu=100,
                        memory=256 << 20) for i in range(n_own)]
           for w in range(n_workers)}
    for p in shared:
        api.create("Pod", p)
    for w in range(n_workers):
        for p in own[w]:
            api.create("Pod", p)

    ctx = multiprocessing.get_context("spawn")
    out_q = ctx.Queue()
    procs = []
    t_wall0 = time.monotonic()
    try:
        for w in range(n_workers):
            pool = own[w] + shared  # shared pods raced by everyone
            cfg = {"worker_id": w, "host": "127.0.0.1",
                   "port": srv.port,
                   "pods_blob": framing.encode_items_blob(pool, "pods"),
                   "stale_window_ms": stale_window_ms,
                   "relist_every": relist_every, "top_k": top_k}
            p = ctx.Process(target=_worker_main, args=(cfg, out_q),
                            name=f"sched-proc-{w}", daemon=True)
            p.start()
            procs.append(p)
        results = []
        deadline = time.monotonic() + timeout_s
        while len(results) < n_workers and time.monotonic() < deadline:
            try:
                results.append(out_q.get(timeout=0.5))
                continue
            except Exception:
                pass
            # a worker that died before reporting (spawn failure, OOM)
            # must not stall the join for the full timeout
            if all(not p.is_alive() for p in procs):
                try:
                    while len(results) < n_workers:
                        results.append(out_q.get(timeout=0.5))
                except Exception:
                    pass
                break
        for p in procs:
            p.join(timeout=max(deadline - time.monotonic(), 1.0))
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=5.0)
        t_wall1 = time.monotonic()
        srv.stop()

    results.sort(key=lambda r: r["worker"])
    ok = [r for r in results if r.get("ok")]
    binds = sum(r["counts"]["binds"] for r in results)
    conflicts = sum(r["counts"]["conflicts"] for r in results)
    # fleet wall-clock: first worker's scheduling start to last end
    # (CLOCK_MONOTONIC is system-wide on Linux, so worker stamps are
    # directly comparable); falls back to the parent's wall if a worker
    # died before stamping
    t0s = [r["t0"] for r in ok if r["t0"]]
    t1s = [r["t1"] for r in ok if r["t1"]]
    span = (max(t1s) - min(t0s)) if t0s and t1s else (t_wall1 - t_wall0)
    span = max(span, 1e-9)
    vars_snap = service.debug_snapshot(0)["vars"]
    fence = {k.rsplit("bind_conflict_reason_", 1)[1]: v
             for k, v in vars_snap.items()
             if "bind_conflict_reason_" in k}
    agg = {
        "workers": n_workers,
        "pods_per_worker": pods_per_worker,
        "overlap": overlap,
        "n_nodes": n_nodes,
        "binds": binds,
        "scheduled_pods_s": binds / span,
        "wall_s": span,
        "conflicts": conflicts,
        "conflict_rate": conflicts / max(binds + conflicts, 1),
        "double_claim": sum(r["counts"]["double_claim"]
                            for r in results),
        "stale_snapshot": sum(r["counts"]["stale_snapshot"]
                              for r in results),
        "relists": sum(r["counts"]["relists"] for r in results),
        "gave_up": sum(r["counts"]["gave_up"] for r in results),
        "worker_failures": [r.get("error") for r in results
                            if not r.get("ok")],
        "missing_workers": n_workers - len(results),
        "server_bind_conflicts": vars_snap.get(
            "counter.extender.bind_conflicts", 0),
        "server_conflict_reasons": fence,
        "duplicate_binds": audit_duplicate_binds(api, pod_prefix),
    }
    return {"workers": results, "agg": agg, "api": api}


__all__ = ["MAX_EVENTS_PER_WORKER", "audit_duplicate_binds",
           "run_process_fleet"]
