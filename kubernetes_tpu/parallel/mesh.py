"""Device-mesh sharding for the scheduler kernels.

The scale axis of the reference is cluster size x pending-queue depth
(SURVEY.md §5.7); here that becomes tensor sharding over a 1-D "nodes" mesh:
every node-indexed array (labels, taints, alloc, requested, port bitmaps...)
is sharded along axis 0 across devices, pod-side arrays are replicated, and
XLA inserts the collectives (max/argmin reductions over the node axis ride
the ICI ring) — the pjit recipe: pick a mesh, annotate shardings, let the
compiler do the communication. This replaces the reference's
workqueue.Parallelize(16, nodes) fan-out (generic_scheduler.go:204,352) with
true SPMD over chips.

The sequential placement scan works unchanged under these shardings: the
per-step dyn-fit/score math is elementwise over N (local to each shard), the
argmax/min reductions become cross-device collectives, and the capacity
commit is a scatter into the owning shard.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Arrays = Dict[str, jax.Array]

NODE_AXIS = "nodes"

# node-side arrays sharded along the node axis; everything else replicated
_NODE_SHARDED_KEYS = frozenset({
    "alloc", "requested", "nonzero", "pod_count", "allowed_pods",
    "schedulable", "mem_pressure", "disk_pressure", "labels", "taints_sched",
    "taints_pref", "port_bitmap", "valid", "avoid", "image_sizes",
    "has_zone", "vol_present", "vol_rw", "pd_present", "pd_counts",
})


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (NODE_AXIS,))


def shard_nodes(nodes: Arrays, mesh: Mesh) -> Arrays:
    """Place node-side arrays sharded along axis 0 of the mesh."""
    out = {}
    for k, v in nodes.items():
        spec = P(NODE_AXIS) if k in _NODE_SHARDED_KEYS else P()
        out[k] = jax.device_put(v, NamedSharding(mesh, spec))
    return out


def replicate(pods: Arrays, mesh: Mesh) -> Arrays:
    sh = NamedSharding(mesh, P())
    return {k: jax.device_put(v, sh) for k, v in pods.items()}


# AffinityData device arrays (ops/affinity.py device_arrays): most are
# class/slot/label-indexed (replicated — the label axis L is the contraction
# axis of the topology einsums, so splitting it would force inner-product
# collectives per scan step; N is the embarrassingly-parallel axis), but
# some carry a node axis and shard with the nodes:
#   sp_static [C, N] axis 1, Z [N, ZN] axis 0, node_has_zone [N] axis 0,
# plus the r08/r09 wave-path bundles (engine/scheduler_engine
# _aff_node_views / _aff_tail_arrays): key_node [C, A, N] axis 2,
# static_forbid [C, N] axis 1, and the tail's projected node incidence
# labels_aff [N, Lp] axis 0 (Lp is the SMALL projected domain axis — it
# stays replicated as a contraction axis, exactly like L)
_AFF_NODE_AXIS = {"sp_static": 1, "Z": 0, "node_has_zone": 0,
                  "key_node": 2, "static_forbid": 1, "labels_aff": 0}


def shard_affinity(aff: Arrays, mesh: Mesh) -> Arrays:
    """Place affinity class arrays: node-axis arrays sharded along the mesh,
    everything else replicated. The affinity scan carry (commdom [C,L],
    committed [C,N], comm_cnt [C]) is created inside the jitted program;
    XLA lays it out to match these operand shardings."""
    out = {}
    for k, v in aff.items():
        ax = _AFF_NODE_AXIS.get(k)
        spec = P() if ax is None else P(*([None] * ax + [NODE_AXIS]))
        out[k] = jax.device_put(v, NamedSharding(mesh, spec))
    return out
