"""Device-mesh sharding for the scheduler kernels.

The scale axis of the reference is cluster size x pending-queue depth
(SURVEY.md §5.7); here that becomes tensor sharding over a 1-D "nodes" mesh:
every node-indexed array (labels, taints, alloc, requested, port bitmaps...)
is sharded along axis 0 across devices, pod-side arrays are replicated, and
XLA inserts the collectives (max/argmin reductions over the node axis ride
the ICI ring) — the pjit recipe: pick a mesh, annotate shardings, let the
compiler do the communication. This replaces the reference's
workqueue.Parallelize(16, nodes) fan-out (generic_scheduler.go:204,352) with
true SPMD over chips.

The sequential placement scan works unchanged under these shardings: the
per-step dyn-fit/score math is elementwise over N (local to each shard), the
argmax/min reductions become cross-device collectives, and the capacity
commit is a scatter into the owning shard.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Arrays = Dict[str, jax.Array]

NODE_AXIS = "nodes"

# node-side arrays sharded along the node axis; everything else replicated
_NODE_SHARDED_KEYS = frozenset({
    "alloc", "requested", "nonzero", "pod_count", "allowed_pods",
    "schedulable", "mem_pressure", "disk_pressure", "labels", "taints_sched",
    "taints_pref", "port_bitmap", "valid", "avoid", "image_sizes",
    "has_zone", "vol_present", "vol_rw", "pd_present", "pd_counts",
})


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (NODE_AXIS,))


def shard_nodes(nodes: Arrays, mesh: Mesh) -> Arrays:
    """Place node-side arrays sharded along axis 0 of the mesh."""
    out = {}
    for k, v in nodes.items():
        spec = P(NODE_AXIS) if k in _NODE_SHARDED_KEYS else P()
        out[k] = jax.device_put(v, NamedSharding(mesh, spec))
    return out


def replicate(pods: Arrays, mesh: Mesh) -> Arrays:
    sh = NamedSharding(mesh, P())
    return {k: jax.device_put(v, sh) for k, v in pods.items()}


# AffinityData device arrays (ops/affinity.py device_arrays): most are
# class/slot/label-indexed (replicated — the label axis L is the contraction
# axis of the topology einsums, so splitting it would force inner-product
# collectives per scan step; N is the embarrassingly-parallel axis), but
# some carry a node axis and shard with the nodes:
#   sp_static [C, N] axis 1, Z [N, ZN] axis 0, node_has_zone [N] axis 0,
# plus the r08/r09 wave-path bundles (engine/scheduler_engine
# _aff_node_views / _aff_tail_arrays): key_node [C, A, N] axis 2,
# static_forbid [C, N] axis 1, and the tail's projected node incidence
# labels_aff [N, Lp] axis 0 (Lp is the SMALL projected domain axis — it
# stays replicated as a contraction axis, exactly like L)
_AFF_NODE_AXIS = {"sp_static": 1, "Z": 0, "node_has_zone": 0,
                  "key_node": 2, "static_forbid": 1, "labels_aff": 0}


def shard_affinity(aff: Arrays, mesh: Mesh) -> Arrays:
    """Place affinity class arrays: node-axis arrays sharded along the mesh,
    everything else replicated. The affinity scan carry (commdom [C,L],
    committed [C,N], comm_cnt [C]) is created inside the jitted program;
    XLA lays it out to match these operand shardings."""
    out = {}
    for k, v in aff.items():
        out[k] = jax.device_put(v, NamedSharding(mesh, aff_spec(k)))
    return out


# ---------------------------------------------------------------- residency
# ISSUE 12: the node axis as a RESIDENT scaling dimension. The recipes
# above place arrays once per call — fine for a dryrun, wrong for an
# always-on engine whose snapshot/topology/static-pre tensors must stay
# sharded across every wave. The helpers below are the residency layer:
# spec tables shared by every consumer (engine uploads, shard_map
# in_specs, the dryrun), and a per-shard ROW update that rebuilds a
# sharded dynamic array touching ONLY the shards whose rows moved — the
# delta path's host->device traffic is then O(touched_shards x N/D)
# rows (whole shards re-ship, so a fold localized to few shards moves a
# fraction of N while a fold spread over every shard degrades to a full
# re-upload — engine.shard_upload_bytes states what actually moved), and
# no cross-device traffic is induced at all (untouched shards keep their
# existing device buffers by reference).


def node_spec(key: str, ndim: int = 2) -> P:
    """PartitionSpec for a snapshot/node-state array by key: node-axis
    arrays shard axis 0, everything else (pd_kind [3,V], pd_max [3],
    scalar-ish vocab tables) replicates."""
    if key in _NODE_SHARDED_KEYS:
        return P(NODE_AXIS, *([None] * (ndim - 1)))
    return P()


def aff_spec(key: str) -> P:
    """PartitionSpec for an AffinityData / wave-bundle device array."""
    ax = _AFF_NODE_AXIS.get(key)
    return P() if ax is None else P(*([None] * ax + [NODE_AXIS]))


def committed_spec() -> P:
    """The wave loop's [C, N] topology-occupancy carry: node axis 1."""
    return P(None, NODE_AXIS)


class ResidentMesh:
    """One engine's device mesh plus its cached NamedShardings.

    NamedSharding construction is cheap but not free, and the engine asks
    for the same handful of specs every wave; caching also gives spec
    IDENTITY, which the partition-spec pin test reads."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self.n_devices = int(mesh.devices.size)
        self._cache: Dict[tuple, NamedSharding] = {}
        # device order along the node axis — shard d owns global rows
        # [d*Nl, (d+1)*Nl); make_array_from_single_device_arrays consumes
        # buffers in this order
        self.devices = list(mesh.devices.reshape(-1))

    def sharding(self, spec: P) -> NamedSharding:
        key = tuple(spec)
        hit = self._cache.get(key)
        if hit is None:
            hit = self._cache[key] = NamedSharding(self.mesh, spec)
        return hit

    def node_sharding(self, key: str, ndim: int = 2) -> NamedSharding:
        return self.sharding(node_spec(key, ndim))

    def aff_sharding(self, key: str) -> NamedSharding:
        return self.sharding(aff_spec(key))

    def committed_sharding(self) -> NamedSharding:
        return self.sharding(committed_spec())

    # ----------------------------------------------------- row delta path

    def update_rows(self, dev: jax.Array, host: np.ndarray,
                    rows: Sequence[int]) -> jax.Array:
        """Rebuild an axis-0-sharded device array from `host`, re-uploading
        ONLY the shards owning `rows`; every other shard keeps its existing
        device buffer (no transfer, no cross-device traffic). The unit of
        upload is a whole SHARD (N/D rows): traffic is
        O(touched_shards x N/D), so row-localized folds ship a fraction
        of N and a fold touching every shard degrades to a full
        re-upload — `touched_nbytes` states the actual byte cost. The
        caller guarantees `host` equals the device content outside the
        touched rows (the engine's dirty-row contract). Returns the new
        array and never mutates `dev` — in-flight waves keep their
        operand.

        Each touched shard's slice is COPIED host-side before device_put:
        even a zero-copy single-device placement then aliases only the
        throwaway slice, never the live snapshot array (the GL001
        copy-required contract, per shard)."""
        n = host.shape[0]
        nl = n // self.n_devices
        touched = {min(int(r) // nl, self.n_devices - 1) for r in rows}
        shards = {s.device: s.data for s in dev.addressable_shards}
        bufs = []
        for d, device in enumerate(self.devices):
            if d in touched:
                bufs.append(jax.device_put(
                    np.array(host[d * nl:(d + 1) * nl]), device))
            else:
                bufs.append(shards[device])
        sharding = self.sharding(P(NODE_AXIS, *([None] * (host.ndim - 1))))
        return jax.make_array_from_single_device_arrays(
            host.shape, sharding, bufs)

    def touched_nbytes(self, host: np.ndarray,
                       rows: Sequence[int]) -> int:
        """Host->device bytes update_rows actually ships for `rows`:
        whole shards, not rows — len(touched_shards) x N/D x row bytes."""
        n = host.shape[0]
        nl = n // self.n_devices
        touched = {min(int(r) // nl, self.n_devices - 1) for r in rows}
        return len(touched) * nl * (host.nbytes // max(n, 1))
