"""Federated replica planner: distribute N replicas across member clusters.

Reimplementation of the reference's planner semantics
(federation/pkg/federation-controller/util/planner/planner.go:67 Plan):

  1. clusters take their MinReplicas first (capacity-capped), in
     decreasing-weight order with an FNV-1 hash of (cluster, rs key) as the
     tiebreak — so single-replica sets don't always land on the
     alphabetically smallest cluster;
  2. with rebalance=false, clusters keep what they already run (up to
     max/capacity) before anything moves — the anti-thrash preallocation;
  3. remaining replicas spread proportionally to Weight, fractions rounded
     up, iterating until nothing moves (max/capacity caps drop clusters
     from later rounds; capacity overshoot is returned as `overflow`).

Preferences wire format is the reference's replica-set-preferences
annotation (federation/pkg/federatedtypes/replicaset.go:35
`federation.kubernetes.io/replica-set-preferences`), JSON like
{"rebalance": true, "clusters": {"*": {"weight": 1}}}.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

PREFERENCES_ANNOTATION = "federation.kubernetes.io/replica-set-preferences"


@dataclass
class ClusterPreferences:
    """fedapi.ClusterPreferences (federation/apis/federation/types.go:153)."""

    min_replicas: int = 0
    max_replicas: Optional[int] = None
    weight: int = 0


@dataclass
class ReplicaAllocationPreferences:
    """fedapi.ReplicaAllocationPreferences (types.go:138): rebalance +
    per-cluster (or "*" wildcard) preferences."""

    rebalance: bool = False
    clusters: Dict[str, ClusterPreferences] = field(default_factory=dict)

    @classmethod
    def parse(cls, text: str) -> "ReplicaAllocationPreferences":
        obj = json.loads(text)
        clusters = {}
        for name, p in (obj.get("clusters") or {}).items():
            mx = p.get("maxReplicas")
            clusters[name] = ClusterPreferences(
                min_replicas=int(p.get("minReplicas", 0)),
                max_replicas=int(mx) if mx is not None else None,
                weight=int(p.get("weight", 0)))
        return cls(rebalance=bool(obj.get("rebalance", False)),
                   clusters=clusters)


DEFAULT_PREFERENCES = ReplicaAllocationPreferences(
    clusters={"*": ClusterPreferences(weight=1)})


def _fnv1_32(data: bytes) -> int:
    """FNV-1 32-bit (Go hash/fnv New32) — the planner's tie hash."""
    h = 0x811C9DC5
    for b in data:
        h = (h * 0x01000193) & 0xFFFFFFFF
        h ^= b
    return h


class Planner:
    def __init__(self, preferences: ReplicaAllocationPreferences):
        self.preferences = preferences

    def plan(self, replicas: int, clusters: List[str],
             current: Optional[Dict[str, int]] = None,
             capacity: Optional[Dict[str, int]] = None,
             key: str = "") -> Tuple[Dict[str, int], Dict[str, int]]:
        """(plan, overflow) — planner.go:67-220, integer-exact."""
        current = current or {}
        capacity = capacity or {}
        prefs: List[Tuple[str, int, ClusterPreferences]] = []
        plan: Dict[str, int] = {}
        overflow: Dict[str, int] = {}
        for name in clusters:
            p = self.preferences.clusters.get(name) \
                or self.preferences.clusters.get("*")
            if p is None:
                plan[name] = 0
            else:
                h = _fnv1_32(name.encode() + key.encode())
                prefs.append((name, h, p))
        # decreasing weight, then increasing hash (byWeight planner.go:38-46)
        prefs.sort(key=lambda t: (-t[2].weight, t[1]))

        remaining = replicas
        for name, _h, p in prefs:
            mn = min(p.min_replicas, remaining)
            if name in capacity:
                mn = min(mn, capacity[name])
            remaining -= mn
            plan[name] = mn

        preallocated: Dict[str, int] = {}
        if not self.preferences.rebalance:
            for name, _h, p in prefs:
                planned = plan[name]
                count = current.get(name)
                if count is not None and count > planned:
                    target = count
                    if p.max_replicas is not None:
                        target = min(p.max_replicas, target)
                    if name in capacity:
                        target = min(capacity[name], target)
                    extra = min(target - planned, remaining)
                    if extra < 0:
                        extra = 0
                    remaining -= extra
                    preallocated[name] = extra
                    plan[name] = extra + planned

        modified = True
        while modified and remaining > 0:
            modified = False
            weight_sum = sum(p.weight for _n, _h, p in prefs)
            if weight_sum <= 0:
                break
            next_prefs = []
            distribute = remaining
            for name, h, p in prefs:
                start = plan[name]
                # fractions rounded up (planner.go:169)
                extra = (distribute * p.weight + weight_sum - 1) // weight_sum
                extra = min(extra, remaining)
                prealloc = preallocated.get(name, 0)
                used_prealloc = min(extra, prealloc)
                preallocated[name] = prealloc - used_prealloc
                extra -= used_prealloc
                if used_prealloc > 0:
                    modified = True
                total = start + extra
                full = False
                if p.max_replicas is not None and total > p.max_replicas:
                    total = p.max_replicas
                    full = True
                if name in capacity and total > capacity[name]:
                    overflow[name] = total - capacity[name]
                    total = capacity[name]
                    full = True
                if not full:
                    next_prefs.append((name, h, p))
                remaining -= total - start
                plan[name] = total
                if total > start:
                    modified = True
            prefs = next_prefs

        return plan, overflow
