"""Federated Services + cross-cluster service DNS.

The reference's federation service story
(federation/pkg/federation-controller/service/ + federation/pkg/
dnsprovider/):

- the service controller materializes a federated Service into every
  ready member cluster;
- the servicedns controller writes a three-level DNS hierarchy per
  service into a dnsprovider (google-clouddns/aws-route53 in-tree;
  an in-memory provider here):

      <svc>.<ns>.<fed>.svc.<zone>.<region>.<domain>   (zone level)
      <svc>.<ns>.<fed>.svc.<region>.<domain>          (region level)
      <svc>.<ns>.<fed>.svc.<domain>                   (global level)

  A level with healthy endpoints gets A records of the serving clusters'
  ingress IPs; a level with NO healthy endpoints gets a CNAME to the
  next level up (dns.go:ensureDNSRrsets) — so a zone-local client is
  always routed somewhere live.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.workloads import Service
from kubernetes_tpu.federation.controller import (
    CLUSTER_KIND,
    FederationControlPlane,
)
from kubernetes_tpu.server.apiserver_lite import Conflict, NotFound

FEDERATED_SERVICE_KIND = "FederatedService"


@dataclass
class FederatedService:
    """The federated object: a Service template spread to every ready
    cluster (the federation apiserver stores plain v1.Service; kept as a
    wrapper for status aggregation symmetry with the workload types)."""

    name: str
    namespace: str = "default"
    template: Service = field(default_factory=lambda: Service(name=""))
    # aggregated status: clusters currently serving healthy endpoints
    serving_clusters: List[str] = field(default_factory=list)
    resource_version: int = 0

    def key(self) -> str:
        return self.namespace + "/" + self.name


@dataclass
class DNSRecord:
    name: str
    rtype: str  # "A" | "CNAME"
    values: List[str]
    ttl: int = 180


class InMemoryDNSProvider:
    """federation/pkg/dnsprovider Interface, collapsed to the rrsets
    surface the service controller drives (ResourceRecordSets.Get/
    StartChangeset Add/Remove/Apply)."""

    def __init__(self):
        self.records: Dict[Tuple[str, str], DNSRecord] = {}

    def ensure(self, name: str, rtype: str, values: List[str],
               ttl: int = 180) -> None:
        self.records[(name, rtype)] = DNSRecord(name, rtype,
                                                sorted(values), ttl)

    def remove(self, name: str, rtype: str) -> None:
        self.records.pop((name, rtype), None)

    def lookup(self, name: str) -> Optional[DNSRecord]:
        for (n, _t), rec in self.records.items():
            if n == name:
                return rec
        return None

    def resolve(self, name: str, _depth: int = 0) -> List[str]:
        """Follow CNAME chains to the A values, like a resolver would."""
        rec = self.lookup(name)
        if rec is None or _depth > 5:
            return []
        if rec.rtype == "A":
            return list(rec.values)
        return self.resolve(rec.values[0], _depth + 1)


class FederatedServiceController:
    """service controller + servicedns controller in one sync body."""

    def __init__(self, plane: FederationControlPlane,
                 dns: Optional[InMemoryDNSProvider] = None,
                 federation: str = "myfed",
                 domain: str = "example.com"):
        self.plane = plane
        # default to the plane's provider so records persist across
        # controller instances (each `ktctl federate sync` builds a new
        # controller but must see the same zone)
        self.dns = dns if dns is not None \
            else getattr(plane, "dns", None) or InMemoryDNSProvider()
        self.federation = federation
        self.domain = domain

    # ----------------------------------------------------------------- sync

    def sync_all(self) -> None:
        fsvcs, _ = self.plane.api.list(FEDERATED_SERVICE_KIND)
        for fsvc in fsvcs:
            self.sync(fsvc)

    def sync(self, fsvc: FederatedService) -> None:
        ready = self.plane.ready_clusters()
        serving: List[str] = []
        for cname, api in list(self.plane.members.items()):
            if cname not in ready:
                continue
            # ensure the member service exists (servicecontroller
            # ensureClusterService)
            tmpl = dataclasses.replace(
                fsvc.template, name=fsvc.name, namespace=fsvc.namespace,
                resource_version=0)
            try:
                api.create("Service", tmpl)
            except Conflict:
                pass
            if self._cluster_healthy(cname, fsvc):
                serving.append(cname)
        self._write_dns(fsvc, serving)
        try:
            cur: FederatedService = self.plane.api.get(
                FEDERATED_SERVICE_KIND, fsvc.namespace, fsvc.name)
            if cur.serving_clusters != sorted(serving):
                self.plane.api.update(
                    FEDERATED_SERVICE_KIND,
                    dataclasses.replace(cur,
                                        serving_clusters=sorted(serving)),
                    expect_rv=cur.resource_version)
        except (NotFound, Conflict):
            pass

    # -------------------------------------------------------------- helpers

    def _cluster_healthy(self, cname: str, fsvc: FederatedService) -> bool:
        """A cluster serves the federated service iff its local Endpoints
        object has ready addresses (servicedns getHealthyEndpoints)."""
        api = self.plane.members.get(cname)
        if api is None:
            return False
        try:
            eps = api.get("Endpoints", fsvc.namespace, fsvc.name)
        except NotFound:
            return False
        return bool(eps.addresses)

    def _ingress_ip(self, cname: str, fsvc: FederatedService) -> str:
        api = self.plane.members[cname]
        try:
            svc = api.get("Service", fsvc.namespace, fsvc.name)
        except NotFound:
            return ""
        return svc.load_balancer_ip or svc.cluster_ip

    def _cluster_meta(self) -> Dict[str, Tuple[str, str]]:
        out = {}
        for c in self.plane.api.list(CLUSTER_KIND)[0]:
            out[c.name] = (c.zone or "zone-x", c.region or "region-x")
        return out

    def dns_name(self, fsvc: FederatedService, zone: str = "",
                 region: str = "") -> str:
        base = f"{fsvc.name}.{fsvc.namespace}.{self.federation}.svc"
        if zone:
            return f"{base}.{zone}.{region}.{self.domain}"
        if region:
            return f"{base}.{region}.{self.domain}"
        return f"{base}.{self.domain}"

    def _write_dns(self, fsvc: FederatedService,
                   serving: List[str]) -> None:
        """ensureDNSRrsets for each level: A records where endpoints
        exist, CNAME one level up where they don't."""
        meta = self._cluster_meta()
        zones: Dict[Tuple[str, str], List[str]] = {}
        regions: Dict[str, List[str]] = {}
        for cname in serving:
            ip = self._ingress_ip(cname, fsvc)
            if not ip:
                continue
            zone, region = meta.get(cname, ("zone-x", "region-x"))
            zones.setdefault((zone, region), []).append(ip)
            regions.setdefault(region, []).append(ip)
        global_ips = sorted({ip for ips in regions.values() for ip in ips})
        gname = self.dns_name(fsvc)
        if global_ips:
            self.dns.ensure(gname, "A", global_ips)
        else:
            self.dns.remove(gname, "A")
        # every known zone/region gets a record so local resolvers always
        # find the chain, even where the service is not (or no longer)
        # locally healthy
        all_zones = {(z, r) for (z, r) in
                     (meta[c] for c in meta)} | set(zones)
        for region in {r for _z, r in all_zones}:
            rname = self.dns_name(fsvc, region=region)
            if regions.get(region):
                self.dns.ensure(rname, "A", sorted(set(regions[region])))
                self.dns.remove(rname, "CNAME")
            elif global_ips:
                self.dns.remove(rname, "A")
                self.dns.ensure(rname, "CNAME", [gname])
            else:
                self.dns.remove(rname, "A")
                self.dns.remove(rname, "CNAME")
        for (zone, region) in all_zones:
            zname = self.dns_name(fsvc, zone=zone, region=region)
            if zones.get((zone, region)):
                self.dns.ensure(zname, "A",
                                sorted(set(zones[(zone, region)])))
                self.dns.remove(zname, "CNAME")
            else:
                self.dns.remove(zname, "A")
                self.dns.ensure(zname, "CNAME",
                                [self.dns_name(fsvc, region=region)])
