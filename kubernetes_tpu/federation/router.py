"""The federation front door (ISSUE 20): ONE router, M cells, a fused
[C, M] routing decision.

The dormant FederationSyncLoop seam (r06) grown into the real tier: the
router holds one CellAggregate column per cell (hydrated by RELIST,
maintained delta-by-delta from the cells' own CELL_AGG folds — the r11
Protean patch discipline one level up), scores every pending pod/gang
against every cell in ONE fused dispatch (ops/federation.py), and admits
each candidate to exactly ONE cell over the existing binary wire.

Cross-cell exactly-once composes from three layers, none of them new:
the router's per-batch idempotency keys (an ambiguous ADMIT replays the
SAME key and converges on the recorded answer), the cell store's
(kind, ns, name) Conflict (a pod can't double-enter one cell), and the
rule that a pod LEAVES its old cell's store — under that store's lock —
before the router may admit it anywhere else (CellService.cell_aggregate
deletes drained/evacuated pods in the same locked fold that returns
them). The acceptance audit is store truth: one bound cell per pod, ever.

Gangs route whole-cell (PAPERS.md §Tiresias): all members of a gang
enter the tensor as ONE row with summed demand, so the quorum fence
inside whichever cell wins never spans a cell boundary.

Brownout: ``brownout(cell)`` marks the column NotReady (routing skips it
instantly) and evacuates the cell's pending pods through the SAME
spillover path overflow uses — re-routed to the surviving cells, bound
once. ``recover(cell)`` re-hydrates the column from RELIST truth.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from kubernetes_tpu.analysis import lockcheck
from kubernetes_tpu.engine.gang import GANG_NAME_ANNOTATION
from kubernetes_tpu.federation.aggregate import (
    CellAggregate,
    aggregate_from_lists,
)
from kubernetes_tpu.observability.registry import TelemetryRegistry

# routing batches below this size take the numpy twin: on a [C, M] this
# small a device dispatch is pure overhead (the fast lane's host-twin
# rationale, one level up)
DEVICE_MIN_BATCH = 256

# events kept per cell lane (perfetto add_process_lanes payload bound)
MAX_EVENTS_PER_CELL = 4096


class WireCell:
    """One cell over the binary wire — the production handle shape."""

    def __init__(self, name: str, host: str, port: int,
                 timeout: float = 60.0):
        from kubernetes_tpu.client.binarywire import BinaryWireClient
        self.name = name
        self._cli = BinaryWireClient(host, port, timeout=timeout)

    def relist(self):
        return self._cli.relist()

    def cell_agg(self, drain_spill: bool = False,
                 evacuate: bool = False):
        return self._cli.cell_agg(drain_spill=drain_spill,
                                  evacuate=evacuate)

    def admit(self, idem_key: str, pods: List) -> Tuple[int, int]:
        return self._cli.admit(idem_key, pods)

    def close(self) -> None:
        self._cli.close()


class LocalCell:
    """In-process handle over a CellService — the test/bench shape with
    zero wire between router and cell (same verbs, same semantics)."""

    def __init__(self, name: str, service):
        self.name = name
        self._svc = service

    def relist(self):
        return self._svc.relist()

    def cell_agg(self, drain_spill: bool = False,
                 evacuate: bool = False):
        return self._svc.cell_aggregate(drain_spill=drain_spill,
                                        evacuate=evacuate)

    def admit(self, idem_key: str, pods: List) -> Tuple[int, int]:
        return self._svc.admit(idem_key, pods)

    def close(self) -> None:
        pass


class FederationRouter:
    """Front-door admission over M cell handles (WireCell / LocalCell)."""

    def __init__(self, cells: List, router_id: str = "fed0",
                 use_device: Optional[bool] = None):
        self.cells = list(cells)
        if not self.cells:
            raise ValueError("FederationRouter needs at least one cell")
        self.router_id = router_id
        # None = auto: device for batches >= DEVICE_MIN_BATCH. The twins
        # are A/B-pinned equal, so this is latency policy, not semantics.
        self.use_device = use_device
        self._lock = lockcheck.make_lock("FederationRouter._lock")
        self.aggs: Dict[str, CellAggregate] = {
            c.name: CellAggregate(cell=c.name) for c in self.cells}
        self._seq = 0
        # candidates no cell fits right now; retried on each pump
        self.backlog: List = []
        self.counters: Dict[str, int] = {
            "routed_pods": 0, "routed_gangs": 0, "admitted": 0,
            "admit_replays": 0, "unroutable": 0, "spill_moved": 0,
            "evacuated_moved": 0, "brownouts": 0, "recoveries": 0,
            "refreshes": 0, "hydrations": 0, "device_batches": 0,
            "host_batches": 0,
        }
        # per-cell lanes in perfetto.add_process_lanes worker shape
        self._events: Dict[str, List[Dict]] = {
            c.name: [] for c in self.cells}
        self.admit_spans: List[Tuple[float, float, int]] = []
        self.telemetry = TelemetryRegistry()
        self.telemetry.register_counters(
            "federation", self.counters_snapshot,
            prom_prefix="tpu_federation")

    # ------------------------------------------------------------ aggregates

    def hydrate(self) -> None:
        """RELIST every cell and rebuild its column from store truth —
        boot and recovery path (the oracle the incremental folds are
        audited against)."""
        for c in self.cells:
            t0 = time.monotonic()
            nodes, bound = c.relist()
            agg = aggregate_from_lists(nodes, bound, cell=c.name)
            with self._lock:
                agg.ready = self.aggs[c.name].ready
                self.aggs[c.name] = agg
                self.counters["hydrations"] += 1
            self._event(c.name, "relist", t0, nodes=len(nodes),
                        bound=len(bound))

    def refresh(self, drain_spill: bool = False) -> List:
        """Pull every ready cell's incrementally-folded column; with
        ``drain_spill`` also collect (and re-route later, via the
        caller) the pods those cells gave up on. Returns the drained
        pods tagged with their origin cell: [(origin, pod), ...]."""
        out: List = []
        for c in self.cells:
            with self._lock:
                cell_ready = self.aggs[c.name].ready
            if not cell_ready:
                continue
            t0 = time.monotonic()
            d, spilled = c.cell_agg(drain_spill=drain_spill)
            agg = CellAggregate.from_dict(d)
            agg.ready = True
            with self._lock:
                self.aggs[c.name] = agg
                self.counters["refreshes"] += 1
            self._event(c.name, "agg", t0, pending=agg.pending,
                        spilled=len(spilled))
            out.extend((c.name, p) for p in spilled)
        return out

    # --------------------------------------------------------------- routing

    def route(self, pods: List, exclude: Optional[Dict[str, str]] = None
              ) -> Tuple[Dict[str, List], List]:
        """Choose one cell per pod/gang; returns ({cell: pods}, leftover).

        Gang members collapse to ONE tensor row (summed demand, shared
        verdict) — a gang never splits. ``exclude`` maps pod key ->
        cell name the pod must NOT return to (spillover: re-admitting a
        spilled pod to its origin would just spill it again). Leftover
        = candidates no ready cell fits (callers backlog them)."""
        from kubernetes_tpu.federation.aggregate import _pod_demand
        if not pods:
            return {}, []
        exclude = exclude or {}
        names = [c.name for c in self.cells]
        # ---- collapse to candidate rows (gangs whole, plain pods solo)
        rows: List[Dict] = []
        gang_rows: Dict[str, Dict] = {}
        for p in pods:
            ann = p.annotations or {}
            g = ann.get(GANG_NAME_ANNOTATION)
            cpu, mem = _pod_demand(p)
            zone = (p.node_selector or {}).get("zone", "")
            if g is None:
                rows.append({"pods": [p], "cpu": cpu, "mem": mem,
                             "zone": zone,
                             "not_cell": exclude.get(p.key(), "")})
            else:
                r = gang_rows.get(g)
                if r is None:
                    r = gang_rows[g] = {
                        "pods": [], "cpu": 0, "mem": 0, "zone": zone,
                        "not_cell": "", "gang": g}
                r["pods"].append(p)
                r["cpu"] += cpu
                r["mem"] += mem
                if zone:
                    r["zone"] = zone
                nc = exclude.get(p.key(), "")
                if nc:
                    r["not_cell"] = nc
        rows.extend(gang_rows.values())
        # ---- the [C, M] tensor off the live columns
        with self._lock:
            aggs = [self.aggs[n] for n in names]
        cpu_free = np.array([a.headroom()[0] for a in aggs],
                            dtype=np.int32)
        mem_free = np.array([a.headroom()[1] for a in aggs],
                            dtype=np.int32)
        cpu_cap = np.array([a.cpu_alloc_m for a in aggs], dtype=np.int32)
        mem_cap = np.array([a.mem_alloc_mib for a in aggs],
                           dtype=np.int32)
        pressure = np.array(
            [a.pending / max(a.nodes_ready, 1) for a in aggs],
            dtype=np.float32)
        ready = np.array([a.ready and a.nodes_ready > 0 for a in aggs],
                         dtype=bool)
        dem_cpu = np.array([r["cpu"] for r in rows], dtype=np.int32)
        dem_mem = np.array([r["mem"] for r in rows], dtype=np.int32)
        dom_ok = np.ones((len(rows), len(names)), dtype=bool)
        for i, r in enumerate(rows):
            if r["zone"]:
                dom_ok[i] = [r["zone"] in a.domains for a in aggs]
            if r["not_cell"] and r["not_cell"] in names:
                dom_ok[i, names.index(r["not_cell"])] = False
        verdict = self._score(dem_cpu, dem_mem, cpu_free, mem_free,
                              cpu_cap, mem_cap, pressure, ready, dom_ok)
        choice, fit = verdict[0], verdict[1]
        # ---- group + optimistic column update (charge pending now so a
        # same-pump second batch sees the admission pressure)
        assigned: Dict[str, List] = {}
        leftover: List = []
        with self._lock:
            for i, r in enumerate(rows):
                if fit[i] <= 0:
                    leftover.extend(r["pods"])
                    self.counters["unroutable"] += len(r["pods"])
                    continue
                cell = names[int(choice[i])]
                assigned.setdefault(cell, []).extend(r["pods"])
                agg = self.aggs[cell]
                agg.pending += len(r["pods"])
                if "gang" in r:
                    self.counters["routed_gangs"] += 1
                self.counters["routed_pods"] += len(r["pods"])
        return assigned, leftover

    def _score(self, dem_cpu, dem_mem, cpu_free, mem_free, cpu_cap,
               mem_cap, pressure, ready, dom_ok) -> np.ndarray:
        from kubernetes_tpu.ops.federation import (
            route_scores,
            route_scores_host,
        )
        c = len(dem_cpu)
        dev = self.use_device
        if dev is None:
            dev = c >= DEVICE_MIN_BATCH
        if not dev:
            with self._lock:
                self.counters["host_batches"] += 1
            return route_scores_host(dem_cpu, dem_mem, cpu_free,
                                     mem_free, cpu_cap, mem_cap,
                                     pressure, ready, dom_ok)
        # pad the C axis to the r10 bucket ladder so the jit kernel
        # compiles once per bucket, not once per batch size; padded rows
        # have zero demand and an all-True domain row — fit everywhere,
        # verdict discarded at the trim
        from kubernetes_tpu.ops.predicates import bucket
        cb = bucket(c)
        if cb != c:
            pad = cb - c
            dem_cpu = np.pad(dem_cpu, (0, pad))
            dem_mem = np.pad(dem_mem, (0, pad))
            dom_ok = np.pad(dom_ok, ((0, pad), (0, 0)),
                            constant_values=True)
        with self._lock:
            self.counters["device_batches"] += 1
        out = route_scores(dem_cpu, dem_mem, cpu_free, mem_free,
                           cpu_cap, mem_cap, pressure, ready, dom_ok)
        verdict = np.asarray(out)  # graftlint: sync-ok — the ONE routing-verdict fetch per batch
        return verdict[:, :c]

    # ------------------------------------------------------------- admission

    def admit(self, pods: List,
              exclude: Optional[Dict[str, str]] = None) -> Dict[str, int]:
        """Route + admit one batch; returns per-cell accepted counts.
        The admission span (route decision + every ADMIT round trip) is
        recorded per batch — the 'router admission on top of per-cell
        create->bound' number the bench reads as p99."""
        t0 = time.monotonic()
        assigned, leftover = self.route(pods, exclude=exclude)
        self.backlog.extend(leftover)
        out: Dict[str, int] = {}
        for c in self.cells:
            batch = assigned.get(c.name)
            if not batch:
                continue
            with self._lock:
                self._seq += 1
                idem = f"{self.router_id}:{c.name}:{self._seq}"
            ta = time.monotonic()
            try:
                accepted, replayed = c.admit(idem, batch)
            except Exception:
                # ambiguous wire fault: replay the SAME key once — the
                # cell's idem cache converges it to the recorded answer
                accepted, replayed = c.admit(idem, batch)
            self._event(c.name, "admit", ta, n=len(batch),
                        accepted=accepted)
            with self._lock:
                self.counters["admitted"] += accepted
                self.counters["admit_replays"] += replayed
            out[c.name] = accepted
        if pods:
            self.admit_spans.append(
                (t0, time.monotonic() - t0, len(pods)))
        return out

    def pump_backlog(self) -> int:
        """Retry the unroutable backlog after a refresh freed capacity."""
        if not self.backlog:
            return 0
        pods, self.backlog = self.backlog, []
        before = len(pods)
        self.admit(pods)
        return before - len(self.backlog)

    def spill_pump(self) -> int:
        """One spillover cycle: refresh every column, drain every cell's
        spill buffer, re-route the drained pods AWAY from their origin
        cells. Returns pods moved."""
        drained = self.refresh(drain_spill=True)
        moved = 0
        if drained:
            exclude = {p.key(): origin for origin, p in drained}
            self.admit([p for _o, p in drained], exclude=exclude)
            moved = len(drained)
            with self._lock:
                self.counters["spill_moved"] += moved
        self.pump_backlog()
        return moved

    # -------------------------------------------------------------- brownout

    def brownout(self, cell: str) -> int:
        """Mark a cell NotReady and drain it: spill buffer AND every
        still-pending pod leave its store, re-routed to the survivors
        through the ordinary spillover path. Returns pods evacuated."""
        handle = self._handle(cell)
        with self._lock:
            self.aggs[cell].ready = False
            self.counters["brownouts"] += 1
        t0 = time.monotonic()
        d, evacuated = handle.cell_agg(drain_spill=True, evacuate=True)
        agg = CellAggregate.from_dict(d)
        agg.ready = False
        with self._lock:
            self.aggs[cell] = agg
        self._event(cell, "brownout", t0, evacuated=len(evacuated))
        if evacuated:
            exclude = {p.key(): cell for p in evacuated}
            self.admit(evacuated, exclude=exclude)
            with self._lock:
                self.counters["evacuated_moved"] += len(evacuated)
        return len(evacuated)

    def recover(self, cell: str) -> None:
        """Bring a browned-out cell back: column re-hydrated from RELIST
        truth, ready again for routing."""
        handle = self._handle(cell)
        t0 = time.monotonic()
        nodes, bound = handle.relist()
        agg = aggregate_from_lists(nodes, bound, cell=cell)
        agg.ready = True
        with self._lock:
            self.aggs[cell] = agg
            self.counters["recoveries"] += 1
        self._event(cell, "recover", t0, bound=len(bound))

    # ------------------------------------------------------------ telemetry

    def _handle(self, cell: str):
        for c in self.cells:
            if c.name == cell:
                return c
        raise KeyError(cell)

    def _event(self, cell: str, kind: str, t0: float, **kw) -> None:
        lane = self._events[cell]
        if len(lane) < MAX_EVENTS_PER_CELL:
            e = {"kind": kind, "t": t0, "dur": time.monotonic() - t0}
            e.update(kw)
            lane.append(e)

    def counters_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counters)

    def lanes(self) -> List[Dict]:
        """Per-cell lanes in perfetto.add_process_lanes worker shape —
        one process row per cell with its relist/agg/admit/brownout
        spans, beside whatever the cells themselves traced."""
        with self._lock:
            return [{"worker": c.name,
                     "counts": {"events": len(self._events[c.name])},
                     "events": list(self._events[c.name])}
                    for c in self.cells]

    def admission_p99_ms(self) -> float:
        """p99 over per-batch admission spans (route + admit wire), ms."""
        if not self.admit_spans:
            return 0.0
        durs = sorted(d for _t, d, _n in self.admit_spans)
        i = min(len(durs) - 1, int(round(0.99 * (len(durs) - 1))))
        return durs[i] * 1e3

    def close(self) -> None:
        for c in self.cells:
            c.close()


__all__ = ["DEVICE_MIN_BATCH", "FederationRouter", "LocalCell",
           "MAX_EVENTS_PER_CELL", "WireCell"]
