"""Watch-driven federation control plane.

The reference's federated sync controllers run on informers + workqueues
exactly like in-cluster controllers (federation/pkg/federatedtypes/ sync
controller: federated-object informer + per-cluster child informers, keys
through a rate-limited queue, cluster lifecycle triggering full
reconciliation) — they never poll. Round 4's federation layer exposed only
`sync_all()` called by tests/CLI (r4 VERDICT weak #6); this module wires
the SAME sync bodies into the repo's informer/workqueue machinery:

- a federation-apiserver informer per federated kind enqueues object keys
  on ADD/MODIFY/DELETE;
- a Cluster informer enqueues EVERYTHING on any cluster event (join,
  unjoin, readiness flip) — the cluster-lifecycle full-reconcile of the
  reference's clusterDeliverer — and auto-starts/stops the member-cluster
  watches;
- each member cluster gets child-kind informers whose events enqueue the
  PARENT federated key, so member-side drift (a deleted or hand-scaled
  child) self-heals from the member's own watch stream;
- one deduplicating WorkQueue carries the keys; pump() drains it through
  the per-type sync bodies (per-object for the replica-planned kinds,
  per-kind for the propagation kinds whose body is whole-kind);
- start() runs the same loop on a background worker thread (the
  controller-manager's `go wait.Until(worker, ...)`) so a live deployment
  needs NO caller-side pumping: cluster-loss rebalance happens from the
  watch event alone. pump() remains the deterministic single-threaded
  test hook.

No caller ever needs sync_all(): cluster-loss rebalance happens from the
watch event alone (tests/test_federation_watch.py)."""

from __future__ import annotations

import threading
from kubernetes_tpu.analysis import lockcheck
from typing import Dict, Optional, Tuple

from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.client.workqueue import WorkQueue
from kubernetes_tpu.federation.controller import (
    CLUSTER_KIND,
    FEDERATED_DEPLOY_KIND,
    FEDERATED_DS_KIND,
    FEDERATED_RS_KIND,
    FederatedDaemonSetController,
    FederatedDeploymentController,
    FederatedPropagationController,
    FederatedReplicaSetController,
    FederationControlPlane,
    PROPAGATED_KINDS,
)
from kubernetes_tpu.server.apiserver_lite import NotFound

# whole-kind sentinel: the propagation sync bodies reconcile a kind at a
# time, so their queue key is the kind itself
ALL = "*"

# member child kind -> (federated kind, per_object)
CHILD_TO_FED: Dict[str, Tuple[str, bool]] = {
    "ReplicaSet": (FEDERATED_RS_KIND, True),
    "Deployment": (FEDERATED_DEPLOY_KIND, True),
    "DaemonSet": (FEDERATED_DS_KIND, False),
    "ConfigMap": ("FederatedConfigMap", False),
    "Secret": ("FederatedSecret", False),
    "Namespace": ("FederatedNamespace", False),
}


class FederationSyncLoop:
    def __init__(self, plane: FederationControlPlane):
        self.plane = plane
        self.queue = WorkQueue()
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._pump_lock = lockcheck.make_lock("FederationSyncLoop._pump_lock")  # worker and test-hook pump()
        # share one body; serialized so sync bodies never interleave
        self.rs_ctrl = FederatedReplicaSetController(plane)
        self.deploy_ctrl = FederatedDeploymentController(plane)
        self.ds_ctrl = FederatedDaemonSetController(plane)
        self.prop_ctrl = FederatedPropagationController(plane)
        self.syncs = 0  # diagnostics
        self._fed_factory = SharedInformerFactory(plane.api)
        self._member_factories: Dict[str, SharedInformerFactory] = {}
        # federated-object informers: every event enqueues that object
        for kind in (FEDERATED_RS_KIND, FEDERATED_DEPLOY_KIND):
            self._watch_fed_kind(kind, per_object=True)
        for kind in (FEDERATED_DS_KIND,) + tuple(
                "Federated" + k for k in PROPAGATED_KINDS):
            self._watch_fed_kind(kind, per_object=False)
        # cluster lifecycle: any event -> watch/unwatch member + requeue all
        self._fed_factory.informer(CLUSTER_KIND).add_event_handler(
            on_add=lambda c: self._on_cluster(c.name),
            on_update=lambda old, new: self._on_cluster(new.name),
            on_delete=lambda c: self._on_cluster_gone(c.name))

    # ------------------------------------------------------------ watches

    def _watch_fed_kind(self, kind: str, per_object: bool) -> None:
        def key_of(obj):
            if per_object:
                return (kind, obj.namespace, obj.name)
            return (kind, ALL, ALL)

        self._fed_factory.informer(kind).add_event_handler(
            on_add=lambda o: self.queue.add(key_of(o)),
            on_update=lambda old, new: self.queue.add(key_of(new)),
            on_delete=lambda o: self.queue.add(key_of(o)))

    def _on_cluster(self, name: str) -> None:
        if name in self.plane.members \
                and name not in self._member_factories:
            self._watch_member(name)
        self.enqueue_all()

    def _on_cluster_gone(self, name: str) -> None:
        factory = self._member_factories.pop(name, None)
        if factory is not None:
            factory.stop()  # deregister the watches — a rejoin builds a
            # fresh factory; dangling ones would buffer events forever
        self.enqueue_all()

    def _watch_member(self, name: str) -> None:
        """Child-kind informers over one member cluster: member-side drift
        enqueues the federated parent."""
        api = self.plane.members.get(name)
        if api is None:
            return
        factory = SharedInformerFactory(api)
        for child, (fed_kind, per_object) in CHILD_TO_FED.items():
            def key_of(obj, fed_kind=fed_kind, per_object=per_object):
                if per_object:
                    return (fed_kind, obj.namespace, obj.name)
                return (fed_kind, ALL, ALL)

            factory.informer(child).add_event_handler(
                on_add=lambda o, k=key_of: self.queue.add(k(o)),
                on_update=lambda old, new, k=key_of: self.queue.add(k(new)),
                on_delete=lambda o, k=key_of: self.queue.add(k(o)))
        self._member_factories[name] = factory

    def enqueue_all(self) -> None:
        """The clusterDeliverer full-reconcile: every federated object (or
        kind) back onto the queue."""
        for kind in (FEDERATED_RS_KIND, FEDERATED_DEPLOY_KIND):
            for obj in self.plane.api.list(kind)[0]:
                self.queue.add((kind, obj.namespace, obj.name))
        for kind in (FEDERATED_DS_KIND,) + tuple(
                "Federated" + k for k in PROPAGATED_KINDS):
            self.queue.add((kind, ALL, ALL))

    # --------------------------------------------------------------- pump

    def _sync_key(self, key: Tuple[str, str, str]) -> None:
        kind, ns, name = key
        if kind == FEDERATED_RS_KIND or kind == FEDERATED_DEPLOY_KIND:
            ctrl = self.rs_ctrl if kind == FEDERATED_RS_KIND \
                else self.deploy_ctrl
            try:
                frs = self.plane.api.get(kind, ns, name)
            except NotFound:
                # deletion: the propagation of absence — remove children
                self._delete_children(ctrl.CHILD_KIND, ns, name)
                return
            ctrl.sync(frs)
        elif kind == FEDERATED_DS_KIND:
            self.ds_ctrl.sync_all()
        else:
            self.prop_ctrl.sync_all()

    def _delete_children(self, child_kind: str, ns: str, name: str) -> None:
        # ALL members, not just ready ones — a child orphaned in a
        # not-ready cluster would otherwise survive forever (nothing
        # requeues a deleted federated object when the cluster comes back).
        # ONLY managed children: member watch events fire for objects
        # federation never owned (a user's local ReplicaSet, a member
        # Deployment's hash-named child RSs), and deleting those here
        # would destroy user workloads — the same ownership guard
        # propagate_kind applies (controller.py MANAGED_ANNOTATION)
        from kubernetes_tpu.federation.controller import MANAGED_ANNOTATION
        for api in list(self.plane.members.values()):
            try:
                cur = api.get(child_kind, ns, name)
            except NotFound:
                continue
            if getattr(cur, "annotations", {}).get(MANAGED_ANNOTATION) \
                    != "true":
                continue
            try:
                api.delete(child_kind, ns, name)
            except NotFound:
                pass

    def pump(self, rounds: int = 1) -> int:
        """Deterministic single-threaded loop: step every informer (watch
        events fire the handlers above), then drain the queue through the
        sync bodies. Returns syncs performed. This is the TEST hook; a live
        deployment runs the same body on the start() worker thread."""
        n = 0
        with self._pump_lock:
            for _ in range(rounds):
                self._fed_factory.step_all()
                for factory in list(self._member_factories.values()):
                    factory.step_all()
                while len(self.queue):
                    try:
                        key = self.queue.get(timeout=0)
                    except Exception:
                        break
                    try:
                        self._sync_key(key)
                        self.syncs += 1
                        n += 1
                    finally:
                        self.queue.done(key)
        return n

    # -------------------------------------------------- background worker

    def start(self, interval_s: float = 0.05) -> None:
        """Run the pump on a daemon worker thread (the reference's
        controller-manager workers, federated sync controller's
        `go wait.Until`): watch events drain into syncs continuously with
        no caller-side pump(rounds) — cluster-loss rebalance, member-drift
        self-heal, and deletion propagation all happen on their own.
        Idempotent while running; a restart after stop() always yields a
        live worker, even if the previous one is still wedged in a hung
        sync body (each worker watches its OWN stop token, so the orphan
        exits when it unwedges and can never be revived; overlap is
        serialized by _pump_lock)."""
        if self._worker is not None and self._worker.is_alive() \
                and not self._stop.is_set():
            return  # already running
        stop = threading.Event()
        self._stop = stop

        def loop():
            while not stop.wait(interval_s):
                try:
                    self.pump(1)
                except Exception:  # a sync body failing on transient state
                    # (member Conflict, mid-churn NotFound) must not kill
                    # the worker — the queue re-delivers on the next event
                    # or full reconcile, like a crashing controller worker
                    # being restarted by wait.Until
                    continue

        self._worker = threading.Thread(target=loop, daemon=True,
                                        name="federation-sync-worker")
        self._worker.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout=timeout)
            if self._worker.is_alive():
                # wedged in a hung sync body: keep the handle (its own stop
                # token is set, so it exits when it unwedges; start() will
                # create a fresh worker with a fresh token)
                return
            self._worker = None
