"""One federation cell: the r18 engine UNCHANGED behind the binary wire
(ISSUE 20).

A cell is the unit of cluster management (PAPERS.md §Borg): its OWN
ApiServerLite store, its OWN engine Scheduler + always-on ScheduleLoop,
served to the front-door router over server/asyncwire.py. The federation
tier adds exactly three wire behaviors on top — nothing inside the
engine changes:

  - ``ADMIT``: the router hands this cell a batch of pending pods. Each
    pod enters the cell store with ``create`` (the scheduler's watch
    picks it up like any arrival); a (kind, ns, name) Conflict means the
    pod is ALREADY here — the replay half of cross-cell exactly-once
    (idempotency keys catch whole-batch replays, the store key catches
    per-pod ones).
  - ``CELL_AGG``: the cell's routing column (federation/aggregate.py),
    maintained delta-by-delta off the cell's OWN watch log on every pull
    — the r11 Protean patch discipline one level up; a compacted log
    falls back to the store-walk rebuild. The drain flag also hands back
    (and forgets) the cell's spill buffer; the evacuate flag additionally
    uproots every still-pending pod — the brownout path.
  - ``RELIST``: overridden to answer from STORE truth (nodes + bound
    pods straight off ApiServerLite), because the router's aggregates
    and the cross-cell audits are defined against commit truth, not any
    evaluator cache.

Spillover: the engine's ``spill_handler`` hook (engine/scheduler.py)
hands pods whose unschedulable verdicts crossed the attempt threshold to
``CellService.spill`` — they wait in the spill buffer until the router's
next drain pulls them OUT of this cell (store delete included, so the
cell's pending count and the pod's cell-of-record move atomically under
the store lock ordering: deleted here before admitted anywhere else).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.analysis import lockcheck
from kubernetes_tpu.federation.aggregate import (
    CellAggregate,
    aggregate_from_lists,
    fold_log,
)
from kubernetes_tpu.server.apiserver_lite import (
    ApiServerLite,
    Conflict,
    NotFound,
    TooOldResourceVersion,
)
from kubernetes_tpu.server.embedded import VerdictService

# idempotency-key memory: enough for every router retry burst in flight;
# beyond this the store's per-pod Conflict is still authoritative
MAX_IDEM_KEYS = 65536


class CellService(VerdictService):
    """The federation verbs over one cell's store + engine.

    ``backend=None`` is the normal federation shape: the router only
    speaks ADMIT / CELL_AGG / RELIST, none of which touch the extender
    backend — a cell co-hosting the sidecar verbs passes its backend
    through and everything composes."""

    def __init__(self, api: ApiServerLite, cell: str = "",
                 backend=None):
        super().__init__(backend)
        self.api = api
        self.cell = cell
        self._lock = lockcheck.make_lock(f"CellService[{cell}]._lock")
        self._agg = CellAggregate(cell=cell)
        self._cursor = 0
        self._spill: Dict[str, object] = {}          # pod key -> pod
        self._idem: Dict[str, Tuple[int, int]] = {}  # key -> result
        self.counters: Dict[str, int] = {
            "admits": 0, "admit_pods": 0, "admit_replays": 0,
            "spilled": 0, "spill_drained": 0, "evacuated": 0,
            "agg_pulls": 0, "agg_rebuilds": 0,
        }

    # ------------------------------------------------------------- verbs

    def relist(self):
        """(nodes, bound pods) from STORE truth — the hydration source
        for router aggregates and the surface the cross-cell audits
        read. The engine's own cache never answers federation reads."""
        nodes, _rv = self.api.list("Node")
        pods, _rv = self.api.list("Pod")
        return nodes, [p for p in pods if p.node_name]

    def admit(self, idem_key: str, pods: List) -> Tuple[int, int]:
        """Admit a router batch into this cell; returns (accepted,
        replayed). Exactly-once composes from two layers: a repeated
        ``idem_key`` replays the recorded answer without touching the
        store (the ambiguous-wire-fault retry), and a pod whose store
        key already exists counts replayed instead of double-entering
        (the pod-level layer that survives idem-cache eviction)."""
        with self._lock:
            if idem_key:
                hit = self._idem.get(idem_key)
                if hit is not None:
                    return hit
        accepted = replayed = 0
        for p in pods:
            try:
                self.api.create("Pod", p)
                accepted += 1
            except Conflict:
                replayed += 1
        out = (accepted, replayed)
        with self._lock:
            if idem_key:
                if len(self._idem) >= MAX_IDEM_KEYS:
                    self._idem.clear()
                self._idem[idem_key] = out
            self.counters["admits"] += 1
            self.counters["admit_pods"] += accepted
            self.counters["admit_replays"] += replayed
        return out

    def spill(self, pods: List) -> None:
        """Engine spill hook: pods THIS cell cannot place, staged for
        the router's next drain. Keyed — a pod the engine spills twice
        (requeue races) stages once."""
        with self._lock:
            for p in pods:
                self._spill[p.key()] = p
            self.counters["spilled"] = len(self._spill) \
                + self.counters["spill_drained"]

    def cell_aggregate(self, drain_spill: bool = False,
                       evacuate: bool = False):
        """The cell's routing column + (optionally) its outbound pods.

        Returns (aggregate dict, spilled pods). Every pull folds the
        watch log since the last cursor into the live aggregate —
        incremental by default, store-walk rebuild when the log was
        compacted past the cursor (monotone counters re-base to store
        truth then; the oracle A/B test covers the incremental path).
        Drained/evacuated pods are DELETED from the store before they
        are returned, so a pod's cell-of-record is never two cells."""
        with self._lock:
            self.counters["agg_pulls"] += 1
            self._fold_locked()
            out: List = []
            if drain_spill and self._spill:
                out.extend(self._spill.values())
                self.counters["spill_drained"] += len(self._spill)
                self._spill.clear()
            if evacuate:
                pods, _rv = self.api.list("Pod")
                seen = {p.key() for p in out}
                pending = [p for p in pods
                           if not p.node_name and p.key() not in seen]
                out.extend(pending)
                self.counters["evacuated"] += len(pending)
            for p in out:
                try:
                    self.api.delete("Pod", p.namespace, p.name)
                except NotFound:
                    pass
            if out:
                self._fold_locked()  # the deletes just logged
            return self._agg.to_dict(), out

    # ----------------------------------------------------------- internals

    def _fold_locked(self) -> None:
        lockcheck.assert_held(self._lock, "CellService._fold_locked")
        try:
            evs = self.api.watch_since(("Node", "Pod"), self._cursor,
                                       timeout=0)
            self._cursor = fold_log(self._agg, evs, self._cursor)
        except TooOldResourceVersion:
            nodes, _rv = self.api.list("Node")
            pods, rv = self.api.list("Pod")
            fresh = aggregate_from_lists(nodes, pods, cell=self.cell)
            fresh.ready = self._agg.ready
            fresh.gen = self._agg.gen + 1
            self._agg = fresh
            self._cursor = rv
            self.counters["agg_rebuilds"] += 1

    def counters_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counters)


class CellAgent:
    """One whole cell, composed: store + engine + always-on loop + wire.

    The engine is the r18 Scheduler verbatim — the ONLY touchpoint is
    the spill_handler hook. ``start()`` boots the wire server and a pump
    thread driving the ScheduleLoop; pods arrive via ADMIT (store
    create), the loop's sync() admits them like any watch arrival."""

    def __init__(self, name: str, nodes: List,
                 budget_s: float = 0.05, min_quantum: int = 64,
                 max_quantum: int = 4096,
                 spill_after_attempts: int = 2,
                 wire_workers: int = 2, port: int = 0):
        from kubernetes_tpu.engine.scheduler import Scheduler
        from kubernetes_tpu.server.asyncwire import AsyncBinaryServer

        self.name = name
        self.api = ApiServerLite(
            max_log=max(200_000, 8 * (len(nodes) + 4096)))
        for n in nodes:
            self.api.create("Node", n)
        self.sched = Scheduler(self.api, record_events=False)
        self.service = CellService(self.api, cell=name)
        self.sched.spill_handler = self.service.spill
        self.sched.spill_after_attempts = spill_after_attempts
        self.sched.start()
        self.loop = self.sched.stream(budget_s=budget_s,
                                      min_quantum=min_quantum,
                                      max_quantum=max_quantum)
        self.server = AsyncBinaryServer(self.service, port=port,
                                        workers=wire_workers)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.server.port

    def start(self) -> None:
        self.server.start()
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name=f"cell-{self.name}")
        self._thread.start()

    def _pump(self) -> None:
        while not self._stop.is_set():
            self.loop.step(wait=0.002)

    def stop(self) -> Dict[str, int]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        stats = self.loop.close()
        self.server.stop()
        return stats


def run_cell_process(cfg: Dict, out_q, ctrl_q) -> None:
    """One cell as a full OS process (spawn target — module level,
    import-safe). Announces {"cell", "port", "ok"} on out_q once the
    wire is up, pumps until ctrl_q delivers "stop", then reports the
    final accounting the federation audits need: every (pod, node)
    placement from STORE truth plus the service counters."""
    import os
    # before any kubernetes_tpu import: the engine pulls in jax, and a
    # CI cell must never grab an accelerator the parent owns
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from kubernetes_tpu.models.hollow import hollow_nodes
    from kubernetes_tpu.parallel.multiproc import audit_duplicate_binds

    name = cfg["cell"]
    nodes = hollow_nodes(int(cfg.get("n_nodes", 64)),
                         seed=int(cfg.get("seed", 0)))
    zones = max(int(cfg.get("zones", 8)), 1)
    zone_prefix = cfg.get("zone_prefix", f"{name}-z")
    for i, n in enumerate(nodes):
        n.labels["zone"] = f"{zone_prefix}{i % zones}"
    agent = CellAgent(
        name, nodes,
        budget_s=float(cfg.get("budget_s", 0.05)),
        min_quantum=int(cfg.get("min_quantum", 64)),
        max_quantum=int(cfg.get("max_quantum", 4096)),
        spill_after_attempts=int(cfg.get("spill_after_attempts", 2)))
    try:
        agent.start()
        out_q.put({"cell": name, "port": agent.port, "ok": True})
        while True:
            try:
                msg = ctrl_q.get(timeout=0.5)
            except Exception:
                continue
            if msg == "stop":
                break
        agent.stop()
        pods, _rv = agent.api.list("Pod")
        bound = {p.key(): p.node_name for p in pods if p.node_name}
        out_q.put({
            "cell": name, "ok": True, "final": True,
            "bound": bound,
            "pending": sum(1 for p in pods if not p.node_name),
            "duplicate_binds": audit_duplicate_binds(agent.api),
            "counters": agent.service.counters_snapshot(),
        })
    except Exception as e:  # noqa: BLE001 — report, never hang the join
        out_q.put({"cell": name, "ok": False, "final": True,
                   "error": f"{type(e).__name__}: {e}"})


__all__ = ["CellAgent", "CellService", "MAX_IDEM_KEYS",
           "run_cell_process"]
