"""Per-cell aggregate state for the federation router (ISSUE 20).

One cell collapses to ONE column of the router's [C, M] routing tensor:
capacity headroom (cpu/mem allocatable minus requested, quantized the
same way resource_row quantizes pod requests), band pressure (pending
backlog normalized by node count), and affinity-domain presence (which
topology domains — zone labels — exist in the cell at all, so a pod with
a required zone affinity never routes to a cell that cannot satisfy it).

Two producers, ONE math:

- ``aggregate_from_lists(nodes, pods)`` rebuilds the aggregate from a
  full (nodes, bound/pending pods) listing — the RELIST-hydration path
  and the store-truth ORACLE the incremental path is audited against;
- ``CellAggregate.apply_event(ev)`` folds one watch event into a live
  aggregate — the delta-by-delta maintenance the cell runs over its own
  event log (the r11 Protean patch discipline one level up: bind/evict
  confirmations patch the column; only a RELIST rebuilds it wholesale).

The A/B test (tests/test_federation_router.py) pins that draining a
cell's whole event log through apply_event lands on the SAME aggregate
``aggregate_from_lists`` computes from the final store state — if the
incremental column ever drifts from store truth, routing decisions are
being made on a lie and the test fails, not the router.

Pure host math — no jax import; the [C, M] tensor assembly and scoring
live in ops/federation.py behind the jit registry.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# quantization mirrors state/snapshot resource_row: cpu in millicores,
# memory in MiB — int headroom keeps the routing tensor integer-exact
_MEM_MIB = 1 << 20


def _pod_demand(pod) -> Tuple[int, int]:
    """(cpu_m, mem_mib) summed over containers — the same request shape
    resource_row quantizes, flattened to the two axes the router scores."""
    cpu = 0
    mem = 0
    for c in pod.containers:
        cpu += int(c.requests.get("cpu", 0))
        mem += int(c.requests.get("memory", 0))
    return cpu, mem // _MEM_MIB


def _node_alloc(node) -> Tuple[int, int]:
    return (int(node.allocatable.milli_cpu),
            int(node.allocatable.memory) // _MEM_MIB)


def _node_ready(node) -> bool:
    # Node.is_ready already folds unschedulable + Ready/OutOfDisk/
    # NetworkUnavailable conditions — the predicate layer's truth
    return node.is_ready()


@dataclass
class CellAggregate:
    """One cell's routing column. ``gen`` counts folds (events applied or
    rebuilds) so the router can tell a fresh column from a stale one."""

    cell: str = ""
    gen: int = 0
    nodes_total: int = 0
    nodes_ready: int = 0
    cpu_alloc_m: int = 0          # sum allocatable cpu (millicores), ready nodes
    mem_alloc_mib: int = 0
    cpu_used_m: int = 0           # sum requests of BOUND pods
    mem_used_mib: int = 0
    pending: int = 0              # pods in store without a node
    bound_total: int = 0          # monotone bind confirmations
    evictions_total: int = 0      # monotone unbind/delete-of-bound
    domains: Dict[str, int] = field(default_factory=dict)  # zone -> nodes
    # not-ready mark is ROUTER state (brownout), carried here so one
    # object is the whole column; the cell itself never sets it
    ready: bool = True
    # internal per-object memos the incremental fold needs (last-seen
    # charge per bound pod, per-node contribution) — not wire fields
    _pod_charge: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    _node_row: Dict[str, Tuple[int, int, bool, str]] = field(
        default_factory=dict)

    # ------------------------------------------------------------ wire form

    WIRE_KEYS = ("cell", "gen", "nodes_total", "nodes_ready",
                 "cpu_alloc_m", "mem_alloc_mib", "cpu_used_m",
                 "mem_used_mib", "pending", "bound_total",
                 "evictions_total", "domains", "ready")

    def to_dict(self) -> Dict:
        return {k: getattr(self, k) for k in self.WIRE_KEYS}

    @classmethod
    def from_dict(cls, d: Dict) -> "CellAggregate":
        agg = cls()
        for k in cls.WIRE_KEYS:
            if k in d:
                setattr(agg, k, d[k])
        agg.domains = dict(agg.domains)
        return agg

    # ------------------------------------------------------------- headroom

    def headroom(self) -> Tuple[int, int]:
        return (self.cpu_alloc_m - self.cpu_used_m,
                self.mem_alloc_mib - self.mem_used_mib)

    # --------------------------------------------------- incremental folds

    def _add_node(self, node) -> None:
        cpu, mem = _node_alloc(node)
        ready = _node_ready(node)
        zone = (getattr(node, "labels", None) or {}).get("zone", "")
        self._node_row[node.name] = (cpu, mem, ready, zone)
        self.nodes_total += 1
        if ready:
            self.nodes_ready += 1
            self.cpu_alloc_m += cpu
            self.mem_alloc_mib += mem
        if zone:
            self.domains[zone] = self.domains.get(zone, 0) + 1

    def _drop_node(self, name: str) -> None:
        row = self._node_row.pop(name, None)
        if row is None:
            return
        cpu, mem, ready, zone = row
        self.nodes_total -= 1
        if ready:
            self.nodes_ready -= 1
            self.cpu_alloc_m -= cpu
            self.mem_alloc_mib -= mem
        if zone:
            left = self.domains.get(zone, 0) - 1
            if left > 0:
                self.domains[zone] = left
            else:
                self.domains.pop(zone, None)

    def _charge_pod(self, pod) -> None:
        cpu, mem = _pod_demand(pod)
        self._pod_charge[pod.key()] = (cpu, mem)
        self.cpu_used_m += cpu
        self.mem_used_mib += mem

    def _discharge_pod(self, key: str) -> None:
        cpu, mem = self._pod_charge.pop(key, (0, 0))
        self.cpu_used_m -= cpu
        self.mem_used_mib -= mem

    def apply_event(self, ev) -> None:
        """Fold one ApiServerLite WatchEvent. Pod MODIFIED with a node is
        the bind confirmation (pending -> bound, capacity charged); a
        DELETED bound pod (or MODIFIED back to nodeless — eviction's
        unbind) discharges and counts an eviction."""
        self.gen += 1
        kind, typ, obj = ev.kind, ev.type, ev.obj
        if kind == "Node":
            if typ == "ADDED":
                self._add_node(obj)
            elif typ == "DELETED":
                self._drop_node(obj.name)
            elif typ == "MODIFIED":
                self._drop_node(obj.name)
                self._add_node(obj)
            return
        if kind != "Pod":
            return
        key = obj.key()
        bound_now = bool(getattr(obj, "node_name", None))
        was_bound = key in self._pod_charge
        if typ == "ADDED":
            if bound_now:
                self._charge_pod(obj)
                self.bound_total += 1
            else:
                self.pending += 1
        elif typ == "MODIFIED":
            if bound_now and not was_bound:
                self.pending = max(self.pending - 1, 0)
                self._charge_pod(obj)
                self.bound_total += 1
            elif not bound_now and was_bound:
                self._discharge_pod(key)
                self.pending += 1
                self.evictions_total += 1
        elif typ == "DELETED":
            if was_bound:
                self._discharge_pod(key)
                self.evictions_total += 1
            else:
                self.pending = max(self.pending - 1, 0)


def aggregate_from_lists(nodes: List, pods: List,
                         cell: str = "") -> CellAggregate:
    """Rebuild the whole column from a (nodes, pods) listing — the
    RELIST-hydration path and the oracle the incremental fold is audited
    against. ``pods`` is every pod the cell's store knows: bound pods
    charge capacity, nodeless ones count pending."""
    agg = CellAggregate(cell=cell, gen=1)
    for n in nodes:
        agg._add_node(n)
    for p in pods:
        if getattr(p, "node_name", None):
            agg._charge_pod(p)
            agg.bound_total += 1
        else:
            agg.pending += 1
    return agg


def fold_log(agg: CellAggregate, events, from_rv: int = 0) -> int:
    """Apply every event with resource_version > from_rv; returns the new
    cursor. The cell calls this on each aggregate() pull — delta-by-delta
    maintenance off its own watch log, never a store walk."""
    cursor = from_rv
    for ev in events:
        if ev.rv <= from_rv:
            continue
        agg.apply_event(ev)
        cursor = max(cursor, ev.rv)
    return cursor


__all__ = ["CellAggregate", "aggregate_from_lists", "fold_log"]
