"""Federation control plane: member-cluster registry + federated-ReplicaSet
sync controller.

The minimal L9 slice of the reference's federation/ tree (38.9k LoC):

- FederationControlPlane owns its OWN apiserver-lite (the
  federation-apiserver) holding Cluster objects
  (federation/apis/federation/types.go Cluster) and FederatedReplicaSet
  objects (a plain workloads.ReplicaSet stored under the federated kind,
  exactly how the federation apiserver re-uses the member type).
- FederatedReplicaSetController is the per-type sync controller
  (federation/pkg/federatedtypes/replicaset.go + scheduling.go +
  sync controller): for each federated RS it reads the replica-set-
  preferences annotation, gathers each READY member cluster's current
  replica state, runs the planner, and creates/updates/deletes the
  per-cluster ReplicaSets to match the plan. A cluster going NotReady
  (or being unjoined) drops out of the plan and its replicas move —
  the rebalance-on-cluster-loss story.

Member clusters are in-process ApiServerLite instances (the rig's answer
to multi-cluster), each typically running its own ReplicaSetController +
Scheduler + fleet; the federation layer only talks to their API servers,
like the reference's federated clientsets.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.api.workloads import ReplicaSet
from kubernetes_tpu.federation.planner import (
    DEFAULT_PREFERENCES,
    PREFERENCES_ANNOTATION,
    Planner,
    ReplicaAllocationPreferences,
)
from kubernetes_tpu.server.apiserver_lite import (
    ApiServerLite,
    Conflict,
    NotFound,
)

FEDERATED_RS_KIND = "FederatedReplicaSet"
FEDERATED_DEPLOY_KIND = "FederatedDeployment"
CLUSTER_KIND = "Cluster"


@dataclass
class Cluster:
    """federation Cluster object: name + readiness (types.go Cluster/
    ClusterStatus; readiness is maintained by the cluster controller's
    healthz probes — here set by join/mark_ready). zone/region feed the
    service-DNS record hierarchy (types.go ClusterStatus.Zones/Region)."""

    name: str
    ready: bool = True
    zone: str = ""
    region: str = ""
    resource_version: int = 0


@dataclass
class FederatedReplicaSet:
    """The federated object: a ReplicaSet template + total replicas +
    preferences annotation (replicaset.go reuses extensions/ReplicaSet)."""

    name: str
    namespace: str = "default"
    replicas: int = 0
    template: ReplicaSet = field(default_factory=lambda: ReplicaSet(name=""))
    annotations: Dict[str, str] = field(default_factory=dict)
    # status (UpdateFederatedStatus): aggregated across clusters
    ready_replicas: int = 0
    resource_version: int = 0

    def key(self) -> str:
        return self.namespace + "/" + self.name


class FederationControlPlane:
    """The federation-apiserver + cluster registry. The DNS provider
    lives here (one zone per federation, like the reference's dnsprovider
    config on the federation-controller-manager) so records persist
    across sync invocations."""

    def __init__(self):
        self.api = ApiServerLite()
        self.members: Dict[str, ApiServerLite] = {}
        from kubernetes_tpu.federation.service_dns import InMemoryDNSProvider
        self.dns = InMemoryDNSProvider()

    # ------------------------------------------------------------ clusters

    def join(self, name: str, api: ApiServerLite, zone: str = "",
             region: str = "") -> None:
        """kubefed join: register a member cluster."""
        self.members[name] = api
        try:
            self.api.create(CLUSTER_KIND,
                            Cluster(name=name, zone=zone, region=region))
        except Conflict:
            self.mark_ready(name, True)

    def unjoin(self, name: str) -> None:
        """kubefed unjoin: deregister. Like the reference, unjoin is pure
        deregistration — objects already in the cluster are left alone and
        simply stop being reconciled (the cluster's owner keeps them)."""
        self.members.pop(name, None)
        try:
            self.api.delete(CLUSTER_KIND, "", name)
        except NotFound:
            pass

    def mark_ready(self, name: str, ready: bool) -> None:
        cur: Cluster = self.api.get(CLUSTER_KIND, "", name)
        self.api.update(CLUSTER_KIND,
                        dataclasses.replace(cur, ready=ready))

    def ready_clusters(self) -> List[str]:
        clusters, _ = self.api.list(CLUSTER_KIND)
        return sorted(c.name for c in clusters
                      if c.ready and c.name in self.members)


class FederatedReplicaSetController:
    """The per-type sync controller, ReplicaSet flavor. The class attrs
    are the federatedtypes adapter surface (federation/pkg/federatedtypes/
    adapter.go): every replica-carrying federated type shares this sync
    body and differs only in its kinds — FederatedDeploymentController
    below is the deployment.go adapter."""

    FED_KIND = FEDERATED_RS_KIND
    CHILD_KIND = "ReplicaSet"

    def __init__(self, plane: FederationControlPlane):
        self.plane = plane

    # ----------------------------------------------------------------- sync

    def sync_all(self) -> None:
        frs_list, _ = self.plane.api.list(self.FED_KIND)
        for frs in frs_list:
            self.sync(frs)

    def sync(self, frs: FederatedReplicaSet) -> None:
        """GetSchedule + ScheduleObject for every member
        (federatedtypes/scheduling.go:90,141): plan, then reconcile each
        cluster's ReplicaSet to its planned replica count."""
        prefs = DEFAULT_PREFERENCES
        ann = frs.annotations.get(PREFERENCES_ANNOTATION)
        if ann:
            prefs = ReplicaAllocationPreferences.parse(ann)
        ready = self.plane.ready_clusters()
        # one child-RS read per member, reused by planning AND reconcile
        child_rs: Dict[str, Optional[ReplicaSet]] = {
            cname: self._cluster_rs(cname, frs)
            for cname in self.plane.members}
        current = {cname: rs.replicas for cname in ready
                   if (rs := child_rs.get(cname)) is not None}
        plan, _overflow = Planner(prefs).plan(
            frs.replicas, ready, current=current, key=frs.key())

        total_ready = 0
        for cname, api in list(self.plane.members.items()):
            want = plan.get(cname, 0)
            rs = child_rs.get(cname)
            if cname not in ready or want == 0:
                # ScheduleAction remove (scheduling.go:141-170)
                if rs is not None and cname in self.plane.members:
                    try:
                        api.delete(self.CHILD_KIND, frs.namespace, frs.name)
                    except NotFound:
                        pass
                continue
            if rs is None:
                child = dataclasses.replace(
                    frs.template, name=frs.name, namespace=frs.namespace,
                    replicas=want, resource_version=0,
                    annotations={**getattr(frs.template, "annotations", {}),
                                 MANAGED_ANNOTATION: "true"})
                try:
                    api.create(self.CHILD_KIND, child)
                except Conflict:
                    pass
            elif rs.replicas != want:
                api.update(self.CHILD_KIND,
                           dataclasses.replace(rs, replicas=want),
                           expect_rv=rs.resource_version)
            if rs is not None:
                total_ready += rs.ready_replicas
        # UpdateFederatedStatus (scheduling.go:172)
        try:
            cur: FederatedReplicaSet = self.plane.api.get(
                self.FED_KIND, frs.namespace, frs.name)
            if cur.ready_replicas != total_ready:
                self.plane.api.update(
                    self.FED_KIND,
                    dataclasses.replace(cur, ready_replicas=total_ready),
                    expect_rv=cur.resource_version)
        except (NotFound, Conflict):
            pass

    def _cluster_rs(self, cname: str, frs: FederatedReplicaSet
                    ) -> Optional[ReplicaSet]:
        api = self.plane.members.get(cname)
        if api is None:
            return None
        try:
            return api.get(self.CHILD_KIND, frs.namespace, frs.name)
        except NotFound:
            return None


@dataclass
class FederatedDeployment:
    """FederatedDeployment (federatedtypes/deployment.go): same shape as
    the RS flavor with a Deployment template."""

    name: str
    namespace: str = "default"
    replicas: int = 0
    template: object = None
    annotations: Dict[str, str] = field(default_factory=dict)
    ready_replicas: int = 0
    resource_version: int = 0

    def key(self) -> str:
        return self.namespace + "/" + self.name


class FederatedDeploymentController(FederatedReplicaSetController):
    """federatedtypes/deployment.go: the Deployment adapter over the
    shared replica-scheduling sync body."""

    FED_KIND = FEDERATED_DEPLOY_KIND
    CHILD_KIND = "Deployment"


# Namespace rides the same body (federatedtypes/namespace.go): a federated
# namespace lands in every ready member; cluster-scoped (namespace "")
PROPAGATED_KINDS = ("ConfigMap", "Secret", "Namespace")
FEDERATED_DS_KIND = "FederatedDaemonSet"


def propagate_kind(plane: FederationControlPlane, conflicts: List[str],
                   fed_kind: str, child_kind: str,
                   status_fields: tuple = ()) -> None:
    """The ONE sync body for every non-scheduled federated type: create
    where missing, overwrite drift (comparing the wire form minus
    resourceVersion and the member-owned status fields), never adopt a
    member-local object of the same name (surfaced via `conflicts`
    instead of destroying data federation never owned), and delete
    managed copies whose federated parent is gone."""
    import copy as _copy

    from kubernetes_tpu.api import wire
    ready = set(plane.ready_clusters())
    fed_objs, _ = plane.api.list(fed_kind)
    fed_keys = {(getattr(o, "namespace", ""), o.name) for o in fed_objs}
    wants = []  # desired state computed ONCE, reused for every member
    for obj in fed_objs:
        want = _copy.deepcopy(obj)
        want.resource_version = 0
        want.annotations = {**getattr(obj, "annotations", {}),
                            MANAGED_ANNOTATION: "true"}
        enc = wire.encode(want)
        enc.pop("resource_version", None)
        for f in status_fields:
            enc.pop(f, None)
        wants.append((obj, want, enc))
    for cname, api in list(plane.members.items()):
        if cname not in ready:
            continue
        for obj, want, want_enc in wants:
            try:
                cur = api.get(child_kind, getattr(obj, "namespace", ""),
                              obj.name)
            except NotFound:
                try:
                    api.create(child_kind, _copy.deepcopy(want))
                except Conflict:
                    pass
                continue
            if getattr(cur, "annotations", {}).get(MANAGED_ANNOTATION) \
                    != "true":
                conflicts.append(
                    f"{cname}/{child_kind}/"
                    f"{getattr(obj, 'namespace', '')}/{obj.name}")
                continue
            cur_enc = wire.encode(cur)
            cur_enc.pop("resource_version", None)
            for f in status_fields:
                cur_enc.pop(f, None)
            if cur_enc != want_enc:
                fresh = _copy.deepcopy(want)
                fresh.resource_version = cur.resource_version
                api.update(child_kind, fresh)
        for existing in api.list(child_kind)[0]:
            if (getattr(existing, "namespace", ""),
                    existing.name) in fed_keys:
                continue
            if getattr(existing, "annotations", {}).get(
                    MANAGED_ANNOTATION) == "true":
                try:
                    api.delete(child_kind,
                               getattr(existing, "namespace", ""),
                               existing.name)
                except NotFound:
                    pass


class FederatedDaemonSetController:
    """federatedtypes/daemonset.go: no replica planning — the DaemonSet
    lands verbatim in EVERY ready member cluster (each cluster's own
    DaemonSet controller then runs one pod per node); the shared
    propagation body supplies the conflict guard and orphan cleanup,
    with the member-owned status fields excluded from drift."""

    def __init__(self, plane: FederationControlPlane):
        self.plane = plane
        self.conflicts: List[str] = []

    def sync_all(self) -> None:
        self.conflicts = []
        propagate_kind(self.plane, self.conflicts, FEDERATED_DS_KIND,
                       "DaemonSet",
                       status_fields=("desired_scheduled",
                                      "current_scheduled"))


MANAGED_ANNOTATION = "federation.kubernetes.io/managed"


class FederatedPropagationController:
    """The non-scheduled federated types (federatedtypes/{configmap,
    secret}.go): objects stored in the federation apiserver under the
    federated kind are copied verbatim into every READY member cluster
    and kept in sync — create where missing, overwrite on drift (data,
    annotations, and Secret type alike), delete from members when the
    federated object goes away. Ownership rides an ANNOTATION, the
    payload is untouched, and a pre-existing member-local object of the
    same name is never adopted or overwritten (a propagation conflict is
    surfaced, not silently resolved by destroying local data)."""

    def __init__(self, plane: FederationControlPlane):
        self.plane = plane
        self.conflicts: List[str] = []  # "<cluster>/<kind>/<ns>/<name>"

    def sync_all(self) -> None:
        self.conflicts = []
        for kind in PROPAGATED_KINDS:
            propagate_kind(self.plane, self.conflicts,
                           "Federated" + kind, kind)
