"""Admission control: mutate-then-validate interceptors ahead of storage.

Mirror of staging/src/k8s.io/apiserver/pkg/admission/chain.go (chainAdmissionHandler
runs every plugin's Admit in order; any error rejects the request) and the
reference's plugin set under plugin/pkg/admission/. The recommended 1.7
plugin order (kube-apiserver docs / pkg/kubeapiserver/options):
NamespaceLifecycle, LimitRanger, ServiceAccount, DefaultTolerationSeconds,
ResourceQuota last.

Each plugin: handles(request) by operation/kind, then admit(request) which
may mutate request.obj or raise Rejected (HTTP 403-equivalent).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from kubernetes_tpu.api.rbac import UserInfo

CREATE, UPDATE, DELETE, CONNECT = "CREATE", "UPDATE", "DELETE", "CONNECT"


class Rejected(Exception):
    """admission denied the request."""


@dataclass
class AdmissionRequest:
    operation: str
    kind: str
    namespace: str
    name: str
    obj: object = None
    old_obj: object = None
    user: Optional[UserInfo] = None
    subresource: str = ""
    # side effects plugins committed during admit (e.g. quota usage CAS) as
    # undo callables, run by rollback() if the request fails downstream
    undo: List = field(default_factory=list)


class AdmissionChain:
    def __init__(self, plugins: List, store=None):
        self.plugins = list(plugins)
        for p in self.plugins:
            if hasattr(p, "set_store"):
                p.set_store(store)

    def admit(self, req: AdmissionRequest) -> None:
        for p in self.plugins:
            if p.handles(req):
                try:
                    p.admit(req)
                except Exception:
                    self.rollback(req)
                    raise

    def rollback(self, req: AdmissionRequest) -> None:
        """Undo plugin side effects after a downstream failure (registry
        validation / storage), newest first."""
        while req.undo:
            req.undo.pop()()


def default_plugins():
    """The reference's recommended plugin set for 1.7 in order
    (pkg/kubeapiserver/options/plugins.go)."""
    from kubernetes_tpu.admission import plugins as m

    return [
        m.NamespaceLifecycle(),
        m.AlwaysPullImages(enabled=False),
        m.LimitRanger(),
        m.ServiceAccountPlugin(),
        m.PodNodeSelector(),
        m.PodTolerationRestriction(),
        m.DefaultTolerationSeconds(),
        m.NodeRestriction(),
        m.PriorityPlugin(),
        m.StorageClassDefault(),
        m.ResourceQuotaPlugin(),  # last, like the reference's ordering
    ]
