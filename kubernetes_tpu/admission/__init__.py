from kubernetes_tpu.admission.chain import (  # noqa: F401
    AdmissionChain,
    AdmissionRequest,
    Rejected,
    default_plugins,
)
