"""Admission plugin implementations (reference: plugin/pkg/admission/*).

Each plugin mirrors the decision logic of its Go counterpart; store access is
through the apiserver-lite store handed to the chain (the reference plugins
use informers/listers — same data, same freshness model in-process).
"""

from __future__ import annotations

import copy
import dataclasses
from dataclasses import dataclass
from typing import Dict, List, Optional

from kubernetes_tpu.admission.chain import (
    AdmissionRequest,
    CREATE,
    DELETE,
    Rejected,
    UPDATE,
)
from kubernetes_tpu.api.cluster import LimitRange, ResourceQuota
from kubernetes_tpu.api.types import (
    Pod,
    Taint,
    TaintEffect,
    Toleration,
    TolerationOperator,
)
from kubernetes_tpu.quota import (
    exceeds,
    quota_scopes_match,
    usage_for,
)


class _StorePlugin:
    store = None

    def set_store(self, store) -> None:
        self.store = store

    def _get(self, kind, ns, name):
        try:
            return self.store.get(kind, ns, name)
        except Exception:
            return None


class NamespaceLifecycle(_StorePlugin):
    """plugin/pkg/admission/namespace/lifecycle: creates in a missing or
    terminating namespace are rejected; deletes of the immortal namespaces
    (default, kube-system) are rejected."""

    IMMORTAL = ("default", "kube-system")
    NAMESPACED_KINDS_EXEMPT = ("Namespace", "Node", "PersistentVolume",
                               "ClusterRole", "ClusterRoleBinding")

    def handles(self, req: AdmissionRequest) -> bool:
        return req.operation in (CREATE, DELETE)

    def admit(self, req: AdmissionRequest) -> None:
        if req.operation == DELETE and req.kind == "Namespace" \
                and req.name in self.IMMORTAL:
            raise Rejected(f"namespace {req.name} is immortal")
        if req.operation != CREATE or req.kind in self.NAMESPACED_KINDS_EXEMPT:
            return
        if not req.namespace or self.store is None:
            return
        ns = self._get("Namespace", "", req.namespace)
        if ns is None:
            # auto-provision default like the provision plugin? The reference
            # runs lifecycle which 404s unknown namespaces.
            raise Rejected(f"namespace {req.namespace} not found")
        if getattr(ns, "phase", "Active") == "Terminating":
            raise Rejected(
                f"namespace {req.namespace} is terminating: cannot create")


class AlwaysPullImages:
    """plugin/pkg/admission/alwayspullimages: force imagePullPolicy=Always.
    Modeled as an annotation since the pull policy lives node-side here."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled

    def handles(self, req: AdmissionRequest) -> bool:
        return self.enabled and req.kind == "Pod" \
            and req.operation in (CREATE, UPDATE)

    def admit(self, req: AdmissionRequest) -> None:
        req.obj.annotations["kubernetes.io/image-pull-policy"] = "Always"


class LimitRanger(_StorePlugin):
    """plugin/pkg/admission/limitranger: apply container default requests/
    limits from LimitRange objects, reject min/max violations."""

    def handles(self, req: AdmissionRequest) -> bool:
        return req.kind == "Pod" and req.operation == CREATE

    def admit(self, req: AdmissionRequest) -> None:
        if self.store is None:
            return
        pod: Pod = req.obj
        ranges = [lr for lr in self.store.list("LimitRange")[0]
                  if lr.namespace == req.namespace]
        for lr in ranges:
            for item in lr.limits:
                if item.type != "Container":
                    continue
                for c in pod.containers:
                    for res, dv in item.default_request.items():
                        c.requests.setdefault(res, dv)
                    for res, dv in item.default.items():
                        c.limits.setdefault(res, dv)
                    for res, mn in item.min.items():
                        if res in c.requests and c.requests[res] < mn:
                            raise Rejected(
                                f"minimum {res} usage per Container is {mn}")
                    for res, mx in item.max.items():
                        if c.requests.get(res, 0) > mx \
                                or c.limits.get(res, 0) > mx:
                            raise Rejected(
                                f"maximum {res} usage per Container is {mx}")


class ServiceAccountPlugin(_StorePlugin):
    """plugin/pkg/admission/serviceaccount: default the pod's service
    account, reject references to missing service accounts. The SA name is
    carried in annotations (the Pod model doesn't reserve a field)."""

    KEY = "kubernetes.io/service-account.name"

    def handles(self, req: AdmissionRequest) -> bool:
        return req.kind == "Pod" and req.operation == CREATE

    def admit(self, req: AdmissionRequest) -> None:
        pod: Pod = req.obj
        name = pod.annotations.get(self.KEY) or "default"
        pod.annotations[self.KEY] = name
        if self.store is None:
            return
        sa = self._get("ServiceAccount", req.namespace, name)
        if sa is None and name != "default":
            raise Rejected(
                f"service account {req.namespace}/{name} does not exist")


class PodNodeSelector(_StorePlugin):
    """plugin/pkg/admission/podnodeselector: merge the namespace's
    node-selector annotation into the pod; conflicts reject."""

    ANNOTATION = "scheduler.alpha.kubernetes.io/node-selector"

    def handles(self, req: AdmissionRequest) -> bool:
        return req.kind == "Pod" and req.operation == CREATE

    def admit(self, req: AdmissionRequest) -> None:
        if self.store is None:
            return
        ns = self._get("Namespace", "", req.namespace)
        if ns is None:
            return
        raw = getattr(ns, "annotations", {}).get(self.ANNOTATION, "")
        if not raw:
            return
        selector: Dict[str, str] = {}
        for part in raw.split(","):
            if "=" in part:
                k, _, v = part.partition("=")
                selector[k.strip()] = v.strip()
        pod: Pod = req.obj
        for k, v in selector.items():
            if k in pod.node_selector and pod.node_selector[k] != v:
                raise Rejected(
                    f"pod node label selector conflicts with namespace "
                    f"node label selector for key {k}")
            pod.node_selector[k] = v


class PodTolerationRestriction(_StorePlugin):
    """plugin/pkg/admission/podtolerationrestriction: merge namespace
    default tolerations; enforce the namespace whitelist."""

    DEFAULT_KEY = "scheduler.alpha.kubernetes.io/defaultTolerations"
    WHITELIST_KEY = "scheduler.alpha.kubernetes.io/tolerationsWhitelist"

    def handles(self, req: AdmissionRequest) -> bool:
        return req.kind == "Pod" and req.operation == CREATE

    def admit(self, req: AdmissionRequest) -> None:
        if self.store is None:
            return
        ns = self._get("Namespace", "", req.namespace)
        if ns is None:
            return
        anns = getattr(ns, "annotations", {})
        pod: Pod = req.obj
        defaults = self._parse(anns.get(self.DEFAULT_KEY, ""))
        if defaults and not pod.tolerations:
            pod.tolerations = defaults
        whitelist = self._parse(anns.get(self.WHITELIST_KEY, ""))
        if whitelist:
            allowed = {(t.key, t.value) for t in whitelist}
            for t in pod.tolerations:
                if (t.key, t.value) not in allowed:
                    raise Rejected(
                        f"pod toleration {t.key}={t.value} not in namespace "
                        "whitelist")

    @staticmethod
    def _parse(raw: str):
        out = []
        for part in raw.split(";"):
            if "=" in part:
                k, _, v = part.partition("=")
                out.append(Toleration(key=k.strip(), value=v.strip()))
        return out


# TaintBasedEvictions not-ready/unreachable taint keys
# (pkg/controller/node + plugin/pkg/admission/defaulttolerationseconds)
NOT_READY_TAINT = "node.alpha.kubernetes.io/notReady"
UNREACHABLE_TAINT = "node.alpha.kubernetes.io/unreachable"
DEFAULT_TOLERATION_SECONDS = 300


class DefaultTolerationSeconds:
    """plugin/pkg/admission/defaulttolerationseconds: add 300s NoExecute
    tolerations for notReady/unreachable unless the pod already has one."""

    def handles(self, req: AdmissionRequest) -> bool:
        return req.kind == "Pod" and req.operation in (CREATE, UPDATE)

    def admit(self, req: AdmissionRequest) -> None:
        pod: Pod = req.obj
        has_nr = any(t.key == NOT_READY_TAINT and
                     t.effect in (None, TaintEffect.NO_EXECUTE)
                     for t in pod.tolerations)
        has_ur = any(t.key == UNREACHABLE_TAINT and
                     t.effect in (None, TaintEffect.NO_EXECUTE)
                     for t in pod.tolerations)
        if not has_nr:
            pod.tolerations = list(pod.tolerations) + [Toleration(
                key=NOT_READY_TAINT, operator=TolerationOperator.EXISTS,
                effect=TaintEffect.NO_EXECUTE,
                toleration_seconds=DEFAULT_TOLERATION_SECONDS)]
        if not has_ur:
            pod.tolerations = list(pod.tolerations) + [Toleration(
                key=UNREACHABLE_TAINT, operator=TolerationOperator.EXISTS,
                effect=TaintEffect.NO_EXECUTE,
                toleration_seconds=DEFAULT_TOLERATION_SECONDS)]


class NodeRestriction:
    """plugin/pkg/admission/noderestriction: a kubelet may only modify its
    own Node object and pods bound to it, and may only CREATE mirror-style
    pods bound to itself that reference no secrets/configmaps/PVCs — else a
    compromised kubelet could mint a pod referencing any secret and ride
    the node authorizer's reachability grant to read it
    (admission.go:112-141 in the reference)."""

    def handles(self, req: AdmissionRequest) -> bool:
        return req.user is not None \
            and req.user.name.startswith("system:node:") \
            and req.kind in ("Node", "Pod")

    def admit(self, req: AdmissionRequest) -> None:
        from kubernetes_tpu.api.types import VolumeKind

        node_name = req.user.name[len("system:node:"):]
        if req.kind == "Node":
            if req.operation in (UPDATE, DELETE) and req.name != node_name:
                raise Rejected(
                    f"node {node_name} cannot modify node {req.name}")
        elif req.kind == "Pod" and req.operation == CREATE:
            pod = req.obj
            if getattr(pod, "node_name", "") != node_name:
                raise Rejected(
                    f"node {node_name} can only create pods bound to itself")
            for vol in getattr(pod, "volumes", None) or []:
                if vol.kind in (VolumeKind.SECRET, VolumeKind.CONFIG_MAP,
                                VolumeKind.PVC):
                    raise Rejected(
                        f"node {node_name} cannot create pods that reference "
                        f"{vol.kind.value} volumes")
        elif req.kind == "Pod" and req.operation in (UPDATE, DELETE):
            pod = req.old_obj or req.obj
            if pod is not None and getattr(pod, "node_name", "") \
                    not in ("", node_name):
                raise Rejected(
                    f"node {node_name} cannot modify pods bound elsewhere")


class PriorityPlugin(_StorePlugin):
    """plugin/pkg/admission/priority (behind the PodPriority gate in 1.7):
    resolve priorityClassName -> priority value."""

    def handles(self, req: AdmissionRequest) -> bool:
        from kubernetes_tpu.utils import features

        return features.enabled("PodPriority") and req.kind == "Pod" \
            and req.operation == CREATE

    def admit(self, req: AdmissionRequest) -> None:
        pod: Pod = req.obj
        if not pod.priority_class:
            return
        pc = self._get("PriorityClass", "", pod.priority_class)
        if pc is None:
            raise Rejected(
                f"no PriorityClass with name {pod.priority_class} was found")
        pod.priority = pc.value


class StorageClassDefault(_StorePlugin):
    """plugin/pkg/admission/storageclass/default: annotate PVCs without a
    class with the default StorageClass."""

    ANNOTATION = "volume.beta.kubernetes.io/storage-class"

    def handles(self, req: AdmissionRequest) -> bool:
        return req.kind == "PersistentVolumeClaim" and req.operation == CREATE

    def admit(self, req: AdmissionRequest) -> None:
        if self.store is None:
            return
        anns = getattr(req.obj, "annotations", None)
        if anns is None or self.ANNOTATION in anns:
            return
        for sc in self.store.list("StorageClass")[0]:
            if getattr(sc, "is_default", False):
                anns[self.ANNOTATION] = sc.name
                return


class PodSecurityPolicyPlugin(_StorePlugin):
    """plugin/pkg/admission/security/podsecuritypolicy (admission.go:120
    Admit): on pod CREATE, try every PodSecurityPolicy in name order; the
    first whose generated defaults validate wins — the pod is mutated with
    those defaults and annotated kubernetes.io/psp=<name>. No policy
    passing (or none existing while the plugin is enabled) rejects the pod.

    Opt-in, like the reference (not in the 1.7 recommended set):
    AdmissionChain(default_plugins() + [PodSecurityPolicyPlugin()], ...)."""

    def handles(self, req: AdmissionRequest) -> bool:
        return req.operation == CREATE and req.kind == "Pod"

    def admit(self, req: AdmissionRequest) -> None:
        from kubernetes_tpu.security.psp import (
            PSP_ANNOTATION,
            PSP_KIND,
            Provider,
        )
        if self.store is None:
            return
        policies = sorted(self.store.list(PSP_KIND)[0],
                          key=lambda p: p.name)
        pod: Pod = req.obj
        all_errs = []
        for psp in policies:
            provider = Provider(psp)
            candidate = provider.apply_defaults(pod)
            errs = provider.validate(candidate)
            if not errs:
                candidate.annotations = dict(candidate.annotations)
                candidate.annotations[PSP_ANNOTATION] = psp.name
                # commit the mutation (the chain passes req.obj onward)
                pod.__dict__.update(candidate.__dict__)
                return
            all_errs.extend(f"{psp.name}: {e}" for e in errs)
        raise Rejected(
            "unable to validate against any pod security policy: "
            + ("; ".join(all_errs) if all_errs else "no policies defined"))


class ResourceQuotaPlugin(_StorePlugin):
    """plugin/pkg/admission/resourcequota: on CREATE, check the delta
    against every matching quota's hard limits and commit the new usage
    through the apiserver's guarded update (the reference's quota CAS loop —
    resource_access.go UpdateQuotaStatus), so a watch event + rv bump is
    emitted for every usage change. Committed increments are recorded on the
    request (req.undo) and rolled back by the chain if registry validation
    or the store create fails afterwards — no leaked usage until resync."""

    _CAS_RETRIES = 5

    def handles(self, req: AdmissionRequest) -> bool:
        return req.operation == CREATE and req.kind in (
            "Pod", "Service", "ReplicationController", "Secret", "ConfigMap",
            "PersistentVolumeClaim", "ResourceQuota")

    def admit(self, req: AdmissionRequest) -> None:
        if self.store is None:
            return
        delta = usage_for(req.kind, req.obj)
        if not delta:
            return
        from kubernetes_tpu.server.apiserver_lite import Conflict
        for _ in range(self._CAS_RETRIES):
            quotas = [q for q in self.store.list("ResourceQuota")[0]
                      if q.namespace == req.namespace
                      and quota_scopes_match(q.scopes, req.kind, req.obj)]
            affected = []
            for q in quotas:
                constrained = [k for k in delta if k in q.hard]
                if not constrained:
                    continue
                over = exceeds(q.hard, q.used, delta)
                if over:
                    raise Rejected(
                        f"exceeded quota: {q.name}, requested: "
                        + ",".join(f"{k}={delta[k]}" for k in over)
                        + ", limited: "
                        + ",".join(f"{k}={q.hard[k]}" for k in over))
                affected.append(q)
            try:
                for q in affected:
                    nq = copy.deepcopy(q)
                    for k, v in delta.items():
                        if k in nq.hard:
                            nq.used[k] = nq.used.get(k, 0) + v
                    self.store.update("ResourceQuota", nq,
                                      expect_rv=q.resource_version)
                    req.undo.append(
                        lambda name=q.name, d=dict(delta):
                        self._decrement(name, req.namespace, d))
                return
            except Conflict:
                # another writer moved a quota between list and update:
                # roll back what this attempt committed and re-check
                while req.undo:
                    req.undo.pop()()
                continue
        raise Rejected("quota update conflict: too many retries")

    def _decrement(self, name: str, namespace: str,
                   delta: Dict[str, int]) -> None:
        from kubernetes_tpu.server.apiserver_lite import Conflict, NotFound
        for _ in range(self._CAS_RETRIES):
            try:
                cur = self.store.get("ResourceQuota", namespace, name)
            except NotFound:
                return
            nq = copy.deepcopy(cur)
            for k, v in delta.items():
                if k in nq.used:
                    nq.used[k] = max(0, nq.used[k] - v)
            try:
                self.store.update("ResourceQuota", nq,
                                  expect_rv=cur.resource_version)
                return
            except Conflict:
                continue


# ---------------------------------------------------------------------------
# round-5 sweep: the remaining static plugins of plugin/pkg/admission/
# ---------------------------------------------------------------------------


@dataclass
class PodPreset:
    """settings.k8s.io PodPreset, reduced to the injection surface this
    model carries: annotations to merge and volumes to append into pods
    matched by a label selector (plugin/pkg/admission/podpreset/admission.go
    injects env/envFrom/volumes/volumeMounts; env lives in annotations
    here)."""

    name: str
    namespace: str = "default"
    selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    volumes: List = dataclasses.field(default_factory=list)
    resource_version: int = 0
    deleted: bool = False


class PodPresetPlugin(_StorePlugin):
    """plugin/pkg/admission/podpreset: merge matching presets into pods at
    CREATE. Reference conflict semantics (admission.go mergePodPresets):
    ANY conflict across the matched presets aborts injection entirely —
    the pod is admitted unmodified, never rejected. Applied presets are
    recorded as podpreset.admission.kubernetes.io/podpreset-<name>
    annotations, like the reference's bookkeeping stamp."""

    STAMP_PREFIX = "podpreset.admission.kubernetes.io/podpreset-"

    def handles(self, req: AdmissionRequest) -> bool:
        return req.operation == CREATE and req.kind == "Pod"

    def admit(self, req: AdmissionRequest) -> None:
        pod: Pod = req.obj
        try:
            presets, _ = self.store.list("PodPreset")
        except Exception:
            return
        matched = [
            p for p in presets
            if p.namespace == req.namespace
            and all(pod.labels.get(k) == v for k, v in p.selector.items())]
        if not matched:
            return
        new_ann: Dict[str, str] = {}
        new_vols = []
        vol_names = {v.name for v in pod.volumes}
        for p in matched:
            for k, v in p.annotations.items():
                if pod.annotations.get(k, v) != v or new_ann.get(k, v) != v:
                    return  # conflict: skip ALL presets, admit unmodified
                new_ann[k] = v
            for vol in p.volumes:
                if vol.name in vol_names:
                    return  # volume-name conflict
                vol_names.add(vol.name)
                new_vols.append(vol)
        pod.annotations.update(new_ann)
        pod.volumes.extend(new_vols)
        for p in matched:
            pod.annotations[self.STAMP_PREFIX + p.name] = \
                str(p.resource_version)


class LimitPodHardAntiAffinityTopology:
    """plugin/pkg/admission/antiaffinity: deny pods whose REQUIRED pod
    anti-affinity uses a topology key other than kubernetes.io/hostname —
    a hard zone/region anti-affinity lets one pod fence whole failure
    domains (admission.go checkPodsWithAntiAffinityTerm)."""

    HOSTNAME = "kubernetes.io/hostname"

    def set_store(self, store) -> None:
        pass

    def handles(self, req: AdmissionRequest) -> bool:
        return req.kind == "Pod" and req.operation in (CREATE, UPDATE)

    def admit(self, req: AdmissionRequest) -> None:
        pod: Pod = req.obj
        aff = pod.affinity
        if aff is None or aff.pod_anti_affinity is None:
            return
        for term in aff.pod_anti_affinity.required_terms:
            if term.topology_key and term.topology_key != self.HOSTNAME:
                raise Rejected(
                    "affinity.podAntiAffinity."
                    "requiredDuringSchedulingIgnoredDuringExecution with "
                    f"topologyKey {term.topology_key!r} is not allowed "
                    f"(only {self.HOSTNAME})")


class DenyEscalatingExec(_StorePlugin):
    """plugin/pkg/admission/exec DenyEscalatingExec: block exec/attach
    CONNECTs into pods that escalate to the host (privileged containers;
    host-network stands in for the reference's hostPID/hostIPC checks —
    the host axes this pod model carries)."""

    def handles(self, req: AdmissionRequest) -> bool:
        from kubernetes_tpu.admission.chain import CONNECT
        return req.operation == CONNECT \
            and req.subresource in ("exec", "attach")

    def admit(self, req: AdmissionRequest) -> None:
        pod = req.obj
        if pod is None:
            pod = self._get("Pod", req.namespace, req.name)
        if pod is None:
            return
        if getattr(pod, "host_network", False):
            raise Rejected(
                "cannot exec into or attach to a container using host "
                "network")
        for c in getattr(pod, "containers", []):
            sc = c.security_context
            if sc is not None and sc.privileged:
                raise Rejected(
                    "cannot exec into or attach to a privileged container")


class OwnerReferencesPermissionEnforcement:
    """plugin/pkg/admission/gc: setting or changing ownerReferences
    requires delete permission on the object — otherwise any writer could
    mark an object for cascade deletion by a controller they don't own
    (gc_admission.go Admit)."""

    def __init__(self, authorize=None):
        # authorize(user, verb, kind, namespace) -> bool; None = allow all
        # (the plugin is inert without an authorizer, like the reference
        # wired without RBAC)
        self._authorize = authorize

    def set_store(self, store) -> None:
        pass

    def handles(self, req: AdmissionRequest) -> bool:
        return req.operation in (CREATE, UPDATE)

    @staticmethod
    def _owner(obj) -> tuple:
        return (getattr(obj, "owner_kind", ""),
                getattr(obj, "owner_name", ""))

    def admit(self, req: AdmissionRequest) -> None:
        if self._authorize is None:
            return
        new_owner = self._owner(req.obj)
        if req.operation == CREATE:
            changed = new_owner != ("", "")
        else:
            changed = req.old_obj is not None \
                and new_owner != self._owner(req.old_obj)
        if not changed:
            return
        if not self._authorize(req.user, "delete", req.kind, req.namespace):
            raise Rejected(
                f"cannot set an ownerReference on a {req.kind} without "
                f"delete permission")


class PersistentVolumeLabel(_StorePlugin):
    """plugin/pkg/admission/persistentvolume/label PersistentVolumeLabel:
    stamp cloud zone/region failure-domain labels onto EBS/GCE-PD PVs at
    CREATE so the VolumeZone predicate can enforce them (admission.go
    findVolumeLabels via the cloud's disk API)."""

    CLOUD_KINDS = ("GCEPersistentDisk", "AWSElasticBlockStore", "AzureDisk")

    def __init__(self, cloud=None):
        self.cloud = cloud

    def handles(self, req: AdmissionRequest) -> bool:
        return req.operation == CREATE and req.kind == "PersistentVolume"

    def admit(self, req: AdmissionRequest) -> None:
        pv = req.obj
        if self.cloud is None or pv.source.kind.value not in self.CLOUD_KINDS:
            return
        zone_of = getattr(self.cloud, "disk_zone", None)
        if zone_of is None:
            return
        zr = zone_of(pv.source.volume_id)
        if zr is None:
            # the reference plugin errors when the cloud can't find the
            # volume (admission.go findVolumeLabels) — stamping a made-up
            # zone would let VolumeZone schedule against fiction
            raise Rejected(
                f"error querying volume {pv.source.volume_id!r}: "
                f"disk not found in cloud provider")
        zone, region = zr
        from kubernetes_tpu.ops.oracle_ext import (
            ZONE_LABEL,
            ZONE_REGION_LABEL,
        )
        # admission labels win over client-supplied ones (the reference
        # overwrites: the cloud is authoritative about where a disk lives)
        pv.labels[ZONE_LABEL] = zone
        pv.labels[ZONE_REGION_LABEL] = region
