"""Dynamic (out-of-process) admission: webhooks + initializers.

Three reference components, all of which move the admission decision OUT of
the apiserver binary — the extensibility story that static plugins can't
give:

- GenericAdmissionWebhook (plugin/pkg/admission/webhook/admission.go): load
  hook configurations from the API (admissionregistration
  ExternalAdmissionHookConfiguration), match rules against the request,
  POST an AdmissionReview to each matching hook, enforce the verdict;
  transport failure falls to the per-hook FailurePolicy (Ignore = allow,
  Fail = reject). The reference's 1.7 webhook is validate-only; this one
  also applies a returned patchedObject when the hook is marked mutating
  (the 1.9 MutatingAdmissionWebhook behavior, asked for by the blueprint).
- ImagePolicyWebhook (plugin/pkg/admission/imagepolicy/admission.go:249
  Admit): for pod writes, POST an ImageReview carrying the pod's images;
  a disallowed verdict rejects with the backend's reason; a backend error
  falls to defaultAllow.
- Initializers (plugin/pkg/admission/initialization/): matching CREATEs get
  the configured pending-initializer list stamped on; the object stays
  invisible to normal LISTs until an initializer controller clears the
  list (the apiserver's uninitialized-object filtering lives in
  server/apiserver.py list()).

The wire POST reuses the repo's one HTTP idiom (http.client against an
in-process ThreadingHTTPServer, the extender seam's shape) so webhook tests
mirror tests/test_extender_http.py.
"""

from __future__ import annotations

import http.client
import json
import urllib.parse
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.admission.chain import (
    AdmissionRequest,
    CREATE,
    Rejected,
    UPDATE,
)
from kubernetes_tpu.api import serde
from kubernetes_tpu.api.types import Pod

FAIL = "Fail"
IGNORE = "Ignore"

# comma-joined pending initializer names (metadata.initializers.pending in
# the reference; an annotation here — Pod carries no initializers field)
PENDING_INITIALIZERS_ANNOTATION = "metadata.initializers.pending"


@dataclass
class Rule:
    """admissionregistration RuleWithOperations, reduced: which operations
    on which kinds a hook intercepts ("*" wildcards both)."""

    operations: List[str] = field(default_factory=lambda: ["*"])
    kinds: List[str] = field(default_factory=lambda: ["*"])

    def matches(self, operation: str, kind: str) -> bool:
        ops_ok = "*" in self.operations or operation in self.operations
        kinds_ok = "*" in self.kinds or kind in self.kinds
        return ops_ok and kinds_ok


@dataclass
class WebhookHook:
    """One hook inside a configuration (ExternalAdmissionHook)."""

    name: str = ""
    url: str = ""  # http://host:port/path (clientConfig collapsed to a URL)
    rules: List[Rule] = field(default_factory=list)
    failure_policy: str = IGNORE  # the reference's default (admission.go)
    timeout_s: float = 5.0
    mutating: bool = False  # apply response.patchedObject to the request


@dataclass
class AdmissionHookConfiguration:
    """The API object the plugin watches (cluster-scoped;
    admissionregistration/v1alpha1 ExternalAdmissionHookConfiguration)."""

    name: str
    hooks: List[WebhookHook] = field(default_factory=list)
    namespace: str = ""
    resource_version: int = 0
    deleted: bool = False


@dataclass
class InitializerConfiguration:
    """admissionregistration InitializerConfiguration: names stamped onto
    matching CREATEs, in order."""

    name: str
    initializers: List[str] = field(default_factory=list)
    kinds: List[str] = field(default_factory=lambda: ["*"])
    namespace: str = ""
    resource_version: int = 0
    deleted: bool = False


def _post_json(url: str, payload: dict, timeout_s: float) -> dict:
    """POST JSON, return decoded JSON response; raises on transport errors
    (connection refused, timeout, non-200, bad JSON)."""
    parts = urllib.parse.urlsplit(url)
    conn = http.client.HTTPConnection(parts.hostname, parts.port,
                                      timeout=timeout_s)
    path = parts.path or "/"
    if parts.query:
        path += "?" + parts.query
    try:
        body = json.dumps(payload)
        conn.request("POST", path, body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            raise ConnectionError(f"webhook returned HTTP {resp.status}")
        return json.loads(data)
    finally:
        conn.close()


def _encode_obj(kind: str, obj) -> Optional[dict]:
    if kind == "Pod" and isinstance(obj, Pod):
        return serde.encode_pod(obj)
    if obj is None:
        return None
    # generic fallback: ship the JSON-safe surface of the dataclass so
    # validating hooks can see any kind (mutation stays Pod-only)
    import dataclasses as dc
    if dc.is_dataclass(obj):
        try:
            return json.loads(json.dumps(dc.asdict(obj), default=str))
        except Exception:
            return {"name": getattr(obj, "name", "")}
    return {"name": getattr(obj, "name", "")}


class GenericAdmissionWebhook:
    """The webhook admission plugin (webhook/admission.go
    GenericAdmissionWebhook.Admit): hooks come from constructor config
    and/or AdmissionHookConfiguration objects in the store."""

    def __init__(self, hooks: Optional[List[WebhookHook]] = None):
        self._static_hooks = list(hooks or [])
        self.store = None
        self.calls = 0  # diagnostics

    def set_store(self, store) -> None:
        self.store = store

    def _hooks(self) -> List[WebhookHook]:
        hooks = list(self._static_hooks)
        if self.store is not None:
            try:
                configs, _ = self.store.list("AdmissionHookConfiguration")
            except Exception:
                configs = []
            for cfg in configs:
                hooks.extend(cfg.hooks)
        return hooks

    def handles(self, req: AdmissionRequest) -> bool:
        # matching needs the live hook-config list; doing it here AND in
        # admit() would scan the config registry twice per request —
        # admit() does the single scan and early-returns on no match
        return True

    def admit(self, req: AdmissionRequest) -> None:
        for hook in self._hooks():
            if not any(r.matches(req.operation, req.kind)
                       for r in hook.rules):
                continue
            review = {
                "kind": "AdmissionReview",
                "request": {
                    "operation": req.operation,
                    "kind": req.kind,
                    "namespace": req.namespace,
                    "name": req.name,
                    "object": _encode_obj(req.kind, req.obj),
                    "userInfo": {"username": req.user.name
                                 if req.user else ""},
                },
            }
            try:
                resp = _post_json(hook.url, review, hook.timeout_s)
                self.calls += 1
            except Exception as e:
                if hook.failure_policy == FAIL:
                    raise Rejected(
                        f"admission webhook {hook.name!r} failed: {e}"
                    ) from None
                continue  # Ignore: fail-open (admission.go default)
            result = resp.get("response", resp)
            if not result.get("allowed", False):
                status = result.get("status", {}) or {}
                msg = status.get("message", "") or "denied"
                raise Rejected(
                    f'admission webhook {hook.name!r} denied the request: '
                    f"{msg}")
            patched = result.get("patchedObject")
            if hook.mutating and patched is not None:
                if req.kind == "Pod":
                    self._apply_pod_patch(req.obj, patched)
                # non-Pod mutation unsupported (validate-only, like 1.7)

    # the ONLY fields a mutating hook may change: the mutable spec surface
    # the wire encoding round-trips. Identity (name/namespace/uid) was
    # already authorized + audited and stays the server's; status and
    # fields the encoding doesn't carry (annotations, tolerations,
    # affinity, ownerRef, phase) must not be wiped by the round-trip.
    _POD_MUTABLE = ("labels", "containers", "volumes", "node_selector",
                    "scheduler_name")

    def _apply_pod_patch(self, obj: Pod, patched: dict) -> None:
        orig = serde.encode_pod(obj)
        if patched == orig:
            return
        new = serde.decode_pod(patched)
        for f in self._POD_MUTABLE:
            setattr(obj, f, getattr(new, f))


class ImagePolicyWebhook:
    """plugin/pkg/admission/imagepolicy/admission.go: Admit (:249) builds
    an ImageReview from the pod's containers and asks the backend; a
    disallowed verdict rejects; backend failure falls to default_allow
    (the config's defaultAllow knob)."""

    def __init__(self, url: str, default_allow: bool = True,
                 timeout_s: float = 5.0):
        self.url = url
        self.default_allow = default_allow
        self.timeout_s = timeout_s

    def set_store(self, store) -> None:
        pass

    def handles(self, req: AdmissionRequest) -> bool:
        return req.kind == "Pod" and req.operation in (CREATE, UPDATE)

    def admit(self, req: AdmissionRequest) -> None:
        pod = req.obj
        review = {
            "kind": "ImageReview",
            "spec": {
                "containers": [{"image": c.image}
                               for c in getattr(pod, "containers", [])],
                "namespace": req.namespace,
                "annotations": dict(getattr(pod, "annotations", {})),
            },
        }
        try:
            resp = _post_json(self.url, review, self.timeout_s)
        except Exception as e:
            if not self.default_allow:
                raise Rejected(
                    f"image policy webhook backend failed: {e}") from None
            return
        status = resp.get("status", {})
        if not status.get("allowed", False):
            reason = status.get("reason", "") or "image policy denied"
            raise Rejected(f"pod rejected by image policy: {reason}")


class Initializers:
    """plugin/pkg/admission/initialization: stamp the configured pending
    initializers onto matching CREATEs. The object then stays hidden from
    LISTs (server/apiserver.py) until a controller clears the list via
    remove_initializer()."""

    def __init__(self, configs: Optional[List[InitializerConfiguration]]
                 = None):
        self._static = list(configs or [])
        self.store = None

    def set_store(self, store) -> None:
        self.store = store

    def _configs(self) -> List[InitializerConfiguration]:
        out = list(self._static)
        if self.store is not None:
            try:
                objs, _ = self.store.list("InitializerConfiguration")
            except Exception:
                objs = []
            out.extend(objs)
        return out

    def handles(self, req: AdmissionRequest) -> bool:
        # single config scan lives in admit() (see GenericAdmissionWebhook)
        return req.operation == CREATE

    def admit(self, req: AdmissionRequest) -> None:
        names: List[str] = []
        for c in self._configs():
            if "*" in c.kinds or req.kind in c.kinds:
                names.extend(n for n in c.initializers if n not in names)
        if not names:
            return
        ann = getattr(req.obj, "annotations", None)
        if ann is None:
            return
        ann[PENDING_INITIALIZERS_ANNOTATION] = ",".join(names)


def is_uninitialized(obj) -> bool:
    ann = getattr(obj, "annotations", None)
    return bool(ann) and bool(ann.get(PENDING_INITIALIZERS_ANNOTATION))


def remove_initializer(store, kind: str, obj, initializer: str) -> None:
    """An initializer controller's completion write: drop `initializer`
    from the pending list (first-in-order semantics; the object becomes
    visible when the list empties). CAS through the store like any
    controller write."""
    import dataclasses
    ann = dict(obj.annotations)
    pending = [n for n in
               ann.get(PENDING_INITIALIZERS_ANNOTATION, "").split(",")
               if n and n != initializer]
    if pending:
        ann[PENDING_INITIALIZERS_ANNOTATION] = ",".join(pending)
    else:
        ann.pop(PENDING_INITIALIZERS_ANNOTATION, None)
    store.update(kind, dataclasses.replace(obj, annotations=ann),
                 expect_rv=obj.resource_version)
