"""Binary fleet framing (ISSUE 11): codec round-trips + the frame fuzzer.

The length-prefixed framing in server/framing.py is the wire the async
fleet transport speaks; a transport bug here is a fleet outage, so the
robustness contract is pinned at the codec layer: every truncated,
oversized, corrupt-length or garbage input raises the typed FrameError
(never an IndexError/struct.error deep in parsing), and the incremental
decoder reassembles arbitrarily fragmented streams byte for byte.
"""

from __future__ import annotations

import struct

import pytest

from kubernetes_tpu.api.types import make_pod
from kubernetes_tpu.server import framing


def _pod(name="fx", cpu=250):
    p = make_pod(name, cpu=cpu, memory=512 << 20)
    p.labels["app"] = "frame-test"
    return p


# ------------------------------------------------------------- round trips


def test_filter_request_roundtrip():
    pod = _pod()
    payload = framing.encode_filter_request(pod, top_k=32,
                                            deadline_ms=10_000)
    dec_pod, top_k, deadline_ms = framing.decode_filter_request(payload)
    assert (top_k, deadline_ms) == (32, 10_000)
    assert dec_pod.name == pod.name and dec_pod.labels == pod.labels
    assert dec_pod.containers[0].requests == pod.containers[0].requests


def test_bind_request_roundtrip_with_and_without_spec():
    pod = _pod("bx")
    payload = framing.encode_bind_request(
        "bx", "default", "u-1", "node-7", snapshot_gen=42,
        idem_key="bx:3", deadline_ms=5000, pod=pod)
    name, ns, uid, node, gen, key, dl, spec = \
        framing.decode_bind_request(payload)
    assert (name, ns, uid, node) == ("bx", "default", "u-1", "node-7")
    assert (gen, key, dl) == (42, "bx:3", 5000)
    assert spec is not None and spec.name == "bx"
    # identifiers-only form: gen None rides as -1, empty key -> None
    payload = framing.encode_bind_request("bx", "default", "u-1", "n")
    out = framing.decode_bind_request(payload)
    assert out[4] is None and out[5] is None and out[7] is None


def test_verdict_and_bind_result_roundtrip():
    p = framing.encode_verdict(9, False, 3, ["a", "b", "c"], ["d"],
                               [("a", 100), ("b", -5)])
    d = framing.decode_verdict(p)
    assert d["gen"] == 9 and not d["all_passed"]
    assert d["passed"] == ["a", "b", "c"] and d["failed"] == ["d"]
    assert d["top"] == [("a", 100), ("b", -5)]
    # compact all-passed: names elided, count carried
    d = framing.decode_verdict(
        framing.encode_verdict(None, True, 5000, None, [], []))
    assert d["gen"] is None and d["all_passed"] and d["passed_count"] == 5000
    assert d["passed"] == [] and d["top"] == []
    for kind in framing.BIND_KINDS:
        r = framing.decode_bind_result(
            framing.encode_bind_result(kind, 17, "CONFLICT: x"))
        assert r == {"kind": kind, "retry_after_ms": 17,
                     "error": "CONFLICT: x"}


def test_control_frames_roundtrip():
    assert framing.decode_overloaded(framing.encode_overloaded(33)) == 33
    assert framing.decode_error(framing.encode_error("boom")) == "boom"
    assert framing.decode_synced(framing.encode_synced(7)) == 7
    assert framing.decode_metrics_text(
        framing.encode_metrics_text("a\nb")) == "a\nb"


def test_items_blob_roundtrip_json_fallback():
    from kubernetes_tpu.api.types import make_node
    nodes = [make_node(f"n{i}", cpu=4000, memory=8 << 30) for i in range(3)]
    blob = framing.encode_items_blob(nodes, "nodes")
    out = framing.decode_items_blob(blob, "nodes")
    assert [n.name for n in out] == ["n0", "n1", "n2"]
    assert out[0].allocatable.milli_cpu == 4000
    pods = [_pod(f"p{i}") for i in range(2)]
    out = framing.decode_items_blob(framing.encode_items_blob(pods, "pods"),
                                    "pods")
    assert [p.name for p in out] == ["p0", "p1"]


# ---------------------------------------------------------------- decoder


def test_decoder_reassembles_byte_by_byte():
    """Interleaved partial writes: three frames fed one byte at a time
    must come out whole, in order, regardless of chunk boundaries."""
    frames = [
        framing.encode_frame(framing.PING, 1),
        framing.encode_frame(framing.FILTER, 2,
                             framing.encode_filter_request(_pod(), 8, 100),
                             flags=framing.FLAG_COMPACT),
        framing.encode_frame(framing.ERROR, 3,
                             framing.encode_error("x" * 300)),
    ]
    stream = b"".join(frames)
    dec = framing.FrameDecoder()
    got = []
    for i in range(len(stream)):
        got.extend(dec.feed(stream[i:i + 1]))
    assert [(v, r) for v, _f, r, _p in got] == [
        (framing.PING, 1), (framing.FILTER, 2), (framing.ERROR, 3)]
    assert got[1][1] == framing.FLAG_COMPACT
    assert framing.decode_error(got[2][3]) == "x" * 300
    assert dec.buffered == 0


def test_decoder_mixed_chunk_sizes():
    frames = [framing.encode_frame(framing.PING, i) for i in range(10)]
    stream = b"".join(frames)
    dec = framing.FrameDecoder()
    got, pos = [], 0
    for sz in (1, 3, 7, 11, 64, 1, 2, 1000):
        got.extend(dec.feed(stream[pos:pos + sz]))
        pos += sz
    got.extend(dec.feed(stream[pos:]))
    assert [r for _v, _f, r, _p in got] == list(range(10))


def test_corrupt_length_prefix_raises_typed():
    # length beyond max_frame: e.g. ASCII garbage read as a u32
    with pytest.raises(framing.FrameError, match="corrupt frame length"):
        framing.FrameDecoder().feed(b"GET / HTTP/1.1\r\n\r\n")
    # length below the header remainder (cannot even hold verb+id)
    bad = struct.pack("!IBBI", 2, framing.PING, 0, 1)
    with pytest.raises(framing.FrameError, match="corrupt frame length"):
        framing.FrameDecoder().feed(bad)


def test_oversized_frame_rejected_before_buffering():
    dec = framing.FrameDecoder(max_frame=64)
    big = framing.encode_frame(framing.ERROR, 1,
                               framing.encode_error("y" * 200))
    with pytest.raises(framing.FrameError, match="corrupt frame length"):
        dec.feed(big)


def test_truncated_frame_waits_truncated_payload_raises():
    # a SHORT feed is not an error — the decoder waits for the rest
    frame = framing.encode_frame(
        framing.BIND, 5, framing.encode_bind_request("a", "ns", "u", "n"))
    dec = framing.FrameDecoder()
    assert dec.feed(frame[:len(frame) - 3]) == []
    assert dec.buffered == len(frame) - 3
    # ...but a payload LYING about its contents is typed at parse time
    lying = framing.encode_frame(framing.VERDICT, 6, b"\x00\x01")
    (verb, _f, _r, payload), = framing.FrameDecoder().feed(lying)
    with pytest.raises(framing.FrameError, match="truncated"):
        framing.decode_verdict(payload)


def test_corrupt_string_and_list_counts_raise_typed():
    # string declaring more bytes than the payload holds
    p = bytes(framing.Writer().u32(1 << 30).buf)
    with pytest.raises(framing.FrameError, match="truncated string"):
        framing.Reader(p).str_()
    # absurd list count must be rejected before looping
    p = bytes(framing.Writer().u32(1 << 31).buf)
    with pytest.raises(framing.FrameError, match="corrupt list count"):
        framing.Reader(p).strs()


def test_pod_blob_typed_failures():
    with pytest.raises(framing.FrameError, match="empty pod blob"):
        framing.decode_pod_blob(b"")
    with pytest.raises(framing.FrameError, match="unknown pod codec"):
        framing.decode_pod_blob(b"\x77{}")
    with pytest.raises(framing.FrameError, match="bad JSON pod blob"):
        framing.decode_pod_blob(bytes([framing.CODEC_JSON]) + b"{nope")


def test_random_garbage_never_escapes_frame_error():
    """The fuzz core: random byte soup either yields frames, waits for
    more input, or raises FrameError — nothing else, ever."""
    import random as _random
    rng = _random.Random(0xF022)
    for trial in range(200):
        dec = framing.FrameDecoder(max_frame=1 << 16)
        blob = bytes(rng.randrange(256)
                     for _ in range(rng.randrange(1, 400)))
        try:
            frames = dec.feed(blob)
        except framing.FrameError:
            continue
        for verb, _f, _r, payload in frames:
            # parsing any claimed payload stays typed too
            for parse in (framing.decode_verdict,
                          framing.decode_bind_request,
                          framing.decode_filter_request):
                try:
                    parse(payload)
                except framing.FrameError:
                    pass
