"""Subprocess body for the kill -9 persistence test (tests/test_persistence.py).

Creates a durable ApiServerLite, loads a cluster, then binds pods one batch
at a time forever, reporting progress on stdout — until the parent SIGKILLs
it mid-storm. Deliberately imports no jax: it exercises the store, not the
kernels, and must start fast.
"""

import sys

from kubernetes_tpu.api.types import Binding, make_node, make_pod
from kubernetes_tpu.server.apiserver_lite import ApiServerLite


def main() -> None:
    data_dir = sys.argv[1]
    n_nodes, n_pods = int(sys.argv[2]), int(sys.argv[3])
    api = ApiServerLite(data_dir=data_dir)
    for i in range(n_nodes):
        api.create("Node", make_node(f"node-{i:04d}"))
    for i in range(n_pods):
        api.create("Pod", make_pod(f"pod-{i:05d}", cpu=100, memory=64 << 20))
    print("READY", flush=True)
    i = 0
    while True:
        api.bind_many([
            Binding(f"pod-{(i + j) % n_pods:05d}", "default", "",
                    f"node-{(i + j) % n_nodes:04d}")
            for j in range(10)
        ])
        i += 10
        print(f"BOUND {i}", flush=True)


if __name__ == "__main__":
    main()
