"""Golden test for the device batch placement: the engine's on-device
sequential scan must produce EXACTLY the same pod->node assignment sequence as
the object-level oracle running the reference's one-pod-at-a-time loop
(schedule -> assume -> next pod), including round-robin tie-break evolution."""

import random

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.engine.scheduler_engine import SchedulingEngine
from kubernetes_tpu.ops import oracle
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.node_info import node_info_map
from tests.helpers import Gi, Mi, random_nodes, random_pod


def oracle_sequence(nodes, pending, priorities):
    """Reference semantics: schedule one, assume, repeat."""
    infos = node_info_map(nodes, [])
    names = sorted(infos.keys())  # snapshot order
    rr = oracle.RoundRobin()
    out = []
    for pod in pending:
        name = oracle.schedule_one(pod, names, infos, rr, priorities)
        out.append(name)
        if name is not None:
            import copy
            p = copy.deepcopy(pod)
            p.node_name = name
            infos[name].add_pod(p)
    return out


def engine_sequence(nodes, pending, priorities):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    eng = SchedulingEngine(cache, priorities=priorities)
    import copy
    results = eng.schedule([copy.deepcopy(p) for p in pending])
    return [r.node_name for r in results]


PSETS = [
    (("LeastRequestedPriority", 1), ("BalancedResourceAllocation", 1),
     ("TaintTolerationPriority", 1)),
    (("MostRequestedPriority", 1),),
    (("EqualPriority", 1),),
]


@pytest.mark.parametrize("seed", [0, 1, 5])
@pytest.mark.parametrize("pset", PSETS)
def test_batch_matches_sequential_oracle(seed, pset):
    rng = random.Random(seed)
    nodes = random_nodes(rng, 12)
    names = [n.name for n in nodes]
    pending = [random_pod(rng, i, names) for i in range(60)]
    for p in pending:
        p.node_name = ""  # ensure all are actually pending
    want = oracle_sequence(nodes, pending, pset)
    got = engine_sequence(nodes, pending, pset)
    assert got == want


def test_capacity_decrement_spreads_pods():
    # 3 identical nodes, pods sized so each node fits exactly 2
    nodes = [make_node(f"n{i}", cpu=2000, memory=4 * Gi, pods=110) for i in range(3)]
    pods = [make_pod(f"p{i}", cpu=1000, memory=2 * Gi) for i in range(7)]
    got = engine_sequence(nodes, pods, (("LeastRequestedPriority", 1),))
    # 6 fit (2 per node), 7th has nowhere to go
    assert got[:6].count("n0") == 2
    assert got[:6].count("n1") == 2
    assert got[:6].count("n2") == 2
    assert got[6] is None


def test_round_robin_tie_break_cycles():
    nodes = [make_node(f"n{i}") for i in range(4)]
    # zero-request pods: all nodes tie -> RR cycles through all 4
    pods = [make_pod(f"p{i}") for i in range(8)]
    got = engine_sequence(nodes, pods, (("EqualPriority", 1),))
    assert got == ["n0", "n1", "n2", "n3", "n0", "n1", "n2", "n3"]


def test_single_fit_skips_rr_counter():
    # one node matches the selector -> early return must NOT advance RR
    nodes = [make_node("labeled", labels={"disk": "ssd"}),
             make_node("a"), make_node("b")]
    sel_pod = make_pod("sel", node_selector={"disk": "ssd"})
    tie_pod1 = make_pod("t1")
    tie_pod2 = make_pod("t2")
    got = engine_sequence(nodes, [sel_pod, tie_pod1, tie_pod2],
                          (("EqualPriority", 1),))
    # snapshot order: a, b, labeled. sel -> labeled (no RR tick);
    # t1 ties on all three (labeled still has most capacity? EqualPriority:
    # all tie) -> counter 0 -> "a"; t2 -> counter 1 -> "b"
    assert got == ["labeled", "a", "b"]


def test_assume_updates_cache_and_next_batch_sees_it():
    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu=1000, memory=2 * Gi))
    cache.add_node(make_node("n1", cpu=1000, memory=2 * Gi))
    eng = SchedulingEngine(cache, priorities=(("LeastRequestedPriority", 1),))
    [r1] = eng.schedule([make_pod("a", cpu=800, memory=Gi)])
    assert r1.node_name is not None
    other = {"n0": "n1", "n1": "n0"}[r1.node_name]
    # second batch: the big pod must land on the other node
    [r2] = eng.schedule([make_pod("b", cpu=800, memory=Gi)])
    assert r2.node_name == other
    # third can't fit anywhere
    [r3] = eng.schedule([make_pod("c", cpu=800, memory=Gi)])
    assert r3.node_name is None
    assert r3.fit_count == 0
