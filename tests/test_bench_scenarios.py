"""Bench scenarios (bench.py): compat-mode scheduleOne-over-HTTP and the
arrival-stream run, at CI scale.

The driver's BENCH run executes these at 5k nodes; here they run small so
CI pins the CONTRACTS: the compat loop binds every pod through the real
extender wire protocol, and the arrival stream produces a non-degenerate
create->bound distribution (p50 != p99 — VERDICT r5 weak #3's pre-loaded
drain gave every pod the same round-wide span)."""

from __future__ import annotations

import bench


def test_compat_scheduleone_over_http_binds_everything():
    pods_s, p50, p99, bound, unsched = bench.measure_compat_scheduleone(
        200, n_pods=60, drivers=3)
    assert bound == 60 and unsched == 0
    assert pods_s > 0
    assert p50 is not None and p99 is not None and p50 <= p99


def test_arrival_stream_distribution_is_not_degenerate():
    # warm=True compiles the micro-wave shape ladder so the measured pass
    # isn't skewed by a mid-stream compile burst (ISSUE 7)
    out = bench.run_arrival(200, rate=300, duration_s=3, warm=True,
                            min_quantum=64, max_quantum=256)
    assert out["bound"] == 900
    # intervals now attribute binds at their bind instants — exact count
    assert sum(out["intervals"]) == 900
    assert out["sustained_pods_s"] > 0
    assert out["p50_ms"] < out["p99_ms"], \
        "per-pod create->bound must be a real distribution"
    # the host-bound honesty fields (ISSUE 2/7): offered rate, end-of-offer
    # backlog and unbound count are reported explicitly, and a fully-kept-up
    # run reports zero unbound
    assert out["offered_pods_s"] == 300.0
    assert out["unbound"] == 0
    assert out["backlog_at_offer_end"] >= 0
    # the ISSUE 7 per-interval honesty plumbing: offered/backlog series
    # aligned with the bind intervals, creator self-audit present
    assert len(out["backlog_series"]) == len(out["intervals"])
    assert sum(out["offered_series"]) == 900
    assert out["offered_realized_pods_s"] > 0
    assert isinstance(out["creator_jitter_ok"], bool)
    assert out["creator_max_burst"] >= 1
    # latency is creator-stamped per pod: honest distributions never report
    # a p50 of zero while pods bound
    assert out["p50_ms"] > 0
