"""Bench scenarios (bench.py): compat-mode scheduleOne-over-HTTP and the
arrival-stream run, at CI scale.

The driver's BENCH run executes these at 5k nodes; here they run small so
CI pins the CONTRACTS: the compat loop binds every pod through the real
extender wire protocol, and the arrival stream produces a non-degenerate
create->bound distribution (p50 != p99 — VERDICT r5 weak #3's pre-loaded
drain gave every pod the same round-wide span)."""

from __future__ import annotations

import bench


def test_compat_scheduleone_over_http_binds_everything():
    pods_s, p50, p99, bound, unsched = bench.measure_compat_scheduleone(
        200, n_pods=60, drivers=3)
    assert bound == 60 and unsched == 0
    assert pods_s > 0
    assert p50 is not None and p99 is not None and p50 <= p99


def test_arrival_stream_distribution_is_not_degenerate():
    # warm=True compiles the micro-wave shape ladder so the measured pass
    # isn't skewed by a mid-stream compile burst (ISSUE 7)
    out = bench.run_arrival(200, rate=300, duration_s=3, warm=True,
                            min_quantum=64, max_quantum=256)
    assert out["bound"] == 900
    # intervals attribute binds at their bind instants over FULL buckets;
    # the trailing partial remainder rides separately (ISSUE 18) — the
    # exact count telescopes across both
    assert sum(out["intervals"]) + out["tail_partial"]["binds"] == 900
    assert out["sustained_pods_s"] > 0
    assert out["p50_ms"] < out["p99_ms"], \
        "per-pod create->bound must be a real distribution"
    # the host-bound honesty fields (ISSUE 2/7): offered rate, end-of-offer
    # backlog and unbound count are reported explicitly, and a fully-kept-up
    # run reports zero unbound
    assert out["offered_pods_s"] == 300.0
    assert out["unbound"] == 0
    assert out["backlog_at_offer_end"] >= 0
    # the ISSUE 7 per-interval honesty plumbing: offered/backlog series
    # aligned with the bind intervals, creator self-audit present
    assert len(out["backlog_series"]) == len(out["intervals"])
    assert sum(out["offered_series"]) + out["tail_partial"]["offered"] \
        == 900
    assert out["offered_realized_pods_s"] > 0
    assert isinstance(out["creator_jitter_ok"], bool)
    assert out["creator_max_burst"] >= 1
    # latency is creator-stamped per pod: honest distributions never report
    # a p50 of zero while pods bound
    assert out["p50_ms"] > 0


def test_interval_series_drops_trailing_partial_bucket():
    """The BENCH_r19 skew (ISSUE 18): a 19-pod sliver in a fractional
    final bucket next to 1322-pod steady buckets read as a rate collapse.
    interval_series must emit FULL buckets only, route the remainder to
    tail_partial with its true width, and telescope exactly."""
    binds = [(0.2, ["a"] * 100), (1.3, ["b"] * 100), (2.4, ["c"] * 100),
             (3.05, ["d"] * 19)]          # 3.05s end -> partial 4th bucket
    creates = [(0.1, 160), (1.1, 159)]
    backlog = [(0.5, 40), (1.5, 10), (3.02, 3)]
    iv, off, bk, tail = bench.interval_series(binds, creates, backlog,
                                              interval_s=1.0)
    assert iv == [100, 100, 100]          # full buckets only
    assert tail["binds"] == 19            # the sliver, out of the series
    assert abs(tail["width_s"] - 0.05) < 1e-9
    assert sum(iv) + tail["binds"] == 319
    assert off == [160, 159, 0] and tail["offered"] == 0
    assert bk == [40, 10, 0] and tail["backlog"] == 3

    # boundary case: a final event exactly ON a bucket edge opens a
    # zero-width tail (the bucket it starts is empty of time) — every
    # bucket in the series is still exactly interval_s wide
    iv2, _off2, _bk2, tail2 = bench.interval_series(
        [(0.5, ["x"] * 5), (2.0, ["y"] * 5)], [(0.1, 10)], [], 1.0)
    assert iv2 == [5, 0] and tail2["binds"] == 5 and tail2["width_s"] == 0.0

    # degenerate: everything inside one partial first bucket
    iv3, _o3, _b3, tail3 = bench.interval_series(
        [(0.2, ["x"])], [(0.1, 1)], [], 1.0)
    assert iv3 == [] and tail3["binds"] == 1
