"""Bench scenarios (bench.py): compat-mode scheduleOne-over-HTTP and the
arrival-stream run, at CI scale.

The driver's BENCH run executes these at 5k nodes; here they run small so
CI pins the CONTRACTS: the compat loop binds every pod through the real
extender wire protocol, and the arrival stream produces a non-degenerate
create->bound distribution (p50 != p99 — VERDICT r5 weak #3's pre-loaded
drain gave every pod the same round-wide span)."""

from __future__ import annotations

import bench


def test_compat_scheduleone_over_http_binds_everything():
    pods_s, p50, p99, bound, unsched = bench.measure_compat_scheduleone(
        200, n_pods=60, drivers=3)
    assert bound == 60 and unsched == 0
    assert pods_s > 0
    assert p50 is not None and p99 is not None and p50 <= p99


def test_arrival_stream_distribution_is_not_degenerate():
    # warm pass compiles the kernels so the measured pass isn't skewed by
    # a mid-stream compile burst
    bench.run_arrival(200, rate=200, duration_s=1)
    out = bench.run_arrival(200, rate=300, duration_s=3)
    assert out["bound"] == 900
    # intervals spread each round's binds over its duration (rounded to
    # 0.1), so the sum matches up to rounding
    assert abs(sum(out["intervals"]) - 900) < 1.0
    assert out["sustained_pods_s"] > 0
    assert out["p50_ms"] < out["p99_ms"], \
        "per-pod create->bound must be a real distribution"
    # the host-bound honesty fields (ISSUE 2): offered rate, end-of-offer
    # backlog and unbound count are reported explicitly, and a fully-kept-up
    # run reports zero unbound
    assert out["offered_pods_s"] == 300.0
    assert out["unbound"] == 0
    assert out["backlog_at_offer_end"] >= 0
