"""Chaos: kill components mid-storm, assert drain-to-bound convergence.

The reference's recovery claims (SURVEY §5.3/§5.4: everything is
level-triggered reconcile — controllers re-list on restart, the scheduler
rebuilds its cache from informers, assumed-pod TTL self-heals, leader
election gives active/passive HA) exercised the chaosmonkey way
(test/e2e/chaosmonkey/chaosmonkey.go, test/e2e/network_partition.go):

  - scheduler killed mid-storm -> replacement converges, no double binds
  - leading daemon crashes WITHOUT releasing its lease -> standby waits
    out the lease and finishes the drain (server.go:127-146 failover)
  - kubelets die mid-storm -> nodelifecycle marks NotReady and evicts;
    pods reschedule onto surviving nodes
  - watch stream compacted under the scheduler's feet
    (TooOldResourceVersion) -> relist, converge
  - apiserver process "crash" + restart from WAL mid-storm -> converge

Invariant after every storm: every pod bound exactly once — the store
refuses double binds, so bind_errors==0 plus all-bound is exactly-once.
"""

from __future__ import annotations

import dataclasses

import pytest

from kubernetes_tpu.api.types import ConditionStatus, make_node, make_pod
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.nodes.kubelet import HollowFleet
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.server.daemon import SchedulerDaemon, SchedulerOptions
from kubernetes_tpu.testing.chaosmonkey import Chaosmonkey, Test
from tests.test_nodes import FakeClock

Gi = 1 << 30


def _cluster(api, n_nodes=30, n_pods=300, cpu=4000):
    for i in range(n_nodes):
        api.create("Node", make_node(f"node-{i:03d}", cpu=cpu,
                                     memory=8 * Gi))
    for i in range(n_pods):
        api.create("Pod", make_pod(f"pod-{i:04d}", cpu=100))


def _assert_converged(api, n_pods, runnable=None):
    pods, _ = api.list("Pod")
    assert len(pods) == n_pods
    unbound = [p.name for p in pods if not p.node_name]
    assert not unbound, f"{len(unbound)} pods never bound: {unbound[:5]}"
    if runnable is not None:
        for p in pods:
            assert p.node_name in runnable, \
                f"{p.name} on dead node {p.node_name}"


def test_scheduler_killed_midstorm_replacement_converges():
    api = ApiServerLite()
    _cluster(api, n_pods=300)
    sched = Scheduler(api, record_events=False)
    sched.start()
    # schedule part of the storm, then the scheduler "dies"
    sched.schedule_round(max_batch=120)
    bound_before = sum(1 for p in api.list("Pod")[0] if p.node_name)
    assert 0 < bound_before < 300
    del sched

    def disruption():
        pass  # the kill already happened; monkey verifies recovery

    cm = Chaosmonkey(disruption)
    outcome = {}

    def run_replacement():
        sched2 = Scheduler(api, record_events=False)
        sched2.start()  # fresh relist: sees bound pods + the rest pending
        outcome.update(sched2.run_until_drained())

    cm.register(Test(test=run_replacement, name="replacement-scheduler"))
    cm.do()
    assert outcome["bind_errors"] == 0  # no double binds attempted
    _assert_converged(api, 300)


def test_daemon_failover_after_leader_crash():
    """Two daemon instances; the leader crashes WITHOUT releasing its
    lease mid-storm. The standby must wait out lease_duration, acquire,
    relist, and finish the drain."""
    clock = FakeClock()
    api = ApiServerLite()
    _cluster(api, n_pods=0)  # nodes only; the storm lands mid-flight
    opts = SchedulerOptions(healthz_port=None)
    a = SchedulerDaemon(api, "daemon-a", opts, now=clock)
    b = SchedulerDaemon(api, "daemon-b", opts, now=clock)
    a.step()  # a acquires
    b.step()
    assert a.is_leader() and not b.is_leader()
    for i in range(240):
        api.create("Pod", make_pod(f"pod-{i:04d}", cpu=100))
    a.scheduler.schedule_round(max_batch=100)
    bound_mid = sum(1 for p in api.list("Pod")[0] if p.node_name)
    assert 0 < bound_mid < 240

    def crash_leader():
        a.stop(release=False)  # hard kill: lease NOT released

    cm = Chaosmonkey(crash_leader)

    def standby_takes_over():
        # within the lease the standby must NOT lead
        b.step()
        assert not b.is_leader()
        clock.t += 16.0  # > lease_duration 15s
        for _ in range(50):
            stats = b.step()
            if b.is_leader() and stats["popped"] == 0 \
                    and b.scheduler.queue.ready_count() == 0:
                break
        assert b.is_leader()

    cm.register(Test(test=standby_takes_over, name="standby-failover"))
    cm.do()
    _assert_converged(api, 240)
    lease = api.get("Lease", "kube-system", "kube-scheduler")
    assert lease.holder == "daemon-b"
    assert lease.leader_transitions == 1
    b.stop()


def test_kubelet_deaths_midstorm_reschedule_elsewhere():
    """Kill a third of the kubelets mid-storm: nodelifecycle marks them
    NotReady after the grace period and evicts their pods; the scheduler
    reschedules onto survivors (network_partition.go's node-death story)."""
    from kubernetes_tpu.controllers.nodelifecycle import (
        NodeLifecycleController,
    )

    from kubernetes_tpu.api.types import LabelSelector
    from kubernetes_tpu.api.workloads import ReplicaSet
    from kubernetes_tpu.controllers.replicaset import ReplicaSetController

    clock = FakeClock()
    api = ApiServerLite()
    factory = SharedInformerFactory(api)
    fleet = HollowFleet(api, factory, now=clock)
    n_nodes, n_pods = 12, 120
    for i in range(n_nodes):
        fleet.add_node(make_node(f"node-{i:03d}", cpu=32_000, memory=64 * Gi))
    # the storm is an RC-managed workload, so evicted pods are REPLACED and
    # rescheduled (the reference's node-death story needs the controller)
    api.create("ReplicaSet", ReplicaSet(
        "web", replicas=n_pods,
        selector=LabelSelector(match_labels={"app": "web"}),
        template=make_pod("", cpu=100, labels={"app": "web"})))
    nlc = NodeLifecycleController(api, factory, now=clock,
                                  eviction_timeout=60.0,  # shorten the 5min
                                  # default so the sim converges in few ticks
                                  record_events=False)
    rsc = ReplicaSetController(api, factory, record_events=False)
    sched = Scheduler(api, record_events=False, now=clock)
    sched.start()
    factory.step_all()
    rsc.pump()
    sched.run_until_drained()
    factory.step_all()
    fleet.step()  # pods running

    dead = [f"node-{i:03d}" for i in range(0, n_nodes, 3)]

    def kill_kubelets():
        for name in dead:
            del fleet.kubelets[name]  # process gone: no more heartbeats

    cm = Chaosmonkey(kill_kubelets)

    def cluster_heals():
        # heartbeats for survivors only; grace period passes for the dead,
        # then the rate-limited eviction drains them over several ticks
        for _ in range(30):
            clock.t += 10.0
            fleet.heartbeat_all()
            factory.step_all()
            nlc.monitor_tick()
            nlc.pump()
            rsc.pump()
            sched.sync()
            sched.schedule_round()
            factory.step_all()
            fleet.step()
        ready = {n.name for n in api.list("Node")[0]
                 if n.condition("Ready") == ConditionStatus.TRUE}
        for name in dead:
            assert name not in ready, f"dead {name} still Ready"

    cm.register(Test(test=cluster_heals, name="node-death-heal"))
    cm.do()
    # convergence: the RS is back to full strength, every replacement
    # runs on a surviving node, nothing Running remains on a dead one
    pods = [p for p in api.list("Pod")[0] if not p.deleted]
    running = [p for p in pods if p.phase == "Running"]
    assert len(running) >= n_pods
    for p in running:
        assert p.node_name not in dead


def test_watch_compaction_forces_relist_and_converges():
    """A tiny event log + a flood of writes while the scheduler lags ->
    TooOldResourceVersion on its next sync -> full relist -> drain."""
    api = ApiServerLite(max_log=50)
    _cluster(api, n_nodes=10, n_pods=60)
    sched = Scheduler(api, record_events=False)
    sched.start()
    sched.schedule_round(max_batch=20)

    def flood():
        # unrelated churn blows the 50-event log out from under the cursor
        for i in range(200):
            api.create("Pod", make_pod(f"noise-{i:03d}", cpu=1,
                                       node_name="node-000"))

    cm = Chaosmonkey(flood)
    cm.register(Test(
        test=lambda: sched.run_until_drained(), name="relist-converge"))
    cm.do()
    pods, _ = api.list("Pod")
    storm = [p for p in pods if p.name.startswith("pod-")]
    assert all(p.node_name for p in storm)
    assert len(storm) == 60


def _bind_transitions(api):
    """Per-pod distinct bound nodes across the event log — the store-level
    exactly-once audit: a pod bound twice (to anywhere) would show two
    distinct transitions; the store refuses them, so >1 here is a real
    double bind."""
    nodes_by_pod = {}
    for ev in api._log:
        if ev.kind == "Pod" and ev.type == "MODIFIED" and ev.obj.node_name:
            nodes_by_pod.setdefault(ev.obj.key(), set()).add(
                ev.obj.node_name)
    return nodes_by_pod


def _drain_stream(sched, loop, deadline_s=60):
    import time as _time
    deadline = _time.monotonic() + deadline_s
    total = {}
    while _time.monotonic() < deadline:
        stats = loop.step()
        for k, v in stats.items():
            total[k] = total.get(k, 0) + v
        if loop.settled():
            return total
        sched.sync(wait=0.02)
    raise AssertionError("stream drain did not settle")


def test_streaming_loop_crash_midoffer_exactly_once():
    """The ISSUE 8 streaming mirror of the scheduler-killed storm: the
    ALWAYS-ON loop dies mid-offer with a wave in flight (popped pods
    never harvested — exactly the state a process crash leaves). A
    replacement scheduler + loop relists and converges: every pod bound
    exactly once, zero double binds, zero bind errors, zero lost pods."""
    api = ApiServerLite()
    _cluster(api, n_pods=300)
    sched = Scheduler(api, record_events=False)
    sched.start()
    loop = sched.stream(budget_s=30.0, min_quantum=64, max_quantum=64)
    loop.step()   # wave 1 dispatched, in flight
    loop.step()   # wave 1 harvested+bound, wave 2 in flight
    assert loop.inflight is not None
    bound_before = sum(1 for p in api.list("Pod")[0] if p.node_name)
    assert 0 < bound_before < 300
    del loop, sched  # CRASH: no close(), no flush — the in-flight wave
    # and every queue-resident pod die with the process

    cm = Chaosmonkey(lambda: None)
    outcome = {}

    def replacement():
        s2 = Scheduler(api, record_events=False)
        s2.start()  # relist: bound pods into cache, the rest pend
        l2 = s2.stream(budget_s=30.0, min_quantum=64, max_quantum=64)
        outcome.update(_drain_stream(s2, l2))
        outcome.update(l2.close())

    cm.register(Test(test=replacement, name="streaming-replacement"))
    cm.do()
    assert outcome["bind_errors"] == 0, outcome
    _assert_converged(api, 300)
    assert all(len(v) == 1 for v in _bind_transitions(api).values())


def test_streaming_injected_bind_faults_exactly_once():
    """Injected bind failures AND landed-but-timed-out binds on the
    STREAMING path: the backoff requeue heals both end to end — all
    pods bound, bind_errors counted (the faults really fired), and the
    store-level audit shows every pod bound exactly once (the timeout
    retries were refused, never double-applied)."""
    from kubernetes_tpu.testing.churn import FaultyBindApi

    api = ApiServerLite()
    _cluster(api, n_pods=0)
    faulty = FaultyBindApi(api, fail_rate=0.05, timeout_rate=0.03, seed=7)
    sched = Scheduler(faulty, record_events=False)
    sched.start()
    loop = sched.stream(budget_s=30.0, min_quantum=64, max_quantum=64)
    for i in range(300):
        api.create("Pod", make_pod(f"pod-{i:04d}", cpu=100))
    total = _drain_stream(sched, loop)
    total.update(loop.close())
    assert faulty.injected_failures > 0 and faulty.injected_timeouts > 0
    assert total["bind_errors"] >= (faulty.injected_failures
                                    + faulty.injected_timeouts), total
    _assert_converged(api, 300)
    assert all(len(v) == 1 for v in _bind_transitions(api).values())


def test_apiserver_crash_restart_midstorm(tmp_path):
    """Durable apiserver dies mid-storm (nothing flushed beyond the WAL);
    a new process restores and a new scheduler converges — the
    restore-from-backup.sh + relist story, from the chaos angle."""
    d = str(tmp_path / "data")
    api = ApiServerLite(data_dir=d)
    _cluster(api, n_pods=200)
    sched = Scheduler(api, record_events=False)
    sched.start()
    sched.schedule_round(max_batch=80)

    state = {}

    def crash_and_restore():
        # drop both objects without close(): batch-flushed WAL survives
        state["api"] = ApiServerLite(data_dir=d)

    cm = Chaosmonkey(crash_and_restore)

    def converge():
        api2 = state["api"]
        pods, _ = api2.list("Pod")
        assert len(pods) == 200
        assert sum(1 for p in pods if p.node_name) >= 80
        sched2 = Scheduler(api2, record_events=False)
        sched2.start()
        totals = sched2.run_until_drained()
        assert totals["bind_errors"] == 0

    cm.register(Test(test=converge, name="apiserver-restart"))
    cm.do()
    _assert_converged(state["api"], 200)
