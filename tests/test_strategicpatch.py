"""Strategic merge patch + 3-way apply semantics
(cli/strategicpatch.py, ktctl apply/patch/edit).

Table-driven after the reference's strategicpatch tests
(staging/src/k8s.io/apimachinery/pkg/util/strategicpatch/patch_test.go) and
apply's 3-way behavior (pkg/kubectl/cmd/apply.go:658): list-item removal,
merge-key item updates, atomic lists, null-deletion, $patch: delete, and
the controller-owned-field pass-through that 2-way diffs get wrong."""

import io
import json

import pytest

from kubernetes_tpu.api.workloads import Namespace
from kubernetes_tpu.cli.ktctl import Ktctl
from kubernetes_tpu.cli.strategicpatch import (
    strategic_merge_patch,
    three_way_merge,
)
from kubernetes_tpu.server.apiserver import ApiServer


# ------------------------------------------------- strategic merge (2-way)

CASES = [
    # (name, current, patch, expected)
    ("scalar update", {"replicas": 3}, {"replicas": 5}, {"replicas": 5}),
    ("null deletes key",
     {"labels": {"a": "1", "b": "2"}},
     {"labels": {"a": None}},
     {"labels": {"b": "2"}}),
    ("nested map merge",
     {"selector": {"match_labels": {"app": "web"}}, "replicas": 1},
     {"selector": {"match_labels": {"tier": "fe"}}},
     {"selector": {"match_labels": {"app": "web", "tier": "fe"}},
      "replicas": 1}),
    ("merge-key list item update in place",
     {"containers": [{"name": "a", "image": "v1"},
                     {"name": "b", "image": "v1"}]},
     {"containers": [{"name": "b", "image": "v2"}]},
     {"containers": [{"name": "a", "image": "v1"},
                     {"name": "b", "image": "v2"}]}),
    ("merge-key list append",
     {"containers": [{"name": "a"}]},
     {"containers": [{"name": "c", "image": "new"}]},
     {"containers": [{"name": "a"}, {"name": "c", "image": "new"}]}),
    ("$patch delete removes keyed item",
     {"containers": [{"name": "a"}, {"name": "b"}]},
     {"containers": [{"name": "a", "$patch": "delete"}]},
     {"containers": [{"name": "b"}]}),
    ("un-keyed list replaces atomically",
     {"access_modes": ["RWO", "RWX"]},
     {"access_modes": ["ROX"]},
     {"access_modes": ["ROX"]}),
    ("$patch replace swaps the whole map",
     {"labels": {"a": "1", "b": "2"}},
     {"labels": {"$patch": "replace", "c": "3"}},
     {"labels": {"c": "3"}}),
]


@pytest.mark.parametrize("name,current,patch,expected",
                         CASES, ids=[c[0] for c in CASES])
def test_strategic_merge_patch(name, current, patch, expected):
    assert strategic_merge_patch(current, patch) == expected


# ------------------------------------------------------ three-way (apply)


def test_three_way_prunes_manifest_removed_list_item():
    """THE case 2-way apply silently loses (r4 VERDICT weak #7): a
    container removed from the manifest must be pruned server-side."""
    original = {"containers": [{"name": "app"}, {"name": "sidecar"}]}
    modified = {"containers": [{"name": "app"}]}
    live = {"containers": [{"name": "app"}, {"name": "sidecar"}],
            "node_name": "n1"}
    merged = three_way_merge(original, modified, live)
    assert [c["name"] for c in merged["containers"]] == ["app"]
    assert merged["node_name"] == "n1"  # server-set field survives


def test_three_way_preserves_controller_owned_fields():
    """An HPA moved live replicas to 5; the manifest does NOT manage
    replicas — apply must not stomp it (the defining 3-way property:
    manifest-UNSPECIFIED fields are controller-owned)."""
    original = {"labels": {"app": "web"}}
    modified = {"labels": {"app": "web", "v": "2"}}
    live = {"replicas": 5, "labels": {"app": "web"}, "status": "ok"}
    merged = three_way_merge(original, modified, live)
    assert merged["replicas"] == 5  # HPA's write survives
    assert merged["labels"] == {"app": "web", "v": "2"}
    assert merged["status"] == "ok"


def test_three_way_reverts_live_drift_on_manifest_specified_fields():
    """CreateThreeWayMergePatch's SECOND diff (patch.go:1958: diff
    (current, modified) with IgnoreDeletions): a field the manifest DOES
    manage is driven back to the manifest's value even when last-applied
    already matches the manifest — kubectl apply reverts manual/live
    drift (this is also why kubectl docs warn against pinning replicas
    under an HPA). ADVICE r5 medium: the previous 2-diff-only merge left
    the drift in place."""
    original = {"replicas": 2, "image": "app:v1"}
    modified = {"replicas": 2, "image": "app:v1"}
    live = {"replicas": 2, "image": "app:drifted", "status": "ok"}
    merged = three_way_merge(original, modified, live)
    assert merged["image"] == "app:v1"  # drift reverted
    assert merged["status"] == "ok"     # unmanaged field untouched


def test_three_way_reverts_drift_inside_merge_keyed_list_item():
    original = {"containers": [{"name": "app", "image": "v1"}]}
    modified = {"containers": [{"name": "app", "image": "v1"}]}
    live = {"containers": [{"name": "app", "image": "hand-edited",
                            "requests": {"cpu": 100}}]}
    merged = three_way_merge(original, modified, live)
    c = merged["containers"][0]
    assert c["image"] == "v1"           # drift reverted
    assert c["requests"] == {"cpu": 100}  # live-only field kept


def test_three_way_readds_manifest_field_controller_removed():
    """A manifest-managed key removed from live comes back (the delta
    half sees an addition)."""
    original = {"labels": {"app": "web"}}
    modified = {"labels": {"app": "web"}}
    live = {"labels": {}}
    merged = three_way_merge(original, modified, live)
    assert merged["labels"] == {"app": "web"}


def test_three_way_deletes_map_key_removed_from_manifest():
    original = {"labels": {"app": "web", "tier": "fe"}}
    modified = {"labels": {"app": "web"}}
    live = {"labels": {"app": "web", "tier": "fe", "ctrl": "x"}}
    merged = three_way_merge(original, modified, live)
    assert merged["labels"] == {"app": "web", "ctrl": "x"}


def test_three_way_reorder_only_is_a_noop_on_live_state():
    """1.7 strategic merge has no $setElementOrder: a pure reorder diffs
    to nothing and live order stands."""
    original = {"containers": [{"name": "a"}, {"name": "b"}]}
    modified = {"containers": [{"name": "b"}, {"name": "a"}]}
    live = {"containers": [{"name": "a"}, {"name": "b"}]}
    assert three_way_merge(original, modified, live) == live


def test_three_way_merge_key_item_field_update():
    original = {"containers": [{"name": "app", "image": "v1"}]}
    modified = {"containers": [{"name": "app", "image": "v2"}]}
    live = {"containers": [{"name": "app", "image": "v1",
                            "requests": {"cpu": 100}}]}
    merged = three_way_merge(original, modified, live)
    c = merged["containers"][0]
    assert c["image"] == "v2"
    assert c["requests"] == {"cpu": 100}  # live-only field kept


# --------------------------------------------------------- through ktctl


def mk_cli():
    api = ApiServer()
    api.store.create("Namespace", Namespace("default"))
    out = io.StringIO()
    return api, Ktctl(api, out=out), out


DEPLOY_V1 = """
kind: Deployment
name: web
namespace: default
replicas: 2
selector:
  match_labels: {app: web}
template:
  name: ""
  namespace: default
  labels: {app: web}
  containers:
  - name: app
    requests: {cpu: 100}
  - name: sidecar
    requests: {cpu: 50}
"""

DEPLOY_V2 = """
kind: Deployment
name: web
namespace: default
replicas: 2
selector:
  match_labels: {app: web}
template:
  name: ""
  namespace: default
  labels: {app: web}
  containers:
  - name: app
    requests: {cpu: 100}
"""


def test_apply_three_way_through_ktctl(tmp_path):
    api, kt, out = mk_cli()
    m = tmp_path / "d.yaml"
    m.write_text(DEPLOY_V1)
    assert kt.run(["apply", "-f", str(m)]) == 0
    dep = api.get("Deployment", "default", "web")
    assert len(dep.template.containers) == 2
    # a controller (HPA) scales live replicas to 5
    api.scale("Deployment", "default", "web", replicas=5)
    # manifest drops the sidecar but still says replicas: 2 — reference
    # semantics (CreateThreeWayMergePatch second diff): the manifest
    # MANAGES replicas, so apply drives it back to 2, reverting the
    # HPA's live write (the documented kubectl-vs-HPA conflict; drop
    # replicas from the manifest to hand it to the controller)
    m.write_text(DEPLOY_V2)
    assert kt.run(["apply", "-f", str(m)]) == 0
    dep = api.get("Deployment", "default", "web")
    # removed list item pruned; manifest-pinned replicas enforced
    assert [c.name for c in dep.template.containers] == ["app"]
    assert dep.replicas == 2
    # server-owned counters the manifest never wrote stay server-owned
    assert dep.resource_version > 0
    # idempotent re-apply reports unchanged
    out.truncate(0), out.seek(0)
    assert kt.run(["apply", "-f", str(m)]) == 0
    assert "unchanged" in out.getvalue()


POD_MANIFEST_V1 = """
apiVersion: v1
kind: Pod
metadata:
  name: web
  namespace: default
  labels: {app: web}
spec:
  containers:
  - name: app
    image: "app:v1"
    resources: {requests: {cpu: 100m}}
"""


def test_apply_kubectl_shaped_pod_manifest(tmp_path):
    """Pod manifests use the metadata/spec shape; apply must merge in that
    shape — updating the image really updates it, and the scheduler-set
    nodeName binding plus pod status survive."""
    api, kt, out = mk_cli()
    m = tmp_path / "p.yaml"
    m.write_text(POD_MANIFEST_V1)
    assert kt.run(["apply", "-f", str(m)]) == 0
    # the scheduler binds it and the kubelet runs it
    live = api.get("Pod", "default", "web")
    live.node_name = "n1"
    live.phase = "Running"
    api.update("Pod", live)
    # user bumps the image
    m.write_text(POD_MANIFEST_V1.replace("app:v1", "app:v2"))
    assert kt.run(["apply", "-f", str(m)]) == 0
    p = api.get("Pod", "default", "web")
    assert p.containers[0].image == "app:v2"  # change really applied
    assert p.node_name == "n1"  # binding survives
    assert p.phase == "Running"  # status survives


def test_patch_kubectl_shaped_pod(tmp_path):
    api, kt, out = mk_cli()
    m = tmp_path / "p.yaml"
    m.write_text(POD_MANIFEST_V1)
    assert kt.run(["apply", "-f", str(m)]) == 0
    patch = json.dumps({"spec": {"priority": 10},
                        "metadata": {"labels": {"tier": "fe"}}})
    assert kt.run(["patch", "pod", "web", "-p", patch]) == 0
    p = api.get("Pod", "default", "web")
    assert p.priority == 10
    assert p.labels == {"app": "web", "tier": "fe"}
    assert p.containers[0].image == "app:v1"  # untouched


def test_patch_verb(tmp_path):
    api, kt, out = mk_cli()
    m = tmp_path / "d.yaml"
    m.write_text(DEPLOY_V1)
    assert kt.run(["apply", "-f", str(m)]) == 0
    patch = json.dumps({"replicas": 7,
                        "template": {"containers": [
                            {"name": "sidecar", "$patch": "delete"}]}})
    assert kt.run(["patch", "deploy", "web", "-p", patch]) == 0
    dep = api.get("Deployment", "default", "web")
    assert dep.replicas == 7
    assert [c.name for c in dep.template.containers] == ["app"]
    assert "patched" in out.getvalue()


def test_edit_verb(tmp_path, monkeypatch):
    api, kt, out = mk_cli()
    m = tmp_path / "d.yaml"
    m.write_text(DEPLOY_V1)
    assert kt.run(["apply", "-f", str(m)]) == 0
    # an "editor" that bumps replicas in place
    editor = tmp_path / "ed.sh"
    editor.write_text("#!/bin/sh\nsed -i 's/replicas: 2/replicas: 9/' $1\n")
    editor.chmod(0o755)
    monkeypatch.setenv("KTCTL_EDITOR", str(editor))
    assert kt.run(["edit", "deploy", "web"]) == 0
    assert api.get("Deployment", "default", "web").replicas == 9


def test_diff_previews_apply_without_writing(tmp_path):
    """kubectl diff semantics: exit 1 + unified diff when apply would
    change something, exit 0 clean, and the live object is untouched."""
    api, kt, out = mk_cli()
    m = tmp_path / "d.yaml"
    m.write_text(DEPLOY_V1)
    # would-create
    assert kt.run(["diff", "-f", str(m)]) == 1
    assert "would be created" in out.getvalue()
    assert kt.run(["apply", "-f", str(m)]) == 0
    # clean: nothing to change
    out.truncate(0), out.seek(0)
    assert kt.run(["diff", "-f", str(m)]) == 0
    assert out.getvalue() == ""
    # manifest drops the sidecar: diff previews the removal, no write
    m.write_text(DEPLOY_V2)
    out.truncate(0), out.seek(0)
    assert kt.run(["diff", "-f", str(m)]) == 1
    assert "sidecar" in out.getvalue()
    dep = api.get("Deployment", "default", "web")
    assert len(dep.template.containers) == 2  # live object untouched


NODE_MANIFEST = """
apiVersion: v1
kind: Node
metadata:
  name: n1
  labels: {pool: web}
  annotations:
    owner: team-a
"""


def test_apply_node_annotation_change_sticks(tmp_path):
    """ADVICE r5 low (ktctl.py _decode_canon): user-requested Node
    annotation changes must survive apply — the old code wholesale-restored
    the live annotation map after the merge, silently discarding them.
    Server-owned keys (TTL controller, attach-detach) still survive."""
    api, kt, out = mk_cli()
    m = tmp_path / "n.yaml"
    m.write_text(NODE_MANIFEST)
    assert kt.run(["apply", "-f", str(m)]) == 0
    # controllers write their own keys on the live object
    live = api.get("Node", "", "n1")
    live.annotations["node.alpha.kubernetes.io/ttl"] = "30"
    live.annotations["volumes.kubernetes.io/attached"] = "vol-1"
    api.update("Node", live)
    # user changes one annotation and adds another
    m.write_text(NODE_MANIFEST.replace("owner: team-a",
                                       "owner: team-b\n    rack: r7"))
    assert kt.run(["apply", "-f", str(m)]) == 0
    n = api.get("Node", "", "n1")
    assert n.annotations["owner"] == "team-b"      # change applied
    assert n.annotations["rack"] == "r7"           # addition applied
    assert n.annotations["node.alpha.kubernetes.io/ttl"] == "30"
    assert n.annotations["volumes.kubernetes.io/attached"] == "vol-1"


def test_apply_node_annotation_removal_prunes(tmp_path):
    """Dropping a previously-applied annotation from the manifest deletes
    it (3-way deletions half), without touching controller-owned keys."""
    api, kt, out = mk_cli()
    m = tmp_path / "n.yaml"
    m.write_text(NODE_MANIFEST)
    assert kt.run(["apply", "-f", str(m)]) == 0
    live = api.get("Node", "", "n1")
    live.annotations["node.alpha.kubernetes.io/ttl"] = "15"
    api.update("Node", live)
    m.write_text(NODE_MANIFEST.replace("\n  annotations:\n    owner: team-a",
                                       ""))
    assert kt.run(["apply", "-f", str(m)]) == 0
    n = api.get("Node", "", "n1")
    assert "owner" not in n.annotations            # pruned
    assert n.annotations["node.alpha.kubernetes.io/ttl"] == "15"


POD_MANIFEST_FLAT = """
kind: Pod
name: flatp
namespace: default
labels: {app: flat}
containers:
- name: app
  image: app:v1
  requests: {cpu: 100}
"""


def test_apply_flat_shape_pod_manifest_updates_apply(tmp_path):
    """decode_any accepts the flat native shape too; the delta projection
    must tolerate the raw manifest not nesting metadata/spec the way the
    canonical encoding does — a flat manifest's image bump must really
    apply (regression: empty projection silently dropped every update
    while still printing 'configured')."""
    api, kt, out = mk_cli()
    m = tmp_path / "p.yaml"
    m.write_text(POD_MANIFEST_FLAT)
    assert kt.run(["apply", "-f", str(m)]) == 0
    assert api.get("Pod", "default", "flatp").containers[0].image == "app:v1"
    m.write_text(POD_MANIFEST_FLAT.replace("app:v1", "app:v2"))
    assert kt.run(["apply", "-f", str(m)]) == 0
    p = api.get("Pod", "default", "flatp")
    assert p.containers[0].image == "app:v2"
    # and drift on a flat-manifest-specified field reverts
    p.labels["app"] = "drifted"
    api.update("Pod", p)
    assert kt.run(["apply", "-f", str(m)]) == 0
    assert api.get("Pod", "default", "flatp").labels["app"] == "flat"
