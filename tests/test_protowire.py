"""Protobuf fast-path codec: round-trip fidelity + the extender's binary
cache sync (the --kube-api-content-type analog, SURVEY §5.8).

The scheduling outcome must be IDENTICAL whether state crossed the wire as
JSON or protobuf — pinned by evaluating the same pod against a backend
synced each way.
"""

from __future__ import annotations

import json
import random
import urllib.request

import pytest

from kubernetes_tpu.api import protowire, serde
from kubernetes_tpu.api.types import make_pod
from kubernetes_tpu.server.extender import ExtenderHTTPServer, TPUExtenderBackend
from tests.test_full_fuzz import _existing, full_random_nodes, full_random_pod

pytestmark = pytest.mark.skipif(not protowire.available(),
                                reason="protoc/protobuf unavailable")


def _rand_cluster(seed=5, n_nodes=24, n_pods=40):
    rng = random.Random(seed)
    nodes = full_random_nodes(rng, n_nodes)
    pods = [full_random_pod(rng, i, [n.name for n in nodes])
            for i in range(n_pods)] + _existing(rng, nodes, 10)
    return nodes, pods


def test_nodes_roundtrip_bitexact():
    nodes, _ = _rand_cluster()
    out = protowire.decode_nodes(protowire.encode_nodes(nodes))
    assert len(out) == len(nodes)
    for a, b in zip(nodes, out):
        assert a == b, f"node {a.name} diverged over the wire"


def test_pods_roundtrip_bitexact_scheduling_fields():
    _, pods = _rand_cluster()
    out = protowire.decode_pods(protowire.encode_pods(pods))
    assert len(out) == len(pods)
    for a, b in zip(pods, out):
        # scheduling-read surface must survive exactly (probes/status-only
        # fields are JSON-path; zero them for the comparison)
        import dataclasses
        strip = dict(resource_version=0, ready=True, restart_count=0,
                     restart_policy="Always", host_network=False,
                     security_context=None)
        ca = dataclasses.replace(a, **strip)
        cb = dataclasses.replace(b, **strip)
        for c in ca.containers + cb.containers:
            c.liveness_probe = c.readiness_probe = None
            c.security_context = None
        assert ca == cb, f"pod {a.key()} diverged over the wire"


def test_binary_payload_is_smaller_than_json():
    nodes, _ = _rand_cluster(n_nodes=200)
    binary = protowire.encode_nodes(nodes)
    as_json = json.dumps({"items": [serde.encode_node(n)
                                    for n in nodes]}).encode()
    assert len(binary) < len(as_json), (len(binary), len(as_json))


def test_extender_binary_sync_scheduling_equivalence():
    """Same cluster synced via JSON vs protobuf -> identical /filter and
    /prioritize answers for the same pod."""
    nodes, pods = _rand_cluster(seed=9, n_nodes=16, n_pods=0)
    bound = [p for p in pods if p.node_name]

    def serve(backend):
        srv = ExtenderHTTPServer(backend)
        srv.start()
        return srv

    # JSON path
    b_json = TPUExtenderBackend()
    srv_json = serve(b_json)
    # protobuf path
    b_pb = TPUExtenderBackend()
    srv_pb = serve(b_pb)
    try:
        url_json = f"http://127.0.0.1:{srv_json.port}"
        url_pb = f"http://127.0.0.1:{srv_pb.port}"
        body = json.dumps({"items": [serde.encode_node(n)
                                     for n in nodes]}).encode()
        req = urllib.request.Request(
            url_json + "/cache/nodes", data=body,
            headers={"Content-Type": "application/json"})
        assert json.loads(urllib.request.urlopen(req, timeout=30).read())[
            "synced"] == len(nodes)
        req = urllib.request.Request(
            url_pb + "/cache/nodes", data=protowire.encode_nodes(nodes),
            headers={"Content-Type": protowire.CONTENT_TYPE})
        assert json.loads(urllib.request.urlopen(req, timeout=30).read())[
            "synced"] == len(nodes)

        pod = make_pod("probe", cpu=100, node_selector={"disk": "ssd"})
        args = json.dumps({"Pod": serde.encode_pod(pod),
                           "NodeNames": [n.name for n in nodes]}).encode()

        def post(url, verb):
            r = urllib.request.Request(
                url + verb, data=args,
                headers={"Content-Type": "application/json"})
            return json.loads(urllib.request.urlopen(r, timeout=120).read())

        f_json = post(url_json, "/filter")
        f_pb = post(url_pb, "/filter")
        assert f_json["NodeNames"] == f_pb["NodeNames"]
        assert sorted(f_json["FailedNodes"]) == sorted(f_pb["FailedNodes"])
        p_json = post(url_json, "/prioritize")
        p_pb = post(url_pb, "/prioritize")
        assert p_json == p_pb
    finally:
        srv_json.stop()
        srv_pb.stop()
