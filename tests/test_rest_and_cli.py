"""REST facade (server/rest_http.py) + RestClient + ktctl CLI.

Harness shape mirrors the reference's cmd tests (pkg/kubectl/cmd/*_test.go
with a fake REST backend) — here the backend is the real chain over HTTP."""

import io
import json

import pytest
import yaml

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.api.workloads import Namespace, ReplicaSet
from kubernetes_tpu.api.types import LabelSelector
from kubernetes_tpu.cli.ktctl import Ktctl
from kubernetes_tpu.cli.rest_client import RestClient
from kubernetes_tpu.server.apiserver import ApiServer
from kubernetes_tpu.server.apiserver_lite import NotFound
from kubernetes_tpu.server.rest_http import RestServer


@pytest.fixture()
def rest():
    api = ApiServer()
    api.store.create("Namespace", Namespace("default"))
    srv = RestServer(api)
    srv.start()
    yield api, RestClient(f"http://127.0.0.1:{srv.port}")
    srv.stop()


def test_rest_crud_roundtrip(rest):
    api, client = rest
    client.create("Node", make_node("n1", cpu=2000))
    client.create("Pod", make_pod("p1", cpu=100, memory=1 << 20))
    node = client.get("Node", "", "n1")
    assert node.allocatable.milli_cpu == 2000
    pods, rv = client.list("Pod")
    assert [p.name for p in pods] == ["p1"] and rv > 0
    p = pods[0]
    p.labels["x"] = "y"
    client.update("Pod", p)
    assert client.get("Pod", "default", "p1").labels["x"] == "y"
    client.delete("Pod", "default", "p1")
    with pytest.raises(NotFound):
        client.get("Pod", "default", "p1")


def test_rest_binding_and_watch(rest):
    api, client = rest
    client.create("Node", make_node("n1"))
    rv0 = client.list("Pod")[1]
    client.create("Pod", make_pod("w"))
    from kubernetes_tpu.api.types import Binding
    client.bind(Binding("w", "default", "default/w", "n1"))
    assert client.get("Pod", "default", "w").node_name == "n1"
    evs = client.watch_since(("Pod",), rv0)
    assert [e.type for e in evs] == ["ADDED", "MODIFIED"]
    assert evs[-1].obj.node_name == "n1"


def test_rest_scale_and_healthz(rest):
    api, client = rest
    api.store.create("ReplicaSet", ReplicaSet(
        "rs", "default", replicas=2,
        selector=LabelSelector(match_labels={"a": "b"})))
    assert client.scale("ReplicaSet", "default", "rs") == 2
    client.scale("ReplicaSet", "default", "rs", replicas=7)
    assert api.store.get("ReplicaSet", "default", "rs").replicas == 7
    assert client.healthz() == {"status": "ok"}
    assert client.version()["gitVersion"].startswith("v1.7")


def make_cli():
    api = ApiServer()
    api.store.create("Namespace", Namespace("default"))
    out = io.StringIO()
    return api, Ktctl(api, out=out), out


def test_ktctl_get_table_and_json():
    api, cli, out = make_cli()
    api.create("Node", make_node("n1"))
    api.create("Pod", make_pod("a", cpu=100, memory=1 << 20))
    api.create("Pod", make_pod("b", cpu=100, memory=1 << 20))
    assert cli.run(["get", "pods"]) == 0
    text = out.getvalue()
    assert "NAME" in text and "a" in text and "b" in text
    out.truncate(0), out.seek(0)
    cli.run(["get", "po", "a", "-o", "json"])
    data = json.loads(out.getvalue())
    assert data[0]["name"] == "a"
    out.truncate(0), out.seek(0)
    cli.run(["get", "nodes", "-o", "name"])
    assert out.getvalue().strip() == "nodes/n1"


def test_ktctl_create_apply_delete(tmp_path):
    api, cli, out = make_cli()
    manifest = tmp_path / "rs.yaml"
    manifest.write_text(yaml.safe_dump({
        "kind": "ReplicaSet", "name": "web", "namespace": "default",
        "replicas": 3,
        "selector": {"match_labels": {"app": "web"}},
    }))
    assert cli.run(["create", "-f", str(manifest)]) == 0
    assert api.store.get("ReplicaSet", "default", "web").replicas == 3
    # apply: unchanged -> "unchanged"; edited -> "configured"
    cli.run(["apply", "-f", str(manifest)])
    assert "configured" in out.getvalue() or "unchanged" in out.getvalue()
    manifest.write_text(yaml.safe_dump({
        "kind": "ReplicaSet", "name": "web", "namespace": "default",
        "replicas": 5,
        "selector": {"match_labels": {"app": "web"}},
    }))
    cli.run(["apply", "-f", str(manifest)])
    assert api.store.get("ReplicaSet", "default", "web").replicas == 5
    cli.run(["delete", "rs", "web"])
    with pytest.raises(NotFound):
        api.store.get("ReplicaSet", "default", "web")


def test_ktctl_accepts_k8s_pod_manifest(tmp_path):
    api, cli, out = make_cli()
    manifest = tmp_path / "pod.yaml"
    manifest.write_text(yaml.safe_dump({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "nginx", "namespace": "default",
                     "labels": {"app": "nginx"}},
        "spec": {"containers": [{
            "name": "c", "image": "nginx:1.13",
            "resources": {"requests": {"cpu": "250m", "memory": "64Mi"}}}]},
    }))
    assert cli.run(["create", "-f", str(manifest)]) == 0
    pod = api.store.get("Pod", "default", "nginx")
    assert pod.containers[0].requests["cpu"] == 250
    assert pod.containers[0].requests["memory"] == 64 << 20


def test_ktctl_label_taint_cordon_drain():
    api, cli, out = make_cli()
    api.create("Node", make_node("n1"))
    api.create("Pod", make_pod("p", node_name=""))
    api.store.bind(__import__("kubernetes_tpu.api.types",
                              fromlist=["Binding"]).Binding(
        "p", "default", "default/p", "n1"))
    cli.run(["label", "nodes", "n1", "zone=a"])
    assert api.store.get("Node", "", "n1").labels["zone"] == "a"
    cli.run(["taint", "nodes", "n1", "dedicated=gpu:NoSchedule"])
    assert api.store.get("Node", "", "n1").taints[0].key == "dedicated"
    cli.run(["taint", "nodes", "n1", "dedicated-"])
    assert api.store.get("Node", "", "n1").taints == []
    cli.run(["cordon", "n1"])
    assert api.store.get("Node", "", "n1").unschedulable
    cli.run(["drain", "n1"])
    assert [p for p in api.store.list("Pod")[0]] == []
    cli.run(["uncordon", "n1"])
    assert not api.store.get("Node", "", "n1").unschedulable


def test_ktctl_scale_top_api_resources():
    api, cli, out = make_cli()
    api.store.create("ReplicaSet", ReplicaSet(
        "rs", "default", replicas=1,
        selector=LabelSelector(match_labels={"a": "b"})))
    cli.run(["scale", "rs", "rs", "--replicas", "4"])
    assert api.store.get("ReplicaSet", "default", "rs").replicas == 4
    api.create("Node", make_node("n1"))
    cli.run(["top", "nodes"])
    assert "n1" in out.getvalue()
    out.truncate(0), out.seek(0)
    cli.run(["api-resources"])
    assert "pods" in out.getvalue() and "nodes" in out.getvalue()


def test_rest_subresource_wrong_method_does_not_fall_through(rest):
    api, client = rest
    from kubernetes_tpu.api.cluster import PodDisruptionBudget
    from kubernetes_tpu.api.types import LabelSelector
    client.create("Pod", make_pod("guarded", labels={"app": "g"}))
    api.store.create("PodDisruptionBudget", PodDisruptionBudget(
        "pdb", "default", min_available=1,
        selector=LabelSelector(match_labels={"app": "g"}),
        disruptions_allowed=0))
    import urllib.request
    req = urllib.request.Request(
        client.base + "/api/v1/namespaces/default/pods/guarded/eviction",
        method="DELETE")
    import urllib.error
    try:
        urllib.request.urlopen(req)
        raise AssertionError("DELETE on eviction subresource succeeded")
    except urllib.error.HTTPError as e:
        assert e.code == 404
    assert api.store.get("Pod", "default", "guarded")  # still there


def test_rest_update_cas_precondition(rest):
    api, client = rest
    client.create("Pod", make_pod("p", labels={"v": "1"}))
    cur = client.get("Pod", "default", "p")
    stale_rv = cur.resource_version
    cur.labels["v"] = "2"
    client.update("Pod", cur)  # bumps rv server-side
    cur.labels["v"] = "3"
    import pytest as _pytest
    from kubernetes_tpu.server.apiserver_lite import Conflict
    with _pytest.raises(Conflict):
        client.update("Pod", cur, expect_rv=stale_rv)


def test_ktctl_bool_flag_then_output_flag():
    api, cli, out = make_cli()
    api.create("Pod", make_pod("a", cpu=10, memory=1 << 20))
    assert cli.run(["get", "pods", "--all-namespaces", "-o", "json"]) == 0
    data = json.loads(out.getvalue())
    assert data[0]["name"] == "a"


def test_ktctl_auth_can_i():
    """auth can-i through the full apiserver's authorizer chain."""
    import io

    from kubernetes_tpu.api.rbac import (
        PolicyRule,
        Role,
        RoleBinding,
        RoleRef,
        Subject,
    )
    from kubernetes_tpu.cli.ktctl import Ktctl
    from kubernetes_tpu.server.apiserver import ApiServer

    srv = ApiServer(auth=False)
    srv.store.create("Role", Role(
        "pod-reader", "default",
        rules=[PolicyRule(verbs=["get", "list"], resources=["pods"])]))
    srv.store.create("RoleBinding", RoleBinding(
        "rb", "default",
        subjects=[Subject(kind="User", name="alice")],
        role_ref=RoleRef(kind="Role", name="pod-reader")))
    out = io.StringIO()
    kt = Ktctl(srv, out=out)
    assert kt.run(["auth", "can-i", "get", "pods", "--as", "alice"]) == 0
    assert kt.run(["auth", "can-i", "delete", "pods", "--as", "alice"]) == 0
    text = out.getvalue().splitlines()
    assert text == ["yes", "no"]


def test_ktctl_expose_and_set_image():
    import io

    from kubernetes_tpu.api.types import LabelSelector, make_pod
    from kubernetes_tpu.api.workloads import Namespace, ReplicaSet
    from kubernetes_tpu.cli.ktctl import Ktctl
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite

    api = ApiServerLite()
    api.create("Namespace", Namespace("default"))
    tmpl = make_pod("", labels={"app": "web"})
    tmpl.containers[0].image = "nginx:1.12"
    api.create("ReplicaSet", ReplicaSet(
        "web", replicas=3,
        selector=LabelSelector(match_labels={"app": "web"}),
        template=tmpl))
    out = io.StringIO()
    kt = Ktctl(api, out=out)
    assert kt.run(["expose", "rs", "web", "--port", "80",
                   "--target-port", "8080"]) == 0
    svc = api.get("Service", "default", "web")
    assert svc.selector == {"app": "web"}
    assert svc.ports[0].port == 80 and svc.ports[0].target_port == 8080
    assert kt.run(["set", "image", "rs", "web", "c0=nginx:1.13"]) == 0
    rs = api.get("ReplicaSet", "default", "web")
    assert rs.template.containers[0].image == "nginx:1.13"


def test_ktctl_get_watch_streams_changes():
    """kubectl get --watch: after the initial table, subsequent writes
    stream as ADDED/MODIFIED/DELETED rows until --watch-timeout."""
    import threading
    import time as _time

    api, kt, out = make_cli()
    api.store.create("Pod", make_pod("p0", cpu=10, memory=1 << 20))

    def mutate():
        _time.sleep(0.15)
        api.store.create("Pod", make_pod("p1", cpu=10, memory=1 << 20))
        _time.sleep(0.1)
        api.store.delete("Pod", "default", "p0")

    t = threading.Thread(target=mutate)
    t.start()
    rc = kt.run(["get", "pods", "--watch", "--watch-timeout", "1"])
    t.join()
    assert rc == 0
    text = out.getvalue()
    assert "ADDED" in text and "p1" in text
    assert "DELETED" in text
    # bad timeout: clean error
    assert kt.run(["get", "pods", "--watch", "--watch-timeout", "x"]) == 1


def test_hyperkube_dispatcher(tmp_path, capsys):
    """cmd/hyperkube analog: one entrypoint, component picked by the
    first argument."""
    from kubernetes_tpu.__main__ import main

    assert main(["version"]) == 0
    assert "v1.7.0-tpu" in capsys.readouterr().out
    assert main([]) == 0  # usage
    assert main(["no-such-thing"]) == 1
    assert main(["apiserver", "--nodes", "3", "--once"]) == 0
    out = capsys.readouterr().out
    assert "listening on http://127.0.0.1:" in out
    assert main(["ktadm", "preflight", "--workdir",
                 str(tmp_path / "c")]) == 0
    assert main(["ktadm", "init", "--workdir", str(tmp_path / "c")]) == 0
    assert main(["ktadm", "reset", "--workdir", str(tmp_path / "c")]) == 0


def test_rollout_pause_resume_freezes_controller():
    """kubectl rollout pause/resume: a paused deployment's rollout
    freezes (the controller skips it) and resumes where it left off."""
    from kubernetes_tpu.api.types import LabelSelector
    from kubernetes_tpu.api.workloads import Deployment
    from kubernetes_tpu.client.informer import SharedInformerFactory
    from kubernetes_tpu.controllers.deployment import DeploymentController

    api, kt, out = make_cli()
    factory = SharedInformerFactory(api.store)
    ctrl = DeploymentController(api.store, factory, record_events=False)
    factory.start()
    dep = Deployment("web", replicas=3,
                     selector=LabelSelector(match_labels={"app": "web"}),
                     template=make_pod("", labels={"app": "web"}, cpu=10))
    api.store.create("Deployment", dep)
    assert kt.run(["rollout", "pause", "deploy", "web"]) == 0
    assert "paused" in out.getvalue()
    factory.step_all()
    ctrl.pump()
    # controller skipped the paused deployment: no child RS created
    assert api.store.list("ReplicaSet")[0] == []
    # pausing twice is an error, like kubectl
    assert kt.run(["rollout", "pause", "deploy", "web"]) == 1
    assert kt.run(["rollout", "resume", "deploy", "web"]) == 0
    factory.step_all()
    ctrl.pump()
    rs = api.store.list("ReplicaSet")[0]
    assert len(rs) == 1 and rs[0].replicas == 3
    # unknown subcommand errors cleanly (restart is a real verb now)
    assert kt.run(["rollout", "bogus", "deploy", "web"]) == 1


def test_describe_shows_events_section():
    """kubectl describe ends with the object's events
    (describe.go DescribeEvents) — recorder events for the object key
    render as an Events: section; objects without events get none."""
    from kubernetes_tpu.client.record import EventRecorder

    api, kt, out = make_cli()
    api.store.create("Pod", make_pod("web", cpu=10, memory=1 << 20))
    rec = EventRecorder(api.store, source="scheduler")
    rec.event("Pod", "default/web", "Warning", "FailedScheduling",
              "0/0 nodes available")
    rec.event("Pod", "default/web", "Warning", "FailedScheduling",
              "0/0 nodes available")  # dedup -> count 2
    assert kt.run(["describe", "pod", "web"]) == 0
    text = out.getvalue()
    assert "Events:" in text and "FailedScheduling" in text
    assert "\t2\t" in text  # correlated count
    out.truncate(0), out.seek(0)
    api.store.create("Pod", make_pod("quiet", cpu=10, memory=1 << 20))
    assert kt.run(["describe", "pod", "quiet"]) == 0
    assert "Events:" not in out.getvalue()


def test_describe_node_shows_cluster_scoped_events():
    """Cluster-scoped objects (Node) key their events by bare name —
    describe must match that convention, not '/name'."""
    from kubernetes_tpu.client.record import EventRecorder

    api, kt, out = make_cli()
    api.store.create("Node", make_node("n1", cpu=1000, memory=1 << 31))
    rec = EventRecorder(api.store, source="nodelifecycle")
    rec.event("Node", "n1", "Warning", "NodeNotReady",
              "Node n1 status is now NotReady")
    assert kt.run(["describe", "node", "n1"]) == 0
    text = out.getvalue()
    assert "Events:" in text and "NodeNotReady" in text


def test_top_pods():
    api, kt, out = make_cli()
    p = make_pod("web", cpu=150, memory=1 << 20)
    p.node_name = "n1"
    p.annotations["bench/actual-mem"] = str(64 << 20)
    api.store.create("Pod", p)
    api.store.create("Pod", make_pod("pending", cpu=10, memory=1 << 20))
    assert kt.run(["top", "pods"]) == 0
    text = out.getvalue()
    assert "web  150m" in text and str(64 << 20) in text
    assert "pending" not in text  # no metrics for unscheduled pods
    assert kt.run(["top", "bogus"]) == 1
