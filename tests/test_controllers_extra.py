"""The second controller wave: quota/serviceaccount/ttl/bootstrap, HPA/PDB/
cronjob, cloud-facing (service LB, routes, PV binder, attach/detach), CSR.

Deterministic pump mode like tests/test_controllers.py; behavioral shape per
the reference's per-controller unit tests (resource_quota_controller_test.go,
serviceaccounts_controller_test.go, horizontal_test.go, disruption_test.go,
cronjob_controller_test.go, servicecontroller_test.go,
routecontroller_test.go, pv_controller_test.go, ttl_controller_test.go)."""

import dataclasses

from kubernetes_tpu.api.cluster import (
    CertificateSigningRequest,
    ResourceQuota,
    Secret,
)
from kubernetes_tpu.api.types import (
    LabelSelector,
    PersistentVolume,
    PersistentVolumeClaim,
    Volume,
    VolumeKind,
    make_node,
    make_pod,
)
from kubernetes_tpu.api.workloads import (
    CronJob,
    HorizontalPodAutoscaler,
    Job,
    Namespace,
    ReplicaSet,
    Service,
)
from kubernetes_tpu.api.cluster import PodDisruptionBudget
from kubernetes_tpu.auth.authn import CertAuthenticator, Credential
from kubernetes_tpu.cloud import AWSLikeCloud, FakeCloud, GCELikeCloud, get_provider
from kubernetes_tpu.controllers.autoscale import StaticMetricsClient, parse_schedule
from kubernetes_tpu.controllers.manager import ControllerManager
from kubernetes_tpu.server.apiserver_lite import ApiServerLite

Mi = 1024 * 1024


def mk_manager(**kw):
    api = ApiServerLite()
    cm = ControllerManager(api, record_events=False, **kw)
    return api, cm


def mk_template(labels):
    return dataclasses.replace(make_pod("", labels=dict(labels), cpu=100),
                               name="")


# -------------------------------------------------------------------- quota

def test_resource_quota_recomputed_from_live_objects():
    api, cm = mk_manager()
    api.create("ResourceQuota", ResourceQuota(
        "q", "default", hard={"pods": 10, "requests.cpu": 10_000}))
    api.create("Pod", make_pod("a", cpu=300, memory=Mi))
    api.create("Pod", make_pod("b", cpu=200, memory=Mi))
    cm.pump_until_stable()
    q = api.get("ResourceQuota", "default", "q")
    assert q.used == {"pods": 2, "requests.cpu": 500}
    api.delete("Pod", "default", "a")
    cm.pump_until_stable()
    q = api.get("ResourceQuota", "default", "q")
    assert q.used == {"pods": 1, "requests.cpu": 200}


# ----------------------------------------------------------- serviceaccount

def test_default_service_account_and_token_created():
    api, cm = mk_manager()
    api.create("Namespace", Namespace("team-a"))
    cm.pump_until_stable()
    sa = api.get("ServiceAccount", "team-a", "default")
    assert "default-token" in sa.secrets
    secret = api.get("Secret", "team-a", "default-token")
    assert secret.type == "kubernetes.io/service-account-token"
    assert secret.data["token"]


# ---------------------------------------------------------------------- ttl

def test_ttl_annotation_follows_cluster_size():
    api, cm = mk_manager()
    for i in range(3):
        api.create("Node", make_node(f"n{i}"))
    cm.pump_until_stable()
    n = api.get("Node", "", "n0")
    assert n.annotations["node.alpha.kubernetes.io/ttl"] == "0"
    ttl = cm.controllers["ttl"]
    assert ttl.desired_ttl(400) == 15
    assert ttl.desired_ttl(1500) == 60
    assert ttl.desired_ttl(9999) == 300


# ---------------------------------------------------------------- bootstrap

def test_bootstrap_signer_and_token_cleaner():
    clock = [100.0]
    api = ApiServerLite()
    cm = ControllerManager(api, record_events=False)
    cm.controllers["tokencleaner"]._now = lambda: clock[0]
    from kubernetes_tpu.api.cluster import ConfigMap

    api.create("ConfigMap", ConfigMap("cluster-info", namespace="kube-public",
                                      data={"kubeconfig": "clusters: []"}))
    api.create("Secret", Secret(
        "bootstrap-token-abc123", namespace="kube-system",
        type="bootstrap.kubernetes.io/token",
        data={"token-id": "abc123", "token-secret": "s3cret",
              "expiration": "200"}))
    cm.pump_until_stable()
    cm.controllers["bootstrapsigner"].enqueue("sign")
    cm.pump_until_stable()
    cmap = api.get("ConfigMap", "kube-public", "cluster-info")
    assert "jws-kubeconfig-abc123" in cmap.data
    # expiry passes -> token cleaned
    clock[0] = 300.0
    cm.controllers["tokencleaner"].enqueue("kube-system/bootstrap-token-abc123")
    cm.pump_until_stable()
    assert all(s.name != "bootstrap-token-abc123"
               for s in api.list("Secret")[0])


# ---------------------------------------------------------------------- hpa

def test_hpa_scales_on_cpu_utilization():
    api, cm = mk_manager()
    metrics = StaticMetricsClient()
    cm.controllers["horizontalpodautoscaling"].metrics = metrics
    api.create("ReplicaSet", ReplicaSet(
        "web", replicas=2, selector=LabelSelector(match_labels={"app": "w"}),
        template=mk_template({"app": "w"})))
    cm.pump_until_stable()
    metrics.default = 200  # 200m used vs 100m requested = 200%
    api.create("HorizontalPodAutoscaler", HorizontalPodAutoscaler(
        "web-hpa", target_kind="ReplicaSet", target_name="web",
        min_replicas=1, max_replicas=10, target_cpu_utilization=100))
    cm.pump_until_stable()
    # scaled 2 -> 4 once; the upscale-forbidden window (horizontal.go)
    # prevents re-scaling against not-yet-converged metrics
    assert api.get("ReplicaSet", "default", "web").replicas == 4
    hpa = api.get("HorizontalPodAutoscaler", "default", "web-hpa")
    assert hpa.current_cpu_utilization == 200
    # inside tolerance after the window -> no change either
    hpa_ctrl = cm.controllers["horizontalpodautoscaling"]
    hpa_ctrl._last_scale.clear()  # simulate the window elapsing
    metrics.default = 105
    hpa_ctrl.resync_all()
    cm.pump_until_stable()
    assert api.get("ReplicaSet", "default", "web").replicas == 4


# --------------------------------------------------------------- disruption

def test_disruption_controller_maintains_pdb_status():
    api, cm = mk_manager()
    for i in range(3):
        p = make_pod(f"w{i}", labels={"app": "w"}, node_name="n1")
        p.phase = "Running"
        api.create("Pod", p)
    api.create("PodDisruptionBudget", PodDisruptionBudget(
        "pdb", min_available=2,
        selector=LabelSelector(match_labels={"app": "w"})))
    cm.pump_until_stable()
    pdb = api.get("PodDisruptionBudget", "default", "pdb")
    assert pdb.current_healthy == 3 and pdb.disruptions_allowed == 1
    api.delete("Pod", "default", "w0")
    cm.pump_until_stable()
    pdb = api.get("PodDisruptionBudget", "default", "pdb")
    assert pdb.current_healthy == 2 and pdb.disruptions_allowed == 0


# ------------------------------------------------------------------ cronjob

def test_parse_schedule_forms():
    assert parse_schedule("@every 90s") == 90
    assert parse_schedule("@every 5m") == 300
    assert parse_schedule("*/10 * * * *") == 600
    assert parse_schedule("0 3 * * *") == 86400


def test_cronjob_spawns_and_respects_forbid():
    clock = [1000.0]
    api = ApiServerLite()
    cm = ControllerManager(api, record_events=False)
    cj_ctrl = cm.controllers["cronjob"]
    cj_ctrl._now = lambda: clock[0]
    api.create("CronJob", CronJob(
        "tick", schedule="@every 60s", concurrency_policy="Forbid",
        job_template=Job(name="", template=mk_template({"cron": "tick"}))))
    cj_ctrl.tick()
    cm.pump_until_stable()
    jobs = [j for j in api.list("Job")[0]]
    assert len(jobs) == 1
    # next window with the first job still active + Forbid -> no new job
    clock[0] += 61
    cj_ctrl.tick()
    cm.pump_until_stable()
    assert len(api.list("Job")[0]) == 1
    # job completes -> next window fires
    j = api.list("Job")[0][0]
    j.complete = True
    api.update("Job", j)
    clock[0] += 61
    cj_ctrl.tick()
    cm.pump_until_stable()
    assert len(api.list("Job")[0]) == 2


# ----------------------------------------------------------------- cloud LB

def test_service_lb_lifecycle_and_providers():
    api, cm = mk_manager()
    cloud = cm.cloud
    api.create("Node", make_node("n1"))
    api.create("Service", Service("web", type="LoadBalancer",
                                  selector={"app": "w"}))
    cm.pump_until_stable()
    svc = api.get("Service", "default", "web")
    assert svc.load_balancer_ip.startswith("172.24.")
    assert cloud.balancer_nodes["default/web"] == ["n1"]
    api.delete("Service", "default", "web")
    cm.pump_until_stable()
    assert "default/web" not in cloud.balancers
    # provider registry + provider-specific surface
    assert isinstance(get_provider("gce-like"), GCELikeCloud)
    aws = AWSLikeCloud()
    st = aws.ensure_load_balancer("default/x", ["n1"])
    assert "elb" in st.ingress_ip


def test_route_controller_syncs_pod_cidrs():
    api, cm = mk_manager()
    n = make_node("n1")
    n.pod_cidr = "10.244.1.0/24"
    api.create("Node", n)
    cm.pump_until_stable()
    routes = cm.cloud.list_routes()
    assert len(routes) == 1 and routes[0].destination_cidr == "10.244.1.0/24"
    api.delete("Node", "", "n1")
    cm.pump_until_stable()
    assert cm.cloud.list_routes() == []


# ---------------------------------------------------------------- pv binder

def test_pv_binder_picks_smallest_fitting_volume():
    api, cm = mk_manager()
    api.create("PersistentVolume", PersistentVolume("big", capacity=100 * Mi))
    api.create("PersistentVolume", PersistentVolume("small", capacity=10 * Mi))
    api.create("PersistentVolumeClaim", PersistentVolumeClaim(
        "claim", capacity=5 * Mi))
    cm.pump_until_stable()
    pvc = api.get("PersistentVolumeClaim", "default", "claim")
    assert pvc.volume_name == "small"
    # second claim too big for the remaining small slots -> big
    api.create("PersistentVolumeClaim", PersistentVolumeClaim(
        "claim2", capacity=50 * Mi))
    cm.pump_until_stable()
    assert api.get("PersistentVolumeClaim", "default",
                   "claim2").volume_name == "big"


def test_attach_detach_records_attachable_volumes():
    api, cm = mk_manager()
    api.create("Node", make_node("n1"))
    pod = make_pod("db", node_name="n1", volumes=[
        Volume(name="data", kind=VolumeKind.AWS_EBS, volume_id="vol-1")])
    api.create("Pod", pod)
    cm.pump_until_stable()
    node = api.get("Node", "", "n1")
    att = node.annotations["volumes.kubernetes.io/attached"]
    assert "vol-1" in att
    api.delete("Pod", "default", "db")
    cm.pump_until_stable()
    node = api.get("Node", "", "n1")
    assert node.annotations["volumes.kubernetes.io/attached"] == ""


# --------------------------------------------------------------------- csr

def test_csr_auto_approved_and_signed_for_kubelet_bootstrap():
    ca = CertAuthenticator(b"test-ca")
    api = ApiServerLite()
    cm = ControllerManager(api, record_events=False, ca=ca)
    api.create("CertificateSigningRequest", CertificateSigningRequest(
        "node-csr-1", requestor="system:bootstrap:abc123",
        groups=["system:bootstrappers"], cn="system:node:n1",
        orgs=["system:nodes"]))
    cm.pump_until_stable()
    csr = api.get("CertificateSigningRequest", "", "node-csr-1")
    assert csr.approved and csr.certificate is not None
    # the issued record authenticates as the node identity
    user = ca.authenticate(Credential(cert=csr.certificate))
    assert user.name == "system:node:n1" and "system:nodes" in user.groups
    # a CSR for someone else's identity is NOT auto-approved
    api.create("CertificateSigningRequest", CertificateSigningRequest(
        "evil", requestor="system:bootstrap:abc123",
        groups=["system:bootstrappers"], cn="system:admin",
        orgs=["system:masters"]))
    cm.pump_until_stable()
    assert not api.get("CertificateSigningRequest", "", "evil").approved
