"""Pallas capacity kernel: bit-identical to the reference jnp path.

The kernel (ops/pallas_kernels.py) runs in interpret mode on the CPU
test backend; every case asserts exact equality against
predicates.resources_fit — including the storage overlay->scratch
fallback (predicates.go:590-604), zero-request override, padding edges
(non-multiple P/N), and randomized sweeps.
"""

import numpy as np
import pytest

from kubernetes_tpu.ops.pallas_kernels import (
    N_BLK,
    P_BLK,
    capacity_fits_pallas,
    resources_fit_fast,
)
from kubernetes_tpu.ops.predicates import resources_fit
from kubernetes_tpu.state.snapshot import R_OVERLAY, R_SCRATCH


def rand_case(rng, p, n, r=6):
    pod_req = rng.integers(0, 1000, size=(p, r), dtype=np.int32)
    zero = rng.random(p) < 0.1
    pod_req[zero] = 0
    zero_req = zero.astype(bool)
    alloc = rng.integers(0, 4000, size=(n, r), dtype=np.int32)
    # some nodes advertise no overlay capacity -> fallback path
    no_overlay = rng.random(n) < 0.4
    alloc[no_overlay, R_OVERLAY] = 0
    requested = (alloc * rng.random((n, r))).astype(np.int32)
    return pod_req, zero_req, alloc, requested


@pytest.mark.parametrize("p,n", [
    (1, 1), (3, 7), (P_BLK, N_BLK), (P_BLK + 1, N_BLK - 1),
    (2 * P_BLK + 17, N_BLK + 129), (5, 3 * N_BLK),
])
def test_kernel_matches_reference_shapes(p, n):
    rng = np.random.default_rng(p * 1000 + n)
    pod_req, zero_req, alloc, requested = rand_case(rng, p, n)
    want = np.asarray(resources_fit(pod_req, zero_req, alloc, requested))
    got = np.asarray(capacity_fits_pallas(pod_req, alloc, requested,
                                          interpret=True))
    got = got | zero_req[:, None]
    assert got.shape == want.shape
    np.testing.assert_array_equal(got, want)


def test_kernel_fuzz_sweep():
    rng = np.random.default_rng(7)
    for trial in range(10):
        p = int(rng.integers(1, 300))
        n = int(rng.integers(1, 600))
        r = int(rng.integers(R_OVERLAY + 1, 12))
        pod_req, zero_req, alloc, requested = rand_case(rng, p, n, r)
        want = np.asarray(resources_fit(pod_req, zero_req, alloc,
                                        requested))
        got = np.asarray(resources_fit_fast(
            pod_req, zero_req, alloc, requested, force=True,
            interpret=True))
        np.testing.assert_array_equal(got, want, err_msg=f"trial {trial}")


def test_overlay_fallback_exact():
    # hand case: overlay demand must spill onto scratch when the node has
    # no overlay capacity, and count against overlay capacity when it does
    r = max(R_SCRATCH, R_OVERLAY) + 1
    pod_req = np.zeros((1, r), dtype=np.int32)
    pod_req[0, R_OVERLAY] = 10
    zero_req = np.zeros(1, dtype=bool)
    alloc = np.zeros((2, r), dtype=np.int32)
    alloc[:, R_SCRATCH] = 5      # scratch cap 5 on both
    alloc[1, R_OVERLAY] = 100    # node 1 has real overlay capacity
    requested = np.zeros((2, r), dtype=np.int32)
    want = np.asarray(resources_fit(pod_req, zero_req, alloc, requested))
    got = np.asarray(resources_fit_fast(pod_req, zero_req, alloc,
                                        requested, force=True,
                                        interpret=True))
    np.testing.assert_array_equal(got, want)
    # semantics: node 0 (no overlay) must reject (10 > scratch 5);
    # node 1 (overlay cap 100) must accept
    assert got[0, 0] == False and got[0, 1] == True  # noqa: E712


def test_dispatcher_falls_back_off_tpu():
    # on the CPU test backend the dispatcher must take the jnp path
    # (no interpret flag) and still match
    rng = np.random.default_rng(3)
    pod_req, zero_req, alloc, requested = rand_case(rng, 200, 300)
    want = np.asarray(resources_fit(pod_req, zero_req, alloc, requested))
    got = np.asarray(resources_fit_fast(pod_req, zero_req, alloc,
                                        requested))
    np.testing.assert_array_equal(got, want)


# ----------------------------------------- topology-incidence matmul


def _affinity_arrays(rng, c=3, s=4, l=600, n=700):
    """Random affinity static arrays shaped like AffinityData's."""
    import jax.numpy as jnp
    aff = {
        "aff_allow": jnp.asarray(
            rng.integers(0, 2, size=(c, s, l)).astype(np.int32)),
        "forbid_static": jnp.asarray(
            rng.integers(0, 2, size=(c, l)).astype(np.int32)),
        "prio_static": jnp.asarray(
            rng.integers(-5, 9, size=(c, l)).astype(np.int32)),
    }
    labels = jnp.asarray(rng.integers(0, 2, size=(n, l)).astype(np.int8))
    return aff, labels


def test_incidence_matmul_interpret_parity():
    import jax.numpy as jnp
    from kubernetes_tpu.ops.pallas_kernels import incidence_matmul_pallas
    rng = np.random.default_rng(11)
    for m, l, n in [(5, 17, 9), (130, 600, 300), (128, 512, 256)]:
        a = rng.integers(-3, 7, size=(m, l)).astype(np.int32)
        b_t = rng.integers(0, 2, size=(n, l)).astype(np.int32)
        want = a @ b_t.T
        got = np.asarray(incidence_matmul_pallas(
            jnp.asarray(a), jnp.asarray(b_t), interpret=True))
        np.testing.assert_array_equal(got, want, err_msg=f"{m},{l},{n}")


def test_precompute_static_fast_parity():
    """Interpret-mode kernel output vs the reference jnp einsums, over
    random incidence structures (r4 VERDICT weak #2's asked-for case)."""
    from kubernetes_tpu.ops.affinity import precompute_static
    from kubernetes_tpu.ops.pallas_kernels import precompute_static_fast
    rng = np.random.default_rng(5)
    for trial in range(4):
        aff, labels = _affinity_arrays(
            rng, c=int(rng.integers(1, 6)), s=int(rng.integers(1, 7)),
            l=int(rng.integers(40, 900)), n=int(rng.integers(50, 800)))
        want = precompute_static(aff, labels)
        got = precompute_static_fast(aff, labels, force=True,
                                     interpret=True)
        for k in ("allow_hit", "forbid_hit", "prio_counts"):
            np.testing.assert_array_equal(
                np.asarray(got[k]), np.asarray(want[k]),
                err_msg=f"trial {trial} key {k}")


def test_precompute_static_fast_falls_back_off_tpu():
    from kubernetes_tpu.ops.affinity import precompute_static
    from kubernetes_tpu.ops.pallas_kernels import precompute_static_fast
    rng = np.random.default_rng(6)
    aff, labels = _affinity_arrays(rng)
    want = precompute_static(aff, labels)
    got = precompute_static_fast(aff, labels)  # CPU backend: jnp path
    for k in ("allow_hit", "forbid_hit", "prio_counts"):
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(want[k]))
