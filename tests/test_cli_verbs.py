"""Round-5 CLI verbs: attach, port-forward, rollout restart
(cli/ktctl.py; ref pkg/kubectl/cmd/{attach,portforward,rollout_restart}.go,
kubelet legs in nodes/kubelet_server.py)."""

import io
import socket

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.api.workloads import Namespace
from kubernetes_tpu.cli.ktctl import Ktctl
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.nodes.kubelet import HollowFleet
from kubernetes_tpu.nodes.kubelet_server import KubeletServer
from kubernetes_tpu.server.apiserver import ApiServer


def mk_cluster():
    api = ApiServer()
    api.store.create("Namespace", Namespace("default"))
    factory = SharedInformerFactory(api.store)
    fleet = HollowFleet(api.store, factory)
    fleet.add_node(make_node("n0"))
    factory.step_all()
    out = io.StringIO()
    kt = Ktctl(api, out=out, kubelets=dict(fleet.kubelets))
    return api, factory, fleet, kt, out


def run_pod(api, factory, fleet, name="web", annotations=None):
    pod = make_pod(name, cpu=100, node_name="n0")
    pod.annotations.update(annotations or {})
    api.store.create("Pod", pod)
    factory.step_all()
    fleet.step()
    assert api.store.get("Pod", "default", name).phase == "Running"
    return pod


def test_attach_streams_running_container():
    api, factory, fleet, kt, out = mk_cluster()
    run_pod(api, factory, fleet,
            annotations={"bench/log-lines": "line1\nline2"})
    assert kt.run(["attach", "web"]) == 0
    assert out.getvalue().strip().endswith("line2")
    # attaching to a pod that is not running errors (unlike logs)
    assert kt.run(["attach", "ghost"]) != 0


def test_attach_over_http_kubelet():
    api, factory, fleet, kt, out = mk_cluster()
    run_pod(api, factory, fleet,
            annotations={"bench/log-lines": "hello"})
    srv = KubeletServer(fleet.kubelets["n0"])
    srv.start()
    try:
        kt.kubelets = {"n0": f"http://127.0.0.1:{srv.port}"}
        assert kt.run(["attach", "web"]) == 0
        assert "hello" in out.getvalue()
    finally:
        srv.stop()


def test_port_forward_round_trip():
    api, factory, fleet, kt, out = mk_cluster()
    run_pod(api, factory, fleet,
            annotations={"bench/port-80": "HTTP/1.0 200 OK\r\n\r\nhome"})
    assert kt.run(["port-forward", "web", "0:80"]) == 0
    fwd = kt.port_forwards[-1]
    try:
        assert f"127.0.0.1:{fwd.local_port}" in out.getvalue()
        # a REAL tcp connection to the forwarded port gets the pod's bytes
        with socket.create_connection(("127.0.0.1", fwd.local_port),
                                      timeout=5) as conn:
            data = b""
            while True:
                chunk = conn.recv(4096)
                if not chunk:
                    break
                data += chunk
        assert data.endswith(b"home")
    finally:
        fwd.stop()


def test_port_forward_rejects_unserved_port():
    api, factory, fleet, kt, out = mk_cluster()
    run_pod(api, factory, fleet)
    assert kt.run(["port-forward", "web", "0:9999"]) != 0
    assert "9999" in out.getvalue()


def test_rollout_restart_stamps_template():
    from kubernetes_tpu.api.types import LabelSelector, Pod
    from kubernetes_tpu.api.workloads import Deployment
    api, factory, fleet, kt, out = mk_cluster()
    api.store.create("Deployment", Deployment(
        name="app", replicas=1,
        selector=LabelSelector(match_labels={"app": "app"}),
        template=Pod(name="", labels={"app": "app"})))
    assert kt.run(["rollout", "restart", "deploy", "app"]) == 0
    dep = api.store.get("Deployment", "default", "app")
    assert "kubectl.kubernetes.io/restartedAt" in dep.template.annotations
    assert "restarted" in out.getvalue()
    # a second restart moves the stamp (a fresh rollout each time)
    first = dep.template.annotations["kubectl.kubernetes.io/restartedAt"]
    import time
    time.sleep(0.01)
    assert kt.run(["rollout", "restart", "deploy", "app"]) == 0
    second = api.store.get("Deployment", "default", "app") \
        .template.annotations["kubectl.kubernetes.io/restartedAt"]
    assert second != first


def test_explain_reads_live_openapi():
    api, factory, fleet, kt, out = mk_cluster()
    assert kt.run(["explain", "pod"]) == 0
    text = out.getvalue()
    assert "KIND:     Pod" in text
    assert "containers" in text
    out.truncate(0), out.seek(0)
    assert kt.run(["explain", "pod.containers"]) == 0
    assert "image" in out.getvalue()
    assert kt.run(["explain", "pod.nosuchfield"]) != 0


def test_run_creates_pod_or_deployment():
    api, factory, fleet, kt, out = mk_cluster()
    assert kt.run(["run", "one", "--image", "app:v1"]) == 0
    assert api.store.get("Pod", "default", "one") \
        .containers[0].image == "app:v1"
    assert kt.run(["run", "many", "--image", "app:v1",
                   "--replicas", "3"]) == 0
    dep = api.store.get("Deployment", "default", "many")
    assert dep.replicas == 3
    assert dep.template.containers[0].image == "app:v1"


def test_autoscale_creates_hpa():
    from kubernetes_tpu.api.types import LabelSelector, Pod
    from kubernetes_tpu.api.workloads import Deployment
    api, factory, fleet, kt, out = mk_cluster()
    api.store.create("Deployment", Deployment(
        name="web", replicas=2,
        selector=LabelSelector(match_labels={"app": "web"}),
        template=Pod(name="", labels={"app": "web"})))
    assert kt.run(["autoscale", "deploy", "web", "--min", "2",
                   "--max", "8", "--cpu-percent", "70"]) == 0
    hpa = api.store.get("HorizontalPodAutoscaler", "default", "web")
    assert hpa.min_replicas == 2 and hpa.max_replicas == 8
    assert hpa.target_cpu_utilization == 70
    assert hpa.target_kind == "Deployment"
    # target must exist, like kubectl
    assert kt.run(["autoscale", "deploy", "ghost", "--max", "4"]) != 0


def test_explain_against_remote_backend_sees_crds():
    """explain over a RestClient backend must read the server-published
    /openapi/v2, so Established CRDs are explainable remotely."""
    from kubernetes_tpu.api.extensions import (
        CRDNames,
        CustomResourceDefinition,
    )
    from kubernetes_tpu.cli.rest_client import RestClient
    from kubernetes_tpu.server.rest_http import RestServer
    api = ApiServer()
    api.store.create("Namespace", Namespace("default"))
    # through the apiserver verb so the CRD is named + Established (the
    # establishing controller work runs at admission; a bare store write
    # would never surface in discovery)
    api.create("CustomResourceDefinition", CustomResourceDefinition(
        name="widgets.example.com", group="example.com", version="v1",
        names=CRDNames(plural="widgets", kind="Widget",
                       singular="widget")))
    srv = RestServer(api)
    srv.start()
    try:
        out = io.StringIO()
        kt = Ktctl(RestClient(f"http://127.0.0.1:{srv.port}"), out=out)
        assert kt.run(["explain", "widgets"]) == 0
        assert "KIND:     Widget" in out.getvalue()
    finally:
        srv.stop()


def test_autoscale_rejects_min_above_max():
    from kubernetes_tpu.api.types import LabelSelector, Pod
    from kubernetes_tpu.api.workloads import Deployment
    api, factory, fleet, kt, out = mk_cluster()
    api.store.create("Deployment", Deployment(
        name="web", replicas=2,
        selector=LabelSelector(match_labels={"app": "web"}),
        template=Pod(name="", labels={"app": "web"})))
    assert kt.run(["autoscale", "deploy", "web", "--min", "9",
                   "--max", "4"]) != 0
    assert "must be at least 1" in out.getvalue()
