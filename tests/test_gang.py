"""Gang (coscheduling) placement: quorum gating, atomicity, rollback.

BASELINE.json config 4 — new capability vs the reference's sequential
one-pod loop. The hard invariant is all-or-nothing: a gang that cannot
fully place leaves ZERO residue (no assumed pods, no partial binds), the
failure mode gang scheduling exists to prevent."""

from __future__ import annotations

import dataclasses

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.engine.gang import (
    GANG_MIN_AVAILABLE_ANNOTATION,
    GANG_NAME_ANNOTATION,
)
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.models.hollow import gang_pods
from kubernetes_tpu.server.apiserver_lite import ApiServerLite

Mi = 1 << 20
Gi = 1 << 30


def _gang_pod(name, gang, quorum, cpu=100):
    p = make_pod(name, cpu=cpu, memory=64 * Mi)
    p.annotations[GANG_NAME_ANNOTATION] = gang
    p.annotations[GANG_MIN_AVAILABLE_ANNOTATION] = str(quorum)
    return p


def _rig(n_nodes=4, cpu=1000):
    api = ApiServerLite()
    for i in range(n_nodes):
        api.create("Node", make_node(f"n{i}", cpu=cpu, memory=8 * Gi))
    sched = Scheduler(api, record_events=False)
    sched.start()
    return api, sched


def test_gang_schedules_atomically_when_it_fits():
    api, sched = _rig()
    for i in range(6):
        api.create("Pod", _gang_pod(f"g-{i}", "job-a", 6))
    totals = sched.run_until_drained()
    assert totals["bound"] == 6
    pods, _ = api.list("Pod")
    assert all(p.node_name for p in pods)


def test_gang_waits_for_quorum():
    api, sched = _rig()
    for i in range(3):
        api.create("Pod", _gang_pod(f"g-{i}", "job-a", 6))
    sched.run_until_drained()
    pods, _ = api.list("Pod")
    assert all(not p.node_name for p in pods), "below quorum: nothing binds"
    assert "job-a" in sched._gang_waiting
    # the remaining members arrive -> the whole gang goes
    for i in range(3, 6):
        api.create("Pod", _gang_pod(f"g-{i}", "job-a", 6))
    totals = sched.run_until_drained()
    assert totals["bound"] == 6
    assert "job-a" not in sched._gang_waiting


def test_infeasible_gang_leaves_zero_residue():
    """One member can never fit and quorum is the full gang -> no member
    binds AND no member stays assumed in the cache (capacity released)."""
    api, sched = _rig(n_nodes=4, cpu=1000)
    for i in range(4):
        api.create("Pod", _gang_pod(f"g-{i}", "job-x", 5))
    api.create("Pod", _gang_pod("g-huge", "job-x", 5, cpu=50_000))
    totals = sched.run_until_drained()
    assert totals["bound"] == 0
    assert totals["unschedulable"] >= 5
    pods, _ = api.list("Pod")
    assert all(not p.node_name for p in pods)
    # zero residue: every node's accounted capacity is untouched
    for info in sched.cache.node_infos().values():
        assert info.requested.milli_cpu == 0
        assert not info.pods


def test_partial_fit_gang_rolls_back():
    """The gang fits individually but not jointly (aggregate capacity
    passes the precheck; per-node packing fails) -> rollback, zero
    residue."""
    api, sched = _rig(n_nodes=2, cpu=1000)
    # 2 nodes x 1000m; gang of 3 pods x 700m: any 2 fit, 3 cannot
    # (aggregate 2100m > 2000m free is caught by the precheck, so use
    # 3 x 650m = 1950m < 2000m aggregate but only 1 fits per node)
    for i in range(3):
        api.create("Pod", _gang_pod(f"g-{i}", "job-p", 3, cpu=650))
    totals = sched.run_until_drained()
    assert totals["bound"] == 0
    pods, _ = api.list("Pod")
    assert all(not p.node_name for p in pods)
    for info in sched.cache.node_infos().values():
        assert info.requested.milli_cpu == 0


def test_quorum_commit_with_stragglers_retrying_solo():
    """Coscheduling PodGroup semantics: the gang commits when minAvailable
    members place; extras past quorum retry individually (the gang is past
    its atomicity point and marked degraded)."""
    api, sched = _rig(n_nodes=2, cpu=1000)
    # 3 members @650m, quorum 2: one fits per node -> 2 place, 1 straggles
    for i in range(3):
        api.create("Pod", _gang_pod(f"g-{i}", "job-q", 2, cpu=650))
    totals = sched.run_until_drained()
    assert totals["bound"] == 2
    pods, _ = api.list("Pod")
    assert sum(1 for p in pods if p.node_name) == 2
    assert "job-q" in sched._gang_degraded
    # capacity frees up -> the straggler schedules SOLO (no quorum gate)
    bound = [p for p in pods if p.node_name]
    api.delete("Pod", bound[0].namespace, bound[0].name)
    for _ in range(200):
        sched.schedule_round()
        if all(p.node_name for p in api.list("Pod")[0]):
            break
        sched._now()  # real clock: waits out the 1s backoff
        import time as _t
        _t.sleep(0.05)
    assert sum(1 for p in api.list("Pod")[0] if p.node_name) == 2


def test_gangs_mix_with_plain_pods():
    api, sched = _rig(n_nodes=4, cpu=4000)
    for i in range(4):
        api.create("Pod", _gang_pod(f"g-{i}", "job-m", 4))
    for i in range(8):
        api.create("Pod", make_pod(f"plain-{i}", cpu=100))
    totals = sched.run_until_drained()
    assert totals["bound"] == 12


def test_gang_bench_profile_places_feasible_gangs_only():
    """The gang storm profile: every feasible gang fully binds, every
    infeasible gang (the ~1/16 with an impossible member) fully stays
    pending — atomicity at storm scale."""
    api = ApiServerLite()
    for i in range(50):
        api.create("Node", make_node(f"node-{i:03d}", cpu=16_000,
                                     memory=64 * Gi))
    pods = gang_pods(32 * 8)  # 32 gangs of 8; gangs 15 and 31 infeasible
    for p in pods:
        api.create("Pod", p)
    sched = Scheduler(api, record_events=False)
    sched.start()
    totals = sched.run_until_drained()
    by_gang = {}
    for p in api.list("Pod")[0]:
        by_gang.setdefault(
            p.annotations[GANG_NAME_ANNOTATION], []).append(bool(p.node_name))
    assert len(by_gang) == 32
    for gname, bound_flags in by_gang.items():
        assert len(set(bound_flags)) == 1, f"{gname} partially bound"
    placed = sum(1 for flags in by_gang.values() if flags[0])
    assert placed == 30  # all but the two infeasible gangs
    assert totals["bound"] == 30 * 8


def test_gang_park_timeout_fires_on_empty_rounds():
    """A below-quorum gang with NO new pod arrivals must still hit the
    parked-too-long sweep (the sweep runs before the empty-round early
    return): FailedScheduling surfaces and members re-queue with backoff."""
    t = [1000.0]
    api = ApiServerLite()
    api.create("Node", make_node("n1", cpu=4000, memory=8 * Gi))
    sched = Scheduler(api, now=lambda: t[0])
    sched.start()
    api.create("Pod", _gang_pod("g-a", "g", 3))
    sched.schedule_round()           # parks below quorum
    assert sched._gang_waiting.get("g")
    t[0] += sched.GANG_WAIT_TIMEOUT_S + 1
    sched.schedule_round()           # EMPTY round: nothing in the queue
    assert not sched._gang_waiting.get("g")
    evs = [e for e in sched.events
           if e.reason == "FailedScheduling" and "below quorum" in e.message]
    assert evs


def test_gang_completing_in_timeout_round_schedules():
    """A gang whose final quorum member arrives in the same round the park
    timeout expires must schedule, not be swept into backoff."""
    t = [1000.0]
    api = ApiServerLite()
    for i in range(3):
        api.create("Node", make_node(f"n{i}", cpu=4000, memory=8 * Gi))
    sched = Scheduler(api, now=lambda: t[0])
    sched.start()
    api.create("Pod", _gang_pod("g-a", "g", 2))
    sched.schedule_round()           # parks 1/2
    assert sched._gang_waiting.get("g")
    t[0] += sched.GANG_WAIT_TIMEOUT_S + 1
    api.create("Pod", _gang_pod("g-b", "g", 2))
    sched.schedule_round()           # completion + timeout in one round
    bound = [p for p in api.list("Pod")[0] if p.node_name]
    assert len(bound) == 2, [p.name for p in bound]
    assert not any(e.reason == "FailedScheduling" and "below quorum"
                   in e.message for e in sched.events)


def test_gang_pipelined_vs_classic_flush_ab():
    """ISSUE 5 A/B: the same gang storm drained with gangs riding the
    pipelined wave path (default) and in FLUSH mode (gang_pipeline=False
    — every gang-bearing chunk drains the pipeline into the classic
    synchronous round, the pre-ISSUE 5 routing). Placements may differ
    (wave tie-breaks vs classic order) but the gang CONTRACT must agree:
    the same gangs fully bind, zero partial gangs, zero residue for the
    losers — and the pipelined run must actually dispatch gangs through
    waves, never flushing."""
    from kubernetes_tpu.utils.trace import COUNTERS

    def drain(gang_pipeline):
        api = ApiServerLite()
        for i in range(50):
            api.create("Node", make_node(f"node-{i:03d}", cpu=16_000,
                                         memory=64 * Gi))
        for p in gang_pods(32 * 8):  # gangs 15 and 31 infeasible
            api.create("Pod", p)
        sched = Scheduler(api, record_events=False)
        sched.gang_pipeline = gang_pipeline
        sched.start()
        COUNTERS.reset()
        totals = sched.run_until_drained(max_batch=64)
        snap = COUNTERS.snapshot()
        by_gang = {}
        for p in api.list("Pod")[0]:
            by_gang.setdefault(p.annotations[GANG_NAME_ANNOTATION],
                               []).append(bool(p.node_name))
        return totals, by_gang, snap, sched

    tot_p, gangs_p, snap_p, sched_p = drain(True)
    tot_c, gangs_c, snap_c, _ = drain(False)
    for by_gang in (gangs_p, gangs_c):
        for gname, flags in by_gang.items():
            assert len(set(flags)) == 1, f"{gname} partially bound"
    bound_p = {g for g, f in gangs_p.items() if f[0]}
    bound_c = {g for g, f in gangs_c.items() if f[0]}
    assert bound_p == bound_c == {f"job-{g:04d}" for g in range(32)
                                  if g % 16 != 15}
    assert tot_p["bound"] == tot_c["bound"] == 30 * 8
    # the pipelined run really took the wave path; flush mode never did
    assert snap_p.get("engine.gang_wave_dispatch", (0, 0))[0] >= 30, snap_p
    assert snap_c.get("engine.gang_wave_dispatch", (0, 0))[0] == 0, snap_c
    # zero residue for the infeasible gangs: assumed capacity all released
    used = sum(i.requested.milli_cpu
               for i in sched_p.cache.node_infos().values())
    assert used == 30 * 8 * 100, used


def test_gang_pipelined_overlap_ab_bit_identical():
    """ISSUE 5 acceptance: the gang-bearing pipelined drain with overlap
    forced off (sequential debug mode) is BIT-IDENTICAL — the gang fence,
    not timing, decides every commit and rollback."""
    def drain(overlap):
        api = ApiServerLite()
        for i in range(8):
            api.create("Node", make_node(f"n{i}", cpu=2000, memory=8 * Gi))
        for g in range(4):
            for m in range(4):
                api.create("Pod", _gang_pod(f"g{g}-{m}", f"job-{g}", 4,
                                            cpu=450))
        for i in range(6):
            api.create("Pod", make_pod(f"plain-{i}", cpu=300,
                                       memory=64 * Mi))
        sched = Scheduler(api, record_events=False)
        sched.start()
        sched.run_until_drained(max_batch=5, overlap=overlap)
        return {p.name: (p.node_name or None) for p in api.list("Pod")[0]}

    assert drain(True) == drain(False)


def test_gang_straggler_released_when_quorum_commits_in_flight():
    """A straggler that pops while its gang's quorum is still IN FLIGHT is
    gated before the commit lands, so it parks below quorum; the harvest
    must release it to schedule solo as soon as the gang commits — not
    strand it until the 60s parked-gang sweep (the classic round marks
    degraded synchronously and never hits this window)."""
    api = ApiServerLite()
    for i in range(4):
        api.create("Node", make_node(f"n{i}", cpu=4000, memory=8 * Gi))
    for i in range(2):           # the quorum pair pops as chunk 1
        api.create("Pod", _gang_pod(f"q-{i}", "job-s", 2))
    api.create("Pod", _gang_pod("q-late", "job-s", 2))  # chunk 2, in-flight
    sched = Scheduler(api, record_events=False)
    sched.start()
    totals = sched.run_until_drained(max_batch=2)
    assert totals["bound"] == 3, totals
    assert "job-s" in sched._gang_degraded
    assert not sched._gang_waiting.get("job-s")
    assert all(p.node_name for p in api.list("Pod")[0])


def test_gang_fence_rollback_is_atomic_with_zero_residue():
    """Forced fence rollback (ISSUE 5): gang B's wave is dispatched BLIND
    to gang A's still-unharvested commits on the only node; at harvest,
    B's members fail the capacity re-validation, so the WHOLE gang rolls
    back atomically — nothing of B is ever assumed, zero residue — and
    requeues with backoff. A binds untouched."""
    from kubernetes_tpu.utils.trace import COUNTERS

    api = ApiServerLite()
    api.create("Node", make_node("n0", cpu=2000, memory=8 * Gi))
    for i in range(2):
        api.create("Pod", _gang_pod(f"a-{i}", "job-a", 2, cpu=1000))
    for i in range(2):
        api.create("Pod", _gang_pod(f"b-{i}", "job-b", 2, cpu=1000))
    sched = Scheduler(api, record_events=True)
    sched.start()
    COUNTERS.reset()
    totals = sched.run_until_drained(max_batch=2)
    snap = COUNTERS.snapshot()
    assert totals["bound"] == 2, totals
    assert totals["gang_requeued"] >= 2, totals  # B rolled back as a unit
    assert snap.get("engine.gang_fence_rollbacks", (0, 0))[0] >= 1, snap
    pods = api.list("Pod")[0]
    by_gang = {}
    for p in pods:
        by_gang.setdefault(p.annotations[GANG_NAME_ANNOTATION],
                           []).append(bool(p.node_name))
    assert len(set(by_gang["job-a"])) == 1  # never partial
    assert len(set(by_gang["job-b"])) == 1
    bound_gangs = [g for g, f in by_gang.items() if f[0]]
    assert len(bound_gangs) == 1, by_gang  # exactly one gang won the node
    # zero residue: only the winner's capacity is accounted
    info = sched.cache.node_infos()["n0"]
    assert info.requested.milli_cpu == 2000, info.requested
    assert len(info.pods) == 2
    evs = [e for e in sched.events
           if e.reason == "FailedScheduling" and "wave fence" in e.message]
    assert evs, [e.message for e in sched.events]


def test_gang_fuzz_all_or_nothing_invariant():
    """Randomized gang mixes; the hard invariant per trial: every gang is
    either FULLY placed (>= quorum members bound) or left with ZERO
    residue (no member bound, no assumed capacity leaked) — the partial-
    placement failure mode gang scheduling exists to prevent."""
    import numpy as np

    rng = np.random.default_rng(1234)
    for trial in range(8):
        n_nodes = int(rng.integers(2, 6))
        node_cpu = 1000
        api = ApiServerLite()
        for i in range(n_nodes):
            api.create("Node", make_node(f"n{i}", cpu=node_cpu,
                                         memory=8 * Gi))
        sched = Scheduler(api)
        sched.start()
        gangs = {}
        for g in range(int(rng.integers(1, 4))):
            size = int(rng.integers(1, 5))
            quorum = int(rng.integers(1, size + 1))
            cpu = int(rng.integers(100, 700))
            gangs[f"g{g}"] = (size, quorum, cpu)
            for m in range(size):
                api.create("Pod", _gang_pod(f"g{g}-{m}", f"g{g}", quorum,
                                            cpu=cpu))
        # a few plain pods competing for the same capacity
        for p in range(int(rng.integers(0, 4))):
            api.create("Pod", make_pod(f"plain-{p}",
                                       cpu=int(rng.integers(50, 400)),
                                       memory=64 * Mi))
        sched.run_until_drained(max_rounds=50)
        pods = api.list("Pod")[0]
        for gname, (size, quorum, cpu) in gangs.items():
            bound = [p for p in pods
                     if p.name.startswith(gname + "-") and p.node_name]
            assert len(bound) == 0 or len(bound) >= quorum, \
                f"trial {trial}: gang {gname} partially placed " \
                f"({len(bound)}/{size}, quorum {quorum})"
        # no node over capacity (assumed-capacity leak check)
        per_node = {}
        for p in pods:
            if p.node_name:
                per_node[p.node_name] = per_node.get(p.node_name, 0) \
                    + p.resource_request().milli_cpu
        for node_name, used in per_node.items():
            assert used <= node_cpu, \
                f"trial {trial}: {node_name} over capacity ({used})"
