"""Federation (L9): planner semantics, federated-RS sync, cluster loss,
kubefed-style CLI verbs.

Reference targets: the replica planner
(federation/pkg/federation-controller/util/planner/planner.go), the
federated ReplicaSet type adapter + scheduling
(federation/pkg/federatedtypes/{replicaset,scheduling}.go), and kubefed
join/unjoin. Two in-process member clusters each run a real
ReplicaSetController + Scheduler, so a federated workload ends as bound
pods in both — and re-balances when a cluster dies (VERDICT r3 #8:
10 replicas spread 5/5, re-balanced on cluster loss)."""

from __future__ import annotations

import io
import json

import pytest

from kubernetes_tpu.api.types import LabelSelector, make_node, make_pod
from kubernetes_tpu.api.workloads import ReplicaSet
from kubernetes_tpu.cli.ktctl import Ktctl
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.replicaset import ReplicaSetController
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.federation.controller import (
    FEDERATED_RS_KIND,
    FederatedReplicaSet,
    FederatedReplicaSetController,
    FederationControlPlane,
)
from kubernetes_tpu.federation.planner import (
    PREFERENCES_ANNOTATION,
    ClusterPreferences,
    Planner,
    ReplicaAllocationPreferences,
)
from kubernetes_tpu.server.apiserver_lite import ApiServerLite

Gi = 1 << 30


# ------------------------------------------------------------------ planner


def _prefs(rebalance=False, **clusters):
    return ReplicaAllocationPreferences(
        rebalance=rebalance,
        clusters={k: v for k, v in clusters.items()})


def test_planner_even_split():
    plan, overflow = Planner(_prefs(
        **{"*": ClusterPreferences(weight=1)})).plan(
            10, ["a", "b"], key="default/web")
    assert plan == {"a": 5, "b": 5}
    assert overflow == {}


def test_planner_weighted():
    plan, _ = Planner(_prefs(
        a=ClusterPreferences(weight=3),
        b=ClusterPreferences(weight=1))).plan(8, ["a", "b"])
    assert plan == {"a": 6, "b": 2}


def test_planner_min_replicas_take_priority():
    plan, _ = Planner(_prefs(
        a=ClusterPreferences(min_replicas=4, weight=0),
        b=ClusterPreferences(weight=1))).plan(6, ["a", "b"])
    assert plan == {"a": 4, "b": 2}


def test_planner_max_replicas_cap():
    plan, _ = Planner(_prefs(
        a=ClusterPreferences(weight=1, max_replicas=2),
        b=ClusterPreferences(weight=1))).plan(10, ["a", "b"])
    assert plan == {"a": 2, "b": 8}


def test_planner_capacity_overflow():
    plan, overflow = Planner(_prefs(
        rebalance=True, **{"*": ClusterPreferences(weight=1)})).plan(
            10, ["a", "b"], capacity={"a": 2})
    assert plan == {"a": 2, "b": 8}
    assert overflow.get("a", 0) >= 1  # a wanted more than its capacity


def test_planner_no_rebalance_keeps_current_layout():
    """rebalance=false: cluster b keeps its 7 even though an even split
    would say 5/5 (the anti-thrash preallocation, planner.go:116-140)."""
    plan, _ = Planner(_prefs(
        **{"*": ClusterPreferences(weight=1)})).plan(
            10, ["a", "b"], current={"b": 7})
    assert plan == {"a": 3, "b": 7}


def test_planner_unlisted_cluster_without_wildcard_gets_zero():
    plan, _ = Planner(_prefs(a=ClusterPreferences(weight=1))).plan(
        5, ["a", "b"])
    assert plan == {"a": 5, "b": 0}


def test_planner_preferences_json_wire_format():
    p = ReplicaAllocationPreferences.parse(json.dumps({
        "rebalance": True,
        "clusters": {"*": {"weight": 2, "minReplicas": 1,
                           "maxReplicas": 9}}}))
    assert p.rebalance is True
    assert p.clusters["*"] == ClusterPreferences(1, 9, 2)


# --------------------------------------------------------- two-cluster rig


class _MemberCluster:
    """A real member: apiserver + RS controller + scheduler."""

    def __init__(self, name: str, n_nodes: int = 4):
        self.name = name
        self.api = ApiServerLite()
        for i in range(n_nodes):
            self.api.create("Node", make_node(f"{name}-node-{i}",
                                              cpu=8000, memory=16 * Gi))
        self.factory = SharedInformerFactory(self.api)
        self.rsc = ReplicaSetController(self.api, self.factory,
                                        record_events=False)
        self.sched = Scheduler(self.api, record_events=False)
        self.sched.start()

    def reconcile(self):
        self.factory.step_all()
        self.rsc.pump()
        self.sched.run_until_drained()

    def bound_pods(self):
        return [p for p in self.api.list("Pod")[0]
                if p.node_name and not p.deleted]


def _federated_rig():
    plane = FederationControlPlane()
    a, b = _MemberCluster("alpha"), _MemberCluster("beta")
    plane.join("alpha", a.api)
    plane.join("beta", b.api)
    ctrl = FederatedReplicaSetController(plane)
    return plane, ctrl, a, b


def _mk_frs(replicas=10, prefs=None):
    tmpl = ReplicaSet(
        name="web", selector=LabelSelector(match_labels={"app": "web"}),
        template=make_pod("", cpu=100, labels={"app": "web"}))
    frs = FederatedReplicaSet(name="web", replicas=replicas, template=tmpl)
    if prefs:
        frs.annotations[PREFERENCES_ANNOTATION] = prefs
    return frs


def test_federated_rs_spreads_5_5_and_runs_in_both_clusters():
    plane, ctrl, a, b = _federated_rig()
    plane.api.create(FEDERATED_RS_KIND, _mk_frs(10))
    ctrl.sync_all()
    assert a.api.get("ReplicaSet", "default", "web").replicas == 5
    assert b.api.get("ReplicaSet", "default", "web").replicas == 5
    a.reconcile()
    b.reconcile()
    assert len(a.bound_pods()) == 5
    assert len(b.bound_pods()) == 5
    # status aggregation on the next sync
    a.factory.step_all(); a.rsc.pump()
    b.factory.step_all(); b.rsc.pump()
    ctrl.sync_all()
    frs = plane.api.get(FEDERATED_RS_KIND, "default", "web")
    assert frs.ready_replicas == 0  # pods Pending (no kubelet in this rig)


def test_rebalance_on_cluster_loss():
    """beta dies -> next sync moves all 10 replicas to alpha (done
    condition of VERDICT r3 #8)."""
    plane, ctrl, a, b = _federated_rig()
    plane.api.create(FEDERATED_RS_KIND, _mk_frs(
        10, prefs=json.dumps({"rebalance": True,
                              "clusters": {"*": {"weight": 1}}})))
    ctrl.sync_all()
    assert a.api.get("ReplicaSet", "default", "web").replicas == 5
    plane.mark_ready("beta", False)  # cluster controller saw it die
    ctrl.sync_all()
    assert a.api.get("ReplicaSet", "default", "web").replicas == 10
    a.reconcile()
    assert len(a.bound_pods()) == 10
    # recovery: beta comes back, replicas spread again
    plane.mark_ready("beta", True)
    ctrl.sync_all()
    assert a.api.get("ReplicaSet", "default", "web").replicas == 5
    assert b.api.get("ReplicaSet", "default", "web").replicas == 5


def test_unjoin_deregisters_and_replicas_move():
    plane, ctrl, a, b = _federated_rig()
    plane.api.create(FEDERATED_RS_KIND, _mk_frs(
        10, prefs=json.dumps({"rebalance": True,
                              "clusters": {"*": {"weight": 1}}})))
    ctrl.sync_all()
    plane.unjoin("beta")
    ctrl.sync_all()
    assert a.api.get("ReplicaSet", "default", "web").replicas == 10
    # beta keeps nothing federated-owned after unjoin sync? the reference
    # leaves unjoined clusters' objects alone (unjoin is deregistration) —
    # the child RS simply stops being reconciled
    assert "beta" not in plane.members


# ---------------------------------------------------------------- kubefed


def test_ktctl_federate_verbs_end_to_end():
    plane = FederationControlPlane()
    a, b = _MemberCluster("alpha"), _MemberCluster("beta")
    out = io.StringIO()
    kt = Ktctl(plane.api, out=out, federation=plane,
               federation_contexts={"alpha": a.api, "beta": b.api})
    assert kt.run(["federate", "join", "alpha"]) == 0
    assert kt.run(["federate", "join", "beta"]) == 0
    assert kt.run(["federate", "create", "rs", "web",
                   "--replicas", "10"]) == 0
    assert kt.run(["federate", "sync"]) == 0
    assert a.api.get("ReplicaSet", "default", "web").replicas == 5
    assert b.api.get("ReplicaSet", "default", "web").replicas == 5
    assert kt.run(["federate", "scale", "rs", "web",
                   "--replicas", "16"]) == 0
    assert kt.run(["federate", "sync"]) == 0
    assert a.api.get("ReplicaSet", "default", "web").replicas \
        + b.api.get("ReplicaSet", "default", "web").replicas == 16
    assert kt.run(["federate", "clusters"]) == 0
    assert kt.run(["federate", "get"]) == 0
    text = out.getvalue()
    assert "alpha\tReady" in text and "beta\tReady" in text
    assert "default/web" in text
    assert kt.run(["federate", "unjoin", "beta"]) == 0
    assert kt.run(["federate", "sync"]) == 0
    assert a.api.get("ReplicaSet", "default", "web").replicas == 16
