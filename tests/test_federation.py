"""Federation (L9): planner semantics, federated-RS sync, cluster loss,
kubefed-style CLI verbs.

Reference targets: the replica planner
(federation/pkg/federation-controller/util/planner/planner.go), the
federated ReplicaSet type adapter + scheduling
(federation/pkg/federatedtypes/{replicaset,scheduling}.go), and kubefed
join/unjoin. Two in-process member clusters each run a real
ReplicaSetController + Scheduler, so a federated workload ends as bound
pods in both — and re-balances when a cluster dies (VERDICT r3 #8:
10 replicas spread 5/5, re-balanced on cluster loss)."""

from __future__ import annotations

import io
import json

import pytest

from kubernetes_tpu.api.types import LabelSelector, make_node, make_pod
from kubernetes_tpu.api.workloads import ReplicaSet
from kubernetes_tpu.cli.ktctl import Ktctl
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.replicaset import ReplicaSetController
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.federation.controller import (
    FEDERATED_RS_KIND,
    FederatedReplicaSet,
    FederatedReplicaSetController,
    FederationControlPlane,
)
from kubernetes_tpu.federation.planner import (
    PREFERENCES_ANNOTATION,
    ClusterPreferences,
    Planner,
    ReplicaAllocationPreferences,
)
from kubernetes_tpu.server.apiserver_lite import ApiServerLite

Gi = 1 << 30


# ------------------------------------------------------------------ planner


def _prefs(rebalance=False, **clusters):
    return ReplicaAllocationPreferences(
        rebalance=rebalance,
        clusters={k: v for k, v in clusters.items()})


def test_planner_even_split():
    plan, overflow = Planner(_prefs(
        **{"*": ClusterPreferences(weight=1)})).plan(
            10, ["a", "b"], key="default/web")
    assert plan == {"a": 5, "b": 5}
    assert overflow == {}


def test_planner_weighted():
    plan, _ = Planner(_prefs(
        a=ClusterPreferences(weight=3),
        b=ClusterPreferences(weight=1))).plan(8, ["a", "b"])
    assert plan == {"a": 6, "b": 2}


def test_planner_min_replicas_take_priority():
    plan, _ = Planner(_prefs(
        a=ClusterPreferences(min_replicas=4, weight=0),
        b=ClusterPreferences(weight=1))).plan(6, ["a", "b"])
    assert plan == {"a": 4, "b": 2}


def test_planner_max_replicas_cap():
    plan, _ = Planner(_prefs(
        a=ClusterPreferences(weight=1, max_replicas=2),
        b=ClusterPreferences(weight=1))).plan(10, ["a", "b"])
    assert plan == {"a": 2, "b": 8}


def test_planner_capacity_overflow():
    plan, overflow = Planner(_prefs(
        rebalance=True, **{"*": ClusterPreferences(weight=1)})).plan(
            10, ["a", "b"], capacity={"a": 2})
    assert plan == {"a": 2, "b": 8}
    assert overflow.get("a", 0) >= 1  # a wanted more than its capacity


def test_planner_no_rebalance_keeps_current_layout():
    """rebalance=false: cluster b keeps its 7 even though an even split
    would say 5/5 (the anti-thrash preallocation, planner.go:116-140)."""
    plan, _ = Planner(_prefs(
        **{"*": ClusterPreferences(weight=1)})).plan(
            10, ["a", "b"], current={"b": 7})
    assert plan == {"a": 3, "b": 7}


def test_planner_unlisted_cluster_without_wildcard_gets_zero():
    plan, _ = Planner(_prefs(a=ClusterPreferences(weight=1))).plan(
        5, ["a", "b"])
    assert plan == {"a": 5, "b": 0}


def test_planner_preferences_json_wire_format():
    p = ReplicaAllocationPreferences.parse(json.dumps({
        "rebalance": True,
        "clusters": {"*": {"weight": 2, "minReplicas": 1,
                           "maxReplicas": 9}}}))
    assert p.rebalance is True
    assert p.clusters["*"] == ClusterPreferences(1, 9, 2)


# --------------------------------------------------------- two-cluster rig


class _MemberCluster:
    """A real member: apiserver + RS controller + scheduler."""

    def __init__(self, name: str, n_nodes: int = 4):
        self.name = name
        self.api = ApiServerLite()
        for i in range(n_nodes):
            self.api.create("Node", make_node(f"{name}-node-{i}",
                                              cpu=8000, memory=16 * Gi))
        self.factory = SharedInformerFactory(self.api)
        self.rsc = ReplicaSetController(self.api, self.factory,
                                        record_events=False)
        self.sched = Scheduler(self.api, record_events=False)
        self.sched.start()

    def reconcile(self):
        self.factory.step_all()
        self.rsc.pump()
        self.sched.run_until_drained()

    def bound_pods(self):
        return [p for p in self.api.list("Pod")[0]
                if p.node_name and not p.deleted]


def _federated_rig():
    plane = FederationControlPlane()
    a, b = _MemberCluster("alpha"), _MemberCluster("beta")
    plane.join("alpha", a.api)
    plane.join("beta", b.api)
    ctrl = FederatedReplicaSetController(plane)
    return plane, ctrl, a, b


def _mk_frs(replicas=10, prefs=None):
    tmpl = ReplicaSet(
        name="web", selector=LabelSelector(match_labels={"app": "web"}),
        template=make_pod("", cpu=100, labels={"app": "web"}))
    frs = FederatedReplicaSet(name="web", replicas=replicas, template=tmpl)
    if prefs:
        frs.annotations[PREFERENCES_ANNOTATION] = prefs
    return frs


def test_federated_rs_spreads_5_5_and_runs_in_both_clusters():
    plane, ctrl, a, b = _federated_rig()
    plane.api.create(FEDERATED_RS_KIND, _mk_frs(10))
    ctrl.sync_all()
    assert a.api.get("ReplicaSet", "default", "web").replicas == 5
    assert b.api.get("ReplicaSet", "default", "web").replicas == 5
    a.reconcile()
    b.reconcile()
    assert len(a.bound_pods()) == 5
    assert len(b.bound_pods()) == 5
    # status aggregation on the next sync
    a.factory.step_all(); a.rsc.pump()
    b.factory.step_all(); b.rsc.pump()
    ctrl.sync_all()
    frs = plane.api.get(FEDERATED_RS_KIND, "default", "web")
    assert frs.ready_replicas == 0  # pods Pending (no kubelet in this rig)


def test_rebalance_on_cluster_loss():
    """beta dies -> next sync moves all 10 replicas to alpha (done
    condition of VERDICT r3 #8)."""
    plane, ctrl, a, b = _federated_rig()
    plane.api.create(FEDERATED_RS_KIND, _mk_frs(
        10, prefs=json.dumps({"rebalance": True,
                              "clusters": {"*": {"weight": 1}}})))
    ctrl.sync_all()
    assert a.api.get("ReplicaSet", "default", "web").replicas == 5
    plane.mark_ready("beta", False)  # cluster controller saw it die
    ctrl.sync_all()
    assert a.api.get("ReplicaSet", "default", "web").replicas == 10
    a.reconcile()
    assert len(a.bound_pods()) == 10
    # recovery: beta comes back, replicas spread again
    plane.mark_ready("beta", True)
    ctrl.sync_all()
    assert a.api.get("ReplicaSet", "default", "web").replicas == 5
    assert b.api.get("ReplicaSet", "default", "web").replicas == 5


def test_unjoin_deregisters_and_replicas_move():
    plane, ctrl, a, b = _federated_rig()
    plane.api.create(FEDERATED_RS_KIND, _mk_frs(
        10, prefs=json.dumps({"rebalance": True,
                              "clusters": {"*": {"weight": 1}}})))
    ctrl.sync_all()
    plane.unjoin("beta")
    ctrl.sync_all()
    assert a.api.get("ReplicaSet", "default", "web").replicas == 10
    # beta keeps nothing federated-owned after unjoin sync? the reference
    # leaves unjoined clusters' objects alone (unjoin is deregistration) —
    # the child RS simply stops being reconciled
    assert "beta" not in plane.members


# ---------------------------------------------------------------- kubefed


def test_ktctl_federate_verbs_end_to_end():
    plane = FederationControlPlane()
    a, b = _MemberCluster("alpha"), _MemberCluster("beta")
    out = io.StringIO()
    kt = Ktctl(plane.api, out=out, federation=plane,
               federation_contexts={"alpha": a.api, "beta": b.api})
    assert kt.run(["federate", "join", "alpha"]) == 0
    assert kt.run(["federate", "join", "beta"]) == 0
    assert kt.run(["federate", "create", "rs", "web",
                   "--replicas", "10"]) == 0
    assert kt.run(["federate", "sync"]) == 0
    assert a.api.get("ReplicaSet", "default", "web").replicas == 5
    assert b.api.get("ReplicaSet", "default", "web").replicas == 5
    assert kt.run(["federate", "scale", "rs", "web",
                   "--replicas", "16"]) == 0
    assert kt.run(["federate", "sync"]) == 0
    assert a.api.get("ReplicaSet", "default", "web").replicas \
        + b.api.get("ReplicaSet", "default", "web").replicas == 16
    assert kt.run(["federate", "clusters"]) == 0
    assert kt.run(["federate", "get"]) == 0
    text = out.getvalue()
    assert "alpha\tReady" in text and "beta\tReady" in text
    assert "default/web" in text
    assert kt.run(["federate", "unjoin", "beta"]) == 0
    assert kt.run(["federate", "sync"]) == 0
    assert a.api.get("ReplicaSet", "default", "web").replicas == 16


# ----------------------------------------- federated Deployment + Service DNS


def test_federated_deployment_spreads_and_rescales():
    from kubernetes_tpu.api.workloads import Deployment
    from kubernetes_tpu.federation.controller import (
        FEDERATED_DEPLOY_KIND,
        FederatedDeployment,
        FederatedDeploymentController,
    )

    plane = FederationControlPlane()
    east, west = ApiServerLite(), ApiServerLite()
    plane.join("east", east, zone="us-east1-a", region="us-east1")
    plane.join("west", west, zone="us-west1-b", region="us-west1")
    tmpl = Deployment(name="web", namespace="default",
                      selector=LabelSelector(match_labels={"app": "web"}),
                      template=make_pod("", labels={"app": "web"}, cpu=50))
    plane.api.create(FEDERATED_DEPLOY_KIND, FederatedDeployment(
        name="web", replicas=6, template=tmpl))
    ctrl = FederatedDeploymentController(plane)
    ctrl.sync_all()
    assert east.get("Deployment", "default", "web").replicas == 3
    assert west.get("Deployment", "default", "web").replicas == 3
    # cluster loss: all replicas move to the survivor
    plane.mark_ready("west", False)
    ctrl.sync_all()
    assert east.get("Deployment", "default", "web").replicas == 6
    import pytest as _pytest
    from kubernetes_tpu.server.apiserver_lite import NotFound
    with _pytest.raises(NotFound):
        west.get("Deployment", "default", "web")


def _dns_rig():
    from kubernetes_tpu.api.workloads import (
        EndpointAddress,
        Endpoints,
        Service,
        ServicePort,
    )
    from kubernetes_tpu.federation.service_dns import (
        FEDERATED_SERVICE_KIND,
        FederatedService,
        FederatedServiceController,
        InMemoryDNSProvider,
    )

    plane = FederationControlPlane()
    east, west = ApiServerLite(), ApiServerLite()
    plane.join("east", east, zone="us-east1-a", region="us-east1")
    plane.join("west", west, zone="us-west1-b", region="us-west1")
    dns = InMemoryDNSProvider()
    ctrl = FederatedServiceController(plane, dns=dns, federation="fed",
                                      domain="example.com")
    fsvc = FederatedService(name="api", template=Service(
        name="api", selector={"app": "api"},
        ports=[ServicePort(port=80)]))
    plane.api.create(FEDERATED_SERVICE_KIND, fsvc)
    return plane, east, west, dns, ctrl, fsvc


def test_federated_service_materializes_and_writes_dns():
    from kubernetes_tpu.api.workloads import EndpointAddress, Endpoints

    plane, east, west, dns, ctrl, fsvc = _dns_rig()
    ctrl.sync_all()
    # services exist in both member clusters
    assert east.get("Service", "default", "api").name == "api"
    assert west.get("Service", "default", "api").name == "api"
    # no endpoints anywhere yet: zone records CNAME up the chain, and the
    # chain dead-ends (no global A record)
    zname = "api.default.fed.svc.us-east1-a.us-east1.example.com"
    assert dns.lookup(zname).rtype == "CNAME"
    assert dns.resolve(zname) == []
    # east gains healthy endpoints + an LB ingress IP
    svc = east.get("Service", "default", "api")
    svc.load_balancer_ip = "34.1.1.1"
    east.update("Service", svc)
    east.create("Endpoints", Endpoints("api", "default", addresses=[
        EndpointAddress(pod_key="default/p1", node_name="n1")]))
    ctrl.sync_all()
    # east zone resolves locally; west zone CNAMEs to region then global,
    # landing on east's ingress
    assert dns.resolve(zname) == ["34.1.1.1"]
    wz = "api.default.fed.svc.us-west1-b.us-west1.example.com"
    assert dns.lookup(wz).rtype == "CNAME"
    assert dns.resolve(wz) == ["34.1.1.1"]
    fed = plane.api.get("FederatedService", "default", "api")
    assert fed.serving_clusters == ["east"]


def test_federated_service_dns_failover_on_cluster_loss():
    from kubernetes_tpu.api.workloads import EndpointAddress, Endpoints

    plane, east, west, dns, ctrl, fsvc = _dns_rig()
    for member, ip in ((east, "34.1.1.1"), (west, "35.2.2.2")):
        ctrl.sync_all()
        svc = member.get("Service", "default", "api")
        svc.load_balancer_ip = ip
        member.update("Service", svc)
        member.create("Endpoints", Endpoints("api", "default", addresses=[
            EndpointAddress(pod_key="default/p", node_name="n")]))
    ctrl.sync_all()
    gname = "api.default.fed.svc.example.com"
    assert dns.resolve(gname) == ["34.1.1.1", "35.2.2.2"]
    # west cluster dies: its zone record fails over through the chain
    plane.mark_ready("west", False)
    ctrl.sync_all()
    wz = "api.default.fed.svc.us-west1-b.us-west1.example.com"
    assert dns.resolve(wz) == ["34.1.1.1"]
    assert dns.resolve(gname) == ["34.1.1.1"]


def test_federate_cli_dns_persists_and_get_lists_all_kinds():
    from kubernetes_tpu.api.workloads import (
        EndpointAddress,
        Endpoints,
        Namespace,
    )
    from kubernetes_tpu.server.apiserver import ApiServer

    plane = FederationControlPlane()
    east, west = ApiServerLite(), ApiServerLite()
    api = ApiServer()
    api.store.create("Namespace", Namespace("default"))
    out = io.StringIO()
    kt = Ktctl(api, out=out, federation=plane,
               federation_contexts={"east": east, "west": west})
    assert kt.run(["federate", "join", "east"]) == 0
    assert kt.run(["federate", "join", "west"]) == 0
    assert kt.run(["federate", "create", "deploy", "web",
                   "--replicas", "4"]) == 0
    assert kt.run(["federate", "create", "service", "api"]) == 0
    assert kt.run(["federate", "sync"]) == 0
    # endpoints appear in east; a SECOND sync (fresh controller instance)
    # must see the same DNS zone — records persist on the plane
    svc = east.get("Service", "default", "api")
    svc.load_balancer_ip = "34.9.9.9"
    east.update("Service", svc)
    east.create("Endpoints", Endpoints("api", "default", addresses=[
        EndpointAddress(pod_key="default/p", node_name="n")]))
    assert kt.run(["federate", "sync"]) == 0
    out.truncate(0), out.seek(0)
    assert kt.run(["federate", "dns", "api"]) == 0
    assert "34.9.9.9" in out.getvalue()
    out.truncate(0), out.seek(0)
    assert kt.run(["federate", "get"]) == 0
    text = out.getvalue()
    assert "federateddeployment/default/web" in text
    assert "federatedservice/default/api" in text
    assert "serving=east" in text


def test_federated_configmap_secret_propagation():
    """federatedtypes/{configmap,secret}.go: federated config objects are
    copied into every ready member, drift is overwritten, deletion
    removes managed member copies, and a cluster joining late converges."""
    from kubernetes_tpu.api.cluster import ConfigMap, Secret
    from kubernetes_tpu.federation.controller import (
        FederatedPropagationController,
    )

    plane = FederationControlPlane()
    east, west = ApiServerLite(), ApiServerLite()
    plane.join("east", east)
    ctrl = FederatedPropagationController(plane)
    plane.api.create("FederatedConfigMap",
                     ConfigMap("settings", "default", data={"mode": "on"}))
    plane.api.create("FederatedSecret",
                     Secret("creds", "default", data={"t": "c2VjcmV0"}))
    ctrl.sync_all()
    cm = east.get("ConfigMap", "default", "settings")
    # payload copied VERBATIM (no marker key injected into data)
    assert cm.data == {"mode": "on"}
    assert cm.annotations["federation.kubernetes.io/managed"] == "true"
    assert east.get("Secret", "default", "creds").data == {"t": "c2VjcmV0"}
    # drift in a member is overwritten on the next sync
    drifted = east.get("ConfigMap", "default", "settings")
    drifted.data = {"mode": "tampered"}
    east.update("ConfigMap", drifted)
    ctrl.sync_all()
    assert east.get("ConfigMap", "default", "settings").data["mode"] == "on"
    # late joiner converges
    plane.join("west", west)
    ctrl.sync_all()
    assert west.get("ConfigMap", "default", "settings").data["mode"] == "on"
    # a member-local object colliding with a federated one is NEVER
    # adopted: it survives untouched and surfaces as a conflict
    west.create("ConfigMap", ConfigMap("collide", "default",
                                       data={"local": "data"}))
    plane.api.create("FederatedConfigMap",
                     ConfigMap("collide", "default", data={"fed": "x"}))
    ctrl.sync_all()
    assert west.get("ConfigMap", "default", "collide").data \
        == {"local": "data"}
    assert any("west/ConfigMap/default/collide" == c
               for c in ctrl.conflicts)
    # an unmanaged member-local configmap survives; the managed copy goes
    # when the federated parent is deleted
    east.create("ConfigMap", ConfigMap("local-only", "default",
                                       data={"k": "v"}))
    plane.api.delete("FederatedConfigMap", "default", "settings")
    ctrl.sync_all()
    import pytest as _pytest

    from kubernetes_tpu.server.apiserver_lite import NotFound
    with _pytest.raises(NotFound):
        east.get("ConfigMap", "default", "settings")
    assert east.get("ConfigMap", "default", "local-only").data["k"] == "v"


def test_federated_daemonset_everywhere():
    """federatedtypes/daemonset.go: no replica planning — the DaemonSet
    lands in every ready cluster, drift reconciles, managed copies go
    with the parent."""
    from kubernetes_tpu.api.types import make_pod
    from kubernetes_tpu.api.workloads import DaemonSet
    from kubernetes_tpu.federation.controller import (
        FEDERATED_DS_KIND,
        FederatedDaemonSetController,
    )

    plane = FederationControlPlane()
    east, west = ApiServerLite(), ApiServerLite()
    plane.join("east", east)
    plane.join("west", west)
    ds = DaemonSet("logger", "default",
                   template=make_pod("", labels={"app": "log"}, cpu=10))
    plane.api.create(FEDERATED_DS_KIND, ds)
    ctrl = FederatedDaemonSetController(plane)
    ctrl.sync_all()
    for member in (east, west):
        got = member.get("DaemonSet", "default", "logger")
        assert got.annotations["federation.kubernetes.io/managed"] == "true"
    # member status fields do NOT count as drift
    cur = east.get("DaemonSet", "default", "logger")
    cur.desired_scheduled = 5
    east.update("DaemonSet", cur)
    rv_before = east.get("DaemonSet", "default", "logger").resource_version
    ctrl.sync_all()
    assert east.get("DaemonSet", "default",
                    "logger").resource_version == rv_before
    # parent deletion removes managed copies
    plane.api.delete(FEDERATED_DS_KIND, "default", "logger")
    ctrl.sync_all()
    import pytest as _pytest

    from kubernetes_tpu.server.apiserver_lite import NotFound
    with _pytest.raises(NotFound):
        east.get("DaemonSet", "default", "logger")


def test_federated_daemonset_never_adopts_local():
    """The shared propagation body's conflict guard applies to DaemonSets
    too: a member-local DaemonSet colliding with a federated one is
    neither overwritten nor later deleted."""
    from kubernetes_tpu.api.types import make_pod
    from kubernetes_tpu.api.workloads import DaemonSet
    from kubernetes_tpu.federation.controller import (
        FEDERATED_DS_KIND,
        FederatedDaemonSetController,
    )

    plane = FederationControlPlane()
    east = ApiServerLite()
    plane.join("east", east)
    east.create("DaemonSet", DaemonSet(
        "logger", "default",
        template=make_pod("", labels={"local": "yes"}, cpu=5)))
    plane.api.create(FEDERATED_DS_KIND, DaemonSet(
        "logger", "default",
        template=make_pod("", labels={"fed": "yes"}, cpu=10)))
    ctrl = FederatedDaemonSetController(plane)
    ctrl.sync_all()
    local = east.get("DaemonSet", "default", "logger")
    assert local.template.labels == {"local": "yes"}  # untouched
    assert "east/DaemonSet/default/logger" in ctrl.conflicts
    plane.api.delete(FEDERATED_DS_KIND, "default", "logger")
    ctrl.sync_all()
    # local object survives the parent deletion
    assert east.get("DaemonSet", "default", "logger").template.labels \
        == {"local": "yes"}
