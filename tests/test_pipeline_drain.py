"""Pipelined drain (ISSUE 2): overlap correctness fence + perf guards.

The drain's two-stage pipeline (engine/scheduler.py _DrainPipeline +
engine/scheduler_engine.py dispatch_waves/harvest_waves) launches wave k+1's
device eval before wave k's host bookkeeping runs, so wave k+1 is encoded
BLIND to wave k's commits. These tests pin the correctness fence (blind
capacity losers requeue and converge), the A/B contract (overlap on/off is
bit-identical — the fence, not the timing, decides placements), and the
warm-round performance invariants via span counters so a later PR cannot
quietly reintroduce the eager path (re-tensorization per chunk, full
snapshot walks per bind, per-op dispatch)."""

from __future__ import annotations

from collections import Counter

from kubernetes_tpu.api.types import (
    Affinity,
    ContainerPort,
    LabelSelector,
    PodAffinity,
    PodAffinityTerm,
    make_node,
    make_pod,
)
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes, load_cluster
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.utils.trace import COUNTERS

Gi = 1 << 30


def mk_sched(nodes, pods, chunk):
    api = ApiServerLite()
    load_cluster(api, nodes, pods)
    s = Scheduler(api, record_events=False)
    s.pipeline_chunk = chunk
    s.start()
    return api, s


def placements(api):
    return {p.name: p.node_name for p in api.list("Pod")[0]}


# --------------------------------------------------------------- the fence


def test_blind_capacity_conflict_requeues_and_converges():
    """Wave k exhausts a node's capacity; wave k+1 (encoded pre-k) placed
    optimistically onto the same nodes. The fence must requeue the losers
    WITHOUT marking them unschedulable, and the retry must converge with
    capacity exactly respected."""
    def build():
        nodes = [make_node(f"n{i:03d}", cpu=2000, memory=8 * Gi, pods=110)
                 for i in range(16)]  # each node fits exactly 2 pods
        pods = [make_pod(f"p{i:03d}", cpu=1000, memory=256 << 20)
                for i in range(40)]
        return mk_sched(nodes, pods, chunk=8)

    api, s = build()
    tot = s.run_until_drained()
    assert tot["bound"] == 32
    assert tot["unschedulable"] >= 8  # 40 pods, 32 slots
    assert tot["fence_requeued"] > 0, \
        "blind waves over 2-pod nodes must hit the fence"
    per_node = Counter(p.node_name for p in api.list("Pod")[0]
                       if p.node_name)
    assert all(v <= 2 for v in per_node.values()), per_node

    # THE A/B: identical dataflow with overlap forced off must produce
    # bit-identical final placements — the fence, not scheduling luck,
    # decides every conflict
    api2, s2 = build()
    tot2 = s2.run_until_drained(overlap=False)
    assert placements(api) == placements(api2)
    assert tot2["fence_requeued"] == tot["fence_requeued"]


def test_blind_port_conflict_requeues_conservatively():
    """Special classes (host ports) cannot be re-validated by the vector
    capacity fence; a blind-window touch on their target node requeues them
    conservatively. End state: both port pods bound, never colliding."""
    nodes = [make_node(f"n{i}", cpu=4000, memory=16 * Gi, pods=110)
             for i in range(2)]
    pods = []
    for i in range(2):
        p = make_pod(f"port-{i}", cpu=100, memory=128 << 20)
        p.containers[0].ports = [ContainerPort(host_port=8080)]
        pods.append(p)
    api, s = mk_sched(nodes, pods, chunk=1)  # one pod per wave -> blind pair
    tot = s.run_until_drained()
    assert tot["bound"] == 2
    assert {p.node_name for p in api.list("Pod")[0]} == {"n0", "n1"}


def test_required_anti_affinity_falls_back_to_strict_and_converges():
    """Chunks carrying required pod anti-affinity are not wave-eligible:
    the pipeline must flush and route them through the classic synchronous
    engine, and the result must match the classic drain exactly."""
    def build():
        nodes = [make_node(f"n{i:02d}", cpu=8000, memory=32 * Gi, pods=110,
                           labels={"host": f"h{i}"}) for i in range(8)]
        pods = []
        for i in range(8):
            p = make_pod(f"iso-{i}", cpu=100, memory=128 << 20,
                         labels={"app": "iso"})
            p.affinity = Affinity(pod_anti_affinity=PodAffinity(
                required_terms=[PodAffinityTerm(
                    label_selector=LabelSelector(match_labels={"app": "iso"}),
                    namespaces=[], topology_key="host")]))
            pods.append(p)
        return mk_sched(nodes, pods, chunk=3)

    api, s = build()
    tot = s.run_until_drained()
    assert tot["bound"] == 8
    assert len({p.node_name for p in api.list("Pod")[0]}) == 8  # 1 per host
    api2, s2 = build()
    s2.run_until_drained(pipeline=False)
    assert placements(api) == placements(api2)


def test_pipelined_equals_sequential_on_seeded_density():
    """Seeded A/B at a non-trivial shape: the overlapped pipeline and its
    sequential (overlap=False) execution are bit-identical in FINAL
    placements — overlap changes wall clock, never results."""
    def build():
        nodes = hollow_nodes(96, seed=7)
        pods = PROFILES["density"](700)
        return mk_sched(nodes, pods, chunk=128)

    api1, s1 = build()
    t1 = s1.run_until_drained()
    api2, s2 = build()
    t2 = s2.run_until_drained(overlap=False)
    assert t1["bound"] == t2["bound"] == 700
    assert placements(api1) == placements(api2)


# ------------------------------------------------------------ perf guards


def test_warm_round_invariants_via_span_counters():
    """The regression tripwire (ISSUE 2 satellite): a WARM pipelined drain
    must (a) re-tensorize nothing (cached class encodings reused), (b) make
    exactly one fused device dispatch per wave, and (c) refresh the
    snapshot via the targeted hint, never a full node walk — so the next
    PR can't quietly reintroduce the eager path."""
    nodes = hollow_nodes(64)
    pods = PROFILES["density"](256)
    api, s = mk_sched(nodes, pods, chunk=128)
    tot = s.run_until_drained(max_batch=128)  # warm: compiles + builds enc
    assert tot["bound"] == 256

    # second storm of the SAME pod class arrives
    for p in PROFILES["density"](256):
        p.name = "warm2-" + p.name
        api.create("Pod", p)
    COUNTERS.reset()
    tot = s.run_until_drained(max_batch=128)
    assert tot["bound"] == 256
    snap = COUNTERS.snapshot()

    # (a) no re-tensorization of cached pod classes
    assert snap.get("engine.wave_encode_build", (0, 0))[0] == 0, snap
    assert snap.get("engine.wave_encode_reuse", (0, 0))[0] >= 2
    # (b) one fused dispatch per wave: 256 pods / 128 chunk = 2 waves
    assert snap.get("engine.wave_dispatch", (0, 0))[0] == 2, snap
    # (c) targeted refresh only — a full scan or rebuild after a plain bind
    # is the regression this test exists to catch
    assert snap.get("snapshot.refresh_scan", (0, 0))[0] == 0, snap
    assert snap.get("snapshot.refresh_rebuild", (0, 0))[0] == 0, snap
    assert snap.get("snapshot.refresh_hinted", (0, 0))[0] >= 2


def test_fence_requeue_is_not_backoff():
    """A fence conflict is a capacity race, not unschedulability: the loser
    must retry in the immediately following waves (plain queue add), not
    sit in the backoff heap."""
    nodes = [make_node(f"n{i:02d}", cpu=1000, memory=4 * Gi, pods=110)
             for i in range(4)]  # 1 pod per node
    pods = [make_pod(f"p{i}", cpu=1000, memory=128 << 20) for i in range(4)]
    api, s = mk_sched(nodes, pods, chunk=2)
    tot = s.run_until_drained()
    assert tot["bound"] == 4, tot  # nobody parked in backoff: all 4 landed
    assert tot["unschedulable"] == 0
