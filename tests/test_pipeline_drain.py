"""Pipelined drain (ISSUE 2): overlap correctness fence + perf guards.

The drain's two-stage pipeline (engine/scheduler.py _DrainPipeline +
engine/scheduler_engine.py dispatch_waves/harvest_waves) launches wave k+1's
device eval before wave k's host bookkeeping runs, so wave k+1 is encoded
BLIND to wave k's commits. These tests pin the correctness fence (blind
capacity losers requeue and converge), the A/B contract (overlap on/off is
bit-identical — the fence, not the timing, decides placements), and the
warm-round performance invariants via span counters so a later PR cannot
quietly reintroduce the eager path (re-tensorization per chunk, full
snapshot walks per bind, per-op dispatch)."""

from __future__ import annotations

from collections import Counter

from kubernetes_tpu.api.types import (
    Affinity,
    ContainerPort,
    LabelSelector,
    PodAffinity,
    PodAffinityTerm,
    make_node,
    make_pod,
)
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes, load_cluster
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.utils.trace import COUNTERS

Gi = 1 << 30


def mk_sched(nodes, pods, chunk):
    api = ApiServerLite()
    load_cluster(api, nodes, pods)
    s = Scheduler(api, record_events=False)
    s.pipeline_chunk = chunk
    s.start()
    return api, s


def placements(api):
    return {p.name: p.node_name for p in api.list("Pod")[0]}


# --------------------------------------------------------------- the fence


def test_blind_capacity_conflict_requeues_and_converges():
    """Wave k exhausts a node's capacity; wave k+1 (encoded pre-k) placed
    optimistically onto the same nodes. The fence must requeue the losers
    WITHOUT marking them unschedulable, and the retry must converge with
    capacity exactly respected."""
    def build():
        nodes = [make_node(f"n{i:03d}", cpu=2000, memory=8 * Gi, pods=110)
                 for i in range(16)]  # each node fits exactly 2 pods
        pods = [make_pod(f"p{i:03d}", cpu=1000, memory=256 << 20)
                for i in range(40)]
        return mk_sched(nodes, pods, chunk=8)

    api, s = build()
    tot = s.run_until_drained()
    assert tot["bound"] == 32
    assert tot["unschedulable"] >= 8  # 40 pods, 32 slots
    assert tot["fence_requeued"] > 0, \
        "blind waves over 2-pod nodes must hit the fence"
    per_node = Counter(p.node_name for p in api.list("Pod")[0]
                       if p.node_name)
    assert all(v <= 2 for v in per_node.values()), per_node

    # THE A/B: identical dataflow with overlap forced off must produce
    # bit-identical final placements — the fence, not scheduling luck,
    # decides every conflict
    api2, s2 = build()
    tot2 = s2.run_until_drained(overlap=False)
    assert placements(api) == placements(api2)
    assert tot2["fence_requeued"] == tot["fence_requeued"]


def test_blind_port_conflict_requeues_conservatively():
    """Special classes (host ports) cannot be re-validated by the vector
    capacity fence; a blind-window touch on their target node requeues them
    conservatively. End state: both port pods bound, never colliding."""
    nodes = [make_node(f"n{i}", cpu=4000, memory=16 * Gi, pods=110)
             for i in range(2)]
    pods = []
    for i in range(2):
        p = make_pod(f"port-{i}", cpu=100, memory=128 << 20)
        p.containers[0].ports = [ContainerPort(host_port=8080)]
        pods.append(p)
    api, s = mk_sched(nodes, pods, chunk=1)  # one pod per wave -> blind pair
    tot = s.run_until_drained()
    assert tot["bound"] == 2
    assert {p.node_name for p in api.list("Pod")[0]} == {"n0", "n1"}


def test_required_anti_affinity_rides_the_wave_path():
    """Chunks carrying required pod anti-affinity are wave-eligible
    (ISSUE 3): the pipeline must NOT flush — hostname-keyed anti classes
    place through the per-wave topology-occupancy mask — the constraint
    must hold exactly (one pod per host), and the overlap A/B must be
    bit-identical (the fence, not timing, decides every conflict)."""
    def build():
        nodes = [make_node(f"n{i:02d}", cpu=8000, memory=32 * Gi, pods=110,
                           labels={"host": f"h{i}"}) for i in range(8)]
        pods = []
        for i in range(8):
            p = make_pod(f"iso-{i}", cpu=100, memory=128 << 20,
                         labels={"app": "iso"})
            p.affinity = Affinity(pod_anti_affinity=PodAffinity(
                required_terms=[PodAffinityTerm(
                    label_selector=LabelSelector(match_labels={"app": "iso"}),
                    namespaces=[], topology_key="host")]))
            pods.append(p)
        return mk_sched(nodes, pods, chunk=3)

    COUNTERS.reset()
    api, s = build()
    tot = s.run_until_drained()
    assert tot["bound"] == 8
    assert len({p.node_name for p in api.list("Pod")[0]}) == 8  # 1 per host
    snap = COUNTERS.snapshot()
    # the chunks dispatched as waves — they never flushed to the classic
    # synchronous round, and the hostname shape needed no strict tail
    assert snap.get("engine.wave_dispatch", (0, 0))[0] >= 2, snap
    assert snap.get("engine.affinity_strict_tail", (0, 0))[0] == 0, snap
    # A/B: same dataflow with overlap forced off is bit-identical
    api2, s2 = build()
    tot2 = s2.run_until_drained(overlap=False)
    assert tot2["bound"] == 8
    assert placements(api) == placements(api2)


def test_required_affinity_group_routes_to_strict_tail():
    """Own required AFFINITY (a co-locating group bootstrapping from
    nothing) is not counter-expressible per wave: those pods must route to
    the seeded strict tail — never silently through the throughput path —
    and the group must land co-located in one topology domain."""
    nodes = [make_node(f"n{i:02d}", cpu=8000, memory=32 * Gi, pods=110,
                       labels={"host": f"h{i}", "zone": f"z{i % 2}"})
             for i in range(6)]
    pods = []
    for i in range(6):
        p = make_pod(f"pack-{i}", cpu=100, memory=128 << 20,
                     labels={"app": "pack"})
        p.affinity = Affinity(pod_affinity=PodAffinity(
            required_terms=[PodAffinityTerm(
                label_selector=LabelSelector(match_labels={"app": "pack"}),
                namespaces=[], topology_key="zone")]))
        pods.append(p)
    COUNTERS.reset()
    api, s = mk_sched(nodes, pods, chunk=2)
    tot = s.run_until_drained()
    assert tot["bound"] == 6, tot
    zones = {p.node_name for p in api.list("Pod")[0] if p.node_name}
    suffix = {int(n[1:]) % 2 for n in zones}
    assert len(suffix) == 1, f"group split across zones: {zones}"
    snap = COUNTERS.snapshot()
    assert snap.get("engine.affinity_strict_tail", (0, 0))[0] == 6, snap
    assert snap.get("engine.wave_dispatch", (0, 0))[0] >= 2, snap


def test_pipelined_equals_sequential_on_seeded_density():
    """Seeded A/B at a non-trivial shape: the overlapped pipeline and its
    sequential (overlap=False) execution are bit-identical in FINAL
    placements — overlap changes wall clock, never results."""
    def build():
        nodes = hollow_nodes(96, seed=7)
        pods = PROFILES["density"](700)
        return mk_sched(nodes, pods, chunk=128)

    api1, s1 = build()
    t1 = s1.run_until_drained()
    api2, s2 = build()
    t2 = s2.run_until_drained(overlap=False)
    assert t1["bound"] == t2["bound"] == 700
    assert placements(api1) == placements(api2)


# ------------------------------------------------------------ perf guards


def test_warm_round_invariants_via_span_counters():
    """The regression tripwire (ISSUE 2 satellite): a WARM pipelined drain
    must (a) re-tensorize nothing (cached class encodings reused), (b) make
    exactly one fused device dispatch per wave, and (c) refresh the
    snapshot via the targeted hint, never a full node walk — so the next
    PR can't quietly reintroduce the eager path."""
    nodes = hollow_nodes(64)
    pods = PROFILES["density"](256)
    api, s = mk_sched(nodes, pods, chunk=128)
    tot = s.run_until_drained(max_batch=128)  # warm: compiles + builds enc
    assert tot["bound"] == 256

    # second storm of the SAME pod class arrives
    for p in PROFILES["density"](256):
        p.name = "warm2-" + p.name
        api.create("Pod", p)
    COUNTERS.reset()
    tot = s.run_until_drained(max_batch=128)
    assert tot["bound"] == 256
    snap = COUNTERS.snapshot()

    # (a) no re-tensorization of cached pod classes
    assert snap.get("engine.wave_encode_build", (0, 0))[0] == 0, snap
    assert snap.get("engine.wave_encode_reuse", (0, 0))[0] >= 2
    # (b) one fused dispatch per wave: 256 pods / 128 chunk = 2 waves
    assert snap.get("engine.wave_dispatch", (0, 0))[0] == 2, snap
    # (c) targeted refresh only — a full scan or rebuild after a plain bind
    # is the regression this test exists to catch
    assert snap.get("snapshot.refresh_scan", (0, 0))[0] == 0, snap
    assert snap.get("snapshot.refresh_rebuild", (0, 0))[0] == 0, snap
    assert snap.get("snapshot.refresh_hinted", (0, 0))[0] >= 2


def test_warm_affinity_drain_dispatch_counters():
    """ISSUE 3 dispatch-count guard: a WARM re-drain of wave-eligible
    affinity chunks must cost ONE fused dispatch per wave, ZERO strict-scan
    tail dispatches, and ZERO ClassBatch/AffinityData rebuilds — so a later
    PR cannot quietly put affinity back on the flush-and-rebuild path. Apps
    are split so consecutive chunks never interact across the blind window
    (the fence stays quiet and the dispatch count is deterministic)."""
    nodes = hollow_nodes(64)

    def mk_pods(prefix, n):
        out = []
        for i in range(n):
            app = f"iso-{i % 2 if i < n // 2 else 2 + i % 2}"
            p = make_pod(f"{prefix}-{i}", cpu=100, memory=128 << 20,
                         labels={"app": app})
            p.affinity = Affinity(pod_anti_affinity=PodAffinity(
                required_terms=[PodAffinityTerm(
                    label_selector=LabelSelector(match_labels={"app": app}),
                    namespaces=[], topology_key="kubernetes.io/hostname")]))
            out.append(p)
        return out

    api, s = mk_sched(nodes, mk_pods("w1", 128), chunk=64)
    tot = s.run_until_drained(max_batch=64)  # warm: compiles + builds enc
    assert tot["bound"] == 128, tot

    for p in mk_pods("w2", 128):  # same classes arrive again
        api.create("Pod", p)
    COUNTERS.reset()
    tot = s.run_until_drained(max_batch=64)
    assert tot["bound"] == 128, tot
    snap = COUNTERS.snapshot()
    # no re-tensorization, no AffinityData rebuild (the encoding's
    # commdom/aff_seq bookkeeping absorbed our own assumes)
    assert snap.get("engine.wave_encode_build", (0, 0))[0] == 0, snap
    assert snap.get("engine.wave_aff_build", (0, 0))[0] == 0, snap
    assert snap.get("engine.wave_encode_reuse", (0, 0))[0] >= 2, snap
    # one fused dispatch per wave: 128 pods / 64 chunk = 2 waves; the
    # hostname shape needs no per-pod strict-scan dispatches at all
    assert snap.get("engine.wave_dispatch", (0, 0))[0] == 2, snap
    assert snap.get("engine.wave_tail_dispatch", (0, 0))[0] == 0, snap
    assert snap.get("engine.affinity_strict_tail", (0, 0))[0] == 0, snap
    assert snap.get("engine.affinity_fence_requeues", (0, 0))[0] == 0, snap
    # targeted refresh only, as in the plain warm drain
    assert snap.get("snapshot.refresh_scan", (0, 0))[0] == 0, snap
    assert snap.get("snapshot.refresh_rebuild", (0, 0))[0] == 0, snap


def test_fence_requeue_is_not_backoff():
    """A fence conflict is a capacity race, not unschedulability: the loser
    must retry in the immediately following waves (plain queue add), not
    sit in the backoff heap."""
    nodes = [make_node(f"n{i:02d}", cpu=1000, memory=4 * Gi, pods=110)
             for i in range(4)]  # 1 pod per node
    pods = [make_pod(f"p{i}", cpu=1000, memory=128 << 20) for i in range(4)]
    api, s = mk_sched(nodes, pods, chunk=2)
    tot = s.run_until_drained()
    assert tot["bound"] == 4, tot  # nobody parked in backoff: all 4 landed
    assert tot["unschedulable"] == 0


def test_zone_anti_blind_window_fenced():
    """Multi-node-domain (zone) required anti-affinity across BLIND
    windows: the per-node fence mirror cannot see a collision on a
    DIFFERENT node of the same domain, so the fence also re-validates
    over the projected domain columns. Two za classes are pinned to
    DIFFERENT nodes of the same zone (same-class blind evaluations are
    identical and collide on the same node, where the per-node mirror
    already catches them); chunk=1 makes za-b's evaluation blind to
    za-a's bind, so only the domain form can reject za-b@n1 against
    za-a@n0. Exactly one za pod may land, in both overlap modes,
    bit-identically."""
    def build():
        nodes = [make_node(f"n{i}", cpu=4000, memory=16 * Gi, pods=110,
                           labels={"host": f"h{i}", "zone": "z0"})
                 for i in range(3)]
        pods = []
        for i, host in enumerate(("h0", "h1")):
            p = make_pod(f"za-{i}", cpu=100 * (i + 1), memory=128 << 20,
                         labels={"app": "za"})
            p.node_selector = {"host": host}
            p.affinity = Affinity(pod_anti_affinity=PodAffinity(
                required_terms=[PodAffinityTerm(
                    label_selector=LabelSelector(match_labels={"app": "za"}),
                    namespaces=[], topology_key="zone")]))
            pods.append(p)
        return mk_sched(nodes, pods, chunk=1)

    api, s = build()
    tot = s.run_until_drained(max_batch=1)
    assert tot["bound"] == 1, tot            # one per zone, no more
    assert tot["unschedulable"] == 1, tot
    api2, s2 = build()
    s2.run_until_drained(max_batch=1, overlap=False)
    assert placements(api) == placements(api2)


def test_affinity_straggler_requeues_not_tail():
    """Max-waves stragglers of wave-eligible classes in an AFFINITY chunk
    must requeue (without backoff), never ride the seeded strict tail —
    the tail's domain projection carries only the wave_strict classes'
    columns, so a straggler's constraints would be invisible there. The
    bottleneck: a special (volume) class pinned to ONE node commits one
    pod per wave, so > 64 pods in one chunk exhaust max_waves."""
    from kubernetes_tpu.api.types import Volume, VolumeKind

    nodes = [make_node(f"n{i}", cpu=8000, memory=32 * Gi, pods=110,
                       labels={"host": f"h{i}"}) for i in range(4)]
    pods = []
    for i in range(70):  # > max_waves(64) pods of one special class
        p = make_pod(f"ro-{i}", cpu=10, memory=16 << 20)
        p.volumes = [Volume(name="shared", kind=VolumeKind.GCE_PD,
                            volume_id="shared-pd", read_only=True)]
        p.node_selector = {"host": "h0"}
        pods.append(p)
    # one anti pod makes the chunk an affinity chunk (enc.adata != None)
    guard = make_pod("iso-0", cpu=100, memory=128 << 20,
                     labels={"app": "iso"})
    guard.affinity = Affinity(pod_anti_affinity=PodAffinity(
        required_terms=[PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"app": "iso"}),
            namespaces=[], topology_key="host")]))
    pods.append(guard)
    COUNTERS.reset()
    api, s = mk_sched(nodes, pods, chunk=128)
    tot = s.run_until_drained(max_batch=128)
    assert tot["bound"] == 71, tot
    snap = COUNTERS.snapshot()
    assert snap.get("engine.affinity_straggler_requeues", (0, 0))[0] > 0, \
        snap  # the bottleneck class DID exhaust max_waves
    assert snap.get("engine.wave_tail_dispatch", (0, 0))[0] == 0, \
        snap  # ... and its stragglers never rode the projected tail
    per_node = Counter(p.node_name for p in api.list("Pod")[0]
                       if p.node_name and p.name.startswith("ro-"))
    assert per_node == {"n0": 70}, per_node


def test_relabel_invalidates_affinity_encoding():
    """A node relabel to ALREADY-interned values rides the delta refresh:
    no vocab growth, no affinity churn — only snapshot.labels_gen records
    that label CONTENT moved. The cached wave encoding bakes topology
    views (key_node / labels_aff) from label content, so reuse keyed on
    (vocab_gen, aff_seq) alone would evaluate required anti-affinity
    against the OLD topology. za-1's node moves from z1 into z0, which
    frees zone z1: a third zone-anti pod MUST bind there — a stale
    encoding still sees z1 occupied and calls it unschedulable."""
    nodes = [make_node(f"n{i}", cpu=4000, memory=16 * Gi, pods=110,
                       labels={"zone": "z0" if i < 2 else "z1"})
             for i in range(4)]

    def za(name):
        p = make_pod(name, cpu=100, memory=128 << 20, labels={"app": "za"})
        p.affinity = Affinity(pod_anti_affinity=PodAffinity(
            required_terms=[PodAffinityTerm(
                label_selector=LabelSelector(match_labels={"app": "za"}),
                namespaces=[], topology_key="zone")]))
        return p

    api, s = mk_sched(nodes, [za("za-0"), za("za-1")], chunk=4)
    tot = s.run_until_drained(max_batch=4)
    assert tot["bound"] == 2, tot
    where = placements(api)
    z1_node = where["za-1"] if where["za-1"] in ("n2", "n3") \
        else where["za-0"]
    assert z1_node in ("n2", "n3"), where

    # relabel the z1 occupant's node into z0 (z0 is already interned)
    node = [n for n in api.list("Node")[0] if n.name == z1_node][0]
    node.labels = dict(node.labels, zone="z0")
    api.update("Node", node)

    api.create("Pod", za("za-2"))
    tot = s.run_until_drained(max_batch=4)
    assert tot["bound"] == 1, (tot, placements(api))
    got = placements(api)["za-2"]
    other_z1 = "n3" if z1_node == "n2" else "n2"
    assert got == other_z1, (got, z1_node)


# ------------------------------------------------- runtime sanitizer (ISSUE 4)


def test_headline_density_drain_under_sanitizer(monkeypatch):
    """GRAFT_SANITIZE=1 on the headline shape (seeded density through the
    pipelined drain): the armed upload seams must catch nothing and change
    nothing — bit-identical placements vs the unsanitized run."""
    def build():
        nodes = hollow_nodes(96, seed=7)
        pods = PROFILES["density"](700)
        return mk_sched(nodes, pods, chunk=128)

    api_ref, s_ref = build()
    tot_ref = s_ref.run_until_drained()
    monkeypatch.setenv("GRAFT_SANITIZE", "1")
    api, s = build()
    tot = s.run_until_drained()
    assert tot["bound"] == tot_ref["bound"] == 700
    assert placements(api) == placements(api_ref)


def test_mixed_affinity_drain_under_sanitizer(monkeypatch):
    """GRAFT_SANITIZE=1 proof run (ISSUE 4): a pipelined mixed-affinity
    drain with every upload seam armed — copy seams assert they really
    copied, frozen-alias seams seal their host sources. The sanitizer must
    catch NOTHING on the current tree, and arming it must not change a
    single placement (the A/B against the unsanitized run)."""
    def build():
        nodes = [make_node(f"n{i:02d}", cpu=8000, memory=32 * Gi, pods=110,
                           labels={"host": f"h{i}", "zone": f"z{i % 2}"})
                 for i in range(8)]
        pods = []
        for i in range(6):  # one-per-host anti: rides the wave path
            p = make_pod(f"iso-{i}", cpu=100, memory=128 << 20,
                         labels={"app": "iso"})
            p.affinity = Affinity(pod_anti_affinity=PodAffinity(
                required_terms=[PodAffinityTerm(
                    label_selector=LabelSelector(match_labels={"app": "iso"}),
                    namespaces=[], topology_key="host")]))
            pods.append(p)
        for i in range(4):  # zone co-location group: seeded strict tail
            p = make_pod(f"co-{i}", cpu=100, memory=128 << 20,
                         labels={"app": "co"})
            p.affinity = Affinity(pod_affinity=PodAffinity(
                required_terms=[PodAffinityTerm(
                    label_selector=LabelSelector(match_labels={"app": "co"}),
                    namespaces=[], topology_key="zone")]))
            pods.append(p)
        pods += [make_pod(f"plain-{i}", cpu=200, memory=256 << 20)
                 for i in range(12)]
        return mk_sched(nodes, pods, chunk=5)

    api_ref, s_ref = build()
    tot_ref = s_ref.run_until_drained()

    monkeypatch.setenv("GRAFT_SANITIZE", "1")
    api, s = build()
    tot = s.run_until_drained()
    assert tot["bound"] == tot_ref["bound"] == 22
    assert placements(api) == placements(api_ref), \
        "arming the sanitizer must not change placements"
    per_host = Counter(p.node_name for p in api.list("Pod")[0]
                       if p.node_name and p.name.startswith("iso-"))
    assert all(v == 1 for v in per_host.values()), per_host
    zone_of = {n.name: n.labels["zone"] for n in api.list("Node")[0]}
    co_zone = {zone_of[p.node_name] for p in api.list("Pod")[0]
               if p.node_name and p.name.startswith("co-")}
    assert len(co_zone) == 1, co_zone  # co-location honored under sanitize


def _aligned_buf(shape, dtype, align=64):
    """A numpy buffer the CPU backend is GUARANTEED to zero-copy when
    handed to jnp.asarray (XLA's CPU client aliases only >=64-byte-aligned
    host buffers — ordinary numpy allocations are 16-aligned, which is
    exactly why the r07 race was flaky instead of reliable)."""
    import numpy as np
    size = int(np.prod(shape)) * np.dtype(dtype).itemsize
    raw = np.zeros(size + align, dtype=np.uint8)
    off = (-raw.ctypes.data) % align
    return raw[off:off + size].view(dtype).reshape(shape)


def test_sanitizer_catches_deliberate_aliasing_regression(monkeypatch):
    """Re-introduce the exact r07/r08 regression shape — a copy-contract
    seam silently degraded to jnp.asarray — and prove the sanitizer
    crashes LOUDLY at the seam instead of letting a blind wave read a
    mutating buffer. The ctor indirection (sanitize._copy_ctor) exists for
    this test: it is the programmatic form of reverting the jnp.array fix."""
    import jax.numpy as jnp
    import numpy as np
    import pytest

    from kubernetes_tpu.analysis import sanitize

    monkeypatch.setenv("GRAFT_SANITIZE", "1")
    buf = _aligned_buf((64, 8), np.int32)
    if not np.shares_memory(np.asarray(jnp.asarray(buf)), buf):
        pytest.skip("backend copies uploads — the aliasing race cannot "
                    "exist here (CPU-only regression)")
    monkeypatch.setattr(sanitize, "_copy_ctor", jnp.asarray)
    with pytest.raises(sanitize.AliasingViolation):
        sanitize.upload_copied(buf)
    # the verified-copy seam (sanitize-mode node_arrays) must refuse too
    with pytest.raises(sanitize.AliasingViolation):
        sanitize.upload_view(buf)


def test_sanitizer_freeze_crashes_at_the_offending_write(monkeypatch):
    """upload_frozen seals its source: a late in-place write — the other
    half of the aliasing race — dies at the WRITE site with numpy's
    read-only error, not three waves later as a corrupted placement."""
    import numpy as np
    import pytest

    from kubernetes_tpu.analysis import sanitize

    monkeypatch.setenv("GRAFT_SANITIZE", "1")
    host = np.ones((16, 4), dtype=np.int8)
    sanitize.upload_frozen(host)
    with pytest.raises(ValueError):
        host[0, 0] = 7
    # disabled -> pure pass-through, source stays writable
    monkeypatch.setenv("GRAFT_SANITIZE", "0")
    host2 = np.ones(8, dtype=np.int32)
    sanitize.upload_frozen(host2)
    host2[0] = 5  # no crash
