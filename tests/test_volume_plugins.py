"""The volume plugin layer + kubelet volume manager.

Reference behaviors pinned (pkg/volume/ + pkg/kubelet/volumemanager/):
- FindPluginBySpec: exactly-one-match semantics (plugins.go:372-392).
- per-driver mount semantics: EmptyDir isolation, HostPath node sharing,
  ConfigMap/Secret payload materialization (secret values land decoded),
  DownwardAPI field rendering, Projected merge, NFS cross-node sharing,
  Local node pinning, attachable devices requiring attach-before-mount.
- reconciler: mounts desired, unmounts orphans, surfaces errors;
  WaitForAttachAndMount timeout -> FailedMount.
- in-use protection: the attach-detach controller must not detach a
  device the kubelet still has mounted.
"""

import base64

import pytest

from kubernetes_tpu.api.cluster import ConfigMap, Secret
from kubernetes_tpu.api.types import (
    NodeSelectorTerm,
    PersistentVolume,
    PersistentVolumeClaim,
    SelectorRequirement,
    Volume,
    VolumeKind,
    make_node,
    make_pod,
)
from kubernetes_tpu.controllers.cloudctrl import (
    ATTACHED_ANNOTATION,
    IN_USE_ANNOTATION,
)
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.volumes import (
    VolumeHost,
    VolumeManager,
    VolumePluginManager,
    VolumeSpec,
    default_plugins,
)
from kubernetes_tpu.volumes.plugins import VolumeError

Mi = 1 << 20


def vol(name, kind=VolumeKind.OTHER, vid="", driver=""):
    return Volume(name=name, kind=kind, volume_id=vid, driver=driver)


def rig(node_name="n1"):
    api = ApiServerLite()
    api.create("Node", make_node(node_name, cpu=4000, memory=1 << 33))
    host = VolumeHost(api=api, node_name=node_name)
    mgr = VolumeManager(VolumePluginManager(default_plugins()), host)
    return api, host, mgr


# ------------------------------------------------------------ plugin lookup


def test_find_plugin_by_spec_exactly_one():
    pm = VolumePluginManager(default_plugins())
    assert pm.find_plugin_by_spec(
        VolumeSpec(volume=vol("v", driver="EmptyDir"))
    ).name == "kubernetes.io/empty-dir"
    assert pm.find_plugin_by_spec(
        VolumeSpec(volume=vol("v", VolumeKind.GCE_PD, "disk-1"))
    ).name == "kubernetes.io/gce-pd"
    assert pm.find_plugin_by_spec(
        VolumeSpec(volume=vol("v", VolumeKind.CONFIG_MAP, "cm"))
    ).name == "kubernetes.io/configmap"
    # a driver hint nothing claims
    with pytest.raises(VolumeError):
        pm.find_plugin_by_spec(
            VolumeSpec(volume=vol("v", driver="FlockerISH")))


def test_duplicate_registration_rejected():
    plugins = default_plugins()
    with pytest.raises(VolumeError):
        VolumePluginManager(plugins + [plugins[0].__class__()])


# ------------------------------------------------------------ driver мounts


def test_emptydir_isolated_per_pod():
    api, host, mgr = rig()
    p1 = make_pod("p1", cpu=10, memory=Mi)
    p1.volumes = [vol("scratch", driver="EmptyDir")]
    p2 = make_pod("p2", cpu=10, memory=Mi)
    p2.volumes = [vol("scratch", driver="EmptyDir")]
    for p in (p1, p2):
        mgr.add_pod(p)
    mgr.reconcile()
    host.pod_dir(p1.key())["scratch"]["f"] = b"one"
    assert "f" not in host.pod_dir(p2.key())["scratch"]


def test_hostpath_shared_on_node():
    api, host, mgr = rig()
    p1 = make_pod("p1", cpu=10, memory=Mi)
    p1.volumes = [vol("logs", driver="HostPath", vid="/var/log")]
    p2 = make_pod("p2", cpu=10, memory=Mi)
    p2.volumes = [vol("logs", driver="HostPath", vid="/var/log")]
    mgr.add_pod(p1)
    mgr.add_pod(p2)
    mgr.reconcile()
    host.pod_dir(p1.key())["logs"]["a.log"] = b"x"
    assert host.pod_dir(p2.key())["logs"]["a.log"] == b"x"


def test_configmap_and_secret_materialize_payload():
    api, host, mgr = rig()
    api.create("ConfigMap", ConfigMap("settings", "default",
                                      data={"mode": "fast"}))
    api.create("Secret", Secret("creds", "default", data={
        "token": base64.b64encode(b"s3cret").decode()}))
    p = make_pod("p", cpu=10, memory=Mi)
    p.volumes = [vol("cfg", VolumeKind.CONFIG_MAP, "settings"),
                 vol("sec", VolumeKind.SECRET, "creds")]
    mgr.add_pod(p)
    mgr.reconcile()
    assert host.pod_dir(p.key())["cfg"]["mode"] == b"fast"
    # secret files land base64-DECODED (pkg/volume/secret/secret.go)
    assert host.pod_dir(p.key())["sec"]["token"] == b"s3cret"


def test_missing_configmap_is_mount_error_not_crash():
    api, host, mgr = rig()
    p = make_pod("p", cpu=10, memory=Mi)
    p.volumes = [vol("cfg", VolumeKind.CONFIG_MAP, "nope")]
    mgr.add_pod(p)
    mounted, _ = mgr.reconcile()
    assert mounted == 0
    with pytest.raises(VolumeError, match="not found"):
        mgr.wait_for_attach_and_mount(p, timeout=0.05)


def test_downward_api_renders_pod_fields():
    api, host, mgr = rig()
    p = make_pod("p", cpu=10, memory=Mi, labels={"app": "web"})
    p.node_name = "n1"
    p.volumes = [vol("info", driver="DownwardAPI")]
    mgr.add_pod(p)
    mgr.reconcile()
    d = host.pod_dir(p.key())["info"]
    assert d["metadata.name"] == b"p"
    assert b'app="web"' in d["metadata.labels"]
    assert d["spec.nodeName"] == b"n1"


def test_projected_merges_sources():
    api, host, mgr = rig()
    api.create("ConfigMap", ConfigMap("cm", "default", data={"k1": "v1"}))
    api.create("Secret", Secret("s", "default", data={
        "k2": base64.b64encode(b"v2").decode()}))
    p = make_pod("p", cpu=10, memory=Mi)
    p.volumes = [vol("all", driver="Projected",
                     vid="configmap:cm,secret:s,downwardAPI")]
    mgr.add_pod(p)
    mgr.reconcile()
    d = host.pod_dir(p.key())["all"]
    assert d["k1"] == b"v1" and d["k2"] == b"v2"
    assert d["metadata.name"] == b"p"


def test_nfs_shared_across_nodes():
    api1, host1, mgr1 = rig("n1")
    host2 = VolumeHost(api=api1, node_name="n2")
    # same shared backend universe (the "network")
    host2.shared_fs = host1.shared_fs
    mgr2 = VolumeManager(VolumePluginManager(default_plugins()), host2)
    p1 = make_pod("p1", cpu=10, memory=Mi)
    p1.volumes = [vol("data", driver="NFS", vid="fs1:/export")]
    p2 = make_pod("p2", cpu=10, memory=Mi)
    p2.volumes = [vol("data", driver="NFS", vid="fs1:/export")]
    mgr1.add_pod(p1)
    mgr2.add_pod(p2)
    mgr1.reconcile()
    mgr2.reconcile()
    host1.pod_dir(p1.key())["data"]["shared.txt"] = b"hello"
    assert host2.pod_dir(p2.key())["data"]["shared.txt"] == b"hello"


# ---------------------------------------------------------------- PVC + local


def test_pvc_resolution_and_local_node_pinning():
    api, host, mgr = rig("n1")
    term = NodeSelectorTerm(match_expressions=[
        SelectorRequirement("kubernetes.io/hostname", "In", ["n2"])])
    api.create("PersistentVolume", PersistentVolume(
        "pv-local", capacity=Mi,
        source=Volume(name="pv-local", driver="Local", volume_id="/mnt/d1"),
        node_affinity_terms=[term]))
    api.create("PersistentVolumeClaim", PersistentVolumeClaim(
        "claim", "default", volume_name="pv-local", capacity=Mi))
    p = make_pod("p", cpu=10, memory=Mi)
    p.volumes = [vol("data", VolumeKind.PVC, "claim")]
    mgr.add_pod(p)
    mounted, _ = mgr.reconcile()
    # n1 does not satisfy the PV's node affinity -> mount must fail
    assert mounted == 0
    with pytest.raises(VolumeError, match="affinity conflict"):
        mgr.wait_for_attach_and_mount(p, timeout=0.05)
    # the right node mounts fine
    node2 = make_node("n2", cpu=4000, memory=1 << 33)
    node2.labels["kubernetes.io/hostname"] = "n2"
    api.create("Node", node2)
    host2 = VolumeHost(api=api, node_name="n2")
    mgr2 = VolumeManager(VolumePluginManager(default_plugins()), host2)
    mgr2.wait_for_attach_and_mount(p, timeout=0.5)
    assert "data" in host2.pod_dir(p.key())


def test_unbound_pvc_is_visible_error():
    api, host, mgr = rig()
    api.create("PersistentVolumeClaim", PersistentVolumeClaim(
        "loose", "default", capacity=Mi))
    p = make_pod("p", cpu=10, memory=Mi)
    p.volumes = [vol("data", VolumeKind.PVC, "loose")]
    with pytest.raises(VolumeError, match="not bound"):
        mgr.add_pod(p)


# ------------------------------------------------------- attach-before-mount


def test_attachable_mount_waits_for_controller_attach():
    api, host, mgr = rig()
    p = make_pod("p", cpu=10, memory=Mi)
    p.volumes = [vol("disk", VolumeKind.GCE_PD, "pd-1")]
    mgr.add_pod(p)
    mounted, _ = mgr.reconcile()
    assert mounted == 0  # not attached yet
    # the attach-detach controller attaches (records on the node)
    node = api.get("Node", "", "n1")
    node.annotations[ATTACHED_ANNOTATION] = "GCEPersistentDisk:pd-1"
    api.update("Node", node)
    mounted, _ = mgr.reconcile()
    assert mounted == 1
    # device content is shared through the backend: remount elsewhere
    host.pod_dir(p.key())["disk"]["state"] = b"v1"
    assert host.shared_fs["GCEPersistentDisk:pd-1"]["state"] == b"v1"
    assert mgr.volumes_in_use() == ["GCEPersistentDisk:pd-1"]


def test_in_use_protection_blocks_detach():
    from kubernetes_tpu.client.informer import SharedInformerFactory
    from kubernetes_tpu.controllers.cloudctrl import AttachDetachController

    api, host, mgr = rig()
    factory = SharedInformerFactory(api)
    ctrl = AttachDetachController(api, factory, record_events=False)
    factory.start()
    p = make_pod("p", cpu=10, memory=Mi)
    p.volumes = [vol("disk", VolumeKind.GCE_PD, "pd-1")]
    p.node_name = "n1"
    api.create("Pod", p)
    factory.step_all()
    ctrl.sync("n1")
    assert "GCEPersistentDisk:pd-1" in api.get(
        "Node", "", "n1").annotations[ATTACHED_ANNOTATION]
    mgr.add_pod(p)
    mgr.reconcile()
    # pod object deleted but kubelet hasn't unmounted yet: in-use guard
    api.delete("Pod", "default", "p")
    node = api.get("Node", "", "n1")
    node.annotations[IN_USE_ANNOTATION] = ",".join(mgr.volumes_in_use())
    api.update("Node", node)
    factory.step_all()
    ctrl.sync("n1")
    assert "GCEPersistentDisk:pd-1" in api.get(
        "Node", "", "n1").annotations[ATTACHED_ANNOTATION]
    # kubelet unmounts -> in-use clears -> controller detaches
    mgr.teardown_pod(p.key())
    node = api.get("Node", "", "n1")
    node.annotations.pop(IN_USE_ANNOTATION)
    api.update("Node", node)
    ctrl.sync("n1")
    assert api.get("Node", "", "n1").annotations.get(
        ATTACHED_ANNOTATION, "") == ""


# ----------------------------------------------------------- reconciliation


def test_reconciler_unmounts_orphans_and_cleans_pod_dir():
    api, host, mgr = rig()
    p = make_pod("p", cpu=10, memory=Mi)
    p.volumes = [vol("a", driver="EmptyDir"), vol("b", driver="EmptyDir")]
    mgr.add_pod(p)
    mgr.reconcile()
    assert mgr.mounted_volumes(p.key()) == {"a", "b"}
    n = mgr.teardown_pod(p.key())
    assert n == 2
    assert p.key() not in host.fs


def test_kubelet_syncpod_gates_on_mount():
    from kubernetes_tpu.nodes.kubelet import HollowKubelet

    api, host, mgr = rig()
    node = api.get("Node", "", "n1")
    kubelet = HollowKubelet(api, node, volume_manager=mgr)
    p = make_pod("web", cpu=10, memory=Mi)
    p.node_name = "n1"
    p.volumes = [vol("cfg", VolumeKind.CONFIG_MAP, "missing-cm")]
    api.create("Pod", p)
    kubelet.handle_pod(p)
    kubelet.workers.drain()
    # mount failed -> pod NOT admitted, FailedMount recorded
    assert p.key() not in kubelet._admitted
    assert api.get("Pod", "default", "web").annotations[
        "kubernetes.io/failure-reason"] == "FailedMount"
    # operator creates the configmap; next sync succeeds
    api.create("ConfigMap", ConfigMap("missing-cm", "default",
                                      data={"k": "v"}))
    kubelet.handle_pod(p)
    kubelet.workers.drain()
    assert p.key() in kubelet._admitted
    assert host.pod_dir(p.key())["cfg"]["k"] == b"v"
