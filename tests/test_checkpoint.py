"""Node-local checkpoint store (dockershim checkpoint analog).

Reference: pkg/kubelet/dockershim/checkpoint_store.go (FileStore atomic
writes, key validation, idempotent delete) + docker_checkpoint.go
(versioned, checksummed sandbox records) + the e2e
dockershim_checkpoint_test.go shape: state written before a kubelet
restart is visible after it.
"""

import os

import pytest

from kubernetes_tpu.api.types import Probe, make_node, make_pod
from kubernetes_tpu.nodes.checkpoint import (
    CorruptCheckpointError,
    FileStore,
    MemStore,
    PodSandboxCheckpointer,
    validate_key,
)
from kubernetes_tpu.nodes.kubelet import HollowKubelet
from kubernetes_tpu.server.apiserver_lite import ApiServerLite

Mi = 1 << 20
Gi = 1 << 30


# ---------------------------------------------------------------- FileStore


def test_filestore_roundtrip_and_idempotent_delete(tmp_path):
    st = FileStore(str(tmp_path / "ckpt"))
    st.write("sandbox-a", b"one")
    st.write("sandbox-b", b"two")
    assert st.read("sandbox-a") == b"one"
    assert st.list() == ["sandbox-a", "sandbox-b"]
    st.write("sandbox-a", b"three")  # overwrite is atomic replace
    assert st.read("sandbox-a") == b"three"
    st.delete("sandbox-a")
    st.delete("sandbox-a")  # missing key is NOT an error
    with pytest.raises(KeyError):
        st.read("sandbox-a")
    assert st.list() == ["sandbox-b"]


def test_key_validation_blocks_traversal(tmp_path):
    st = FileStore(str(tmp_path / "ckpt"))
    for bad in ("", "..", "a/b", "../evil", "/abs", ".hidden" * 50):
        with pytest.raises(ValueError):
            validate_key(bad)
        with pytest.raises(ValueError):
            st.write(bad, b"x")


def test_checkpointer_checksum_rejects_corruption(tmp_path):
    st = FileStore(str(tmp_path / "ckpt"))
    ck = PodSandboxCheckpointer(st)
    ck.checkpoint("default/web", {"restarts": 3, "node": "n1"})
    assert ck.restore("default/web") == {"restarts": 3, "node": "n1"}
    assert ck.pod_keys() == ["default/web"]
    # flip bytes on disk: restore must refuse, not return garbage
    path = os.path.join(st.directory, "default_web")
    with open(path, "r+b") as f:
        data = f.read().replace(b'"restarts": 3', b'"restarts": 9')
        f.seek(0)
        f.write(data)
        f.truncate()
    with pytest.raises(CorruptCheckpointError):
        ck.restore("default/web")


def test_memstore_matches_filestore_contract():
    st = MemStore()
    st.write("k", b"v")
    assert st.read("k") == b"v"
    st.delete("k")
    st.delete("k")
    with pytest.raises(KeyError):
        st.read("k")


# ------------------------------------------------- kubelet restart recovery


def _live_pod(name):
    p = make_pod(name, cpu=50, memory=Mi)
    p.containers[0].liveness_probe = Probe(
        initial_delay_s=0, period_s=1, failure_threshold=1)
    p.annotations["bench/liveness-fail-at"] = "5"
    return p


def test_kubelet_restart_resumes_restart_counters(tmp_path):
    t = [1000.0]
    api = ApiServerLite()
    node = make_node("n1", cpu=4000, memory=8 * Gi)
    api.create("Node", node)
    ck = PodSandboxCheckpointer(FileStore(str(tmp_path / "ckpt")))
    kubelet = HollowKubelet(api, node, now=lambda: t[0], checkpointer=ck)
    pod = _live_pod("web")
    pod.node_name = "n1"
    api.create("Pod", pod)
    kubelet.handle_pod(pod)
    kubelet.workers.drain()
    # run past the liveness-failure point a few times -> restarts accrue
    for _ in range(3):
        t[0] += 6.0
        kubelet.step()
    restarts = kubelet._restarts.get(pod.key(), 0)
    assert restarts >= 2
    # kubelet process dies; a NEW kubelet on the same node + checkpoint
    # dir resumes the counter instead of resetting to zero
    kubelet2 = HollowKubelet(api, node, now=lambda: t[0], checkpointer=ck)
    kubelet2.handle_pod(api.get("Pod", "default", "web"))
    kubelet2.workers.drain()
    assert kubelet2._restarts.get(pod.key()) == restarts
    # pod deletion cleans the checkpoint up
    kubelet2.forget_pod(pod)
    kubelet2.workers.drain()
    assert ck.pod_keys() == []


def test_corrupt_checkpoint_dropped_on_restart(tmp_path):
    api = ApiServerLite()
    node = make_node("n1", cpu=4000, memory=8 * Gi)
    api.create("Node", node)
    store = FileStore(str(tmp_path / "ckpt"))
    store.write("default_web", b"{not json")
    ck = PodSandboxCheckpointer(store)
    kubelet = HollowKubelet(api, node, checkpointer=ck)
    # the invalid checkpoint was removed, kubelet starts clean
    assert store.list() == []
    assert kubelet._restored == {}


def test_bench_matrix_cell_runs_tiny():
    """bench_matrix.py's cell runner end-to-end at toy scale (the
    upstream bench matrix shape, scheduler_bench_test.go:32-52)."""
    import bench_matrix

    elapsed = bench_matrix.run_cell(20, 10, 30)
    assert elapsed > 0


def test_restore_all_survives_any_blob_shape(tmp_path):
    store = FileStore(str(tmp_path / "ckpt"))
    store.write("arr", b"[1, 2]")          # valid JSON, wrong shape
    store.write("num", b"42")              # valid JSON, wrong shape
    store.write("badpod", b'{"pod": 7, "version": "v1", "record": {}}')
    ck = PodSandboxCheckpointer(store)
    ck.checkpoint("default/ok", {"restarts": 1})
    assert ck.restore_all() == {"default/ok": {"restarts": 1}}
    # all malformed blobs pruned, the valid one kept
    assert store.list() == ["default_ok"]


def test_long_pod_keys_checkpoint_safely(tmp_path):
    ck = PodSandboxCheckpointer(FileStore(str(tmp_path / "ckpt")))
    long_key = ("n" * 250) + "/" + ("p" * 250)
    ck.checkpoint(long_key, {"restarts": 5})
    assert ck.restore(long_key) == {"restarts": 5}
    assert ck.restore_all() == {long_key: {"restarts": 5}}
    ck.remove(long_key)
    assert ck.pod_keys() == []


def test_orphaned_checkpoint_gc(tmp_path):
    """A checkpoint for a pod deleted while the kubelet was down is
    removed by the sync-loop sweep, not inherited by a future pod."""
    api = ApiServerLite()
    node = make_node("n1", cpu=4000, memory=8 * Gi)
    api.create("Node", node)
    ck = PodSandboxCheckpointer(FileStore(str(tmp_path / "ckpt")))
    ck.checkpoint("default/ghost", {"restarts": 7, "node": "n1"})
    kubelet = HollowKubelet(api, node, checkpointer=ck)
    assert "default/ghost" in kubelet._restored
    kubelet.step()
    assert kubelet._restored == {}
    assert ck.pod_keys() == []
