"""Volume predicate tests: oracle semantics tables + kernel parity + engine
sequential (carry) behavior.

Table cases adapted from the reference's predicates_test.go volume sections
(TestDiskConflicts/TestAWSDiskConflicts/TestRBDDiskConflicts,
TestVolumeCountConflicts, TestVolumeZonePredicate) — semantics, not code.
"""

import json
import random

import numpy as np
import pytest

from kubernetes_tpu.api.types import (
    ALPHA_STORAGE_NODE_AFFINITY_ANNOTATION,
    PersistentVolume,
    PersistentVolumeClaim,
    Volume,
    VolumeKind,
    make_node,
    make_pod,
)
from kubernetes_tpu.engine.batch import place_batch
from kubernetes_tpu.ops import oracle
from kubernetes_tpu.ops import oracle_volumes as ov
from kubernetes_tpu.ops.predicates import fits_jit, node_arrays, pod_arrays
from kubernetes_tpu.state.node_info import node_info_map
from kubernetes_tpu.state.snapshot import ClusterSnapshot, PodBatch
from kubernetes_tpu.state.volumes import (
    REGION_LABEL,
    ZONE_LABEL,
    VolumeContext,
)
from kubernetes_tpu.utils import features


def gce(pd, ro=False):
    return Volume(name=pd, kind=VolumeKind.GCE_PD, volume_id=pd, read_only=ro)


def ebs(vid, ro=False):
    return Volume(name=vid, kind=VolumeKind.AWS_EBS, volume_id=vid, read_only=ro)


def rbd(mons, pool, image, ro=False):
    return Volume(name=image, kind=VolumeKind.RBD, monitors=list(mons),
                  pool=pool, image=image, read_only=ro)


def iscsi(iqn, ro=False):
    return Volume(name=iqn, kind=VolumeKind.ISCSI, volume_id=iqn, read_only=ro)


def azure(name):
    return Volume(name=name, kind=VolumeKind.AZURE_DISK, volume_id=name)


def pvc(claim):
    return Volume(name=claim, kind=VolumeKind.PVC, volume_id=claim)


def _info(node, pods=()):
    return node_info_map([node], list(pods))[node.name]


# ---------------------------------------------------------------- oracle


def test_no_disk_conflict_tables():
    node = make_node("n1")
    # (pod volume, existing volume, conflict?)
    cases = [
        (gce("a"), gce("a"), True),
        (gce("a"), gce("b"), False),
        (gce("a", ro=True), gce("a", ro=True), False),  # both RO OK
        (gce("a", ro=True), gce("a"), True),  # existing RW
        (gce("a"), gce("a", ro=True), True),  # new RW
        (ebs("v1"), ebs("v1"), True),
        (ebs("v1", ro=True), ebs("v1", ro=True), True),  # EBS: RO irrelevant
        (ebs("v1"), ebs("v2"), False),
        (rbd(["m1", "m2"], "p", "i"), rbd(["m2", "m3"], "p", "i"), True),
        (rbd(["m1"], "p", "i"), rbd(["m2"], "p", "i"), False),  # no shared mon
        (rbd(["m1"], "p", "i"), rbd(["m1"], "q", "i"), False),  # diff pool
        (rbd(["m1"], "p", "i", ro=True), rbd(["m1"], "p", "i", ro=True), False),
        (iscsi("iqn1"), iscsi("iqn1"), True),
        (iscsi("iqn1", ro=True), iscsi("iqn1", ro=True), False),
        (gce("a"), ebs("a"), False),  # cross-kind never conflicts
    ]
    for new_vol, old_vol, want_conflict in cases:
        holder = make_pod("holder", node_name="n1", volumes=[old_vol])
        info = _info(node, [holder])
        pod = make_pod("p", volumes=[new_vol])
        assert ov.no_disk_conflict(pod, info) == (not want_conflict), \
            (new_vol, old_vol)


def test_max_pd_volume_count_inline_and_pvc():
    node = make_node("n1")
    ctx = VolumeContext(
        pvs={"pv-ebs": PersistentVolume("pv-ebs", source=ebs("pv-vol"))},
        pvcs={("default", "claim1"): PersistentVolumeClaim(
            "claim1", volume_name="pv-ebs")},
    )
    # node already has 2 distinct EBS volumes (one inline, one via PVC)
    holders = [
        make_pod("h1", node_name="n1", volumes=[ebs("v1")]),
        make_pod("h2", node_name="n1", volumes=[pvc("claim1")]),
    ]
    info = _info(node, holders)
    limits = (2, 2, 2)
    # new distinct volume exceeds the limit of 2
    assert ov.max_pd_volume_count(
        make_pod("p", volumes=[ebs("v9")]), info, ctx, limits) == [False, True, True]
    # re-using an existing volume does not count as new
    assert ov.max_pd_volume_count(
        make_pod("p", volumes=[ebs("v1")]), info, ctx, limits) == [True, True, True]
    # no relevant volumes -> quick pass even at the limit
    assert ov.max_pd_volume_count(
        make_pod("p", volumes=[gce("g")]), info, ctx, limits)[0] is True
    # missing PVC counts as a unique volume toward every filter
    missing = make_pod("p", volumes=[pvc("nope")])
    assert ov.max_pd_volume_count(missing, info, ctx, limits)[0] is False


def test_volume_zone_tables():
    ctx = VolumeContext(
        pvs={"pv1": PersistentVolume("pv1", labels={ZONE_LABEL: "us-1a"}),
             "pv2": PersistentVolume("pv2", labels={REGION_LABEL: "us"})},
        pvcs={("default", "c1"): PersistentVolumeClaim("c1", volume_name="pv1"),
              ("default", "c2"): PersistentVolumeClaim("c2", volume_name="pv2")},
    )
    pod = make_pod("p", volumes=[pvc("c1")])
    same = _info(make_node("n1", labels={ZONE_LABEL: "us-1a"}))
    other = _info(make_node("n2", labels={ZONE_LABEL: "us-1b"}))
    nozone = _info(make_node("n3"))
    assert ov.no_volume_zone_conflict(pod, same, ctx)
    assert not ov.no_volume_zone_conflict(pod, other, ctx)
    assert ov.no_volume_zone_conflict(pod, nozone, ctx)  # fast-path
    # region-labeled PV vs zone-labeled node: missing region key fails
    pod2 = make_pod("p2", volumes=[pvc("c2")])
    assert not ov.no_volume_zone_conflict(pod2, same, ctx)
    region_node = _info(make_node("n4", labels={REGION_LABEL: "us"}))
    assert ov.no_volume_zone_conflict(pod2, region_node, ctx)
    # unresolvable claim -> error (pod fails the round)
    with pytest.raises(Exception):
        ov.no_volume_zone_conflict(
            make_pod("p3", volumes=[pvc("missing")]), same, ctx)


def test_volume_node_affinity_gated():
    ann = json.dumps({
        "requiredDuringSchedulingIgnoredDuringExecution": {
            "nodeSelectorTerms": [
                {"matchExpressions": [
                    {"key": "kubernetes.io/hostname", "operator": "In",
                     "values": ["n1"]}]}]}})
    ctx = VolumeContext(
        pvs={"local1": PersistentVolume(
            "local1", annotations={ALPHA_STORAGE_NODE_AFFINITY_ANNOTATION: ann})},
        pvcs={("default", "lc"): PersistentVolumeClaim("lc", volume_name="local1")},
    )
    pod = make_pod("p", volumes=[pvc("lc")])
    n1 = _info(make_node("n1", labels={"kubernetes.io/hostname": "n1"}))
    n2 = _info(make_node("n2", labels={"kubernetes.io/hostname": "n2"}))
    # gate off (default): predicate passes everywhere
    assert ov.no_volume_node_conflict(pod, n2, ctx)
    features.DEFAULT_FEATURE_GATE.set("PersistentLocalVolumes", True)
    try:
        assert ov.no_volume_node_conflict(pod, n1, ctx)
        assert not ov.no_volume_node_conflict(pod, n2, ctx)
    finally:
        features.DEFAULT_FEATURE_GATE.reset()


# ---------------------------------------------------------------- kernel


def _kernel_matrix(pods, nodes, bound=(), ctx=None):
    infos = node_info_map(nodes, list(bound))
    snap = ClusterSnapshot()
    snap.refresh(infos, volume_ctx=ctx or VolumeContext())
    # PodBatch interns volume keys; finalize_volumes (called inside) rebuilds
    # the node presence matrices from the cached per-row volume lists
    batch = PodBatch(pods, snap)
    m = np.asarray(fits_jit(pod_arrays(batch), node_arrays(snap)))
    return m, snap, infos, batch


def _rand_volume(rng):
    r = rng.random()
    ro = rng.random() < 0.4
    if r < 0.25:
        return gce(rng.choice(["pd1", "pd2", "pd3"]), ro=ro)
    if r < 0.5:
        return ebs(rng.choice(["v1", "v2", "v3"]), ro=ro)
    if r < 0.65:
        return rbd(rng.sample(["m1", "m2", "m3"], rng.randint(1, 2)),
                   rng.choice(["p1", "p2"]), rng.choice(["i1", "i2"]), ro=ro)
    if r < 0.8:
        return iscsi(rng.choice(["q1", "q2"]), ro=ro)
    if r < 0.9:
        return azure(rng.choice(["d1", "d2"]))
    return pvc(rng.choice(["c1", "c2", "c-missing"]))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_kernel_matches_oracle_with_volumes(seed):
    rng = random.Random(seed)
    ctx = VolumeContext(
        pvs={"pv1": PersistentVolume("pv1", source=gce("pd-shared"),
                                     labels={ZONE_LABEL: "us-1a"}),
             "pv2": PersistentVolume("pv2", source=ebs("ebs-shared"))},
        pvcs={("default", "c1"): PersistentVolumeClaim("c1", volume_name="pv1"),
              ("default", "c2"): PersistentVolumeClaim("c2", volume_name="pv2")},
    )
    nodes = []
    for i in range(12):
        labels = {}
        if rng.random() < 0.5:
            labels[ZONE_LABEL] = rng.choice(["us-1a", "us-1b"])
        nodes.append(make_node(f"vn-{i:02d}", labels=labels))
    names = [n.name for n in nodes]
    bound = []
    for i in range(25):
        vols = [_rand_volume(rng) for _ in range(rng.randint(0, 2))]
        p = make_pod(f"bound-{i}", volumes=vols, node_name=rng.choice(names))
        bound.append(p)
    pending = [make_pod(f"pend-{i}", cpu=100,
                        volumes=[_rand_volume(rng)
                                 for _ in range(rng.randint(0, 2))])
               for i in range(30)]

    import os
    os.environ["KUBE_MAX_PD_VOLS"] = "3"  # small limit to exercise MaxPD
    try:
        m, snap, infos, batch = _kernel_matrix(pending, nodes, bound, ctx)
        from kubernetes_tpu.ops.oracle_ext import SchedulingContext
        octx = SchedulingContext(infos, [], volume_ctx=ctx)
        mismatches = []
        for pi, pod in enumerate(pending):
            for ni, nm in enumerate(snap.node_names):
                expect = oracle.pod_fits(pod, infos[nm], octx)
                if batch.needs_host_check[pi]:
                    if expect and not m[pi, ni]:
                        mismatches.append((pod.name, nm, expect))
                elif bool(m[pi, ni]) != expect:
                    mismatches.append((pod.name, nm, expect, bool(m[pi, ni])))
        assert not mismatches, mismatches[:10]
    finally:
        del os.environ["KUBE_MAX_PD_VOLS"]


def test_batch_carry_sees_committed_volumes():
    """Two pods mounting the same EBS volume must land on different nodes —
    the on-device commit makes pod 0's volume visible to pod 1 (the assume
    semantics of scheduler.go:188 inside one device program)."""
    nodes = [make_node("a"), make_node("b")]
    infos = node_info_map(nodes, [])
    snap = ClusterSnapshot()
    snap.refresh(infos)
    pods = [make_pod("p0", cpu=100, volumes=[ebs("shared")]),
            make_pod("p1", cpu=100, volumes=[ebs("shared")]),
            make_pod("p2", cpu=100, volumes=[ebs("shared")])]
    batch = PodBatch(pods, snap)
    narr = node_arrays(snap)
    from kubernetes_tpu.engine.batch import node_state
    import jax.numpy as jnp
    from kubernetes_tpu.ops import priorities as prio
    # direct place_batch callers without AffinityData must strip the two
    # cluster-topology priorities (the engine does the same when no class
    # carries affinity/spread state — batch.py's guard rejects silent zeros)
    plain = tuple((nm, w) for nm, w in prio.DEFAULT_PRIORITIES
                  if nm not in prio.AFFINITY_PRIORITIES)
    selected, fit_counts, state, _ = place_batch(
        pod_arrays(batch), narr, node_state(narr), jnp.uint32(0), plain)
    sel = np.asarray(selected)
    assert sel[0] >= 0 and sel[1] >= 0
    assert sel[0] != sel[1]  # conflict forced apart
    assert sel[2] == -1  # only two nodes; third pod cannot fit anywhere
    assert int(np.asarray(fit_counts)[2]) == 0
