"""Client layer tests: workqueue invariants, informer sync/watch/index,
leader election state machine, event dedup.

Modeled on client-go's util/workqueue tests, tools/cache reflector tests,
and tools/leaderelection tests (behavioral shape, not a port).
"""

import threading

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.client.informer import SharedInformer, SharedInformerFactory, Store
from kubernetes_tpu.client.leaderelection import LeaderElector, LeaseLock
from kubernetes_tpu.client.record import EventRecorder
from kubernetes_tpu.client.workqueue import (
    ItemExponentialFailureRateLimiter,
    RateLimitingQueue,
    ShutDown,
    WorkQueue,
    parallelize,
)
from kubernetes_tpu.server.apiserver_lite import ApiServerLite


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------- workqueue


def test_workqueue_dedupes_adds():
    q = WorkQueue()
    q.add("a")
    q.add("a")
    q.add("b")
    assert len(q) == 2
    assert q.get(0) == "a"
    q.done("a")
    assert q.get(0) == "b"


def test_workqueue_requeues_item_added_while_processing():
    q = WorkQueue()
    q.add("a")
    item = q.get(0)
    q.add("a")  # re-add while in flight
    assert len(q) == 0  # parked in dirty, not queued
    q.done(item)
    assert q.get(0) == "a"  # exactly one requeue


def test_workqueue_shutdown_raises():
    q = WorkQueue()
    q.shut_down()
    with pytest.raises(ShutDown):
        q.get(0)


def test_rate_limiter_exponential_and_forget():
    rl = ItemExponentialFailureRateLimiter(base=1.0, max_delay=8.0)
    assert [rl.when("x") for _ in range(5)] == [1.0, 2.0, 4.0, 8.0, 8.0]
    rl.forget("x")
    assert rl.when("x") == 1.0


def test_rate_limiting_queue_add_after():
    clock = FakeClock()
    q = RateLimitingQueue(now=clock)
    q.add_after("later", 5.0)
    q.add("now")
    assert q.get(0) == "now"
    q.done("now")
    with pytest.raises(TimeoutError):
        q.get(0)
    clock.t = 5.0
    assert q.get(0) == "later"


def test_parallelize_covers_all_pieces():
    seen = set()
    lock = threading.Lock()

    def work(i):
        with lock:
            seen.add(i)

    parallelize(4, 100, work)
    assert seen == set(range(100))


# ----------------------------------------------------------------- store


def test_store_index_by_node():
    s = Store()
    s.add_index("node", lambda p: [p.node_name] if p.node_name else [])
    p1 = make_pod("a", node_name="n1")
    p2 = make_pod("b", node_name="n1")
    p3 = make_pod("c", node_name="n2")
    for p in (p1, p2, p3):
        s.upsert(p)
    assert {p.name for p in s.by_index("node", "n1")} == {"a", "b"}
    # move b to n2
    import dataclasses
    s.upsert(dataclasses.replace(p2, node_name="n2"))
    assert {p.name for p in s.by_index("node", "n1")} == {"a"}
    assert {p.name for p in s.by_index("node", "n2")} == {"b", "c"}
    s.remove(p3)
    assert {p.name for p in s.by_index("node", "n2")} == {"b"}


# --------------------------------------------------------------- informer


def test_informer_sync_then_watch_events():
    api = ApiServerLite()
    api.create("Node", make_node("n1"))
    inf = SharedInformer(api, "Node")
    adds, updates, deletes = [], [], []
    inf.add_event_handler(
        on_add=lambda o: adds.append(o.name),
        on_update=lambda old, new: updates.append(new.name),
        on_delete=lambda o: deletes.append(o.name),
    )
    inf.step()  # initial list
    assert inf.has_synced() and adds == ["n1"]
    api.create("Node", make_node("n2"))
    n1 = api.get("Node", "", "n1")
    import dataclasses
    api.update("Node", dataclasses.replace(n1, unschedulable=True))
    api.delete("Node", "", "n2")
    inf.step()
    assert adds == ["n1", "n2"]
    assert updates == ["n1"]
    assert deletes == ["n2"]
    assert inf.store.get("n1").unschedulable


def test_informer_relist_after_compaction():
    api = ApiServerLite(max_log=4)
    inf = SharedInformer(api, "Pod")
    inf.step()
    for i in range(20):  # blow past the bounded log
        api.create("Pod", make_pod(f"p{i}"))
    inf.step()  # TooOld -> relist
    inf.step()
    assert len(inf.store) == 20


def test_late_handler_gets_synthetic_adds():
    api = ApiServerLite()
    api.create("Node", make_node("n1"))
    inf = SharedInformer(api, "Node")
    inf.step()
    got = []
    inf.add_event_handler(on_add=lambda o: got.append(o.name))
    assert got == ["n1"]


def test_factory_shares_informers():
    api = ApiServerLite()
    f = SharedInformerFactory(api)
    assert f.informer("Pod") is f.informer("Pod")
    api.create("Pod", make_pod("p"))
    f.step_all()
    assert f.informer("Pod").store.get("default/p") is not None


# --------------------------------------------------------- leader election


def test_leader_election_acquire_steal_and_renew():
    api = ApiServerLite()
    clock = FakeClock()
    events = []
    a = LeaderElector(LeaseLock(api, "sched"), "A", lease_duration=15.0,
                      on_started_leading=lambda: events.append("A-start"),
                      on_stopped_leading=lambda: events.append("A-stop"),
                      now=clock)
    b = LeaderElector(LeaseLock(api, "sched"), "B", lease_duration=15.0,
                      on_started_leading=lambda: events.append("B-start"),
                      now=clock)
    assert a.step() and a.is_leader()
    assert not b.step()  # A's lease is live
    clock.t = 10.0
    assert a.step()  # renew
    clock.t = 20.0
    assert not b.step()  # renewed at t=10, expires t=25
    clock.t = 26.0
    assert b.step() and b.is_leader()  # steal expired lease
    assert not a.step()  # A deposed
    assert not a.is_leader()
    assert events == ["A-start", "B-start", "A-stop"]
    lease = api.get("Lease", "kube-system", "sched")
    assert lease.holder == "B" and lease.leader_transitions == 1


def test_leader_tolerates_transient_renew_failure_within_deadline():
    api = ApiServerLite()
    clock = FakeClock()
    stops = []
    a = LeaderElector(LeaseLock(api, "cm"), "A", lease_duration=15.0,
                      renew_deadline=10.0,
                      on_stopped_leading=lambda: stops.append("A"), now=clock)
    assert a.step()
    # interleaved write bumps the lease rv so A's next CAS fails transiently
    lease = api.get("Lease", "kube-system", "cm")
    import dataclasses
    api.update("Lease", dataclasses.replace(lease))
    clock.t = 5.0

    orig_update = a.lock.update
    calls = {"n": 0}

    def flaky_update(lease, expect_rv):
        calls["n"] += 1
        if calls["n"] == 1:
            from kubernetes_tpu.server.apiserver_lite import Conflict
            raise Conflict("transient")
        return orig_update(lease, expect_rv)

    a.lock.update = flaky_update
    assert not a.step()  # renew failed...
    assert a.is_leader() and stops == []  # ...but within the deadline window
    clock.t = 6.0
    assert a.step() and a.is_leader()  # recovered


# ------------------------------------------------------------------ events


def test_event_recorder_dedups_into_count():
    api = ApiServerLite()
    rec = EventRecorder(api, source="scheduler")
    for _ in range(3):
        rec.event("Pod", "default/p", "Warning", "FailedScheduling", "no fit")
    rec.event("Pod", "default/p", "Normal", "Scheduled", "bound to n1")
    evs, _ = api.list("Event")
    assert len(evs) == 2
    by_reason = {e.reason: e for e in evs}
    assert by_reason["FailedScheduling"].count == 3
    assert by_reason["Scheduled"].count == 1
