"""Wire-format tests: resource.Quantity parsing, k8s JSON Pod/Node decoding,
Policy JSON compat (the format guarded upstream by
plugin/pkg/scheduler/api/compatibility_test.go)."""

from kubernetes_tpu.api import serde
from kubernetes_tpu.api.policy import parse_policy, PROVIDERS
from kubernetes_tpu.api.types import SelectorOperator, TaintEffect


def test_quantity_parsing():
    assert serde.quantity_milli("100m") == 100
    assert serde.quantity_milli("2") == 2000
    assert serde.quantity_milli("0.5") == 500
    assert serde.quantity_milli("2500m") == 2500
    assert serde.quantity_value("128Mi") == 128 * 1024 * 1024
    assert serde.quantity_value("1Gi") == 1024 ** 3
    assert serde.quantity_value("1G") == 10 ** 9
    assert serde.quantity_value("500k") == 500_000
    assert serde.quantity_value("1.5Gi") == 3 * 1024 ** 3 // 2
    assert serde.quantity_value("7") == 7
    # milli rounding is ceil (quantity.go ScaledValue rounds up)
    assert serde.quantity_milli("1m") == 1
    assert serde.quantity_value("100m") == 1


def test_decode_pod_full():
    pod = serde.decode_pod({
        "metadata": {"name": "web-1", "namespace": "prod", "uid": "u-1",
                     "labels": {"app": "web"},
                     "ownerReferences": [{"kind": "ReplicaSet", "name": "web",
                                          "controller": True}]},
        "spec": {
            "schedulerName": "default-scheduler",
            "nodeSelector": {"disk": "ssd"},
            "containers": [{
                "name": "c", "image": "nginx:1.13",
                "resources": {"requests": {"cpu": "250m", "memory": "64Mi",
                                           "nvidia.com/gpu": "1"}},
                "ports": [{"hostPort": 8080, "containerPort": 80}],
            }],
            "tolerations": [{"key": "dedicated", "operator": "Equal",
                             "value": "gpu", "effect": "NoSchedule"}],
            "affinity": {"nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [{"matchExpressions": [
                        {"key": "zone", "operator": "In", "values": ["a", "b"]}]}]},
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {"weight": 5, "preference": {"matchExpressions": [
                        {"key": "disk", "operator": "Exists"}]}}]}},
        },
    })
    assert pod.key() == "prod/web-1"
    req = pod.resource_request()
    assert req.milli_cpu == 250
    assert req.memory == 64 * 1024 * 1024
    assert req.nvidia_gpu == 1
    assert pod.used_ports() == [8080]
    assert pod.owner_kind == "ReplicaSet"
    assert pod.tolerations[0].effect == TaintEffect.NO_SCHEDULE
    na = pod.affinity.node_affinity
    assert na.required_terms[0].match_expressions[0].operator == SelectorOperator.IN
    assert na.preferred_terms[0][0] == 5


def test_decode_node_full():
    node = serde.decode_node({
        "metadata": {"name": "n1", "labels": {"zone": "a"}},
        "spec": {"unschedulable": False,
                 "taints": [{"key": "flaky", "value": "", "effect": "NoExecute"}]},
        "status": {
            "allocatable": {"cpu": "4", "memory": "32Gi", "pods": "110",
                            "nvidia.com/gpu": "2", "example.com/foo": "5"},
            "conditions": [{"type": "Ready", "status": "True"},
                           {"type": "MemoryPressure", "status": "False"}],
        },
    })
    assert node.allocatable.milli_cpu == 4000
    assert node.allocatable.memory == 32 * 1024 ** 3
    assert node.allocatable.nvidia_gpu == 2
    assert node.allocatable.extended == {"example.com/foo": 5}
    assert node.allowed_pod_number == 110
    assert node.taints[0].effect == TaintEffect.NO_EXECUTE
    assert node.is_ready()


def test_encode_decode_roundtrip():
    from kubernetes_tpu.api.types import make_node, make_pod
    pod = make_pod("p", cpu=100, memory=1024 ** 3, ports=[80])
    pod2 = serde.decode_pod(serde.encode_pod(pod))
    assert pod2.resource_request().milli_cpu == 100
    assert pod2.used_ports() == [80]
    node = make_node("n", cpu=4000, gpu=2)
    node2 = serde.decode_node(serde.encode_node(node))
    assert node2.allocatable.milli_cpu == 4000
    assert node2.allocatable.nvidia_gpu == 2
    assert node2.is_ready()


POLICY_JSON = """{
  "kind": "Policy", "apiVersion": "v1",
  "predicates": [
    {"name": "PodFitsResources"},
    {"name": "TestLabelsPresence",
     "argument": {"labelsPresence": {"labels": ["foo"], "presence": true}}},
    {"name": "TestServiceAffinity",
     "argument": {"serviceAffinity": {"labels": ["region"]}}}
  ],
  "priorities": [
    {"name": "LeastRequestedPriority", "weight": 1},
    {"name": "TestServiceAntiAffinity", "weight": 3,
     "argument": {"serviceAntiAffinity": {"label": "zone"}}}
  ],
  "extenders": [
    {"urlPrefix": "http://127.0.0.1:9998/scheduler",
     "filterVerb": "filter", "prioritizeVerb": "prioritize",
     "weight": 5, "nodeCacheCapable": true, "enableHttps": false}
  ]
}"""


def test_policy_parse_reference_format():
    # shape mirrors the 1.7 Policy files in compatibility_test.go
    pol = parse_policy(POLICY_JSON)
    assert [p.name for p in pol.predicates] == [
        "PodFitsResources", "TestLabelsPresence", "TestServiceAffinity"]
    assert pol.predicates[1].labels_presence.labels == ["foo"]
    assert pol.predicates[2].service_affinity.labels == ["region"]
    assert pol.priorities[0].weight == 1
    assert pol.priorities[1].service_antiaffinity_label == "zone"
    ext = pol.extenders[0]
    assert ext.url_prefix.endswith("/scheduler")
    assert ext.filter_verb == "filter"
    assert ext.weight == 5
    assert ext.node_cache_capable
    assert ext.http_timeout_s == 5.0


def test_policy_empty_sections_distinguish_nil():
    # nil predicates -> provider defaults; empty list -> no predicates
    assert parse_policy("{}").predicates is None
    assert parse_policy('{"predicates": []}').predicates == []


def test_providers():
    dp = PROVIDERS["DefaultProvider"]["priorities"]
    ca = PROVIDERS["ClusterAutoscalerProvider"]["priorities"]
    assert ("LeastRequestedPriority", 1) in dp
    assert ("MostRequestedPriority", 1) in ca
    assert ("LeastRequestedPriority", 1) not in ca
    assert ("NodePreferAvoidPodsPriority", 10000) in dp


def test_decode_pod_owner_uid():
    # regression: uid drop silently disabled NodePreferAvoidPods matching
    pod = serde.decode_pod({
        "metadata": {"name": "p", "ownerReferences": [
            {"kind": "ReplicaSet", "name": "rs", "uid": "u-42",
             "controller": True}]},
        "spec": {"containers": []}})
    assert pod.owner_uid == "u-42"
    assert pod.owner_kind == "ReplicaSet"


# ------------------------------------------------ scheme (runtime.Scheme)


def test_scheme_scheduler_config_roundtrip_and_defaults():
    from kubernetes_tpu.api.scheme import (
        DEFAULT_SCHEME,
        KubeSchedulerConfiguration,
        SchemeError,
    )

    # defaults applied at decode (v1alpha1 defaults.go)
    cfg = DEFAULT_SCHEME.decode({
        "apiVersion": "componentconfig/v1alpha1",
        "kind": "KubeSchedulerConfiguration"})
    assert cfg.scheduler_name == "default-scheduler"
    assert cfg.leader_election.leader_elect is True
    assert cfg.leader_election.lease_duration_s == 15.0
    assert cfg.hard_pod_affinity_symmetric_weight == 1
    # explicit fields survive a versioned round-trip
    cfg2 = DEFAULT_SCHEME.decode({
        "apiVersion": "componentconfig/v1alpha1",
        "kind": "KubeSchedulerConfiguration",
        "schedulerName": "tpu-scheduler",
        "policyConfigFile": "/etc/policy.json",
        "featureGates": "PodPriority=true,AllAlpha=false",
        "leaderElection": {"leaseDuration": "1m30s",
                           "leaderElect": False}})
    assert cfg2.leader_election.lease_duration_s == 90.0
    assert cfg2.feature_gates == {"PodPriority": True, "AllAlpha": False}
    wire = DEFAULT_SCHEME.encode(cfg2, "componentconfig/v1alpha1",
                                 "KubeSchedulerConfiguration")
    assert wire["apiVersion"] == "componentconfig/v1alpha1"
    again = DEFAULT_SCHEME.decode(wire)
    assert again == cfg2
    # unknown version fails loudly
    import pytest as _pytest
    with _pytest.raises(SchemeError):
        DEFAULT_SCHEME.decode({"apiVersion": "componentconfig/v9",
                               "kind": "KubeSchedulerConfiguration"})
    # validation: the weight range check
    with _pytest.raises(SchemeError):
        DEFAULT_SCHEME.decode({
            "apiVersion": "componentconfig/v1alpha1",
            "kind": "KubeSchedulerConfiguration",
            "hardPodAffinitySymmetricWeight": 1000})


def test_scheme_duration_parsing():
    from kubernetes_tpu.api.scheme import SchemeError, _seconds

    assert _seconds("15s") == 15.0
    assert _seconds("1m30s") == 90.0
    assert _seconds("2h") == 7200.0
    assert _seconds("250ms") == 0.25
    assert _seconds(7) == 7.0
    import pytest as _pytest
    for bad in ("15", "s", "1x"):
        with _pytest.raises(SchemeError):
            _seconds(bad)


def test_scheme_policy_v1_decodes_through_parser():
    from kubernetes_tpu.api.scheme import DEFAULT_SCHEME

    pol = DEFAULT_SCHEME.decode({
        "apiVersion": "v1", "kind": "Policy",
        "predicates": [{"name": "PodFitsResources"}],
        "priorities": [{"name": "LeastRequestedPriority", "weight": 1}]})
    assert [p.name for p in pol.predicates] == ["PodFitsResources"]
    # the unversioned legacy shape (--use-legacy-policy-config) decodes too
    pol2 = DEFAULT_SCHEME.decode({
        "apiVersion": "", "kind": "Policy",
        "predicates": [{"name": "PodFitsHostPorts"}]})
    assert [p.name for p in pol2.predicates] == ["PodFitsHostPorts"]
