"""Chain-level tests for the round-5 static-plugin sweep: podpreset,
antiaffinity, exec, gc, persistentvolume (plugin/pkg/admission/<dir>
analogs in admission/plugins.py), each driven through a real
AdmissionChain + ApiServer where the operation exists."""

import pytest

from kubernetes_tpu.admission.chain import (
    AdmissionChain,
    AdmissionRequest,
    CONNECT,
    Rejected,
)
from kubernetes_tpu.admission.plugins import (
    DenyEscalatingExec,
    LimitPodHardAntiAffinityTopology,
    OwnerReferencesPermissionEnforcement,
    PersistentVolumeLabel,
    PodPreset,
    PodPresetPlugin,
)
from kubernetes_tpu.api.rbac import UserInfo
from kubernetes_tpu.api.types import (
    Affinity,
    PersistentVolume,
    PodAffinity,
    PodAffinityTerm,
    SecurityContext,
    Volume,
    VolumeKind,
    make_pod,
)
from kubernetes_tpu.cloud.provider import FakeCloud
from kubernetes_tpu.ops.oracle_ext import ZONE_LABEL, ZONE_REGION_LABEL
from kubernetes_tpu.server.apiserver import ApiServer


def mk_server(*plugins):
    api = ApiServer()
    api.admission = AdmissionChain(list(plugins), store=api.store)
    return api


# ------------------------------------------------------------- podpreset


def test_podpreset_injects_into_matching_pods():
    api = mk_server(PodPresetPlugin())
    api.store.create("PodPreset", PodPreset(
        name="env", selector={"app": "web"},
        annotations={"preset/DB_HOST": "db.local"},
        volumes=[Volume(name="cache")]))
    pod = make_pod("p", cpu=10, labels={"app": "web"})
    api.create("Pod", pod)
    stored = api.store.get("Pod", "default", "p")
    assert stored.annotations["preset/DB_HOST"] == "db.local"
    assert any(v.name == "cache" for v in stored.volumes)
    # the applied preset is stamped (reference bookkeeping annotation)
    assert stored.annotations[
        "podpreset.admission.kubernetes.io/podpreset-env"] != ""
    # non-matching pod untouched
    other = make_pod("q", cpu=10, labels={"app": "api"})
    api.create("Pod", other)
    assert "preset/DB_HOST" not in \
        api.store.get("Pod", "default", "q").annotations


def test_podpreset_conflict_skips_all_presets_without_rejecting():
    api = mk_server(PodPresetPlugin())
    api.store.create("PodPreset", PodPreset(
        name="a", selector={"app": "web"},
        annotations={"preset/KEY": "from-a"}))
    api.store.create("PodPreset", PodPreset(
        name="b", selector={"app": "web"},
        annotations={"preset/KEY": "from-b"}))  # conflicting value
    pod = make_pod("p", cpu=10, labels={"app": "web"})
    api.create("Pod", pod)  # admitted, NOT rejected
    stored = api.store.get("Pod", "default", "p")
    assert "preset/KEY" not in stored.annotations  # nothing injected
    assert not any(k.startswith("podpreset.admission")
                   for k in stored.annotations)


# ----------------------------------------------------------- antiaffinity


def test_hard_antiaffinity_topology_limited_to_hostname():
    api = mk_server(LimitPodHardAntiAffinityTopology())
    ok = make_pod("ok", cpu=10)
    ok.affinity = Affinity(pod_anti_affinity=PodAffinity(required_terms=[
        PodAffinityTerm(topology_key="kubernetes.io/hostname")]))
    api.create("Pod", ok)
    bad = make_pod("bad", cpu=10)
    bad.affinity = Affinity(pod_anti_affinity=PodAffinity(required_terms=[
        PodAffinityTerm(
            topology_key="failure-domain.beta.kubernetes.io/zone")]))
    with pytest.raises(Rejected) as e:
        api.create("Pod", bad)
    assert "topologyKey" in str(e.value)


# ------------------------------------------------------------------ exec


def test_deny_escalating_exec():
    chain = AdmissionChain([DenyEscalatingExec()])
    priv = make_pod("priv", cpu=10)
    priv.containers[0].security_context = SecurityContext(privileged=True)
    with pytest.raises(Rejected):
        chain.admit(AdmissionRequest(CONNECT, "Pod", "default", "priv",
                                     obj=priv, subresource="exec"))
    hostnet = make_pod("hn", cpu=10)
    hostnet.host_network = True
    with pytest.raises(Rejected):
        chain.admit(AdmissionRequest(CONNECT, "Pod", "default", "hn",
                                     obj=hostnet, subresource="attach"))
    # plain pod execs fine; non-exec subresources are not handled
    chain.admit(AdmissionRequest(CONNECT, "Pod", "default", "ok",
                                 obj=make_pod("ok", cpu=10),
                                 subresource="exec"))
    chain.admit(AdmissionRequest(CONNECT, "Pod", "default", "priv",
                                 obj=priv, subresource="portforward"))


# -------------------------------------------------------------------- gc


def test_owner_references_need_delete_permission():
    def authorize(user, verb, kind, namespace):
        return user is not None and user.name == "controller"

    chain = AdmissionChain([OwnerReferencesPermissionEnforcement(authorize)])
    owned = make_pod("p", cpu=10, owner=("ReplicaSet", "rs-1"))
    with pytest.raises(Rejected):
        chain.admit(AdmissionRequest(
            "CREATE", "Pod", "default", "p", obj=owned,
            user=UserInfo("mallory")))
    # the rightful controller may set owner refs
    chain.admit(AdmissionRequest(
        "CREATE", "Pod", "default", "p", obj=owned,
        user=UserInfo("controller")))
    # updates that do NOT touch owner refs pass for anyone
    old = make_pod("q", cpu=10, owner=("ReplicaSet", "rs-1"))
    new = make_pod("q", cpu=10, owner=("ReplicaSet", "rs-1"))
    new.labels["x"] = "y"
    chain.admit(AdmissionRequest(
        "UPDATE", "Pod", "default", "q", obj=new, old_obj=old,
        user=UserInfo("mallory")))
    # updates that CHANGE owner refs are gated
    stolen = make_pod("q", cpu=10, owner=("ReplicaSet", "rs-2"))
    with pytest.raises(Rejected):
        chain.admit(AdmissionRequest(
            "UPDATE", "Pod", "default", "q", obj=stolen, old_obj=old,
            user=UserInfo("mallory")))


# -------------------------------------------------- persistentvolume/label


def test_persistent_volume_label_stamps_cloud_zone():
    cloud = FakeCloud()
    cloud.create_disk("disk-1", zone="zone-b", region="region-2")
    api = mk_server(PersistentVolumeLabel(cloud))
    pv = PersistentVolume(
        name="pv-1", source=Volume(kind=VolumeKind.GCE_PD,
                                   volume_id="disk-1"),
        labels={ZONE_LABEL: "client-lie"})
    api.create("PersistentVolume", pv)
    stored = api.store.get("PersistentVolume", "", "pv-1")
    # the cloud is authoritative: the client-supplied zone is overwritten
    assert stored.labels[ZONE_LABEL] == "zone-b"
    assert stored.labels[ZONE_REGION_LABEL] == "region-2"
    # non-cloud PVs untouched
    nfs = PersistentVolume(name="pv-2",
                           source=Volume(kind=VolumeKind.OTHER,
                                         volume_id="srv:/export"))
    api.create("PersistentVolume", nfs)
    assert ZONE_LABEL not in api.store.get(
        "PersistentVolume", "", "pv-2").labels
    # a PV referencing a disk the cloud never made is rejected, not
    # stamped with a fabricated zone
    ghost = PersistentVolume(name="pv-3",
                             source=Volume(kind=VolumeKind.GCE_PD,
                                           volume_id="no-such-disk"))
    with pytest.raises(Rejected):
        api.create("PersistentVolume", ghost)
