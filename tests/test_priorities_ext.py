"""Golden tests for the second wave of priorities: NodeAffinity(preferred),
NodePreferAvoidPods, ImageLocality (kernel vs oracle), and the oracle-only
SelectorSpread / InterPodAffinity implementations against hand-built tables
in the style of the reference's *_test.go files."""

import json
import random

import numpy as np
import pytest

from kubernetes_tpu.api.types import (
    Affinity,
    ContainerImage,
    NodeAffinity,
    NodeSelectorTerm,
    PodAffinity,
    PodAffinityTerm,
    LabelSelector,
    SelectorOperator,
    SelectorRequirement,
    WorkloadObject,
    make_node,
    make_pod,
)
from kubernetes_tpu.ops import oracle, oracle_ext
from kubernetes_tpu.ops import priorities as prio
from kubernetes_tpu.ops.predicates import node_arrays, pod_arrays
from kubernetes_tpu.state.node_info import node_info_map
from kubernetes_tpu.state.snapshot import AVOID_PODS_ANNOTATION, ClusterSnapshot, PodBatch
from tests.helpers import Gi, Mi


def build(pods, nodes, bound=()):
    infos = node_info_map(nodes, list(bound))
    snap = ClusterSnapshot()
    snap.refresh(infos)
    batch = PodBatch(pods, snap)
    return pod_arrays(batch), node_arrays(snap), snap, infos


def kernel_scores(pods, nodes, pset, bound=()):
    parrs, narrs, snap, infos = build(pods, nodes, bound)
    import jax.numpy as jnp
    fits = jnp.asarray(np.ones((len(pods), narrs["alloc"].shape[0]), dtype=bool))
    got = np.asarray(prio.score(parrs, narrs, pset, fits))
    return got[:, : len(snap.node_names)], snap, infos


def test_node_affinity_priority_matches_oracle():
    nodes = [make_node("n0", labels={"disk": "ssd", "zone": "a"}),
             make_node("n1", labels={"disk": "hdd", "zone": "a"}),
             make_node("n2", labels={"zone": "b"})]
    pod = make_pod("p")
    pod.affinity = Affinity(node_affinity=NodeAffinity(preferred_terms=[
        (5, NodeSelectorTerm([SelectorRequirement("disk", SelectorOperator.IN, ["ssd"])])),
        (3, NodeSelectorTerm([SelectorRequirement("zone", SelectorOperator.IN, ["a"])])),
        (2, NodeSelectorTerm([])),  # empty term matches everything
    ]))
    got, snap, infos = kernel_scores([pod], nodes, (("NodeAffinityPriority", 1),))
    ordered = [infos[nm] for nm in snap.node_names]
    want = oracle_ext.node_affinity_scores(pod, ordered)
    # counts: n0=10, n1=5, n2=2 -> scores int(10*c/10) = [10, 5, 2]
    assert list(got[0]) == want == [10, 5, 2]


def test_node_affinity_priority_no_preferences_scores_zero():
    nodes = [make_node("n0"), make_node("n1")]
    got, snap, infos = kernel_scores([make_pod("p")], nodes,
                                     (("NodeAffinityPriority", 1),))
    assert list(got[0]) == [0, 0]


def test_prefer_avoid_pods_matches_oracle():
    annotation = json.dumps({"preferAvoidPods": [
        {"podSignature": {"podController": {"kind": "ReplicaSet",
                                            "uid": "rs-1",
                                            "apiVersion": "v1"}},
         "reason": "some reason"}]})
    n0 = make_node("n0")
    n0.annotations[AVOID_PODS_ANNOTATION] = annotation
    n1 = make_node("n1")
    owned = make_pod("owned")
    owned.owner_kind, owned.owner_uid = "ReplicaSet", "rs-1"
    other_rs = make_pod("other")
    other_rs.owner_kind, other_rs.owner_uid = "ReplicaSet", "rs-2"
    bare = make_pod("bare")
    got, snap, infos = kernel_scores(
        [owned, other_rs, bare], [n0, n1],
        (("NodePreferAvoidPodsPriority", 1),))
    ordered = [infos[nm] for nm in snap.node_names]
    for i, pod in enumerate([owned, other_rs, bare]):
        assert list(got[i]) == oracle_ext.prefer_avoid_scores(pod, ordered)
    col = {nm: i for i, nm in enumerate(snap.node_names)}
    assert got[0, col["n0"]] == 0 and got[0, col["n1"]] == 10
    assert got[1, col["n0"]] == 10  # different RS uid not avoided
    assert got[2, col["n0"]] == 10  # non-controller pod never avoided


def test_image_locality_matches_oracle():
    Mi_ = 1024 * 1024
    n0 = make_node("n0")
    n0.images = [ContainerImage(["nginx:1.13"], 500 * Mi_),
                 ContainerImage(["redis:3.2", "redis:latest"], 100 * Mi_)]
    n1 = make_node("n1")
    n1.images = [ContainerImage(["nginx:1.13"], 10 * Mi_)]  # < 23MB floor
    n2 = make_node("n2")
    pod = make_pod("p")
    pod.containers[0].image = "nginx:1.13"
    got, snap, infos = kernel_scores([pod], [n0, n1, n2],
                                     (("ImageLocalityPriority", 1),))
    ordered = [infos[nm] for nm in snap.node_names]
    want = oracle_ext.image_locality_scores(pod, ordered)
    assert list(got[0]) == want
    col = {nm: i for i, nm in enumerate(snap.node_names)}
    # 500MB -> int(10*(500-23)/(1000-23))+1 = 5 ; below floor -> 0 ; absent -> 0
    assert got[0, col["n0"]] == 5
    assert got[0, col["n1"]] == 0
    assert got[0, col["n2"]] == 0


def test_selector_spread_oracle_zone_weighting():
    zoneA = {"failure-domain.beta.kubernetes.io/zone": "a"}
    zoneB = {"failure-domain.beta.kubernetes.io/zone": "b"}
    nodes = [make_node("a0", labels=zoneA), make_node("a1", labels=zoneA),
             make_node("b0", labels=zoneB)]
    svc = WorkloadObject("Service", "web", "default", match_labels={"app": "web"})
    bound = []
    for i, nm in enumerate(["a0", "a0", "a1"]):
        p = make_pod(f"w{i}", labels={"app": "web"})
        p.node_name = nm
        bound.append(p)
    infos = node_info_map(nodes, bound)
    ctx = oracle_ext.SchedulingContext(infos, [svc])
    pod = make_pod("new", labels={"app": "web"})
    ordered = [infos[nm] for nm in sorted(infos)]
    scores = oracle_ext.selector_spread_scores(pod, ordered, ctx)
    by = dict(zip(sorted(infos), scores))
    # counts: a0=2, a1=1, b0=0; zoneA=3, zoneB=0; maxNode=2, maxZone=3
    # a0: node (2-2)/2*10=0,  zone 0   -> 0
    # a1: node (2-1)/2*10=5,  zone 0   -> 5*(1/3) = 1
    # b0: node 10, zone 10             -> 10
    assert by == {"a0": 0, "a1": 1, "b0": 10}


def test_selector_spread_no_owners_scores_max():
    nodes = [make_node("n0"), make_node("n1")]
    infos = node_info_map(nodes, [])
    ctx = oracle_ext.SchedulingContext(infos, [])
    scores = oracle_ext.selector_spread_scores(
        make_pod("p"), [infos["n0"], infos["n1"]], ctx)
    assert scores == [10, 10]


def _aff_term(labels, key="zone", namespaces=()):
    return PodAffinityTerm(
        label_selector=LabelSelector(match_labels=labels),
        namespaces=list(namespaces), topology_key=key)


def test_interpod_affinity_predicate_oracle():
    zoneA = {"zone": "a"}
    zoneB = {"zone": "b"}
    nodes = [make_node("na", labels=zoneA), make_node("nb", labels=zoneB)]
    store = make_pod("store", labels={"app": "store"})
    store.node_name = "na"
    infos = node_info_map(nodes, [store])
    ctx = oracle_ext.SchedulingContext(infos)
    # required affinity to app=store in same zone -> only na
    web = make_pod("web")
    web.affinity = Affinity(pod_affinity=PodAffinity(
        required_terms=[_aff_term({"app": "store"})]))
    assert oracle_ext.inter_pod_affinity_fits(web, nodes[0], ctx)
    assert not oracle_ext.inter_pod_affinity_fits(web, nodes[1], ctx)
    # required anti-affinity to app=store in same zone -> only nb
    anti = make_pod("anti")
    anti.affinity = Affinity(pod_anti_affinity=PodAffinity(
        required_terms=[_aff_term({"app": "store"})]))
    assert not oracle_ext.inter_pod_affinity_fits(anti, nodes[0], ctx)
    assert oracle_ext.inter_pod_affinity_fits(anti, nodes[1], ctx)


def test_interpod_affinity_bootstrap_self_match():
    # first pod of a self-referencing group may schedule anywhere
    nodes = [make_node("na", labels={"zone": "a"})]
    infos = node_info_map(nodes, [])
    ctx = oracle_ext.SchedulingContext(infos)
    first = make_pod("first", labels={"app": "db"})
    first.affinity = Affinity(pod_affinity=PodAffinity(
        required_terms=[_aff_term({"app": "db"})]))
    assert oracle_ext.inter_pod_affinity_fits(first, nodes[0], ctx)
    # but a pod NOT matching its own term is stuck when no match exists
    wannabe = make_pod("wannabe", labels={"app": "web"})
    wannabe.affinity = Affinity(pod_affinity=PodAffinity(
        required_terms=[_aff_term({"app": "db"})]))
    assert not oracle_ext.inter_pod_affinity_fits(wannabe, nodes[0], ctx)


def test_interpod_existing_anti_affinity_symmetry():
    # an existing pod's required anti-affinity blocks the incoming pod
    nodes = [make_node("na", labels={"zone": "a"}),
             make_node("nb", labels={"zone": "b"})]
    guard = make_pod("guard", labels={"app": "guard"})
    guard.node_name = "na"
    guard.affinity = Affinity(pod_anti_affinity=PodAffinity(
        required_terms=[_aff_term({"app": "web"})]))
    infos = node_info_map(nodes, [guard])
    ctx = oracle_ext.SchedulingContext(infos)
    web = make_pod("web", labels={"app": "web"})
    assert not oracle_ext.inter_pod_affinity_fits(web, nodes[0], ctx)
    assert oracle_ext.inter_pod_affinity_fits(web, nodes[1], ctx)
    # unrelated pod unaffected
    other = make_pod("other", labels={"app": "other"})
    assert oracle_ext.inter_pod_affinity_fits(other, nodes[0], ctx)


def test_interpod_affinity_priority_counts():
    zoneA = {"zone": "a"}
    zoneB = {"zone": "b"}
    nodes = [make_node("na", labels=zoneA), make_node("nb", labels=zoneB)]
    store = make_pod("store", labels={"app": "store"})
    store.node_name = "na"
    infos = node_info_map(nodes, [store])
    ctx = oracle_ext.SchedulingContext(infos)
    pod = make_pod("web")
    pod.affinity = Affinity(pod_affinity=PodAffinity(
        preferred_terms=[(10, _aff_term({"app": "store"}))]))
    ordered = [infos[nm] for nm in sorted(infos)]
    scores = oracle_ext.interpod_affinity_scores(pod, ordered, ctx)
    # na gets +10 (same zone as store), nb 0 -> normalized [10, 0]
    assert scores == [10, 0]


def test_engine_schedules_affinity_pods_via_host_path():
    """End-to-end through the engine: affinity pods take the oracle path and
    land correctly relative to device-placed pods."""
    from kubernetes_tpu.engine.scheduler_engine import SchedulingEngine
    from kubernetes_tpu.state.cache import SchedulerCache
    cache = SchedulerCache()
    cache.add_node(make_node("na", labels={"zone": "a"}))
    cache.add_node(make_node("nb", labels={"zone": "b"}))
    eng = SchedulingEngine(cache)
    store = make_pod("store", labels={"app": "store"},
                     node_selector={"zone": "a"})
    [r] = eng.schedule([store])
    assert r.node_name == "na"
    web = make_pod("web", labels={"app": "web"})
    web.affinity = Affinity(pod_affinity=PodAffinity(
        required_terms=[_aff_term({"app": "store"})]))
    [r2] = eng.schedule([web])
    assert r2.node_name == "na"
    anti = make_pod("anti")
    anti.affinity = Affinity(pod_anti_affinity=PodAffinity(
        required_terms=[_aff_term({"app": "store"})]))
    [r3] = eng.schedule([anti])
    assert r3.node_name == "nb"


def test_engine_symmetry_blocks_non_affinity_pod():
    """Regression: a plain pod matching an EXISTING pod's required
    anti-affinity must not be placed by the device fast path onto a
    conflicting topology (predicates.go:1146 symmetry)."""
    from kubernetes_tpu.engine.scheduler_engine import SchedulingEngine
    from kubernetes_tpu.state.cache import SchedulerCache
    cache = SchedulerCache()
    cache.add_node(make_node("na", labels={"zone": "a"}))
    cache.add_node(make_node("nb", labels={"zone": "b"}))
    guard = make_pod("guard", labels={"app": "guard"})
    guard.node_name = "na"
    guard.affinity = Affinity(pod_anti_affinity=PodAffinity(
        required_terms=[_aff_term({"app": "web"})]))
    cache.add_pod(guard)
    eng = SchedulingEngine(cache)
    web = make_pod("web", labels={"app": "web"})  # NO affinity of its own
    [r] = eng.schedule([web])
    assert r.node_name == "nb"
    # and a second web pod has nowhere to go once nb hosts... nothing blocks
    # nb, so it also lands on nb
    web2 = make_pod("web2", labels={"app": "web"})
    [r2] = eng.schedule([web2])
    assert r2.node_name == "nb"
    # unrelated pod is unaffected and uses the fast path
    other = make_pod("other", labels={"app": "other"})
    [r3] = eng.schedule([other])
    assert r3.node_name is not None
