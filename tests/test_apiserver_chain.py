"""The apiserver handler chain: authn -> authz (RBAC/Node) -> admission ->
strategy -> store, plus subresources (eviction+PDB, scale, namespace
two-phase delete) and the audit trail.

Harness shape mirrors the reference's apiserver integration tests (in-process
server, table-driven identities) — test/integration/auth, plugin/pkg/
admission/*/admission_test.go."""

import pytest

from kubernetes_tpu.admission import AdmissionChain, Rejected, default_plugins
from kubernetes_tpu.api.cluster import (
    Eviction,
    LimitRange,
    LimitRangeItem,
    PodDisruptionBudget,
    ResourceQuota,
    ServiceAccount,
)
from kubernetes_tpu.api.rbac import (
    PolicyRule,
    Role,
    RoleBinding,
    RoleRef,
    Subject,
)
from kubernetes_tpu.api.types import Binding, LabelSelector, make_node, make_pod
from kubernetes_tpu.api.workloads import Namespace, ReplicaSet
from kubernetes_tpu.auth.authn import (
    BootstrapTokenAuthenticator,
    CertAuthenticator,
    Credential,
    ServiceAccountTokenAuthenticator,
    TokenAuthenticator,
    Unauthenticated,
    UnionAuthenticator,
)
from kubernetes_tpu.auth.authz import Forbidden
from kubernetes_tpu.api.rbac import UserInfo
from kubernetes_tpu.server.apiserver import ApiServer, Invalid, TooManyRequests

Mi = 1024 * 1024
Gi = 1024 * Mi


def make_server(auth=False, tokens=None):
    authn = UnionAuthenticator([
        TokenAuthenticator(tokens or {}),
        ServiceAccountTokenAuthenticator(b"sa-signing-key"),
        CertAuthenticator(b"ca-key"),
    ])
    api = ApiServer(auth=auth, authenticator=authn)
    api.store.create("Namespace", Namespace("default"))
    api.bootstrap_rbac()
    return api


# ------------------------------------------------------------------- authn

def test_union_authenticator_and_token_auth():
    api = make_server(auth=True, tokens={
        "secret-token": UserInfo("alice", groups=["system:masters"])})
    cred = Credential(token="secret-token")
    api.create("Pod", make_pod("p1"), cred=cred)
    assert api.get("Pod", "default", "p1", cred=cred).name == "p1"
    with pytest.raises(Unauthenticated):
        api.create("Pod", make_pod("p2"), cred=Credential(token="wrong"))


def test_service_account_jwt_roundtrip():
    sa = ServiceAccountTokenAuthenticator(b"key")
    tok = sa.issue("kube-system", "builder", uid="u1")
    user = sa.authenticate(Credential(token=tok))
    assert user.name == "system:serviceaccount:kube-system:builder"
    assert "system:serviceaccounts" in user.groups
    assert sa.authenticate(Credential(token=tok[:-2] + "xx")) is None


def test_bootstrap_token_expiry_and_revoke():
    clock = [0.0]
    bt = BootstrapTokenAuthenticator(now=lambda: clock[0])
    bt.add_token("abc123", "s3cret", ttl=10)
    u = bt.authenticate(Credential(token="abc123.s3cret"))
    assert u.name == "system:bootstrap:abc123"
    clock[0] = 11
    assert bt.authenticate(Credential(token="abc123.s3cret")) is None
    assert bt.expired_ids() == ["abc123"]


def test_cert_authenticator_rejects_forged_groups():
    ca = CertAuthenticator(b"ca")
    cert = ca.sign("bob", ["dev"])
    assert ca.authenticate(Credential(cert=cert)).name == "bob"
    cert["orgs"] = ["system:masters"]  # forge
    assert ca.authenticate(Credential(cert=cert)) is None


# ------------------------------------------------------------------- authz

def test_rbac_namespaced_role_binding():
    api = make_server(auth=True, tokens={
        "admin": UserInfo("root", groups=["system:masters"]),
        "dev": UserInfo("dev-user")})
    admin = Credential(token="admin")
    dev = Credential(token="dev")
    api.store.create("Role", Role("pod-reader", "default", rules=[
        PolicyRule(verbs=["get", "list"], resources=["pods"])]))
    api.store.create("RoleBinding", RoleBinding(
        "read-pods", "default",
        subjects=[Subject("User", "dev-user")],
        role_ref=RoleRef("Role", "pod-reader")))
    api.create("Pod", make_pod("p1"), cred=admin)
    assert api.get("Pod", "default", "p1", cred=dev).name == "p1"
    with pytest.raises(Forbidden):
        api.create("Pod", make_pod("p2"), cred=dev)
    with pytest.raises(Forbidden):
        api.delete("Pod", "default", "p1", cred=dev)


def test_scheduler_bootstrap_role_allows_binding():
    api = make_server(auth=True, tokens={
        "sched": UserInfo("system:kube-scheduler"),
        "admin": UserInfo("root", groups=["system:masters"])})
    api.create("Pod", make_pod("w"), cred=Credential(token="admin"))
    api.create("Node", make_node("n1"), cred=Credential(token="admin"))
    # scheduler can list nodes and post bindings, but not delete pods
    api.list("Node", cred=Credential(token="sched"))
    api.bind(Binding("w", "default", "default/w", "n1"),
             cred=Credential(token="sched"))
    with pytest.raises(Forbidden):
        api.delete("Pod", "default", "w", cred=Credential(token="sched"))


def test_node_authorizer_scopes_to_own_node():
    api = make_server(auth=True)
    ca = CertAuthenticator(b"ca-key")
    kubelet = Credential(cert=ca.sign("system:node:n1", ["system:nodes"]))
    api.store.create("Node", make_node("n1"))
    api.store.create("Node", make_node("n2"))
    n1 = api.get("Node", "", "n1", cred=kubelet)
    api.update("Node", n1, cred=kubelet)
    with pytest.raises(Forbidden):
        n2 = api.store.get("Node", "", "n2")
        api.update("Node", n2, cred=kubelet)
    # pod bound to n1 is updatable; pod bound to n2 is not
    api.store.create("Pod", make_pod("mine", node_name="n1"))
    api.store.create("Pod", make_pod("theirs", node_name="n2"))
    p = api.get("Pod", "default", "mine", cred=kubelet)
    api.update_status("Pod", p, cred=kubelet)
    with pytest.raises(Forbidden):
        q = api.store.get("Pod", "default", "theirs")
        api.update_status("Pod", q, cred=kubelet)


# --------------------------------------------------------------- admission

def test_namespace_lifecycle_blocks_creates():
    api = make_server()
    with pytest.raises(Rejected):
        api.create("Pod", make_pod("p", namespace="nope"))
    api.store.create("Namespace", Namespace("closing", phase="Terminating"))
    with pytest.raises(Rejected):
        api.create("Pod", make_pod("p", namespace="closing"))
    with pytest.raises(Rejected):
        api.delete("Namespace", "", "default")


def test_limit_ranger_defaults_and_bounds():
    api = make_server()
    api.store.create("LimitRange", LimitRange("lims", "default", limits=[
        LimitRangeItem(type="Container",
                       default_request={"cpu": 100, "memory": 64 * Mi},
                       max={"cpu": 2000})]))
    pod = make_pod("defaulted")
    pod.containers[0].requests.clear()
    api.create("Pod", pod)
    got = api.get("Pod", "default", "defaulted")
    assert got.containers[0].requests == {"cpu": 100, "memory": 64 * Mi}
    with pytest.raises(Rejected):
        api.create("Pod", make_pod("too-big", cpu=4000))


def test_default_toleration_seconds_added():
    api = make_server()
    api.create("Pod", make_pod("p"))
    got = api.get("Pod", "default", "p")
    keys = {t.key for t in got.tolerations}
    assert "node.alpha.kubernetes.io/notReady" in keys
    assert "node.alpha.kubernetes.io/unreachable" in keys
    assert all(t.toleration_seconds == 300 for t in got.tolerations)


def test_resource_quota_enforced_and_usage_tracked():
    api = make_server()
    api.store.create("ResourceQuota", ResourceQuota(
        "quota", "default", hard={"pods": 2, "requests.cpu": 1000}))
    api.create("Pod", make_pod("a", cpu=400, memory=Mi))
    api.create("Pod", make_pod("b", cpu=400, memory=Mi))
    with pytest.raises(Rejected):  # pod count exceeded
        api.create("Pod", make_pod("c", cpu=100, memory=Mi))
    q = api.store.get("ResourceQuota", "default", "quota")
    assert q.used["pods"] == 2 and q.used["requests.cpu"] == 800
    api.store.create("Namespace", Namespace("other"))
    api.create("Pod", make_pod("c", namespace="other", cpu=100, memory=Mi))


def test_quota_cpu_exceeded():
    api = make_server()
    api.store.create("ResourceQuota", ResourceQuota(
        "cpuq", "default", hard={"requests.cpu": 500}))
    api.create("Pod", make_pod("a", cpu=400, memory=Mi))
    with pytest.raises(Rejected):
        api.create("Pod", make_pod("b", cpu=200, memory=Mi))


def test_pod_node_selector_merged_from_namespace():
    api = make_server()
    api.store.create("Namespace", Namespace(
        "tenant", annotations={
            "scheduler.alpha.kubernetes.io/node-selector": "team=infra"}))
    api.create("Pod", make_pod("p", namespace="tenant"))
    assert api.get("Pod", "tenant", "p").node_selector == {"team": "infra"}


def test_node_restriction_admission():
    api = make_server(auth=True)
    ca = CertAuthenticator(b"ca-key")
    kubelet = Credential(cert=ca.sign("system:node:n1", ["system:nodes"]))
    api.store.create("Node", make_node("n1"))
    api.store.create("Pod", make_pod("other", node_name="n2"))
    with pytest.raises((Rejected, Forbidden)):
        api.delete("Pod", "default", "other", cred=kubelet)


# ------------------------------------------------------------ subresources

def test_eviction_respects_pdb():
    api = make_server()
    for i in range(3):
        api.create("Pod", make_pod(f"w{i}", labels={"app": "web"}))
    api.store.create("PodDisruptionBudget", PodDisruptionBudget(
        "web-pdb", "default", min_available=2,
        selector=LabelSelector(match_labels={"app": "web"}),
        disruptions_allowed=1))
    api.evict(Eviction("w0", "default"))
    with pytest.raises(TooManyRequests):
        api.evict(Eviction("w1", "default"))
    assert len([p for p in api.store.list("Pod")[0]]) == 2


def test_scale_subresource():
    api = make_server()
    api.store.create("ReplicaSet", ReplicaSet(
        "rs", "default", replicas=3,
        selector=LabelSelector(match_labels={"a": "b"})))
    assert api.scale("ReplicaSet", "default", "rs") == 3
    api.scale("ReplicaSet", "default", "rs", replicas=5)
    assert api.store.get("ReplicaSet", "default", "rs").replicas == 5
    with pytest.raises(Invalid):
        api.scale("ReplicaSet", "default", "rs", replicas=-1)


def test_namespace_two_phase_delete():
    api = make_server()
    api.store.create("Namespace", Namespace("doomed"))
    api.delete("Namespace", "", "doomed")
    assert api.store.get("Namespace", "", "doomed").phase == "Terminating"
    api.finalize_namespace("doomed")
    with pytest.raises(Exception):
        api.store.get("Namespace", "", "doomed")


def test_strategy_validation():
    api = make_server()
    api.create("Pod", make_pod("ok"))
    bound = make_pod("bound", node_name="n1")
    api.store.create("Pod", bound)
    moved = make_pod("bound", node_name="n2")
    with pytest.raises(Invalid):
        api.update("Pod", moved)
    bad = make_pod("bad", cpu=100)
    bad.containers[0].limits["cpu"] = 50  # request > limit
    with pytest.raises(Invalid):
        api.create("Pod", bad)


def test_audit_log_records_denials():
    api = make_server(auth=True, tokens={"t": UserInfo("nobody")})
    with pytest.raises(Forbidden):
        api.create("Pod", make_pod("p"), cred=Credential(token="t"))
    ev = api.audit_log[-1]
    assert ev.user == "nobody" and ev.verb == "create" and ev.code == 403
    assert api.healthz() == {"status": "ok"}
    assert "admission" in api.configz()


def test_namespaced_list_with_namespaced_rbac():
    api = make_server(auth=True, tokens={
        "dev": UserInfo("dev-user"),
        "admin": UserInfo("root", groups=["system:masters"])})
    api.store.create("Namespace", Namespace("team-a"))
    api.store.create("Role", Role("reader", "team-a", rules=[
        PolicyRule(verbs=["list", "get"], resources=["pods"])]))
    api.store.create("RoleBinding", RoleBinding(
        "readers", "team-a", subjects=[Subject("User", "dev-user")],
        role_ref=RoleRef("Role", "reader")))
    api.create("Pod", make_pod("p1", namespace="team-a"),
               cred=Credential(token="admin"))
    api.create("Pod", make_pod("p2"), cred=Credential(token="admin"))
    objs, _ = api.list("Pod", cred=Credential(token="dev"),
                       namespace="team-a")
    assert [p.name for p in objs] == ["p1"]
    with pytest.raises(Forbidden):  # cluster-wide list still forbidden
        api.list("Pod", cred=Credential(token="dev"))


def test_admission_defaults_are_validated():
    api = make_server()
    api.store.create("LimitRange", LimitRange("lims", "default", limits=[
        LimitRangeItem(type="Container", default_request={"cpu": 500})]))
    bad = make_pod("defaulted-over-limit")
    bad.containers[0].requests.clear()
    bad.containers[0].limits["cpu"] = 100  # default request 500 > limit 100
    with pytest.raises(Invalid):
        api.create("Pod", bad)
