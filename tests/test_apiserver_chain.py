"""The apiserver handler chain: authn -> authz (RBAC/Node) -> admission ->
strategy -> store, plus subresources (eviction+PDB, scale, namespace
two-phase delete) and the audit trail.

Harness shape mirrors the reference's apiserver integration tests (in-process
server, table-driven identities) — test/integration/auth, plugin/pkg/
admission/*/admission_test.go."""

import pytest

from kubernetes_tpu.admission import AdmissionChain, Rejected, default_plugins
from kubernetes_tpu.api.cluster import (
    Eviction,
    LimitRange,
    LimitRangeItem,
    PodDisruptionBudget,
    ResourceQuota,
    ServiceAccount,
)
from kubernetes_tpu.api.rbac import (
    PolicyRule,
    Role,
    RoleBinding,
    RoleRef,
    Subject,
)
from kubernetes_tpu.api.types import Binding, LabelSelector, make_node, make_pod
from kubernetes_tpu.api.workloads import Namespace, ReplicaSet
from kubernetes_tpu.auth.authn import (
    BootstrapTokenAuthenticator,
    CertAuthenticator,
    Credential,
    ServiceAccountTokenAuthenticator,
    TokenAuthenticator,
    Unauthenticated,
    UnionAuthenticator,
)
from kubernetes_tpu.auth.authz import Forbidden
from kubernetes_tpu.api.rbac import UserInfo
from kubernetes_tpu.server.apiserver import ApiServer, Invalid, TooManyRequests

Mi = 1024 * 1024
Gi = 1024 * Mi


def make_server(auth=False, tokens=None):
    authn = UnionAuthenticator([
        TokenAuthenticator(tokens or {}),
        ServiceAccountTokenAuthenticator(b"sa-signing-key"),
        CertAuthenticator(b"ca-key"),
    ])
    api = ApiServer(auth=auth, authenticator=authn)
    api.store.create("Namespace", Namespace("default"))
    api.bootstrap_rbac()
    return api


# ------------------------------------------------------------------- authn

def test_union_authenticator_and_token_auth():
    api = make_server(auth=True, tokens={
        "secret-token": UserInfo("alice", groups=["system:masters"])})
    cred = Credential(token="secret-token")
    api.create("Pod", make_pod("p1"), cred=cred)
    assert api.get("Pod", "default", "p1", cred=cred).name == "p1"
    with pytest.raises(Unauthenticated):
        api.create("Pod", make_pod("p2"), cred=Credential(token="wrong"))


def test_service_account_jwt_roundtrip():
    sa = ServiceAccountTokenAuthenticator(b"key")
    tok = sa.issue("kube-system", "builder", uid="u1")
    user = sa.authenticate(Credential(token=tok))
    assert user.name == "system:serviceaccount:kube-system:builder"
    assert "system:serviceaccounts" in user.groups
    assert sa.authenticate(Credential(token=tok[:-2] + "xx")) is None


def test_bootstrap_token_expiry_and_revoke():
    clock = [0.0]
    bt = BootstrapTokenAuthenticator(now=lambda: clock[0])
    bt.add_token("abc123", "s3cret", ttl=10)
    u = bt.authenticate(Credential(token="abc123.s3cret"))
    assert u.name == "system:bootstrap:abc123"
    clock[0] = 11
    assert bt.authenticate(Credential(token="abc123.s3cret")) is None
    assert bt.expired_ids() == ["abc123"]


def test_cert_authenticator_rejects_forged_groups():
    ca = CertAuthenticator(b"ca")
    cert = ca.sign("bob", ["dev"])
    assert ca.authenticate(Credential(cert=cert)).name == "bob"
    cert["orgs"] = ["system:masters"]  # forge
    assert ca.authenticate(Credential(cert=cert)) is None


# ------------------------------------------------------------------- authz

def test_rbac_namespaced_role_binding():
    api = make_server(auth=True, tokens={
        "admin": UserInfo("root", groups=["system:masters"]),
        "dev": UserInfo("dev-user")})
    admin = Credential(token="admin")
    dev = Credential(token="dev")
    api.store.create("Role", Role("pod-reader", "default", rules=[
        PolicyRule(verbs=["get", "list"], resources=["pods"])]))
    api.store.create("RoleBinding", RoleBinding(
        "read-pods", "default",
        subjects=[Subject("User", "dev-user")],
        role_ref=RoleRef("Role", "pod-reader")))
    api.create("Pod", make_pod("p1"), cred=admin)
    assert api.get("Pod", "default", "p1", cred=dev).name == "p1"
    with pytest.raises(Forbidden):
        api.create("Pod", make_pod("p2"), cred=dev)
    with pytest.raises(Forbidden):
        api.delete("Pod", "default", "p1", cred=dev)


def test_scheduler_bootstrap_role_allows_binding():
    api = make_server(auth=True, tokens={
        "sched": UserInfo("system:kube-scheduler"),
        "admin": UserInfo("root", groups=["system:masters"])})
    api.create("Pod", make_pod("w"), cred=Credential(token="admin"))
    api.create("Node", make_node("n1"), cred=Credential(token="admin"))
    # scheduler can list nodes and post bindings, but not delete pods
    api.list("Node", cred=Credential(token="sched"))
    api.bind(Binding("w", "default", "default/w", "n1"),
             cred=Credential(token="sched"))
    with pytest.raises(Forbidden):
        api.delete("Pod", "default", "w", cred=Credential(token="sched"))


def test_node_authorizer_scopes_to_own_node():
    api = make_server(auth=True)
    ca = CertAuthenticator(b"ca-key")
    kubelet = Credential(cert=ca.sign("system:node:n1", ["system:nodes"]))
    api.store.create("Node", make_node("n1"))
    api.store.create("Node", make_node("n2"))
    n1 = api.get("Node", "", "n1", cred=kubelet)
    api.update("Node", n1, cred=kubelet)
    with pytest.raises(Rejected):
        n2 = api.store.get("Node", "", "n2")
        api.update("Node", n2, cred=kubelet)
    # pod bound to n1 is updatable; pod bound to n2 is not
    api.store.create("Pod", make_pod("mine", node_name="n1"))
    api.store.create("Pod", make_pod("theirs", node_name="n2"))
    p = api.get("Pod", "default", "mine", cred=kubelet)
    api.update_status("Pod", p, cred=kubelet)
    # cross-node pod writes are blocked by NodeRestriction admission (the
    # node authorizer only has NO_OPINION there, like the reference)
    with pytest.raises(Rejected):
        q = api.store.get("Pod", "default", "theirs")
        api.update_status("Pod", q, cred=kubelet)


# --------------------------------------------------------------- admission

def test_namespace_lifecycle_blocks_creates():
    api = make_server()
    with pytest.raises(Rejected):
        api.create("Pod", make_pod("p", namespace="nope"))
    api.store.create("Namespace", Namespace("closing", phase="Terminating"))
    with pytest.raises(Rejected):
        api.create("Pod", make_pod("p", namespace="closing"))
    with pytest.raises(Rejected):
        api.delete("Namespace", "", "default")


def test_limit_ranger_defaults_and_bounds():
    api = make_server()
    api.store.create("LimitRange", LimitRange("lims", "default", limits=[
        LimitRangeItem(type="Container",
                       default_request={"cpu": 100, "memory": 64 * Mi},
                       max={"cpu": 2000})]))
    pod = make_pod("defaulted")
    pod.containers[0].requests.clear()
    api.create("Pod", pod)
    got = api.get("Pod", "default", "defaulted")
    assert got.containers[0].requests == {"cpu": 100, "memory": 64 * Mi}
    with pytest.raises(Rejected):
        api.create("Pod", make_pod("too-big", cpu=4000))


def test_default_toleration_seconds_added():
    api = make_server()
    api.create("Pod", make_pod("p"))
    got = api.get("Pod", "default", "p")
    keys = {t.key for t in got.tolerations}
    assert "node.alpha.kubernetes.io/notReady" in keys
    assert "node.alpha.kubernetes.io/unreachable" in keys
    assert all(t.toleration_seconds == 300 for t in got.tolerations)


def test_resource_quota_enforced_and_usage_tracked():
    api = make_server()
    api.store.create("ResourceQuota", ResourceQuota(
        "quota", "default", hard={"pods": 2, "requests.cpu": 1000}))
    api.create("Pod", make_pod("a", cpu=400, memory=Mi))
    api.create("Pod", make_pod("b", cpu=400, memory=Mi))
    with pytest.raises(Rejected):  # pod count exceeded
        api.create("Pod", make_pod("c", cpu=100, memory=Mi))
    q = api.store.get("ResourceQuota", "default", "quota")
    assert q.used["pods"] == 2 and q.used["requests.cpu"] == 800
    api.store.create("Namespace", Namespace("other"))
    api.create("Pod", make_pod("c", namespace="other", cpu=100, memory=Mi))


def test_quota_cpu_exceeded():
    api = make_server()
    api.store.create("ResourceQuota", ResourceQuota(
        "cpuq", "default", hard={"requests.cpu": 500}))
    api.create("Pod", make_pod("a", cpu=400, memory=Mi))
    with pytest.raises(Rejected):
        api.create("Pod", make_pod("b", cpu=200, memory=Mi))


def test_pod_node_selector_merged_from_namespace():
    api = make_server()
    api.store.create("Namespace", Namespace(
        "tenant", annotations={
            "scheduler.alpha.kubernetes.io/node-selector": "team=infra"}))
    api.create("Pod", make_pod("p", namespace="tenant"))
    assert api.get("Pod", "tenant", "p").node_selector == {"team": "infra"}


def test_node_restriction_admission():
    api = make_server(auth=True)
    ca = CertAuthenticator(b"ca-key")
    kubelet = Credential(cert=ca.sign("system:node:n1", ["system:nodes"]))
    api.store.create("Node", make_node("n1"))
    api.store.create("Pod", make_pod("other", node_name="n2"))
    with pytest.raises((Rejected, Forbidden)):
        api.delete("Pod", "default", "other", cred=kubelet)


# ------------------------------------------------------------ subresources

def test_eviction_respects_pdb():
    api = make_server()
    for i in range(3):
        api.create("Pod", make_pod(f"w{i}", labels={"app": "web"}))
    api.store.create("PodDisruptionBudget", PodDisruptionBudget(
        "web-pdb", "default", min_available=2,
        selector=LabelSelector(match_labels={"app": "web"}),
        disruptions_allowed=1))
    api.evict(Eviction("w0", "default"))
    with pytest.raises(TooManyRequests):
        api.evict(Eviction("w1", "default"))
    assert len([p for p in api.store.list("Pod")[0]]) == 2


def test_scale_subresource():
    api = make_server()
    api.store.create("ReplicaSet", ReplicaSet(
        "rs", "default", replicas=3,
        selector=LabelSelector(match_labels={"a": "b"})))
    assert api.scale("ReplicaSet", "default", "rs") == 3
    api.scale("ReplicaSet", "default", "rs", replicas=5)
    assert api.store.get("ReplicaSet", "default", "rs").replicas == 5
    with pytest.raises(Invalid):
        api.scale("ReplicaSet", "default", "rs", replicas=-1)


def test_namespace_two_phase_delete():
    api = make_server()
    api.store.create("Namespace", Namespace("doomed"))
    api.delete("Namespace", "", "doomed")
    assert api.store.get("Namespace", "", "doomed").phase == "Terminating"
    api.finalize_namespace("doomed")
    with pytest.raises(Exception):
        api.store.get("Namespace", "", "doomed")


def test_strategy_validation():
    api = make_server()
    api.create("Pod", make_pod("ok"))
    bound = make_pod("bound", node_name="n1")
    api.store.create("Pod", bound)
    moved = make_pod("bound", node_name="n2")
    with pytest.raises(Invalid):
        api.update("Pod", moved)
    bad = make_pod("bad", cpu=100)
    bad.containers[0].limits["cpu"] = 50  # request > limit
    with pytest.raises(Invalid):
        api.create("Pod", bad)


def test_audit_log_records_denials():
    api = make_server(auth=True, tokens={"t": UserInfo("nobody")})
    with pytest.raises(Forbidden):
        api.create("Pod", make_pod("p"), cred=Credential(token="t"))
    ev = api.audit_log[-1]
    assert ev.user == "nobody" and ev.verb == "create" and ev.code == 403
    assert api.healthz() == {"status": "ok"}
    assert "admission" in api.configz()


def test_namespaced_list_with_namespaced_rbac():
    api = make_server(auth=True, tokens={
        "dev": UserInfo("dev-user"),
        "admin": UserInfo("root", groups=["system:masters"])})
    api.store.create("Namespace", Namespace("team-a"))
    api.store.create("Role", Role("reader", "team-a", rules=[
        PolicyRule(verbs=["list", "get"], resources=["pods"])]))
    api.store.create("RoleBinding", RoleBinding(
        "readers", "team-a", subjects=[Subject("User", "dev-user")],
        role_ref=RoleRef("Role", "reader")))
    api.create("Pod", make_pod("p1", namespace="team-a"),
               cred=Credential(token="admin"))
    api.create("Pod", make_pod("p2"), cred=Credential(token="admin"))
    objs, _ = api.list("Pod", cred=Credential(token="dev"),
                       namespace="team-a")
    assert [p.name for p in objs] == ["p1"]
    with pytest.raises(Forbidden):  # cluster-wide list still forbidden
        api.list("Pod", cred=Credential(token="dev"))


def test_admission_defaults_are_validated():
    api = make_server()
    api.store.create("LimitRange", LimitRange("lims", "default", limits=[
        LimitRangeItem(type="Container", default_request={"cpu": 500})]))
    bad = make_pod("defaulted-over-limit")
    bad.containers[0].requests.clear()
    bad.containers[0].limits["cpu"] = 100  # default request 500 > limit 100
    with pytest.raises(Invalid):
        api.create("Pod", bad)


# ---------------------------------------------- round-2 security hardening

def test_node_authorizer_secret_reachability():
    """node_authorizer.go: a kubelet may only GET a named secret/configmap
    referenced by a pod bound to it — never list/watch, never other nodes'
    secrets (ADVICE r1: list-all-secrets broke node isolation)."""
    from kubernetes_tpu.api.cluster import Secret
    from kubernetes_tpu.api.types import Volume, VolumeKind

    api = make_server(auth=True)
    ca = CertAuthenticator(b"ca-key")
    kubelet = Credential(cert=ca.sign("system:node:n1", ["system:nodes"]))
    api.store.create("Node", make_node("n1"))
    api.store.create("Secret", Secret("mine"))
    api.store.create("Secret", Secret("not-mine"))
    api.store.create("Pod", make_pod(
        "p", node_name="n1",
        volumes=[Volume(name="v", kind=VolumeKind.SECRET, volume_id="mine")]))
    # referenced by a pod on n1 -> get allowed
    assert api.get("Secret", "default", "mine", cred=kubelet).name == "mine"
    # unreferenced secret -> forbidden
    with pytest.raises(Forbidden):
        api.get("Secret", "default", "not-mine", cred=kubelet)
    # list/watch of all secrets -> forbidden (bootstrap role grants get only)
    with pytest.raises(Forbidden):
        api.list("Secret", cred=kubelet)


def test_csr_requestor_stamped_from_authenticated_user():
    """ADVICE r1: client-supplied requestor/groups must be overwritten from
    the authenticated identity (strategy.PrepareForCreate), else any CSR
    creator escalates to an auto-approved node cert."""
    from kubernetes_tpu.api.cluster import CertificateSigningRequest

    api = make_server(auth=True,
                      tokens={"evil": UserInfo("mallory", groups=["devs"])})
    api.store.create("Role", Role(
        "csr-creator", "", rules=[PolicyRule(
            verbs=["create"], api_groups=["*"],
            resources=["certificatesigningrequests"])]))
    # cluster-scoped resource: bind via ClusterRoleBinding-equivalent rule
    from kubernetes_tpu.api.rbac import ClusterRole, ClusterRoleBinding
    api.store.create("ClusterRole", ClusterRole(
        "csr-creator", rules=[PolicyRule(
            verbs=["create"], api_groups=["*"],
            resources=["certificatesigningrequests"])]))
    api.store.create("ClusterRoleBinding", ClusterRoleBinding(
        "mallory-csr", subjects=[Subject("User", "mallory")],
        role_ref=RoleRef("ClusterRole", "csr-creator")))
    api.create("CertificateSigningRequest", CertificateSigningRequest(
        "sneaky", requestor="system:bootstrap:abc",
        groups=["system:bootstrappers"], cn="system:node:n1",
        orgs=["system:nodes"]), cred=Credential(token="evil"))
    csr = api.store.get("CertificateSigningRequest", "", "sneaky")
    assert csr.requestor == "mallory"
    assert "devs" in csr.groups
    assert "system:bootstrappers" not in csr.groups  # escalation stamped out


def test_quota_usage_rolled_back_on_failed_create():
    """ADVICE r1: usage committed at admission must be rolled back when the
    create fails downstream — and every change flows through store.update
    (watch event + rv bump), never in-place mutation."""
    api = make_server()
    api.store.create("ResourceQuota", ResourceQuota(
        "q", "default", hard={"pods": 5}))
    api.create("Pod", make_pod("dup"))
    q1 = api.store.get("ResourceQuota", "default", "q")
    assert q1.used["pods"] == 1
    rv1 = q1.resource_version
    # duplicate name -> store.create raises after admission committed usage
    with pytest.raises(Exception):
        api.create("Pod", make_pod("dup"))
    q2 = api.store.get("ResourceQuota", "default", "q")
    assert q2.used["pods"] == 1  # rolled back
    assert q2.resource_version > rv1  # through guarded updates, not in-place


def test_eviction_rejects_multiple_pdbs():
    """eviction.go: more than one matching PDB is an error, not a multi-
    decrement."""
    api = make_server()
    api.store.create("Pod", make_pod("web", labels={"app": "web"}))
    for i in range(2):
        api.store.create("PodDisruptionBudget", PodDisruptionBudget(
            f"pdb{i}", "default",
            selector=LabelSelector(match_labels={"app": "web"}),
            disruptions_allowed=1))
    with pytest.raises(Invalid):
        api.evict(Eviction("web", "default"))


def test_node_cannot_self_grant_secret_via_pod_create():
    """code-review r2: NodeRestriction must reject node-created pods that
    reference secrets/configmaps/PVCs (admission.go mirror-pod rules) —
    else a kubelet mints a pod referencing any secret and rides the
    reachability grant."""
    from kubernetes_tpu.api.cluster import Secret
    from kubernetes_tpu.api.types import Volume, VolumeKind

    api = make_server(auth=True)
    ca = CertAuthenticator(b"ca-key")
    kubelet = Credential(cert=ca.sign("system:node:n1", ["system:nodes"]))
    api.store.create("Node", make_node("n1"))
    api.store.create("Secret", Secret("loot"))
    with pytest.raises(Rejected):
        api.create("Pod", make_pod(
            "steal", node_name="n1",
            volumes=[Volume("v", VolumeKind.SECRET, "loot")]), cred=kubelet)
    with pytest.raises(Rejected):  # pods bound elsewhere can't be created
        api.create("Pod", make_pod("other", node_name="n2"), cred=kubelet)
    # a plain mirror-style pod bound to itself is fine
    api.create("Pod", make_pod("static", node_name="n1"), cred=kubelet)


def test_csr_identity_immutable_after_create():
    """code-review r2: requestor/groups/cn/orgs frozen at create; approval
    flips need the approval subresource permission."""
    from kubernetes_tpu.api.cluster import CertificateSigningRequest
    from kubernetes_tpu.api.rbac import ClusterRole, ClusterRoleBinding

    api = make_server(auth=True,
                      tokens={"u": UserInfo("mallory", groups=["devs"])})
    api.store.create("ClusterRole", ClusterRole(
        "csr-rw", rules=[PolicyRule(
            verbs=["create", "update", "get"], api_groups=["*"],
            resources=["certificatesigningrequests"])]))
    api.store.create("ClusterRoleBinding", ClusterRoleBinding(
        "b", subjects=[Subject("User", "mallory")],
        role_ref=RoleRef("ClusterRole", "csr-rw")))
    cred = Credential(token="u")
    api.create("CertificateSigningRequest", CertificateSigningRequest(
        "c1", cn="system:node:nX", orgs=["system:nodes"]), cred=cred)
    csr = api.store.get("CertificateSigningRequest", "", "c1")
    import copy
    evil = copy.deepcopy(csr)
    evil.groups = ["system:bootstrappers"]
    with pytest.raises(Invalid):
        api.update("CertificateSigningRequest", evil, cred=cred)
    flip = copy.deepcopy(csr)
    flip.approved = True
    with pytest.raises(Forbidden):  # no …/approval permission
        api.update("CertificateSigningRequest", flip, cred=cred)


def test_audit_policy_levels_and_suppression():
    """Policy-driven auditing (apiserver/pkg/audit/policy): first match
    wins, level None suppresses, no-match falls to the default."""
    from kubernetes_tpu.server.apiserver import AuditPolicy, AuditRule

    policy = AuditPolicy(rules=[
        # the classic noise rule: don't log the healthcheck user's reads
        AuditRule(level="None", users=["system:kube-proxy"],
                  verbs=["list", "get"]),
        AuditRule(level="Request", resources=["secrets"]),
        AuditRule(level="Metadata", verbs=["list"]),
    ], default_level="Metadata")
    api = ApiServer(audit_policy=policy)
    api.store.create("Namespace", Namespace("default"))
    from kubernetes_tpu.api.cluster import Secret

    api.create("Secret", Secret("s1", "default", data={}))
    api.list("Pod")
    entries = {(e.resource, e.verb): e.level for e in api.audit_log}
    assert entries[("secrets", "create")] == "Request"
    assert entries[("pods", "list")] == "Metadata"
    # suppressed: the proxy user's list never lands in the log
    before = len(api.audit_log)
    from kubernetes_tpu.api.rbac import UserInfo as _UI

    api._audit(_UI("system:kube-proxy"), "list", "Endpoints", "", "", 200)
    assert len(api.audit_log) == before
    # same user's WRITE is not matched by the None rule -> default level
    api._audit(_UI("system:kube-proxy"), "update", "Endpoints", "", "", 200)
    assert api.audit_log[-1].level == "Metadata"


def test_impersonation_filter():
    """endpoints/filters/impersonation.go: --as requires the impersonate
    verb on users (and groups per requested group); the chain then runs
    as the impersonated identity."""
    import dataclasses as _dc

    from kubernetes_tpu.api.rbac import (
        ClusterRole,
        ClusterRoleBinding,
        PolicyRule,
        RoleRef,
        Subject,
    )

    api = make_server(auth=True, tokens={
        "admin": UserInfo("root", groups=["system:masters"]),
        "ci": UserInfo("ci-bot"),
        "dev": UserInfo("dev-user")})
    # grant ci-bot the impersonate verb on the dev-user identity only
    api.store.create("ClusterRole", ClusterRole("impersonator", rules=[
        PolicyRule(verbs=["impersonate"], resources=["users"])]))
    api.store.create("ClusterRoleBinding", ClusterRoleBinding(
        "ci-impersonates", subjects=[Subject("User", "ci-bot")],
        role_ref=RoleRef("ClusterRole", "impersonator")))
    # dev-user can read pods
    api.store.create("Role", Role("pod-reader", "default", rules=[
        PolicyRule(verbs=["get", "list"], resources=["pods"])]))
    api.store.create("RoleBinding", RoleBinding(
        "read-pods", "default", subjects=[Subject("User", "dev-user")],
        role_ref=RoleRef("Role", "pod-reader")))
    api.create("Pod", make_pod("p"), cred=Credential(token="admin"))

    as_dev = Credential(token="ci", impersonate_user="dev-user")
    # the request runs AS dev-user: read allowed, write forbidden
    objs, _ = api.list("Pod", cred=as_dev, namespace="default")
    assert [p.name for p in objs] == ["p"]
    with pytest.raises(Forbidden):
        api.create("Pod", make_pod("p2"), cred=as_dev)
    # audit attributes the entry to the impersonated identity
    assert any(e.user == "dev-user" for e in api.audit_log)
    # a user WITHOUT the impersonate grant is refused
    with pytest.raises(Forbidden, match="cannot impersonate"):
        api.list("Pod",
                 cred=Credential(token="dev", impersonate_user="root"))
    # impersonating a group requires the groups grant too (not held)
    with pytest.raises(Forbidden, match='cannot impersonate group'):
        api.list("Pod", cred=Credential(
            token="ci", impersonate_user="dev-user",
            impersonate_groups=("system:masters",)))


def test_ktctl_as_flag_impersonates():
    import io

    from kubernetes_tpu.api.rbac import (
        ClusterRole,
        ClusterRoleBinding,
        PolicyRule,
        RoleRef,
        Subject,
    )
    from kubernetes_tpu.cli.ktctl import Ktctl

    api = make_server(auth=True, tokens={
        "admin": UserInfo("root", groups=["system:masters"]),
        "ci": UserInfo("ci-bot")})
    api.store.create("ClusterRole", ClusterRole("impersonator", rules=[
        PolicyRule(verbs=["impersonate"], resources=["users"])]))
    api.store.create("ClusterRoleBinding", ClusterRoleBinding(
        "ci-imp", subjects=[Subject("User", "ci-bot")],
        role_ref=RoleRef("ClusterRole", "impersonator")))
    api.store.create("Role", Role("pod-reader", "default", rules=[
        PolicyRule(verbs=["get", "list"], resources=["pods"])]))
    api.store.create("RoleBinding", RoleBinding(
        "read-pods", "default", subjects=[Subject("User", "dev-user")],
        role_ref=RoleRef("Role", "pod-reader")))
    api.create("Pod", make_pod("p"), cred=Credential(token="admin"))
    out = io.StringIO()
    kt = Ktctl(api, out=out, cred=Credential(token="ci"))
    # ci-bot alone cannot list pods...
    with pytest.raises(Forbidden):
        kt.run(["get", "pods"])
    # ...but --as dev-user can (and only for this invocation)
    assert kt.run(["get", "pods", "--as", "dev-user"]) == 0
    assert "p" in out.getvalue()
    with pytest.raises(Forbidden):
        kt.run(["get", "pods"])


def test_denied_impersonation_is_audited_and_equals_form_caught():
    """Review regressions: a 403 impersonation attempt lands in the audit
    log attributed to the REAL user; the --as=value equals form cannot
    slip past as an ordinary flag."""
    import io

    from kubernetes_tpu.cli.ktctl import Ktctl

    api = make_server(auth=True, tokens={
        "admin": UserInfo("root", groups=["system:masters"]),
        "dev": UserInfo("dev-user")})
    with pytest.raises(Forbidden):
        api.list("Pod", cred=Credential(token="dev",
                                        impersonate_user="root"))
    denied = [e for e in api.audit_log if e.code == 403]
    assert denied and denied[-1].user == "dev-user"
    # equals form: same Forbidden as the space form, never full privilege
    out = io.StringIO()
    kt = Ktctl(api, out=out, cred=Credential(token="dev"))
    with pytest.raises(Forbidden):
        kt.run(["get", "pods", "--as=root"])


def test_denied_impersonation_audited_on_watch_and_bind_many():
    """The audit invariant holds on the non-_run entry points too."""
    from kubernetes_tpu.api.types import Binding

    api = make_server(auth=True, tokens={
        "dev": UserInfo("dev-user")})
    for call in (
        lambda: api.watch_since(("Pod",), 0, timeout=0.01,
                                cred=Credential(token="dev",
                                                impersonate_user="root")),
        lambda: api.bind_many(
            [Binding("p", "default", "default/p", "n1")],
            cred=Credential(token="dev", impersonate_user="root")),
    ):
        before = len(api.audit_log)
        with pytest.raises(Forbidden):
            call()
        assert len(api.audit_log) == before + 1
        assert api.audit_log[-1].code == 403
        assert api.audit_log[-1].user == "dev-user"


def test_denied_watch_is_audited():
    api = make_server(auth=True, tokens={"dev": UserInfo("dev-user")})
    before = len(api.audit_log)
    with pytest.raises(Forbidden):
        api.watch_since(("Node",), 0, timeout=0.01,
                        cred=Credential(token="dev"))
    assert len(api.audit_log) == before + 1
    assert api.audit_log[-1].code == 403
    assert api.audit_log[-1].verb == "watch"


def test_allowed_watch_and_default_storageclass_field():
    api = make_server(auth=True, tokens={
        "admin": UserInfo("root", groups=["system:masters"])})
    api.watch_since(("Pod",), 0, timeout=0.01,
                    cred=Credential(token="admin"))
    assert any(e.verb == "watch" and e.code == 200 for e in api.audit_log)
    # StorageClass carries the is-default marker the admission plugin reads
    from kubernetes_tpu.api.cluster import StorageClass

    sc = StorageClass("fast", provisioner="gce-pd", is_default=True)
    api.create("StorageClass", sc, cred=Credential(token="admin"))
    got = api.get("StorageClass", "", "fast", cred=Credential(token="admin"))
    assert got.is_default is True
