"""Federation tier (ISSUE 20): front-door router + cells end to end.

Pins the four acceptance seams:

  - ROUTER DETERMINISM: a frozen [C, M] cell-aggregate tensor produces
    bit-identical cell choices run to run AND across the device/host
    scoring twins (routing is a pure function of the tensor — the
    argmax tie-break is first-occurrence, never hash order);
  - GANGS ROUTE WHOLE-CELL: every member of a gang lands in ONE cell's
    store and binds there (the quorum fence never spans a cell
    boundary), audited from store truth;
  - BROWNOUT SPILLOVER EXACTLY-ONCE: a cell going NotReady drains its
    pending pods through the spillover path to survivors; the event-log
    audit holds — each pod key has bind events in AT MOST one cell,
    ever, and per-cell duplicate-bind audits stay hard zero;
  - AGGREGATE ORACLE A/B: the incrementally-folded CELL_AGG column
    equals the aggregate rebuilt from a full store walk on every shared
    field (the RELIST hydration path and the delta path can never
    disagree about a cell's capacity picture).

Plus the satellite seams: brownout-schedule determinism, the A/B
range-overlap escalation helper, and the trend reader's 1-core
churn_vs_quiet annotation (non-gating, like box_change).
"""

from __future__ import annotations

import numpy as np
import pytest

from kubernetes_tpu.api.types import make_pod
from kubernetes_tpu.engine.gang import (
    GANG_MIN_AVAILABLE_ANNOTATION,
    GANG_NAME_ANNOTATION,
)
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.federation.aggregate import (
    CellAggregate,
    aggregate_from_lists,
)
from kubernetes_tpu.federation.cell import CellService
from kubernetes_tpu.federation.router import FederationRouter, LocalCell
from kubernetes_tpu.models.hollow import hollow_nodes
from kubernetes_tpu.parallel.multiproc import audit_duplicate_binds
from kubernetes_tpu.server.apiserver_lite import ApiServerLite


def _pod(name, cpu=100, mem=64 << 20, **kw):
    return make_pod(name, cpu=cpu, memory=mem, **kw)


def _gang(name, members, cpu=50):
    out = []
    for m in range(members):
        p = _pod(f"{name}-{m}", cpu=cpu, mem=32 << 20)
        p.annotations[GANG_NAME_ANNOTATION] = name
        p.annotations[GANG_MIN_AVAILABLE_ANNOTATION] = str(members)
        out.append(p)
    return out


class _Cell:
    """One in-process cell: store + engine + CellService, pumped from
    the test's own thread (deterministic — no pump thread)."""

    def __init__(self, name, n_nodes=16, zones=4):
        self.name = name
        self.api = ApiServerLite()
        for i, n in enumerate(hollow_nodes(n_nodes)):
            n.labels["zone"] = f"{name}-z{i % zones}"
            self.api.create("Node", n)
        self.sched = Scheduler(self.api, record_events=False)
        self.svc = CellService(self.api, cell=name)
        self.sched.spill_handler = self.svc.spill
        self.sched.spill_after_attempts = 2
        self.sched.start()
        self.loop = self.sched.stream(budget_s=0.05, min_quantum=8,
                                      max_quantum=128)
        self.handle = LocalCell(name, self.svc)

    def pump(self, steps=8):
        for _ in range(steps):
            self.loop.step(wait=0.001)

    def bound_keys(self):
        pods, _rv = self.api.list("Pod")
        return {p.key(): p.node_name for p in pods if p.node_name}

    def close(self):
        self.loop.close()


@pytest.fixture
def two_cells():
    cells = [_Cell("alpha", n_nodes=16), _Cell("beta", n_nodes=16)]
    router = FederationRouter([c.handle for c in cells])
    router.hydrate()
    yield cells, router
    for c in cells:
        c.close()


def _drain(cells, router, rounds=60):
    for _ in range(rounds):
        for c in cells:
            c.pump(4)
        router.spill_pump()
        if sum(a.pending for a in router.aggs.values()) == 0 \
                and not router.backlog:
            return
    raise AssertionError(
        f"fleet did not drain: pending="
        f"{ {n: a.pending for n, a in router.aggs.items()} } "
        f"backlog={len(router.backlog)}")


# ------------------------------------------------------------ determinism


def _frozen_router(use_device):
    """Router over dummy handles with a HAND-FROZEN aggregate tensor —
    route() reads only the columns, so no cell machinery is needed."""

    class _Dummy:
        def __init__(self, name):
            self.name = name

        def close(self):
            pass

    router = FederationRouter([_Dummy(n) for n in ("c0", "c1", "c2")],
                              use_device=use_device)
    shapes = {
        "c0": dict(nodes_total=10, nodes_ready=10, cpu_alloc_m=40_000,
                   mem_alloc_mib=40_960, cpu_used_m=35_000,
                   mem_used_mib=4_096, pending=0,
                   domains={"z0": 5, "z1": 5}),
        "c1": dict(nodes_total=10, nodes_ready=10, cpu_alloc_m=40_000,
                   mem_alloc_mib=40_960, cpu_used_m=8_000,
                   mem_used_mib=4_096, pending=12,
                   domains={"z1": 10}),
        "c2": dict(nodes_total=10, nodes_ready=10, cpu_alloc_m=40_000,
                   mem_alloc_mib=40_960, cpu_used_m=8_000,
                   mem_used_mib=4_096, pending=0, domains={"z2": 10}),
    }
    for name, kw in shapes.items():
        agg = CellAggregate(cell=name, ready=True, **kw)
        router.aggs[name] = agg
    return router


def _mixed_batch():
    pods = [_pod(f"d{i}", cpu=100 + 50 * (i % 3)) for i in range(40)]
    pods += [_pod("z1-pin", cpu=100, node_selector={"zone": "z1"}),
             _pod("z2-pin", cpu=100, node_selector={"zone": "z2"})]
    pods += _gang("dg", 4)
    return pods


def test_frozen_tensor_routes_bit_identical_run_to_run():
    a1, l1 = _frozen_router(False).route(_mixed_batch())
    a2, l2 = _frozen_router(False).route(_mixed_batch())
    as_keys = lambda a: {c: [p.key() for p in ps]  # noqa: E731
                         for c, ps in a.items()}
    assert as_keys(a1) == as_keys(a2)
    assert [p.key() for p in l1] == [p.key() for p in l2]
    # the frozen shape exercises every verdict class: the loaded c0
    # loses ties, zone pins land on their only domain, someone routes
    assert a1, "nothing routed"
    z1_cell = [c for c, ps in a1.items()
               if any(p.name == "z1-pin" for p in ps)]
    assert z1_cell and z1_cell[0] in ("c0", "c1")
    z2_cell = [c for c, ps in a1.items()
               if any(p.name == "z2-pin" for p in ps)]
    assert z2_cell == ["c2"]


def test_device_and_host_twins_route_identically():
    """use_device=True pads C to the bucket ladder and scores through
    the jitted kernel; the numpy twin must produce the SAME choices —
    the routing policy is latency, never semantics."""
    ah, _lh = _frozen_router(False).route(_mixed_batch())
    ad, _ld = _frozen_router(True).route(_mixed_batch())
    assert {c: [p.key() for p in ps] for c, ps in ah.items()} \
        == {c: [p.key() for p in ps] for c, ps in ad.items()}


def test_route_scores_twins_bitwise_equal():
    from kubernetes_tpu.ops.federation import (
        route_scores,
        route_scores_host,
    )
    rng = np.random.RandomState(7)
    C, M = 33, 5
    args = (rng.randint(0, 2000, C).astype(np.int32),
            rng.randint(0, 2000, C).astype(np.int32),
            rng.randint(-500, 40_000, M).astype(np.int32),
            rng.randint(-500, 40_000, M).astype(np.int32),
            rng.randint(1, 80_000, M).astype(np.int32),
            rng.randint(1, 80_000, M).astype(np.int32),
            rng.uniform(0, 3, M).astype(np.float32),
            rng.rand(M) > 0.3,
            rng.rand(C, M) > 0.2)
    dev = np.asarray(route_scores(*args))
    host = route_scores_host(*args)
    assert np.array_equal(dev, host)


# ------------------------------------------------------------------ gangs


def test_gang_routes_whole_cell_and_binds_there(two_cells):
    cells, router = two_cells
    gang = _gang("tg", 5)
    filler = [_pod(f"f{i}") for i in range(10)]
    router.admit(filler + gang)
    assert router.counters_snapshot()["routed_gangs"] == 1
    _drain(cells, router)
    homes = set()
    for c in cells:
        bound = c.bound_keys()
        members = [k for k in bound if k.startswith("default/tg-")]
        if members:
            homes.add(c.name)
            assert len(members) == 5, \
                f"gang split inside {c.name}: {members}"
    assert len(homes) == 1, f"gang spanned cells: {homes}"


# --------------------------------------------------- brownout exactly-once


def _bind_event_cells(cells):
    """Event-log audit surface: pod key -> set of cells whose store log
    EVER carried a bind event (Pod MODIFIED naming a node) for it."""
    seen = {}
    for c in cells:
        with c.api._lock:
            log = list(c.api._log)
        for ev in log:
            if ev.kind != "Pod" or ev.type != "MODIFIED":
                continue
            node = getattr(ev.obj, "node_name", "")
            if node:
                seen.setdefault(ev.obj.key(), set()).add(c.name)
    return seen


def test_brownout_spillover_is_exactly_once(two_cells):
    cells, router = two_cells
    alpha, beta = cells
    pods = [_pod(f"b{i}") for i in range(30)]
    router.admit(pods)
    # only beta's engine runs before the fault: whatever landed on
    # alpha is still pending there when it browns out
    beta.pump(4)
    evacuated = router.brownout("alpha")
    assert not router.aggs["alpha"].ready
    _drain(cells, router)
    router.recover("alpha")
    assert router.aggs["alpha"].ready
    # store truth: every pod bound exactly once, somewhere
    all_bound = {}
    for c in cells:
        for k, node in c.bound_keys().items():
            assert k not in all_bound, \
                f"{k} bound in two cells: {all_bound[k]} and {c.name}"
            all_bound[k] = c.name
        assert audit_duplicate_binds(c.api) == 0
    assert len(all_bound) == 30
    # event-log audit: one bound cell per pod EVER — an evacuated pod
    # left alpha's store before beta could bind it, so no pod key has
    # bind events in both logs
    for key, homes in _bind_event_cells(cells).items():
        assert len(homes) == 1, f"{key} has bind events in {homes}"
    if evacuated:
        assert router.counters_snapshot()["evacuated_moved"] == evacuated


def test_admit_wire_fault_replays_same_idem_key(two_cells):
    """An ambiguous ADMIT fault (reply lost AFTER the cell committed)
    replays the SAME idempotency key; the cell's idem cache converges
    the retry to the recorded answer — no pod double-enters."""
    cells, router = two_cells
    alpha = cells[0]
    real_admit = alpha.handle.admit
    state = {"fired": False}

    def flaky_admit(idem_key, pods):
        out = real_admit(idem_key, pods)
        if not state["fired"]:
            state["fired"] = True
            raise ConnectionError("reply lost after commit")
        return out

    alpha.handle.admit = flaky_admit
    router.admit([_pod(f"r{i}") for i in range(8)])
    assert state["fired"]
    pods, _rv = alpha.api.list("Pod")
    beta_pods, _rv = cells[1].api.list("Pod")
    names = sorted(p.name for p in pods) + sorted(
        p.name for p in beta_pods)
    assert names == sorted(f"r{i}" for i in range(8))
    # the replay hit the idem cache, not the store
    assert alpha.svc.counters_snapshot()["admit_replays"] == 0


# ---------------------------------------------------------- oracle A/B


def test_folded_aggregate_equals_store_oracle(two_cells):
    cells, router = two_cells
    alpha = cells[0]
    router.admit([_pod(f"o{i}") for i in range(20)])
    alpha.pump(6)
    cells[1].pump(6)
    d, _spilled = alpha.handle.cell_agg()
    folded = CellAggregate.from_dict(d)
    nodes, _rv = alpha.api.list("Node")
    pods, _rv = alpha.api.list("Pod")
    oracle = aggregate_from_lists(nodes, pods, cell="alpha")
    for key in ("nodes_total", "nodes_ready", "cpu_alloc_m",
                "mem_alloc_mib", "cpu_used_m", "mem_used_mib",
                "pending", "bound_total", "domains"):
        assert getattr(folded, key) == getattr(oracle, key), \
            f"fold/oracle diverge on {key}"
    # and the RELIST hydration path agrees on the capacity picture
    router.hydrate()
    hyd = router.aggs["alpha"]
    for key in ("nodes_total", "nodes_ready", "cpu_alloc_m",
                "mem_alloc_mib", "cpu_used_m", "mem_used_mib",
                "domains"):
        assert getattr(hyd, key) == getattr(oracle, key), \
            f"hydrate/oracle diverge on {key}"


def test_compacted_log_rebuild_matches_oracle():
    """A watch log compacted past the fold cursor forces the store-walk
    rebuild — the rebuilt column must equal the oracle too."""
    api = ApiServerLite(max_log=64)
    for i, n in enumerate(hollow_nodes(8)):
        n.labels["zone"] = f"g-z{i % 2}"
        api.create("Node", n)
    svc = CellService(api, cell="gamma")
    d, _sp = svc.cell_aggregate()
    assert d["nodes_total"] == 8
    # blow past the 64-event log bound so the cursor is too old
    for i in range(200):
        api.create("Pod", _pod(f"c{i}"))
    d, _sp = svc.cell_aggregate()
    assert svc.counters_snapshot()["agg_rebuilds"] == 1
    nodes, _rv = api.list("Node")
    pods, _rv = api.list("Pod")
    oracle = aggregate_from_lists(nodes, pods, cell="gamma")
    assert d["pending"] == oracle.pending == 200
    assert d["cpu_used_m"] == oracle.cpu_used_m


# ------------------------------------------------------------- satellites


def test_brownout_schedule_deterministic_and_bounded():
    from kubernetes_tpu.testing.churn import make_brownout_schedule
    a = make_brownout_schedule(["c0", "c1", "c2"], 10.0, down_s=2.0,
                               count=3, seed=42)
    b = make_brownout_schedule(["c0", "c1", "c2"], 10.0, down_s=2.0,
                               count=3, seed=42)
    assert a == b
    assert a != make_brownout_schedule(["c0", "c1", "c2"], 10.0,
                                       down_s=2.0, count=3, seed=43)
    busy = {}
    for op in a:
        assert 1.0 <= op.t <= 9.0
        assert busy.get(op.cell, -1.0) < op.t, "same-cell overlap"
        busy[op.cell] = op.t + op.down_s


def test_ab_ranges_overlap_helper():
    from bench import _ab_ranges_overlap
    assert _ab_ranges_overlap([1.0, 3.0], [2.5, 4.0])
    assert not _ab_ranges_overlap([1.0, 2.0], [3.0, 4.0])
    assert not _ab_ranges_overlap([], [1.0])
    assert _ab_ranges_overlap([2.0], [2.0])


def test_trend_single_core_churn_regression_not_gated():
    """A churn_vs_quiet drop on a 1-cpu box against a round with no
    recorded cpus is annotated single_core_band — reported, never
    fatal (the r11-vs-r19 attribution: box shape, not code)."""
    from kubernetes_tpu.observability.trend import find_regressions
    rounds = [(11, {"churn_vs_quiet": 0.664}),
              (21, {"churn_vs_quiet": 0.386, "cpus": 1,
                    "churn_attribution": {"cpus": 1, "bar": 0.35}})]
    regs = find_regressions(rounds)
    assert len(regs) == 1
    assert "single_core_band" in regs[0]
    # WITHOUT the disclosed attribution the same drop still gates —
    # leniency must be earned by evidence in the artifact
    bare = find_regressions([(11, {"churn_vs_quiet": 0.664}),
                             (21, {"churn_vs_quiet": 0.386, "cpus": 1})])
    assert bare and "single_core_band" not in bare[0]
    # the main() fatal filter drops annotated regressions
    fatal = [g for g in regs
             if "box_change" not in g and "single_core_band" not in g]
    assert fatal == []
    # a genuinely same-shape 2-core drop still gates
    rounds2 = [(11, {"churn_vs_quiet": 0.664, "cpus": 2}),
               (21, {"churn_vs_quiet": 0.386, "cpus": 2})]
    regs2 = find_regressions(rounds2)
    assert regs2 and "single_core_band" not in regs2[0] \
        and "box_change" not in regs2[0]


def test_trend_knows_federation_headlines():
    from kubernetes_tpu.observability.trend import HEADLINE_METRICS
    keys = {k for k, _l, _d in HEADLINE_METRICS}
    assert {"federation_agg_nodes", "federation_router_p99_ms",
            "federation_spillover_bound"} <= keys


def test_unroutable_pods_backlog_then_admit_after_capacity():
    """A pod no ready cell fits goes to the router backlog (counted
    unroutable), and pump_backlog admits it once capacity appears."""

    class _Dummy:
        def __init__(self, name):
            self.name = name
            self.batches = []

        def admit(self, idem_key, pods):
            self.batches.append(list(pods))
            return len(pods), 0

        def close(self):
            pass

    cell = _Dummy("solo")
    router = FederationRouter([cell])
    agg = CellAggregate(cell="solo", ready=True, nodes_total=2,
                        nodes_ready=2, cpu_alloc_m=1000,
                        mem_alloc_mib=1024, cpu_used_m=900,
                        mem_used_mib=0)
    router.aggs["solo"] = agg
    router.admit([_pod("big", cpu=500, mem=64 << 20)])
    assert len(router.backlog) == 1
    assert router.counters_snapshot()["unroutable"] == 1
    assert cell.batches == []
    with router._lock:
        router.aggs["solo"].cpu_used_m = 100
    assert router.pump_backlog() == 1
    assert [p.name for b in cell.batches for p in b] == ["big"]
