"""Dynamic admission: webhook + imagepolicy + initializers
(admission/webhook.py), driven through the REAL ApiServer chain against an
in-process HTTP backend — the shape of tests/test_extender_http.py and the
reference's httptest-backed webhook admission tests
(plugin/pkg/admission/webhook/admission_test.go,
imagepolicy/admission_test.go)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubernetes_tpu.admission.chain import AdmissionChain, Rejected
from kubernetes_tpu.admission.webhook import (
    AdmissionHookConfiguration,
    GenericAdmissionWebhook,
    ImagePolicyWebhook,
    InitializerConfiguration,
    Initializers,
    PENDING_INITIALIZERS_ANNOTATION,
    Rule,
    WebhookHook,
    is_uninitialized,
    remove_initializer,
)
from kubernetes_tpu.api.types import make_pod
from kubernetes_tpu.server.apiserver import ApiServer


class WebhookBackend:
    """Scriptable admission backend. `decide(review) -> response dict`."""

    def __init__(self, decide):
        self.decide = decide
        self.reviews = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                review = json.loads(self.rfile.read(length))
                outer.reviews.append(review)
                body = json.dumps(outer.decide(review)).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}/admit"

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


def mk_server(*plugins):
    api = ApiServer()
    api.admission = AdmissionChain(list(plugins), store=api.store)
    return api


# ------------------------------------------------------------- webhook


def test_validating_webhook_denies_through_the_chain():
    backend = WebhookBackend(lambda review: {
        "response": {"allowed":
                     "forbidden" not in review["request"]["name"],
                     "status": {"message": "name is forbidden"}}})
    try:
        hook = WebhookHook(name="name-police", url=backend.url,
                           rules=[Rule(operations=["CREATE"],
                                       kinds=["Pod"])])
        api = mk_server(GenericAdmissionWebhook([hook]))
        api.create("Pod", make_pod("ok-pod", cpu=10))  # allowed
        with pytest.raises(Rejected) as e:
            api.create("Pod", make_pod("forbidden-pod", cpu=10))
        assert "name-police" in str(e.value)
        assert "name is forbidden" in str(e.value)
        # the denied pod never reached storage
        assert [p.name for p in api.store.list("Pod")[0]] == ["ok-pod"]
        # the review carried the serialized object + user identity keys
        assert backend.reviews[0]["request"]["object"]["metadata"][
            "name"] == "ok-pod"
    finally:
        backend.stop()


def test_mutating_webhook_patches_the_object():
    def decide(review):
        obj = dict(review["request"]["object"])
        obj["metadata"].setdefault("labels", {})["injected"] = "true"
        return {"response": {"allowed": True, "patchedObject": obj}}

    backend = WebhookBackend(decide)
    try:
        hook = WebhookHook(name="injector", url=backend.url, mutating=True,
                           rules=[Rule(operations=["CREATE"],
                                       kinds=["Pod"])])
        api = mk_server(GenericAdmissionWebhook([hook]))
        api.create("Pod", make_pod("p", cpu=10))
        stored = api.store.get("Pod", "default", "p")
        assert stored.labels.get("injected") == "true"
    finally:
        backend.stop()


def test_mutating_webhook_cannot_steal_identity_or_wipe_fields():
    """A hook's patchedObject only lands on the mutable spec surface:
    renames/re-namespacing are ignored (identity was authorized + audited
    already), and fields the wire encoding doesn't carry (annotations)
    survive the round-trip instead of being wiped."""
    def decide(review):
        obj = dict(review["request"]["object"])
        obj["metadata"] = dict(obj["metadata"])
        obj["metadata"]["name"] = "evil"
        obj["metadata"]["namespace"] = "kube-system"
        obj["metadata"].setdefault("labels", {})["injected"] = "true"
        return {"response": {"allowed": True, "patchedObject": obj}}

    backend = WebhookBackend(decide)
    try:
        hook = WebhookHook(name="thief", url=backend.url, mutating=True,
                           rules=[Rule(operations=["CREATE"],
                                       kinds=["Pod"])])
        api = mk_server(GenericAdmissionWebhook([hook]))
        pod = make_pod("p", cpu=10)
        pod.annotations["keep"] = "me"
        api.create("Pod", pod)
        stored = api.store.get("Pod", "default", "p")  # original identity
        assert stored.labels.get("injected") == "true"  # mutation applied
        assert stored.annotations.get("keep") == "me"  # nothing wiped
        with pytest.raises(Exception):
            api.store.get("Pod", "kube-system", "evil")
    finally:
        backend.stop()


def test_failure_policy_ignore_vs_fail():
    dead_url = "http://127.0.0.1:1/admit"  # nothing listens on port 1
    rules = [Rule(operations=["CREATE"], kinds=["Pod"])]
    # Ignore (the reference default): fail-open
    api = mk_server(GenericAdmissionWebhook(
        [WebhookHook(name="down", url=dead_url, rules=rules,
                     failure_policy="Ignore", timeout_s=0.5)]))
    api.create("Pod", make_pod("p1", cpu=10))
    # Fail: fail-closed
    api2 = mk_server(GenericAdmissionWebhook(
        [WebhookHook(name="down", url=dead_url, rules=rules,
                     failure_policy="Fail", timeout_s=0.5)]))
    with pytest.raises(Rejected) as e:
        api2.create("Pod", make_pod("p2", cpu=10))
    assert "down" in str(e.value)


def test_hook_configs_load_from_the_api():
    """Hooks registered as AdmissionHookConfiguration API objects take
    effect on subsequent requests — the dynamic half of 'dynamic
    admission' (the reference watches admissionregistration objects)."""
    backend = WebhookBackend(lambda review: {
        "response": {"allowed": False, "status": {"message": "nope"}}})
    try:
        api = mk_server(GenericAdmissionWebhook())
        api.create("Pod", make_pod("before", cpu=10))  # no hooks yet
        api.store.create(
            "AdmissionHookConfiguration",
            AdmissionHookConfiguration(
                name="deny-all",
                hooks=[WebhookHook(name="deny", url=backend.url,
                                   rules=[Rule(operations=["CREATE"],
                                               kinds=["Pod"])])]))
        with pytest.raises(Rejected):
            api.create("Pod", make_pod("after", cpu=10))
        # removing the configuration restores admission
        api.store.delete("AdmissionHookConfiguration", "", "deny-all")
        api.create("Pod", make_pod("after2", cpu=10))
    finally:
        backend.stop()


# --------------------------------------------------------- imagepolicy


def test_image_policy_webhook_denies_by_image():
    backend = WebhookBackend(lambda review: {
        "status": {"allowed": not any(
            "evil" in c["image"]
            for c in review["spec"]["containers"]),
            "reason": "image on deny list"}})
    try:
        api = mk_server(ImagePolicyWebhook(backend.url))
        ok = make_pod("ok", cpu=10)
        ok.containers[0].image = "registry/app:v1"
        api.create("Pod", ok)
        bad = make_pod("bad", cpu=10)
        bad.containers[0].image = "registry/evil:v1"
        with pytest.raises(Rejected) as e:
            api.create("Pod", bad)
        assert "deny list" in str(e.value)
    finally:
        backend.stop()


def test_image_policy_default_allow_on_backend_error():
    dead = "http://127.0.0.1:1/review"
    api = mk_server(ImagePolicyWebhook(dead, default_allow=True,
                                       timeout_s=0.5))
    api.create("Pod", make_pod("p", cpu=10))  # fail-open
    api2 = mk_server(ImagePolicyWebhook(dead, default_allow=False,
                                        timeout_s=0.5))
    with pytest.raises(Rejected):
        api2.create("Pod", make_pod("p2", cpu=10))  # fail-closed


# -------------------------------------------------------- initializers


def test_initializers_stamp_hide_and_release():
    api = mk_server(Initializers())
    api.store.create(
        "InitializerConfiguration",
        InitializerConfiguration(name="pod-init",
                                 initializers=["podimage.example.com"],
                                 kinds=["Pod"]))
    api.create("Pod", make_pod("p", cpu=10))
    stored = api.store.get("Pod", "default", "p")
    assert stored.annotations[PENDING_INITIALIZERS_ANNOTATION] \
        == "podimage.example.com"
    assert is_uninitialized(stored)
    # hidden from normal LIST; visible with includeUninitialized
    assert api.list("Pod")[0] == []
    assert [p.name for p in
            api.list("Pod", include_uninitialized=True)[0]] == ["p"]
    # the initializer controller completes its work
    remove_initializer(api.store, "Pod", stored, "podimage.example.com")
    visible = api.list("Pod")[0]
    assert [p.name for p in visible] == ["p"]
    assert not is_uninitialized(visible[0])


def test_initializers_only_touch_matching_kinds():
    api = mk_server(Initializers([InitializerConfiguration(
        name="svc-only", initializers=["x.example.com"],
        kinds=["Service"])]))
    api.create("Pod", make_pod("p", cpu=10))
    assert PENDING_INITIALIZERS_ANNOTATION not in \
        api.store.get("Pod", "default", "p").annotations
