"""Async binary fleet wire (ISSUE 11): the transport swap moved NO
semantics.

server/asyncwire.py serves the binary framing from ONE event loop over
the same service core (server/embedded.py VerdictService) the HTTP
extender delegates to. These tests pin:

  - the fleet scheduleOne contract end to end over the binary wire
    (fused verdict, fenced bind, ledger replay, snapshot generations);
  - TRANSPORT EQUIVALENCE: the ISSUE 9 injected-fault client storm and
    the tight-fleet fence-conflict scenario re-run over this wire with
    the same store-truth ONE-bound-node-per-pod audit (zero duplicates);
  - the robustness envelope as typed FRAMES: OVERLOADED + retry-after
    past the pending bound, DEADLINE for queued-dead work;
  - the frame fuzzer: corrupt/truncated/garbage streams and poisoned
    payloads shed cleanly with typed errors and never wedge the event
    loop or leak a pending ticket.
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.client.binarywire import (
    BinaryWireClient,
    WireDeadline,
    WireError,
    WireOverloaded,
)
from kubernetes_tpu.models.hollow import hollow_nodes
from kubernetes_tpu.server import framing
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.server.asyncwire import AsyncBinaryServer
from kubernetes_tpu.server.embedded import VerdictService
from kubernetes_tpu.server.extender import TPUExtenderBackend
from kubernetes_tpu.testing.churn import FaultyBindApi, extender_store_binder

N_NODES = 96


def _pod(name: str, cpu: int = 100):
    return make_pod(name, cpu=cpu, memory=256 << 20)


def _serve(nodes=None, binder=None, stale_window_s=0.02, **srv_kw):
    backend = TPUExtenderBackend(binder=binder,
                                 stale_window_s=stale_window_s,
                                 coalesce_window_s=0.0005)
    nodes = nodes if nodes is not None else hollow_nodes(N_NODES)
    backend.sync_nodes(nodes)
    backend.filter(_pod("warm"), None, None)
    srv = AsyncBinaryServer(VerdictService(backend), **srv_kw)
    srv.start()
    return backend, srv


def _counters(backend):
    with backend._counters_lock:
        return dict(backend._counters)


# ------------------------------------------------------------ happy path


def test_wire_scheduleone_end_to_end():
    backend, srv = _serve()
    try:
        c = BinaryWireClient("127.0.0.1", srv.port).connect()
        c.ping()
        pod = _pod("e2e")
        v = c.filter_fused(pod, top_k=8, deadline_ms=10_000)
        assert v.all_passed and v.passed_count == N_NODES
        assert v.passed is None  # compact elision over the wire
        assert v.snapshot_gen is not None and len(v.top_scores) == 8
        node = v.top_scores[0][0]
        r = c.bind("e2e", "default", pod.uid, node,
                   snapshot_gen=v.snapshot_gen, idem_key="e2e:1", pod=pod)
        assert r.ok, r
        # idempotent replay over the wire: no second assume
        pods0 = backend.cache.pod_count()
        r = c.bind("e2e", "default", pod.uid, node,
                   snapshot_gen=v.snapshot_gen, idem_key="e2e:1", pod=pod)
        assert r.ok and backend.cache.pod_count() == pods0
        # wire-level coalescing + the replay are visible in the counters
        snap = _counters(backend)
        assert snap.get("wire_batches", 0) >= 1
        assert snap.get("bind_replays", 0) == 1
        assert "tpu_extender_wire_batches_total" in c.metrics()
        c.close()
    finally:
        srv.stop()


def test_wire_sync_replaces_cluster_membership():
    backend, srv = _serve()
    try:
        c = BinaryWireClient("127.0.0.1", srv.port).connect()
        small = [make_node(f"s-{i}", cpu=4000, memory=8 << 30)
                 for i in range(4)]
        assert c.sync_nodes(small) == 4
        v = c.filter_fused(_pod("after-sync"), top_k=8)
        assert v.passed_count == 4
        assert {h for h, _s in v.top_scores} == {n.name for n in small}
        c.close()
    finally:
        srv.stop()


# ----------------------------------------------- transport equivalence


def test_wire_fence_conflict_typed_and_retryable():
    """The tight-fleet fence scenario over the binary wire (the HTTP
    twin lives in test_extender_multifrontend.py): a racing commit at
    the same generation answers a typed retryable CONFLICT frame, and
    the retry against a fresh verdict succeeds elsewhere."""
    tiny = [make_node(f"tiny-{i}", cpu=1000, memory=4 << 30, pods=110)
            for i in range(2)]
    # always-fresh verdicts, like the HTTP twin: this test pins the
    # FENCE, not the stale-window memo
    backend, srv = _serve(nodes=tiny, stale_window_s=0.0)
    try:
        c = BinaryWireClient("127.0.0.1", srv.port).connect()
        spec = make_pod("a", cpu=900, memory=256 << 20)
        v = c.filter_fused(spec, top_k=4, deadline_ms=10_000)
        assert v.passed_count == 2
        gen = v.snapshot_gen
        r = c.bind("a", "default", "u-a", "tiny-0", snapshot_gen=gen,
                   idem_key="a:1", pod=spec)
        assert r.ok
        spec_b = make_pod("b", cpu=900, memory=256 << 20)
        r = c.bind("b", "default", "u-b", "tiny-0", snapshot_gen=gen,
                   idem_key="b:1", pod=spec_b)
        assert r.kind == "conflict" and r.error.startswith("CONFLICT")
        assert r.retry_after_s > 0
        v2 = c.filter_fused(spec_b, top_k=4)
        assert [h for h, _s in v2.top_scores] == ["tiny-1"]
        r = c.bind("b", "default", "u-b", "tiny-1",
                   snapshot_gen=v2.snapshot_gen, idem_key="b:2", pod=spec_b)
        assert r.ok
        c.close()
    finally:
        srv.stop()


def test_wire_storm_exactly_once_under_faults():
    """TRANSPORT EQUIVALENCE, the headline audit: the ISSUE 9 8-client
    injected-fault storm re-run over the binary wire — failures AND
    landed timeouts injected at the store, conflicts retried, ambiguous
    attempts replayed on the same ledger key — and the store-truth audit
    still shows ONE bound node per pod, ever."""
    api = ApiServerLite(max_log=100_000)
    nodes = hollow_nodes(N_NODES)
    for n in nodes:
        api.create("Node", n)
    faulty = FaultyBindApi(api, fail_rate=0.10, timeout_rate=0.10, seed=11)
    backend, srv = _serve(nodes=nodes,
                          binder=extender_store_binder(faulty))
    n_clients, per = 8, 10
    for c_ in range(n_clients):
        for i in range(per):
            api.create("Pod", _pod(f"wstorm-{c_}-{i}"))
    errors, lock = [], threading.Lock()
    start = threading.Barrier(n_clients)

    def drive(ci):
        rng = random.Random(4200 + ci)
        cli = BinaryWireClient("127.0.0.1", srv.port, timeout=30).connect()
        try:
            start.wait(timeout=20)
            for i in range(per):
                name = f"wstorm-{ci}-{i}"
                spec = _pod(name)
                bound = False
                for attempt in range(30):
                    try:
                        v = cli.filter_fused(spec, top_k=16,
                                             deadline_ms=10_000)
                    except WireOverloaded as e:
                        time.sleep(e.retry_after_s * rng.uniform(0.5, 1.5))
                        continue
                    except WireDeadline:
                        continue
                    scores = v.top_scores or []
                    if not scores:
                        time.sleep(0.01 * rng.uniform(0.5, 1.5))
                        continue
                    best = scores[0][1]
                    top = [h for h, s in scores if s == best]
                    node = top[rng.randrange(len(top))]
                    try:
                        r = cli.bind(name, "default", spec.uid, node,
                                     snapshot_gen=v.snapshot_gen,
                                     idem_key=f"{name}:{attempt}", pod=spec)
                    except WireOverloaded as e:
                        time.sleep(e.retry_after_s * rng.uniform(0.5, 1.5))
                        continue
                    if r.ok:
                        bound = True
                        break
                    if r.retryable:
                        time.sleep(r.retry_after_s * rng.uniform(0.5, 1.5))
                        continue
                    if "already assigned" in r.error:
                        bound = True  # landed earlier; store is truth
                        break
                    if r.kind == "error":
                        # ambiguous: same key converges via the ledger
                        r2 = cli.bind(name, "default", spec.uid, node,
                                      idem_key=f"{name}:{attempt}",
                                      pod=spec)
                        if r2.ok or "already assigned" in r2.error:
                            bound = True
                            break
                    # clean failure / shed: fresh attempt, fresh key
                if not bound:
                    raise AssertionError(f"{name} never bound")
        except Exception as e:  # noqa: BLE001 — surfaced below
            with lock:
                errors.append(f"client {ci}: {type(e).__name__}: {e}")
        finally:
            cli.close()

    threads = [threading.Thread(target=drive, args=(ci,))
               for ci in range(n_clients)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    finally:
        srv.stop()
    assert not errors, errors
    pods, _rv = api.list("Pod")
    storm = [p for p in pods if p.name.startswith("wstorm-")]
    assert len(storm) == n_clients * per
    assert all(p.node_name for p in storm)
    first_node = {}
    for e in api._log:
        if e.kind == "Pod" and e.type == "MODIFIED" and e.obj.node_name \
                and e.obj.name.startswith("wstorm-"):
            prev = first_node.setdefault(e.obj.name, e.obj.node_name)
            assert prev == e.obj.node_name, \
                f"duplicate bind: {e.obj.name} -> {prev} AND " \
                f"{e.obj.node_name}"
    assert faulty.injected_failures + faulty.injected_timeouts > 0
    snap = _counters(backend)
    assert snap.get("bind_errors", 0) > 0  # faults really exercised
    assert snap.get("wire_batches", 0) >= 1


# ------------------------------------------------------- backpressure


def test_wire_overloaded_frame_past_pending_bound():
    backend, srv = _serve(max_pending=1)
    entered = threading.Event()
    release = threading.Event()
    real = backend._eval_many

    def slow(pods):
        entered.set()
        release.wait(timeout=10)
        return real(pods)

    backend._eval_many = slow
    results, overloads, lock = [], [], threading.Lock()

    def drive(i):
        cli = BinaryWireClient("127.0.0.1", srv.port, timeout=30).connect()
        try:
            v = cli.filter_fused(_pod(f"ovl-{i}"), top_k=4)
            with lock:
                results.append(v.passed_count)
        except WireOverloaded as e:
            assert e.retry_after_s > 0
            with lock:
                overloads.append(e)
        finally:
            cli.close()

    try:
        # leader batch: popped off the pending list, stalls in the worker
        t1 = threading.Thread(target=drive, args=(0,))
        t1.start()
        assert entered.wait(timeout=10)
        # fills the one pending slot behind the stalled batch
        t2 = threading.Thread(target=drive, args=(1,))
        t2.start()
        deadline = time.monotonic() + 10
        while len(srv._pend) < 1:
            assert time.monotonic() < deadline, "ticket never queued"
            time.sleep(0.002)
        # ...and everything past the bound sheds with the typed frame
        for i in range(2, 6):
            drive(i)
        release.set()
        t1.join(timeout=30)
        t2.join(timeout=30)
    finally:
        backend._eval_many = real
        srv.stop()
    assert len(overloads) == 4, overloads
    assert sorted(results) == [N_NODES, N_NODES]
    assert _counters(backend).get("admission_shed", 0) == 4


def test_wire_deadline_sheds_queued_dead_work():
    backend, srv = _serve()
    entered = threading.Event()
    release = threading.Event()
    real = backend._eval_many

    def slow(pods):
        entered.set()
        release.wait(timeout=10)
        return real(pods)

    backend._eval_many = slow
    outcomes, lock = [], threading.Lock()

    def drive(i, deadline_ms):
        cli = BinaryWireClient("127.0.0.1", srv.port, timeout=30).connect()
        try:
            cli.filter_fused(_pod(f"dl-{i}"), top_k=4,
                             deadline_ms=deadline_ms)
            with lock:
                outcomes.append("served")
        except WireDeadline:
            with lock:
                outcomes.append("shed")
        finally:
            cli.close()

    try:
        t1 = threading.Thread(target=drive, args=(0, 0))
        t1.start()
        assert entered.wait(timeout=10)
        # queued behind the stalled batch with a 1ms deadline: by the
        # time the next batch forms it is queued-dead and must shed
        t2 = threading.Thread(target=drive, args=(1, 1))
        t2.start()
        deadline = time.monotonic() + 10
        while len(srv._pend) < 1:
            assert time.monotonic() < deadline, "ticket never queued"
            time.sleep(0.002)
        time.sleep(0.05)  # let the 1ms deadline expire while queued
        release.set()
        t1.join(timeout=30)
        t2.join(timeout=30)
    finally:
        backend._eval_many = real
        srv.stop()
    assert sorted(outcomes) == ["served", "shed"]
    assert _counters(backend).get("deadline_shed", 0) >= 1


# ------------------------------------------------------------ frame fuzz


def _raw(port: int) -> socket.socket:
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    s.settimeout(10)
    return s


def _recv_frames(sock, want: int = 1):
    dec = framing.FrameDecoder()
    frames = []
    while len(frames) < want:
        data = sock.recv(65536)
        if not data:
            break
        frames.extend(dec.feed(data))
    return frames


def test_fuzz_corrupt_length_answers_error_and_closes():
    backend, srv = _serve()
    try:
        s = _raw(srv.port)
        s.sendall(b"POST /filter HTTP/1.1\r\n\r\n")  # ASCII as u32: huge
        frames = _recv_frames(s)
        assert frames and frames[0][0] == framing.ERROR
        assert "FrameError" in framing.decode_error(frames[0][3])
        # stream desync: the server closes after the typed error
        assert s.recv(65536) == b""
        s.close()
        # the LOOP is not wedged: a fresh connection serves normally
        c = BinaryWireClient("127.0.0.1", srv.port).connect()
        assert c.filter_fused(_pod("after-fuzz"), top_k=4).passed_count \
            == N_NODES
        c.close()
        assert _counters(backend).get("wire_frame_errors", 0) >= 1
    finally:
        srv.stop()


def test_fuzz_poisoned_payload_keeps_connection():
    """A frame whose LENGTH is honest but whose payload lies (garbage pod
    blob) is a payload-scoped fault: typed ERROR, connection keeps
    serving — the head-of-line discipline of the HTTP unknown-path
    audit, on the binary wire."""
    backend, srv = _serve()
    try:
        s = _raw(srv.port)
        s.sendall(framing.encode_frame(framing.FILTER, 9, b"\xde\xad\xbe"))
        frames = _recv_frames(s)
        assert frames[0][0] == framing.ERROR and frames[0][2] == 9
        # same connection, valid request: still served
        s.sendall(framing.encode_frame(framing.PING, 10))
        frames = _recv_frames(s)
        assert frames[0][0] == framing.PONG and frames[0][2] == 10
        # unknown verb: typed too, connection still alive
        s.sendall(framing.encode_frame(0x55, 11))
        frames = _recv_frames(s)
        assert frames[0][0] == framing.ERROR
        assert "unknown verb" in framing.decode_error(frames[0][3])
        s.sendall(framing.encode_frame(framing.PING, 12))
        assert _recv_frames(s)[0][0] == framing.PONG
        s.close()
    finally:
        srv.stop()


def test_fuzz_truncated_and_interleaved_partial_writes():
    """Truncated frames (client dies mid-write) and partial writes
    dribbled byte-by-byte: the server reassembles honest streams and
    cleans up dishonest ones without wedging or leaking tickets."""
    backend, srv = _serve()
    try:
        # (a) dribble a VALID filter frame one byte at a time
        frame = framing.encode_frame(
            framing.FILTER, 21,
            framing.encode_filter_request(_pod("dribble"), 4, 10_000),
            flags=framing.FLAG_COMPACT)
        s = _raw(srv.port)
        for i in range(0, len(frame), 3):
            s.sendall(frame[i:i + 3])
            time.sleep(0.0005)
        frames = _recv_frames(s)
        assert frames[0][0] == framing.VERDICT and frames[0][2] == 21
        s.close()
        # (b) truncated mid-frame then the peer vanishes: no response
        # owed, nothing leaks
        s = _raw(srv.port)
        s.sendall(frame[:17])
        s.close()
        # (c) oversized declared length: typed error + close
        s = _raw(srv.port)
        s.sendall(struct.pack("!IBBI", framing.MAX_FRAME + 7,
                              framing.FILTER, 0, 1))
        frames = _recv_frames(s)
        assert frames and frames[0][0] == framing.ERROR
        s.close()
        # (d) random garbage soup, several connections
        rng = random.Random(0xFA22)
        for _ in range(5):
            s = _raw(srv.port)
            s.sendall(bytes(rng.randrange(256) for _ in range(257)))
            try:
                _recv_frames(s)  # error frame or straight close — either
            except OSError:
                pass
            s.close()
        # the loop survives it all and no ticket/in-flight state leaked
        c = BinaryWireClient("127.0.0.1", srv.port).connect()
        v = c.filter_fused(_pod("post-soup"), top_k=4)
        assert v.passed_count == N_NODES
        c.close()
        deadline = time.monotonic() + 5
        while (srv._pend or srv._inflight) and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not srv._pend and srv._inflight == 0
    finally:
        srv.stop()


def test_client_rejects_mismatched_response_id():
    backend, srv = _serve()
    try:
        c = BinaryWireClient("127.0.0.1", srv.port).connect()
        # hand-roll a request whose id the client did not issue
        c._sock.sendall(framing.encode_frame(framing.PING, 999))
        with pytest.raises(WireError, match="response id"):
            c.ping()
        c.close()
    finally:
        srv.stop()

def test_client_surfaces_stream_level_error_message():
    """A corrupt length prefix makes the server answer ERROR with request
    id 0 (it cannot attribute an id to a desynced stream). The CLIENT
    must surface the server's message, not diagnose a bogus id
    mismatch."""
    backend, srv = _serve()
    try:
        c = BinaryWireClient("127.0.0.1", srv.port).connect()
        c._sock.sendall(b"GET / HTTP/1.1\r\n\r\n")  # ASCII as u32: huge
        with pytest.raises(WireError, match="FrameError"):
            c.ping()
        c.close()
    finally:
        srv.stop()


def test_stop_resolves_queued_bind_tickets():
    """stop() must resolve queued BIND tickets too (not only filters) and
    give the awaiting coroutines a loop cycle to write their ERROR
    responses — a blocking client must fail fast, not sit in recv()
    until its socket timeout."""
    import threading as _threading

    ev = _threading.Event()

    def slow_binder(name, ns, uid, node):
        ev.set()
        time.sleep(0.5)  # holds the dispatcher's worker round busy
        return ""

    backend, srv = _serve(binder=slow_binder, max_batch=1)
    outcomes = []

    def drive(i):
        c = BinaryWireClient("127.0.0.1", srv.port, timeout=30).connect()
        try:
            c.bind(f"stp-{i}", "default", f"u-{i}", "hollow-node-0",
                   idem_key=f"stp:{i}")
            outcomes.append("ok")
        except (WireError, OSError) as e:
            outcomes.append(str(e))
        finally:
            c.close()

    threads = [_threading.Thread(target=drive, args=(i,)) for i in range(3)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    assert ev.wait(10)  # first bind is ON the worker; others queue
    time.sleep(0.05)
    srv.stop()
    for t in threads:
        t.join(timeout=10)
    elapsed = time.perf_counter() - t0
    assert len(outcomes) == 3, outcomes
    # nobody waited out a socket timeout: the queued tickets resolved to
    # typed "server stopped" errors (or the in-flight one bound fine)
    assert elapsed < 10, elapsed
    assert all(o == "ok" or "server stopped" in o
               or "closed connection" in o for o in outcomes), outcomes


# ------------------------------------------- tsan-lite storm leg (ISSUE 19)


def test_lockcheck_leg_wire_scheduleone_bit_identical(monkeypatch):
    """scheduleOne over the binary wire with GRAFT_LOCKCHECK=1: the
    armed world (event loop, coalescer condition, fence, ledger, store
    condition — all checked twins) returns the same verdict, the same
    top scores, and a working idempotent bind, with zero recorded
    lock-discipline violations."""
    from kubernetes_tpu.analysis import lockcheck

    pod = _pod("lc-wire")
    backend, srv = _serve()  # unarmed reference
    try:
        c = BinaryWireClient("127.0.0.1", srv.port).connect()
        want = c.filter_fused(pod, top_k=8, deadline_ms=10_000)
        c.close()
    finally:
        srv.stop()

    monkeypatch.setenv("GRAFT_LOCKCHECK", "1")
    lockcheck.reset()
    api = ApiServerLite()
    nodes = hollow_nodes(N_NODES)
    for n in nodes:
        api.create("Node", n)
    api.create("Pod", pod)  # the store binder binds STORE pods
    binder = extender_store_binder(FaultyBindApi(api))
    backend, srv = _serve(nodes=nodes, binder=binder)
    try:
        c = BinaryWireClient("127.0.0.1", srv.port).connect()
        v = c.filter_fused(pod, top_k=8, deadline_ms=10_000)
        assert v.passed_count == want.passed_count == N_NODES
        assert v.top_scores == want.top_scores  # bit-identical ranking
        node = v.top_scores[0][0]
        r = c.bind("lc-wire", "default", pod.uid, node,
                   snapshot_gen=v.snapshot_gen, idem_key="lc:1", pod=pod)
        assert r.ok, r
        pods0 = backend.cache.pod_count()
        r = c.bind("lc-wire", "default", pod.uid, node,
                   snapshot_gen=v.snapshot_gen, idem_key="lc:1", pod=pod)
        assert r.ok and backend.cache.pod_count() == pods0
        c.close()
    finally:
        srv.stop()
    lockcheck.assert_clean()
