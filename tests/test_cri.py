"""The CRI-shaped runtime boundary (nodes/cri.py, kuberuntime.py,
images.py).

What the reference tests at this seam: kuberuntime_manager_test.go
(computePodActions table cases), image_gc_manager_test.go (threshold + LRU
+ in-use protection), image_manager_test.go (pull policies), and the
kubemark thesis that the SAME kubelet runs against a fake runtime
(hollow-node.go:119-121) or a real one — here proven by running the hollow
kubelet unchanged against the process runtime.
"""

import time

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.nodes.cri import (
    CREATED,
    EXITED,
    RUNNING,
    ContainerConfig,
    FakeRuntimeService,
    PodSandboxConfig,
    ProcessRuntimeService,
    SANDBOX_NOTREADY,
)
from kubernetes_tpu.nodes.images import (
    ImageGCManager,
    ImageGCPolicy,
    ImageManager,
    ImagePullError,
)
from kubernetes_tpu.nodes.kubelet import HollowFleet, HollowKubelet
from kubernetes_tpu.nodes.kuberuntime import RuntimeManager
from kubernetes_tpu.server.apiserver_lite import ApiServerLite


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# ------------------------------------------------------- FakeRuntimeService


def test_fake_runtime_sandbox_and_container_lifecycle():
    clock = FakeClock()
    rt = FakeRuntimeService(boot_latency=2.0, now=clock)
    sid = rt.run_pod_sandbox(PodSandboxConfig(name="p", namespace="ns"))
    cid = rt.create_container(sid, ContainerConfig(name="c", image="img"))
    assert rt.container_status(cid).state == CREATED
    rt.start_container(cid)
    # boot latency: still CREATED until the clock advances
    assert rt.container_status(cid).state == CREATED
    clock.t += 2.0
    assert rt.container_status(cid).state == RUNNING
    rt.stop_container(cid)
    st = rt.container_status(cid)
    assert st.state == EXITED and st.exit_code == 137
    rt.stop_container(cid)  # idempotent
    assert rt.container_status(cid).exit_code == 137
    rt.stop_pod_sandbox(sid)
    assert rt.pod_sandbox_status(sid).state == SANDBOX_NOTREADY
    rt.remove_pod_sandbox(sid)
    assert rt.pod_sandbox_status(sid) is None
    assert rt.list_containers(sandbox_id=sid) == []


def test_fake_runtime_scripted_exit_and_attempts():
    clock = FakeClock()
    rt = FakeRuntimeService(now=clock)
    sid = rt.run_pod_sandbox(PodSandboxConfig(name="p"))
    cid = rt.create_container(
        sid, ContainerConfig(name="c", run_seconds=5.0, fail_exit=True))
    rt.start_container(cid)
    assert rt.container_status(cid).state == RUNNING
    clock.t += 5.0
    st = rt.container_status(cid)
    assert st.state == EXITED and st.exit_code == 1
    # same-named container again = attempt 1 (restart counting rides this)
    cid2 = rt.create_container(sid, ContainerConfig(name="c"))
    assert rt.container_status(cid2).attempt == 1


# ---------------------------------------------------------- RuntimeManager


def mk_manager(clock=None, boot_latency=0.0):
    clock = clock or FakeClock()
    rt = FakeRuntimeService(boot_latency=boot_latency, now=clock)
    mgr = RuntimeManager(rt, image_manager=ImageManager(rt), now=clock)
    return rt, mgr, clock


def test_compute_pod_actions_fresh_pod_creates_sandbox():
    _, mgr, _ = mk_manager()
    pod = make_pod("p", cpu=100)
    actions = mgr.compute_pod_actions(pod, mgr.pod_status(pod))
    assert actions.create_sandbox
    assert len(actions.containers_to_start) == 1
    # executing them converges: second sync is a no-op
    mgr.sync_pod(pod)
    again = mgr.compute_pod_actions(pod, mgr.pod_status(pod))
    assert not again.create_sandbox and not again.containers_to_start


def test_kubelet_killed_pod_with_restart_policy_never_reports_failed():
    """ADVICE r5 low (kuberuntime.py:162): every latest attempt EXITED 137
    (kubelet-killed) and restartPolicy Never forbids a fresh attempt —
    pod_status must report a terminal Failed phase, or the pod sits in the
    kubelet's _starting set unready forever. Mirrors the reference's
    GetPhase: stopped containers that cannot restart fail the pod."""
    rt, mgr, clock = mk_manager()
    pod = make_pod("p", cpu=100)
    pod.restart_policy = "Never"
    mgr.sync_pod(pod)
    mgr.restart_pod_containers(pod)  # liveness path: CRI kill -> exit 137
    st = mgr.pod_status(pod)
    assert st.completed_phase == "Failed"
    # and compute_pod_actions still refuses a fresh attempt
    actions = mgr.compute_pod_actions(pod, st)
    assert not actions.containers_to_start and not actions.create_sandbox


def test_kubelet_killed_pod_with_restartable_policy_stays_pending():
    """Same 137 state under restartPolicy Always: NOT terminal — the next
    sync starts a fresh attempt instead."""
    rt, mgr, clock = mk_manager()
    pod = make_pod("p", cpu=100)
    mgr.sync_pod(pod)
    mgr.restart_pod_containers(pod)
    st = mgr.pod_status(pod)
    assert st.completed_phase == ""
    actions = mgr.compute_pod_actions(pod, st)
    assert actions.containers_to_start  # fresh attempt queued


def test_compute_pod_actions_restarts_killed_not_completed():
    rt, mgr, clock = mk_manager()
    pod = make_pod("p", cpu=100)
    pod.annotations["bench/run-seconds"] = "3"
    mgr.sync_pod(pod)
    clock.t += 3.0
    st = mgr.pod_status(pod)
    assert st.completed_phase == "Succeeded"
    # natural completion: no restart even with restartPolicy Always
    actions = mgr.compute_pod_actions(pod, st)
    assert not actions.containers_to_start
    # a KILLED container (exit 137) does restart under Always
    pod2 = make_pod("p2", cpu=100)
    mgr.sync_pod(pod2)
    mgr.restart_pod_containers(pod2)
    actions = mgr.compute_pod_actions(pod2, mgr.pod_status(pod2))
    assert len(actions.containers_to_start) == 1
    mgr.sync_pod(pod2)
    assert mgr.pod_status(pod2).restarts == 1


def test_kill_pod_removes_sandbox():
    rt, mgr, _ = mk_manager()
    pod = make_pod("p", cpu=100)
    mgr.sync_pod(pod)
    assert mgr.sandbox_ready(pod.key())
    mgr.kill_pod(pod.key())
    assert not mgr.sandbox_ready(pod.key())
    assert rt.list_pod_sandboxes() == []


# ----------------------------------------------- kubelet drives the CRI


def mk_fleet(n_nodes=1, clock=None, **kw):
    clock = clock or FakeClock()
    api = ApiServerLite()
    factory = SharedInformerFactory(api)
    fleet = HollowFleet(api, factory, now=clock, **kw)
    for i in range(n_nodes):
        fleet.add_node(make_node(f"n{i}", cpu=1000, memory=1 << 30, pods=8))
    factory.step_all()
    return api, factory, fleet, clock


def test_kubelet_lifecycle_flows_through_cri_ops():
    api, factory, fleet, clock = mk_fleet()
    kubelet = fleet.kubelets["n0"]
    rt = kubelet.runtime
    pod = make_pod("p", cpu=100, node_name="n0")
    pod.annotations["bench/run-seconds"] = "4"
    api.create("Pod", pod)
    factory.step_all()
    fleet.step()
    assert rt.ops.get("RunPodSandbox") == 1
    assert rt.ops.get("StartContainer") == 1
    assert rt.ops.get("PullImage") == 1
    assert api.get("Pod", "default", "p").phase == "Running"
    clock.t += 4.0
    fleet.step()
    assert api.get("Pod", "default", "p").phase == "Succeeded"
    # teardown reached the runtime once the final status round-tripped
    factory.step_all()
    fleet.step()
    assert rt.ops.get("RemovePodSandbox") == 1
    assert rt.list_pod_sandboxes() == []


def test_process_runtime_plugs_in_without_kubelet_changes():
    """The boundary's proof: a kubelet constructed with the REAL
    process-spawning runtime (sandbox = pause process) runs a pod to
    completion — no kubelet code knows which runtime is behind it."""
    api = ApiServerLite()
    factory = SharedInformerFactory(api)
    node = make_node("n0", cpu=1000, memory=1 << 30, pods=8)
    rt = ProcessRuntimeService()
    kubelet = HollowKubelet(api, node, runtime=rt)  # wall clock
    try:
        kubelet.register()
        pod = make_pod("p", cpu=100, node_name="n0")
        pod.annotations["bench/run-seconds"] = "0"
        api.create("Pod", pod)
        kubelet.handle_pod(pod)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            kubelet.step()
            if api.get("Pod", "default", "p").phase == "Succeeded":
                break
            time.sleep(0.05)
        assert api.get("Pod", "default", "p").phase == "Succeeded"
        # the sandbox really was a process (pause binary or sleep)
        assert rt.list_pod_sandboxes() != []
    finally:
        rt.close()


def test_process_runtime_failing_workload():
    rt = ProcessRuntimeService()
    try:
        mgr = RuntimeManager(rt)
        pod = make_pod("f", cpu=100)
        pod.annotations["bench/run-seconds"] = "0"
        pod.annotations["bench/fail"] = "1"
        mgr.sync_pod(pod)
        deadline = time.monotonic() + 10.0
        phase = ""
        while time.monotonic() < deadline:
            phase = mgr.pod_status(pod).completed_phase
            if phase:
                break
            time.sleep(0.05)
        assert phase == "Failed"
    finally:
        rt.close()


# ------------------------------------------------------------------ images


def test_image_pull_policies():
    rt = FakeRuntimeService()
    im = ImageManager(rt)
    pod = make_pod("p", cpu=100)
    im.ensure_image_exists(pod, "app:v1")  # IfNotPresent default: pulls
    im.ensure_image_exists(pod, "app:v1")  # present: no pull
    assert im.pulls == 1
    pod.annotations["bench/image-pull-policy"] = "Always"
    im.ensure_image_exists(pod, "app:v1")
    assert im.pulls == 2
    pod.annotations["bench/image-pull-policy"] = "Never"
    with pytest.raises(ImagePullError):
        im.ensure_image_exists(pod, "ghost:v1")


def test_image_gc_policy_validation():
    with pytest.raises(ValueError):
        ImageGCPolicy(high_threshold_percent=101)
    with pytest.raises(ValueError):
        ImageGCPolicy(low_threshold_percent=-1)
    with pytest.raises(ValueError):
        ImageGCPolicy(high_threshold_percent=50, low_threshold_percent=60)


def test_image_gc_lru_with_in_use_protection():
    clock = FakeClock()
    rt = FakeRuntimeService(now=clock)
    gc = ImageGCManager(rt, capacity_bytes=1000,
                        policy=ImageGCPolicy(85, 50))
    rt.pull_image("old:v1", size_bytes=400)
    clock.t += 10
    rt.pull_image("used:v1", size_bytes=300)
    clock.t += 10
    rt.pull_image("new:v1", size_bytes=200)
    # "used" is referenced by a container -> protected
    sid = rt.run_pod_sandbox(PodSandboxConfig(name="p"))
    rt.create_container(sid, ContainerConfig(name="c", image="used:v1"))
    # usage 900/1000 = 90% >= high 85 -> free down to 50% (500)
    freed = gc.garbage_collect()
    assert freed >= 400
    refs = {i.ref for i in rt.list_images()}
    assert "used:v1" in refs  # in-use protected
    assert "old:v1" not in refs  # LRU victim first
    assert rt.image_fs_info() <= 500
    # below threshold: next pass is a no-op
    assert gc.garbage_collect() == 0


def test_disk_pressure_reclaims_images_before_evicting_pods():
    """eviction_manager.go reclaimNodeLevelResources: image GC satisfies
    the disk signal, so no pod dies."""
    clock = FakeClock()
    api = ApiServerLite()
    factory = SharedInformerFactory(api)
    fleet = HollowFleet(api, factory, now=clock)
    node = make_node("n0", cpu=1000, memory=1 << 30, pods=8)
    node.allocatable.storage_scratch = 1 << 30  # a real image/scratch fs
    fleet.add_node(node)
    factory.step_all()
    kubelet = fleet.kubelets["n0"]
    rt = kubelet.runtime
    pod = make_pod("p", cpu=100, node_name="n0")
    api.create("Pod", pod)
    factory.step_all()
    fleet.step()
    assert api.get("Pod", "default", "p").phase == "Running"
    # stuff the image fs past the node's disk eviction limit with an
    # unused image; the pod itself uses almost nothing
    disk_cap = kubelet.eviction.disk_limit
    assert disk_cap > 0
    rt.pull_image("fat:v1", size_bytes=disk_cap + 1000)
    fleet.step()
    # image GC reclaimed; the pod survived and pressure cleared
    assert api.get("Pod", "default", "p").phase == "Running"
    assert not kubelet.eviction.disk_pressure
    assert "fat:v1" not in {i.ref for i in rt.list_images()}


def test_liveness_restart_rides_cri_attempts():
    """The kubelet's liveness restart is CRI kill + fresh attempt; the
    runtime's attempt counter matches the kubelet's restart count."""
    from kubernetes_tpu.api.types import Probe
    api, factory, fleet, clock = mk_fleet()
    kubelet = fleet.kubelets["n0"]
    pod = make_pod("p", cpu=100, node_name="n0")
    pod.containers[0].liveness_probe = Probe(period_s=1.0,
                                             failure_threshold=1)
    pod.annotations["bench/liveness-fail-at"] = "5"
    api.create("Pod", pod)
    factory.step_all()
    fleet.step()
    assert api.get("Pod", "default", "p").phase == "Running"
    clock.t += 6.0
    fleet.step()  # liveness fails -> kill
    fleet.step()  # fresh attempt running again
    assert api.get("Pod", "default", "p").restart_count == 1
    assert kubelet.runtime_mgr.pod_status(pod).restarts == 1


def test_node_allocatable_reservation():
    """--kube-reserved semantics (pkg/kubelet/cm/node_container_manager.go):
    the node registers allocatable = capacity - reserved; the scheduler
    and node-side admission see only the allocatable slice."""
    from kubernetes_tpu.api.types import Resource
    api = ApiServerLite()
    node = make_node("n0", cpu=4000, memory=8 << 30)
    kl = HollowKubelet(api, node,
                       reserved=Resource(milli_cpu=500, memory=1 << 30))
    kl.register()
    reg = api.get("Node", "", "n0")
    assert reg.allocatable.milli_cpu == 3500
    assert reg.allocatable.memory == 7 << 30
    assert reg.capacity.milli_cpu == 4000  # capacity still published
    # node-side admission enforces the RESERVED boundary, not capacity
    big = make_pod("big", cpu=3600, node_name="n0")
    api.create("Pod", big)
    kl.handle_pod(big)
    kl.step()
    p = api.get("Pod", "default", "big")
    assert p.phase == "Failed"
    assert p.annotations["kubernetes.io/failure-reason"] == "OutOfcpu"
    ok = make_pod("fits", cpu=3400, node_name="n0")
    api.create("Pod", ok)
    kl.handle_pod(ok)
    kl.step()
    assert api.get("Pod", "default", "fits").phase == "Running"
