"""Policy-format compatibility + no-dead-knob suite.

Mirror of the reference's compatibility test
(plugin/pkg/scheduler/algorithmprovider/defaults/compatibility_test.go):
every v1.7 Policy knob must (a) parse from the reference's JSON wire format
and (b) OBSERVABLY change scheduling behavior — a knob that parses and then
does nothing is a lying config file (VERDICT r3 missing #4 / weak #6).

Behavior targets:
  ServiceAffinity      predicates.go:783  (label-homogeneous service pods)
  NodeLabelPresence    predicates.go:717
  ServiceAntiAffinity  selector_spreading.go:220
  NodeLabel preference node_label.go:45
"""

from __future__ import annotations

import copy
import random

import pytest

from kubernetes_tpu.api.policy import parse_policy
from kubernetes_tpu.api.types import WorkloadObject, make_node, make_pod
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.engine.scheduler_engine import SchedulingEngine
from kubernetes_tpu.ops import oracle
from kubernetes_tpu.ops.policy_algos import (
    NodeLabelPresencePred,
    NodeLabelPrio,
    ServiceAffinityPred,
    ServiceAntiAffinityPrio,
    algorithms_from_policy,
)
from kubernetes_tpu.ops.oracle_ext import SchedulingContext
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.node_info import node_info_map
from tests.helpers import Gi

# The full v1.7 knob surface in the reference's JSON wire format (same
# format --policy-config-file accepts; custom names carry the argument, as
# in compatibility_test.go's "TestServiceAffinity")
V17_POLICY_JSON = """{
  "kind": "Policy",
  "apiVersion": "v1",
  "predicates": [
    {"name": "MatchNodeSelector"},
    {"name": "PodFitsResources"},
    {"name": "PodFitsHostPorts"},
    {"name": "HostName"},
    {"name": "NoDiskConflict"},
    {"name": "NoVolumeZoneConflict"},
    {"name": "MaxEBSVolumeCount"},
    {"name": "MaxGCEPDVolumeCount"},
    {"name": "MaxAzureDiskVolumeCount"},
    {"name": "MatchInterPodAffinity"},
    {"name": "GeneralPredicates"},
    {"name": "PodToleratesNodeTaints"},
    {"name": "CheckNodeMemoryPressure"},
    {"name": "CheckNodeDiskPressure"},
    {"name": "CheckNodeCondition"},
    {"name": "NoVolumeNodeConflict"},
    {"name": "CustomServiceAffinity",
     "argument": {"serviceAffinity": {"labels": ["region"]}}},
    {"name": "CustomLabelsPresence",
     "argument": {"labelsPresence": {"labels": ["foo"], "presence": true}}}
  ],
  "priorities": [
    {"name": "LeastRequestedPriority", "weight": 1},
    {"name": "BalancedResourceAllocation", "weight": 1},
    {"name": "SelectorSpreadPriority", "weight": 1},
    {"name": "InterPodAffinityPriority", "weight": 1},
    {"name": "NodePreferAvoidPodsPriority", "weight": 10000},
    {"name": "NodeAffinityPriority", "weight": 1},
    {"name": "TaintTolerationPriority", "weight": 1},
    {"name": "CustomServiceAntiAffinity", "weight": 3,
     "argument": {"serviceAntiAffinity": {"label": "zone"}}},
    {"name": "CustomLabelPreference", "weight": 4,
     "argument": {"labelPreference": {"label": "bar", "presence": true}}}
  ],
  "extenders": [
    {"urlPrefix": "http://127.0.0.1:12346/scheduler",
     "filterVerb": "filter", "prioritizeVerb": "prioritize",
     "weight": 5, "enableHttps": false, "nodeCacheCapable": true}
  ]
}"""


def test_v17_policy_parses_every_knob():
    pol = parse_policy(V17_POLICY_JSON)
    assert len(pol.predicates) == 18
    assert len(pol.priorities) == 9
    kernel_prios, algos = algorithms_from_policy(pol)
    assert ServiceAffinityPred(("region",)) in algos.predicates
    assert NodeLabelPresencePred(("foo",), True) in algos.predicates
    assert ServiceAntiAffinityPrio("zone", 3) in algos.priorities
    assert NodeLabelPrio("bar", True, 4) in algos.priorities
    assert ("NodePreferAvoidPodsPriority", 10000) in kernel_prios
    assert pol.extenders[0].node_cache_capable is True
    assert pol.extenders[0].weight == 5


def test_unknown_names_raise():
    with pytest.raises(ValueError, match="unknown predicate"):
        algorithms_from_policy(parse_policy(
            '{"predicates": [{"name": "NoSuchPredicate"}]}'))
    with pytest.raises(ValueError, match="unknown priority"):
        algorithms_from_policy(parse_policy(
            '{"priorities": [{"name": "NoSuchPriority", "weight": 1}]}'))


# ---------------------------------------------------------------- behavior


def _engine(nodes, existing, workloads, policy_json, mode="strict"):
    kernel_prios, algos = algorithms_from_policy(parse_policy(policy_json))
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in existing:
        cache.add_pod(copy.deepcopy(p))
    eng = SchedulingEngine(cache, priorities=kernel_prios,
                           workloads_provider=lambda: workloads,
                           policy_algos=algos)
    return eng


@pytest.mark.parametrize("mode", ["strict", "wave"])
def test_labels_presence_required_filters(mode):
    nodes = [make_node("labeled", labels={"foo": "x"}),
             make_node("bare")]
    eng = _engine(nodes, [], [], """{
      "predicates": [{"name": "P", "argument":
        {"labelsPresence": {"labels": ["foo"], "presence": true}}}],
      "priorities": [{"name": "EqualPriority", "weight": 1}]}""")
    res = eng.schedule([make_pod(f"p{i}", cpu=100) for i in range(4)],
                       mode=mode)
    assert all(r.node_name == "labeled" for r in res)


@pytest.mark.parametrize("mode", ["strict", "wave"])
def test_labels_presence_forbidden_filters(mode):
    nodes = [make_node("labeled", labels={"retiring": "2017"}),
             make_node("bare")]
    eng = _engine(nodes, [], [], """{
      "predicates": [{"name": "P", "argument":
        {"labelsPresence": {"labels": ["retiring"], "presence": false}}}],
      "priorities": [{"name": "EqualPriority", "weight": 1}]}""")
    res = eng.schedule([make_pod(f"p{i}", cpu=100) for i in range(4)],
                       mode=mode)
    assert all(r.node_name == "bare" for r in res)


SA_POLICY = """{
  "predicates": [{"name": "SA", "argument":
    {"serviceAffinity": {"labels": ["region"]}}}],
  "priorities": [{"name": "EqualPriority", "weight": 1}]}"""


def test_service_affinity_pins_to_existing_pod_region():
    """First service pod ran in region r2 -> all later service pods must
    stay in r2 (predicates.go:798-846 backfill from pods[0]'s node)."""
    nodes = [make_node(f"n-r1-{i}", labels={"region": "r1"}) for i in range(2)] \
        + [make_node(f"n-r2-{i}", labels={"region": "r2"}) for i in range(2)]
    first = make_pod("svc-first", cpu=100, labels={"app": "a"},
                     node_name="n-r2-0")
    svc = WorkloadObject("Service", "svc", "default", match_labels={"app": "a"})
    eng = _engine(nodes, [first], [svc], SA_POLICY)
    res = eng.schedule([make_pod(f"p{i}", cpu=100, labels={"app": "a"})
                        for i in range(3)])
    assert all(r.node_name.startswith("n-r2-") for r in res)


@pytest.mark.parametrize("mode", ["strict", "wave"])
def test_service_affinity_pins_in_batch(mode):
    """No existing pods: the batch's OWN first placement pins the region for
    the rest — in-batch visibility through the cache-backed pod lister
    (factory.go:139), which routes these classes to the host path."""
    nodes = [make_node("a-r1", labels={"region": "r1"}),
             make_node("b-r2", labels={"region": "r2"})]
    svc = WorkloadObject("Service", "svc", "default", match_labels={"app": "a"})
    eng = _engine(nodes, [], [svc], SA_POLICY, mode)
    res = eng.schedule([make_pod(f"p{i}", cpu=100, labels={"app": "a"})
                        for i in range(4)], mode=mode)
    regions = {r.node_name[-2:] for r in res}
    assert len(regions) == 1, f"service pods split regions: {res}"


def test_service_affinity_without_service_uses_node_selector_only():
    nodes = [make_node("r1", labels={"region": "r1"}),
             make_node("r2", labels={"region": "r2"})]
    eng = _engine(nodes, [], [], SA_POLICY)
    pod = make_pod("p0", cpu=100, node_selector={"region": "r2"})
    res = eng.schedule([pod])
    assert res[0].node_name == "r2"
    # and with no selector at all, both nodes stay feasible
    eng2 = _engine(nodes, [], [], SA_POLICY)
    assert eng2.schedule([make_pod("p1", cpu=100)])[0].fit_count == 2


@pytest.mark.parametrize("mode", ["strict", "wave"])
def test_node_label_priority_prefers(mode):
    nodes = [make_node("plain"), make_node("preferred", labels={"bar": "1"})]
    eng = _engine(nodes, [], [], """{
      "priorities": [{"name": "L", "weight": 4, "argument":
        {"labelPreference": {"label": "bar", "presence": true}}}]}""", mode)
    res = eng.schedule([make_pod(f"p{i}", cpu=100) for i in range(3)],
                       mode=mode)
    assert all(r.node_name == "preferred" for r in res)


def test_service_anti_affinity_spreads_across_label_values():
    nodes = [make_node("z1", labels={"zone": "z1"}),
             make_node("z2", labels={"zone": "z2"})]
    svc = WorkloadObject("Service", "svc", "default", match_labels={"app": "a"})
    existing = make_pod("svc-0", cpu=100, labels={"app": "a"},
                        node_name="z1")
    eng = _engine(nodes, [existing], [svc], """{
      "priorities": [{"name": "AA", "weight": 3, "argument":
        {"serviceAntiAffinity": {"label": "zone"}}}]}""")
    res = eng.schedule([make_pod("p0", cpu=100, labels={"app": "a"})])
    assert res[0].node_name == "z2"


def test_scheduler_accepts_policy_end_to_end():
    """Policy flows through the daemon wrapper (factory.go:619 path)."""
    api = ApiServerLite()
    api.create("Node", make_node("labeled", labels={"foo": "x"}))
    api.create("Node", make_node("bare"))
    for i in range(3):
        api.create("Pod", make_pod(f"p{i}", cpu=100))
    sched = Scheduler(api, record_events=False, policy=parse_policy("""{
      "predicates": [{"name": "P", "argument":
        {"labelsPresence": {"labels": ["foo"], "presence": true}}}],
      "priorities": [{"name": "LeastRequestedPriority", "weight": 1}]}"""))
    sched.start()
    totals = sched.run_until_drained()
    assert totals["bound"] == 3
    pods, _ = api.list("Pod")
    assert all(p.node_name == "labeled" for p in pods)


# ------------------------------------------------------- oracle differential


def _policy_oracle_sequence(nodes, existing, workloads, pending,
                            kernel_prios, algos):
    infos = node_info_map(nodes, existing)
    names = sorted(infos.keys())
    rr = oracle.RoundRobin()
    ctx = SchedulingContext(infos, workloads, policy_algos=algos)
    out = []
    for pod in pending:
        name = oracle.schedule_one(pod, names, infos, rr, kernel_prios, ctx)
        out.append(name)
        if name is not None:
            p = copy.deepcopy(pod)
            p.node_name = name
            infos[name].add_pod(p)
            ctx.invalidate()
    return out


FUZZ_POLICY = """{
  "predicates": [
    {"name": "GeneralPredicates"},
    {"name": "NLP", "argument":
      {"labelsPresence": {"labels": ["ok"], "presence": true}}},
    {"name": "SA", "argument": {"serviceAffinity": {"labels": ["region"]}}}
  ],
  "priorities": [
    {"name": "LeastRequestedPriority", "weight": 1},
    {"name": "AA", "weight": 3, "argument":
      {"serviceAntiAffinity": {"label": "zone"}}},
    {"name": "LP", "weight": 4, "argument":
      {"labelPreference": {"label": "fast", "presence": true}}}
  ]}"""


@pytest.mark.parametrize("seed", [0, 1, 2, 5])
def test_policy_fuzz_engine_matches_oracle(seed):
    """Randomized differential: strict engine with ALL four policy knobs
    active must match the object-level oracle placement-for-placement."""
    rng = random.Random(seed)
    nodes = []
    for i in range(8):
        labels = {"host": f"h{i}"}
        if rng.random() < 0.8:
            labels["ok"] = "1"
        if rng.random() < 0.7:
            labels["region"] = f"r{rng.randint(0, 2)}"
        if rng.random() < 0.7:
            labels["zone"] = f"z{rng.randint(0, 2)}"
        if rng.random() < 0.5:
            labels["fast"] = "ssd"
        nodes.append(make_node(f"node-{i}", cpu=8000, memory=32 * Gi,
                               labels=labels))
    apps = ["a", "b", "c"]
    workloads = [WorkloadObject("Service", f"svc-{a}", "default",
                                match_labels={"app": a})
                 for a in apps if rng.random() < 0.8]
    existing = []
    for i in range(6):
        p = make_pod(f"bound-{i}", cpu=100, labels={"app": rng.choice(apps)})
        p.node_name = rng.choice(nodes).name
        existing.append(p)
    pending = [make_pod(f"pend-{i}", cpu=rng.choice([100, 400]),
                        labels={"app": rng.choice(apps)})
               for i in range(12)]

    kernel_prios, algos = algorithms_from_policy(parse_policy(FUZZ_POLICY))
    want = _policy_oracle_sequence(nodes, existing, workloads,
                                   pending, kernel_prios, algos)
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in existing:
        cache.add_pod(copy.deepcopy(p))
    eng = SchedulingEngine(cache, priorities=kernel_prios,
                           workloads_provider=lambda: workloads,
                           policy_algos=algos)
    got = [r.node_name
           for r in eng.schedule([copy.deepcopy(p) for p in pending])]
    assert got == want
