"""Threaded stress of the shared mutable state: cache, queue, store, and
the watch+schedule interleaving.

The reference leans on `-race` builds (hack/make-rules/test.sh:107
KUBE_RACE) and construction (single scheduleOne goroutine, mutex-guarded
caches — schedulercache/cache.go:50); Python has no race detector, so
these tests hammer the same invariants under real threads:

  - SchedulerCache: assume/confirm/forget/expire from competing threads
    leaves balanced node accounting
  - SchedulingQueue: concurrent producers/consumers pop every pod exactly
    once
  - ApiServerLite: racing binders bind every pod exactly once; a watcher
    sees a strictly-increasing rv stream covering every write
  - Scheduler vs churn: a live scheduler drains while another thread keeps
    creating pods — converges with zero double binds

Also covers the proxy healthcheck server (pkg/proxy/healthcheck) since it
is probed concurrently by external LBs in the reference.
"""

from __future__ import annotations

import threading

from kubernetes_tpu.api.types import Binding, make_node, make_pod
from kubernetes_tpu.engine.queue import SchedulingQueue
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.state.cache import SchedulerCache

Gi = 1 << 30


def _run_threads(fns):
    errors = []

    def wrap(fn):
        def go():
            try:
                fn()
            except Exception as e:  # pragma: no cover - failure path
                errors.append(e)
        return go

    threads = [threading.Thread(target=wrap(fn)) for fn in fns]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "stress thread wedged"
    assert not errors, errors


def test_cache_concurrent_assume_confirm_forget_balances():
    cache = SchedulerCache(ttl_seconds=1000.0)
    cache.add_node(make_node("n0", cpu=10_000_000, memory=1000 * Gi))
    base = cache.node_infos()["n0"].requested.milli_cpu
    n_per = 300

    def assume_then_forget(tag):
        def go():
            for i in range(n_per):
                p = make_pod(f"{tag}-{i}", cpu=7, node_name="n0")
                cache.assume_pod(p)
                cache.finish_binding(p)
                if i % 2:
                    cache.forget_pod(p)
                else:
                    cache.add_pod(p)   # informer confirm
                    cache.remove_pod(p)  # and deletion
        return go

    _run_threads([assume_then_forget(f"t{k}") for k in range(4)])
    info = cache.node_infos()["n0"]
    assert info.requested.milli_cpu == base
    assert not info.pods
    assert cache.pod_count() == 0


def test_queue_concurrent_producers_consumers_exactly_once():
    q = SchedulingQueue()
    n_producers, n_per = 4, 250
    total = n_producers * n_per
    popped = []
    popped_lock = threading.Lock()
    done = threading.Event()

    def producer(tag):
        def go():
            for i in range(n_per):
                q.add(make_pod(f"{tag}-{i}"))
        return go

    def consumer():
        while not done.is_set() or len(q):
            batch = q.pop_batch(max_n=16, wait=0.01)
            if batch:
                with popped_lock:
                    popped.extend(p.key() for p in batch)
            with popped_lock:
                if len(popped) >= total:
                    return

    producers = [producer(f"p{k}") for k in range(n_producers)]
    consumers = [consumer, consumer]

    def run_producers():
        _run_threads(producers)
        done.set()

    prod_thread = threading.Thread(target=run_producers)
    prod_thread.start()
    _run_threads(consumers)
    prod_thread.join(timeout=60)
    assert len(popped) == total
    assert len(set(popped)) == total, "a pod was popped twice"


def test_apiserver_racing_binders_bind_exactly_once():
    api = ApiServerLite()
    api.create("Node", make_node("n0"))
    n_pods = 400
    for i in range(n_pods):
        api.create("Pod", make_pod(f"p{i:03d}", cpu=10))
    conflicts = []
    lock = threading.Lock()

    def binder(offset):
        def go():
            errs = 0
            # every binder tries EVERY pod: exactly one thread can win each
            for i in range(n_pods):
                j = (i + offset) % n_pods
                out = api.bind_many([Binding(f"p{j:03d}", "default", "",
                                             "n0")])
                if out[0] is not None:
                    errs += 1
            with lock:
                conflicts.append(errs)
        return go

    _run_threads([binder(k * 100) for k in range(4)])
    pods, _ = api.list("Pod")
    assert all(p.node_name == "n0" for p in pods)
    # 4 attempts per pod, exactly 1 success: 3 conflicts each
    assert sum(conflicts) == 3 * n_pods


def test_watcher_sees_monotonic_rv_stream_under_writes():
    api = ApiServerLite(max_log=100_000)
    stop = threading.Event()
    seen = []

    def writer():
        for i in range(500):
            api.create("Pod", make_pod(f"w-{i:03d}"))
        stop.set()

    def watcher():
        rv = 0
        while True:
            evs = api.watch_since(("Pod",), rv, timeout=0.05)
            for ev in evs:
                assert ev.rv > rv, "rv went backwards"
                rv = ev.rv
                seen.append(ev.rv)
            if stop.is_set() and not evs:
                return

    _run_threads([writer, watcher])
    assert len(seen) == 500
    assert seen == sorted(seen)


def test_scheduler_drains_under_concurrent_churn():
    from kubernetes_tpu.engine.scheduler import Scheduler

    api = ApiServerLite()
    for i in range(20):
        api.create("Node", make_node(f"n{i:02d}", cpu=64_000,
                                     memory=256 * Gi))
    sched = Scheduler(api, record_events=False)
    sched.start()
    n_pods = 600
    created = threading.Event()

    def churn():
        for i in range(n_pods):
            api.create("Pod", make_pod(f"c-{i:03d}", cpu=50))
        created.set()

    totals = {"bound": 0, "bind_errors": 0}

    def drain():
        while not created.is_set() or any(
                not p.node_name for p in api.list("Pod")[0]):
            stats = sched.schedule_round(wait=0.01)
            totals["bound"] += stats["bound"]
            totals["bind_errors"] += stats["bind_errors"]

    _run_threads([churn, drain])
    assert totals["bound"] == n_pods
    assert totals["bind_errors"] == 0
    pods, _ = api.list("Pod")
    assert all(p.node_name for p in pods)


# --------------------------------------------------------- proxy healthz


def test_proxy_healthcheck_server_reports_local_endpoints():
    import json
    import urllib.request
    import urllib.error

    from kubernetes_tpu.api.workloads import Service, ServicePort
    from kubernetes_tpu.client.informer import SharedInformerFactory
    from kubernetes_tpu.controllers.endpoint import EndpointController
    from kubernetes_tpu.nodes.proxy import HollowProxy, ProxyHealthServer

    api = ApiServerLite()
    factory = SharedInformerFactory(api)
    api.create("Service", Service("svc", "default", selector={"app": "w"},
                                  ports=[ServicePort(port=80)]))
    p0 = make_pod("w0", cpu=10, labels={"app": "w"}, node_name="n0")
    p0.phase = "Running"
    api.create("Pod", p0)
    epc = EndpointController(api, factory, record_events=False)
    proxy = HollowProxy(factory)
    factory.step_all()
    epc.pump()
    factory.step_all()
    hs0 = ProxyHealthServer(proxy, "n0")
    hs1 = ProxyHealthServer(proxy, "n1")
    hs0.start()
    hs1.start()
    try:
        def probe(port):
            url = f"http://127.0.0.1:{port}/healthz/default/svc"
            try:
                with urllib.request.urlopen(url, timeout=10) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        code0, body0 = probe(hs0.port)
        assert code0 == 200 and body0["localEndpoints"] == 1
        code1, body1 = probe(hs1.port)  # n1 has no local endpoint
        assert code1 == 503 and body1["localEndpoints"] == 0
    finally:
        hs0.stop()
        hs1.stop()
