"""Scheduler daemon composition + slow-schedule tracing.

Daemon: plugin/cmd/kube-scheduler/app/server.go:67 (healthz + metrics +
leader election + policy flags). Trace: the 100ms utiltrace dump of
core/generic_scheduler.go:89-90 / trace.go:33-90.
"""

from __future__ import annotations

import json
import urllib.request

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.server.daemon import SchedulerDaemon, SchedulerOptions
from kubernetes_tpu.utils.trace import Trace


# -------------------------------------------------------------------- trace


def test_trace_dumps_only_when_slow():
    t = {"now": 0.0}
    out = []
    tr = Trace("Scheduling round", now=lambda: t["now"],
               sink=out.append, pods=3)
    t["now"] = 0.02
    tr.step("informer sync done")
    t["now"] = 0.05
    tr.step("batch placement computed (device)")
    assert tr.log_if_long(0.1) is False and out == []  # fast: silent
    t["now"] = 0.31
    tr.step("bindings written")
    assert tr.log_if_long(0.1) is True
    dump = out[0]
    assert 'Trace "Scheduling round" pods=3' in dump
    assert "informer sync done" in dump and "(+30.0ms)" in dump
    assert "bindings written" in dump


def test_scheduler_round_emits_trace_when_over_threshold(monkeypatch):
    """The wired-in trace fires for a genuinely slow round."""
    import kubernetes_tpu.engine.scheduler as sched_mod

    api = ApiServerLite()
    api.create("Node", make_node("n0"))
    api.create("Pod", make_pod("p0", cpu=100))
    sched = sched_mod.Scheduler(api, record_events=False)
    sched.start()
    dumps = []
    real_trace = sched_mod.Trace
    monkeypatch.setattr(
        sched_mod, "Trace",
        lambda name, **kw: real_trace(name, sink=dumps.append, **kw))
    # force slowness: a schedule call that "takes" 5s via a patched engine
    real_schedule = sched.engine.schedule

    def slow_schedule(*a, **kw):
        import time as _t
        r = real_schedule(*a, **kw)
        _t.sleep(0.15)  # > 0.1s-per-pod threshold for a 1-pod round
        return r

    sched.engine.schedule = slow_schedule
    sched.schedule_round()
    assert len(dumps) == 1
    assert "batch placement computed (device)" in dumps[0]


# ------------------------------------------------------------------- daemon


def test_daemon_healthz_metrics_and_leader_endpoints():
    api = ApiServerLite()
    for i in range(4):
        api.create("Node", make_node(f"n{i}"))
    for i in range(8):
        api.create("Pod", make_pod(f"p{i}", cpu=100))
    d = SchedulerDaemon(api, "me", SchedulerOptions(healthz_port=0))
    try:
        d.step()  # acquire + schedule
        port = d.healthz_port
        assert port

        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10) as r:
                return r.read().decode()

        assert get("/healthz") == "ok"
        assert get("/leader") == "true"
        metrics = get("/metrics")
        assert "scheduler" in metrics  # prometheus text with histograms
        pods, _ = api.list("Pod")
        assert all(p.node_name for p in pods)
    finally:
        d.stop()


def test_daemon_policy_config_file(tmp_path):
    policy_file = tmp_path / "policy.json"
    policy_file.write_text(json.dumps({
        "predicates": [
            {"name": "GeneralPredicates"},
            {"name": "P", "argument": {"labelsPresence":
                                       {"labels": ["ok"], "presence": True}}},
        ],
        "priorities": [{"name": "LeastRequestedPriority", "weight": 1}],
    }))
    api = ApiServerLite()
    api.create("Node", make_node("labeled", labels={"ok": "1"}))
    api.create("Node", make_node("bare"))
    for i in range(4):
        api.create("Pod", make_pod(f"p{i}", cpu=100))
    d = SchedulerDaemon(
        api, "me", SchedulerOptions(healthz_port=None, leader_elect=False,
                                    policy_config_file=str(policy_file)))
    try:
        for _ in range(3):
            d.step()
        pods, _ = api.list("Pod")
        assert all(p.node_name == "labeled" for p in pods)
    finally:
        d.stop()


def test_daemon_demo_main(capsys):
    from kubernetes_tpu.server.daemon import main
    main(["--nodes", "10", "--pods", "40"])
    out = capsys.readouterr().out
    assert "bound=40/40" in out
    assert "leader=daemon-a" in out


def test_daemon_graceful_stop_releases_lease_for_immediate_handoff():
    """Graceful stop (release=True) zeroes the lease so the standby
    acquires WITHOUT waiting out lease_duration — contrast with the crash
    path in tests/test_chaos.py::test_daemon_failover_after_leader_crash."""
    from tests.test_nodes import FakeClock

    clock = FakeClock()
    api = ApiServerLite()
    api.create("Node", make_node("n0"))
    opts = SchedulerOptions(healthz_port=None)
    a = SchedulerDaemon(api, "a", opts, now=clock)
    b = SchedulerDaemon(api, "b", opts, now=clock)
    a.step()
    b.step()
    assert a.is_leader() and not b.is_leader()
    a.stop(release=True)
    assert api.get("Lease", "kube-system", "kube-scheduler").holder == ""
    b.step()  # NO clock advance needed
    assert b.is_leader()
    b.stop()


def test_released_lease_acquirable_even_at_small_clock_values():
    """Regression (review): holder=="" must read as free even when
    now < lease_duration — a FakeClock at t=1 or a freshly booted
    monotonic clock must not have to wait out a phantom lease."""
    from kubernetes_tpu.client.leaderelection import LeaderElector, LeaseLock
    from tests.test_nodes import FakeClock

    clock = FakeClock(t=1.0)  # below the 15s lease_duration
    api = ApiServerLite()
    lock = LeaseLock(api, "kube-scheduler")
    a = LeaderElector(lock, "a", now=clock)
    b = LeaderElector(lock, "b", now=clock)
    assert a.step() is True
    a.release()
    assert b.step() is True, "released lease must be immediately acquirable"


def test_daemon_from_component_config(tmp_path):
    """--config: a versioned KubeSchedulerConfiguration drives the daemon
    options (the reference's componentconfig path, types.go:158-198)."""
    import json

    from kubernetes_tpu.api.scheme import DEFAULT_SCHEME
    from kubernetes_tpu.server.daemon import SchedulerOptions

    cfg = DEFAULT_SCHEME.decode({
        "apiVersion": "componentconfig/v1alpha1",
        "kind": "KubeSchedulerConfiguration",
        "schedulerName": "tpu-sched",
        "healthzBindAddress": "127.0.0.1:0",
        "leaderElection": {"leaderElect": False,
                           "lockObjectName": "my-lock"}})
    opts = SchedulerOptions.from_component_config(cfg)
    assert opts.scheduler_name == "tpu-sched"
    assert opts.leader_elect is False
    assert opts.lock_object_name == "my-lock"
    assert opts.healthz_port == 0
