"""Extender hot-path: latency + vocab-isolation under adversarial churn.

VERDICT r3 weak #5 / task: /filter + /prioritize at 5k nodes must stay well
inside the reference's 5s extender budget (core/extender.go:36) under label
churn — previously every request with a fresh topology key / selector value
grew the shared snapshot vocab, forcing a full label-matrix rebuild (and a
recompile at the new width) per request. EvalCache
(engine/scheduler_engine.py) now isolates request-driven growth: churn pods
take the exact oracle, their pairs intern in one batch at the next sync.

The hard guarantees tested are STRUCTURAL (snapshot version stability,
oracle-route and build counters); the wall-clock p99 bound is a generous
CI-safe ceiling, far under the 5s budget.
"""

from __future__ import annotations

import time

import pytest

from kubernetes_tpu.api.types import (
    Affinity,
    NodeAffinity,
    NodeSelectorTerm,
    SelectorOperator,
    SelectorRequirement,
    make_pod,
)
from kubernetes_tpu.models.hollow import hollow_nodes
from kubernetes_tpu.server.extender import TPUExtenderBackend

N_NODES = 5000
BUDGET_S = 5.0  # extender.go:36 default HTTP timeout
CI_P99_S = 2.5  # generous CPU-CI ceiling, still ~2x under budget


@pytest.fixture(scope="module")
def backend():
    b = TPUExtenderBackend()
    nodes = hollow_nodes(N_NODES)
    for i, n in enumerate(nodes):  # zones so affinity domains exist
        n.labels["zone"] = f"z{i % 16}"
    b.sync_nodes(nodes)
    # warm: first call pays snapshot build + kernel compile
    b.filter(make_pod("warm", cpu=100), None, None)
    return b


def _churn_pod(i: int):
    """Fresh never-seen selector key+value every request (adversarial)."""
    req = SelectorRequirement(key=f"churn-key-{i}",
                              operator=SelectorOperator.IN,
                              values=[f"churn-val-{i}"])
    return make_pod(f"churn-{i}", cpu=100, affinity=Affinity(
        node_affinity=NodeAffinity(
            required_terms=[NodeSelectorTerm(match_expressions=[req])])))


def test_churn_requests_cannot_force_rebuilds(backend):
    snap = backend.engine.snapshot
    v0 = snap.version
    routes0 = backend.eval_cache.oracle_routes
    lat = []
    for i in range(25):
        t0 = time.perf_counter()
        passed, failed = backend.filter(_churn_pod(i), None, None)
        lat.append(time.perf_counter() - t0)
        assert passed == []  # no node carries the churned label
        assert len(failed) == N_NODES
    assert snap.version == v0, \
        "adversarial churn must not rebuild the shared snapshot"
    assert backend.eval_cache.oracle_routes == routes0 + 25
    lat.sort()
    p99 = lat[int(len(lat) * 0.99)]
    assert p99 < CI_P99_S < BUDGET_S, f"churn p99 {p99:.3f}s"


def test_image_churn_cannot_force_rebuilds(backend):
    """Container-image names intern into the snapshot too (ImageLocality);
    image churn must route like label churn, not rebuild per request."""
    snap = backend.engine.snapshot
    v0 = snap.version
    routes0 = backend.eval_cache.oracle_routes
    for i in range(10):
        p = make_pod(f"img-{i}", cpu=100)
        p.containers[0].image = f"registry.example/churn:{i}"
        passed, _ = backend.filter(p, None, None)
        assert len(passed) == N_NODES  # image only affects scoring
    assert snap.version == v0
    assert backend.eval_cache.oracle_routes == routes0 + 10


def test_steady_requests_hit_the_lru(backend):
    builds0 = backend.eval_cache.builds
    lat = []
    for i in range(25):
        t0 = time.perf_counter()
        passed, _ = backend.filter(make_pod(f"steady-{i}", cpu=100),
                                   None, None)
        lat.append(time.perf_counter() - t0)
        assert len(passed) == N_NODES
    # same spec class + same snapshot version -> at most one tensorization
    assert backend.eval_cache.builds <= builds0 + 1
    lat.sort()
    p99 = lat[int(len(lat) * 0.99)]
    assert p99 < CI_P99_S < BUDGET_S, f"steady p99 {p99:.3f}s"


def test_prioritize_scores_under_budget(backend):
    t0 = time.perf_counter()
    scores = backend.prioritize(make_pod("prio", cpu=100), None, None)
    dt = time.perf_counter() - t0
    assert len(scores) == N_NODES
    assert dt < BUDGET_S
    assert {s for _, s in scores} != {0}  # real integer scores, not a stub


def test_churned_pairs_intern_in_one_batch_at_next_sync(backend):
    """The queued churn pairs land in ONE vocab rebuild at the next cache
    sync, after which an equivalent pod takes the device path."""
    snap = backend.engine.snapshot
    assert backend.eval_cache._pending_pairs  # queued by the churn test
    nodes = backend.cache.node_infos()
    resync = [info.node for info in nodes.values() if info.node is not None]
    backend.sync_nodes(resync)
    routes_before = backend.eval_cache.oracle_routes
    passed, _ = backend.filter(_churn_pod(0), None, None)
    assert passed == []  # still fits nothing (no node has the label)
    # but it went through the device path this time, not the oracle
    assert backend.eval_cache.oracle_routes == routes_before
    assert not backend.eval_cache._pending_pairs
