"""Extender warm fast lane: device-resident state between requests.

The r6 perf work (VERDICT r5 "Next round" #1): a warm /filter+/prioritize
round must be a single fused [1,N] kernel dispatch over device-resident
cluster state — not a per-request snapshot rebuild. These tests pin the
STRUCTURE of that fast lane via the utils.trace.COUNTERS spans the lane
emits and the EvalCache's own counters:

  - a second /filter for an equivalent pod serves from the result memo:
    no AffinityData rebuild, no precompute_static re-run (the fused kernel
    — counted as extender.fused_eval — is not dispatched at all);
  - /prioritize after /filter rides the same evaluation (fused verbs);
  - sync_nodes invalidates everything: full refresh, re-encode,
    device re-upload;
  - a bind invalidates RESULTS (capacity moved) but keeps the encoding
    (vocab_gen keying) and refreshes exactly one dynamic row
    (snapshot.refresh changed_hint);
  - the warm path agrees with the stateless args-mode evaluation.
"""

from __future__ import annotations

import pytest

from kubernetes_tpu.api.types import (
    Affinity,
    PodAffinity,
    PodAffinityTerm,
    LabelSelector,
    make_node,
    make_pod,
)
from kubernetes_tpu.models.hollow import hollow_nodes
from kubernetes_tpu.server.extender import TPUExtenderBackend
from kubernetes_tpu.utils.trace import COUNTERS

N_NODES = 200


@pytest.fixture()
def backend():
    b = TPUExtenderBackend()
    nodes = hollow_nodes(N_NODES)
    for i, n in enumerate(nodes):
        n.labels["zone"] = f"z{i % 4}"
    b.sync_nodes(nodes)
    b.filter(make_pod("warm", cpu=100), None, None)  # compile + first encode
    return b


def _pod(name: str, cpu: int = 100):
    return make_pod(name, cpu=cpu, memory=256 << 20)


def test_second_filter_serves_from_result_memo(backend):
    """Equivalent pod, unchanged cluster: the second /filter must not
    rebuild AffinityData, re-run the static precompute, or even dispatch
    the kernel — pure memo hit."""
    backend.filter(_pod("a"), None, None)
    before = COUNTERS.snapshot()
    builds0 = backend.eval_cache.builds
    passed, failed = backend.filter(_pod("b"), None, None)
    assert len(passed) == N_NODES and not failed
    assert backend.eval_cache.builds == builds0
    assert COUNTERS.count("extender.affinity_data_build") == \
        before.get("extender.affinity_data_build", (0, 0))[0]
    assert COUNTERS.count("extender.fused_eval") == \
        before.get("extender.fused_eval", (0, 0))[0]
    assert COUNTERS.count("extender.result_hit") == \
        before.get("extender.result_hit", (0, 0))[0] + 1


def test_prioritize_rides_the_filter_evaluation(backend):
    """The fused-verb contract: /prioritize for the pod /filter just
    evaluated reuses the (fits, scores) pair — zero device work."""
    pod = _pod("fused")
    backend.filter(pod, None, None)
    evals0 = COUNTERS.count("extender.fused_eval")
    hits0 = backend.eval_cache.result_hits
    scores = backend.prioritize(pod, None, None)
    assert len(scores) == N_NODES
    assert COUNTERS.count("extender.fused_eval") == evals0
    assert backend.eval_cache.result_hits == hits0 + 1


def test_sync_nodes_invalidates_device_resident_cache(backend):
    backend.filter(_pod("pre-sync"), None, None)
    refresh0 = COUNTERS.count("extender.refresh_full")
    uploads0 = COUNTERS.count("engine.device_upload_arrays")
    builds0 = backend.eval_cache.builds
    # re-sync with one node's allocatable changed: full refresh + fresh
    # evaluation (the memo and encodings keyed on the old version/sync gen
    # must not serve)
    nodes = [info.node for info in backend.cache.node_infos().values()]
    nodes[0] = make_node(nodes[0].name, cpu=8000, memory=64 << 30, pods=110,
                         labels=dict(nodes[0].labels))
    backend.sync_nodes(nodes)
    passed, _ = backend.filter(_pod("post-sync"), None, None)
    assert len(passed) == N_NODES
    assert COUNTERS.count("extender.refresh_full") == refresh0 + 1
    assert COUNTERS.count("engine.device_upload_arrays") > uploads0
    assert backend.eval_cache.builds == builds0 + 1


def test_bind_invalidates_results_but_keeps_encoding(backend):
    """A bind moves capacity: the (fits, scores) memo for the new snapshot
    version must MISS (one fused dispatch), but the pod-side encoding is
    capacity-independent and survives (vocab_gen keying) — and the refresh
    is the targeted one-row delta, not a full N-node generation walk."""
    backend.filter(_pod("pre-bind"), None, None)
    builds0 = backend.eval_cache.builds
    evals0 = COUNTERS.count("extender.fused_eval")
    full0 = COUNTERS.count("extender.refresh_full")
    hint0 = COUNTERS.count("extender.refresh_hint")
    version0 = backend.engine.snapshot.version
    assert backend.bind("bound-1", "default", "u1", "hollow-node-3") == ""
    scores = backend.prioritize(_pod("post-bind"), None, None)
    assert len(scores) == N_NODES
    assert backend.engine.snapshot.version == version0 + 1
    assert COUNTERS.count("extender.fused_eval") == evals0 + 1  # re-eval
    assert backend.eval_cache.builds == builds0                 # no re-encode
    assert COUNTERS.count("extender.refresh_full") == full0     # no full walk
    assert COUNTERS.count("extender.refresh_hint") == hint0 + 1
    # the committed pod really moved the node's row
    i = backend.engine.snapshot.node_index["hollow-node-3"]
    assert backend.engine.snapshot.pod_count[i] == 1


def test_warm_path_agrees_with_stateless_args_mode(backend):
    """Same pod, same cluster: the cached fast lane and the per-request
    args-mode evaluation (fresh snapshot per call) must agree on both the
    verdicts and the integer scores."""
    pod = _pod("parity")
    warm_passed, _ = backend.filter(pod, None, None)
    warm_scores = dict(backend.prioritize(pod, None, None))
    nodes = [info.node for info in backend.cache.node_infos().values()
             if info.node is not None]
    args_passed, _ = backend.filter(pod, nodes, None)
    args_scores = dict(backend.prioritize(pod, nodes, None))
    assert sorted(warm_passed) == sorted(args_passed)
    assert warm_scores == args_scores


def test_affinity_sync_demotes_the_aff_free_lane(backend):
    """The /bind wire carries identifiers only, so affinity knowledge
    arrives with the BULK SYNC: once a synced bound pod carries
    pod-affinity, cluster_aff_free flips and later evaluations rebuild
    AffinityData against the live pair set (the symmetry check now has
    something to check)."""
    assert backend.eval_cache.cluster_aff_free
    aff = Affinity(pod_affinity=PodAffinity(required_terms=[
        PodAffinityTerm(label_selector=LabelSelector(
            match_labels={"app": "guard"}), topology_key="zone")]))
    guard = make_pod("guard", cpu=100, labels={"app": "guard"}, affinity=aff)
    guard.node_name = "hollow-node-0"
    backend.sync_pods([guard])
    assert not backend.eval_cache.cluster_aff_free
    # plain pods now take the affinity-aware path (symmetry vs the guard)
    builds0 = backend.eval_cache.builds
    passed, _ = backend.filter(_pod("plain-after-aff"), None, None)
    assert len(passed) == N_NODES  # guard's affinity forbids nothing here
    assert backend.eval_cache.builds == builds0 + 1
    # and a later sync that removes the guard restores the fast lane
    backend.sync_pods([])
    assert backend.eval_cache.cluster_aff_free


def test_compat_scheduleone_loop_commits_capacity(backend):
    """A scheduleOne-shaped stream (filter -> prioritize -> bind) against
    the warm lane: every bind is visible to the next evaluation, and the
    full-refresh count stays flat (per-bind refreshes ride the hint)."""
    full0 = COUNTERS.count("extender.refresh_full")
    chosen = []
    for i in range(8):
        pod = _pod(f"so-{i}")
        passed, _ = backend.filter(pod, None, None)
        scores = backend.prioritize(pod, None, None)
        host = max(scores, key=lambda e: e[1])[0]
        assert backend.bind(pod.name, pod.namespace, pod.uid, host) == ""
        chosen.append(host)
    snap = backend.engine.snapshot
    for host in set(chosen):
        assert snap.pod_count[snap.node_index[host]] >= 1
    assert COUNTERS.count("extender.refresh_full") == full0
