"""Randomized cluster fixture generator for kernel-vs-oracle golden tests.

Follows the table-driven spirit of the reference's predicates_test.go /
priorities_test.go (pods x nodes x expected verdict), but generates the tables
randomly with a seeded RNG so every feature axis (resources, labels, taints,
ports, conditions, selectors, affinity) gets cross-product coverage.

Memory values are Mi-multiples so the snapshot's KiB quantization is lossless
and oracle (bytes) vs kernel (KiB) comparisons are exact.
"""

from __future__ import annotations

import random
from typing import Dict, List, Tuple

from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    ContainerPort,
    Node,
    NodeAffinity,
    NodeSelectorTerm,
    Pod,
    SelectorOperator,
    SelectorRequirement,
    Taint,
    TaintEffect,
    Toleration,
    TolerationOperator,
    make_node,
    make_pod,
)

Mi = 1024 * 1024
Gi = 1024 * Mi

LABEL_KEYS = ["zone", "disk", "arch", "tier"]
LABEL_VALUES = {
    "zone": ["us-1a", "us-1b", "eu-1a"],
    "disk": ["ssd", "hdd"],
    "arch": ["amd64", "arm64"],
    "tier": ["web", "db", "cache"],
}
TAINTS = [
    Taint("dedicated", "gpu", TaintEffect.NO_SCHEDULE),
    Taint("dedicated", "infra", TaintEffect.NO_SCHEDULE),
    Taint("flaky", "", TaintEffect.NO_EXECUTE),
    Taint("noisy", "", TaintEffect.PREFER_NO_SCHEDULE),
]


def random_nodes(rng: random.Random, n: int) -> List[Node]:
    nodes = []
    for i in range(n):
        labels = {}
        for k in LABEL_KEYS:
            if rng.random() < 0.8:
                labels[k] = rng.choice(LABEL_VALUES[k])
        if rng.random() < 0.3:
            labels["rank"] = str(rng.randint(0, 9))
        taints = [t for t in TAINTS if rng.random() < 0.2]
        node = make_node(
            f"node-{i}",
            cpu=rng.choice([1000, 2000, 4000, 8000]),
            memory=rng.choice([4, 8, 32, 64]) * Gi,
            pods=rng.choice([2, 10, 110]),
            gpu=rng.choice([0, 0, 0, 4]),
            labels=labels,
            taints=taints,
            ready=rng.random() > 0.05,
            unschedulable=rng.random() < 0.05,
        )
        if rng.random() < 0.1:
            for c in node.conditions:
                if c.type == "MemoryPressure" and rng.random() < 0.5:
                    c.status = "True"  # type: ignore[assignment]
                if c.type == "DiskPressure" and rng.random() < 0.5:
                    c.status = "True"  # type: ignore[assignment]
        nodes.append(node)
    return nodes


def random_pod(rng: random.Random, i: int, node_names: List[str]) -> Pod:
    kind = rng.random()
    if kind < 0.1:
        # best-effort, zero-request pod (exercises the early-exit path)
        pod = Pod(name=f"pod-{i}", containers=[Container(name="c0")])
    else:
        pod = make_pod(
            f"pod-{i}",
            cpu=rng.choice([None, 0, 100, 500, 1500, 4000]),
            memory=rng.choice([None, 0, 128 * Mi, 1 * Gi, 8 * Gi]),
            gpu=rng.choice([None, None, None, 1, 8]),
        )
    if rng.random() < 0.3:
        pod.node_selector = {
            k: rng.choice(LABEL_VALUES[k])
            for k in rng.sample(LABEL_KEYS, rng.randint(1, 2))
        }
    if rng.random() < 0.25:
        pod.tolerations = [
            Toleration(t.key, TolerationOperator.EQUAL, t.value, t.effect)
            for t in TAINTS if rng.random() < 0.6
        ]
        if rng.random() < 0.2:
            pod.tolerations.append(
                Toleration("", TolerationOperator.EXISTS, "", None))
    if rng.random() < 0.2:
        ops = [
            SelectorRequirement("disk", SelectorOperator.IN, ["ssd", "hdd"]),
            SelectorRequirement("arch", SelectorOperator.NOT_IN, ["arm64"]),
            SelectorRequirement("tier", SelectorOperator.EXISTS, []),
            SelectorRequirement("zone", SelectorOperator.DOES_NOT_EXIST, []),
            SelectorRequirement("rank", SelectorOperator.GT, ["3"]),
            SelectorRequirement("rank", SelectorOperator.LT, ["7"]),
        ]
        terms = []
        for _ in range(rng.randint(1, 2)):
            terms.append(NodeSelectorTerm(
                rng.sample(ops, rng.randint(1, 2))))
        pod.affinity = Affinity(node_affinity=NodeAffinity(required_terms=terms))
    if rng.random() < 0.15:
        pod.containers[0].ports = [
            ContainerPort(host_port=rng.choice([80, 443, 8080, 9090]))]
    if rng.random() < 0.05:
        pod.node_name = rng.choice(node_names)  # PodFitsHost constraint... but
        # a pod with node_name set is "bound"; for PodFitsHost testing we keep
        # it pending — the field is only read by the predicate here
    return pod
