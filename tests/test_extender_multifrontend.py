"""Multi-frontend extender service (ISSUE 9): coalesced dispatch,
optimistic concurrency, exactly-once binds, backpressure.

The seam a real kube-scheduler hits (server/extender.py) serving a FLEET:

  - coalesced dispatch: concurrent /filter+/prioritize evaluations batch
    into ONE fused [C, N] kernel call (engine.evaluate_pods_batch) against
    the shared device-resident snapshot — pinned via span counters and an
    exact parity check against the per-request path;
  - optimistic concurrency: verdicts carry a snapshot generation; /bind
    commits through a fence re-validating capacity/liveness/topology
    against CURRENT cache truth, answering a typed retryable CONFLICT;
  - exactly-once: bind idempotency keys make a timed-out-but-landed bind
    replay safely (BindLedger), audited against STORE truth with
    testing/churn.FaultyBindApi injecting the at-most-once ambiguity;
  - backpressure: bounded coalescer queue -> Overloaded (HTTP 429 +
    Retry-After), per-request deadlines shed dead work, a faulting
    coalescer degrades to per-request evaluation instead of an outage.
"""

from __future__ import annotations

import json
import random
import threading

import numpy as np
import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.models.hollow import hollow_nodes
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.server.coalescer import DeadlineExceeded, Overloaded
from kubernetes_tpu.server.extender import (
    ExtenderHTTPServer,
    TPUExtenderBackend,
)
from kubernetes_tpu.testing.churn import FaultyBindApi, extender_store_binder
from kubernetes_tpu.utils.trace import COUNTERS

N_NODES = 120


def _pod(name: str, cpu: int = 100):
    return make_pod(name, cpu=cpu, memory=256 << 20)


def _backend(**kw) -> TPUExtenderBackend:
    b = TPUExtenderBackend(**kw)
    nodes = hollow_nodes(N_NODES)
    for i, n in enumerate(nodes):
        n.labels["zone"] = f"z{i % 4}"
    b.sync_nodes(nodes)
    b.filter(_pod("warm"), None, None)  # compile + first encode
    return b


# ------------------------------------------------------------- coalescing


def test_batch_eval_matches_per_request_exactly():
    """The fused [C, N] batch path and the single-pod warm lane must agree
    on every verdict and every integer score — and a multi-class batch
    must cost ONE batch dispatch, not C."""
    b = _backend()
    pods = [_pod(f"mc-{i}", cpu=100 * (1 + i % 3)) for i in range(9)]
    d0 = COUNTERS.count("extender.fused_eval_batch")
    outs = b._eval_many(pods)
    assert COUNTERS.count("extender.fused_eval_batch") == d0 + 1
    ref = TPUExtenderBackend()
    ref.sync_nodes([i.node for i in b.cache.node_infos().values()])
    for p, v in zip(pods, outs):
        with ref._lock:
            _snap, m, s = ref._eval_locked(p, None)
        assert (np.asarray(v.m) == np.asarray(m)).all()
        assert (np.asarray(v.s) == np.asarray(s)).all()


def test_concurrent_filters_coalesce_into_shared_dispatches():
    """A storm of concurrent same-class /filter requests serves from a
    shared evaluation: dispatch count stays far below request count, and
    every thread sees the full verdict."""
    b = _backend(coalesce_window_s=0.002)
    b.filter(_pod("seed"), None, None)
    n_threads = 12
    start = threading.Barrier(n_threads)
    results, errors = [], []
    lock = threading.Lock()

    def drive(i):
        try:
            start.wait(timeout=10)
            passed, failed, gen = b.filter_verdict(_pod(f"storm-{i}"))
            with lock:
                results.append((len(passed), len(failed), gen))
        except Exception as e:  # noqa: BLE001 — surfaced below
            with lock:
                errors.append(e)

    f0 = COUNTERS.count("extender.fused_eval")
    fb0 = COUNTERS.count("extender.fused_eval_batch")
    threads = [threading.Thread(target=drive, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    assert len(results) == n_threads
    assert all(r == (N_NODES, 0, results[0][2]) for r in results)
    # all same class at one snapshot version: at most a couple of
    # dispatches total (leader races), never one per request
    dispatches = (COUNTERS.count("extender.fused_eval") - f0
                  + COUNTERS.count("extender.fused_eval_batch") - fb0)
    assert dispatches <= 2, dispatches
    with b._counters_lock:
        assert b._counters["coalesce_requests"] >= n_threads


def test_coalescer_fault_degrades_to_per_request(monkeypatch):
    """A faulting batch evaluation must not take the verb down: the leader
    falls back to per-request eval and the fault is counted."""
    b = _backend()
    calls = {"n": 0}
    real = b._eval_many

    def boom(pods):
        calls["n"] += 1
        raise RuntimeError("injected coalescer fault")

    monkeypatch.setattr(b, "_eval_many", boom)
    passed, failed, gen = b.filter_verdict(_pod("degraded"))
    assert len(passed) == N_NODES and not failed
    with b._counters_lock:
        assert b._counters["coalesce_faults"] == 1
    monkeypatch.setattr(b, "_eval_many", real)
    passed, _f, _g = b.filter_verdict(_pod("recovered"))
    assert len(passed) == N_NODES


# --------------------------------------------------- fence + concurrency


def test_bind_fence_conflict_is_typed_and_retryable():
    """Omega at the wire: two frontends verdict at the same generation;
    the second's commit must fence out with a typed CONFLICT, and its
    retry against a fresh verdict must succeed elsewhere."""
    b = TPUExtenderBackend()
    # two nodes, each with room for exactly one of these pods
    b.sync_nodes([make_node(f"tiny-{i}", cpu=1000, memory=4 << 30, pods=110)
                  for i in range(2)])
    spec = make_pod("a", cpu=900, memory=256 << 20)
    passed, _f, gen = b.filter_verdict(spec)
    assert sorted(passed) == ["tiny-0", "tiny-1"]
    err, kind, _ = b.bind_verdict("a", "default", "u-a", "tiny-0",
                                  snapshot_gen=gen, idem_key="a:1",
                                  pod_spec=spec)
    assert (err, kind) == ("", "ok")
    # frontend B read the SAME generation, races to the same node
    spec_b = make_pod("b", cpu=900, memory=256 << 20)
    err, kind, retry_s = b.bind_verdict("b", "default", "u-b", "tiny-0",
                                        snapshot_gen=gen, idem_key="b:1",
                                        pod_spec=spec_b)
    assert kind == "conflict" and err.startswith("CONFLICT")
    assert retry_s > 0
    with b._counters_lock:
        assert b._counters["bind_conflicts"] == 1
    # the contract: re-run scheduleOne against a fresh verdict
    passed, _f, gen2 = b.filter_verdict(spec_b)
    assert passed == ["tiny-1"]
    err, kind, _ = b.bind_verdict("b", "default", "u-b", "tiny-1",
                                  snapshot_gen=gen2, idem_key="b:2",
                                  pod_spec=spec_b)
    assert (err, kind) == ("", "ok")


def test_bind_skips_fence_when_generation_current():
    """A verdict at the CURRENT commit generation provably re-validated
    nothing away — its own /filter pass is the fence."""
    b = _backend()
    spec = _pod("cur")
    passed, _f, gen = b.filter_verdict(spec)
    s0 = COUNTERS.count("extender.fence_skipped")  # structural: via _count
    err, kind, _ = b.bind_verdict("cur", "default", "u-c", passed[0],
                                  snapshot_gen=gen, pod_spec=spec)
    assert (err, kind) == ("", "ok")
    with b._counters_lock:
        assert b._counters.get("bind_fence_skipped", 0) == 1
    # stale generation (a commit happened): the fence must run
    err, kind, _ = b.bind_verdict("cur2", "default", "u-c2", passed[1],
                                  snapshot_gen=gen, pod_spec=_pod("cur2"))
    assert (err, kind) == ("", "ok")
    with b._counters_lock:
        assert b._counters.get("bind_fence_skipped", 0) == 1  # unchanged
    del s0


def test_stale_window_serves_memo_and_fence_guards():
    """Bounded staleness: inside stale_window_s a bind does NOT invalidate
    verdicts (memo keeps serving, zero device work), and commits stay
    guarded by the fence against live cache truth."""
    b = _backend(stale_window_s=30.0)
    spec = _pod("sw-0")
    passed, _f, gen = b.filter_verdict(spec)
    evals0 = (COUNTERS.count("extender.fused_eval")
              + COUNTERS.count("extender.fused_eval_batch"))
    stale0 = COUNTERS.count("extender.stale_served")
    for i in range(5):
        err, kind, _ = b.bind_verdict(f"sw-{i}", "default", f"u-{i}",
                                      passed[i], snapshot_gen=gen,
                                      pod_spec=_pod(f"sw-{i}"))
        assert (err, kind) == ("", "ok"), (i, err)
        p2, _f2, g2 = b.filter_verdict(_pod(f"sw-chk-{i}"))
        assert len(p2) == N_NODES
        assert g2 == gen  # generation frozen inside the window
    assert (COUNTERS.count("extender.fused_eval")
            + COUNTERS.count("extender.fused_eval_batch")) == evals0
    assert COUNTERS.count("extender.stale_served") > stale0
    # capacity really accrued in the CACHE even though the snapshot lags
    infos = b.cache.node_infos()
    assert sum(len(i.pods) for i in infos.values()) == 5


# ------------------------------------------------------- exactly-once


def test_idempotent_replay_returns_recorded_outcome():
    b = _backend()
    spec = _pod("idem")
    passed, _f, gen = b.filter_verdict(spec)
    node = passed[0]
    assert b.bind_verdict("idem", "default", "u-i", node, snapshot_gen=gen,
                          idem_key="idem:1", pod_spec=spec)[1] == "ok"
    pods0 = b.cache.pod_count()
    err, kind, _ = b.bind_verdict("idem", "default", "u-i", node,
                                  snapshot_gen=gen, idem_key="idem:1",
                                  pod_spec=spec)
    assert (err, kind) == ("", "ok")
    assert b.cache.pod_count() == pods0  # no second assume
    with b._counters_lock:
        assert b._counters["bind_replays"] == 1


def test_timeout_bind_replays_to_exactly_once_at_store():
    """The at-most-once ambiguity over the wire: the bind API times out
    but the write LANDED. The client retries with the SAME idempotency
    key; the ledger replays against the recorded node and the store's
    same-node refusal heals to success — exactly-once, store-audited."""
    api = ApiServerLite()
    for n in hollow_nodes(8):
        api.create("Node", n)
    pod = _pod("ghost")
    api.create("Pod", pod)
    faulty = FaultyBindApi(api, timeout_rate=1.0, seed=7)
    b = TPUExtenderBackend(binder=extender_store_binder(faulty))
    b.sync_nodes([api.get("Node", "", f"hollow-node-{i}") for i in range(8)])
    passed, _f, gen = b.filter_verdict(pod)
    node = passed[0]
    err, kind, _ = b.bind_verdict("ghost", "default", pod.uid, node,
                                  snapshot_gen=gen, idem_key="ghost:1",
                                  pod_spec=pod)
    assert kind == "error" and "timeout" in err
    assert faulty.injected_timeouts == 1
    # the write landed at the store despite the error
    assert api.get("Pod", "default", "ghost").node_name == node
    # retry, same key: replays to the SAME node, heals to success
    faulty.timeout_rate = 0.0
    err, kind, _ = b.bind_verdict("ghost", "default", pod.uid, "ignored",
                                  snapshot_gen=None, idem_key="ghost:1",
                                  pod_spec=pod)
    assert (err, kind) == ("", "ok")
    assert api.get("Pod", "default", "ghost").node_name == node
    # exactly one bind ever landed: one MODIFIED event with a node set
    events, _rv = api.list("Pod"), None
    binds = [e for e in api._log
             if e.kind == "Pod" and e.type == "MODIFIED"
             and e.obj.name == "ghost" and e.obj.node_name]
    assert len(binds) == 1


def test_concurrent_client_storm_exactly_once_under_faults():
    """The headline robustness audit: N frontends hammer filter/
    prioritize/bind on ONE backend with injected bind failures AND
    timeouts, retrying CONFLICTs with jittered backoff. Afterwards: every
    pod is bound to EXACTLY ONE node at the store (truth reconciled), and
    every CONFLICT retried to success."""
    api = ApiServerLite(max_log=100_000)
    nodes = hollow_nodes(N_NODES)
    for n in nodes:
        api.create("Node", n)
    faulty = FaultyBindApi(api, fail_rate=0.10, timeout_rate=0.10, seed=11)
    b = TPUExtenderBackend(binder=extender_store_binder(faulty),
                           stale_window_s=0.02, coalesce_window_s=0.001)
    b.sync_nodes(nodes)
    b.filter(_pod("warm"), None, None)
    n_clients, per = 8, 10
    for c in range(n_clients):
        for i in range(per):
            api.create("Pod", _pod(f"storm-{c}-{i}"))
    errors, lock = [], threading.Lock()
    conflicts_seen = [0]
    start = threading.Barrier(n_clients)

    def drive(c):
        rng = random.Random(1000 + c)
        try:
            start.wait(timeout=20)
            for i in range(per):
                name = f"storm-{c}-{i}"
                spec = _pod(name)
                bound = False
                for attempt in range(25):
                    passed, _f, gen = b.filter_verdict(spec)
                    scores, _g = b.prioritize_verdict(spec, passed)
                    best = max(s for _n, s in scores)
                    top = [n for n, s in scores if s == best]
                    node = top[rng.randrange(len(top))]
                    err, kind, retry_s = b.bind_verdict(
                        name, "default", spec.uid, node, snapshot_gen=gen,
                        idem_key=f"{name}:{attempt}", pod_spec=spec)
                    if kind == "ok":
                        bound = True
                        break
                    if kind in ("conflict", "pending"):
                        with lock:
                            conflicts_seen[0] += 1
                        __import__("time").sleep(
                            retry_s * rng.uniform(0.5, 1.5))
                        continue
                    if kind == "error":
                        if "already assigned" in err:
                            bound = True  # landed earlier; store is truth
                            break
                        # ambiguous: same key converges via the ledger
                        err2, kind2, _ = b.bind_verdict(
                            name, "default", spec.uid, node,
                            snapshot_gen=None,
                            idem_key=f"{name}:{attempt}", pod_spec=spec)
                        if kind2 == "ok" or "already assigned" in err2:
                            bound = True
                            break
                        continue  # clean failure: next attempt, fresh key
                if not bound:
                    raise AssertionError(f"{name} never bound")
        except Exception as e:  # noqa: BLE001 — surfaced below
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=drive, args=(c,))
               for c in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    # STORE-TRUTH exactly-once audit: every pod bound, and the event log
    # shows exactly one landed bind per pod (a second would have been
    # refused by the store)
    pods, _rv = api.list("Pod")
    storm = [p for p in pods if p.name.startswith("storm-")]
    assert len(storm) == n_clients * per
    assert all(p.node_name for p in storm)
    first_node = {}
    for e in api._log:
        if e.kind == "Pod" and e.type == "MODIFIED" and e.obj.node_name \
                and e.obj.name.startswith("storm-"):
            prev = first_node.setdefault(e.obj.name, e.obj.node_name)
            assert prev == e.obj.node_name, \
                f"duplicate bind: {e.obj.name} -> {prev} AND {e.obj.node_name}"
    assert faulty.injected_failures + faulty.injected_timeouts > 0
    with b._counters_lock:
        snap = dict(b._counters)
    assert snap.get("bind_errors", 0) > 0  # faults really exercised


# ------------------------------------------------------- backpressure


def test_admission_control_sheds_past_queue_depth():
    b = _backend(coalesce_max_depth=2)
    entered = threading.Event()
    release = threading.Event()
    real = b._eval_many

    def slow(pods):
        entered.set()
        release.wait(timeout=10)
        return real(pods)

    b._eval_many = slow
    outs, overloads, lock = [], [], threading.Lock()

    def drive(i):
        try:
            out = b.coalescer.submit(_pod(f"adm-{i}"))
            with lock:
                outs.append(out)
        except Overloaded as e:
            assert e.retry_after_s > 0
            with lock:
                overloads.append(e)

    # one leader, parked inside the (stalled) evaluation...
    leader = threading.Thread(target=drive, args=(0,))
    leader.start()
    assert entered.wait(timeout=10)
    # ...two followers fill the bounded queue...
    followers = [threading.Thread(target=drive, args=(i,)) for i in (1, 2)]
    for t in followers:
        t.start()
    deadline = __import__("time").monotonic() + 10
    while len(b.coalescer._queue) < 2:
        assert __import__("time").monotonic() < deadline, "queue never filled"
        __import__("time").sleep(0.001)
    # ...and everything past max_depth sheds SYNCHRONOUSLY with a hint
    for i in range(3, 8):
        drive(i)
    release.set()
    leader.join(timeout=30)
    for t in followers:
        t.join(timeout=30)
    b._eval_many = real
    assert len(overloads) == 5, overloads  # all past-depth submits shed
    assert len(outs) == 3  # leader + the two queued followers served
    with b._counters_lock:
        assert b._counters["admission_shed"] == len(overloads)


def test_expired_deadline_is_shed_not_evaluated():
    b = _backend()
    with pytest.raises(DeadlineExceeded):
        # deadline already elapsed relative to arrival: the leader sheds
        # at batch formation (deadline_s measured from submit)
        b.coalescer.submit(_pod("dead"), deadline_s=-0.001)
    with b._counters_lock:
        assert b._counters["deadline_shed"] >= 1
    # bind-side shed: nothing happened, and the same key retries fresh
    spec = _pod("dead-bind")
    passed, _f, gen = b.filter_verdict(spec)
    err, kind, _ = b.bind_verdict("dead-bind", "default", "u-d", passed[0],
                                  snapshot_gen=gen, idem_key="db:1",
                                  deadline_s=-0.001, pod_spec=spec)
    assert (err, kind) == ("DEADLINE_EXCEEDED", "shed")
    err, kind, _ = b.bind_verdict("dead-bind", "default", "u-d", passed[0],
                                  snapshot_gen=gen, idem_key="db:1",
                                  pod_spec=spec)
    assert (err, kind) == ("", "ok")


# ------------------------------------------------------------- HTTP wire


def test_http_wire_conflict_429_compact_and_keepalive():
    """The wire contract end to end on ONE keep-alive connection: compact
    filter (SnapshotGen + AllPassed), TopK prioritize, 409 CONFLICT with
    RetryAfterMs, 429 + Retry-After past the in-flight cap, new counters
    on /metrics."""
    import http.client

    from kubernetes_tpu.api import serde

    b = TPUExtenderBackend()
    b.sync_nodes([make_node(f"tiny-{i}", cpu=1000, memory=4 << 30, pods=110)
                  for i in range(2)])
    srv = ExtenderHTTPServer(b, prefix="/scheduler")
    srv.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)

        def post(path, obj):
            conn.request("POST", f"/scheduler{path}",
                         json.dumps(obj), {"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp.status, resp.getheader("Retry-After"), \
                json.loads(resp.read())

        spec = make_pod("w1", cpu=900, memory=256 << 20)
        enc = serde.encode_pod(spec)
        status, _ra, out = post("/filter", {"Pod": enc, "NodeNames": None,
                                            "Compact": True, "TopK": 8})
        assert status == 200
        assert out["AllPassed"] and out["NodeNames"] is None
        assert out["PassedCount"] == 2 and "SnapshotGen" in out
        # fused verbs: the same verdict's top scores ride the filter
        # response, one round trip (and only FITTING nodes appear)
        assert len(out["TopScores"]) == 2
        assert {e["Host"] for e in out["TopScores"]} == {"tiny-0", "tiny-1"}
        gen = out["SnapshotGen"]
        status, _ra, scores = post("/prioritize",
                                   {"Pod": enc, "NodeNames": None, "TopK": 1})
        assert status == 200 and len(scores) == 1
        # same connection still live (keep-alive): bind via the wire
        status, _ra, out = post("/bind", {
            "PodName": "w1", "PodNamespace": "default", "PodUID": "u1",
            "Node": "tiny-0", "SnapshotGen": gen, "IdempotencyKey": "w1:1",
            "Pod": enc})
        assert status == 200 and out["Error"] == ""
        # racing twin at the same gen -> typed 409 with a retry hint
        spec2 = make_pod("w2", cpu=900, memory=256 << 20)
        status, _ra, out = post("/bind", {
            "PodName": "w2", "PodNamespace": "default", "PodUID": "u2",
            "Node": "tiny-0", "SnapshotGen": gen, "IdempotencyKey": "w2:1",
            "Pod": serde.encode_pod(spec2)})
        assert status == 409
        assert out["Conflict"] and out["RetryAfterMs"] >= 1
        assert out["Error"].startswith("CONFLICT")
        # in-flight cap: 0 -> every verb answers 429 + Retry-After
        srv.max_inflight = 0
        status, ra, out = post("/filter", {"Pod": enc, "NodeNames": None})
        assert status == 429 and ra is not None
        assert out["RetryAfterMs"] > 0
        srv.max_inflight = 256
        # metrics carry the new counters, scraped consistently
        conn.request("GET", "/metrics")
        body = conn.getresponse().read().decode()
        for needle in ("tpu_extender_bind_conflicts_total 1",
                       "tpu_extender_admission_shed_total",
                       "tpu_extender_coalesce_requests_total",
                       "tpu_extender_commit_gen"):
            assert needle in body, needle
        conn.close()
    finally:
        srv.stop()


def test_http_unknown_path_keeps_connection_alive():
    """The keep-alive desync audit (ISSUE 9 satellite): a POST to an
    unknown path must drain its body so the NEXT request on the same
    connection still parses."""
    import http.client

    b = _backend()
    srv = ExtenderHTTPServer(b, prefix="/scheduler")
    srv.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        conn.request("POST", "/scheduler/nope",
                     json.dumps({"junk": "x" * 4096}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 404
        resp.read()
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        assert resp.status == 200 and resp.read() == b"ok"
        conn.close()
    finally:
        srv.stop()


# ------------------------------------------- tsan-lite storm leg (ISSUE 19)


def test_lockcheck_leg_coalesced_storm_bit_identical(monkeypatch):
    """The coalesced-dispatch storm with every lock instrumented
    (GRAFT_LOCKCHECK=1 at construction): verdicts and integer scores are
    bit-identical to the unarmed world, and the checker ends the run
    with ZERO recorded violations — the concurrency discipline holds on
    the real workload, not just the fixtures."""
    from kubernetes_tpu.analysis import lockcheck

    ref = _backend()  # unarmed reference, built BEFORE the knob flips
    pods = [_pod(f"lc-{i}", cpu=100 * (1 + i % 3)) for i in range(9)]
    want = ref._eval_many(pods)

    monkeypatch.setenv("GRAFT_LOCKCHECK", "1")
    lockcheck.reset()
    b = _backend(coalesce_window_s=0.002)  # checked twins throughout
    for v, w in zip(b._eval_many(pods), want):
        assert (np.asarray(v.m) == np.asarray(w.m)).all()
        assert (np.asarray(v.s) == np.asarray(w.s)).all()

    n_threads = 8
    start = threading.Barrier(n_threads)
    results, errors = [], []
    lock = threading.Lock()

    def drive(i):
        try:
            start.wait(timeout=10)
            passed, failed, _gen = b.filter_verdict(_pod(f"lcs-{i}"))
            with lock:
                results.append((len(passed), len(failed)))
        except Exception as e:  # noqa: BLE001 — surfaced below
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=drive, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    assert results == [(N_NODES, 0)] * n_threads
    lockcheck.assert_clean()
