"""Sparrow fast lane (ISSUE 17): the sub-10 ms admission tier beside the
bulk waves.

What these tests pin:

- **off-by-default bit-identity**: the lane armed with ZERO
  latency-critical pods is invisible — the same frozen arrival trace
  places every pod on the SAME node as a lane-less run, with zero
  fast-lane dispatches and the same wave count (span counters, not
  vibes);
- **exactly-once under contention**: a fast bind and an in-flight wave
  racing one last-slot node resolve through the fence — store truth
  shows exactly one bind, the wave row requeues;
- **doomed-note fence**: a node-dying watch event noted but not yet
  applied (engine.note_node_doomed) refuses the fast bind BEFORE the
  liveness ladder — the ISSUE 8 fence extended to this path;
- **typed outcome partition**: bound + fell_back + bind_error +
  superseded == fast pods popped, with the fence-loss reasons counted
  by name;
- **delta-free evals**: a fast-only window builds zero encodings and
  walks zero full snapshots (the wave machinery never wakes);
- **device/host twin equivalence**: the jitted [1, k] kernel and its
  numpy twin agree on winner and fit count exactly (score within float
  rounding);
- **per-tier SLO**: fast binds burn the fast tier's own objective and
  surface as slo.fast.* through the telemetry registry and the
  Prometheus rendering every transport serves.
"""

from __future__ import annotations

import time

import numpy as np

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.engine.fastlane import (
    FASTLANE_ANNOTATION,
    FastLane,
    eligible,
    is_latency_critical,
)
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes, load_cluster
from kubernetes_tpu.ops.fastlane import (
    FAST_NODE_KEYS,
    sample_eval,
    sample_eval_host,
)
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.utils.trace import COUNTERS

Mi = 1 << 20
Gi = 1 << 30

TRACE = (37, 128, 5, 96)


def mk_sched(n_nodes=64):
    api = ApiServerLite()
    load_cluster(api, hollow_nodes(n_nodes), [])
    s = Scheduler(api, record_events=False)
    s.start()
    return api, s


def feed(api, group, tag):
    pods = PROFILES["density"](group)
    for p in pods:
        p.name = f"{tag}-{p.name}"
        api.create("Pod", p)


def fast_pod(name, cpu=100, mem=128 * Mi):
    p = make_pod(name, cpu=cpu, memory=mem)
    p.annotations[FASTLANE_ANNOTATION] = "true"
    return p


def placements(api):
    return {p.name: p.node_name for p in api.list("Pod")[0]}


def fast_counters():
    return {k: v[0] for k, v in COUNTERS.snapshot().items()
            if k.startswith("fastlane.")}


# ------------------------------------------------------------- eligibility


def test_tier_contract_annotation_and_priority_band():
    p = make_pod("plain", cpu=100, memory=64 * Mi)
    assert not is_latency_critical(p)
    p.annotations[FASTLANE_ANNOTATION] = "true"
    assert is_latency_critical(p) and eligible(p)
    q = make_pod("banded", cpu=100, memory=64 * Mi)
    q.priority = 2_000_000_000
    assert is_latency_critical(q) and eligible(q)


def test_eligibility_declines_everything_the_kernel_cannot_model():
    base = fast_pod("f")
    assert eligible(base)
    for mutate in (
        lambda p: setattr(p, "node_name", "pinned"),
        lambda p: setattr(p, "node_selector", {"zone": "a"}),
        lambda p: setattr(p, "tolerations", [object()]),
    ):
        p = fast_pod("f2")
        mutate(p)
        assert not eligible(p), mutate
    sel = make_pod("sel", cpu=100, memory=64 * Mi, ports=[8080])
    sel.annotations[FASTLANE_ANNOTATION] = "true"
    assert not eligible(sel)  # host ports: not in the [1,k] kernel
    ext = make_pod("ext", cpu=100, memory=64 * Mi,
                   extended={"example.com/foo": 1})
    ext.annotations[FASTLANE_ANNOTATION] = "true"
    assert not eligible(ext)  # extended resource: vocab-dependent row


# --------------------------------------------------- frozen-trace A/B (off)


def test_lane_armed_but_unused_is_bit_identical():
    """The satellite A/B: fast lane ENABLED with zero latency-critical
    pods must be invisible — same binds as a lane-less run on the same
    frozen trace, zero fast-lane dispatches, same wave count."""
    quantum = 128

    def run(fastlane):
        api, s = mk_sched()
        COUNTERS.reset()
        loop = s.stream(budget_s=30.0, min_quantum=quantum,
                        max_quantum=quantum, fastlane=fastlane)
        for gi, group in enumerate(TRACE):
            feed(api, group, f"g{gi}")
            loop.step()
        loop.drain()
        loop.close()
        snap = COUNTERS.snapshot()
        return placements(api), {
            "waves": snap.get("engine.wave_dispatch", (0, 0))[0],
            "fast": {k: v[0] for k, v in snap.items()
                     if k.startswith("fastlane.")}}

    pa, ca = run(True)
    pb, cb = run(False)
    assert pa == pb, {k: (pa[k], pb[k]) for k in pa if pa[k] != pb[k]}
    assert all(v for v in pa.values()), "trace must fully bind"
    # zero extra dispatches: the armed-but-unused lane never popped,
    # never evaluated, never touched a counter — and admitted the same
    # number of waves
    assert not any(ca["fast"].values()), ca["fast"]
    assert ca["waves"] == cb["waves"], (ca, cb)


# -------------------------------------------------------------- happy path


def test_fast_pods_bind_through_the_lane():
    api, s = mk_sched()
    loop = s.stream(budget_s=30.0, fastlane=True)
    feed(api, 64, "warm")
    loop.drain()
    COUNTERS.reset()
    for i in range(8):
        api.create("Pod", fast_pod(f"fast-{i}"))
    loop.drain()
    loop.close()
    c = fast_counters()
    assert c.get("fastlane.bound", 0) == 8, c
    placed = placements(api)
    assert all(placed[f"fast-{i}"] for i in range(8))
    # typed outcome partition: every popped fast pod lands in exactly
    # one outcome bucket
    outcomes = (c.get("fastlane.bound", 0)
                + c.get("fastlane.fell_back", 0)
                + c.get("fastlane.bind_error", 0)
                + c.get("fastlane.superseded", 0))
    assert outcomes == 8, c


def test_fast_only_window_is_delta_free():
    """Fast-lane evals never build an encoding and never walk the full
    snapshot: the counter-proof that the lane rides RESIDENT state (the
    acceptance bar's span-counter invariant)."""
    api, s = mk_sched()
    loop = s.stream(budget_s=30.0, fastlane=True)
    feed(api, 64, "warm")  # primes snapshot + encoding via the wave path
    loop.drain()
    COUNTERS.reset()
    for i in range(16):
        api.create("Pod", fast_pod(f"fast-{i}"))
    loop.drain()
    loop.close()
    snap = COUNTERS.snapshot()

    def cnt(name):
        return snap.get(name, (0, 0.0))[0]

    assert cnt("fastlane.bound") == 16, snap
    assert cnt("engine.wave_encode_build") == 0, snap
    assert cnt("engine.wave_dispatch") == 0, snap
    assert cnt("snapshot.refresh_scan") == 0, snap
    assert cnt("snapshot.refresh_rebuild") == 0, snap


# -------------------------------------------------------------- contention


def test_contended_node_store_truth_shows_exactly_one_bind():
    """A fast bind and an in-flight (blind) wave race the ONE node with
    one free slot: the fast pod lands first through its fence, the wave
    row must lose at the harvest fence — store truth shows exactly one
    pod on the node, no duplicate bind, no lost pod."""
    api = ApiServerLite()
    load_cluster(api, [make_node("solo", cpu=150, memory=1 * Gi,
                                 pods=110)], [])
    s = Scheduler(api, record_events=False)
    s.start()
    loop = s.stream(budget_s=30.0, fastlane=True)
    COUNTERS.reset()
    # the bulk pod rides a wave; dispatch it and leave it in flight
    # (blind window open)
    api.create("Pod", make_pod("bulk-0", cpu=100, memory=64 * Mi))
    s.sync()
    pods = s.queue.pop_batch()
    assert [p.name for p in pods] == ["bulk-0"]
    handle = s.engine.dispatch_waves(pods, time.monotonic())
    # while the wave is in flight, a latency-critical pod takes the slot
    api.create("Pod", fast_pod("fast-0"))
    s.sync()
    stats = {}
    assert loop._pump_fast(stats, busy=handle) == 1
    # now harvest: the fence re-validates the wave row against live
    # truth (the fast bind moved capacity) and must requeue it
    s._complete_wave(handle)
    placed = placements(api)
    assert placed["fast-0"] == "solo"
    assert not placed["bulk-0"], placed
    assert sum(1 for v in placed.values() if v == "solo") == 1
    c = fast_counters()
    assert c.get("fastlane.bound", 0) == 1, c
    loop.close()


def test_capacity_fence_loss_resamples_then_falls_back():
    """A stale snapshot score loses the capacity fence: the lane
    resamples with jitter (typed counter) and after bounded retries
    hands the pod to the wave path — never drops it."""
    api = ApiServerLite()
    load_cluster(api, [make_node("solo", cpu=150, memory=1 * Gi,
                                 pods=110)], [])
    s = Scheduler(api, record_events=False)
    s.start()
    loop = s.stream(budget_s=30.0, fastlane=True)
    COUNTERS.reset()
    api.create("Pod", fast_pod("fast-0"))
    s.sync()
    assert loop._pump_fast({}) == 1  # binds; snapshot NOT refreshed
    api.create("Pod", fast_pod("fast-1"))
    s.sync()
    assert loop._pump_fast({}) == 1  # stale eval fits, live fence says no
    c = fast_counters()
    assert c.get("fastlane.bound", 0) == 1, c
    assert c.get("fastlane.fence_capacity", 0) >= 1, c
    assert c.get("fastlane.resampled", 0) >= 1, c
    assert c.get("fastlane.fell_back", 0) == 1, c
    # the loser is safe on the bulk tier, not lost
    assert s.queue.ready_count() == 1
    loop.close()


def test_doomed_note_blocks_fast_bind_before_liveness():
    """Node-kill during a fast-lane bind (the satellite): the owner has
    SEEN the dying watch event (note_node_doomed) but not applied it —
    the fence must refuse the bind on the note alone, and the pod falls
    back to the wave path rather than landing on a dying node."""
    api = ApiServerLite()
    load_cluster(api, [make_node("dying", cpu=4000, memory=4 * Gi,
                                 pods=110)], [])
    s = Scheduler(api, record_events=False)
    s.start()
    loop = s.stream(budget_s=30.0, fastlane=True)
    COUNTERS.reset()
    s.engine.note_node_doomed("dying")
    api.create("Pod", fast_pod("fast-0"))
    s.sync()
    assert loop._pump_fast({}) == 1
    c = fast_counters()
    assert c.get("fastlane.fence_doomed", 0) >= 1, c
    assert c.get("fastlane.fell_back", 0) == 1, c
    assert c.get("fastlane.bound", 0) == 0, c
    placed = placements(api)
    assert not placed["fast-0"]
    # the doom clears (event applied, node lived): the wave path binds it
    s.engine.clear_node_doomed("dying")
    loop.drain()
    loop.close()
    assert placements(api)["fast-0"] == "dying"


# --------------------------------------------------------- eval twin parity


def test_device_and_host_eval_twins_agree():
    """The jitted [1, k] kernel and its numpy twin must agree on winner
    and fit count EXACTLY (same inputs), score within float rounding —
    the routing choice (device idle vs busy) is latency policy, never a
    semantics fork."""
    api, s = mk_sched(16)
    loop = s.stream(budget_s=30.0, fastlane=True)
    feed(api, 48, "warm")  # uneven load so scores differ across nodes
    loop.drain()
    loop.close()
    snap = s.engine.snapshot
    nodes = {k: np.asarray(getattr(snap, k)) for k in FAST_NODE_KEYS}
    req = snap.resource_row(milli_cpu=100, memory=128 * Mi, gpu=0,
                            scratch=0, overlay=0, extended={}, up=True,
                            width=snap.num_resources)
    rng = np.random.default_rng(7)
    for _trial in range(8):
        idx = rng.integers(0, len(snap.node_names), size=16).astype(
            np.int32)
        host = sample_eval_host(idx, req, False, False, nodes)
        dev = np.asarray(sample_eval(idx, req, False, False,
                                     nodes))  # graftlint: sync-ok
        assert int(host[0]) == int(dev[0]), (host, dev)
        assert int(host[1]) == int(dev[1]), (host, dev)
        assert abs(int(host[2]) - int(dev[2])) <= 2, (host, dev)


def test_device_path_used_when_device_idle_and_current():
    """When no wave is in flight and the resident device arrays are at
    the snapshot's version, the eval dispatches on device (counted) —
    and returns the same bind the host twin would have made."""
    api, s = mk_sched(8)
    loop = s.stream(budget_s=30.0, fastlane=True)
    feed(api, 16, "warm")
    loop.drain()
    fl = loop.fastlane
    # force the resident mirror current (a harvest bumps the snapshot
    # version past the device's; re-align as a fresh dispatch would —
    # _nodes_on_device stamps _device_version itself)
    s.engine._refresh()
    s.engine._nodes_on_device()
    COUNTERS.reset()
    api.create("Pod", fast_pod("fast-dev"))
    s.sync()
    pods = s.queue.pop_fast()
    assert len(pods) == 1
    fl.schedule(pods[0], time.monotonic(), device_ok=True)
    c = fast_counters()
    assert c.get("fastlane.dispatch_device", 0) == 1, c
    assert c.get("fastlane.bound", 0) == 1, c
    assert placements(api)["fast-dev"]
    loop.close()


# ------------------------------------------------------------ per-tier SLO


def test_fast_tier_slo_surfaces_through_registry_and_prometheus():
    from kubernetes_tpu.observability.registry import TelemetryRegistry
    from kubernetes_tpu.observability.slo import SLO_FAST
    SLO_FAST.clear()
    SLO_FAST.enable()
    try:
        api, s = mk_sched(8)
        loop = s.stream(budget_s=30.0, fastlane=True)
        feed(api, 16, "warm")
        loop.drain()
        COUNTERS.reset()
        for i in range(4):
            api.create("Pod", fast_pod(f"fast-{i}"))
        loop.drain()
        loop.close()
        assert fast_counters().get("fastlane.bound", 0) == 4
        reg = TelemetryRegistry()
        snap = reg.snapshot()
        fast_keys = [k for k in snap if k.startswith("slo.fast.")]
        assert fast_keys, sorted(snap)[:20]
        text = reg.render_prometheus()
        assert "tpu_slo_fast_" in text
        # the extender's /debug/slo payload (all three transports share
        # this one method) carries the fast tier beside the bulk one
        from kubernetes_tpu.server.extender import TPUExtenderBackend
        payload = TPUExtenderBackend().debug_slo()
        assert "fast" in payload and isinstance(payload["fast"], dict)
    finally:
        SLO_FAST.disable()
        SLO_FAST.clear()


# ------------------------------------------------------------ queue tiering


def test_fallback_pod_never_reroutes_into_the_fast_tier():
    """add_bulk bypasses the classifier: a fell-back latency-critical
    pod rides the wave path next (no starvation loop)."""
    api, s = mk_sched(4)
    loop = s.stream(budget_s=30.0, fastlane=True)
    p = fast_pod("loopy")
    s.queue.add_bulk([p])
    assert s.queue.fast_count() == 0
    assert s.queue.ready_count() == 1
    got = s.queue.pop_batch()
    assert [q.name for q in got] == ["loopy"]
    loop.close()


def test_bulk_aging_guard_untouched_by_fast_tier():
    """The r14 starvation guard lives on the BULK tier only: an aged
    bulk pod still pops ahead of fresh high-priority arrivals while the
    fast tier drains separately."""
    from kubernetes_tpu.utils import features
    api, s = mk_sched(4)
    loop = s.stream(budget_s=30.0, fastlane=True)
    q = s.queue
    old = make_pod("old-victim", cpu=100, memory=64 * Mi)
    young = make_pod("young-vip", cpu=100, memory=64 * Mi)
    young.priority = 1000
    fast = fast_pod("fast-0")
    features.DEFAULT_FEATURE_GATE.set("PodPriority", True)
    try:
        q.add(old)
        q.add(young)
        q.add(fast)
        # backdate the victim past the aging threshold (the r14 guard's
        # trigger); the vip stays fresh
        q._queued_at[old.key()] -= q.aging_threshold_s + 1.0
        assert q.fast_count() == 1
        popped = q.pop_batch()
    finally:
        features.DEFAULT_FEATURE_GATE.set("PodPriority", False)
    assert [p.name for p in popped] == ["old-victim", "young-vip"]
    assert [p.name for p in q.pop_fast()] == ["fast-0"]
    loop.close()


# -------------------------------------------------------------- trend gate


def _write_round(tmp_path, r, **metrics):
    import json
    doc = {"n": 1, "cmd": "python bench.py", "rc": 0, "parsed": metrics}
    (tmp_path / f"BENCH_r{r:02d}.json").write_text(json.dumps(doc))


def test_trend_learns_fastlane_headlines(tmp_path):
    """bench --trend gates `fastlane_p99_ms` (down) and
    `mixed_bulk_sustained` (up) from r19 on — absent history tolerated,
    a past-band move in the bad direction flags."""
    from kubernetes_tpu.observability import trend

    assert ("fastlane_p99_ms", "fastlane p99 ms", "down") \
        in trend.HEADLINE_METRICS
    assert ("mixed_bulk_sustained", "mixed bulk frac", "up") \
        in trend.HEADLINE_METRICS
    _write_round(tmp_path, 18, value=30000.0)  # pre-r19: no fastlane keys
    _write_round(tmp_path, 19, value=30000.0, fastlane_p99_ms=7.5,
                 mixed_bulk_sustained=1.0)
    assert trend.find_regressions(trend.load_rounds(str(tmp_path))) == []
    _write_round(tmp_path, 20, value=30000.0, fastlane_p99_ms=25.0,
                 mixed_bulk_sustained=0.5)  # both past the band, bad way
    regs = trend.find_regressions(trend.load_rounds(str(tmp_path)))
    assert sorted(g["metric"] for g in regs) == \
        ["fastlane_p99_ms", "mixed_bulk_sustained"]


def test_trend_annotates_box_shape_change(tmp_path, capsys):
    """The r18 lesson as a feature: a flagged delta whose two rounds ran
    on DIFFERENT cpu counts carries `box_change` and is reported but
    NOT gated (exit 0); the same delta on a same-shape box gates."""
    from kubernetes_tpu.observability import trend

    _write_round(tmp_path, 18, churn_vs_quiet=0.85, cpus=2)
    _write_round(tmp_path, 19, churn_vs_quiet=0.45, cpus=1)  # 2->1 core
    regs = trend.find_regressions(trend.load_rounds(str(tmp_path)))
    assert [g["metric"] for g in regs] == ["churn_vs_quiet"]
    assert regs[0]["box_change"] == "2 -> 1 cpus"
    assert trend.main(["--root", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "box change: 2 -> 1 cpus" in out and "not gated" in out
    # same drop, same box shape: a real regression, exit 1
    _write_round(tmp_path, 20, churn_vs_quiet=0.85, cpus=1)
    _write_round(tmp_path, 21, churn_vs_quiet=0.45, cpus=1)
    regs = trend.find_regressions(trend.load_rounds(str(tmp_path)))
    assert regs and "box_change" not in regs[0]
    assert trend.main(["--root", str(tmp_path)]) == 1


def test_round_cpus_reads_r18_multiproc_fallback():
    """Pre-r19 artifacts only disclosed the box inside the multiproc
    sub-dict; `round_cpus` must still see it."""
    from kubernetes_tpu.observability.trend import round_cpus

    assert round_cpus({"cpus": 2}) == 2
    assert round_cpus({"multiproc": {"cpus": 1}}) == 1
    assert round_cpus({"value": 1.0}) is None
