"""Hollow kubelet / proxy / node lifecycle tests.

Behavioral shape from the reference's kubelet status tests, kubemark
hollow-node flow, proxier sync tests, and node_controller_test.go's
fake-clock eviction scenarios.
"""

import dataclasses

from kubernetes_tpu.api.types import (
    ConditionStatus,
    Taint,
    TaintEffect,
    Toleration,
    TolerationOperator,
    make_node,
    make_pod,
)
from kubernetes_tpu.api.workloads import Service, ServicePort
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.nodelifecycle import (
    ZONE_LABEL,
    NodeLifecycleController,
    TAINT_UNREACHABLE,
)
from kubernetes_tpu.nodes.kubelet import HollowFleet
from kubernetes_tpu.nodes.proxy import HollowProxy
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.utils import features


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def mk_fleet(n_nodes=2, clock=None, **kw):
    clock = clock or FakeClock()
    api = ApiServerLite()
    factory = SharedInformerFactory(api)
    fleet = HollowFleet(api, factory, now=clock, **kw)
    for i in range(n_nodes):
        fleet.add_node(make_node(f"n{i}", cpu=1000, memory=1 << 30, pods=4))
    factory.step_all()
    return api, factory, fleet, clock


# ------------------------------------------------------------------ kubelet


def test_kubelet_runs_bound_pod():
    api, factory, fleet, clock = mk_fleet()
    api.create("Pod", make_pod("p", cpu=100, node_name="n0"))
    factory.step_all()
    fleet.step()
    assert api.get("Pod", "default", "p").phase == "Running"


def test_kubelet_startup_latency_and_completion():
    api, factory, fleet, clock = mk_fleet(startup_latency=3.0)
    pod = make_pod("job-pod", cpu=100, node_name="n0")
    pod.annotations["bench/run-seconds"] = "10"
    api.create("Pod", pod)
    factory.step_all()
    fleet.step()
    assert api.get("Pod", "default", "job-pod").phase == "Pending"  # starting
    clock.t += 3.0
    fleet.step()
    assert api.get("Pod", "default", "job-pod").phase == "Running"
    clock.t += 10.0
    fleet.step()
    assert api.get("Pod", "default", "job-pod").phase == "Succeeded"


def test_kubelet_admission_rejects_over_capacity():
    api, factory, fleet, clock = mk_fleet()  # nodes: 1000m cpu
    api.create("Pod", make_pod("big1", cpu=800, node_name="n0"))
    factory.step_all()
    fleet.step()
    # second pod over cpu capacity on the same node
    api.create("Pod", make_pod("big2", cpu=800, node_name="n0"))
    factory.step_all()
    fleet.step()
    p2 = api.get("Pod", "default", "big2")
    assert p2.phase == "Failed"
    assert p2.annotations["kubernetes.io/failure-reason"] == "OutOfcpu"
    # but it fits on the other node
    api.create("Pod", make_pod("big3", cpu=800, node_name="n1"))
    factory.step_all()
    fleet.step()
    assert api.get("Pod", "default", "big3").phase == "Running"


def test_kubelet_heartbeat_updates_node():
    api, factory, fleet, clock = mk_fleet()
    clock.t += 100.0
    fleet.heartbeat_all()
    node = api.get("Node", "", "n0")
    assert node.heartbeat == clock.t
    assert node.condition("Ready") == ConditionStatus.TRUE


def test_kubelet_forgets_deleted_pod_freeing_capacity():
    api, factory, fleet, clock = mk_fleet()
    api.create("Pod", make_pod("a", cpu=800, node_name="n0"))
    factory.step_all()
    fleet.step()
    api.delete("Pod", "default", "a")
    factory.step_all()
    api.create("Pod", make_pod("b", cpu=800, node_name="n0"))
    factory.step_all()
    fleet.step()
    assert api.get("Pod", "default", "b").phase == "Running"


# -------------------------------------------------------------------- proxy


def test_proxy_routes_round_robin_and_resyncs():
    api = ApiServerLite()
    factory = SharedInformerFactory(api)
    from kubernetes_tpu.controllers.endpoint import EndpointController
    epc = EndpointController(api, factory, record_events=False)
    proxy = HollowProxy(factory)
    api.create("Service", Service(name="web", selector={"app": "web"},
                                  ports=[ServicePort(port=80, target_port=8080)]))
    for i in range(3):
        api.create("Pod", dataclasses.replace(
            make_pod(f"w{i}", labels={"app": "web"}, node_name=f"n{i}"),
            phase="Running"))
    factory.step_all()
    epc.pump()
    factory.step_all()
    backends = proxy.backends("default/web", 80)
    assert len(backends) == 3
    assert all(port == 8080 for _, port, _ in backends)
    picked = {proxy.route("default/web", 80)[2] for _ in range(3)}
    assert picked == {"n0", "n1", "n2"}  # round robin covers all
    # endpoint removal propagates
    api.delete("Pod", "default", "w0")
    factory.step_all()
    epc.pump()
    factory.step_all()
    assert len(proxy.backends("default/web", 80)) == 2
    assert proxy.route("default/unknown", 80) is None


# ----------------------------------------------------------- node lifecycle


def mk_lifecycle(n_nodes=4, zones=1, clock=None, **kw):
    clock = clock or FakeClock()
    api = ApiServerLite()
    factory = SharedInformerFactory(api)
    for i in range(n_nodes):
        node = make_node(f"n{i}", labels={ZONE_LABEL: f"z{i % zones}"})
        node.heartbeat = clock.t
        api.create("Node", node)
    nlc = NodeLifecycleController(
        api, factory, grace_period=40.0, eviction_timeout=300.0,
        record_events=False, now=clock, **kw)
    factory.step_all()
    return api, factory, nlc, clock


def test_dead_node_marked_unknown_then_pods_evicted():
    api, factory, nlc, clock = mk_lifecycle()
    api.create("Pod", make_pod("victim", node_name="n0"))
    api.create("Pod", make_pod("safe", node_name="n1"))
    factory.step_all()
    # n0's kubelet dies; others keep heartbeating
    for tick in range(8):
        clock.t += 60.0
        for i in (1, 2, 3):
            n = api.get("Node", "", f"n{i}")
            api.update("Node", dataclasses.replace(n, heartbeat=clock.t))
        factory.step_all()
        nlc.monitor_tick()
        factory.step_all()
    assert api.get("Node", "", "n0").condition("Ready") == ConditionStatus.UNKNOWN
    names = {p.name for p in api.list("Pod")[0]}
    assert "victim" not in names and "safe" in names


def test_static_node_gets_grace_from_first_observation():
    """A Node that never heartbeat (heartbeat=0.0: decoded/static objects)
    must get the grace period from first observation, not be drained on the
    first tick."""
    clock = FakeClock(t=50_000.0)  # monotonic clock far from 0
    api = ApiServerLite()
    factory = SharedInformerFactory(api)
    api.create("Node", make_node("static"))  # heartbeat defaults to 0.0
    api.create("Node", make_node("live"))
    api.create("Pod", make_pod("p", node_name="static"))
    nlc = NodeLifecycleController(api, factory, grace_period=40.0,
                                  eviction_timeout=60.0, record_events=False,
                                  now=clock)
    factory.step_all()

    def tick(dt):
        clock.t += dt
        n = api.get("Node", "", "live")
        api.update("Node", dataclasses.replace(n, heartbeat=clock.t))
        factory.step_all()
        nlc.monitor_tick()
        factory.step_all()

    tick(0.0)
    assert api.get("Node", "", "static").condition("Ready") == ConditionStatus.TRUE
    assert len(api.list("Pod")[0]) == 1  # not drained on first observation
    # but with nobody ever heartbeating it, it IS eventually drained
    for _ in range(8):
        tick(30.0)
    assert api.get("Node", "", "static").condition("Ready") == ConditionStatus.UNKNOWN
    assert api.list("Pod")[0] == []


def test_full_zone_disruption_stops_evictions():
    api, factory, nlc, clock = mk_lifecycle(n_nodes=4, zones=2)
    api.create("Pod", make_pod("p0", node_name="n0"))
    factory.step_all()
    # zone z0 = {n0, n2}: kill both kubelets; z1 stays healthy
    for tick in range(8):
        clock.t += 60.0
        for i in (1, 3):
            n = api.get("Node", "", f"n{i}")
            api.update("Node", dataclasses.replace(n, heartbeat=clock.t))
        factory.step_all()
        nlc.monitor_tick()
        factory.step_all()
    assert nlc.zone_states["z0"] == "FullDisruption"
    # pods NOT evicted despite timeout: master assumes its own partition
    assert any(p.name == "p0" for p in api.list("Pod")[0])


def test_taint_based_eviction_spares_tolerating_pods():
    features.DEFAULT_FEATURE_GATE.set("TaintBasedEvictions", True)
    try:
        api, factory, nlc, clock = mk_lifecycle()
        tol = Toleration(key=TAINT_UNREACHABLE,
                         operator=TolerationOperator.EXISTS,
                         effect=TaintEffect.NO_EXECUTE)
        api.create("Pod", make_pod("tolerant", node_name="n0",
                                   tolerations=[tol]))
        api.create("Pod", make_pod("intolerant", node_name="n0"))
        factory.step_all()
        for tick in range(10):
            clock.t += 60.0
            for i in (1, 2, 3):
                n = api.get("Node", "", f"n{i}")
                api.update("Node", dataclasses.replace(n, heartbeat=clock.t))
            factory.step_all()
            nlc.monitor_tick()
            factory.step_all()
        node = api.get("Node", "", "n0")
        assert any(t.key == TAINT_UNREACHABLE for t in node.taints)
        names = {p.name for p in api.list("Pod")[0]}
        assert names == {"tolerant"}
        # node recovers: taint removed
        api.update("Node", dataclasses.replace(node, heartbeat=clock.t))
        factory.step_all()
        nlc.monitor_tick()
        factory.step_all()
        assert api.get("Node", "", "n0").taints == []
    finally:
        features.DEFAULT_FEATURE_GATE.reset()


def test_eviction_rate_limited_across_nodes_in_zone():
    api, factory, nlc, clock = mk_lifecycle(n_nodes=10)
    for i in range(5):  # 5 dead nodes with a pod each
        api.create("Pod", make_pod(f"p{i}", node_name=f"n{i}"))
    factory.step_all()

    def tick(dt):
        clock.t += dt
        for i in range(5, 10):  # n5..n9 keep heartbeating
            n = api.get("Node", "", f"n{i}")
            api.update("Node", dataclasses.replace(n, heartbeat=clock.t))
        factory.step_all()
        nlc.monitor_tick()
        factory.step_all()
        return len(api.list("Pod")[0])

    counts = [tick(60.0) for _ in range(12)]
    # evictions begin once unhealthy-duration crosses 300s, then proceed at
    # most one node-drain per tick (rate 0.1/s, burst 1, 60s ticks)
    assert counts[0] == 5  # within timeout: nothing evicted
    assert counts[-1] == 0  # eventually all drained
    drops = [a - b for a, b in zip(counts, counts[1:])]
    assert max(drops) == 1, f"rate limit breached: {counts}"
