"""Golden tests: TPU predicate kernels vs the pure-Python oracle.

Mirrors the table-driven strategy of the reference's predicates_test.go
(3,661 lines of pods x nodes x expected-fit tables) with randomized tables:
every (pod, node) pair's kernel verdict must equal the object-level oracle.
"""

import random

import numpy as np
import pytest

from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    ContainerPort,
    NodeAffinity,
    NodeSelectorTerm,
    Pod,
    SelectorOperator,
    SelectorRequirement,
    Toleration,
    TolerationOperator,
    make_node,
    make_pod,
)
from kubernetes_tpu.ops import oracle
from kubernetes_tpu.ops.predicates import fits_jit, node_arrays, pod_arrays
from kubernetes_tpu.state.node_info import NodeInfo, node_info_map
from kubernetes_tpu.state.snapshot import ClusterSnapshot, PodBatch
from tests.helpers import Gi, Mi, random_nodes, random_pod


def kernel_fits_matrix(pods, nodes, bound_pods=()):
    infos = node_info_map(nodes, list(bound_pods))
    snap = ClusterSnapshot()
    snap.refresh(infos)
    batch = PodBatch(pods, snap)
    m = np.asarray(fits_jit(pod_arrays(batch), node_arrays(snap)))
    # columns follow snapshot (sorted) node order
    return m, snap.node_names, infos, batch


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_random_cluster_matches_oracle(seed):
    rng = random.Random(seed)
    nodes = random_nodes(rng, 24)
    names = [n.name for n in nodes]
    pending = [random_pod(rng, i, names) for i in range(40)]
    # some already-bound pods occupying capacity/ports
    bound = []
    for i in range(30):
        p = random_pod(rng, 1000 + i, names)
        p.node_name = rng.choice(names)
        p.node_selector = {}
        p.affinity = None
        bound.append(p)

    m, snap_names, infos, batch = kernel_fits_matrix(pending, nodes, bound)
    mismatches = []
    for pi, pod in enumerate(pending):
        for ni, nm in enumerate(snap_names):
            expect = oracle.pod_fits(pod, infos[nm])
            if batch.needs_host_check[pi]:
                # over-approximation allowed: kernel True, oracle False is OK
                if expect and not m[pi, ni]:
                    mismatches.append((pod.name, nm, expect, bool(m[pi, ni])))
            elif bool(m[pi, ni]) != expect:
                mismatches.append((pod.name, nm, expect, bool(m[pi, ni])))
    assert not mismatches, mismatches[:10]


def test_zero_request_pod_only_checks_pod_count():
    node = make_node("n1", cpu=100, memory=128 * Mi, pods=2)
    hog = make_pod("hog", cpu=100, memory=128 * Mi, node_name="n1")
    zero = Pod(name="zero", containers=[Container(name="c0")])
    m, names, infos, _ = kernel_fits_matrix([zero], [node], [hog])
    assert m[0, 0]  # full node, but zero-request pod fits (predicates.go:576)
    # second bound pod exhausts allowedPodNumber=2
    hog2 = make_pod("hog2", node_name="n1")
    m, names, infos, _ = kernel_fits_matrix([zero], [node], [hog, hog2])
    assert not m[0, 0]


def test_host_port_conflict():
    node = make_node("n1")
    holder = make_pod("holder", ports=[8080], node_name="n1")
    want_same = make_pod("w1", ports=[8080])
    want_other = make_pod("w2", ports=[8081])
    m, *_ = kernel_fits_matrix([want_same, want_other], [node], [holder])
    assert not m[0, 0]
    assert m[1, 0]


def test_node_selector_and_affinity_or_terms():
    n_ssd = make_node("ssd-node", labels={"disk": "ssd"})
    n_hdd = make_node("hdd-node", labels={"disk": "hdd"})
    n_bare = make_node("bare-node")
    sel = make_pod("sel", node_selector={"disk": "ssd"})
    aff = make_pod("aff")
    aff.affinity = Affinity(node_affinity=NodeAffinity(required_terms=[
        NodeSelectorTerm([SelectorRequirement("disk", SelectorOperator.IN, ["ssd"])]),
        NodeSelectorTerm([SelectorRequirement("disk", SelectorOperator.IN, ["hdd"])]),
    ]))
    none_match = make_pod("none")
    none_match.affinity = Affinity(node_affinity=NodeAffinity(required_terms=[]))
    m, names, *_ = kernel_fits_matrix(
        [sel, aff, none_match], [n_ssd, n_hdd, n_bare])
    col = {nm: i for i, nm in enumerate(names)}
    assert m[0, col["ssd-node"]] and not m[0, col["hdd-node"]] and not m[0, col["bare-node"]]
    assert m[1, col["ssd-node"]] and m[1, col["hdd-node"]] and not m[1, col["bare-node"]]
    # empty required_terms list matches NO nodes (predicates.go:646)
    assert not m[2].any()


def test_selector_not_in_matches_absent_key():
    labeled = make_node("labeled", labels={"arch": "arm64"})
    unlabeled = make_node("unlabeled")
    p = make_pod("p")
    p.affinity = Affinity(node_affinity=NodeAffinity(required_terms=[
        NodeSelectorTerm([SelectorRequirement("arch", SelectorOperator.NOT_IN, ["arm64"])]),
    ]))
    m, names, *_ = kernel_fits_matrix([p], [labeled, unlabeled])
    col = {nm: i for i, nm in enumerate(names)}
    assert not m[0, col["labeled"]]
    assert m[0, col["unlabeled"]]


def test_taints_and_tolerations():
    from kubernetes_tpu.api.types import Taint, TaintEffect
    tainted = make_node("tainted", taints=[Taint("dedicated", "gpu", TaintEffect.NO_SCHEDULE)])
    prefer = make_node("prefer", taints=[Taint("noisy", "", TaintEffect.PREFER_NO_SCHEDULE)])
    plain = make_pod("plain")
    tolerant = make_pod("tolerant", tolerations=[
        Toleration("dedicated", TolerationOperator.EQUAL, "gpu", TaintEffect.NO_SCHEDULE)])
    wildcard = make_pod("wild", tolerations=[
        Toleration("", TolerationOperator.EXISTS, "", None)])
    m, names, *_ = kernel_fits_matrix([plain, tolerant, wildcard], [tainted, prefer])
    col = {nm: i for i, nm in enumerate(names)}
    assert not m[0, col["tainted"]]
    assert m[0, col["prefer"]]  # PreferNoSchedule never filters
    assert m[1, col["tainted"]]
    assert m[2, col["tainted"]]


def test_unready_and_unschedulable_nodes_filtered():
    bad = make_node("bad", ready=False)
    cordoned = make_node("cordoned", unschedulable=True)
    good = make_node("good")
    p = make_pod("p", cpu=100)
    m, names, *_ = kernel_fits_matrix([p], [bad, cordoned, good])
    col = {nm: i for i, nm in enumerate(names)}
    assert not m[0, col["bad"]]
    assert not m[0, col["cordoned"]]
    assert m[0, col["good"]]


def test_gpu_and_resource_accounting():
    gpu_node = make_node("gpu", gpu=2)
    cpu_node = make_node("cpu")
    holder = make_pod("holder", gpu=1, node_name="gpu")
    one = make_pod("one", gpu=1)
    two = make_pod("two", gpu=2)
    m, names, *_ = kernel_fits_matrix([one, two], [gpu_node, cpu_node], [holder])
    col = {nm: i for i, nm in enumerate(names)}
    assert m[0, col["gpu"]]
    assert not m[1, col["gpu"]]  # 1 used + 2 wanted > 2
    assert not m[0, col["cpu"]]
