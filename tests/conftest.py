"""Test env: force the CPU backend with 8 virtual devices BEFORE jax import,
so sharding/mesh tests run anywhere (multi-chip TPU hardware is not available
in CI; the driver separately dry-runs __graft_entry__.dryrun_multichip)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The image's sitecustomize may have already registered a TPU PJRT plugin and
# prepended its platform to jax_platforms (overriding the env var). Backends
# are not initialized yet at conftest-import time, so force the config back.
import jax

if jax.config.jax_platforms != "cpu":
    jax.config.update("jax_platforms", "cpu")
