"""Test env: force the CPU backend with 8 virtual devices BEFORE jax import,
so sharding/mesh tests run anywhere (multi-chip TPU hardware is not available
in CI; the driver separately dry-runs __graft_entry__.dryrun_multichip)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
