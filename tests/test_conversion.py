"""Core-group versioned conversion (api/scheme.py core codecs,
api/serde.py encoders).

Pins the runtime.Scheme invariants the reference's generated conversions
guarantee (pkg/api/v1/conversion.go, apimachinery runtime.Scheme):
decode applies defaults exactly once; decode(encode(x)) == x over the
wire-carried surface; v1<->v2 converts losslessly through the internal
hub including field renames; unknown versions fail loudly. The fuzz
round-trips random manifests, the moral analog of the reference's
roundtrip_test.go fuzzing (apimachinery/pkg/api/testing)."""

import random

import pytest

from kubernetes_tpu.api.scheme import DEFAULT_SCHEME, SchemeError
from kubernetes_tpu.api.types import Pod


# --------------------------------------------------------------- defaults


def test_pod_decode_applies_defaults_once():
    data = {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "p"},
            "spec": {"containers": [{"name": "c"}]}}
    pod = DEFAULT_SCHEME.decode(data)
    assert pod.scheduler_name == "default-scheduler"  # defaulted
    assert pod.restart_policy == "Always"  # defaulted
    assert pod.namespace == "default"  # defaulted
    # encode makes the defaults explicit; a second decode is idempotent
    wire = DEFAULT_SCHEME.encode(pod, "v1", "Pod")
    assert wire["spec"]["schedulerName"] == "default-scheduler"
    assert wire["spec"]["restartPolicy"] == "Always"
    assert DEFAULT_SCHEME.decode(wire) == pod


def test_unknown_core_version_fails_loudly():
    with pytest.raises(SchemeError):
        DEFAULT_SCHEME.decode({"apiVersion": "v9", "kind": "Pod",
                               "metadata": {"name": "p"}})


# ---------------------------------------------------------- field renames


def test_pod_v2_round_trip_renames_fields():
    v1 = {"apiVersion": "v1", "kind": "Pod",
          "metadata": {"name": "p", "namespace": "ns"},
          "spec": {"containers": [{"name": "c"}],
                   "nodeName": "n1", "schedulerName": "custom"}}
    pod = DEFAULT_SCHEME.decode(v1)
    v2 = DEFAULT_SCHEME.encode(pod, "v2", "Pod")
    assert v2["spec"]["boundNode"] == "n1"  # renamed
    assert v2["spec"]["scheduler"] == "custom"  # renamed
    assert "nodeName" not in v2["spec"]
    assert "schedulerName" not in v2["spec"]
    # v2 decodes to the SAME internal object (two-hop conversion)
    assert DEFAULT_SCHEME.decode(v2) == pod
    # and scheme.convert round-trips versioned->versioned
    v1_again = DEFAULT_SCHEME.convert(v2, "v1")
    assert v1_again["spec"]["nodeName"] == "n1"
    assert DEFAULT_SCHEME.decode(v1_again) == pod


def test_node_v2_round_trip_renames_unschedulable():
    v1 = {"apiVersion": "v1", "kind": "Node",
          "metadata": {"name": "n1", "labels": {"zone": "a"}},
          "spec": {"unschedulable": True, "taints": []},
          "status": {"allocatable": {"cpu": "4000m", "memory": "1048576",
                                     "pods": "110"},
                     "conditions": [{"type": "Ready", "status": "True"}]}}
    node = DEFAULT_SCHEME.decode(v1)
    assert node.unschedulable is True
    v2 = DEFAULT_SCHEME.encode(node, "v2", "Node")
    assert v2["spec"]["schedulingDisabled"] is True
    assert "unschedulable" not in v2["spec"]
    assert DEFAULT_SCHEME.decode(v2) == node


def test_service_v1_codec():
    data = {"apiVersion": "v1", "kind": "Service",
            "name": "svc", "namespace": "default",
            "selector": {"app": "web"}}
    svc = DEFAULT_SCHEME.decode(data)
    assert svc.name == "svc" and svc.selector == {"app": "web"}
    wire = DEFAULT_SCHEME.encode(svc, "v1", "Service")
    assert DEFAULT_SCHEME.decode(wire) == svc
    # the kubectl metadata/spec manifest shape decodes to the same object
    manifest = {"apiVersion": "v1", "kind": "Service",
                "metadata": {"name": "svc", "namespace": "default"},
                "spec": {"selector": {"app": "web"}}}
    assert DEFAULT_SCHEME.decode(manifest) == svc


def test_node_annotations_round_trip():
    v1 = {"apiVersion": "v1", "kind": "Node",
          "metadata": {"name": "n1", "annotations": {"k": "v"}},
          "spec": {}, "status": {"allocatable": {"cpu": "1000m",
                                                 "memory": "1048576",
                                                 "pods": "10"}}}
    node = DEFAULT_SCHEME.decode(v1)
    assert node.annotations == {"k": "v"}
    assert DEFAULT_SCHEME.decode(
        DEFAULT_SCHEME.encode(node, "v2", "Node")) == node


def test_empty_affinity_stanzas_round_trip():
    """decode({'nodeAffinity': {}}) and decode({'podAffinity': {}}) are
    real states (match-everything / empty) and must survive encode."""
    data = {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "p"},
            "spec": {"containers": [{"name": "c"}],
                     "affinity": {"nodeAffinity": {},
                                  "podAffinity": {}}}}
    pod = DEFAULT_SCHEME.decode(data)
    assert pod.affinity is not None
    assert pod.affinity.node_affinity is not None
    assert pod.affinity.pod_affinity is not None
    assert DEFAULT_SCHEME.decode(
        DEFAULT_SCHEME.encode(pod, "v1", "Pod")) == pod


# -------------------------------------------------------- round-trip fuzz


def _random_manifest(rng: random.Random) -> dict:
    Mi = 1 << 20
    containers = []
    for i in range(rng.randint(1, 3)):
        c = {"name": f"c{i}",
             "image": rng.choice(["", "app:v1", "registry/x:2"]),
             "resources": {"requests": {
                 "cpu": f"{rng.randint(1, 4000)}m",
                 "memory": str(rng.randint(1, 64) * Mi)}}}
        if rng.random() < 0.3:
            c["resources"]["limits"] = {
                "cpu": f"{rng.randint(1000, 8000)}m"}
        if rng.random() < 0.3:
            c["ports"] = [{"hostPort": rng.randint(0, 1),
                           "containerPort": rng.randint(1, 9999),
                           "protocol": "TCP"}]
        if rng.random() < 0.3:
            c["livenessProbe"] = {
                "exec": {}, "initialDelaySeconds": float(rng.randint(0, 9)),
                "periodSeconds": 10.0, "failureThreshold": 3,
                "successThreshold": 1}
        containers.append(c)
    spec = {"containers": containers,
            "nodeName": rng.choice(["", "n1"]),
            "schedulerName": rng.choice(["default-scheduler", "custom"]),
            "restartPolicy": rng.choice(["Always", "OnFailure", "Never"])}
    if rng.random() < 0.4:
        spec["tolerations"] = [{
            "key": "dedicated", "operator": "Equal", "value": "gpu",
            "effect": "NoSchedule"}]
    if rng.random() < 0.4:
        spec["affinity"] = {
            "nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [{"matchExpressions": [{
                        "key": "zone", "operator": "In",
                        "values": ["a", "b"]}]}]}},
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [{
                    "topologyKey": "kubernetes.io/hostname",
                    "labelSelector": {"matchLabels": {"app": "web"}}}],
                "preferredDuringSchedulingIgnoredDuringExecution": [{
                    "weight": 7, "podAffinityTerm": {
                        "topologyKey": "zone",
                        "labelSelector": {"matchExpressions": [{
                            "key": "tier", "operator": "NotIn",
                            "values": ["db"]}]}}}]}}
    if rng.random() < 0.3:
        spec["priority"] = rng.randint(1, 1000)
    if rng.random() < 0.2:
        spec["hostNetwork"] = True
    meta = {"name": f"p{rng.randint(0, 999)}",
            "namespace": rng.choice(["default", "kube-system"]),
            "labels": {"app": rng.choice(["web", "db"])}}
    if rng.random() < 0.4:
        meta["annotations"] = {"a": "1", "b": "two"}
    if rng.random() < 0.3:
        meta["ownerReferences"] = [{"kind": "ReplicaSet", "name": "rs-1",
                                    "uid": "u1", "controller": True}]
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": meta, "spec": spec}


def test_round_trip_fuzz_v1_and_v2():
    rng = random.Random(7)
    for i in range(200):
        data = _random_manifest(rng)
        pod = DEFAULT_SCHEME.decode(data)
        assert isinstance(pod, Pod)
        # v1 round trip
        assert DEFAULT_SCHEME.decode(
            DEFAULT_SCHEME.encode(pod, "v1", "Pod")) == pod, i
        # v2 round trip (rename hop both ways through internal)
        assert DEFAULT_SCHEME.decode(
            DEFAULT_SCHEME.encode(pod, "v2", "Pod")) == pod, i
        # versioned->versioned conversion is stable after the first hop
        v2 = DEFAULT_SCHEME.convert(data, "v2")
        v1b = DEFAULT_SCHEME.convert(v2, "v1")
        assert DEFAULT_SCHEME.convert(v1b, "v2") == v2, i


def test_generic_v1_codecs_cover_every_wire_kind():
    """The scheme serves a v1 codec for EVERY reflective wire kind, in
    both accepted manifest shapes, round-tripping losslessly."""
    from kubernetes_tpu.api.wire import KIND_REGISTRY
    for kind in KIND_REGISTRY:
        assert ("v1", kind) in DEFAULT_SCHEME.versions(), kind
    # flat native shape
    dep = DEFAULT_SCHEME.decode({
        "apiVersion": "v1", "kind": "Deployment",
        "name": "web", "replicas": 3})
    assert dep.name == "web" and dep.replicas == 3
    assert DEFAULT_SCHEME.decode(
        DEFAULT_SCHEME.encode(dep, "v1", "Deployment")) == dep
    # kubectl metadata/spec shape
    dep2 = DEFAULT_SCHEME.decode({
        "apiVersion": "v1", "kind": "Deployment",
        "metadata": {"name": "web", "namespace": "prod",
                     "labels": {"a": "b"}},
        "spec": {"replicas": 5}})
    assert dep2.namespace == "prod" and dep2.replicas == 5
    assert dep2.labels == {"a": "b"}


def test_node_capacity_reservation_round_trip():
    """A node publishing capacity != allocatable (node-allocatable
    reservation) keeps both through the codec."""
    from kubernetes_tpu.api.types import Resource, make_node
    from kubernetes_tpu.api import serde
    node = make_node("n1", cpu=3500, memory=7 << 30)
    node.capacity = Resource(milli_cpu=4000, memory=8 << 30)
    enc = serde.encode_node(node)
    assert enc["status"]["capacity"]["cpu"] == "4000m"
    back = serde.decode_node(enc)
    assert back.allocatable.milli_cpu == 3500
    assert back.capacity.milli_cpu == 4000
