"""Native layer: C++ hostops kernels, the pause binary, the make build.

The native seam of SURVEY §2 ("C++ host-side tensor snapshot encoder" +
the pause.c equivalent, reference build/pause/pause.c). Every kernel must
be bit-identical to its pure-Python fallback; the toolchain is baked into
the image, so the build paths are exercised for real here.
"""

from __future__ import annotations

import os
import random
import shutil
import signal
import subprocess
import time

import numpy as np
import pytest

from kubernetes_tpu import native

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HAVE_GXX = shutil.which("g++") is not None


@pytest.mark.skipif(not HAVE_GXX, reason="no g++ in image")
def test_hostops_builds_and_loads():
    assert native.available(), "hostops must build on demand with g++"


def _python_only(monkeypatch):
    """Force the fallback path regardless of the loaded library."""
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)


@pytest.mark.skipif(not HAVE_GXX, reason="no g++ in image")
def test_port_bitmaps_native_matches_python(monkeypatch):
    rng = random.Random(7)
    pairs = np.array([[rng.randrange(0, 64),
                       rng.randrange(-5, 70000)]  # incl. out-of-range
                      for _ in range(500)], dtype=np.int64)
    a = np.zeros((64, 2048), dtype=np.uint32)
    native.fill_port_bitmaps(pairs, a)
    b = np.zeros((64, 2048), dtype=np.uint32)
    with pytest.MonkeyPatch.context() as mp:
        _python_only(mp)
        native.fill_port_bitmaps(pairs, b)
    np.testing.assert_array_equal(a, b)
    assert a.any()


@pytest.mark.skipif(not HAVE_GXX, reason="no g++ in image")
def test_multi_hot_native_matches_python(monkeypatch):
    rng = random.Random(11)
    pairs = np.array([[rng.randrange(-2, 40), rng.randrange(-2, 70)]
                      for _ in range(400)], dtype=np.int64)
    a = np.zeros((32, 64), dtype=np.int8)
    native.fill_multi_hot(pairs, a)
    b = np.zeros((32, 64), dtype=np.int8)
    with pytest.MonkeyPatch.context() as mp:
        _python_only(mp)
        native.fill_multi_hot(pairs, b)
    np.testing.assert_array_equal(a, b)
    assert a.any()


@pytest.mark.skipif(not HAVE_GXX, reason="no g++ in image")
def test_fnv1a64_native_matches_python():
    for data in (b"", b"x", b"kubernetes-tpu", bytes(range(256)) * 3):
        got = native.fnv1a64(data)
        with pytest.MonkeyPatch.context() as mp:
            _python_only(mp)
            want = native.fnv1a64(data)
        assert got == want


def test_snapshot_label_rebuild_uses_batch_scatter():
    """The wiring point: finalize_labels' full-matrix rebuild goes through
    fill_multi_hot and stays correct (vs the logical per-row content)."""
    from kubernetes_tpu.api.types import make_node, make_pod
    from kubernetes_tpu.state.node_info import node_info_map
    from kubernetes_tpu.state.snapshot import ClusterSnapshot, PodBatch

    nodes = [make_node(f"n{i}", labels={"zone": f"z{i % 3}",
                                        "disk": "ssd" if i % 2 else "hdd"})
             for i in range(16)]
    snap = ClusterSnapshot()
    snap.refresh(node_info_map(nodes, []))
    # grow the demand-driven vocab -> full rebuild through the batch scatter
    pod = make_pod("p", node_selector={"zone": "z1", "disk": "ssd"})
    PodBatch([pod], snap)
    # every INTERNED pair's column carries exactly its nodes' bits (the
    # vocab is selector-demand-driven; un-referenced labels have no column)
    for key, val in (("zone", "z1"), ("disk", "ssd")):
        col = snap.label_vocab.get(key, val)
        assert col >= 0
        for n in nodes:
            row = snap.node_index[n.name]  # rows are sorted-name order
            want = 1 if n.labels.get(key) == val else 0
            assert snap.labels[row, col] == want, (n.name, key, val)


# ------------------------------------------------------------------ pause


@pytest.mark.skipif(not HAVE_GXX, reason="no g++ in image")
def test_pause_builds_and_terminates_cleanly(tmp_path):
    binary = tmp_path / "pause"
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-o", str(binary),
         os.path.join(ROOT, "build", "pause", "pause.cc")],
        check=True, capture_output=True, timeout=120)
    proc = subprocess.Popen([str(binary)], stderr=subprocess.PIPE)
    try:
        time.sleep(0.2)
        assert proc.poll() is None  # pausing, not exiting
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=10) == 0  # clean shutdown on TERM
        assert b"signal" in proc.stderr.read()
    finally:
        if proc.poll() is None:
            proc.kill()


@pytest.mark.skipif(not HAVE_GXX or shutil.which("make") is None,
                    reason="no toolchain")
def test_make_builds_everything(tmp_path):
    env = dict(os.environ)
    subprocess.run(["make", "-C", os.path.join(ROOT, "build"), "clean"],
                   check=True, capture_output=True, env=env, timeout=120)
    subprocess.run(["make", "-C", os.path.join(ROOT, "build"), "all"],
                   check=True, capture_output=True, env=env, timeout=300)
    assert os.path.exists(os.path.join(ROOT, "build", "bin", "pause"))
    assert os.path.exists(os.path.join(ROOT, "native", "libhostops.so"))
