"""Embedded verdict API (ISSUE 11): the in-process deployment mode.

server/embedded.py is both the transport-agnostic service core every
wire adapts (VerdictService — the HTTP extender and the async binary
wire delegate here) and the zero-wire embedding a co-located frontend
links directly (EmbeddedVerdictAPI). These tests pin the embedding
contract: the coalescer, stale window, fence and ledger stay INTACT
under concurrent embedded frontends — embedding removes the socket,
never a semantic.
"""

from __future__ import annotations

import random
import threading

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.models.hollow import hollow_nodes
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.server.embedded import (
    BindResult,
    EmbeddedVerdictAPI,
    FilterVerdict,
    VerdictService,
)
from kubernetes_tpu.testing.churn import FaultyBindApi, extender_store_binder

N_NODES = 96


def _pod(name: str, cpu: int = 100):
    return make_pod(name, cpu=cpu, memory=256 << 20)


def _embedded(binder=None, **kw) -> EmbeddedVerdictAPI:
    api = EmbeddedVerdictAPI(binder=binder, **kw)
    nodes = hollow_nodes(N_NODES)
    api.sync_nodes(nodes)
    api.filter(_pod("warm"))
    return api


def test_filter_verdict_contract_and_compact_elision():
    api = _embedded()
    v = api.filter(_pod("fv"), top_k=8, compact=True)
    assert isinstance(v, FilterVerdict)
    assert v.all_passed and v.passed_count == N_NODES
    assert v.passed is None  # compact + all passed: elided
    assert len(v.top_scores) == 8 and v.snapshot_gen is not None
    # non-compact keeps the echo; top_k=0 keeps /prioritize separate
    v = api.filter(_pod("fv2"))
    assert v.passed is not None and len(v.passed) == N_NODES
    assert v.top_scores is None
    # restricted candidate set: never elided, split honors the names
    v = api.filter(_pod("fv3"), node_names=[v.passed[0], "no-such-node"],
                   top_k=4, compact=True)
    assert v.passed_count == 1 and len(v.failed) == 1
    assert [h for h, _s in v.top_scores] == v.passed


def test_bind_result_typed_fence_conflict():
    api = EmbeddedVerdictAPI(stale_window_s=0.0)
    api.sync_nodes([make_node(f"tiny-{i}", cpu=1000, memory=4 << 30,
                              pods=110) for i in range(2)])
    spec = make_pod("a", cpu=900, memory=256 << 20)
    v = api.filter(spec, top_k=4)
    res = api.bind("a", "default", "u-a", "tiny-0",
                   snapshot_gen=v.snapshot_gen, idem_key="a:1", pod=spec)
    assert isinstance(res, BindResult) and res.ok
    spec_b = make_pod("b", cpu=900, memory=256 << 20)
    res = api.bind("b", "default", "u-b", "tiny-0",
                   snapshot_gen=v.snapshot_gen, idem_key="b:1", pod=spec_b)
    assert res.retryable and res.kind == "conflict"
    assert res.error.startswith("CONFLICT") and res.retry_after_s > 0
    # TopScores after the fix: a non-fitting node must NOT appear even
    # when fewer than k nodes fit (the int32 sentinel-wrap regression)
    v2 = api.filter(spec_b, top_k=4)
    assert [h for h, _s in v2.top_scores] == ["tiny-1"]


def test_schedule_one_embedded_frontends_store_audited():
    """N embedded frontend threads drive schedule_one concurrently under
    injected bind faults: every pod lands on exactly one node at the
    store, evaluations coalesce, capacity accrues."""
    store = ApiServerLite(max_log=100_000)
    nodes = hollow_nodes(N_NODES)
    for n in nodes:
        store.create("Node", n)
    faulty = FaultyBindApi(store, fail_rate=0.1, timeout_rate=0.1, seed=5)
    api = EmbeddedVerdictAPI(binder=extender_store_binder(faulty),
                             coalesce_window_s=0.001)
    api.sync_nodes(nodes)
    api.filter(_pod("warm"))
    n_clients, per = 6, 8
    for c in range(n_clients):
        for i in range(per):
            store.create("Pod", _pod(f"emb-{c}-{i}"))
    errors, lock = [], threading.Lock()
    start = threading.Barrier(n_clients)

    def drive(c):
        rng = random.Random(9000 + c)
        try:
            start.wait(timeout=20)
            for i in range(per):
                api.schedule_one(_pod(f"emb-{c}-{i}"), top_k=16, rng=rng)
        except Exception as e:  # noqa: BLE001 — surfaced below
            with lock:
                errors.append(f"{c}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=drive, args=(c,))
               for c in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    pods, _rv = store.list("Pod")
    bound = [p for p in pods if p.name.startswith("emb-") and p.node_name]
    assert len(bound) == n_clients * per
    # store-truth exactly-once: one bound node per pod, ever
    first = {}
    for e in store._log:
        if e.kind == "Pod" and e.type == "MODIFIED" and e.obj.node_name \
                and e.obj.name.startswith("emb-"):
            prev = first.setdefault(e.obj.name, e.obj.node_name)
            assert prev == e.obj.node_name, e.obj.name
    # embedding kept the coalescer in the loop (not one eval per call)
    with api.backend._counters_lock:
        snap = dict(api.backend._counters)
    assert snap.get("coalesce_requests", 0) >= n_clients * per
    assert faulty.injected_failures + faulty.injected_timeouts > 0
    # capacity accrued in the embedded cache — allowing the landed-
    # timeout ambiguity its contract: a bind that landed at the store
    # but errored back may stay cache-unknown until the next bulk sync
    # delivers the spec (the store, not the cache, is truth)
    infos = api.backend.cache.node_infos()
    accrued = sum(len(i.pods) for i in infos.values())
    assert 0 < accrued <= n_clients * per
    assert accrued >= n_clients * per - faulty.injected_timeouts \
        - faulty.injected_failures


def test_service_core_is_shared_with_http_transport():
    """The HTTP extender serves THROUGH the same VerdictService class the
    embedding exposes — the refactor's point: no transport owns a
    semantic."""
    from kubernetes_tpu.server.extender import (
        ExtenderHTTPServer,
        TPUExtenderBackend,
    )
    b = TPUExtenderBackend()
    b.sync_nodes(hollow_nodes(4))
    srv = ExtenderHTTPServer(b)
    assert isinstance(srv.service, VerdictService)
    assert srv.service.backend is b
    out = srv.handle_filter({"Pod": {"metadata": {"name": "x"},
                                     "spec": {"containers": []}},
                             "Compact": True, "TopK": 2})
    assert out["AllPassed"] and out["PassedCount"] == 4
    assert len(out["TopScores"]) == 2
