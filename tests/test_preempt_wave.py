"""Wave-path preemption (ISSUE 14): priority bands + displacement on the
always-on pipeline.

Pinned here:
- FUZZ ORACLE: plan_wave_preemptions (device victim scan + exact
  verification over a copy-on-write overlay) produces byte-identical
  plans to the classic round's pick_preemption/PreemptionState loop —
  node choice ordering, the reprieve loop, infeasible nodes, multi-
  preemptor reservation effects, and the affinity-gated memo path.
- ATOMICITY: the store's evict+bind is all-or-nothing; injected eviction
  FAILURES roll back with zero residue on either side, injected
  landed-but-timed-out evictions heal through the watch stream with
  exactly-once binds audited against store truth.
- DISRUPTION BUDGET: sliding-window rate limit + per-band floors
  (FakeClock unit) and the e2e budget_deferred path.
- STARVATION GUARD: queue aging pops a long-waiting victim ahead of a
  sustained high-priority stream the moment capacity frees.
- CRASH-MID-PREEMPTION: a relisted replacement scheduler converges with
  one bound node per preemptor ever and every victim evicted at most
  once.
"""

import random

import numpy as np
import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.engine.preempt_wave import (
    DisruptionBudget,
    plan_wave_preemptions,
)
from kubernetes_tpu.engine.preemption import PreemptionState, pick_preemption
from kubernetes_tpu.engine.queue import SchedulingQueue
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.engine.scheduler_engine import SchedulingEngine
from kubernetes_tpu.models.hollow import load_cluster
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.testing.churn import (
    FaultyBindApi,
    audit_cache_vs_store,
    audit_store_transitions,
)
from kubernetes_tpu.utils import features
from kubernetes_tpu.utils.trace import COUNTERS
from tests.test_nodes import FakeClock

Mi = 1 << 20
Gi = 1 << 30


@pytest.fixture()
def pod_priority():
    features.DEFAULT_FEATURE_GATE.set("PodPriority", True)
    yield
    features.DEFAULT_FEATURE_GATE.reset()


def prio_pod(name, priority, cpu=200, mem=256 * Mi, node_name=""):
    p = make_pod(name, cpu=cpu, memory=mem, node_name=node_name)
    p.priority = priority
    return p


# --------------------------------------------------------- fuzz oracle


def _classic_plans(cache, preemptors):
    """The classic `_preempt_round` planning loop, side effects stripped:
    pick_preemption + PreemptionState over snapshot_infos clones with the
    nominated-pod reservation — the oracle the wave path must match."""
    from kubernetes_tpu.ops.oracle_ext import SchedulingContext
    from kubernetes_tpu.state.volumes import VolumeContext

    infos = cache.snapshot_infos()
    ctx = SchedulingContext(infos, [], hard_pod_affinity_weight=1,
                            volume_ctx=VolumeContext(), policy_algos=None)
    state = None
    out = []
    for pod in sorted(preemptors, key=lambda p: -p.priority):
        if pod.priority <= 0:
            break
        if state is None:
            state = PreemptionState(infos)
        plan = pick_preemption(pod, infos, ctx=ctx, state=state)
        if plan is None:
            continue
        for vic in plan.victims:
            info = infos.get(plan.node_name)
            if info is not None:
                info.remove_pod(vic)
        info = infos.get(plan.node_name)
        if info is not None:
            info.add_pod(pod)
        state.apply_plan(plan, pod)
        ctx.infos = infos
        ctx.invalidate()
        out.append((pod.key(), plan.node_name,
                    sorted(v.key() for v in plan.victims)))
    return out


def _fuzz_cluster(seed):
    rng = random.Random(seed)
    cache = SchedulerCache()
    n_nodes = rng.randint(4, 10)
    for i in range(n_nodes):
        cache.add_node(make_node(f"n{i:02d}",
                                 cpu=rng.choice([1000, 1600, 2400]),
                                 memory=rng.choice([4, 8]) * Gi,
                                 pods=rng.choice([6, 10, 110])))
    k = 0
    for i in range(n_nodes):
        for _ in range(rng.randint(0, 6)):
            p = prio_pod(f"b{k:03d}", rng.choice([0, 0, 1, 2, 5, 10]),
                         cpu=rng.choice([100, 200, 400, 700]),
                         mem=rng.choice([128, 256, 512]) * Mi,
                         node_name=f"n{i:02d}")
            cache.add_pod(p)
            k += 1
    pre = []
    for j in range(rng.randint(1, 5)):
        pre.append(prio_pod(
            f"pre{j}", rng.choice([1, 3, 5, 8, 20]),
            cpu=rng.choice([300, 600, 900, 1500, 50_000]),
            mem=rng.choice([256, 512, 1024]) * Mi))
    return cache, pre


def test_fuzz_wave_plans_equal_classic():
    """Node choice ordering, reprieve loop, infeasible nodes, and
    multi-preemptor reservation effects — wave == classic, many seeds."""
    mismatches = []
    for seed in range(24):
        cache, pre = _fuzz_cluster(seed)
        engine = SchedulingEngine(cache)
        engine._refresh()
        wave = [(pl.pod.key(), pl.node_name,
                 sorted(v.key() for v in pl.victims))
                for pl in plan_wave_preemptions(engine, pre)]
        classic = _classic_plans(cache, pre)
        if wave != classic:
            mismatches.append((seed, wave, classic))
    assert not mismatches, mismatches[:2]


def test_fuzz_equal_with_affinity_residents():
    """Affinity-carrying residents couple nodes, which gates the
    same-class verification memo OFF — plans must still equal classic."""
    from kubernetes_tpu.api.types import (
        Affinity,
        LabelSelector,
        PodAffinity,
        PodAffinityTerm,
    )
    for seed in (3, 7, 11):
        cache, pre = _fuzz_cluster(seed)
        aff = Affinity(pod_anti_affinity=PodAffinity(
            required_terms=[PodAffinityTerm(
                label_selector=LabelSelector(match_labels={"app": "x"}),
                namespaces=[], topology_key="kubernetes.io/hostname")]))
        carrier = prio_pod(f"carrier-{seed}", 0, cpu=100,
                           node_name="n00")
        carrier.labels = {"app": "x"}
        carrier.affinity = aff
        cache.add_pod(carrier)
        engine = SchedulingEngine(cache)
        engine._refresh()
        wave = [(pl.pod.key(), pl.node_name,
                 sorted(v.key() for v in pl.victims))
                for pl in plan_wave_preemptions(engine, pre)]
        assert wave == _classic_plans(cache, pre), seed


def test_band_overflow_falls_back_to_host_prefilter():
    """More distinct priorities than band columns: the device scan bows
    out, the host pre-filter serves the round, plans still == classic."""
    cache = SchedulerCache()
    cache.add_node(make_node("n00", cpu=2000, memory=8 * Gi))
    for j in range(20):  # 20 distinct priorities > PRIO_BANDS (16)
        cache.add_pod(prio_pod(f"b{j}", j, cpu=90, node_name="n00"))
    engine = SchedulingEngine(cache)
    engine._refresh()
    assert engine.snapshot.prio_band_overflow
    assert engine.preempt_scan([prio_pod("pre", 50, cpu=500)]) is None
    c0 = COUNTERS.snapshot().get("engine.preempt_scan_host_fallback",
                                 (0, 0))[0]
    pre = [prio_pod("pre", 50, cpu=500)]
    wave = [(pl.pod.key(), pl.node_name,
             sorted(v.key() for v in pl.victims))
            for pl in plan_wave_preemptions(engine, pre)]
    assert wave == _classic_plans(cache, pre)
    assert COUNTERS.snapshot()["engine.preempt_scan_host_fallback"][0] \
        == c0 + 1


# -------------------------------------------- snapshot band consistency


def test_band_columns_incremental_equals_rebuild():
    """The raw-delta band fold (apply_assume_delta prio_rows) must agree
    with a from-scratch rebuild — compared as priority -> per-node sums
    (band COLUMN order is first-seen and may differ)."""
    api = ApiServerLite()
    nodes = [make_node(f"n{i:02d}", cpu=4000, memory=16 * Gi, pods=110)
             for i in range(4)]
    load_cluster(api, nodes, [])
    s = Scheduler(api, record_events=False)
    s.start()
    for i in range(40):
        api.create("Pod", prio_pod(f"p{i:02d}", [0, 100, 1000][i % 3]))
    loop = s.pipeline(chunk=16)
    while True:
        st = loop.step()
        if st["popped"] == 0 and loop.idle and s.sync() == 0 \
                and s.queue.ready_count() == 0:
            break
    loop.close()
    snap = s.engine.snapshot

    def by_prio(sn):
        return {prio: (sn.band_cpu[:, b].copy(), sn.band_mem[:, b].copy(),
                       sn.band_count[:, b].copy())
                for prio, b in sn.prio_bands.items()}
    live = by_prio(snap)
    from kubernetes_tpu.state.snapshot import ClusterSnapshot
    fresh = ClusterSnapshot()
    fresh.refresh(s.cache.node_infos())
    ref = by_prio(fresh)
    assert set(live) == set(ref)
    for prio in ref:
        for a, b in zip(live[prio], ref[prio]):
            assert np.array_equal(a, b), prio


# ------------------------------------------------- atomic commit paths


def _full_cluster(n_nodes=2, slots=4, evict_fail=0.0, evict_timeout=0.0,
                  clock=None):
    """A cluster preloaded FULL of bound low-priority pods, wrapped in
    the eviction-fault proxy, plus a streaming scheduler."""
    api = ApiServerLite()
    nodes = [make_node(f"n{i:02d}", cpu=slots * 200, memory=16 * Gi,
                       pods=slots) for i in range(n_nodes)]
    pods = []
    k = 0
    for i in range(n_nodes):
        for _ in range(slots):
            pods.append(prio_pod(f"low-{k:02d}", 0,
                                 node_name=f"n{i:02d}"))
            k += 1
    load_cluster(api, nodes, pods)
    fapi = FaultyBindApi(api, evict_fail_rate=evict_fail,
                         evict_timeout_rate=evict_timeout)
    kw = {"record_events": False}
    if clock is not None:
        kw["now"] = clock
    s = Scheduler(fapi, **kw)
    s.start()
    return api, fapi, s


def test_preempt_commit_binds_preemptor_and_requeues_victims(pod_priority):
    api, fapi, s = _full_cluster()
    loop = s.stream(budget_s=30.0, min_quantum=16, max_quantum=16)
    api.create("Pod", prio_pod("hi", 1000))
    total = {}
    for _ in range(6):
        for k, v in loop.step().items():
            total[k] = total.get(k, 0) + v
        if total.get("preemptions", 0):
            break
    assert total.get("preemptions", 0) == 1, total
    assert total.get("victims_evicted", 0) == 1
    hi = api.get("Pod", "default", "hi")
    assert hi.node_name  # bound atomically with the eviction
    unbound = [p for p in api.list("Pod")[0]
               if not p.node_name and p.name != "hi"]
    assert len(unbound) == 1  # exactly one victim displaced
    # the victim re-entered the pending pool as an ordinary arrival (a
    # few steps in it has been retried against the full cluster and
    # parked on backoff — still pending, never lost)
    for _ in range(3):
        loop.step()
    loop.flush()
    assert unbound[0].key() in s.queue._keys
    assert not audit_cache_vs_store(s, api)
    loop.close()


def test_injected_evict_failure_rolls_back_zero_residue(pod_priority):
    clock = FakeClock()
    api, fapi, s = _full_cluster(evict_fail=1.0, clock=clock)
    loop = s.stream(budget_s=30.0, min_quantum=16, max_quantum=16)
    loop.degrade_window = 99  # keep the wave path under the fault storm
    api.create("Pod", prio_pod("hi", 1000))
    total = {}
    for _ in range(4):
        for k, v in loop.step().items():
            total[k] = total.get(k, 0) + v
        clock.t += 3.0  # the preemptor's backoff elapses between steps
    assert total.get("preempt_rollbacks", 0) >= 1, total
    assert total.get("preemptions", 0) == 0
    # ZERO residue: store untouched, nothing assumed, preemptor pending
    assert not api.get("Pod", "default", "hi").node_name
    assert all(p.node_name for p in api.list("Pod")[0]
               if p.name != "hi")
    assert not s.cache.is_assumed("default/hi")
    assert "default/hi" in s.queue._keys
    assert not audit_cache_vs_store(s, api)
    # faults healed: the SAME pending preemptor commits cleanly
    fapi.evict_fail_rate = 0.0
    clock.t += 3.0
    for _ in range(4):
        for k, v in loop.step().items():
            total[k] = total.get(k, 0) + v
        clock.t += 3.0
        if total.get("preemptions", 0):
            break
    assert total.get("preemptions", 0) == 1, total
    assert api.get("Pod", "default", "hi").node_name
    tr = audit_store_transitions(api)
    assert tr["binds"]["default/hi"] == 1
    assert all(c == 1 for k, c in tr["evicts"].items()), tr["evicts"]
    loop.close()


def test_landed_timeout_heals_exactly_once(pod_priority):
    """The at-most-once ambiguity on the victim-delete seam: the commit
    LANDS but errors — the scheduler rolls back, the watch stream heals,
    and the store shows exactly one bind ever for the preemptor."""
    clock = FakeClock()
    api, fapi, s = _full_cluster(evict_timeout=1.0, clock=clock)
    loop = s.stream(budget_s=30.0, min_quantum=16, max_quantum=16)
    loop.degrade_window = 99
    api.create("Pod", prio_pod("hi", 1000))
    total = {}
    for _ in range(6):
        for k, v in loop.step().items():
            total[k] = total.get(k, 0) + v
        clock.t += 3.0
        if api.get("Pod", "default", "hi").node_name \
                and "default/hi" not in s.queue._keys:
            break
    assert total.get("preempt_rollbacks", 0) >= 1, total
    hi = api.get("Pod", "default", "hi")
    assert hi.node_name  # the "failed" commit had landed
    # healed through sync: confirmed bound, out of the queue, cache truth
    assert "default/hi" not in s.queue._keys
    assert not s.cache.is_assumed("default/hi")
    tr = audit_store_transitions(api)
    assert tr["binds"]["default/hi"] == 1  # never double-bound
    assert all(c == 1 for c in tr["evicts"].values()), tr["evicts"]
    assert not audit_cache_vs_store(s, api)
    loop.close()


def test_crash_mid_preemption_relist_audit(pod_priority):
    """Crash after a landed-but-unacknowledged commit: a replacement
    scheduler relists and converges — one bound node per preemptor ever,
    every victim evicted at most once, no ghost capacity."""
    clock = FakeClock()
    api, fapi, s = _full_cluster(evict_timeout=1.0, clock=clock)
    loop = s.stream(budget_s=30.0, min_quantum=16, max_quantum=16)
    loop.degrade_window = 99
    api.create("Pod", prio_pod("hi", 1000))
    total = {}
    for _ in range(3):
        for k, v in loop.step().items():
            total[k] = total.get(k, 0) + v
        clock.t += 3.0
        if total.get("preempt_rollbacks", 0):
            break
    assert total.get("preempt_rollbacks", 0) >= 1
    # CRASH: abandon the first scheduler before any watch healing
    s2 = Scheduler(fapi, record_events=False, now=clock)
    s2.start()
    loop2 = s2.stream(budget_s=30.0, min_quantum=16, max_quantum=16)
    loop2.degrade_window = 99
    for _ in range(4):
        loop2.step()
        clock.t += 3.0
    tr = audit_store_transitions(api)
    assert tr["binds"].get("default/hi", 0) == 1
    assert all(c == 1 for c in tr["evicts"].values()), tr["evicts"]
    assert not audit_cache_vs_store(s2, api)
    loop2.close()


# ------------------------------------------------- disruption budgets


def test_disruption_budget_sliding_window_fakeclock():
    clock = FakeClock()
    b = DisruptionBudget(max_evictions_per_min=3, now=clock)
    vics = [prio_pod(f"v{i}", 0) for i in range(2)]
    assert b.admit(vics)
    assert b.admit([vics[0]])
    assert not b.admit([vics[1]])  # 3 consumed, window full
    assert b.window_evictions() == 3
    clock.t += 61.0
    assert b.admit(vics)  # the window slid
    assert b.window_evictions() == 2


def test_disruption_budget_band_floor():
    b = DisruptionBudget(max_evictions_per_min=100, band_floor={0: 5})
    vics = [prio_pod(f"v{i}", 0) for i in range(3)]
    assert not b.admit(vics, band_counts={0: 7})  # 7 - 3 < floor 5
    assert b.admit(vics, band_counts={0: 9})      # 9 - 3 >= 5
    assert b.admit([prio_pod("x", 100)], band_counts={0: 5, 100: 99})


def test_budget_deferred_blocks_eviction_e2e(pod_priority):
    api, fapi, s = _full_cluster()
    s.disruption_budget = DisruptionBudget(max_evictions_per_min=0)
    loop = s.stream(budget_s=30.0, min_quantum=16, max_quantum=16)
    api.create("Pod", prio_pod("hi", 1000))
    total = {}
    for _ in range(4):
        for k, v in loop.step().items():
            total[k] = total.get(k, 0) + v
    assert total.get("budget_deferred", 0) >= 1, total
    assert total.get("preemptions", 0) == 0
    assert all(p.node_name for p in api.list("Pod")[0]
               if p.name != "hi")  # nothing was evicted
    assert not api.get("Pod", "default", "hi").node_name
    loop.close()


# ------------------------------------------------- starvation guard


def test_queue_aging_promotes_starved_victim(pod_priority):
    clock = FakeClock()
    q = SchedulingQueue(now=clock)
    q.aging_threshold_s = 5.0
    q.add(prio_pod("victim", 0))
    clock.t += 6.0  # past the aging threshold
    q.add(prio_pod("fresh-hi", 1000))
    out = q.pop_batch()
    assert [p.name for p in out] == ["victim", "fresh-hi"]
    # un-aged: priority order holds
    q.add(prio_pod("lo2", 0))
    q.add(prio_pod("hi2", 1000))
    assert [p.name for p in q.pop_batch()] == ["hi2", "lo2"]


def test_no_permanent_starvation_under_high_band_stream(pod_priority):
    """A preempted low-priority victim must rebind once capacity frees,
    even while high-priority pods keep arriving: with a 1-pod admission
    quantum only the queue HEAD gets tried each step, so without aging
    the victim would sit behind the ever-growing high-band queue
    forever. The offered high pods are infeasible (bigger than the
    node), so the freed capacity is genuinely the victim's to take."""
    clock = FakeClock()
    api, fapi, s = _full_cluster(n_nodes=1, slots=3, clock=clock)
    s.queue.aging_threshold_s = 5.0
    loop = s.stream(budget_s=30.0, min_quantum=1, max_quantum=1)
    api.create("Pod", prio_pod("hi-0", 1000))
    for _ in range(4):
        loop.step()
        clock.t += 3.0
    victim = next(p for p in api.list("Pod")[0] if not p.node_name)
    assert victim.priority == 0  # a low-band pod was displaced
    # sustained high-priority offered stream, each pod larger than the
    # whole node: unschedulable forever, but they keep outranking the
    # victim at the head of a priority-ordered queue
    hi_seq = [1]

    def offer_hi():
        api.create("Pod", prio_pod(f"hi-{hi_seq[0]}", 1000, cpu=700))
        hi_seq[0] += 1

    for _ in range(3):
        offer_hi()
        loop.step()
        clock.t += 0.4
    assert not api.get("Pod", victim.namespace, victim.name).node_name
    clock.t += 10.0  # victim ages past the threshold
    # capacity frees: the bound high pod leaves — one 200m slot opens
    api.delete("Pod", "default", "hi-0")
    for _ in range(10):
        offer_hi()
        loop.step()
        clock.t += 0.4
        if api.get("Pod", victim.namespace, victim.name).node_name:
            break
    assert api.get("Pod", victim.namespace, victim.name).node_name, \
        "aged victim never rebound — permanent starvation"
    loop.close()


# --------------------------------------- wave path stays on the waves


def test_preemption_rides_wave_path_without_flush(pod_priority):
    """Preemption must not drag the stream through the classic round:
    victims are UNBOUND (not deleted), the scan dispatches on device,
    and the loop reports the commit through wave-path stats."""
    api, fapi, s = _full_cluster(n_nodes=3, slots=4)
    loop = s.stream(budget_s=30.0, min_quantum=16, max_quantum=16)
    c0 = {k: v[0] for k, v in COUNTERS.snapshot().items()}
    api.create("Pod", prio_pod("hi", 1000))
    total = {}
    for _ in range(6):
        for k, v in loop.step().items():
            total[k] = total.get(k, 0) + v
        if total.get("preemptions", 0):
            break
    c1 = {k: v[0] for k, v in COUNTERS.snapshot().items()}
    assert total.get("preemptions", 0) == 1
    assert c1.get("engine.preempt_scan_dispatch", 0) \
        > c0.get("engine.preempt_scan_dispatch", 0)
    assert c1.get("engine.preempt_commits", 0) \
        == c0.get("engine.preempt_commits", 0) + 1
    # victims are unbound, never deleted: the store still has every pod
    assert len(api.list("Pod")[0]) == 3 * 4 + 1
    assert not loop.degraded
    loop.close()


def test_sustained_preempt_rollbacks_trip_degraded_mode(pod_priority):
    """The new failure class feeds the existing hysteresis: a store that
    keeps refusing atomic commits drops the loop to the classic round."""
    clock = FakeClock()
    api, fapi, s = _full_cluster(evict_fail=1.0, clock=clock)
    loop = s.stream(budget_s=30.0, min_quantum=16, max_quantum=16)
    loop.degrade_window = 3
    api.create("Pod", prio_pod("hi", 1000))
    for _ in range(8):
        loop.step()
        clock.t += 3.0
        if loop.degraded:
            break
    assert loop.degraded
    loop.close()


# --------------------------------------------------- observability


def test_preempt_counters_and_recorder_lane(pod_priority):
    from kubernetes_tpu.observability.perfetto import build_chrome_trace
    from kubernetes_tpu.observability.recorder import RECORDER
    from kubernetes_tpu.observability.registry import TelemetryRegistry

    api, fapi, s = _full_cluster()
    loop = s.stream(budget_s=30.0, min_quantum=16, max_quantum=16)
    RECORDER.clear()
    RECORDER.enable()
    try:
        api.create("Pod", prio_pod("hi", 1000))
        total = {}
        for _ in range(6):
            for k, v in loop.step().items():
                total[k] = total.get(k, 0) + v
            if total.get("preemptions", 0):
                break
    finally:
        RECORDER.disable()
    assert total.get("preemptions", 0) == 1
    events = RECORDER.snapshot()
    kinds = {e["kind"] for e in events}
    assert {"preempt_propose", "preempt_commit",
            "victim_requeue"} <= kinds, kinds
    trace = build_chrome_trace(events)
    lanes = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("name") == "thread_name"}
    assert "preempt" in lanes
    names = {e["name"] for e in trace["traceEvents"]}
    assert any(n.startswith("victim-select") for n in names), names
    assert any(n.startswith("preempt-commit") for n in names), names
    # counters land in the unified registry namespace — the identical
    # snapshot every transport (/debug/vars, STATS verb, debug_snapshot)
    # serves; transport parity itself is pinned by test_observability
    snap = TelemetryRegistry().snapshot()
    assert snap.get("span.engine.preempt_commits.count", 0) >= 1
    assert "span.engine.victims_evicted.count" in snap
    # ... and through a live transport surface: VerdictService's
    # debug_snapshot (the embedded twin of /debug/vars and STATS) serves
    # the same registry fold, so the preemption counters are visible on
    # every introspection transport
    from kubernetes_tpu.server.embedded import VerdictService
    from kubernetes_tpu.server.extender import TPUExtenderBackend
    dv = VerdictService(TPUExtenderBackend()).debug_snapshot()["vars"]
    assert dv.get("span.engine.preempt_commits.count", 0) >= 1
    assert "span.engine.preempt_scan_dispatch.count" in dv
    loop.close()
