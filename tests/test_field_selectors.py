"""Field selectors (apimachinery/pkg/fields + registry GetAttrs).

Pinned reference behaviors:
- parse: k=v / k==v / k!=v comma-joined, ANDed (fields/selector.go);
- per-kind selectable sets (pod/strategy.go PodToSelectableFields:
  metadata.*, spec.nodeName, spec.schedulerName, spec.restartPolicy,
  status.phase);
- unsupported field label is an error, not an empty result;
- served through list on the apiserver, REST (?fieldSelector=), and
  ktctl --field-selector.
"""

import io

import pytest

from kubernetes_tpu.api.fields import (
    FieldSelectorError,
    filter_objects,
    parse_field_selector,
)
from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.api.workloads import Namespace
from kubernetes_tpu.cli.ktctl import Ktctl
from kubernetes_tpu.server.apiserver import ApiServer, Invalid

Mi = 1 << 20


def test_parse_forms_and_errors():
    sel = parse_field_selector("spec.nodeName=n1,status.phase!=Failed")
    assert sel.requirements == (("spec.nodeName", "=", "n1"),
                                ("status.phase", "!=", "Failed"))
    assert parse_field_selector("a==b").requirements == (("a", "=", "b"),)
    assert parse_field_selector("").empty
    for bad in ("nodeName", "=v", ",,=,"):
        with pytest.raises(FieldSelectorError):
            parse_field_selector(bad)


def make_server():
    api = ApiServer()
    api.store.create("Namespace", Namespace("default"))
    for i, phase in enumerate(("Running", "Pending", "Running")):
        p = make_pod(f"p{i}", cpu=10, memory=Mi)
        p.node_name = f"n{i % 2}"
        p.phase = phase
        api.store.create("Pod", p)
    api.store.create("Node", make_node("n0", cpu=1000, memory=1 << 31))
    n1 = make_node("n1", cpu=1000, memory=1 << 31)
    n1.unschedulable = True
    api.store.create("Node", n1)
    return api


def test_list_with_field_selector():
    api = make_server()
    objs, _ = api.list("Pod", field_selector="spec.nodeName=n0")
    assert sorted(o.name for o in objs) == ["p0", "p2"]
    objs, _ = api.list("Pod",
                       field_selector="spec.nodeName=n0,"
                                      "status.phase!=Pending")
    assert sorted(o.name for o in objs) == ["p0", "p2"]
    objs, _ = api.list("Pod", field_selector="status.phase=Pending")
    assert [o.name for o in objs] == ["p1"]
    objs, _ = api.list("Node", field_selector="spec.unschedulable=true")
    assert [o.name for o in objs] == ["n1"]
    objs, _ = api.list("Pod", field_selector="metadata.name=p1")
    assert [o.name for o in objs] == ["p1"]


def test_unsupported_field_label_is_invalid():
    api = make_server()
    with pytest.raises(Invalid, match="field label not supported"):
        api.list("Pod", field_selector="spec.bogus=x")


def test_generic_kind_supports_metadata_only():
    api = make_server()
    objs, _ = api.list("Namespace", field_selector="metadata.name=default")
    assert [o.name for o in objs] == ["default"]
    with pytest.raises(Invalid):
        api.list("Namespace", field_selector="spec.finalizers=x")


def test_field_selector_over_rest_and_cli():
    from kubernetes_tpu.cli.rest_client import RestClient
    from kubernetes_tpu.server.rest_http import RestServer

    api = make_server()
    srv = RestServer(api)
    srv.start()
    try:
        client = RestClient(f"http://127.0.0.1:{srv.port}")
        objs, _ = client.list("Pod", field_selector="spec.nodeName=n1")
        assert [o.name for o in objs] == ["p1"]
        from kubernetes_tpu.cli.rest_client import HttpError
        with pytest.raises(HttpError):
            client.list("Pod", field_selector="nope=1")
    finally:
        srv.stop()
    out = io.StringIO()
    kt = Ktctl(api, out=out)
    assert kt.run(["get", "pods", "--field-selector",
                   "status.phase=Running", "-o", "name"]) == 0
    assert sorted(out.getvalue().split()) == ["pods/p0", "pods/p2"]
    # bad selector: clean CLI error
    assert kt.run(["get", "pods", "--field-selector", "bogus"]) == 1


def test_filter_objects_direct():
    pods = []
    for i in range(4):
        p = make_pod(f"p{i}", cpu=1, memory=Mi)
        p.node_name = "nA" if i % 2 == 0 else "nB"
        pods.append(p)
    sel = parse_field_selector("spec.nodeName=nA")
    assert [p.name for p in filter_objects("Pod", pods, sel)] \
        == ["p0", "p2"]


def test_invalid_selector_rejected_even_on_empty_cluster():
    """Finding regression: validation is unconditional, not per matched
    object — an empty cluster must not make a bad selector succeed."""
    api = ApiServer()
    api.store.create("Namespace", Namespace("default"))
    with pytest.raises(Invalid, match="field label not supported"):
        api.list("Pod", field_selector="spec.bogus=x")
    # short-circuit case: first requirement matches nothing, second is
    # invalid — still an error
    api2 = make_server()
    with pytest.raises(Invalid):
        api2.list("Pod",
                  field_selector="status.phase=NoSuch,spec.bogus=x")


def test_named_get_with_selector_is_rejected():
    api = make_server()
    out = io.StringIO()
    kt = Ktctl(api, out=out)
    assert kt.run(["get", "pods", "p0", "--field-selector",
                   "spec.nodeName=n1"]) == 1
    assert "cannot be combined" in out.getvalue()
