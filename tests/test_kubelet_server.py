"""The kubelet API server (pkg/kubelet/server) + ktctl logs/exec.

Pinned: the URL layout and status semantics of the reference kubelet's
read-only/debugging handlers — /healthz, /pods, /stats/summary,
/containerLogs/<ns>/<pod> (tailLines honored, 404 for a pod not running
on this node), POST /exec (canned hollow-runtime outputs, 501 for
commands the runtime has no handler for) — and the kubectl verbs that
consume them end-to-end in both in-process and HTTP modes.
"""

import io
import json
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.cli.ktctl import Ktctl
from kubernetes_tpu.nodes.kubelet import HollowKubelet
from kubernetes_tpu.nodes.kubelet_server import KubeletServer
from kubernetes_tpu.server.apiserver_lite import ApiServerLite

Mi = 1 << 20
Gi = 1 << 30


def rig():
    api = ApiServerLite()
    node = make_node("n1", cpu=4000, memory=8 * Gi)
    api.create("Node", node)
    kubelet = HollowKubelet(api, node)
    pod = make_pod("web", cpu=50, memory=Mi)
    pod.node_name = "n1"
    pod.annotations["bench/log-lines"] = "line1\nline2\nline3"
    pod.annotations["bench/exec-cat /etc/hostname"] = "web-host"
    api.create("Pod", pod)
    kubelet.handle_pod(pod)
    kubelet.workers.drain()
    assert pod.key() in kubelet._admitted
    return api, kubelet, pod


def test_kubelet_server_endpoints():
    api, kubelet, pod = rig()
    srv = KubeletServer(kubelet)
    srv.start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        with urllib.request.urlopen(base + "/healthz") as r:
            assert r.read() == b"ok"
        with urllib.request.urlopen(base + "/pods") as r:
            items = json.loads(r.read())["items"]
            assert [(i["name"], i["namespace"]) for i in items] \
                == [("web", "default")]
        with urllib.request.urlopen(base + "/stats/summary") as r:
            stats = json.loads(r.read())
            assert stats["node"]["cpu"]["usageMilli"] == 50
            assert stats["pods"] == 1
        with urllib.request.urlopen(
                base + "/containerLogs/default/web") as r:
            assert r.read().decode() == "line1\nline2\nline3"
        with urllib.request.urlopen(
                base + "/containerLogs/default/web?tailLines=1") as r:
            assert r.read().decode() == "line3"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/containerLogs/default/ghost")
        assert ei.value.code == 404
        req = urllib.request.Request(
            base + "/exec/default/web?command=cat%20/etc/hostname",
            data=b"", method="POST")
        with urllib.request.urlopen(req) as r:
            assert r.read().decode() == "web-host"
        req = urllib.request.Request(
            base + "/exec/default/web?command=rm%20-rf",
            data=b"", method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 501
    finally:
        srv.stop()


def test_ktctl_logs_and_exec_in_process():
    api, kubelet, pod = rig()
    out = io.StringIO()
    kt = Ktctl(api, out=out, kubelets={"n1": kubelet})
    assert kt.run(["logs", "web"]) == 0
    assert "line2" in out.getvalue()
    out.truncate(0), out.seek(0)
    assert kt.run(["logs", "web", "--tail", "1"]) == 0
    assert out.getvalue().strip() == "line3"
    out.truncate(0), out.seek(0)
    assert kt.run(["exec", "web", "--", "cat", "/etc/hostname"]) == 0
    assert out.getvalue().strip() == "web-host"
    # unknown command and unknown node fail cleanly
    assert kt.run(["exec", "web", "--", "reboot"]) == 1
    p2 = make_pod("pending", cpu=10, memory=Mi)
    api.create("Pod", p2)
    assert kt.run(["logs", "pending"]) == 1


def test_ktctl_logs_over_http():
    api, kubelet, pod = rig()
    srv = KubeletServer(kubelet)
    srv.start()
    try:
        out = io.StringIO()
        kt = Ktctl(api, out=out,
                   kubelets={"n1": f"http://127.0.0.1:{srv.port}"})
        assert kt.run(["logs", "web", "--tail", "2"]) == 0
        assert out.getvalue().strip() == "line2\nline3"
        out.truncate(0), out.seek(0)
        assert kt.run(["exec", "web", "--", "cat", "/etc/hostname"]) == 0
        assert out.getvalue().strip() == "web-host"
    finally:
        srv.stop()


def test_tail_zero_and_bad_tail():
    """kubectl --tail=0 prints nothing; a non-numeric tail is a 400, not
    a traceback (review-finding regression)."""
    api, kubelet, pod = rig()
    assert kubelet.serve_logs("default", "web", tail="0") == ""
    out = io.StringIO()
    kt = Ktctl(api, out=out, kubelets={"n1": kubelet})
    assert kt.run(["logs", "web", "--tail", "0"]) == 0
    assert out.getvalue().strip() == ""
    assert kt.run(["logs", "web", "--tail", "xyz"]) == 1
    assert kt.run(["logs", "no-such-pod"]) == 1  # clean error, rc=1
    srv = KubeletServer(kubelet)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(
                base + "/containerLogs/default/web?tailLines=0") as r:
            assert r.read() == b""
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                base + "/containerLogs/default/web?tailLines=abc")
        assert ei.value.code == 400
    finally:
        srv.stop()


def test_concurrent_pods_requests_during_churn():
    """/pods iterates a snapshot, never the live dict — concurrent reads
    during admit/evict churn must not 500 (review-finding regression)."""
    import threading

    api, kubelet, pod = rig()
    srv = KubeletServer(kubelet)
    srv.start()
    base = f"http://127.0.0.1:{srv.port}"
    errors = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            try:
                with urllib.request.urlopen(base + "/pods", timeout=5) as r:
                    json.loads(r.read())
                with urllib.request.urlopen(base + "/stats/summary",
                                            timeout=5) as r:
                    json.loads(r.read())
            except Exception as e:  # any failure is a real defect
                errors.append(e)
                return

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for i in range(60):
            p = make_pod(f"churn-{i}", cpu=1, memory=Mi)
            p.node_name = "n1"
            api.create("Pod", p)
            kubelet.handle_pod(p)
            kubelet.workers.drain()
            kubelet.forget_pod(p)
            kubelet.workers.drain()
    finally:
        stop.set()
        for t in threads:
            t.join()
        srv.stop()
    assert not errors, errors[:1]
