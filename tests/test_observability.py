"""Flight recorder + unified telemetry (ISSUE 13).

Pins the contracts the observability tentpole rests on:

- the recorder ring is bounded (overwrites oldest, counts drops), typed,
  ordered, and an EXACT no-op when disabled — a drain with the recorder
  off emits zero events;
- a pipelined drain records dispatch/harvest/bind-flush per wave with
  matching wave ids, and the Perfetto exporter renders host/device/fence
  lanes with the host-tail-under-device-eval overlap VISIBLE (the r14
  attribution as data, not prose);
- the unified registry folds spans + SchedulerMetrics + service counters
  + gauges into one labeled namespace with a single Prometheus render
  (legacy metric names intact);
- TRANSPORT PARITY: HTTP /debug/vars, the binary STATS verb and the
  embedded debug_snapshot serve IDENTICAL registry contents, and
  mid-storm scrapes never tear (the r12 dedicated-lock audit pattern);
- Histogram growth is bounded by the weighted reservoir while
  percentile() stays exact below the bound and rank-accurate on a known
  distribution above it;
- a budget-breaching streaming step dumps its Trace step breakdown
  (log_if_long at the budget threshold), fake-clock pinned.
"""

from __future__ import annotations

import json
import threading

import numpy as np
import pytest

from kubernetes_tpu.api.types import make_pod
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes, load_cluster
from kubernetes_tpu.observability import perfetto
from kubernetes_tpu.observability import recorder as rec
from kubernetes_tpu.observability.recorder import RECORDER, FlightRecorder
from kubernetes_tpu.observability.registry import TelemetryRegistry
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.utils.metrics import Histogram


@pytest.fixture
def flight():
    """The process-wide ring, armed for one test and ALWAYS disarmed
    after — global state must never leak across tests."""
    RECORDER.clear()
    RECORDER.enable()
    try:
        yield RECORDER
    finally:
        RECORDER.disable()
        RECORDER.clear()


def mk_sched(n_nodes=64, n_pods=0):
    api = ApiServerLite()
    load_cluster(api, hollow_nodes(n_nodes),
                 PROFILES["density"](n_pods) if n_pods else [])
    s = Scheduler(api, record_events=False)
    s.start()
    return api, s


# ---------------------------------------------------------------- the ring


def test_ring_bounds_order_and_drops():
    r = FlightRecorder(capacity=8)
    r.enable()
    for i in range(20):
        r.record(rec.DISPATCH, wave=i, t0=float(i), a=i)
    ev = r.snapshot()
    assert len(ev) == 8
    assert [e["wave"] for e in ev] == list(range(12, 20))  # oldest->newest
    assert r.stats()["events"] == 20
    assert r.stats()["dropped"] == 12
    tail = r.snapshot(last=3)
    assert [e["wave"] for e in tail] == [17, 18, 19]
    r.clear()
    assert r.snapshot() == [] and r.stats()["events"] == 0


def test_disabled_recorder_is_exact_noop():
    """Emit sites guard on .enabled — a full pipelined drain with the
    recorder off must leave the ring untouched."""
    assert not RECORDER.enabled
    before = RECORDER.stats()["events"]
    api, s = mk_sched(n_pods=300)
    s.run_until_drained(max_batch=128)
    assert RECORDER.stats()["events"] == before


def test_drain_records_typed_waves_with_matching_ids(flight):
    api, s = mk_sched(n_pods=500)
    totals = s.run_until_drained(max_batch=128)
    assert totals["bound"] == 500
    ev = flight.snapshot()
    by_kind = {}
    for e in ev:
        by_kind.setdefault(e["kind"], []).append(e)
    # one dispatch + one harvest + one bind-flush per wave, ids joined
    disp = {e["wave"] for e in by_kind["dispatch"]}
    harv = {e["wave"] for e in by_kind["harvest"]}
    flush = {e["wave"] for e in by_kind["bind_flush"]}
    assert disp and disp == harv == flush
    assert sum(e["a"] for e in by_kind["dispatch"]) == 500   # pods admitted
    assert sum(e["a"] for e in by_kind["bind_flush"]) == 500  # pods bound
    for e in ev:
        assert e["t"] > 0 and e["dur"] >= 0


# ------------------------------------------------------------ the exporter


def test_perfetto_export_lanes_and_overlap(flight, tmp_path):
    """The exported timeline carries distinct host/device/fence lanes and
    the pipelined overlap is VISIBLE: at least one wave's device-eval
    window contains the previous wave's bind-flush."""
    api, s = mk_sched(n_pods=800)
    s.run_until_drained(max_batch=128)
    ev = flight.snapshot()
    out = tmp_path / "trace.json"
    trace = perfetto.export_chrome_trace(ev, str(out))
    # the file is loadable chrome://tracing JSON (object form)
    loaded = json.loads(out.read_text())
    assert loaded["traceEvents"]
    lanes = {m["args"]["name"] for m in loaded["traceEvents"]
             if m.get("ph") == "M" and m["name"] == "thread_name"}
    # fastlane joined the fixed lane set in r19 (ISSUE 17)
    assert lanes == {"host", "device", "fence", "preempt", "fastlane"}
    tids = {"host": None, "device": None}
    for m in trace["traceEvents"]:
        if m.get("ph") == "M" and m["name"] == "thread_name" \
                and m["args"]["name"] in tids:
            tids[m["args"]["name"]] = m["tid"]
    spans = [m for m in trace["traceEvents"] if m.get("ph") == "X"]
    host = [m for m in spans if m["tid"] == tids["host"]]
    device = [m for m in spans if m["tid"] == tids["device"]]
    assert host and device
    # overlap: some host bind-flush lies inside a LATER wave's device span
    flushes = [m for m in host if m["name"].startswith("bind-flush")]
    overlapped = any(
        d["ts"] <= f["ts"] and f["ts"] + f["dur"] <= d["ts"] + d["dur"]
        and d["name"] != f"device-eval {f['name'].split()[-1]}"
        for f in flushes for d in device)
    assert overlapped, (flushes, device)
    # and the quantitative form agrees
    assert perfetto.overlap_seconds(ev) > 0


def test_perfetto_fence_lane_markers(flight, tmp_path):
    """Fence-requeue / degraded / churn events render as instants on the
    fence lane."""
    flight.record(rec.FENCE_REQUEUE, wave=3, a=2, b=1)
    flight.record(rec.DEGRADED, a=1, b=3)
    flight.record(rec.CHURN_OP, a=rec.CHURN_OP_CODES["kill"], b=1)
    trace = perfetto.build_chrome_trace(flight.snapshot())
    instants = [m for m in trace["traceEvents"] if m.get("ph") == "i"]
    names = {m["name"] for m in instants}
    assert {"fence-requeue w3", "degraded-enter", "churn:kill"} <= names
    assert all(m["tid"] == perfetto.TID_FENCE for m in instants)


# ------------------------------------------------------------ the registry


def test_registry_folds_all_sources_one_namespace():
    from kubernetes_tpu.utils.metrics import SchedulerMetrics
    from kubernetes_tpu.utils.trace import COUNTERS

    reg = TelemetryRegistry()
    m = SchedulerMetrics()
    m.e2e_latency.observe(0.01)
    m.scheduled.inc(7)
    counters = {"binds": 3}
    reg.register_metrics("sched", m)
    reg.register_counters("svc", lambda: dict(counters),
                          prom_prefix="tpu_svc")
    reg.register_gauges("g", lambda: {"tpu_quantum": 512})
    COUNTERS.inc("obs.test_span")
    snap = reg.snapshot()
    assert snap["counter.svc.binds"] == 3
    assert snap["gauge.tpu_quantum"] == 512
    assert snap["counter.sched.scheduler_pods_scheduled_total"] == 7
    assert snap[
        "hist.sched.scheduler_e2e_scheduling_latency_seconds.count"] == 1
    assert snap["span.obs.test_span.count"] >= 1
    assert "recorder.events" in snap and "recorder.enabled" in snap
    text = reg.render_prometheus()
    assert "tpu_svc_binds_total 3" in text
    assert "# TYPE tpu_quantum gauge\ntpu_quantum 512" in text
    assert 'tpu_span_count_total{span="obs.test_span"}' in text
    assert "scheduler_pods_scheduled_total 7" in text
    assert "tpu_flight_recorder_events" in text
    # re-registering under the same key replaces, never accumulates
    reg.register_gauges("g", lambda: {"tpu_quantum": 1024})
    assert reg.snapshot()["gauge.tpu_quantum"] == 1024


def test_stream_gauges_registered_on_scheduler_registry():
    api, s = mk_sched(n_nodes=16)
    loop = s.stream(budget_s=0.25, min_quantum=256)
    snap = s.telemetry.snapshot()
    assert snap["gauge.stream_quantum"] == loop.quantum
    assert snap["gauge.stream_degraded"] == 0
    assert snap["gauge.stream_budget_ms"] == 250.0
    assert "gauge.stream_backlog" in snap
    # close() drops the dead loop's gauges (stale-introspection guard) —
    # unless a replacement loop already took the key over
    loop.close()
    assert "gauge.stream_quantum" not in s.telemetry.snapshot()
    loop2 = s.stream(budget_s=0.25)
    loop3 = s.stream(budget_s=0.5)
    loop2.close()  # superseded registration stays loop3's
    assert s.telemetry.snapshot()["gauge.stream_budget_ms"] == 500.0
    loop3.close()


def test_overlap_seconds_matches_pairwise_reference():
    """The O(n log n) union/prefix form must agree with the brute-force
    all-pairs intersection on a randomized event soup (and stay fast on
    a big ring — the full-ring export case)."""
    rng = np.random.default_rng(5)
    events = []
    t = 0.0
    for w in range(400):
        t += float(rng.uniform(0.001, 0.01))
        d_dur = float(rng.uniform(0.001, 0.02))
        events.append({"kind": "dispatch", "wave": w, "t": t,
                       "dur": d_dur, "a": 1, "b": 0})
        h0 = t + d_dur + float(rng.uniform(0.0, 0.01))
        b_dur = float(rng.uniform(0.001, 0.03))
        events.append({"kind": "harvest", "wave": w, "t": h0,
                       "dur": b_dur, "a": 1, "b": 0})
        events.append({"kind": "bind_flush", "wave": w,
                       "t": h0 + float(rng.uniform(-0.01, 0.01)),
                       "dur": float(rng.uniform(0.001, 0.02)),
                       "a": 1, "b": 0})

    def brute(evs):
        device, hostspans, dend = [], [], {}
        for e in evs:
            if e["kind"] == "dispatch":
                dend[e["wave"]] = e["t"] + e["dur"]
                hostspans.append((e["t"], e["t"] + e["dur"], e["wave"]))
            elif e["kind"] == "harvest":
                device.append((dend.get(e["wave"], e["t"]),
                               e["t"] + e["dur"], e["wave"]))
            elif e["kind"] == "bind_flush":
                hostspans.append((e["t"], e["t"] + e["dur"], e["wave"]))
        total = 0.0
        for h0, h1, hw in hostspans:
            cov = 0.0
            for d0, d1, dw in device:
                if dw == hw:
                    continue
                lo, hi = max(h0, d0), min(h1, d1)
                if hi > lo:
                    cov += hi - lo
            total += min(cov, h1 - h0)
        return total

    got = perfetto.overlap_seconds(events)
    ref = brute(events)
    # union-minus-own undercounts only where device windows of different
    # waves overlap each other (one batch owns the device at a time in
    # the real engine); on this soup windows DO overlap, so allow the
    # conservative side only
    assert got <= ref + 1e-9
    assert got >= 0.5 * ref  # and it is the same quantity, not garbage
    # non-overlapping device windows (the real engine's shape): exact
    seq = []
    t = 0.0
    for w in range(50):
        seq.append({"kind": "dispatch", "wave": w, "t": t, "dur": 0.002,
                    "a": 1, "b": 0})
        seq.append({"kind": "harvest", "wave": w, "t": t + 0.010,
                    "dur": 0.001, "a": 1, "b": 0})
        if w:
            seq.append({"kind": "bind_flush", "wave": w - 1,
                        "t": t + 0.004, "dur": 0.003, "a": 1, "b": 0})
        t += 0.012
    assert perfetto.overlap_seconds(seq) == pytest.approx(brute(seq))


# ------------------------------------------------------- transport parity


def _parity_rig(n_nodes=48):
    from kubernetes_tpu.server.asyncwire import AsyncBinaryServer
    from kubernetes_tpu.server.embedded import VerdictService
    from kubernetes_tpu.server.extender import (
        ExtenderHTTPServer,
        TPUExtenderBackend,
    )

    b = TPUExtenderBackend(coalesce_window_s=0.0005)
    b.sync_nodes(hollow_nodes(n_nodes))
    b.filter(make_pod("warm", cpu=100, memory=256 << 20), None, None)
    svc = VerdictService(b)
    http_srv = ExtenderHTTPServer(b)
    http_srv.start()
    bin_srv = AsyncBinaryServer(svc)
    bin_srv.start()
    return b, svc, http_srv, bin_srv


def _http_get(port, path):
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    try:
        conn.request("GET", path)
        return json.loads(conn.getresponse().read())
    finally:
        conn.close()


def test_transport_parity_identical_snapshots_mid_storm(flight):
    """The same registry snapshot through all three transports: identical
    counter names AND values once quiesced, torn-read-free while a
    filter/bind storm is concurrently mutating every source (the r12
    dedicated-lock audit, extended to the introspection path)."""
    from kubernetes_tpu.client.binarywire import BinaryWireClient

    b, svc, http_srv, bin_srv = _parity_rig()
    errors: list = []
    stop = threading.Event()

    def storm(i):
        try:
            for j in range(25):
                b.filter_verdict(make_pod(f"storm-{i}-{j}", cpu=100,
                                          memory=256 << 20))
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    def scraper():
        # mid-storm reads must never raise or tear: every fetch parses,
        # and the key SET is identical across transports at every pull
        c = BinaryWireClient("127.0.0.1", bin_srv.port).connect()
        try:
            while not stop.is_set():
                hv = _http_get(http_srv.port, "/debug/vars")
                bv = c.stats()["vars"]
                ev = svc.debug_snapshot()["vars"]
                for snap in (hv, bv, ev):
                    assert "gauge.tpu_extender_commit_gen" in snap
                    assert "recorder.events" in snap
        except Exception as e:  # noqa: BLE001
            errors.append(e)
        finally:
            c.close()

    threads = [threading.Thread(target=storm, args=(i,)) for i in range(6)]
    sc = threading.Thread(target=scraper)
    sc.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    stop.set()
    sc.join(timeout=60)
    assert not errors, errors
    # quiesced: the three transports serve IDENTICAL contents
    c = BinaryWireClient("127.0.0.1", bin_srv.port).connect()
    try:
        http_vars = _http_get(http_srv.port, "/debug/vars")
        bin_snap = c.stats(last=10)
        emb_snap = svc.debug_snapshot(last=10)
        assert http_vars == bin_snap["vars"] == emb_snap["vars"]
        assert bin_snap["trace"] == emb_snap["trace"]
        # the storm really moved the sources this snapshot folds
        assert http_vars["counter.extender.coalesce_requests"] >= 150
        http_trace = _http_get(http_srv.port, "/debug/trace?last=10")
        assert http_trace == bin_snap["trace"]
    finally:
        c.close()
        bin_srv.stop()
        http_srv.stop()


def test_debug_trace_last_bounds_the_tail(flight):
    b, svc, http_srv, bin_srv = _parity_rig(n_nodes=8)
    try:
        for i in range(12):
            flight.record(rec.DISPATCH, wave=i, a=1)
        tail = _http_get(http_srv.port, "/debug/trace?last=4")
        assert [e["wave"] for e in tail] == [8, 9, 10, 11]
        # absent param -> bounded default tail (256 covers these 12)
        full = _http_get(http_srv.port, "/debug/trace")
        assert len(full) == 12
        # literal last=0 means NO trace on EVERY transport (parity)
        assert _http_get(http_srv.port, "/debug/trace?last=0") == []
        assert svc.debug_snapshot(last=0)["trace"] == []
    finally:
        bin_srv.stop()
        http_srv.stop()


# ------------------------------------------------- bounded histogram store


def test_histogram_store_is_bounded_under_always_on_load():
    """The r15 leak fix: unbounded _values/_chunks growth under the
    always-on loop is capped by the weighted reservoir."""
    h = Histogram("x", reservoir_max=4096)
    rng = np.random.default_rng(7)
    for _ in range(100):
        h.observe_batch(list(rng.exponential(0.05, 5000)))
    assert h.count == 500_000
    assert h.stored_points <= 4096
    # weighted observe_many entries count toward the bound too
    h2 = Histogram("y", reservoir_max=512)
    for i in range(5000):
        h2.observe_many(float(i % 97) / 97.0, 3)
    assert h2.stored_points <= 512
    assert h2.count == 15000


def test_histogram_percentile_accuracy_on_known_distribution():
    """Rank accuracy through compaction, pinned on a known distribution:
    the compacted percentile must land within a small rank tolerance of
    the exact value."""
    h = Histogram("x", reservoir_max=8192)
    rng = np.random.default_rng(11)
    all_vals = []
    for _ in range(60):
        vals = list(rng.exponential(0.05, 4000))
        all_vals.extend(vals)
        h.observe_batch(vals)
    arr = np.sort(np.asarray(all_vals))
    for p in (50, 90, 99):
        exact = float(arr[min(int(p / 100 * len(arr)), len(arr) - 1)])
        got = h.percentile(p)
        # tolerance: +-0.5% of rank around the exact quantile
        lo = float(arr[max(int((p - 0.5) / 100 * len(arr)), 0)])
        hi = float(arr[min(int((p + 0.5) / 100 * len(arr)),
                           len(arr) - 1)])
        assert lo <= got <= hi, (p, got, exact, lo, hi)


def test_histogram_percentile_exact_below_the_bound():
    """Below the reservoir bound nothing compacts: rank semantics are
    identical to the pre-r15 exact walk, across BOTH stores."""
    h = Histogram("x")
    h.observe_batch([0.5, 0.1, 0.9, 0.3])  # chunk store
    h.observe_many(0.2, 3)                 # weighted store
    # expanded multiset: [.1 .2 .2 .2 .3 .5 .9], ranks 0..6
    assert h.percentile(0) == 0.1
    assert h.percentile(50) == pytest.approx(0.2)
    assert h.percentile(100) == 0.9
    assert h.stored_points == 5
    empty = Histogram("e")
    assert empty.percentile(99) == 0.0
    # totals() reads (count, sum) under the lock for the registry
    assert h.totals() == (7, pytest.approx(0.5 + 0.1 + 0.9 + 0.3 + 0.6))


# ------------------------------------------- budget-breach streaming trace


def test_stream_budget_breach_dumps_trace_fake_clock():
    """A pod-ful streaming step whose fake-clock span crosses the budget
    dumps the step breakdown; under-budget steps stay silent."""
    api, s = mk_sched(n_nodes=16)
    loop = s.stream(budget_s=0.25, min_quantum=256)
    dumps: list = []

    class Tick:
        def __init__(self, dt):
            self.t = 1000.0
            self.dt = dt

        def __call__(self):
            self.t += self.dt
            return self.t

    try:
        loop.trace_sink = dumps.append
        # under budget: 1ms between trace stamps -> no dump
        loop.trace_now = Tick(0.001)
        for p in PROFILES["density"](32):
            p.name = "quiet-" + p.name
            api.create("Pod", p)
        loop.step()
        loop.step()  # harvest the in-flight wave
        assert dumps == []
        # breach: every trace stamp advances 100ms -> the pod-ful step's
        # total crosses the 250ms budget and the breakdown dumps
        loop.trace_now = Tick(0.1)
        for p in PROFILES["density"](32):
            p.name = "slow-" + p.name
            api.create("Pod", p)
        loop.step()
        assert len(dumps) == 1
        text = dumps[0]
        assert "micro-wave step" in text
        assert "informer sync done" in text
        assert "micro-wave popped" in text
        assert "quantum=" in text
        # idle ticks never dump, whatever the clock says
        n = len(dumps)
        loop.step()  # harvests, pod-ful in effect (prev wave) — may dump
        loop.step()  # now truly idle
        idle_dumps = len(dumps)
        loop.step()
        assert len(dumps) == idle_dumps
    finally:
        loop.close()


def test_stream_trace_off_in_fixed_mode():
    """The drain (fixed-chunk mode) never constructs the per-step trace —
    budget tracing is a streaming-mode contract."""
    api, s = mk_sched(n_nodes=16, n_pods=64)
    dumps: list = []
    pipe = s.pipeline(chunk=32)
    pipe.trace_sink = dumps.append
    pipe.trace_now = lambda: 0.0  # would crash Trace math if ever used
    while True:
        st = pipe.step()
        if st["popped"] == 0 and pipe.idle:
            break
    pipe.close()
    assert dumps == []


# ----------------------------------------------------------- churn marker


def test_churn_ops_land_on_the_ring(flight):
    from kubernetes_tpu.testing.churn import (
        ChurnConfig,
        ChurnInjector,
        make_churn_schedule,
    )

    api = ApiServerLite()
    load_cluster(api, hollow_nodes(12), [])
    cfg = ChurnConfig(seed=3, node_churn_per_min=3.0, evict_per_min_abs=0)
    inj = ChurnInjector(api, make_churn_schedule(
        [n.name for n in api.list("Node")[0]], cfg, duration_s=2.0))
    inj.apply_until(2.0)
    assert sum(inj.applied.values()) > 0
    ops = [e for e in flight.snapshot() if e["kind"] == "churn_op"]
    assert len(ops) == sum(inj.applied.values())
    names = {rec.CHURN_OP_NAMES[e["a"]] for e in ops}
    assert names <= set(rec.CHURN_OP_CODES)
