"""Wave-parallel batch mode (engine/waves.py) + equivalence classes
(state/classes.py).

Wave semantics are batch-defined (new capability vs the reference's
sequential loop) but must be *score-exact* and *capacity-exact*: every
placement lands on a node that fit the pod at its wave's frozen state, no
node is ever overcommitted, and a pod is reported unschedulable only when no
node fits (monotonicity makes that verdict equal to the strict engine's)."""

import random
from collections import Counter

import numpy as np
import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.engine.scheduler_engine import SchedulingEngine
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.classes import ClassBatch, pod_class_key
from kubernetes_tpu.state.node_info import node_info_map
from tests.helpers import Gi, Mi, random_nodes, random_pod

PRIO = (("LeastRequestedPriority", 1), ("BalancedResourceAllocation", 1))


def run_mode(nodes, pods, mode, priorities=PRIO):
    import copy
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    eng = SchedulingEngine(cache, priorities=priorities)
    return eng.schedule([copy.deepcopy(p) for p in pods], mode=mode), eng


def test_class_key_groups_identical_specs():
    a = make_pod("a", cpu=100, memory=Mi)
    b = make_pod("b", cpu=100, memory=Mi)
    c = make_pod("c", cpu=200, memory=Mi)
    assert pod_class_key(a) == pod_class_key(b)
    assert pod_class_key(a) != pod_class_key(c)


def test_class_batch_dedup_and_gather():
    cache = SchedulerCache()
    for n in random_nodes(random.Random(0), 6):
        cache.add_node(n)
    eng = SchedulingEngine(cache)
    eng.snapshot.refresh(cache.node_infos())
    pods = [make_pod(f"p{i}", cpu=100 * (i % 3), memory=Mi) for i in range(12)]
    batch = ClassBatch(pods, eng.snapshot)
    assert batch.num_classes == 3
    assert len(batch.pod_class) == 12
    # class rows reproduce per-pod encoding: gather == direct PodBatch
    from kubernetes_tpu.state.snapshot import PodBatch
    direct = PodBatch(pods, eng.snapshot)
    np.testing.assert_array_equal(
        batch.reps_batch.req[batch.pod_class], direct.req)
    np.testing.assert_array_equal(
        batch.reps_batch.nonzero[batch.pod_class], direct.nonzero)


def test_wave_matches_strict_when_no_ties():
    # distinct node sizes -> distinct scores -> no RR involvement
    nodes = [make_node(f"n{i}", cpu=1000 * (i + 1), memory=(i + 1) * 2 * Gi,
                       pods=110) for i in range(5)]
    pods = [make_pod(f"p{i}", cpu=300, memory=512 * Mi) for i in range(8)]
    got_w, _ = run_mode(nodes, pods, "wave")
    got_s, _ = run_mode(nodes, pods, "strict")
    # wave re-scores after each conflict round; strict after every pod. With
    # all-identical pods both must produce the same multiset of placements
    # and identical per-pod feasibility.
    assert [r.node_name is None for r in got_w] \
        == [r.node_name is None for r in got_s]
    assert Counter(r.node_name for r in got_w) \
        == Counter(r.node_name for r in got_s)


@pytest.mark.parametrize("seed", [0, 3, 7])
def test_wave_placements_are_valid_and_exhaustive(seed):
    """Every wave placement must fit (validated object-level), and every
    unschedulable verdict must be real (no node fits even on the empty run)."""
    rng = random.Random(seed)
    nodes = random_nodes(rng, 10)
    names = [n.name for n in nodes]
    pods = [random_pod(rng, i, names) for i in range(50)]
    for p in pods:
        p.node_name = ""
    results, eng = run_mode(nodes, pods, "wave")
    infos = node_info_map(nodes, [])
    placed = 0
    for r in results:
        if r.node_name is None:
            continue
        placed += 1
        info = infos[r.node_name]
        import copy
        q = copy.deepcopy(r.pod)
        q.node_name = r.node_name
        info.add_pod(q)
    # capacity is never exceeded after all commits
    for nm, info in infos.items():
        node = info.node
        assert info.requested.milli_cpu <= node.allocatable.milli_cpu, nm
        assert info.requested.memory <= node.allocatable.memory, nm
        assert len(info.pods) <= node.allowed_pod_number, nm
    assert placed > 0


def test_wave_spreads_identical_pods_across_tie_set():
    nodes = [make_node(f"n{i}", cpu=4000, memory=8 * Gi, pods=110)
             for i in range(8)]
    pods = [make_pod(f"p{i}", cpu=100, memory=128 * Mi) for i in range(24)]
    results, _ = run_mode(nodes, pods, "wave")
    counts = Counter(r.node_name for r in results)
    assert None not in counts
    assert set(counts.values()) == {3}  # perfectly even 24/8


def test_wave_capacity_exact_with_overflow():
    nodes = [make_node(f"n{i}", cpu=1000, memory=2 * Gi, pods=110)
             for i in range(3)]
    # each node fits exactly 2 (cpu) -> 6 slots, 9 pods
    pods = [make_pod(f"p{i}", cpu=500, memory=256 * Mi) for i in range(9)]
    results, _ = run_mode(nodes, pods, "wave")
    ok = [r for r in results if r.node_name is not None]
    bad = [r for r in results if r.node_name is None]
    assert len(ok) == 6 and len(bad) == 3
    assert all(v == 2 for v in Counter(r.node_name for r in ok).values())
    assert all(r.fit_count == 0 for r in bad)


def test_wave_host_ports_serialize_per_node():
    nodes = [make_node(f"n{i}", cpu=4000, memory=8 * Gi) for i in range(2)]
    pods = [make_pod(f"p{i}", cpu=100, memory=Mi, ports=[8080])
            for i in range(4)]
    results, _ = run_mode(nodes, pods, "wave")
    names = [r.node_name for r in results]
    # only one 8080 per node -> exactly 2 placed
    assert Counter(n is not None for n in names)[True] == 2
    placed = [n for n in names if n is not None]
    assert len(set(placed)) == 2


def test_wave_deterministic():
    rng = random.Random(11)
    nodes = random_nodes(rng, 9)
    pods = [random_pod(rng, i, [n.name for n in nodes]) for i in range(40)]
    for p in pods:
        p.node_name = ""
    a, _ = run_mode(nodes, pods, "wave")
    b, _ = run_mode(nodes, pods, "wave")
    assert [r.node_name for r in a] == [r.node_name for r in b]


def test_wave_second_batch_sees_committed_state():
    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu=1000, memory=2 * Gi))
    cache.add_node(make_node("n1", cpu=1000, memory=2 * Gi))
    eng = SchedulingEngine(cache, priorities=PRIO)
    [r1] = eng.schedule([make_pod("a", cpu=800, memory=Gi)], mode="wave")
    assert r1.node_name is not None
    other = {"n0": "n1", "n1": "n0"}[r1.node_name]
    [r2] = eng.schedule([make_pod("b", cpu=800, memory=Gi)], mode="wave")
    assert r2.node_name == other
    [r3] = eng.schedule([make_pod("c", cpu=800, memory=Gi)], mode="wave")
    assert r3.node_name is None and r3.fit_count == 0
