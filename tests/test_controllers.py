"""Controller reconcile tests, deterministic pump mode.

Behavioral shape follows the reference's controller unit tests
(replica_set_test.go, deployment_controller_test.go, job_controller_test.go,
daemoncontroller_test.go, gc_controller_test.go) — spec vs observed diffs
through a fake-clock pump, no threads.
"""

import dataclasses

from kubernetes_tpu.api.types import LabelSelector, Pod, make_node, make_pod
from kubernetes_tpu.api.workloads import (
    DaemonSet,
    Deployment,
    Job,
    Namespace,
    ReplicaSet,
    Service,
    StatefulSet,
)
from kubernetes_tpu.controllers.manager import ControllerManager
from kubernetes_tpu.controllers.namespace import delete_namespace
from kubernetes_tpu.server.apiserver_lite import ApiServerLite


def mk_template(labels):
    return dataclasses.replace(make_pod("", labels=dict(labels), cpu=100), name="")


def mk_manager():
    api = ApiServerLite()
    cm = ControllerManager(api, record_events=False)
    return api, cm


def pods_of(api, ns="default"):
    return [p for p in api.list("Pod")[0] if p.namespace == ns]


def set_phase(api, pod, phase, node="n1"):
    fresh = api.get("Pod", pod.namespace, pod.name)
    api.update("Pod", dataclasses.replace(fresh, phase=phase,
                                          node_name=fresh.node_name or node))


# ----------------------------------------------------------------- replicaset


def test_replicaset_scales_up_and_down():
    api, cm = mk_manager()
    rs = ReplicaSet(name="web", replicas=3,
                    selector=LabelSelector(match_labels={"app": "web"}),
                    template=mk_template({"app": "web"}))
    api.create("ReplicaSet", rs)
    cm.pump_until_stable()
    assert len(pods_of(api)) == 3
    got = api.get("ReplicaSet", "default", "web")
    assert got.observed_replicas == 3
    # scale down to 1
    api.update("ReplicaSet", dataclasses.replace(got, replicas=1))
    cm.pump_until_stable()
    assert len(pods_of(api)) == 1


def test_replicaset_replaces_failed_pod_and_reports_ready():
    api, cm = mk_manager()
    rs = ReplicaSet(name="web", replicas=2,
                    selector=LabelSelector(match_labels={"app": "web"}),
                    template=mk_template({"app": "web"}))
    api.create("ReplicaSet", rs)
    cm.pump_until_stable()
    p0, p1 = pods_of(api)
    set_phase(api, p0, "Running")
    set_phase(api, p1, "Failed")
    cm.pump_until_stable()
    live = [p for p in pods_of(api) if p.phase != "Failed"]
    assert len(live) == 2  # failed pod replaced
    assert api.get("ReplicaSet", "default", "web").ready_replicas == 1


def test_replicaset_adopts_matching_orphan():
    api, cm = mk_manager()
    api.create("Pod", make_pod("orphan", labels={"app": "web"}))
    rs = ReplicaSet(name="web", replicas=1,
                    selector=LabelSelector(match_labels={"app": "web"}),
                    template=mk_template({"app": "web"}))
    api.create("ReplicaSet", rs)
    cm.pump_until_stable()
    pods = pods_of(api)
    assert len(pods) == 1 and pods[0].name == "orphan"
    assert pods[0].owner_kind == "ReplicaSet"


# ----------------------------------------------------------------- deployment


def test_deployment_creates_rs_and_rolls_template():
    api, cm = mk_manager()
    dep = Deployment(name="api", replicas=3,
                     selector=LabelSelector(match_labels={"app": "api"}),
                     template=mk_template({"app": "api"}),
                     max_surge=1, max_unavailable=1)
    api.create("Deployment", dep)
    cm.pump_until_stable()
    rses = api.list("ReplicaSet")[0]
    assert len(rses) == 1 and rses[0].replicas == 3
    assert rses[0].owner_kind == "Deployment"
    # pods ready
    for p in pods_of(api):
        set_phase(api, p, "Running")
    cm.pump_until_stable()
    assert api.get("Deployment", "default", "api").ready_replicas == 3

    # roll: change the template (new image -> new hash)
    fresh = api.get("Deployment", "default", "api")
    new_tpl = dataclasses.replace(fresh.template)
    new_tpl.containers = [dataclasses.replace(new_tpl.containers[0], image="v2")] \
        if new_tpl.containers else []
    new_tpl = dataclasses.replace(new_tpl, annotations={"rev": "2"})
    api.update("Deployment", dataclasses.replace(fresh, template=new_tpl))
    for _ in range(10):  # drive the rollout, marking new pods ready as they come
        cm.pump_until_stable()
        for p in pods_of(api):
            if p.phase != "Running":
                set_phase(api, p, "Running")
    cm.pump_until_stable()
    rses = api.list("ReplicaSet")[0]
    by_replicas = sorted(rses, key=lambda r: r.replicas)
    assert len(rses) == 2
    assert by_replicas[0].replicas == 0  # old RS fully drained
    assert by_replicas[1].replicas == 3  # new RS at target
    dep_now = api.get("Deployment", "default", "api")
    assert dep_now.revision == 2 and dep_now.updated_replicas == 3


def test_deployment_scale_down_shrinks_new_rs():
    api, cm = mk_manager()
    dep = Deployment(name="api", replicas=5,
                     selector=LabelSelector(match_labels={"app": "api"}),
                     template=mk_template({"app": "api"}))
    api.create("Deployment", dep)
    cm.pump_until_stable()
    assert len(pods_of(api)) == 5
    fresh = api.get("Deployment", "default", "api")
    api.update("Deployment", dataclasses.replace(fresh, replicas=3))
    cm.pump_until_stable()
    assert len(pods_of(api)) == 3
    assert api.list("ReplicaSet")[0][0].replicas == 3


def test_replicaset_selector_template_mismatch_stops_not_loops():
    api, cm = mk_manager()
    rs = ReplicaSet(name="bad", replicas=3,
                    selector=LabelSelector(match_labels={"app": "web"}),
                    template=mk_template({"app": "api"}))  # mismatched
    api.create("ReplicaSet", rs)
    cm.pump_until_stable()
    assert pods_of(api) == []  # no unbounded creation


def test_endpoints_drop_pod_relabeled_out_of_selector():
    api, cm = mk_manager()
    api.create("Service", Service(name="svc", selector={"app": "web"}))
    api.create("Pod", dataclasses.replace(
        make_pod("w1", labels={"app": "web"}, node_name="n1"), phase="Running"))
    cm.pump_until_stable()
    assert [a.pod_key for a in api.get("Endpoints", "default", "svc").addresses] \
        == ["default/w1"]
    p = api.get("Pod", "default", "w1")
    api.update("Pod", dataclasses.replace(p, labels={"app": "db"}))
    cm.pump_until_stable()
    assert api.get("Endpoints", "default", "svc").addresses == []


# ----------------------------------------------------------------------- job


def test_job_runs_to_completion():
    api, cm = mk_manager()
    job = Job(name="calc", completions=3, parallelism=2,
              template=dataclasses.replace(mk_template({"job": "calc"}),
                                           restart_policy="Never"))
    api.create("Job", job)
    cm.pump_until_stable()
    assert len(pods_of(api)) == 2  # parallelism cap
    for p in pods_of(api):
        set_phase(api, p, "Succeeded")
    cm.pump_until_stable()
    # 2 done, 1 to go -> one more pod
    active = [p for p in pods_of(api) if p.phase == "Pending"]
    assert len(active) == 1
    set_phase(api, active[0], "Succeeded")
    cm.pump_until_stable()
    got = api.get("Job", "default", "calc")
    assert got.complete and got.succeeded == 3 and got.active == 0


# ------------------------------------------------------------------ daemonset


def test_daemonset_one_pod_per_eligible_node():
    api, cm = mk_manager()
    for i in range(3):
        api.create("Node", make_node(f"n{i}"))
    api.create("Node", make_node("cordoned", ready=False))
    ds = DaemonSet(name="agent",
                   selector=LabelSelector(match_labels={"ds": "agent"}),
                   template=mk_template({"ds": "agent"}))
    api.create("DaemonSet", ds)
    cm.pump_until_stable()
    pods = pods_of(api)
    assert {p.node_name for p in pods} == {"n0", "n1", "n2"}  # direct binding
    got = api.get("DaemonSet", "default", "agent")
    assert got.desired_scheduled == 3 and got.current_scheduled == 3
    # node joins -> pod appears
    api.create("Node", make_node("n3"))
    cm.pump_until_stable()
    assert {p.node_name for p in pods_of(api)} == {"n0", "n1", "n2", "n3"}


# ----------------------------------------------------------------- statefulset


def test_statefulset_ordered_creation_and_reverse_scale_down():
    api, cm = mk_manager()
    ss = StatefulSet(name="db", replicas=3,
                     selector=LabelSelector(match_labels={"ss": "db"}),
                     template=mk_template({"ss": "db"}))
    api.create("StatefulSet", ss)
    cm.pump_until_stable()
    assert [p.name for p in pods_of(api)] == ["db-0"]  # strict ordering
    set_phase(api, pods_of(api)[0], "Running")
    cm.pump_until_stable()
    names = sorted(p.name for p in pods_of(api))
    assert names == ["db-0", "db-1"]
    for p in pods_of(api):
        if p.phase != "Running":
            set_phase(api, p, "Running")
    cm.pump_until_stable()
    assert sorted(p.name for p in pods_of(api)) == ["db-0", "db-1", "db-2"]
    # scale to 1: highest ordinals go first
    fresh = api.get("StatefulSet", "default", "db")
    api.update("StatefulSet", dataclasses.replace(fresh, replicas=1))
    cm.pump_until_stable()
    assert sorted(p.name for p in pods_of(api)) == ["db-0"]


# ------------------------------------------------------------------ endpoints


def test_endpoints_track_ready_pods():
    api, cm = mk_manager()
    api.create("Service", Service(name="svc", selector={"app": "web"}))
    api.create("Pod", make_pod("w1", labels={"app": "web"}, node_name="n1"))
    api.create("Pod", make_pod("w2", labels={"app": "web"}, node_name="n2"))
    api.create("Pod", make_pod("other", labels={"app": "db"}, node_name="n1"))
    cm.pump_until_stable()
    eps = api.get("Endpoints", "default", "svc")
    assert eps.addresses == []  # none Running yet
    for name in ("w1", "w2"):
        p = api.get("Pod", "default", name)
        api.update("Pod", dataclasses.replace(p, phase="Running"))
    cm.pump_until_stable()
    eps = api.get("Endpoints", "default", "svc")
    assert sorted(a.pod_key for a in eps.addresses) == ["default/w1", "default/w2"]
    # pod dies -> address removed
    api.delete("Pod", "default", "w1")
    cm.pump_until_stable()
    eps = api.get("Endpoints", "default", "svc")
    assert [a.pod_key for a in eps.addresses] == ["default/w2"]


# -------------------------------------------------------------------- gc


def test_gc_cascade_on_owner_delete():
    api, cm = mk_manager()
    dep = Deployment(name="api", replicas=2,
                     selector=LabelSelector(match_labels={"app": "api"}),
                     template=mk_template({"app": "api"}))
    api.create("Deployment", dep)
    cm.pump_until_stable()
    assert len(pods_of(api)) == 2
    api.delete("Deployment", "default", "api")
    cm.pump_until_stable()
    assert api.list("ReplicaSet")[0] == []  # RS collected
    assert pods_of(api) == []  # pods collected transitively


def test_podgc_reaps_pods_on_vanished_nodes_and_terminated_excess():
    api, cm = mk_manager()
    cm.controllers["podgc"].terminated_threshold = 1
    api.create("Node", make_node("n1"))
    api.create("Pod", make_pod("on-gone-node", node_name="ghost"))
    api.create("Pod", dataclasses.replace(make_pod("done1"), phase="Succeeded"))
    api.create("Pod", dataclasses.replace(make_pod("done2"), phase="Succeeded"))
    cm.pump_until_stable()
    cm.controllers["podgc"].resync()
    cm.pump_until_stable()
    names = {p.name for p in pods_of(api)}
    assert "on-gone-node" not in names
    assert names == {"done2"}  # oldest terminated reaped down to threshold


# ------------------------------------------------------------------ namespace


def test_namespace_lifecycle_deletes_contents():
    api, cm = mk_manager()
    api.create("Namespace", Namespace(name="team-a"))
    api.create("Pod", make_pod("p1", namespace="team-a"))
    api.create("Service", Service(name="s1", namespace="team-a"))
    api.create("Pod", make_pod("keep", namespace="default"))
    cm.pump_until_stable()
    delete_namespace(api, "team-a")
    cm.pump_until_stable()
    assert all(p.namespace != "team-a" for p in api.list("Pod")[0])
    assert api.list("Service")[0] == []
    assert [p.name for p in api.list("Pod")[0]] == ["keep"]
    import pytest
    from kubernetes_tpu.server.apiserver_lite import NotFound
    with pytest.raises(NotFound):
        api.get("Namespace", "", "team-a")


# ------------------------------------------------------------------ threaded


def test_controller_manager_threaded_converges():
    api = ApiServerLite()
    cm = ControllerManager(api, record_events=False)
    rs = ReplicaSet(name="web", replicas=5,
                    selector=LabelSelector(match_labels={"app": "web"}),
                    template=mk_template({"app": "web"}))
    api.create("ReplicaSet", rs)
    cm.start(workers=2, poll=0.005)
    import time
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if len(pods_of(api)) == 5:
            break
        time.sleep(0.02)
    cm.stop()
    assert len(pods_of(api)) == 5
