"""pkg/probe executors against the framework's OWN HTTP surfaces.

The probers are exercised the way the reference's are: HTTP probes of
live endpoints (kubelet API /healthz, REST apiserver /healthz), TCP
probes of their listeners, failure on dead ports/4xx/5xx, and the
exec prober's Success/Failure/Unknown mapping (exec.go maps
infrastructure errors to Unknown, not Failure).
"""

import socket

from kubernetes_tpu.api.types import make_node
from kubernetes_tpu.api.workloads import Namespace
from kubernetes_tpu.nodes.kubelet import HollowKubelet
from kubernetes_tpu.nodes.kubelet_server import KubeletServer
from kubernetes_tpu.server.apiserver import ApiServer
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.server.rest_http import RestServer
from kubernetes_tpu.utils.probe import (
    FAILURE,
    SUCCESS,
    UNKNOWN,
    probe_exec,
    probe_http,
    probe_tcp,
)


def test_http_probe_against_live_surfaces():
    api = ApiServer()
    api.store.create("Namespace", Namespace("default"))
    rest = RestServer(api)
    rest.start()
    lite = ApiServerLite()
    node = make_node("n1", cpu=1000, memory=1 << 31)
    lite.create("Node", node)
    ks = KubeletServer(HollowKubelet(lite, node))
    ks.start()
    try:
        for port, path in ((rest.port, "/healthz"), (ks.port, "/healthz")):
            result, msg = probe_http(f"http://127.0.0.1:{port}{path}")
            assert result == SUCCESS, msg
        # 404 is a FAILED probe, not an error
        result, msg = probe_http(f"http://127.0.0.1:{ks.port}/nope")
        assert result == FAILURE and "404" in msg
        # TCP connect succeeds on a live listener
        assert probe_tcp("127.0.0.1", rest.port)[0] == SUCCESS
    finally:
        rest.stop()
        ks.stop()


def test_probe_failures_on_dead_endpoints():
    # grab a port nobody is listening on
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    result, msg = probe_http(f"http://127.0.0.1:{port}/healthz",
                             timeout=0.3)
    assert result == FAILURE  # refused connection = failed probe
    assert probe_tcp("127.0.0.1", port, timeout=0.3)[0] == FAILURE


def test_exec_probe_result_mapping():
    assert probe_exec(lambda: (0, "ok")) == (SUCCESS, "ok")
    assert probe_exec(lambda: (2, "bad")) == (FAILURE, "bad")
    # infrastructure error -> Unknown, like exec.go
    def boom():
        raise RuntimeError("runtime unavailable")
    result, msg = probe_exec(boom)
    assert result == UNKNOWN and "unavailable" in msg


def test_probe_daemon_healthz_lifecycle():
    """The prober against the scheduler daemon's healthz — alive while
    running, FAILED after stop (the liveness signal an operator's probe
    would consume, server.go's healthz story)."""
    from kubernetes_tpu.api.types import make_pod
    from kubernetes_tpu.server.daemon import SchedulerDaemon, \
        SchedulerOptions

    api = ApiServerLite()
    api.create("Node", make_node("n1", cpu=4000, memory=1 << 33))
    api.create("Pod", make_pod("p", cpu=100))
    d = SchedulerDaemon(api, "probe-d",
                        SchedulerOptions(leader_elect=False))
    d.step()
    url = f"http://127.0.0.1:{d.healthz_port}/healthz"
    result, msg = probe_http(url)
    assert result == SUCCESS, msg
    d.stop()
    assert probe_http(url, timeout=0.3)[0] == FAILURE
