"""Durable-store tests: WAL framing, snapshot/restore, compaction, and the
kill -9 mid-storm recovery story.

Reference parity targets: etcd's WAL+snapshot recovery behind
storage/etcd3/store.go (:85 New, :257 GuaranteedUpdate CAS),
cluster/restore-from-backup.sh, and the level-triggered relist resume of
SURVEY §5.4 (a watcher holding a pre-crash resourceVersion must get
TooOldResourceVersion and rebuild from a fresh List)."""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import pytest

from kubernetes_tpu.api.types import Binding, make_node, make_pod
from kubernetes_tpu.server.apiserver_lite import (
    ApiServerLite,
    TooOldResourceVersion,
)
from kubernetes_tpu.server.durable import DurableStore, WriteAheadLog


# ------------------------------------------------------------------ WAL unit


def test_wal_roundtrip(tmp_path):
    path = str(tmp_path / "wal.log")
    w = WriteAheadLog(path)
    recs = [b"alpha", b"x" * 10_000, b""]
    for r in recs:
        w.append(r)
    w.flush()
    w.close()
    assert list(WriteAheadLog.replay(path)) == recs


def test_wal_torn_tail_stops_cleanly(tmp_path):
    path = str(tmp_path / "wal.log")
    w = WriteAheadLog(path)
    w.append(b"first")
    w.append(b"second-record-payload")
    w.flush()
    w.close()
    size = os.path.getsize(path)
    with open(path, "r+b") as f:  # tear the last record mid-payload
        f.truncate(size - 7)
    assert list(WriteAheadLog.replay(path)) == [b"first"]


def test_wal_corrupt_crc_stops(tmp_path):
    path = str(tmp_path / "wal.log")
    w = WriteAheadLog(path)
    w.append(b"good")
    w.append(b"evil")
    w.flush()
    w.close()
    with open(path, "r+b") as f:  # flip a payload byte of the last record
        f.seek(-1, os.SEEK_END)
        f.write(b"\xff")
    assert list(WriteAheadLog.replay(path)) == [b"good"]


def test_wal_torn_tail_repaired_on_restore(tmp_path):
    """Restoring over a torn tail must TRUNCATE it: appending after the tear
    would bury every post-restart record behind an unreadable frame, so the
    next restore would silently lose acknowledged writes."""
    d = str(tmp_path / "data")
    api = ApiServerLite(data_dir=d)
    api.create("Pod", make_pod("p1"))
    api.create("Pod", make_pod("p2"))
    api.close()
    wal = os.path.join(d, DurableStore.WAL)
    with open(wal, "r+b") as f:  # tear the last record
        f.truncate(os.path.getsize(wal) - 3)

    api2 = ApiServerLite(data_dir=d)  # p2's record torn away
    pods, _ = api2.list("Pod")
    assert [p.name for p in pods] == ["p1"]
    api2.create("Pod", make_pod("p3"))  # appended after the repaired tail
    api2.close()

    api3 = ApiServerLite(data_dir=d)  # p3 must survive the next replay
    pods, _ = api3.list("Pod")
    assert sorted(p.name for p in pods) == ["p1", "p3"]


# ------------------------------------------------------- store level restore


def test_restore_objects_and_rv(tmp_path):
    d = str(tmp_path / "data")
    api = ApiServerLite(data_dir=d)
    api.create("Node", make_node("n1"))
    api.create("Pod", make_pod("p1", cpu=100))
    api.create("Pod", make_pod("p2", cpu=100))
    api.bind(Binding("p1", "default", "", "n1"))
    api.delete("Pod", "default", "p2")
    rv = api.current_rv()
    api.close()

    api2 = ApiServerLite(data_dir=d)
    assert api2.current_rv() == rv
    assert api2.get("Pod", "default", "p1").node_name == "n1"
    with pytest.raises(Exception):
        api2.get("Pod", "default", "p2")
    nodes, _ = api2.list("Node")
    assert [n.name for n in nodes] == ["n1"]
    # rv continuity: the next write must move past the restored rv
    api2.create("Pod", make_pod("p3"))
    assert api2.current_rv() == rv + 1


def test_watch_resume_after_restart_requires_relist(tmp_path):
    d = str(tmp_path / "data")
    api = ApiServerLite(data_dir=d)
    api.create("Node", make_node("n1"))
    pre_crash_rv = api.current_rv()
    api.create("Pod", make_pod("p1"))
    api.close()

    api2 = ApiServerLite(data_dir=d)
    # a watcher resuming from a pre-restart cursor must be told to relist
    with pytest.raises(TooOldResourceVersion):
        api2.watch_since(("Pod", "Node"), pre_crash_rv)
    # the relist handshake works and yields a valid new cursor
    pods, rv = api2.list("Pod")
    assert [p.name for p in pods] == ["p1"]
    assert api2.watch_since(("Pod",), rv) == []  # current cursor: no events
    api2.create("Pod", make_pod("p2"))
    evs = api2.watch_since(("Pod",), rv)
    assert [e.obj.name for e in evs] == ["p2"]


def test_compaction_truncates_wal_and_survives(tmp_path):
    d = str(tmp_path / "data")
    api = ApiServerLite(data_dir=d, compact_every=50)
    for i in range(120):  # crosses the threshold twice
        api.create("Pod", make_pod(f"p{i}"))
    assert os.path.exists(os.path.join(d, DurableStore.SNAPSHOT))
    # WAL holds only records since the last snapshot, not all 120
    assert api._durable._records_since_snapshot == 120 % 50
    remaining = sum(1 for _ in WriteAheadLog.replay(
        os.path.join(d, DurableStore.WAL)))
    assert remaining == 120 % 50
    api.close()
    api2 = ApiServerLite(data_dir=d)
    pods, _ = api2.list("Pod")
    assert len(pods) == 120


def test_in_memory_mode_unchanged(tmp_path):
    api = ApiServerLite()
    api.create("Pod", make_pod("p1"))
    assert api._durable is None


# --------------------------------------------------- kill -9 mid-storm (e2e)


def test_kill9_midstorm_recovery_and_drain(tmp_path):
    """The VERDICT-specified story: kill the process mid-storm, restart,
    every pod reaches bound exactly once, watches resume from a valid rv."""
    d = str(tmp_path / "data")
    n_nodes, n_pods = 20, 200
    env = dict(os.environ, PYTHONPATH=os.path.dirname(os.path.dirname(__file__)))
    proc = subprocess.Popen(
        [sys.executable, os.path.join(os.path.dirname(__file__),
                                      "_persistence_storm.py"),
         d, str(n_nodes), str(n_pods)],
        stdout=subprocess.PIPE, text=True, env=env)
    # watchdog: a silently-stalled child must fail the test, not hang the
    # blocking stdout read forever
    import threading
    watchdog = threading.Timer(60, proc.kill)
    watchdog.start()
    try:
        # wait until the storm is genuinely mid-flight, then SIGKILL
        bound = 0
        for line in proc.stdout:
            if line.startswith("BOUND"):
                bound = int(line.split()[1])
                if bound >= 50:
                    break
        assert bound >= 50, "storm subprocess made no progress before 60s"
        proc.kill()  # SIGKILL — no atexit, no flush, no close
        proc.wait(timeout=30)
        assert proc.returncode == -signal.SIGKILL
    finally:
        watchdog.cancel()
        if proc.poll() is None:
            proc.kill()
        proc.stdout.close()

    # ---- restart: restore every flushed write --------------------------
    api = ApiServerLite(data_dir=d)
    pods, rv = api.list("Pod")
    nodes, _ = api.list("Node")
    assert len(nodes) == n_nodes
    assert len(pods) == n_pods, "creates were flushed before the storm"
    already = [p for p in pods if p.node_name]
    # every BOUND report the parent saw was flushed batch-wise; the tail
    # batch may or may not have made it — at-least-reported durability
    assert len(already) >= min(50, n_pods)
    assert rv == api.current_rv() > 0

    # ---- a real scheduler drains the rest; no pod is bound twice -------
    from kubernetes_tpu.engine.scheduler import Scheduler
    sched = Scheduler(api, record_events=False)
    sched.start()
    totals = sched.run_until_drained()
    assert totals["bind_errors"] == 0
    pods, _ = api.list("Pod")
    assert all(p.node_name for p in pods)
    # exactly once: rebinding a bound pod must be refused by the store
    errs = api.bind_many([Binding(pods[0].name, "default", "", "node-0000")])
    assert errs[0] is not None and "conflict" in errs[0]
    api.close()

    # ---- and the restored store is itself durable ----------------------
    api2 = ApiServerLite(data_dir=d)
    pods2, _ = api2.list("Pod")
    assert sorted((p.name, p.node_name) for p in pods2) \
        == sorted((p.name, p.node_name) for p in pods)
