"""Watch-driven federation (federation/sync_loop.py).

The r4 VERDICT's weak #6 done-criterion: cluster-loss rebalance happens
from a WATCH EVENT with no manual sync_all() call — plus the other
reference behaviors the informer wiring buys (member-drift self-heal from
the member's own watch stream, auto-watch on join, deletion propagation).
Reference pattern: federation/pkg/federatedtypes sync controllers on
informers + workqueue with clusterDeliverer full-reconciles."""

from kubernetes_tpu.api.cluster import ConfigMap
from kubernetes_tpu.api.workloads import ReplicaSet
from kubernetes_tpu.federation.controller import (
    FEDERATED_RS_KIND,
    FederatedReplicaSet,
    FederationControlPlane,
    MANAGED_ANNOTATION,
)
from kubernetes_tpu.federation.sync_loop import FederationSyncLoop
from kubernetes_tpu.server.apiserver_lite import ApiServerLite, NotFound


def mk_plane(*names):
    plane = FederationControlPlane()
    members = {}
    for n in names:
        api = ApiServerLite()
        members[n] = api
        plane.join(n, api)
    return plane, members


def mk_frs(replicas=10, name="web"):
    return FederatedReplicaSet(
        name=name, replicas=replicas,
        template=ReplicaSet(name=name))


def test_create_event_drives_children():
    plane, members = mk_plane("alpha", "beta")
    loop = FederationSyncLoop(plane)
    loop.pump()  # cluster ADDs start the member watches
    plane.api.create(FEDERATED_RS_KIND, mk_frs(10))
    loop.pump(rounds=2)
    a = members["alpha"].get("ReplicaSet", "default", "web")
    b = members["beta"].get("ReplicaSet", "default", "web")
    assert a.replicas + b.replicas == 10
    assert loop.syncs > 0


def test_cluster_loss_rebalances_from_watch_event():
    """THE done-criterion: no sync_all anywhere — readiness flips on the
    federation apiserver, the Cluster informer fires, the queue drains,
    replicas move."""
    plane, members = mk_plane("alpha", "beta")
    loop = FederationSyncLoop(plane)
    loop.pump()
    plane.api.create(FEDERATED_RS_KIND, mk_frs(10))
    loop.pump(rounds=2)
    before = members["alpha"].get("ReplicaSet", "default", "web").replicas
    assert 0 < before < 10
    # beta dies: ONLY the API write happens; the loop must react on its own
    plane.mark_ready("beta", False)
    loop.pump(rounds=2)
    assert members["alpha"].get(
        "ReplicaSet", "default", "web").replicas == 10
    try:
        beta_rs = members["beta"].get("ReplicaSet", "default", "web")
        assert beta_rs is None or beta_rs.replicas == 0
    except NotFound:
        pass  # removed from the lost cluster's plan entirely


def test_member_drift_self_heals_from_member_watch():
    """Someone hand-deletes the child in a member cluster: the MEMBER's
    watch stream enqueues the federated parent; no federation-side event
    needed."""
    plane, members = mk_plane("alpha", "beta")
    loop = FederationSyncLoop(plane)
    loop.pump()
    plane.api.create(FEDERATED_RS_KIND, mk_frs(10))
    loop.pump(rounds=2)
    members["alpha"].delete("ReplicaSet", "default", "web")
    loop.pump(rounds=2)
    assert members["alpha"].get("ReplicaSet", "default", "web") is not None


def test_late_join_auto_watched_and_rebalanced():
    import json

    from kubernetes_tpu.federation.planner import PREFERENCES_ANNOTATION
    plane, members = mk_plane("alpha")
    loop = FederationSyncLoop(plane)
    loop.pump()
    frs = mk_frs(10)
    # rebalance=true: without it the planner is deliberately sticky and a
    # late joiner gets nothing (reference planner semantics)
    frs.annotations[PREFERENCES_ANNOTATION] = json.dumps(
        {"rebalance": True, "clusters": {"*": {"weight": 1}}})
    plane.api.create(FEDERATED_RS_KIND, frs)
    loop.pump(rounds=2)
    assert members["alpha"].get(
        "ReplicaSet", "default", "web").replicas == 10
    # a new cluster joins: the Cluster ADD event triggers the rebalance
    gamma = ApiServerLite()
    plane.join("gamma", gamma)
    loop.pump(rounds=2)
    a = members["alpha"].get("ReplicaSet", "default", "web").replicas
    g = gamma.get("ReplicaSet", "default", "web").replicas
    assert a + g == 10 and g > 0
    # and gamma's own drift now self-heals (its watch is live)
    gamma.delete("ReplicaSet", "default", "web")
    loop.pump(rounds=2)
    assert gamma.get("ReplicaSet", "default", "web") is not None


def test_deletion_propagates_absence():
    plane, members = mk_plane("alpha", "beta")
    loop = FederationSyncLoop(plane)
    loop.pump()
    plane.api.create(FEDERATED_RS_KIND, mk_frs(6))
    loop.pump(rounds=2)
    plane.api.delete(FEDERATED_RS_KIND, "default", "web")
    loop.pump(rounds=2)
    for api in members.values():
        try:
            assert api.get("ReplicaSet", "default", "web") is None
        except NotFound:
            pass


def test_loop_never_deletes_unmanaged_member_objects():
    """A user's plain ReplicaSet created directly in a member cluster has
    no federated parent: its watch event enqueues a federated key that
    resolves NotFound — and the loop must LEAVE IT ALONE (the managed
    ownership guard), not delete it from every cluster."""
    plane, members = mk_plane("alpha", "beta")
    loop = FederationSyncLoop(plane)
    loop.pump()
    members["alpha"].create("ReplicaSet",
                            ReplicaSet(name="local-web", replicas=3))
    loop.pump(rounds=3)
    survivor = members["alpha"].get("ReplicaSet", "default", "local-web")
    assert survivor is not None and survivor.replicas == 3
    # while MANAGED children of a real deleted federated object DO go
    plane.api.create(FEDERATED_RS_KIND, mk_frs(4, name="owned"))
    loop.pump(rounds=2)
    assert members["alpha"].get("ReplicaSet", "default", "owned") \
        .annotations[MANAGED_ANNOTATION] == "true"
    plane.api.delete(FEDERATED_RS_KIND, "default", "owned")
    loop.pump(rounds=2)
    try:
        gone = members["alpha"].get("ReplicaSet", "default", "owned")
        assert gone is None
    except NotFound:
        pass


def _wait_until(fn, timeout=10.0, interval=0.02):
    import time
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if fn():
                return True
        except NotFound:
            pass
        time.sleep(interval)
    return False


def test_background_worker_rebalances_without_pump():
    """VERDICT item #8 (first half): the sync loop runs on its OWN worker
    thread — create a federated RS, kill a cluster, and replicas move with
    NO test-side pump(rounds) anywhere. pump() stays available as the
    deterministic hook (every other test here), but a live deployment only
    calls start()."""
    plane, members = mk_plane("alpha", "beta")
    loop = FederationSyncLoop(plane)
    loop.start(interval_s=0.01)
    try:
        plane.api.create(FEDERATED_RS_KIND, mk_frs(10))
        assert _wait_until(
            lambda: members["alpha"].get("ReplicaSet", "default",
                                         "web").replicas
            + members["beta"].get("ReplicaSet", "default", "web").replicas
            == 10), "worker never reconciled the federated RS"
        # beta dies: only the API write happens; the worker must react
        plane.mark_ready("beta", False)
        assert _wait_until(
            lambda: members["alpha"].get("ReplicaSet", "default",
                                         "web").replicas == 10), \
            "worker never rebalanced after cluster loss"
    finally:
        loop.stop()
    assert loop.syncs > 0


def test_propagated_kinds_flow_through_the_loop():
    plane, members = mk_plane("alpha", "beta")
    loop = FederationSyncLoop(plane)
    loop.pump()
    plane.api.create("FederatedConfigMap",
                     ConfigMap(name="settings", data={"k": "v"}))
    loop.pump(rounds=2)
    for api in members.values():
        cm = api.get("ConfigMap", "default", "settings")
        assert cm.data == {"k": "v"}
        assert cm.annotations[MANAGED_ANNOTATION] == "true"
    plane.api.delete("FederatedConfigMap", "default", "settings")
    loop.pump(rounds=2)
    for api in members.values():
        try:
            assert api.get("ConfigMap", "default", "settings") is None
        except NotFound:
            pass


def test_federated_namespace_propagates():
    from kubernetes_tpu.api.workloads import Namespace
    plane, members = mk_plane("alpha", "beta")
    loop = FederationSyncLoop(plane)
    loop.pump()
    plane.api.create("FederatedNamespace",
                     Namespace(name="team-a", labels={"team": "a"}))
    loop.pump(rounds=2)
    for api in members.values():
        ns = api.get("Namespace", "", "team-a")
        assert ns.labels == {"team": "a"}
        assert ns.annotations[MANAGED_ANNOTATION] == "true"
    plane.api.delete("FederatedNamespace", "", "team-a")
    loop.pump(rounds=2)
    for api in members.values():
        try:
            assert api.get("Namespace", "", "team-a") is None
        except NotFound:
            pass
