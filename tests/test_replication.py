"""Store replication: WAL shipping + promotion (server/replication.py).

The analog of etcd's replicated availability (raft behind
storage/etcd3/store.go:85) at warm-standby fidelity: a follower ships the
primary's snapshot+WAL, survives primary compaction mid-stream, never
ships a torn frame, and promotes to a serving store a fresh scheduler
converges against. The failover storm runs at 1k nodes / 10k pods — the
scale r4's VERDICT asked chaos scenarios to reach."""

import os

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.server.durable import DurableStore
from kubernetes_tpu.server.replication import (
    WalShippingStandby,
    _complete_frame_prefix,
)
from kubernetes_tpu.testing.chaosmonkey import Chaosmonkey, Test

Gi = 1 << 30


# ------------------------------------------------------------- mechanics


def test_ship_replicates_incrementally(tmp_path):
    p, s = str(tmp_path / "p"), str(tmp_path / "s")
    api = ApiServerLite(data_dir=p)
    standby = WalShippingStandby(p, s)
    api.create("Node", make_node("n1"))
    standby.ship()
    assert standby.standby_rv() == 1
    api.create("Node", make_node("n2"))
    api.create("Pod", make_pod("a", cpu=10))
    standby.ship()
    assert standby.standby_rv() == 3
    # an idle pass ships nothing
    assert standby.ship() == 0


def test_ship_survives_primary_compaction(tmp_path):
    p, s = str(tmp_path / "p"), str(tmp_path / "s")
    # tiny compaction threshold: every write compacts soon
    api = ApiServerLite(data_dir=p, compact_every=5)
    standby = WalShippingStandby(p, s)
    for i in range(23):
        api.create("Pod", make_pod(f"p{i}", cpu=10))
        if i % 3 == 0:
            standby.ship()
    standby.ship()
    # the follower crossed several snapshot+truncate cycles and still
    # restores the full prefix
    assert standby.standby_rv() == 23
    api2 = standby.promote()
    assert len(api2.list("Pod")[0]) == 23


def test_ship_is_frame_aligned(tmp_path):
    """A half-written primary record must NOT cross the wire: ship only
    whole frames, pick the tail up next pass."""
    p, s = str(tmp_path / "p"), str(tmp_path / "s")
    api = ApiServerLite(data_dir=p)
    api.create("Node", make_node("n1"))
    standby = WalShippingStandby(p, s)
    standby.ship()
    # simulate a torn primary flush: append half a record's bytes
    wal = os.path.join(p, DurableStore.WAL)
    full = open(wal, "rb").read()
    with open(wal, "ab") as f:
        f.write(full[: max(5, len(full) // 4)])
    before = standby._wal_offset
    standby.ship()
    assert standby._wal_offset == before  # refused the torn tail
    assert standby.standby_rv() == 1  # standby still clean
    # the primary finishes the record (here: restore truncates the tear,
    # then a real write lands) and shipping resumes
    api2 = ApiServerLite(data_dir=p)
    api2.create("Node", make_node("n2"))
    standby.ship()
    assert standby.standby_rv() >= 2


def test_complete_frame_prefix():
    import struct
    import zlib
    hdr = struct.Struct("<II")
    rec = b"payload-bytes"
    frame = hdr.pack(len(rec), zlib.crc32(rec)) + rec
    assert _complete_frame_prefix(frame) == len(frame)
    assert _complete_frame_prefix(frame + frame[:4]) == len(frame)
    assert _complete_frame_prefix(frame[:7]) == 0
    assert _complete_frame_prefix(b"") == 0


# ------------------------------------------------- the failover storm


def test_store_failover_midstorm_1k_nodes(tmp_path):
    """Primary apiserver dies mid-storm at 1k nodes / 10k pods; the
    standby promotes from shipped WAL; a fresh scheduler relists and
    converges; binds stay exactly-once against the promoted truth."""
    p, s = str(tmp_path / "p"), str(tmp_path / "s")
    api = ApiServerLite(data_dir=p, max_log=100_000)
    for i in range(1000):
        api.create("Node", make_node(f"node-{i:04d}", cpu=4000,
                                     memory=16 * Gi))
    for i in range(10_000):
        api.create("Pod", make_pod(f"pod-{i:05d}", cpu=100))
    standby = WalShippingStandby(p, s)
    standby.ship()  # replicate the cluster + pending queue
    sched = Scheduler(api, record_events=False)
    sched.start()
    sched.schedule_round(max_batch=4000)
    standby.ship()  # the shipped prefix includes ~4k binds
    # more binds land AFTER the last ship: asynchronous shipping loses
    # them at failover (warm-standby semantics, stated in the module doc)
    sched.schedule_round(max_batch=2000)
    bound_primary = sum(1 for pd in api.list("Pod")[0] if pd.node_name)
    assert bound_primary >= 6000

    state = {}

    def primary_dies_standby_promotes():
        state["api"] = standby.promote(max_log=100_000)

    cm = Chaosmonkey(primary_dies_standby_promotes)

    def converge():
        api2 = state["api"]
        pods = api2.list("Pod")[0]
        assert len(pods) == 10_000  # every creation was shipped
        restored_bound = sum(1 for pd in pods if pd.node_name)
        # the shipped prefix survived; the unshipped tail did not
        assert 4000 <= restored_bound <= bound_primary
        sched2 = Scheduler(api2, record_events=False)
        sched2.start()  # fresh relist against the promoted store
        totals = sched2.run_until_drained()
        # exactly-once: the store refused any double bind
        assert totals["bind_errors"] == 0

    cm.register(Test(test=converge, name="store-failover"))
    cm.do()
    pods = state["api"].list("Pod")[0]
    unbound = [pd.name for pd in pods if not pd.node_name]
    assert not unbound, f"{len(unbound)} pods never bound"
