"""The tsan-lite runtime checker (analysis/lockcheck.py).

Static GL006/GL007 prove what the AST shows; these tests pin the runtime
half: inversion witnesses without losing the race, assert_held guards on
the ``*_locked()`` convention, the guaranteed-self-deadlock raise, and —
load-bearing for every shipped configuration — EXACT pass-through when
the knob is off.
"""

import threading

import pytest

from kubernetes_tpu.analysis import lockcheck


@pytest.fixture()
def armed(monkeypatch):
    monkeypatch.setenv("GRAFT_LOCKCHECK", "1")
    lockcheck.reset()
    yield
    lockcheck.reset()


# ------------------------------------------------------------ knob off


def test_knob_off_returns_raw_primitives(monkeypatch):
    monkeypatch.delenv("GRAFT_LOCKCHECK", raising=False)
    assert not lockcheck.enabled()
    lk = lockcheck.make_lock("X._lock")
    assert type(lk) is type(threading.Lock())  # raw _thread.lock, no wrapper
    rl = lockcheck.make_rlock("X._rlock")
    assert type(rl) is type(threading.RLock())
    cv = lockcheck.make_condition("X._cv")
    assert type(cv) is threading.Condition
    # assert_held is an isinstance-gated no-op on raw primitives
    lockcheck.assert_held(lk, "anything")
    assert lockcheck.violations() == []


# ----------------------------------------------------------- inversion


def test_abba_inversion_recorded_without_losing_the_race(armed):
    a = lockcheck.make_lock("Cell._a")
    b = lockcheck.make_lock("Cell._b")
    with a:
        with b:
            pass
    # single-threaded, never actually deadlocks — the edge table still
    # has the witness
    with b:
        with a:
            pass
    vs = lockcheck.violations()
    assert len(vs) == 1
    assert "lock-order inversion" in vs[0]
    assert "Cell._a" in vs[0] and "Cell._b" in vs[0]
    with pytest.raises(AssertionError, match="lock-order inversion"):
        lockcheck.assert_clean()
    lockcheck.reset()
    assert lockcheck.violations() == []
    lockcheck.assert_clean()


def test_consistent_order_is_clean(armed):
    a = lockcheck.make_lock("Cell._a")
    b = lockcheck.make_lock("Cell._b")
    for _ in range(3):
        with a:
            with b:
                pass
    assert lockcheck.violations() == []


def test_same_name_different_objects_no_edge(armed):
    """Two INSTANCES of one class share a lock id; no order exists
    between peers, so hand-over-hand on two instances is not an
    inversion."""
    a1 = lockcheck.make_lock("Peer._lock")
    a2 = lockcheck.make_lock("Peer._lock")
    with a1:
        with a2:
            pass
    with a2:
        with a1:
            pass
    assert lockcheck.violations() == []


def test_cross_thread_inversion_detected(armed):
    """The realistic shape: each direction on its OWN thread, never
    racing — lockdep-style, the edge table spans threads."""
    a = lockcheck.make_lock("Cell._a")
    b = lockcheck.make_lock("Cell._b")

    def fwd():
        with a:
            with b:
                pass

    def bwd():
        with b:
            with a:
                pass

    t = threading.Thread(target=fwd)
    t.start()
    t.join()
    t = threading.Thread(target=bwd)
    t.start()
    t.join()
    vs = lockcheck.violations()
    assert len(vs) == 1 and "inversion" in vs[0]


# ------------------------------------------------------- self-deadlock


def test_nonreentrant_reacquire_raises_instead_of_hanging(armed):
    lk = lockcheck.make_lock("C._lock")
    with lk:
        with pytest.raises(RuntimeError, match="guaranteed deadlock"):
            lk.acquire()
    # the raise happened BEFORE the raw acquire: lock is free again
    assert lk.acquire(timeout=0.5)
    lk.release()


def test_rlock_reentry_is_fine(armed):
    rl = lockcheck.make_rlock("C._rlock")
    with rl:
        with rl:
            pass
    assert lockcheck.violations() == []
    # fully released: another thread can take it (and give it back)
    got = []

    def taker():
        ok = rl.acquire(timeout=1)
        got.append(ok)
        if ok:
            rl.release()

    t = threading.Thread(target=taker)
    t.start()
    t.join()
    assert got == [True]


# --------------------------------------------------------- assert_held


def test_assert_held_records_unguarded_locked_call(armed):
    lk = lockcheck.make_lock("C._lock")
    with lk:
        lockcheck.assert_held(lk, "guarded path")
    assert lockcheck.violations() == []
    lockcheck.assert_held(lk, "bare path")
    vs = lockcheck.violations()
    assert len(vs) == 1
    assert "guard not held" in vs[0] and "bare path" in vs[0]


def test_assert_held_catches_real_torn_metrics_write(armed):
    """The r18 regression at runtime: calling Histogram._observe_locked
    without its lock is exactly what GL007 catches statically — the
    armed checker catches the same bug if it sneaks past the linter."""
    from kubernetes_tpu.utils.metrics import Histogram

    h = Histogram("t")  # constructed while armed -> checked lock
    h.observe(0.25)     # the public path holds the lock
    assert lockcheck.violations() == []
    h._observe_locked(0.5, 1)  # the bug: bare call
    vs = lockcheck.violations()
    assert len(vs) == 1 and "Histogram._lock" in vs[0]
    assert h.count == 2  # behaviour unchanged; only the report differs


# ----------------------------------------------------------- condition


def test_condition_wait_pops_and_restores_held_entry(armed):
    """wait() releases the lock for the duration, so the held entry must
    pop for the sleep and come back on wake — otherwise every lock the
    wait predicate (or the woken continuation) touches would hang a
    phantom cv-> X edge on the thread."""
    cv = lockcheck.make_condition("Q._lock")
    seen = []
    ready = []

    def producer():
        with cv:
            ready.append(1)
            cv.notify_all()

    with cv:
        assert cv._is_held()
        t = threading.Thread(target=producer)
        t.start()
        # the predicate runs on the waiter thread DURING the wait
        assert cv.wait_for(
            lambda: (seen.append(cv._is_held()), bool(ready))[1],
            timeout=5)
        assert cv._is_held()
    t.join()
    assert seen and not any(seen)
    assert lockcheck.violations() == []
