"""Process fleet over one shared cell (ISSUE 16): Omega's actual shape.

These tests pin the multi-process seams end to end:

  - RELIST: the hydration verb — framing round-trip, and commit TRUTH
    over the wire (a bind landed through the fence shows up in the next
    relist, assumed occupancy included);
  - the DOUBLE-CLAIM fence: two schedulers racing the same pod through
    the shared fence produce exactly one bind and one TYPED conflict,
    audited against the store's event log (zero ghost binds);
  - fence-conflict counters PARTITION exactly (sum of typed reasons ==
    total conflicts) and read byte-identical through all three
    transports (HTTP /debug/vars, binary STATS, embedded snapshot);
  - the reader-task leak fix: worker-process connection teardown leaves
    no pending asyncio task server-side — clean client closes drain to
    zero, and stop() cancels (and counts) any stragglers;
  - perfetto: one lane per scheduler process, fence-conflict events as
    instant markers aligned to the ring time base;
  - the trend gate learns the multiproc_N scenario headline from r18.
"""

from __future__ import annotations

import json
import time

import pytest

from kubernetes_tpu.api.types import make_pod
from kubernetes_tpu.client.binarywire import BinaryWireClient
from kubernetes_tpu.models.hollow import hollow_nodes
from kubernetes_tpu.observability import podtrace as pt
from kubernetes_tpu.server import framing
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.server.asyncwire import AsyncBinaryServer
from kubernetes_tpu.server.embedded import VerdictService
from kubernetes_tpu.server.extender import TPUExtenderBackend
from kubernetes_tpu.testing.churn import FaultyBindApi, extender_store_binder


def _pod(name: str, cpu: int = 100):
    return make_pod(name, cpu=cpu, memory=256 << 20)


def _cell(n_nodes: int = 32, with_store: bool = True):
    """One shared cell: store + fenced backend + service + binary wire."""
    api = ApiServerLite()
    nodes = hollow_nodes(n_nodes)
    binder = None
    if with_store:
        for n in nodes:
            api.create("Node", n)
        binder = extender_store_binder(FaultyBindApi(api))
    b = TPUExtenderBackend(binder=binder, coalesce_window_s=0.0005)
    b.sync_nodes(nodes)
    b.filter(_pod("warm"), None, None)
    svc = VerdictService(b)
    srv = AsyncBinaryServer(svc)
    srv.start()
    return api, b, svc, srv


# ------------------------------------------------------------------ relist


def test_relist_framing_roundtrip():
    nodes = hollow_nodes(5)
    pods = [make_pod(f"r-{i}", cpu=100, memory=64 << 20,
                     node_name=f"hollow-node-{i}") for i in range(3)]
    blob = framing.encode_relist_result(nodes, pods)
    rn, rp = framing.decode_relist_result(blob)
    assert [n.name for n in rn] == [n.name for n in nodes]
    assert [(p.name, p.node_name) for p in rp] == \
        [(p.name, p.node_name) for p in pods]
    # empty cell round-trips too (a worker can hydrate before any bind)
    rn, rp = framing.decode_relist_result(
        framing.encode_relist_result([], []))
    assert rn == [] and rp == []


def test_relist_over_wire_returns_commit_truth():
    """A bind committed through the fence is visible to the NEXT relist
    — assumed occupancy included, not just store-confirmed pods. That
    visibility is what bounds a sibling process's staleness."""
    api, b, svc, srv = _cell()
    cli = BinaryWireClient("127.0.0.1", srv.port).connect()
    try:
        nodes, pods = cli.relist()
        assert len(nodes) == 32 and pods == []
        p = _pod("mp-a")
        api.create("Pod", p)
        fv = cli.filter_fused(p)
        host = max(fv.top_scores, key=lambda t: t[1])[0]
        r = cli.bind(p.name, p.namespace, p.uid, host,
                     snapshot_gen=fv.snapshot_gen, idem_key="mp-a:1",
                     pod=p)
        assert r.kind == "ok"
        nodes, pods = cli.relist()
        assert [(q.key(), q.node_name) for q in pods] == \
            [("default/mp-a", host)]
    finally:
        cli.close()
        srv.stop()


# ------------------------------------------------------- double-claim fence


def test_two_schedulers_race_one_pod_exactly_one_bind():
    """The satellite's core claim, deterministic: two clients race the
    SAME pod to DIFFERENT nodes through fresh ledger keys (two
    independent schedulers, not a retry). Exactly one bind lands; the
    loser gets the TYPED double-claim conflict naming the owner; the
    store's event log shows exactly one bind — zero ghosts."""
    api, b, svc, srv = _cell()
    c1 = BinaryWireClient("127.0.0.1", srv.port).connect()
    c2 = BinaryWireClient("127.0.0.1", srv.port).connect()
    try:
        p = _pod("raced")
        api.create("Pod", p)
        r1 = c1.bind(p.name, p.namespace, p.uid, "hollow-node-3",
                     snapshot_gen=None, idem_key="raced:w0:0", pod=p)
        assert r1.kind == "ok"
        r2 = c2.bind(p.name, p.namespace, p.uid, "hollow-node-7",
                     snapshot_gen=None, idem_key="raced:w1:0", pod=p)
        assert r2.kind == "conflict"
        assert "double-claim" in r2.error
        assert "already claimed on hollow-node-3" in r2.error
        # typed partition: the conflict is double_claim, nothing else
        vars_ = svc.debug_snapshot()["vars"]
        assert vars_["counter.extender.bind_conflicts"] == 1
        assert vars_[
            "counter.extender.bind_conflict_reason_double_claim"] == 1
        # store truth: ONE bind event, on the winner's node
        binds = [e for e in api._log
                 if e.kind == "Pod" and e.type == "MODIFIED"
                 and e.obj.node_name]
        assert [(e.obj.name, e.obj.node_name) for e in binds] == \
            [("raced", "hollow-node-3")]
    finally:
        c1.close()
        c2.close()
        srv.stop()


def test_double_claim_probe_spares_same_node_replay():
    """A client retrying a bind that already LANDED on the same node
    (the timeout-ambiguity heal) must NOT trip the double-claim probe —
    same-node re-binds fall through to the idempotent heal path."""
    api, b, svc, srv = _cell()
    cli = BinaryWireClient("127.0.0.1", srv.port).connect()
    try:
        p = _pod("healme")
        api.create("Pod", p)
        r1 = cli.bind(p.name, p.namespace, p.uid, "hollow-node-2",
                      snapshot_gen=None, idem_key="healme:1", pod=p)
        assert r1.kind == "ok"
        # fresh key, SAME node: a second scheduler converging on the
        # same placement (or a lost-ack retry) heals, not conflicts
        r2 = cli.bind(p.name, p.namespace, p.uid, "hollow-node-2",
                      snapshot_gen=None, idem_key="healme:2", pod=p)
        assert r2.kind == "ok"
        vars_ = svc.debug_snapshot()["vars"]
        assert vars_.get("counter.extender.bind_conflicts", 0) == 0
    finally:
        cli.close()
        srv.stop()


# -------------------------------------------- typed counters on 3 transports


def test_fence_conflict_counters_partition_on_all_transports():
    """Sum of bind_conflict_reason_* == bind_conflicts, with three
    distinct reasons seeded (double_claim, liveness, capacity), and the
    snapshot byte-identical through HTTP /debug/vars, binary STATS and
    the embedded debug_snapshot."""
    from kubernetes_tpu.server.extender import ExtenderHTTPServer

    api, b, svc, srv = _cell(n_nodes=16)
    http_srv = ExtenderHTTPServer(b)
    http_srv.start()
    cli = BinaryWireClient("127.0.0.1", srv.port).connect()
    try:
        p = _pod("part-a")
        api.create("Pod", p)
        assert cli.bind(p.name, p.namespace, p.uid, "hollow-node-0",
                        snapshot_gen=None, idem_key="pa:1",
                        pod=p).kind == "ok"
        # double_claim: fresh key, different node
        r = cli.bind(p.name, p.namespace, p.uid, "hollow-node-1",
                     snapshot_gen=None, idem_key="pa:2", pod=p)
        assert r.kind == "conflict" and "double-claim" in r.error
        # liveness: the target node does not exist
        q = _pod("part-b")
        r = cli.bind(q.name, q.namespace, q.uid, "ghost-node",
                     snapshot_gen=None, idem_key="pb:1", pod=q)
        assert r.kind == "conflict" and "unknown" in r.error
        # capacity: a pod no node can hold
        big = make_pod("part-c", cpu=10**9, memory=1 << 50)
        r = cli.bind(big.name, big.namespace, big.uid, "hollow-node-2",
                     snapshot_gen=None, idem_key="pc:1", pod=big)
        assert r.kind == "conflict" and "insufficient" in r.error

        import http.client
        conn = http.client.HTTPConnection("127.0.0.1", http_srv.port,
                                          timeout=15)
        try:
            conn.request("GET", "/debug/vars")
            hv = json.loads(conn.getresponse().read())
        finally:
            conn.close()
        bv = cli.stats()["vars"]
        ev = svc.debug_snapshot()["vars"]
        assert hv == bv == ev  # transport parity, byte-identical
        total = ev["counter.extender.bind_conflicts"]
        by_reason = {nm: ev.get(
            f"counter.extender.bind_conflict_reason_{nm}", 0)
            for nm in pt.REASON_NAMES}
        assert total == 3
        assert sum(by_reason.values()) == total  # exact partition
        assert by_reason["double_claim"] == 1
        assert by_reason["liveness"] == 1
        assert by_reason["capacity"] == 1
    finally:
        cli.close()
        http_srv.stop()
        srv.stop()


def test_wire_fence_conflict_lands_in_ring_as_typed_instant():
    """With the flight recorder armed, a wire fence conflict records a
    FENCE_REQUEUE with wave=-1 (no wave owns it) carrying the typed
    reason code — the hook the perfetto instants render from."""
    from kubernetes_tpu.observability.recorder import RECORDER

    api, b, svc, srv = _cell(n_nodes=8)
    cli = BinaryWireClient("127.0.0.1", srv.port).connect()
    RECORDER.clear()
    RECORDER.enable()
    try:
        p = _pod("ring-a")
        api.create("Pod", p)
        assert cli.bind(p.name, p.namespace, p.uid, "hollow-node-0",
                        snapshot_gen=None, idem_key="ra:1",
                        pod=p).kind == "ok"
        r = cli.bind(p.name, p.namespace, p.uid, "hollow-node-1",
                     snapshot_gen=None, idem_key="ra:2", pod=p)
        assert r.kind == "conflict"
        evs = [e for e in RECORDER.snapshot()
               if e["kind"] == "fence_requeue" and e["wave"] < 0]
        assert len(evs) == 1
        assert evs[0]["b"] == pt.REASON_DOUBLE_CLAIM
    finally:
        RECORDER.disable()
        RECORDER.clear()
        cli.close()
        srv.stop()


# ------------------------------------------------------- reader-task leak


def test_clean_client_close_leaves_no_reader_tasks():
    """The satellite fix: a worker process closing its connection must
    not leak the server-side reader task. shutdown() on close delivers
    EOF now; the server discards the task; teardown cancels zero."""
    api, b, svc, srv = _cell(n_nodes=8, with_store=False)
    clients = [BinaryWireClient("127.0.0.1", srv.port).connect()
               for _ in range(3)]
    for c in clients:
        c.ping()
    assert len(srv._conn_tasks) == 3
    for c in clients:
        c.close()
    deadline = time.monotonic() + 5.0
    while srv._conn_tasks and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(srv._conn_tasks) == 0  # EOF drained every reader task
    srv.stop()
    assert srv.cancelled_conn_tasks == 0  # nothing left to cancel
    assert srv._thread is None or not srv._thread.is_alive()


def test_stop_cancels_straggler_reader_tasks():
    """Connections still open at stop() are cancelled and COUNTED —
    no pending task survives the loop (the pre-fix leak shape)."""
    api, b, svc, srv = _cell(n_nodes=8, with_store=False)
    clients = [BinaryWireClient("127.0.0.1", srv.port).connect()
               for _ in range(2)]
    for c in clients:
        c.ping()
    srv.stop()  # clients deliberately left open
    assert srv.cancelled_conn_tasks == 2
    assert srv._thread is None or not srv._thread.is_alive()
    for c in clients:
        c.close()


# ------------------------------------------------------------ process fleet


def test_process_fleet_racing_overlapped_pool_exactly_once():
    """The tentpole, end to end: TWO full scheduler processes (own
    interpreter, own evaluator, own bounded-stale snapshot) race a
    fully-overlapped pending pool through one shared cell. Store-truth
    audit: every pod binds exactly once, zero duplicates; the losers'
    refusals are TYPED double-claims; the server's conflict counters
    partition exactly."""
    from kubernetes_tpu.parallel.multiproc import run_process_fleet

    out = run_process_fleet(2, pods_per_worker=8, overlap=1.0,
                            n_nodes=48, relist_every=4,
                            pod_prefix="racetest", timeout_s=180.0)
    agg = out["agg"]
    assert agg["missing_workers"] == 0, agg
    assert agg["worker_failures"] == [], agg
    assert agg["duplicate_binds"] == 0  # the hard-zero bar
    # every contested pod landed exactly once at the store
    api = out["api"]
    bound_events: dict = {}
    for e in api._log:
        if e.kind == "Pod" and e.type == "MODIFIED" and e.obj.node_name \
                and e.obj.name.startswith("racetest"):
            bound_events.setdefault(e.obj.name, []).append(
                e.obj.node_name)
    assert len(bound_events) == 8
    assert all(len(v) == 1 for v in bound_events.values()), bound_events
    # both processes converged on the same placements (store is truth)
    workers = out["workers"]
    assert len(workers) == 2
    for w in workers:
        for key, node in w["bound"].items():
            name = key.split("/", 1)[1]
            assert bound_events[name] == [node], (key, node)
    # with 8 contested pods on 48 nodes, the losing process sees typed
    # double-claims (same-node coincidences are the only escape and
    # cannot cover all 8); the partition stays exact
    assert agg["double_claim"] >= 1
    reasons = agg["server_conflict_reasons"]
    assert sum(reasons.values()) == agg["server_bind_conflicts"]


# ----------------------------------------------------------------- perfetto


def test_perfetto_renders_wire_conflicts_and_process_lanes():
    """One lane per scheduler process; fence-conflict instants typed by
    reason name on the fence lane AND the process lane, all aligned to
    the ring's time base."""
    from kubernetes_tpu.observability.perfetto import (
        TID_PROC_BASE, add_process_lanes, build_chrome_trace)

    t0 = 1000.0
    ring = [
        {"kind": "dispatch", "wave": 1, "t": t0, "dur": 0.001,
         "a": 4, "b": 0},
        {"kind": "fence_requeue", "wave": -1, "t": t0 + 0.002,
         "dur": 0.0, "a": 1, "b": pt.REASON_DOUBLE_CLAIM},
        {"kind": "fence_requeue", "wave": 2, "t": t0 + 0.003,
         "dur": 0.0, "a": 2, "b": 1},
    ]
    trace = build_chrome_trace(ring)
    names = [e["name"] for e in trace["traceEvents"]]
    assert "fence-conflict:double_claim" in names  # typed wire instant
    assert "fence-requeue w2" in names  # wave-owned shape untouched
    workers = [
        {"worker": 0, "counts": {"binds": 2, "conflicts": 0},
         "events": [
             {"kind": "relist", "t": t0 + 0.001, "dur": 0.0005, "n": 0},
             {"kind": "bind", "t": t0 + 0.004, "dur": 0.001,
              "pod": "default/a", "node": "n0", "attempt": 0}]},
        {"worker": 1, "counts": {"binds": 1, "conflicts": 1},
         "events": [
             {"kind": "conflict", "t": t0 + 0.002, "dur": 0.0004,
              "pod": "default/a", "reason": "double_claim",
              "owner": "n0"}]},
    ]
    add_process_lanes(trace, workers, t_base=t0)
    evs = trace["traceEvents"]
    lane_meta = [e for e in evs if e["ph"] == "M"
                 and e.get("tid", 0) >= TID_PROC_BASE]
    assert len(lane_meta) == 2  # one lane per process
    assert "sched-proc 0" in lane_meta[0]["args"]["name"]
    w1_conflicts = [e for e in evs if e["ph"] == "i"
                    and e.get("tid") == TID_PROC_BASE + 1]
    assert w1_conflicts[0]["name"] == "fence-conflict:double_claim"
    # ring alignment: the worker instant sits at its monotonic offset
    # from the ring's t_base (2ms), comparable with the fence lane's
    assert w1_conflicts[0]["ts"] == pytest.approx(2000.0, abs=0.2)
    binds = [e for e in evs if e["ph"] == "X"
             and e.get("tid") == TID_PROC_BASE and e["name"] == "bind"]
    assert binds and binds[0]["dur"] == pytest.approx(1000.0, abs=0.2)


# -------------------------------------------------------------- trend gate


def test_trend_learns_multiproc_headline(tmp_path):
    """bench --trend gates the multiproc_N aggregate from r18 on:
    absent history tolerated, a past-band drop flags."""
    from kubernetes_tpu.observability import trend

    assert ("multiproc_pods_s", "multiproc agg/s", "up") \
        in trend.HEADLINE_METRICS

    def w(r, **metrics):
        doc = {"n": 1, "cmd": "python bench.py", "rc": 0,
               "parsed": metrics}
        (tmp_path / f"BENCH_r{r:02d}.json").write_text(json.dumps(doc))

    w(17, value=30000.0)  # pre-r18 round: no multiproc key
    w(18, value=30000.0, multiproc_pods_s=50.0)
    assert trend.find_regressions(trend.load_rounds(str(tmp_path))) == []
    w(19, value=30000.0, multiproc_pods_s=20.0)  # -60%: regression
    regs = trend.find_regressions(trend.load_rounds(str(tmp_path)))
    assert [g["metric"] for g in regs] == ["multiproc_pods_s"]


# ------------------------------------------- tsan-lite storm leg (ISSUE 19)


def test_lockcheck_leg_process_fleet_exactly_once(monkeypatch):
    """The two-process race with GRAFT_LOCKCHECK=1 end to end: spawned
    children inherit the knob through the environment, so EVERY lock on
    both sides is a checked twin. The exactly-once audit must hold
    unchanged, the parent-side checker must end silent, and a child-side
    guaranteed-self-deadlock raise would surface as a worker failure."""
    from kubernetes_tpu.analysis import lockcheck
    from kubernetes_tpu.parallel.multiproc import run_process_fleet

    monkeypatch.setenv("GRAFT_LOCKCHECK", "1")
    lockcheck.reset()
    out = run_process_fleet(2, pods_per_worker=6, overlap=1.0,
                            n_nodes=32, relist_every=3,
                            pod_prefix="lcfleet", timeout_s=180.0)
    agg = out["agg"]
    assert agg["missing_workers"] == 0, agg
    assert agg["worker_failures"] == [], agg
    assert agg["duplicate_binds"] == 0
    lockcheck.assert_clean()
