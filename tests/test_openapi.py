"""OpenAPI spec serving (server/openapi.py; ref
staging/src/k8s.io/apiserver/pkg/server/routes/openapi.go)."""

import http.client
import json

from kubernetes_tpu.api.extensions import CRDNames, CustomResourceDefinition
from kubernetes_tpu.server.apiserver import ApiServer, KIND_INFO
from kubernetes_tpu.server.openapi import build_spec
from kubernetes_tpu.server.rest_http import RestServer


def test_spec_covers_every_served_kind():
    api = ApiServer()
    spec = build_spec(api.store)
    assert spec["swagger"] == "2.0"
    for kind, (plural, cluster_scoped) in KIND_INFO.items():
        assert kind in spec["definitions"], kind
        base = f"/api/v1/{plural}" if cluster_scoped \
            else f"/api/v1/namespaces/{{namespace}}/{plural}"
        assert base in spec["paths"], kind
        assert base + "/{name}" in spec["paths"], kind
        assert "get" in spec["paths"][base]
        assert "delete" in spec["paths"][base + "/{name}"]
    # definitions reflect the live dataclasses, not hand-written copies
    pod = spec["definitions"]["Pod"]
    assert pod["properties"]["name"]["type"] == "string"
    assert pod["properties"]["containers"]["type"] == "array"
    assert pod["properties"]["priority"]["type"] == "integer"


def test_spec_includes_established_crds():
    api = ApiServer()
    api.store.create("CustomResourceDefinition", CustomResourceDefinition(
        name="widgets.example.com", group="example.com", version="v1",
        names=CRDNames(plural="widgets", kind="Widget",
                       singular="widget")))
    spec = build_spec(api.store)
    assert "Widget" in spec["definitions"]
    assert ("/apis/example.com/v1/namespaces/{namespace}/widgets"
            in spec["paths"])


def test_spec_served_over_http_at_both_paths():
    api = ApiServer()
    srv = RestServer(api)
    srv.start()
    try:
        for path in ("/openapi/v2", "/swagger.json"):
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=5)
            conn.request("GET", path)
            resp = conn.getresponse()
            assert resp.status == 200
            spec = json.loads(resp.read())
            assert spec["swagger"] == "2.0"
            assert "Pod" in spec["definitions"]
            conn.close()
    finally:
        srv.stop()
