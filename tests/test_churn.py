"""Churn-hardened always-on engine (ISSUE 8).

Pins the four robustness contracts the streaming loop gained:

- LIVENESS FENCE: a blind-wave row targeting a node deleted or cordoned
  mid-flight requeues WITH backoff instead of binding into a ghost —
  including the flush ordering (the dying event marks the node doomed
  BEFORE the pipeline flush harvests against the pre-event cache), and
  cache.remove_node forgetting assumed pods on the dead node.

- PROTEAN INVALIDATION: foreign binds/unbinds of plain pods — including
  anti-affinity TARGETS — patch exactly the forbid rows they touch
  (engine.aff_patch_rows) instead of rebuilding AffinityData wholesale
  (engine.aff_full_rebuilds stays at zero); label-row churn on nodes
  hosting nothing affinity-relevant patches too (label_patch_rows);
  events the patch CANNOT absorb exactly (an affinity-carrying foreign
  pod) still rebuild.

- DEGRADED MODE: sustained fence losses drop the loop to the classic
  synchronous round (no blind window to fence) and recover automatically
  — hysteresis pinned at the unit level, the classic fallback pinned
  end-to-end.

- HOUSEKEEPING UNDER LOAD: backoff gc + assume-TTL expiry run on a
  wall-clock cadence even when no round is ever empty (the saturated
  stream), so bookkeeping cannot grow without bound.

Plus the frozen churn-trace A/B: the SAME seeded churn schedule applied
at the same step boundaries to the streaming loop and the fixed-chunk
pipelined drain yields bit-identical placements — churn changes WHAT the
cluster is, never what a wave means.
"""

from __future__ import annotations

import time

from kubernetes_tpu.api.types import (
    Affinity,
    LabelSelector,
    PodAffinity,
    PodAffinityTerm,
    make_node,
    make_pod,
)
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.engine.streaming import ScheduleLoop
from kubernetes_tpu.models.hollow import load_cluster
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.testing.churn import ChurnInjector, ChurnOp
from kubernetes_tpu.utils.trace import COUNTERS
from tests.test_nodes import FakeClock

Gi = 1 << 30
HOSTNAME_KEY = "kubernetes.io/hostname"


def iso_pod(name, app="iso"):
    p = make_pod(name, cpu=100, memory=128 << 20, labels={"app": app})
    p.affinity = Affinity(pod_anti_affinity=PodAffinity(
        required_terms=[PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"app": app}),
            namespaces=[], topology_key=HOSTNAME_KEY)]))
    return p


def mk_nodes(n, cpu=4000):
    return [make_node(f"n{i:02d}", cpu=cpu, memory=16 * Gi, pods=110,
                      labels={HOSTNAME_KEY: f"n{i:02d}",
                              "zone": "z0" if i % 2 == 0 else "z1"})
            for i in range(n)]


def mk_sched(nodes, now=None):
    api = ApiServerLite()
    load_cluster(api, nodes, [])
    kw = {"record_events": False}
    if now is not None:
        kw["now"] = now
    s = Scheduler(api, **kw)
    s.start()
    return api, s


def placements(api, prefix=""):
    return {p.name: p.node_name for p in api.list("Pod")[0]
            if p.name.startswith(prefix)}


# ---------------------------------------------------------- liveness fence


def test_node_deleted_mid_wave_liveness_fence_requeues_every_row():
    """The ISSUE 8 acceptance shape: a wave is IN FLIGHT when its target
    node is deleted. The fence must requeue every affected row (not bind
    into the ghost), and the pods must land on surviving nodes."""
    api, s = mk_sched(mk_nodes(2))
    loop = s.pipeline(chunk=64)
    # 60 x 100m pods on 2 x 4000m nodes: the wave MUST spread over both
    for i in range(60):
        api.create("Pod", make_pod(f"lv-{i:03d}", cpu=100,
                                   memory=128 << 20))
    COUNTERS.reset()
    loop.step()                      # dispatch in flight, nothing harvested
    assert loop.inflight is not None
    api.delete("Node", "", "n01")    # the node dies mid-wave
    loop.step()                      # sync dooms n01, flushes, fences
    snap = COUNTERS.snapshot()
    assert snap.get("engine.liveness_fence_requeues", (0, 0))[0] > 0, snap
    # nothing may have bound into the ghost — at any point
    for p in api.list("Pod")[0]:
        assert p.node_name != "n01", f"{p.name} bound into deleted n01"
    # capacity for the requeued rows arrives; the backoff elapses; all bind
    api.create("Node", make_node("n99", cpu=4000, memory=16 * Gi, pods=110,
                                 labels={HOSTNAME_KEY: "n99"}))
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        loop.step()
        if loop.settled():
            break
        s.sync(wait=0.05)
    loop.close()
    where = placements(api, "lv-")
    assert len(where) == 60 and all(where.values()), where
    assert set(where.values()) <= {"n00", "n99"}, set(where.values())


def test_remove_node_forgets_and_returns_assumed_pods():
    """The cache-level audit: an assumed pod on a removed node is
    forgotten (no phantom capacity until TTL) and handed back for
    requeue; confirmed pods survive into the nodeless stub."""
    cache = SchedulerCache()
    cache.add_node(make_node("nx", cpu=4000, memory=16 * Gi))
    confirmed = make_pod("conf", cpu=100, node_name="nx")
    cache.add_pod(confirmed)
    assumed = make_pod("assumed", cpu=100)
    assumed.node_name = "nx"
    cache.assume_pod(assumed)
    assert cache.is_assumed(assumed.key())
    back = cache.remove_node("nx")
    assert [p.name for p in back] == ["assumed"]
    assert not cache.is_assumed(assumed.key())
    assert cache.pod_count() == 1  # only the confirmed pod remains
    infos = cache.node_infos()
    assert [q.name for q in infos["nx"].pods] == ["conf"]


def test_cordon_mid_wave_liveness_fence_requeues():
    """Cordon (spec.unschedulable) is a dying event for the in-flight
    wave exactly like deletion: rows targeting the cordoned node requeue
    with backoff and bind elsewhere."""
    api, s = mk_sched(mk_nodes(2))
    loop = s.pipeline(chunk=64)
    for i in range(60):
        api.create("Pod", make_pod(f"cd-{i:03d}", cpu=100,
                                   memory=128 << 20))
    COUNTERS.reset()
    loop.step()
    assert loop.inflight is not None
    node = api.get("Node", "", "n01")
    import dataclasses
    api.update("Node", dataclasses.replace(node, unschedulable=True))
    loop.step()
    snap = COUNTERS.snapshot()
    assert snap.get("engine.liveness_fence_requeues", (0, 0))[0] > 0, snap
    for p in api.list("Pod")[0]:
        assert p.node_name != "n01", f"{p.name} bound into cordoned n01"
    loop.close()


# ------------------------------------------------------ Protean invalidation


def warm_iso(api, s, loop, n=4):
    for i in range(n):
        api.create("Pod", iso_pod(f"warm-iso-{i}"))
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        loop.step()
        if loop.settled():
            return
        s.sync(wait=0.02)
    raise AssertionError("warm drain did not settle")


def drain_loop(s, loop, deadline_s=30):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        loop.step()
        if loop.settled():
            return
        s.sync(wait=0.02)
    raise AssertionError("drain did not settle")


def test_foreign_plain_bind_patches_not_rebuilds():
    """A PLAIN foreign pod labeled like an anti-affinity target binding
    onto a node is exactly one new forbidden source: the cached encoding
    PATCHES that row (aff_patch_rows), never rebuilds (aff_full_rebuilds
    == 0) — and the constraint HOLDS: the next iso pod avoids the node
    the foreign target landed on."""
    api, s = mk_sched(mk_nodes(8))
    loop = s.pipeline(chunk=64)
    warm_iso(api, s, loop, n=4)
    occupied = {p.node_name for p in api.list("Pod")[0]}
    free = sorted(set(f"n{i:02d}" for i in range(8)) - occupied)
    assert free
    COUNTERS.reset()
    # foreign bind: an already-bound pod arrives on the watch (a foreign
    # scheduler's work) with labels MATCHING the iso anti selector
    api.create("Pod", make_pod("foreign-tgt", cpu=100,
                               labels={"app": "iso"},
                               node_name=free[0]))
    api.create("Pod", iso_pod("iso-after-foreign"))
    drain_loop(s, loop)
    snap = COUNTERS.snapshot()
    assert snap.get("engine.aff_full_rebuilds", (0, 0))[0] == 0, snap
    assert snap.get("engine.aff_patch_rows", (0, 0))[0] >= 1, snap
    where = placements(api)
    assert where["iso-after-foreign"], where
    assert where["iso-after-foreign"] != free[0], \
        (where["iso-after-foreign"], free[0])
    assert where["iso-after-foreign"] not in occupied
    loop.close()


def test_foreign_unbind_patches_and_frees_the_node():
    """The foreign target leaving decrements the patched forbid count
    exactly — the freed node is placeable again, still without a rebuild."""
    api, s = mk_sched(mk_nodes(6, cpu=400))  # 4 pods per node by cpu
    loop = s.pipeline(chunk=64)
    warm_iso(api, s, loop, n=4)
    occupied = {p.node_name for p in api.list("Pod")[0]}
    free = sorted(set(f"n{i:02d}" for i in range(6)) - occupied)
    assert len(free) >= 2
    COUNTERS.reset()
    api.create("Pod", make_pod("foreign-tgt", cpu=100,
                               labels={"app": "iso"}, node_name=free[0]))
    api.create("Pod", iso_pod("iso-a"))
    drain_loop(s, loop)
    assert placements(api)["iso-a"] == free[1]  # only free[1] is legal
    api.delete("Pod", "default", "foreign-tgt")  # the target leaves
    api.create("Pod", iso_pod("iso-b"))
    drain_loop(s, loop)
    snap = COUNTERS.snapshot()
    assert snap.get("engine.aff_full_rebuilds", (0, 0))[0] == 0, snap
    assert snap.get("engine.aff_patch_rows", (0, 0))[0] >= 2, snap
    assert placements(api)["iso-b"] == free[0]  # freed exactly
    loop.close()


def test_foreign_affinity_carrier_forces_rebuild():
    """A foreign pod CARRYING anti-affinity is a potential symmetry
    source — its own terms bake into forbid_static, which no row patch
    can express. The encoding must rebuild, and the symmetry must hold
    against the rebuilt arrays."""
    api, s = mk_sched(mk_nodes(6))
    loop = s.pipeline(chunk=64)
    # warm with PLAIN pods labeled like a guard's target, so the
    # encoding exists and carries the 'tgt' class
    for i in range(3):
        api.create("Pod", make_pod(f"warm-tgt-{i}", cpu=100,
                                   labels={"app": "tgt"}))
    drain_loop(s, loop)
    COUNTERS.reset()
    guard = iso_pod("foreign-guard", app="tgt")
    guard.node_name = "n05"
    api.create("Pod", guard)  # bound foreign pod WITH anti-affinity
    api.create("Pod", make_pod("tgt-after", cpu=100,
                               labels={"app": "tgt"}))
    drain_loop(s, loop)
    snap = COUNTERS.snapshot()
    assert snap.get("engine.aff_full_rebuilds", (0, 0))[0] >= 1, snap
    # symmetry: the new target may not land beside the foreign guard
    assert placements(api)["tgt-after"] != "n05", placements(api)
    loop.close()


def test_relabel_of_unoccupied_node_patches_not_rebuilds():
    """Label-content churn on a node hosting nothing affinity-relevant
    re-derives just that ROW of the topology views (label_patch_rows);
    the selector side reads the refreshed labels either way."""
    import dataclasses
    api, s = mk_sched(mk_nodes(8))
    loop = s.pipeline(chunk=64)
    # intern the zone pairs via a selector class, and build an affinity
    # encoding via iso pods
    sel = make_pod("warm-sel", cpu=100)
    sel.node_selector = {"zone": "z0"}
    api.create("Pod", sel)
    warm_iso(api, s, loop, n=2)
    empty = sorted(set(f"n{i:02d}" for i in range(8))
                   - {p.node_name for p in api.list("Pod")[0]})
    assert empty
    COUNTERS.reset()
    node = api.get("Node", "", empty[0])
    api.update("Node", dataclasses.replace(
        node, labels=dict(node.labels, zone="z1" if
                          node.labels["zone"] == "z0" else "z0")))
    api.create("Pod", iso_pod("iso-after-relabel"))
    drain_loop(s, loop)
    snap = COUNTERS.snapshot()
    assert snap.get("engine.label_patch_rows", (0, 0))[0] >= 1, snap
    assert snap.get("engine.aff_full_rebuilds", (0, 0))[0] == 0, snap
    assert placements(api)["iso-after-relabel"], placements(api)
    loop.close()


# ------------------------------------------------------------ degraded mode


class _FakeEngine:
    wave_pad_floor = 0


class _FakeSched:
    def __init__(self):
        self.engine = _FakeEngine()
        self._pipeline = None
        self.pipeline_chunk = 4096


def test_degraded_mode_hysteresis_and_recovery():
    """Unit contract of the churn-health model: enter only after
    degrade_window CONSECUTIVE breached pod-ful steps (one bad wave must
    not flap the mode), idle steps freeze the window, recovery after
    recover_steps pod-ful classic steps."""
    loop = ScheduleLoop(_FakeSched(), budget_s=0.2, min_quantum=64,
                        max_quantum=256)
    loop.degrade_window = 3
    loop.recover_steps = 2

    def stats(bound, requeues):
        return {"bound": bound, "fence_requeued": requeues,
                "liveness_requeued": 0, "gang_requeued": 0}

    loop._note_health(stats(10, 90))
    loop._note_health(stats(10, 90))
    assert not loop.degraded          # 2 < window
    loop._note_health(stats(0, 0))    # idle: freezes, does not reset...
    loop._note_health(stats(90, 10))  # ...a healthy step DOES reset
    loop._note_health(stats(10, 90))
    loop._note_health(stats(10, 90))
    assert not loop.degraded
    loop._note_health(stats(10, 90))  # third consecutive: enter
    assert loop.degraded
    loop._note_health(stats(0, 0))    # idle: not a recovery step
    assert loop.degraded
    loop._note_health(stats(50, 0))
    loop._note_health(stats(50, 0))   # recover_steps pod-ful steps: exit
    assert not loop.degraded


def test_degraded_mode_classic_round_still_binds():
    """End-to-end: force the loop degraded and verify pods still bind
    through the classic synchronous fallback, the step is counted, and
    the mode recovers."""
    api, s = mk_sched(mk_nodes(4))
    loop = s.stream(budget_s=30.0, min_quantum=64, max_quantum=64)
    loop.degraded = True
    loop.recover_steps = 2
    COUNTERS.reset()
    for i in range(40):
        api.create("Pod", make_pod(f"dg-{i:03d}", cpu=100))
    total = {}
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        stats = loop.step()
        for k, v in stats.items():
            total[k] = total.get(k, 0) + v
        if loop.settled():
            break
        s.sync(wait=0.02)
    loop.close()
    where = placements(api, "dg-")
    assert len(where) == 40 and all(where.values()), where
    assert total.get("degraded_steps", 0) >= 1, total
    assert not loop.degraded  # recovered after the storm bound


# -------------------------------------------------- housekeeping under load


def test_housekeeping_runs_under_sustained_load():
    """A saturated stream never has an empty round — backoff stamps and
    assume-TTL expiry must still gc on the wall-clock cadence (ISSUE 8
    satellite: the empty-round gate starved them before)."""
    clock = FakeClock()
    api, s = mk_sched(mk_nodes(4), now=clock)
    loop = s.stream(budget_s=30.0, min_quantum=64, max_quantum=64)
    loop.gc_interval_s = 0.0  # every step, regardless of load
    # a stale backoff stamp for a pod long since bound
    s.queue.backoff.next_delay("ghost-pod")
    assert "ghost-pod" in s.queue.backoff._durations
    clock.t += 1000.0  # far past 2 * MAX_BACKOFF
    api.create("Pod", make_pod("hk-0", cpu=100))
    stats = loop.step()  # pod-ful step: housekeeping must run anyway
    assert stats["popped"] == 1, stats
    assert "ghost-pod" not in s.queue.backoff._durations
    loop.close()


# ------------------------------------------------- frozen churn-trace A/B


TRACE = (
    # (arrival group size, churn ops applied BEFORE the step)
    (37, ()),
    (48, (ChurnOp(0.0, "kill", node="n03"),)),
    (25, (ChurnOp(0.0, "respawn", node="n03"),
          ChurnOp(0.0, "cordon", node="n05"),
          ChurnOp(0.0, "evict", evict_slot=7),)),
    (40, (ChurnOp(0.0, "uncordon", node="n05"),
          ChurnOp(0.0, "relabel", node="n06", zone="zone-b"),
          ChurnOp(0.0, "evict", evict_slot=3),)),
)


def _run_trace(streaming: bool):
    clock = FakeClock()
    api, s = mk_sched(mk_nodes(16), now=clock)
    if streaming:
        loop = s.stream(budget_s=30.0, min_quantum=64, max_quantum=64)
    else:
        loop = s.pipeline(chunk=64)
    injector = ChurnInjector(api, [])
    gi = 0
    for group, ops in TRACE:
        injector.schedule = list(ops)
        injector._next = 0
        injector.apply_until(0.0)
        for i in range(group):
            kind = "iso" if i % 10 == 0 else "web"
            if kind == "iso":
                p = iso_pod(f"tr-g{gi}-iso-{i:03d}")
            else:
                p = make_pod(f"tr-g{gi}-web-{i:03d}", cpu=100,
                             memory=128 << 20)
            api.create("Pod", p)
        loop.step()
        gi += 1
    # make every backoff deterministic-ready before the final drain: the
    # fake clock jumps past MAX_BACKOFF, so both sides promote the same
    # deferred set in the same order
    clock.t += 120.0
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        loop.step()
        if loop.settled():
            break
        clock.t += 120.0
        s.sync(wait=0.02)
    assert loop.settled(), "trace drain did not settle"
    loop.close()
    return {p.name: p.node_name for p in api.list("Pod")[0]}


def test_frozen_churn_trace_streaming_equals_pipelined():
    """The ISSUE 8 A/B: the same frozen arrival + churn trace consumed by
    the streaming loop and by the fixed-chunk pipelined drain — same
    quantum, same step boundaries, same seeded churn ops — places every
    surviving pod on the SAME node. Churn (node kills, cordons, relabels,
    evictions, liveness requeues) changes what the cluster IS, never what
    a wave means."""
    pa = _run_trace(streaming=True)
    pb = _run_trace(streaming=False)
    assert set(pa) == set(pb), set(pa) ^ set(pb)
    diff = {k: (pa[k], pb[k]) for k in pa if pa[k] != pb[k]}
    assert not diff, diff
    assert all(v for v in pa.values()), \
        [k for k, v in pa.items() if not v]


# ------------------------------------------------------- rolling updates


def test_rolling_update_respects_bounds_and_binds_exactly_once():
    """Deployment-shaped rolling update (ISSUE 18): the evict-and-
    recreate controller stepped deterministically against store truth,
    scheduler drained between steps. The surge bound (never more than
    replicas + max_surge pods of the app), the availability bound
    (never fewer than replicas - max_unavailable bound pods), full
    completion, and the store-truth exactly-once audit (every
    replacement bound exactly once, zero ghost residue in the cache)
    must all hold."""
    from kubernetes_tpu.testing.churn import (
        RollingUpdateConfig,
        RollingUpdateDriver,
        audit_cache_vs_store,
        audit_store_transitions,
    )

    replicas, surge, unavail = 12, 3, 3
    api = ApiServerLite()
    load_cluster(api, mk_nodes(8), [])
    s = Scheduler(api, record_events=False)
    s.start()

    def web_pod(rev, i):
        return make_pod(f"web-{rev}-{i:03d}", cpu=100,
                        memory=128 << 20,
                        labels={"app": "web", "rev": rev})

    for i in range(replicas):
        api.create("Pod", web_pod("1", i))
    assert s.run_until_drained()["bound"] == replicas

    cfg = RollingUpdateConfig(replicas=replicas, max_surge=surge,
                              max_unavailable=unavail)
    driver = RollingUpdateDriver(api, cfg, lambda i: web_pod("2", i))
    steps = 0
    while not driver.step():
        s.run_until_drained()
        steps += 1
        assert steps < 60, f"rolling update did not converge: " \
            f"{driver.bounds_report()}"
    rep = driver.bounds_report()
    assert rep["surge_respected"], rep
    assert rep["unavailable_respected"], rep
    # a bounded update is necessarily multi-step: with surge=3 it takes
    # at least replicas/surge controller passes
    assert steps >= replicas // surge, (steps, rep)
    assert rep["evicted"] == replicas and rep["created"] == replicas
    # end state: only new-revision pods, all bound
    pods = api.list("Pod")[0]
    web = [p for p in pods if p.labels.get("app") == "web"]
    assert len(web) == replicas
    assert all(p.labels["rev"] == "2" and p.node_name for p in web)
    # store-truth audits: every replacement bound exactly once, zero
    # ghost residue in the scheduler cache
    trans = audit_store_transitions(api)
    repl = {k for k in driver.replacement_keys}
    assert all(trans["binds"].get(k, 0) == 1 for k in repl), trans["binds"]
    assert audit_cache_vs_store(s, api) == []


def test_diurnal_rate_curve_shape():
    from kubernetes_tpu.testing.churn import diurnal_rate

    rate = diurnal_rate(1000.0, amp=0.5, period_s=60.0)
    assert abs(rate(0.0) - 1000.0) < 1e-6          # mean at phase 0
    assert abs(rate(15.0) - 1500.0) < 1e-6         # peak at quarter period
    assert abs(rate(45.0) - 500.0) < 1e-6          # trough at 3/4 period
    # never negative, even at amp > 1
    deep = diurnal_rate(100.0, amp=1.5, period_s=10.0)
    assert min(deep(t / 10.0) for t in range(100)) >= 0.0
