"""Repo hygiene — the cmd/importverifier + cmd/clicheck analog.

The reference ships small verifier binaries run in CI (importverifier:
no forbidden import edges; clicheck: every CLI command documented).
Equivalents here:
- every module under kubernetes_tpu imports cleanly (dead imports and
  circular-import regressions fail fast, not at first use in prod);
- no module opens or reads the read-only reference tree at runtime
  (file:line strings in docstrings are parity citations, not code);
- every ktctl cmd_* verb is reachable through run()'s dispatch;
- the wire KIND_REGISTRY and the apiserver KIND_INFO agree on the kinds
  both layers must serve.
"""

import importlib
import pathlib
import pkgutil

import kubernetes_tpu

ROOT = pathlib.Path(kubernetes_tpu.__file__).parent


def test_every_module_imports():
    failures = []
    for mod in pkgutil.walk_packages(kubernetes_tpu.__path__,
                                     prefix="kubernetes_tpu."):
        if mod.name.endswith("__main__"):
            continue
        try:
            importlib.import_module(mod.name)
        except Exception as e:  # noqa: BLE001 - collecting all failures
            failures.append(f"{mod.name}: {type(e).__name__}: {e}")
    assert not failures, failures


def test_no_runtime_reads_of_the_reference_tree():
    offenders = []
    for path in ROOT.rglob("*.py"):
        for i, line in enumerate(path.read_text().splitlines(), 1):
            stripped = line.strip()
            if stripped.startswith("#"):
                continue
            if "/root/reference" in stripped:
                offenders.append(f"{path}:{i}: {stripped[:80]}")
    assert not offenders, offenders


def test_ktctl_verbs_dispatchable():
    import io

    from kubernetes_tpu.cli.ktctl import Ktctl
    from kubernetes_tpu.server.apiserver import ApiServer

    kt = Ktctl(ApiServer(), out=io.StringIO())
    verbs = [m[len("cmd_"):].replace("_", "-") for m in dir(kt)
             if m.startswith("cmd_")]
    assert len(verbs) >= 20
    for verb in verbs:
        assert getattr(kt, "cmd_" + verb.replace("-", "_"), None) \
            is not None


def test_wire_registry_covers_served_kinds():
    from kubernetes_tpu.api.wire import KIND_REGISTRY
    from kubernetes_tpu.server.apiserver import KIND_INFO

    # kinds the apiserver serves but the wire codec cannot carry would
    # break the REST facade on first touch
    missing = [k for k in KIND_INFO if k not in KIND_REGISTRY]
    assert not missing, missing
