"""The cluster-in-a-box e2e: every layer in one scenario.

The shape of test/e2e (framework.go creates the env, specs drive user
workflows): bootstrap with ktadm, join nodes over the token flow, run
the controller manager + scheduler + hollow kubelets, then act as a
user — apply a Deployment through ktctl, watch pods go Running, scale,
read logs through the kubelet API, and tear down — asserting the state
every layer should converge to.
"""

import io
import os

from kubernetes_tpu.api.types import make_node
from kubernetes_tpu.auth.authn import Credential
from kubernetes_tpu.cli.ktadm import KtAdm, ca_hash
from kubernetes_tpu.cli.ktctl import Ktctl
from kubernetes_tpu.client.informer import SharedInformerFactory
from kubernetes_tpu.controllers.deployment import DeploymentController
from kubernetes_tpu.controllers.replicaset import ReplicaSetController
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.nodes.kubelet import HollowKubelet

Mi = 1 << 20
Gi = 1 << 30


def test_cluster_in_a_box(tmp_path):
    # ---- control plane bootstrap (ktadm init) --------------------------
    adm = KtAdm(out=io.StringIO())
    cluster = adm.init(str(tmp_path / "cluster"))
    api = cluster.api

    # ---- two workers join over the bootstrap-token flow ----------------
    kubelets = {}
    for i in range(2):
        name = f"worker-{i}"
        cred = adm.join(cluster, name, cluster.token,
                        ca_cert_hash=ca_hash(cluster.ca_key))
        node = api.get("Node", "", name, cred=cluster.admin_cred)
        kubelets[name] = HollowKubelet(api.store, node)

    # ---- controllers + scheduler against the same store ----------------
    factory = SharedInformerFactory(api.store)
    dep_ctrl = DeploymentController(api.store, factory,
                                    record_events=False)
    rs_ctrl = ReplicaSetController(api.store, factory,
                                   record_events=False)
    factory.start()
    sched = Scheduler(api.store)
    sched.start()

    # warm the placement kernels at every batch-size bucket the
    # deployment's rounds can hit (1..4 pods round up to buckets 1/2/4),
    # then reset the metrics: the SLO below is measured the way the
    # reference measures it — dedicated latency pods against a RUNNING
    # cluster (metrics_util.go:389-396), never the first-ever compile
    from kubernetes_tpu.api.types import make_pod
    from kubernetes_tpu.utils.metrics import SchedulerMetrics
    # warmup pods carry the SAME label pair the measured deployment's pods
    # will (app=web): a fresh pair would grow the snapshot label vocab at
    # measure time and trigger a recompile inside the SLO window
    for burst in (1, 2, 4):
        for i in range(burst):
            # both label pairs the test will use later stay in-vocab
            app = "web" if i % 2 == 0 else "latency"
            api.store.create("Pod", make_pod(f"warmup-{burst}-{i}", cpu=1,
                                             labels={"app": app}))
        sched.run_until_drained()
        for i in range(burst):
            api.store.delete("Pod", "default", f"warmup-{burst}-{i}")
    sched.run_until_drained()  # drain the deletion events
    sched.metrics = SchedulerMetrics()

    # ---- user: apply a Deployment manifest through ktctl ---------------
    out = io.StringIO()
    kt = Ktctl(api, out=out, cred=cluster.admin_cred, kubelets=kubelets)
    manifest = tmp_path / "web.yaml"
    manifest.write_text("""
kind: Deployment
name: web
namespace: default
replicas: 4
selector:
  match_labels: {app: web}
template:
  name: ""
  namespace: default
  labels: {app: web}
  containers:
  - name: app
    requests: {cpu: 100, memory: 1048576}
  annotations:
    bench/log-lines: "booting\\nserving"
""")
    assert kt.run(["apply", "-f", str(manifest)]) == 0

    # ---- converge: controllers stamp pods, scheduler binds, kubelets run
    for _ in range(10):
        factory.step_all()
        dep_ctrl.pump()
        rs_ctrl.pump()
        sched.run_until_drained()
        for name, kl in kubelets.items():
            for p in api.store.list("Pod")[0]:
                if p.node_name == name:
                    kl.handle_pod(p)
            kl.workers.drain()
            kl.step()
        pods = [p for p in api.store.list("Pod")[0]
                if p.labels.get("app") == "web"]
        if len(pods) == 4 and all(p.node_name for p in pods):
            break
    pods = [p for p in api.store.list("Pod")[0]
            if p.labels.get("app") == "web"]
    assert len(pods) == 4
    assert all(p.node_name in kubelets for p in pods)
    # spread across both workers (SelectorSpread at work)
    assert len({p.node_name for p in pods}) == 2

    # ---- pod-startup SLO (e2e framework metrics_util.go:46,389-396:
    # p99 pod startup <= 5s), measured the way the reference measures it:
    # DEDICATED latency pods against the now-fully-RUNNING cluster (the
    # first deployment warmed every shape, including the RS-workload-
    # dependent spread arrays the scheduler first saw with it — a compile
    # inside the SLO window would measure the compiler, not the cluster)
    assert all(api.store.get("Pod", p.namespace, p.name).phase == "Running"
               for p in pods)
    sched.metrics = SchedulerMetrics()
    manifest2 = tmp_path / "latency.yaml"
    manifest2.write_text("""
kind: Deployment
name: latency
namespace: default
replicas: 4
selector:
  match_labels: {app: latency}
template:
  name: ""
  namespace: default
  labels: {app: latency}
  containers:
  - name: app
    requests: {cpu: 100, memory: 1048576}
""")
    assert kt.run(["apply", "-f", str(manifest2)]) == 0
    for _ in range(10):
        factory.step_all()
        dep_ctrl.pump()
        rs_ctrl.pump()
        sched.run_until_drained()
        if sched.metrics.create_to_bound.count >= 4:
            break
    c2b = sched.metrics.create_to_bound
    assert c2b.count >= 4
    assert c2b.percentile(99) <= 5.0
    assert kt.run(["delete", "deploy", "latency"]) == 0
    for _ in range(10):
        factory.step_all()
        dep_ctrl.pump()
        rs_ctrl.pump()
        if not [p for p in api.store.list("Pod")[0]
                if p.owner_name.startswith("latency") and not p.deleted]:
            break

    # ---- user: get with selectors, logs via the kubelet API ------------
    out.truncate(0), out.seek(0)
    assert kt.run(["get", "pods", "-l", "app=web", "-o", "name"]) == 0
    assert len(out.getvalue().split()) == 4
    out.truncate(0), out.seek(0)
    assert kt.run(["logs", pods[0].name, "--tail", "1"]) == 0
    assert out.getvalue().strip() == "serving"

    # ---- user: scale down; the stack converges again -------------------
    assert kt.run(["scale", "deploy", "web", "--replicas", "1"]) == 0
    for _ in range(10):
        factory.step_all()
        dep_ctrl.pump()
        rs_ctrl.pump()
        sched.run_until_drained()
        alive = [p for p in api.store.list("Pod")[0]
                 if p.labels.get("app") == "web" and not p.deleted]
        if len(alive) == 1:
            break
    assert len([p for p in api.store.list("Pod")[0]
                if p.labels.get("app") == "web" and not p.deleted]) == 1

    # ---- teardown: delete through the CLI; everything drains -----------
    assert kt.run(["delete", "deploy", "web"]) == 0
    for _ in range(10):
        factory.step_all()
        dep_ctrl.pump()
        rs_ctrl.pump()
        if not [p for p in api.store.list("Pod")[0]
                if p.labels.get("app") == "web" and not p.deleted]:
            break
    # the audit trail saw the whole session under real identities
    assert any(e.user == "kubernetes-admin" for e in api.audit_log)
