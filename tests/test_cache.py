"""SchedulerCache assume/confirm/forget/expire state machine tests —
modeled on the reference's cache_test.go (878 lines: TestAssumePodScheduled,
TestExpirePod, TestAddPodWillConfirm, TestForgetPod, ...)."""

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.state.cache import SchedulerCache
from tests.helpers import Gi


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def make_cache(ttl=30.0):
    clock = FakeClock()
    cache = SchedulerCache(ttl_seconds=ttl, now=clock)
    cache.add_node(make_node("n1"))
    cache.add_node(make_node("n2"))
    return cache, clock


def test_assume_adds_resources():
    cache, _ = make_cache()
    pod = make_pod("p1", cpu=1000, memory=1 * Gi)
    pod.node_name = "n1"
    cache.assume_pod(pod)
    infos = cache.node_infos()
    assert infos["n1"].requested.milli_cpu == 1000
    assert len(infos["n1"].pods) == 1
    assert cache.is_assumed("default/p1")


def test_expire_releases_assumed():
    cache, clock = make_cache(ttl=30.0)
    pod = make_pod("p1", cpu=1000)
    pod.node_name = "n1"
    cache.assume_pod(pod)
    cache.finish_binding(pod)
    clock.t = 31.0
    expired = cache.cleanup_assumed()
    assert expired == ["default/p1"]
    assert cache.node_infos()["n1"].requested.milli_cpu == 0


def test_unfinished_binding_never_expires():
    cache, clock = make_cache(ttl=30.0)
    pod = make_pod("p1", cpu=1000)
    pod.node_name = "n1"
    cache.assume_pod(pod)  # no finish_binding
    clock.t = 1e9
    assert cache.cleanup_assumed() == []
    assert cache.node_infos()["n1"].requested.milli_cpu == 1000


def test_add_confirms_assumed():
    cache, clock = make_cache(ttl=30.0)
    pod = make_pod("p1", cpu=1000)
    pod.node_name = "n1"
    cache.assume_pod(pod)
    cache.finish_binding(pod)
    cache.add_pod(pod)  # informer confirmation
    assert not cache.is_assumed("default/p1")
    clock.t = 1e9
    assert cache.cleanup_assumed() == []  # confirmed pods never expire
    assert cache.node_infos()["n1"].requested.milli_cpu == 1000


def test_add_moves_pod_when_bound_elsewhere():
    cache, _ = make_cache()
    pod = make_pod("p1", cpu=1000)
    pod.node_name = "n1"
    cache.assume_pod(pod)
    confirmed = make_pod("p1", cpu=1000)
    confirmed.node_name = "n2"  # another scheduler won
    cache.add_pod(confirmed)
    assert cache.node_infos()["n1"].requested.milli_cpu == 0
    assert cache.node_infos()["n2"].requested.milli_cpu == 1000


def test_forget_undoes_assume():
    cache, _ = make_cache()
    pod = make_pod("p1", cpu=1000)
    pod.node_name = "n1"
    cache.assume_pod(pod)
    cache.forget_pod(pod)
    assert cache.node_infos()["n1"].requested.milli_cpu == 0
    assert cache.pod_count() == 0


def test_remove_pod():
    cache, _ = make_cache()
    pod = make_pod("p1", cpu=500)
    pod.node_name = "n1"
    cache.add_pod(pod)
    cache.remove_pod(pod)
    assert cache.node_infos()["n1"].requested.milli_cpu == 0


def test_update_pod_moves_resources():
    cache, _ = make_cache()
    p_old = make_pod("p1", cpu=500)
    p_old.node_name = "n1"
    cache.add_pod(p_old)
    p_new = make_pod("p1", cpu=800)
    p_new.node_name = "n1"
    cache.update_pod(p_old, p_new)
    assert cache.node_infos()["n1"].requested.milli_cpu == 800


def test_generation_counters_drive_deltas():
    cache, _ = make_cache()
    g0 = cache.node_infos()["n1"].generation
    pod = make_pod("p1", cpu=100)
    pod.node_name = "n1"
    cache.add_pod(pod)
    infos = cache.node_infos()
    assert infos["n1"].generation > g0
    # untouched node unchanged
    assert infos["n2"].generation == cache.node_infos()["n2"].generation
