"""Cloud provider layer: the Disks surface + per-provider flavors.

Reference: pkg/cloudprovider (Interface: Instances/Zones/LoadBalancer/
Routes) extended with the disk-management calls the volume attachers
drive (providers/{gce,aws,azure} AttachDisk/DetachDisk). Pinned:
- single-writer attach (multi-attach errors), idempotent re-attach,
  per-node attachable-disk limits, delete-while-attached refused;
- provider flavors: Azure's tighter disk cap, OpenStack requiring
  pre-created Cinder volumes, vSphere exposing no LB/routes;
- the volumes Attacher/Detacher driving a cloud end-to-end.
"""

import pytest

from kubernetes_tpu.api.types import Volume, VolumeKind
from kubernetes_tpu.cloud.provider import (
    DiskError,
    FakeCloud,
    get_provider,
)
from kubernetes_tpu.volumes.plugins import VolumeHost, VolumeSpec
from kubernetes_tpu.volumes.drivers import GCEPDPlugin


def test_disk_lifecycle_and_multi_attach_guard():
    cloud = FakeCloud()
    cloud.create_disk("pd-1", size_gb=100)
    cloud.attach_disk("pd-1", "n1")
    cloud.attach_disk("pd-1", "n1")  # idempotent
    assert cloud.disks_attached("n1") == ["pd-1"]
    with pytest.raises(DiskError, match="already attached"):
        cloud.attach_disk("pd-1", "n2")
    with pytest.raises(DiskError, match="attached"):
        cloud.delete_disk("pd-1")
    # detach from the wrong node is a no-op; right node frees it
    cloud.detach_disk("pd-1", "n2")
    assert cloud.disks_attached("n1") == ["pd-1"]
    cloud.detach_disk("pd-1", "n1")
    assert cloud.disks_attached("n1") == []
    cloud.attach_disk("pd-1", "n2")  # now attachable elsewhere
    cloud.detach_disk("pd-1", "n2")
    cloud.delete_disk("pd-1")
    assert "pd-1" not in cloud.disks


def test_per_node_disk_limit():
    cloud = get_provider("azure-like")
    for i in range(cloud.max_disks_per_node):
        cloud.attach_disk(f"d{i}", "n1")
    with pytest.raises(DiskError, match="limit"):
        cloud.attach_disk("overflow", "n1")
    cloud.attach_disk("overflow", "n2")  # other nodes unaffected


def test_provider_flavors():
    os_cloud = get_provider("openstack-like")
    with pytest.raises(DiskError, match="does not exist"):
        os_cloud.attach_disk("vol-x", "n1")  # Cinder: create first
    os_cloud.create_disk("vol-x")
    os_cloud.attach_disk("vol-x", "n1")
    vs = get_provider("vsphere-like")
    assert not vs.has_load_balancer() and not vs.has_routes()
    assert vs.has_disks()
    az = get_provider("azure-like")
    st = az.ensure_load_balancer("default/svc", ["n1"])
    assert st.ingress_ip.startswith("20.0.0.")
    with pytest.raises(KeyError):
        get_provider("digitalocean-like")


def test_volume_attacher_drives_the_cloud():
    cloud = FakeCloud()
    host = VolumeHost(cloud=cloud, node_name="n1")
    plugin = GCEPDPlugin()
    spec = VolumeSpec(volume=Volume(name="data", kind=VolumeKind.GCE_PD,
                                    volume_id="pd-db"))
    dev = plugin.new_attacher(host).attach(spec, "n1")
    assert dev == "GCEPersistentDisk:pd-db"
    assert cloud.disks_attached("n1") == ["pd-db"]
    plugin.new_detacher(host).detach(dev, "n1")
    assert cloud.disks_attached("n1") == []


def test_attach_detach_controller_drives_cloud():
    """End to end: the controller's desired-state pass calls the cloud's
    AttachDisk/DetachDisk and refuses to record an attachment the cloud
    rejected (multi-attach guard surfaces as FailedAttachVolume)."""
    from kubernetes_tpu.api.types import make_node, make_pod
    from kubernetes_tpu.client.informer import SharedInformerFactory
    from kubernetes_tpu.controllers.cloudctrl import (
        ATTACHED_ANNOTATION,
        AttachDetachController,
    )
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite

    api = ApiServerLite()
    cloud = FakeCloud()
    for n in ("n1", "n2"):
        api.create("Node", make_node(n, cpu=1000, memory=1 << 31))
    factory = SharedInformerFactory(api)
    ctrl = AttachDetachController(api, factory, record_events=False,
                                  cloud=cloud)
    factory.start()
    p1 = make_pod("p1", cpu=10, memory=1 << 20)
    p1.node_name = "n1"
    p1.volumes = [Volume(name="v", kind=VolumeKind.GCE_PD,
                         volume_id="pd-shared")]
    api.create("Pod", p1)
    factory.step_all()
    ctrl.sync("n1")
    assert cloud.disks_attached("n1") == ["pd-shared"]
    assert "GCEPersistentDisk:pd-shared" in api.get(
        "Node", "", "n1").annotations[ATTACHED_ANNOTATION]
    # a second pod on ANOTHER node wants the same disk: the cloud refuses
    # the multi-attach and the controller must NOT record it
    p2 = make_pod("p2", cpu=10, memory=1 << 20)
    p2.node_name = "n2"
    p2.volumes = [Volume(name="v", kind=VolumeKind.GCE_PD,
                         volume_id="pd-shared")]
    api.create("Pod", p2)
    factory.step_all()
    # a direct sync raises to signal the rate-limited queue to RETRY the
    # refused attach (the queue absorbs this in the worker loop)
    with pytest.raises(RuntimeError, match="already attached"):
        ctrl.sync("n2")
    assert cloud.disks_attached("n2") == []
    assert ATTACHED_ANNOTATION not in api.get("Node", "", "n2").annotations
    # first pod leaves: detach happens on the cloud too
    api.delete("Pod", "default", "p1")
    factory.step_all()
    ctrl.sync("n1")
    assert cloud.disks_attached("n1") == []
    # and the second node can now attach on its next sync
    ctrl.sync("n2")
    assert cloud.disks_attached("n2") == ["pd-shared"]


def test_refused_attach_retries_through_the_queue():
    """Finding regression: a cloud-refused attach must be re-queued (the
    losing node gets the disk once the winner releases it, with no pod
    event ever landing on the loser)."""
    from kubernetes_tpu.api.types import make_node, make_pod
    from kubernetes_tpu.client.informer import SharedInformerFactory
    from kubernetes_tpu.controllers.cloudctrl import AttachDetachController
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite

    api = ApiServerLite()
    cloud = FakeCloud()
    for n in ("n1", "n2"):
        api.create("Node", make_node(n, cpu=1000, memory=1 << 31))
    factory = SharedInformerFactory(api)
    ctrl = AttachDetachController(api, factory, record_events=False,
                                  cloud=cloud)
    factory.start()
    for pname, node in (("p1", "n1"), ("p2", "n2")):
        p = make_pod(pname, cpu=10, memory=1 << 20)
        p.node_name = node
        p.volumes = [Volume(name="v", kind=VolumeKind.GCE_PD,
                            volume_id="pd-shared")]
        api.create("Pod", p)
    import time as _time

    factory.step_all()
    ctrl.pump()  # through the queue: n1 wins, n2 refused + requeued
    assert cloud.disks_attached("n1") == ["pd-shared"]
    # winner's pod goes away; its sync detaches
    api.delete("Pod", "default", "p1")
    factory.step_all()
    ctrl.pump()
    # the requeued n2 key eventually attaches WITHOUT any new n2 event
    # (rate-limited delay is 5ms-base exponential)
    deadline = _time.monotonic() + 5.0
    while _time.monotonic() < deadline:
        ctrl.pump()
        if cloud.disks_attached("n2") == ["pd-shared"]:
            break
        _time.sleep(0.02)
    assert cloud.disks_attached("n2") == ["pd-shared"]
