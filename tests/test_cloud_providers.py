"""Cloud provider layer: the Disks surface + per-provider flavors.

Reference: pkg/cloudprovider (Interface: Instances/Zones/LoadBalancer/
Routes) extended with the disk-management calls the volume attachers
drive (providers/{gce,aws,azure} AttachDisk/DetachDisk). Pinned:
- single-writer attach (multi-attach errors), idempotent re-attach,
  per-node attachable-disk limits, delete-while-attached refused;
- provider flavors: Azure's tighter disk cap, OpenStack requiring
  pre-created Cinder volumes, vSphere exposing no LB/routes;
- the volumes Attacher/Detacher driving a cloud end-to-end.
"""

import pytest

from kubernetes_tpu.api.types import Volume, VolumeKind
from kubernetes_tpu.cloud.provider import (
    DiskError,
    FakeCloud,
    get_provider,
)
from kubernetes_tpu.volumes.plugins import VolumeHost, VolumeSpec
from kubernetes_tpu.volumes.drivers import GCEPDPlugin


def test_disk_lifecycle_and_multi_attach_guard():
    cloud = FakeCloud()
    cloud.create_disk("pd-1", size_gb=100)
    cloud.attach_disk("pd-1", "n1")
    cloud.attach_disk("pd-1", "n1")  # idempotent
    assert cloud.disks_attached("n1") == ["pd-1"]
    with pytest.raises(DiskError, match="already attached"):
        cloud.attach_disk("pd-1", "n2")
    with pytest.raises(DiskError, match="attached"):
        cloud.delete_disk("pd-1")
    # detach from the wrong node is a no-op; right node frees it
    cloud.detach_disk("pd-1", "n2")
    assert cloud.disks_attached("n1") == ["pd-1"]
    cloud.detach_disk("pd-1", "n1")
    assert cloud.disks_attached("n1") == []
    cloud.attach_disk("pd-1", "n2")  # now attachable elsewhere
    cloud.detach_disk("pd-1", "n2")
    cloud.delete_disk("pd-1")
    assert "pd-1" not in cloud.disks


def test_per_node_disk_limit():
    cloud = get_provider("azure-like")
    for i in range(cloud.max_disks_per_node):
        cloud.attach_disk(f"d{i}", "n1")
    with pytest.raises(DiskError, match="limit"):
        cloud.attach_disk("overflow", "n1")
    cloud.attach_disk("overflow", "n2")  # other nodes unaffected


def test_provider_flavors():
    os_cloud = get_provider("openstack-like")
    with pytest.raises(DiskError, match="does not exist"):
        os_cloud.attach_disk("vol-x", "n1")  # Cinder: create first
    os_cloud.create_disk("vol-x")
    os_cloud.attach_disk("vol-x", "n1")
    vs = get_provider("vsphere-like")
    assert not vs.has_load_balancer() and not vs.has_routes()
    assert vs.has_disks()
    az = get_provider("azure-like")
    st = az.ensure_load_balancer("default/svc", ["n1"])
    assert st.ingress_ip.startswith("20.0.0.")
    with pytest.raises(KeyError):
        get_provider("digitalocean-like")


def test_volume_attacher_drives_the_cloud():
    cloud = FakeCloud()
    host = VolumeHost(cloud=cloud, node_name="n1")
    plugin = GCEPDPlugin()
    spec = VolumeSpec(volume=Volume(name="data", kind=VolumeKind.GCE_PD,
                                    volume_id="pd-db"))
    dev = plugin.new_attacher(host).attach(spec, "n1")
    assert dev == "GCEPersistentDisk:pd-db"
    assert cloud.disks_attached("n1") == ["pd-db"]
    plugin.new_detacher(host).detach(dev, "n1")
    assert cloud.disks_attached("n1") == []


def test_attach_detach_controller_drives_cloud():
    """End to end: the controller's desired-state pass calls the cloud's
    AttachDisk/DetachDisk and refuses to record an attachment the cloud
    rejected (multi-attach guard surfaces as FailedAttachVolume)."""
    from kubernetes_tpu.api.types import make_node, make_pod
    from kubernetes_tpu.client.informer import SharedInformerFactory
    from kubernetes_tpu.controllers.cloudctrl import (
        ATTACHED_ANNOTATION,
        AttachDetachController,
    )
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite

    api = ApiServerLite()
    cloud = FakeCloud()
    for n in ("n1", "n2"):
        api.create("Node", make_node(n, cpu=1000, memory=1 << 31))
    factory = SharedInformerFactory(api)
    ctrl = AttachDetachController(api, factory, record_events=False,
                                  cloud=cloud)
    factory.start()
    p1 = make_pod("p1", cpu=10, memory=1 << 20)
    p1.node_name = "n1"
    p1.volumes = [Volume(name="v", kind=VolumeKind.GCE_PD,
                         volume_id="pd-shared")]
    api.create("Pod", p1)
    factory.step_all()
    ctrl.sync("n1")
    assert cloud.disks_attached("n1") == ["pd-shared"]
    assert "GCEPersistentDisk:pd-shared" in api.get(
        "Node", "", "n1").annotations[ATTACHED_ANNOTATION]
    # a second pod on ANOTHER node wants the same disk: the cloud refuses
    # the multi-attach and the controller must NOT record it
    p2 = make_pod("p2", cpu=10, memory=1 << 20)
    p2.node_name = "n2"
    p2.volumes = [Volume(name="v", kind=VolumeKind.GCE_PD,
                         volume_id="pd-shared")]
    api.create("Pod", p2)
    factory.step_all()
    # a direct sync raises to signal the rate-limited queue to RETRY the
    # refused attach (the queue absorbs this in the worker loop)
    with pytest.raises(RuntimeError, match="already attached"):
        ctrl.sync("n2")
    assert cloud.disks_attached("n2") == []
    assert ATTACHED_ANNOTATION not in api.get("Node", "", "n2").annotations
    # first pod leaves: detach happens on the cloud too
    api.delete("Pod", "default", "p1")
    factory.step_all()
    ctrl.sync("n1")
    assert cloud.disks_attached("n1") == []
    # and the second node can now attach on its next sync
    ctrl.sync("n2")
    assert cloud.disks_attached("n2") == ["pd-shared"]


def test_refused_attach_retries_through_the_queue():
    """Finding regression: a cloud-refused attach must be re-queued (the
    losing node gets the disk once the winner releases it, with no pod
    event ever landing on the loser)."""
    from kubernetes_tpu.api.types import make_node, make_pod
    from kubernetes_tpu.client.informer import SharedInformerFactory
    from kubernetes_tpu.controllers.cloudctrl import AttachDetachController
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite

    api = ApiServerLite()
    cloud = FakeCloud()
    for n in ("n1", "n2"):
        api.create("Node", make_node(n, cpu=1000, memory=1 << 31))
    factory = SharedInformerFactory(api)
    ctrl = AttachDetachController(api, factory, record_events=False,
                                  cloud=cloud)
    factory.start()
    for pname, node in (("p1", "n1"), ("p2", "n2")):
        p = make_pod(pname, cpu=10, memory=1 << 20)
        p.node_name = node
        p.volumes = [Volume(name="v", kind=VolumeKind.GCE_PD,
                            volume_id="pd-shared")]
        api.create("Pod", p)
    import time as _time

    factory.step_all()
    ctrl.pump()  # through the queue: n1 wins, n2 refused + requeued
    assert cloud.disks_attached("n1") == ["pd-shared"]
    # winner's pod goes away; its sync detaches
    api.delete("Pod", "default", "p1")
    factory.step_all()
    ctrl.pump()
    # the requeued n2 key eventually attaches WITHOUT any new n2 event
    # (rate-limited delay is 5ms-base exponential)
    deadline = _time.monotonic() + 5.0
    while _time.monotonic() < deadline:
        ctrl.pump()
        if cloud.disks_attached("n2") == ["pd-shared"]:
            break
        _time.sleep(0.02)
    assert cloud.disks_attached("n2") == ["pd-shared"]


def _pv_rig():
    from kubernetes_tpu.api.cluster import StorageClass
    from kubernetes_tpu.client.informer import SharedInformerFactory
    from kubernetes_tpu.controllers.cloudctrl import PersistentVolumeBinder
    from kubernetes_tpu.server.apiserver_lite import ApiServerLite

    api = ApiServerLite()
    api.create("StorageClass", StorageClass(
        "fast", provisioner="kubernetes.io/gce-pd", is_default=True))
    api.create("StorageClass", StorageClass(
        "manual", provisioner="kubernetes.io/no-provisioner",
        reclaim_policy="Retain"))
    factory = SharedInformerFactory(api)
    binder = PersistentVolumeBinder(api, factory, record_events=False)
    factory.start()
    return api, factory, binder


def test_dynamic_provisioning_and_reclaim():
    """pv_controller provisionClaim + reclaimVolume: a classed claim with
    no matching PV gets one minted by the class's provisioner, binds on
    the requeue, and the PV is deleted when the claim goes away."""
    from kubernetes_tpu.api.types import PersistentVolumeClaim
    from kubernetes_tpu.controllers.cloudctrl import CLASS_ANNOTATION

    api, factory, binder = _pv_rig()
    api.create("PersistentVolumeClaim", PersistentVolumeClaim(
        "data", "default", capacity=1 << 30,
        annotations={CLASS_ANNOTATION: "fast"}))
    factory.step_all()
    binder.pump()
    factory.step_all()
    binder.pump()  # the provisioned PV's ADDED event requeues the claim
    pvc = api.get("PersistentVolumeClaim", "default", "data")
    assert pvc.volume_name == "pvc-1eb304af-data"
    pv = api.get("PersistentVolume", "", "pvc-1eb304af-data")
    assert pv.capacity == 1 << 30
    assert pv.annotations[CLASS_ANNOTATION] == "fast"
    assert pv.source.kind.value == "GCEPersistentDisk"
    # claim deleted -> reclaim Delete removes the provisioned PV
    api.delete("PersistentVolumeClaim", "default", "data")
    factory.step_all()
    binder.pump()
    import pytest as _pytest

    from kubernetes_tpu.server.apiserver_lite import NotFound
    with _pytest.raises(NotFound):
        api.get("PersistentVolume", "", "pvc-1eb304af-data")


def test_class_matching_and_no_provisioner():
    """A classed claim must not bind a classless PV; no-provisioner
    classes wait for a manually created same-class PV (and Retain keeps
    the PV on claim deletion)."""
    from kubernetes_tpu.api.types import (
        PersistentVolume,
        PersistentVolumeClaim,
        Volume,
    )
    from kubernetes_tpu.controllers.cloudctrl import CLASS_ANNOTATION

    api, factory, binder = _pv_rig()
    # a classless PV big enough for the claim — must NOT be taken
    api.create("PersistentVolume", PersistentVolume(
        "classless", capacity=10 << 30, source=Volume(name="classless")))
    api.create("PersistentVolumeClaim", PersistentVolumeClaim(
        "data", "default", capacity=1 << 30,
        annotations={CLASS_ANNOTATION: "manual"}))
    factory.step_all()
    binder.pump()
    pvc = api.get("PersistentVolumeClaim", "default", "data")
    assert pvc.volume_name == ""  # no same-class PV, no provisioner
    # operator creates a manual-class PV: the claim binds it
    api.create("PersistentVolume", PersistentVolume(
        "manual-1", capacity=2 << 30, source=Volume(name="manual-1"),
        annotations={CLASS_ANNOTATION: "manual"}))
    factory.step_all()
    binder.pump()
    assert api.get("PersistentVolumeClaim", "default",
                   "data").volume_name == "manual-1"
    # Retain: claim deletion keeps the PV
    api.delete("PersistentVolumeClaim", "default", "data")
    factory.step_all()
    binder.pump()
    assert api.get("PersistentVolume", "", "manual-1").name == "manual-1"


def test_default_class_admission_annotates_pvc():
    """The StorageClassDefault plugin (now that PVCs carry annotations):
    a class-less claim created through the chain gets the default class
    and dynamic provisioning kicks in."""
    from kubernetes_tpu.api.cluster import StorageClass
    from kubernetes_tpu.api.types import PersistentVolumeClaim
    from kubernetes_tpu.api.workloads import Namespace
    from kubernetes_tpu.controllers.cloudctrl import CLASS_ANNOTATION
    from kubernetes_tpu.server.apiserver import ApiServer

    api = ApiServer()
    api.store.create("Namespace", Namespace("default"))
    api.store.create("StorageClass", StorageClass(
        "fast", provisioner="kubernetes.io/gce-pd", is_default=True))
    api.create("PersistentVolumeClaim", PersistentVolumeClaim(
        "data", "default", capacity=1 << 20))
    got = api.get("PersistentVolumeClaim", "default", "data")
    assert got.annotations[CLASS_ANNOTATION] == "fast"


def test_reclaim_spares_rebound_pv():
    """Finding regression: a PV rebound by another claim between the
    delete and the reclaim pass must NOT be deleted."""
    from kubernetes_tpu.api.types import PersistentVolumeClaim
    from kubernetes_tpu.controllers.cloudctrl import CLASS_ANNOTATION

    api, factory, binder = _pv_rig()
    api.create("PersistentVolumeClaim", PersistentVolumeClaim(
        "a", "default", capacity=1 << 20,
        annotations={CLASS_ANNOTATION: "fast"}))
    factory.step_all(); binder.pump()
    factory.step_all(); binder.pump()
    pv_name = api.get("PersistentVolumeClaim", "default", "a").volume_name
    assert pv_name
    # claim a deleted; claim b binds the same PV BEFORE the reclaim runs
    api.delete("PersistentVolumeClaim", "default", "a")
    api.create("PersistentVolumeClaim", PersistentVolumeClaim(
        "b", "default", capacity=1 << 20,
        annotations={CLASS_ANNOTATION: "fast"}))
    factory.step_all()
    binder.sync("default/b")          # b binds the freed PV
    assert api.get("PersistentVolumeClaim", "default",
                   "b").volume_name == pv_name
    binder.pump()                     # the queued reclaim:default/a runs
    assert api.get("PersistentVolume", "", pv_name).name == pv_name
