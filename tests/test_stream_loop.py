"""Always-on incremental engine (ISSUE 7): the streaming micro-wave loop.

Pins the three contracts that make the arrival stream the headline
instead of the drain:

- the frozen-arrival-trace A/B: streaming micro-waves produce BIT-
  IDENTICAL placements to the fixed-chunk pipelined drain over the same
  admission boundaries — admission control changes WHEN waves run, never
  what a wave means (same discipline as the PR-2 pipelined==sequential
  and PR-5 gang A/Bs);
- the delta-only invariant: while the loop is live, span counters prove
  zero re-tensorization (encoding reuse only), zero full snapshot walks
  (hinted refresh only), and every fence-accepted assume riding the
  raw-delta fold (snapshot.apply_assume_delta) — the Firmament property
  BENCH_r09 showed the drain-shaped engine did NOT have under arrivals;
- quantum adaptation: the admission cap doubles only on consecutive
  saturated under-budget waves, halves the moment latency crosses the
  budget, and never leaves [min_quantum, max_quantum].

Plus the tier-1-fast arrival smoke (ISSUE 7 satellite): a few-second
offered stream on a small cluster must SUSTAIN the offered rate with a
loose create->bound p99 bound, so a streaming regression surfaces
without running the full bench.
"""

from __future__ import annotations

import time

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.engine.scheduler import Scheduler
from kubernetes_tpu.engine.streaming import ScheduleLoop
from kubernetes_tpu.models.hollow import PROFILES, hollow_nodes, load_cluster
from kubernetes_tpu.server.apiserver_lite import ApiServerLite
from kubernetes_tpu.utils.trace import COUNTERS

Gi = 1 << 30

# a ragged arrival trace: group sizes deliberately non-bucket-aligned so
# the pad-floor machinery (not luck) is what keeps shapes stable
TRACE = (37, 128, 5, 96, 64, 111)


def mk_sched(n_nodes=64):
    api = ApiServerLite()
    load_cluster(api, hollow_nodes(n_nodes), [])
    s = Scheduler(api, record_events=False)
    s.start()
    return api, s


def feed(api, group, tag):
    pods = PROFILES["density"](group)
    for p in pods:
        p.name = f"{tag}-{p.name}"
        api.create("Pod", p)


def placements(api):
    return {p.name: p.node_name for p in api.list("Pod")[0]}


def drain_idle(sched, loop):
    loop.drain()  # the loop's shared quiesce predicate (settled())


# ------------------------------------------------------- frozen-trace A/B


def test_frozen_trace_streaming_equals_pipelined_drain():
    """The ISSUE 7 A/B: the same frozen arrival trace consumed (a) by the
    streaming loop (budget admission, micro-wave quantum) and (b) by the
    fixed-chunk pipelined drain must place every pod on the SAME node.
    Both admit one trace group per step (group sizes stay under the
    quantum/chunk), so the wave boundaries — and therefore the RR draws,
    the blind windows, and the fence decisions — are identical by
    construction; the test pins that the admission-control layer adds
    nothing else."""
    quantum = 128  # >= max(TRACE): one step consumes one group exactly

    # (a) streaming: latency budget generous so adaptation never shrinks
    # the cap below a group mid-trace
    api_a, s_a = mk_sched()
    loop = s_a.stream(budget_s=30.0, min_quantum=quantum,
                      max_quantum=quantum)
    for gi, group in enumerate(TRACE):
        feed(api_a, group, f"g{gi}")
        loop.step()
    drain_idle(s_a, loop)
    loop.close()

    # (b) the pipelined drain, same trace, same chunk (=> same pad floor)
    api_b, s_b = mk_sched()
    pipe = s_b.pipeline(chunk=quantum)
    for gi, group in enumerate(TRACE):
        feed(api_b, group, f"g{gi}")
        pipe.step()
    drain_idle(s_b, pipe)
    pipe.close()

    pa, pb = placements(api_a), placements(api_b)
    assert pa == pb, {k: (pa[k], pb[k]) for k in pa if pa[k] != pb[k]}
    assert all(v for v in pa.values()), "trace must fully bind"
    assert len(pa) == sum(TRACE)


# ----------------------------------------------------- delta-only invariant


def test_stream_delta_only_invariants():
    """While the loop is live, between micro-waves ONLY the delta touches
    the device (ISSUE 7 tentpole): encoding reuse (zero ClassBatch/
    AffinityData rebuilds), hinted refresh (zero full generation scans,
    zero shape rebuilds), and raw-delta assume folds for every bound pod.
    This is the counter-proof that the warm path is the ONLY path —
    the regression BENCH_r09 exposed (arrival stream going cold between
    rounds) trips these exact counters."""
    api, s = mk_sched()
    loop = s.stream(budget_s=30.0, min_quantum=128, max_quantum=128)
    feed(api, 128, "warm")  # warm: compiles + builds the encoding
    loop.step()
    drain_idle(s, loop)

    COUNTERS.reset()
    groups = (96, 128, 57)
    for gi, group in enumerate(groups):
        feed(api, group, f"live{gi}")
        loop.step()
    drain_idle(s, loop)
    loop.close()
    snap = COUNTERS.snapshot()

    def cnt(name):
        return snap.get(name, (0, 0.0))[0]

    bound = sum(groups)
    assert {p.name: p.node_name
            for p in api.list("Pod")[0] if p.name.startswith("live")} \
        and all(p.node_name for p in api.list("Pod")[0])
    # zero re-tensorization: the cached class encoding serves every wave
    assert cnt("engine.wave_encode_build") == 0, snap
    assert cnt("engine.wave_encode_reuse") >= len(groups)
    # zero full snapshot walks: the owner's dirty notes cover everything
    assert cnt("snapshot.refresh_scan") == 0, snap
    assert cnt("snapshot.refresh_rebuild") == 0, snap
    assert cnt("snapshot.refresh_hinted") >= len(groups)
    # every fence-accepted assume rode the raw-delta fold, none walked
    assert cnt("snapshot.assume_delta_rows") == bound, snap
    # one fused dispatch per micro-wave
    assert cnt("engine.wave_dispatch") == len(groups), snap


# ------------------------------------------------------ quantum adaptation


class _FakeEngine:
    wave_pad_floor = 0


class _FakeSched:
    def __init__(self):
        self.engine = _FakeEngine()
        self._pipeline = None
        self.pipeline_chunk = 4096


class _FakeHandle:
    def __init__(self, n, latency):
        self.pods = [None] * n
        self.pop_ts = time.monotonic() - latency


def test_quantum_adaptation_bounds_and_hysteresis():
    """Unit contract of the admission model: grow only after TWO
    consecutive saturated waves well under budget (one lucky wave must
    not mint a compiled shape), shrink immediately when the EWMA crosses
    the budget, clamp to [min_quantum, max_quantum]."""
    s = _FakeSched()
    loop = ScheduleLoop(s, budget_s=0.2, min_quantum=64, max_quantum=256)
    assert loop.quantum == 64
    assert s.engine.wave_pad_floor == 64  # the shape-ladder floor

    # one fast full wave: no growth yet (hysteresis)
    loop._observe_wave(_FakeHandle(64, 0.01))
    assert loop.quantum == 64
    # second consecutive: grows
    loop._observe_wave(_FakeHandle(64, 0.01))
    assert loop.quantum == 128
    # a partial wave resets the streak
    loop._observe_wave(_FakeHandle(10, 0.01))
    loop._observe_wave(_FakeHandle(128, 0.01))
    assert loop.quantum == 128
    loop._observe_wave(_FakeHandle(128, 0.01))
    assert loop.quantum == 256
    # cap: saturated fast waves cannot exceed max_quantum
    loop._observe_wave(_FakeHandle(256, 0.01))
    loop._observe_wave(_FakeHandle(256, 0.01))
    assert loop.quantum == 256
    # over-budget wave shrinks immediately...
    loop._observe_wave(_FakeHandle(256, 5.0))
    assert loop.quantum == 128
    # ...and the floor holds no matter how slow it gets
    loop._observe_wave(_FakeHandle(128, 5.0))
    loop._observe_wave(_FakeHandle(64, 5.0))
    loop._observe_wave(_FakeHandle(64, 5.0))
    assert loop.quantum == 64


def test_fixed_mode_pins_one_shape():
    """budget_s=None is the drain: quantum == chunk, pad floor == chunk —
    the ISSUE 2 contract the headline drain's compile stability rides."""
    s = _FakeSched()
    loop = ScheduleLoop(s, chunk=1000)
    assert loop.quantum == 1000
    assert s.engine.wave_pad_floor == 1000
    loop._observe_wave(_FakeHandle(1000, 9.9))  # adaptation inert
    assert loop.quantum == 1000


# ------------------------------------------------------- tier-1 fast smoke


def test_arrival_smoke_sustains_offered_rate():
    """The CI streaming smoke (ISSUE 7 satellite): a small offered stream
    must be consumed AT the offered rate with a loose latency bound.
    Asserts through bench.run_arrival so the smoke exercises the same
    honesty plumbing (creator stamps, per-interval series) the headline
    uses; shapes are tiny so the ladder warm is cheap on CI."""
    import bench

    out = bench.run_arrival(64, rate=300, duration_s=2.0, warm=True,
                            min_quantum=64, max_quantum=256,
                            budget_ms=500.0)
    assert out["bound"] == 600 and out["unbound"] == 0
    assert sum(out["intervals"]) + out["tail_partial"]["binds"] == 600
    assert sum(out["offered_series"]) + out["tail_partial"]["offered"] \
        == 600
    # sustained >= offered: the loop kept up INSIDE the offer window
    # (tolerance for interval-edge rounding on a 2-bucket window)
    assert out["sustained_pods_s"] >= 0.95 * out["offered_pods_s"], out
    # loose p99: double-digit ms warm on this box; anything near a second
    # means the stream went cold mid-offer
    assert out["p99_ms"] is not None and out["p99_ms"] < 1500.0, out
    assert out["backlog_at_offer_end"] < 300, out
    assert isinstance(out["creator_jitter_ok"], bool)
    assert len(out["backlog_series"]) == len(out["intervals"])
