"""Tensorization-layer tests: demand-driven label vocab, generation-diffed
delta refresh, and per-array dirty tracking (the device-upload contract that
keeps steady-state rounds at ~KBs of host->HBM traffic)."""

import random

import numpy as np

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.state.node_info import node_info_map
from kubernetes_tpu.state.snapshot import ClusterSnapshot, PodBatch
from tests.helpers import Gi, random_nodes, random_pod


def build(n_nodes=24, seed=3):
    rng = random.Random(seed)
    nodes = random_nodes(rng, n_nodes)
    infos = node_info_map(nodes, [])
    snap = ClusterSnapshot()
    snap.refresh(infos)
    return rng, nodes, infos, snap


def test_pod_add_marks_only_dynamic_arrays_dirty():
    rng, nodes, infos, snap = build()
    PodBatch([random_pod(rng, i, [n.name for n in nodes]) for i in range(30)], snap)
    snap.dirty.clear()
    p = make_pod("x", cpu=100, memory=1 * Gi)
    p.node_name = nodes[0].name
    infos[nodes[0].name].add_pod(p)
    assert not snap.refresh(infos)  # delta, not rebuild
    assert snap.dirty == {"requested", "nonzero", "pod_count"}


def test_pod_with_ports_also_dirties_port_bitmap():
    rng, nodes, infos, snap = build()
    snap.dirty.clear()
    p = make_pod("y", ports=[8080])
    p.node_name = nodes[1].name
    infos[nodes[1].name].add_pod(p)
    snap.refresh(infos)
    assert snap.dirty == {"requested", "nonzero", "pod_count", "port_bitmap"}


def test_node_spec_change_dirties_static_arrays():
    """Change detection (ISSUE 8): a spec change dirties exactly the
    arrays whose values moved; re-setting an identical spec (the respawn
    /flap-heavy churn shape) dirties NO static array — a dirty mark per
    fault event re-uploaded megabytes and invalidated the cached wave
    precompute once per kill, which measured as the churn collapse."""
    import dataclasses
    rng, nodes, infos, snap = build()
    snap.dirty.clear()
    infos[nodes[2].name].set_node(nodes[2])  # identical values
    snap.refresh(infos)
    assert not (snap.dirty & set(snap.STATIC)), snap.dirty
    snap.dirty.clear()
    node = nodes[2]
    changed = dataclasses.replace(
        node, labels=dict(node.labels, zone="zz-new"),
        allocatable=dataclasses.replace(node.allocatable,
                                        milli_cpu=node.allocatable.milli_cpu
                                        + 1000))
    # intern the new pair so the label ROW actually changes content
    snap.ensure_label_pair("zone", "zz-new")
    snap.finalize_labels()
    infos[node.name].set_node(changed)
    snap.refresh(infos)
    assert "labels" in snap.dirty and "alloc" in snap.dirty


def test_label_vocab_is_pod_demand_driven():
    # node-unique labels (hostname-style) must not widen the label matrix
    nodes = [make_node(f"n{i}", labels={"hostname": f"n{i}", "zone": "a"})
             for i in range(100)]
    infos = node_info_map(nodes, [])
    snap = ClusterSnapshot()
    snap.refresh(infos)
    PodBatch([make_pod("p", node_selector={"zone": "a"})], snap)
    assert snap.labels.shape[1] <= 8  # only 'zone=a' interned (+padding)
    # selecting a hostname interns exactly that pair and still matches
    PodBatch([make_pod("q", node_selector={"hostname": "n42"})], snap)
    assert len(snap.label_vocab) == 2


def test_identical_batches_do_not_rebuild_labels():
    rng, nodes, infos, snap = build()
    pods = [random_pod(rng, i, [n.name for n in nodes]) for i in range(30)]
    PodBatch(pods, snap)
    v0 = snap.version
    PodBatch(pods, snap)
    assert snap.version == v0


def test_quantization_is_conservative():
    snap = ClusterSnapshot()
    infos = node_info_map([make_node("n", memory=1 * Gi + 512)], [])
    snap.refresh(infos)
    i = snap.node_index["n"]
    # allocatable rounds DOWN (can't overcommit via quantization)
    assert snap.alloc[i, 1] == (1 * Gi + 512) >> 10
    p = make_pod("p", memory=1023)  # request rounds UP to 1 KiB
    b = PodBatch([p], snap)
    assert b.req[0, 1] == 1


def test_removed_then_readded_node_membership_rebuild():
    rng, nodes, infos, snap = build(n_nodes=9)
    del infos[nodes[0].name]
    assert snap.refresh(infos)  # membership change -> rebuild
    assert nodes[0].name not in snap.node_index


def test_unknown_extended_resource_marks_pod_impossible():
    # a pod requesting an ext resource NO node advertises must become
    # unschedulable, not crash the batch build (padded-vocab overflow)
    rng, nodes, infos, snap = build(n_nodes=8)
    pods = [make_pod(f"x{i}", cpu=100,
                     extended={f"example.com/weird-{i}": 1}) for i in range(6)]
    b = PodBatch(pods, snap)
    assert b.impossible.all()
    sane = PodBatch([make_pod("ok", cpu=100)], snap)
    assert not sane.impossible.any()


def test_bound_pod_with_unknown_extended_resource_interned_on_refresh():
    rng, nodes, infos, snap = build(n_nodes=8)
    p = make_pod("b", cpu=100, extended={"example.com/foreign": 2})
    p.node_name = nodes[0].name
    infos[nodes[0].name].add_pod(p)
    snap.refresh(infos)  # must not raise; vocab grows, arrays widen
    assert snap.ext_vocab.get("example.com/foreign", "") >= 0


def test_bulk_rebuild_matches_per_row_writers():
    """The vectorized full-rebuild path (_write_rows_bulk) must produce
    byte-identical arrays to the per-row delta writers over a feature-rich
    random cluster: re-running the per-row writers on every row after a
    bulk build must change nothing."""
    import random

    import numpy as np

    from kubernetes_tpu.state.node_info import node_info_map
    from tests.test_full_fuzz import _existing, full_random_nodes

    rng = random.Random(31)
    nodes = full_random_nodes(rng, 24)
    existing = _existing(rng, nodes, 16)
    infos = node_info_map(nodes, existing)
    snap = ClusterSnapshot()
    snap.refresh(infos)  # full build -> bulk path

    arrays = ("alloc", "requested", "nonzero", "pod_count", "allowed_pods",
              "schedulable", "mem_pressure", "disk_pressure", "labels",
              "taints_sched", "taints_pref", "port_bitmap", "valid",
              "avoid", "image_sizes", "has_zone", "vol_present", "vol_rw",
              "pd_present", "pd_counts")
    before = {k: np.copy(getattr(snap, k)) for k in arrays}
    for nm in snap.node_names:
        i = snap.node_index[nm]
        snap._write_dynamic_row(i, infos[nm])
        snap._write_static_row(i, infos[nm])
        snap._write_ports_row(i, infos[nm])
    for k in arrays:
        np.testing.assert_array_equal(
            getattr(snap, k), before[k], err_msg=f"bulk != per-row for {k}")
