"""Extender wire-protocol tests: a scheduler-side fake client POSTs
ExtenderArgs JSON (capitalized Go-style keys, like the reference's internal
structs marshal) and asserts on the filter/prioritize/bind results — the
shape of test/integration/scheduler/extender_test.go:71-126 with the roles
flipped (there the extender is fake; here the scheduler is)."""

import http.client
import json

import pytest

from kubernetes_tpu.api import serde
from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.server.extender import ExtenderHTTPServer


class FakeBackend:
    """machine1/2/3-style predicate backend (extender_test.go FakeExtender)."""

    def __init__(self):
        self.bound = []
        self.synced_nodes = []
        self.synced_pods = []

    def filter(self, pod, nodes, node_names):
        cands = node_names if node_names is not None else [n.name for n in nodes]
        passed = [n for n in cands if not n.endswith("1")]
        failed = {n: "ends with 1" for n in cands if n.endswith("1")}
        return passed, failed

    def prioritize(self, pod, nodes, node_names):
        cands = node_names if node_names is not None else [n.name for n in nodes]
        return [(n, 10 if n.endswith("2") else 1) for n in cands]

    def bind(self, pod_name, pod_namespace, pod_uid, node):
        self.bound.append((pod_namespace, pod_name, node))
        return ""

    def sync_nodes(self, nodes):
        self.synced_nodes = nodes

    def sync_pods(self, pods):
        self.synced_pods = pods

    def metrics_text(self):
        return "# fake"


@pytest.fixture()
def server():
    backend = FakeBackend()
    srv = ExtenderHTTPServer(backend, prefix="/scheduler")
    srv.start()
    yield srv, backend
    srv.stop()


def post(port, path, obj):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    body = json.dumps(obj)
    conn.request("POST", path, body, {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = json.loads(resp.read())
    conn.close()
    return resp.status, data


def get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def _args_cache_capable():
    pod = make_pod("p1", cpu=100)
    return {"Pod": serde.encode_pod(pod),
            "NodeNames": ["machine1", "machine2", "machine3"]}


def test_filter_node_cache_capable(server):
    srv, _ = server
    status, out = post(srv.port, "/scheduler/filter", _args_cache_capable())
    assert status == 200
    assert out["NodeNames"] == ["machine2", "machine3"]
    assert out["FailedNodes"] == {"machine1": "ends with 1"}
    assert out["Error"] == ""


def test_filter_with_full_nodes():
    backend = FakeBackend()
    srv = ExtenderHTTPServer(backend)
    srv.start()
    try:
        nodes = [make_node("machine1"), make_node("machine2")]
        args = {"Pod": serde.encode_pod(make_pod("p", cpu=100)),
                "Nodes": {"Items": [serde.encode_node(n) for n in nodes]}}
        status, out = post(srv.port, "/filter", args)
        assert status == 200
        names = [n["metadata"]["name"] for n in out["Nodes"]["Items"]]
        assert names == ["machine2"]
    finally:
        srv.stop()


def test_prioritize(server):
    srv, _ = server
    status, out = post(srv.port, "/scheduler/prioritize", _args_cache_capable())
    assert status == 200
    assert out == [{"Host": "machine1", "Score": 1},
                   {"Host": "machine2", "Score": 10},
                   {"Host": "machine3", "Score": 1}]


def test_bind(server):
    srv, backend = server
    status, out = post(srv.port, "/scheduler/bind", {
        "PodName": "p1", "PodNamespace": "default", "PodUID": "u1",
        "Node": "machine2"})
    assert status == 200
    assert out == {"Error": ""}
    assert backend.bound == [("default", "p1", "machine2")]


def test_lowercase_keys_accepted(server):
    # v1 wire mirror uses lowercase tags (api/v1/types.go) — accept both
    srv, _ = server
    pod = make_pod("p1", cpu=100)
    status, out = post(srv.port, "/scheduler/filter",
                       {"pod": serde.encode_pod(pod),
                        "nodenames": ["machine1", "machine2"]})
    assert status == 200
    assert out["NodeNames"] == ["machine2"]


def test_cache_sync_endpoints(server):
    srv, backend = server
    nodes = [serde.encode_node(make_node("n1")), serde.encode_node(make_node("n2"))]
    status, out = post(srv.port, "/scheduler/cache/nodes", {"items": nodes})
    assert status == 200 and out["synced"] == 2
    assert [n.name for n in backend.synced_nodes] == ["n1", "n2"]
    p = make_pod("bp", cpu=100)
    p.node_name = "n1"
    status, out = post(srv.port, "/scheduler/cache/pods",
                       {"items": [serde.encode_pod(p)]})
    assert status == 200 and out["synced"] == 1
    assert backend.synced_pods[0].node_name == "n1"


def test_healthz_and_metrics(server):
    srv, _ = server
    assert get(srv.port, "/healthz") == (200, b"ok")
    status, body = get(srv.port, "/metrics")
    assert status == 200 and b"fake" in body


def test_malformed_json_yields_in_band_error(server):
    srv, _ = server
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=5)
    conn.request("POST", "/scheduler/filter", "{not json",
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = json.loads(resp.read())
    conn.close()
    assert resp.status == 500
    assert "Error" in data


def test_tpu_backend_sync_pods_removes_deleted_pods():
    from kubernetes_tpu.server.extender import TPUExtenderBackend
    backend = TPUExtenderBackend()
    backend.sync_nodes([make_node("n1")])
    p = make_pod("gone", cpu=500)
    p.node_name = "n1"
    backend.sync_pods([p])
    assert backend.cache.node_infos()["n1"].requested.milli_cpu == 500
    # next full sync omits the pod -> its capacity is released
    backend.sync_pods([])
    assert backend.cache.node_infos()["n1"].requested.milli_cpu == 0
    assert backend._known_pods == {}


def test_tpu_backend_stale_node_labels_not_served_in_args_mode():
    # non-cache-capable: node state ships per request; a label change between
    # requests must be honored (regression: shared-snapshot generation diffing)
    from kubernetes_tpu.server.extender import TPUExtenderBackend
    backend = TPUExtenderBackend()
    pod = make_pod("p", cpu=100, node_selector={"zone": "b"})
    n = make_node("n2", labels={"zone": "b"})
    passed, _ = backend.filter(pod, [n], None)
    assert passed == ["n2"]
    n_changed = make_node("n2", labels={"zone": "c"})
    passed, failed = backend.filter(pod, [n_changed], None)
    assert passed == [] and "n2" in failed
