"""graftlint (ISSUE 4): per-rule fixtures, pragma/baseline plumbing, and
the clean-tree gate.

Fixture discipline: every rule fires on a minimal known-bad snippet AND
stays silent on the blessed idiom — so a rule regression shows up as a
missed fixture, not as a silent pass over the real tree. The clean-tree
gate is the tier-1 contract of the whole subsystem: the package lints to
ZERO unsuppressed findings (pragmas carry the justifications in-code; the
shipped baseline is empty).

Pure AST — no jax import, no device; the gate costs well under a second.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import kubernetes_tpu
from kubernetes_tpu.analysis.lint import (
    lint_gate,
    load_baseline,
    run_paths,
    write_baseline,
)

PKG_DIR = os.path.dirname(os.path.abspath(kubernetes_tpu.__file__))


def lint_src(tmp_path, src, name="snippet.py", rules=None):
    f = tmp_path / name
    f.write_text(textwrap.dedent(src))
    findings, _sup, errors = run_paths([str(f)], rules=rules)
    assert not errors, errors
    return findings


def rules_of(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------------------- GL001


def test_gl001_fires_on_asarray_then_mutate(tmp_path):
    fs = lint_src(tmp_path, """
        import jax.numpy as jnp
        import numpy as np

        def upload():
            buf = np.zeros(8)
            dev = jnp.asarray(buf)
            buf[0] = 1.0
            return dev
    """)
    assert rules_of(fs) == ["GL001"]


def test_gl001_fires_on_class_scoped_alias(tmp_path):
    """The r08 committed_nodes shape: upload in one method, in-place fold
    in another — lifetime spans calls, so the alias must be assumed live."""
    fs = lint_src(tmp_path, """
        import jax.numpy as jnp
        import numpy as np

        class Engine:
            def dispatch(self, enc):
                return jnp.asarray(enc.committed_nodes)

            def harvest(self, enc, cls, node):
                np.add.at(enc.committed_nodes, (cls, node), 1)
    """)
    assert rules_of(fs) == ["GL001"]


def test_gl001_silent_on_copying_idioms(tmp_path):
    fs = lint_src(tmp_path, """
        import jax.numpy as jnp
        import numpy as np

        def upload():
            buf = np.zeros(8)
            a = jnp.array(buf)          # copy constructor
            b = jnp.asarray(buf.copy()) # explicit host copy
            c = jnp.asarray(buf)        # alias, but buf is never mutated
            return a, b, c
    """)
    assert fs == []


def test_gl001_copy_required_contract(tmp_path):
    """The machine-checked form of the old prose comments: downgrading a
    copy-required seam to jnp.asarray fires; the copying form passes."""
    bad = lint_src(tmp_path, """
        import jax.numpy as jnp

        def seam(host):
            dev = jnp.asarray(host)  # graftlint: copy-required
            return dev
    """)
    assert rules_of(bad) == ["GL001"]
    good = lint_src(tmp_path, """
        import jax.numpy as jnp

        def seam(host):
            dev = jnp.array(host)  # graftlint: copy-required
            return dev
    """, name="good.py")
    assert good == []


# ------------------------------------------------------------------- GL002


GL002_BAD = """
    import functools
    import jax
    import numpy as np

    @functools.partial(jax.jit, static_argnames=("k",))
    def kernel(x, k=1):
        return x * k

    def hot_path(x):
        out = kernel(x)
        host = np.asarray(out)
        return host
"""


def test_gl002_fires_on_sync_of_jitted_result(tmp_path):
    fs = lint_src(tmp_path, GL002_BAD)
    assert rules_of(fs) == ["GL002"]


def test_gl002_pragma_blesses_the_sync(tmp_path):
    fs = lint_src(tmp_path, GL002_BAD.replace(
        "host = np.asarray(out)",
        "host = np.asarray(out)  # graftlint: sync-ok"))
    assert fs == []


def test_gl002_silent_on_numpy_on_numpy(tmp_path):
    """np.asarray of host data is free — taint only flows from jitted
    calls and WaveHandle device fields, and a rebind clears it."""
    fs = lint_src(tmp_path, """
        import functools
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            return x + 1

        def fine(x):
            res = kernel(x)
            res = np.asarray(res)  # graftlint: sync-ok (the one fetch)
            twice = np.asarray(res)      # already host: no second sync
            n = int(res[0])              # host scalar
            return twice, n
    """)
    assert fs == []


def test_gl002_registry_covers_tail_rounds_entry(tmp_path):
    """ISSUE 5: the conflict-round tail (engine/waves.tail_rounds_loop)
    is a new jitted entry point; the project-wide jit registry must pick
    it up from the REAL source file so GL002 taint coverage extends to
    its callers — an unblessed fetch of its packed result is a pipeline
    stall and must fire."""
    import ast

    from kubernetes_tpu.analysis.rules.base import ProjectIndex

    waves_py = os.path.join(PKG_DIR, "engine", "waves.py")
    with open(waves_py, "r", encoding="utf-8") as fh:
        waves_src = fh.read()
    index = ProjectIndex()
    index.scan(ast.parse(waves_src))
    assert "tail_rounds_loop" in index.jitted_names, \
        "new tail entry point missing from the jit registry"
    # cross-file taint: the fixture only CALLS the entry point; the
    # jitted-ness comes from the registry built over the real waves.py
    fixture = tmp_path / "harvest_tail.py"
    fixture.write_text(textwrap.dedent("""
        import numpy as np
        from kubernetes_tpu.engine.waves import tail_rounds_loop

        def harvest_tail(cls, nodes, state, pc, counter, prios):
            packed, _st = tail_rounds_loop(cls, nodes, state, pc,
                                           counter, prios)
            return np.asarray(packed)
    """))
    findings, _sup, errors = run_paths([waves_py, str(fixture)],
                                       rules=["GL002"])
    assert not errors, errors
    assert any(f.rule == "GL002" and "harvest_tail" in f.context
               for f in findings), findings
    # the blessed form (the harvest's documented fetch) stays silent
    fixture.write_text(fixture.read_text().replace(
        "return np.asarray(packed)",
        "return np.asarray(packed)  # graftlint: sync-ok"))
    findings, _sup, errors = run_paths([waves_py, str(fixture)],
                                       rules=["GL002"])
    assert not errors, errors
    assert not [f for f in findings if "harvest_tail" in f.context], findings


def test_gl002_registry_covers_streaming_pop_seam(tmp_path):
    """ISSUE 7: the always-on loop's micro-wave pop dispatches through
    the registered jitted entry points (waves_loop and friends) — the
    registry built over the REAL waves.py must extend GL002 taint to a
    streaming-shaped consumer, because the pop seam is exactly where a
    hidden device->host sync would silently serialize the loop (one
    unblessed fetch per micro-wave = the whole overlap forfeited at
    20k pops/s)."""
    import ast

    from kubernetes_tpu.analysis.rules.base import ProjectIndex

    waves_py = os.path.join(PKG_DIR, "engine", "waves.py")
    with open(waves_py, "r", encoding="utf-8") as fh:
        index = ProjectIndex()
        index.scan(ast.parse(fh.read()))
    # the streaming dispatch path's device entry points, all registered
    # via decoration
    for entry in ("waves_loop", "tail_rounds_loop", "precompute_jit",
                  "frozen_affinity_scores"):
        assert entry in index.jitted_names, entry
    fixture = tmp_path / "stream_pump.py"
    fixture.write_text(textwrap.dedent("""
        import numpy as np
        from kubernetes_tpu.engine.waves import waves_loop

        def pump_micro_wave(queue, cls_arr, nodes, state, pc, ctr, prios):
            packed, _st = waves_loop(cls_arr, nodes, state, pc, ctr,
                                     prios)
            return np.asarray(packed)
    """))
    findings, _sup, errors = run_paths([waves_py, str(fixture)],
                                       rules=["GL002"])
    assert not errors, errors
    assert any(f.rule == "GL002" and "pump_micro_wave" in f.context
               for f in findings), findings
    # the blessed harvest fetch stays silent
    fixture.write_text(fixture.read_text().replace(
        "return np.asarray(packed)",
        "return np.asarray(packed)  # graftlint: sync-ok"))
    findings, _sup, errors = run_paths([waves_py, str(fixture)],
                                       rules=["GL002"])
    assert not errors, errors
    assert not [f for f in findings if "pump_micro_wave" in f.context], \
        findings


def test_gl002_registry_covers_hostcheck_static_column_seam(tmp_path):
    """ISSUE 18: host-check classes ride the wave via a precomputed
    `host_fit` [C, N] column ANDed inside the fused static eval
    (ops/predicates.static_fits, entered through waves.precompute_jit).
    The column is built host-side from label truth and uploaded frozen —
    the registry built over the REAL waves.py must extend GL002 taint to
    a consumer feeding the host_fit-bearing class dict, because an
    unblessed fetch at this seam would serialize every host-check wave
    (exactly the flush this PR removed, reintroduced as a hidden
    sync)."""
    import ast

    from kubernetes_tpu.analysis.rules.base import ProjectIndex

    waves_py = os.path.join(PKG_DIR, "engine", "waves.py")
    with open(waves_py, "r", encoding="utf-8") as fh:
        index = ProjectIndex()
        index.scan(ast.parse(fh.read()))
    assert "precompute_jit" in index.jitted_names, \
        "host-check static-column entry missing from the jit registry"
    fixture = tmp_path / "host_column.py"
    fixture.write_text(textwrap.dedent("""
        import numpy as np
        from kubernetes_tpu.engine.waves import precompute_jit

        def eval_host_static_chunk(cls, nodes, host_rows, priorities):
            cls = dict(cls, host_fit=host_rows)  # frozen label column
            pre = precompute_jit(cls, nodes, priorities=priorities)
            return np.asarray(pre["static_fit"])
    """))
    findings, _sup, errors = run_paths([waves_py, str(fixture)],
                                       rules=["GL002"])
    assert not errors, errors
    assert any(f.rule == "GL002" and "eval_host_static_chunk" in f.context
               for f in findings), findings
    # the blessed form (the dispatch's documented fetch point) is silent
    fixture.write_text(fixture.read_text().replace(
        'return np.asarray(pre["static_fit"])',
        'return np.asarray(pre["static_fit"])  # graftlint: sync-ok'))
    findings, _sup, errors = run_paths([waves_py, str(fixture)],
                                       rules=["GL002"])
    assert not errors, errors
    assert not [f for f in findings
                if "eval_host_static_chunk" in f.context], findings


def test_gl002_registry_covers_batched_extender_eval(tmp_path):
    """ISSUE 9: the coalesced multi-frontend eval adds a jitted entry
    point (scheduler_engine._fused_eval_batch_jit, the [C, N] sibling of
    the extender's fused single-pod dispatch) — the project-wide registry
    must pick it up from the REAL source so GL002 taint extends to
    consumers: an unblessed fetch of the batch result would stall the
    coalescing window once per micro-batch, exactly the hidden-sync
    hazard the fleet throughput story rests on."""
    import ast

    from kubernetes_tpu.analysis.rules.base import ProjectIndex

    eng_py = os.path.join(PKG_DIR, "engine", "scheduler_engine.py")
    with open(eng_py, "r", encoding="utf-8") as fh:
        index = ProjectIndex()
        index.scan(ast.parse(fh.read()))
    for entry in ("_fused_eval_jit", "_fused_eval_batch_jit"):
        assert entry in index.jitted_names, entry
    fixture = tmp_path / "coalesced_eval.py"
    fixture.write_text(textwrap.dedent("""
        import numpy as np
        from kubernetes_tpu.engine.scheduler_engine import (
            _fused_eval_batch_jit,
        )

        def serve_window(parr, narr, plain, weights, mode):
            m, s = _fused_eval_batch_jit(parr, narr, None, plain,
                                         weights, mode)
            return np.asarray(m), np.asarray(s)
    """))
    findings, _sup, errors = run_paths([eng_py, str(fixture)],
                                       rules=["GL002"])
    assert not errors, errors
    assert any(f.rule == "GL002" and "serve_window" in f.context
               for f in findings), findings
    # the blessed form (the batch's one documented result fetch) is silent
    fixture.write_text(textwrap.dedent("""
        import numpy as np
        from kubernetes_tpu.engine.scheduler_engine import (
            _fused_eval_batch_jit,
        )

        def serve_window(parr, narr, plain, weights, mode):
            m, s = _fused_eval_batch_jit(parr, narr, None, plain,
                                         weights, mode)
            m = np.asarray(m)  # graftlint: sync-ok
            s = np.asarray(s)  # graftlint: sync-ok
            return m, s
    """))
    findings, _sup, errors = run_paths([eng_py, str(fixture)],
                                       rules=["GL002"])
    assert not errors, errors
    assert not [f for f in findings if "serve_window" in f.context], findings


def test_gl002_registry_does_not_taint_async_wire(tmp_path):
    """ISSUE 11: the async binary wire (server/asyncwire.py + framing +
    the binary client) is pure HOST-side plumbing — it never calls a
    jitted entry point and never fetches a device value; all device work
    stays behind the service core's blessed seams. The registry built
    over the REAL engine sources must therefore produce ZERO GL002
    findings over the new wire modules: if taint ever reaches the event
    loop's reads, either the wire started dispatching device work inline
    (a loop-wedging hazard — one unblessed fetch per frame serializes
    every connection) or the rule broke. Mirrors the r12 batched-eval
    fixture from the opposite direction: that one proves the registry
    EXTENDS to consumers; this one proves the wire is not one."""
    import ast

    from kubernetes_tpu.analysis.rules.base import ProjectIndex

    eng_py = os.path.join(PKG_DIR, "engine", "scheduler_engine.py")
    waves_py = os.path.join(PKG_DIR, "engine", "waves.py")
    wire_files = [
        os.path.join(PKG_DIR, "server", "asyncwire.py"),
        os.path.join(PKG_DIR, "server", "framing.py"),
        os.path.join(PKG_DIR, "server", "embedded.py"),
        os.path.join(PKG_DIR, "client", "binarywire.py"),
    ]
    # the registry really carries the jitted entry points (scan sanity:
    # an empty registry would make this test pass vacuously)
    index = ProjectIndex()
    for src in (eng_py, waves_py):
        with open(src, "r", encoding="utf-8") as fh:
            index.scan(ast.parse(fh.read()))
    assert "_fused_eval_batch_jit" in index.jitted_names
    assert "waves_loop" in index.jitted_names
    findings, _sup, errors = run_paths([eng_py, waves_py] + wire_files,
                                       rules=["GL002"])
    assert not errors, errors
    tainted = [f for f in findings
               if any(os.path.basename(w) in f.path for w in wire_files)]
    assert not tainted, tainted
    # negative control, the r12 pattern inverted: a wire-shaped consumer
    # that DOES fetch a jitted result from its serve path fires — the
    # silence above is the wire's purity, not the rule going blind
    fixture = tmp_path / "bad_wire.py"
    fixture.write_text(textwrap.dedent("""
        import numpy as np
        from kubernetes_tpu.engine.scheduler_engine import (
            _fused_eval_batch_jit,
        )

        def serve_frame(parr, narr, plain, weights, mode):
            m, s = _fused_eval_batch_jit(parr, narr, None, plain,
                                         weights, mode)
            return np.asarray(m)
    """))
    findings, _sup, errors = run_paths([eng_py, str(fixture)],
                                       rules=["GL002"])
    assert not errors, errors
    assert any(f.rule == "GL002" and "serve_frame" in f.context
               for f in findings), findings


def test_lint_gate_covers_new_wire_modules():
    """ISSUE 11 satellite: `bench --lint-gate` discovers the new wire
    modules (they are ordinary package files — but a collection
    regression here would silently exempt the fleet transport from every
    rule, so the coverage is pinned)."""
    from kubernetes_tpu.analysis.lint import collect_files

    files = collect_files([PKG_DIR])
    for rel in (("server", "asyncwire.py"), ("server", "framing.py"),
                ("server", "embedded.py"), ("client", "binarywire.py")):
        assert os.path.join(PKG_DIR, *rel) in files, rel


def test_gl003_fires_on_ragged_coalesced_batch(tmp_path):
    """ISSUE 9: the coalescing window's batch axis is where a ragged-
    shape recompile storm would creep back in — slicing the class arrays
    to the data-dependent batch size in the serve loop must fire GL003;
    the shipped pad-to-bucket idiom (pod_arrays_bucketed rows=bucket(C))
    stays silent."""
    eng_py = os.path.join(PKG_DIR, "engine", "scheduler_engine.py")
    bad = tmp_path / "ragged_window.py"
    bad.write_text(textwrap.dedent("""
        from kubernetes_tpu.engine.scheduler_engine import (
            _fused_eval_batch_jit,
        )

        def serve(windows, parr, narr, plain, weights, mode):
            out = []
            while windows:
                n = windows.pop()
                out.append(_fused_eval_batch_jit(parr[:n], narr, None,
                                                 plain, weights, mode))
            return out
    """))
    findings, _sup, errors = run_paths([eng_py, str(bad)], rules=["GL003"])
    assert not errors, errors
    assert any(f.rule == "GL003" and "serve" in f.context
               for f in findings), findings
    good = tmp_path / "bucketed_window.py"
    good.write_text(textwrap.dedent("""
        import numpy as np
        from kubernetes_tpu.engine.scheduler_engine import (
            _fused_eval_batch_jit,
        )

        def serve(windows, parr, narr, plain, weights, mode, pad):
            out = []
            while windows:
                n = windows.pop()
                rows = np.zeros(pad, dtype=np.int32)
                rows[:n] = parr[:n]
                out.append(_fused_eval_batch_jit(rows, narr, None,
                                                 plain, weights, mode))
            return out
    """))
    findings, _sup, errors = run_paths([eng_py, str(good)], rules=["GL003"])
    assert not errors, errors
    assert not [f for f in findings if f.rule == "GL003"
                and "bucketed_window" in f.path], findings


def test_gl003_fires_on_ragged_micro_wave_pop(tmp_path):
    """ISSUE 7: the micro-wave pop is where the ragged-shape recompile
    storm would creep back in — an arrival loop slicing its pod arrays
    to the data-dependent pop size before a registered jitted entry
    point must fire GL003; the pad-to-bucket idiom (wave_pad_floor /
    predicates.bucket, what ScheduleLoop actually rides) stays silent."""
    waves_py = os.path.join(PKG_DIR, "engine", "waves.py")
    bad = tmp_path / "ragged_pump.py"
    bad.write_text(textwrap.dedent("""
        from kubernetes_tpu.engine.waves import waves_loop

        def pump(queue, cls_arr, nodes, state, pc, ctr, prios):
            out = []
            while queue:
                n = queue.pop()
                out.append(waves_loop(cls_arr, nodes, state, pc[:n],
                                      ctr, prios))
            return out
    """))
    findings, _sup, errors = run_paths([waves_py, str(bad)],
                                       rules=["GL003"])
    assert not errors, errors
    assert any(f.rule == "GL003" and "pump" in f.context
               for f in findings), findings
    # blessed: pad to a fixed bucket OUTSIDE the call's operand — no
    # ragged slice reaches the jitted entry point
    good = tmp_path / "bucketed_pump.py"
    good.write_text(textwrap.dedent("""
        import numpy as np
        from kubernetes_tpu.engine.waves import waves_loop

        def pump(queue, cls_arr, nodes, state, pc, ctr, prios, pad):
            out = []
            while queue:
                n = queue.pop()
                pc_pad = np.full(pad, 0, dtype=np.int32)
                pc_pad[:n] = pc[:n]
                out.append(waves_loop(cls_arr, nodes, state, pc_pad,
                                      ctr, prios))
            return out
    """))
    findings, _sup, errors = run_paths([waves_py, str(good)],
                                       rules=["GL003"])
    assert not errors, errors
    assert not [f for f in findings if f.rule == "GL003"
                and "bucketed_pump" in f.path], findings


def test_gl002_registry_covers_victim_scan_seam(tmp_path):
    """ISSUE 14: wave-path preemption adds a jitted entry point
    (ops/preempt.victim_scan_jit — the [C, N] victim pre-filter) — the
    project-wide registry must pick it up from the REAL source so GL002
    taint extends to consumers: an unblessed fetch of the candidate
    rows would stall the harvest tail once per preemption round."""
    import ast

    from kubernetes_tpu.analysis.rules.base import ProjectIndex

    pre_py = os.path.join(PKG_DIR, "ops", "preempt.py")
    with open(pre_py, "r", encoding="utf-8") as fh:
        index = ProjectIndex()
        index.scan(ast.parse(fh.read()))
    assert "victim_scan_jit" in index.jitted_names
    fixture = tmp_path / "victim_select.py"
    fixture.write_text(textwrap.dedent("""
        import numpy as np
        from kubernetes_tpu.ops.preempt import victim_scan_jit

        def select_victims(need_cpu, need_mem, prio, dev):
            cand, bound = victim_scan_jit(need_cpu, need_mem, prio,
                                          dev, dev, dev, dev, dev, dev,
                                          dev, dev)
            return np.asarray(cand)
    """))
    findings, _sup, errors = run_paths([pre_py, str(fixture)],
                                       rules=["GL002"])
    assert not errors, errors
    assert any(f.rule == "GL002" and "select_victims" in f.context
               for f in findings), findings
    # the blessed fetch (the scan's documented synchronous consume)
    fixture.write_text(fixture.read_text().replace(
        "return np.asarray(cand)",
        "return np.asarray(cand)  # graftlint: sync-ok"))
    findings, _sup, errors = run_paths([pre_py, str(fixture)],
                                       rules=["GL002"])
    assert not errors, errors
    assert not [f for f in findings if "select_victims" in f.context], \
        findings


def test_gl003_fires_on_ragged_victim_set(tmp_path):
    """ISSUE 14: a preemption round's preemptor count is data-dependent —
    slicing the need arrays to it before the victim-scan jit would mint
    one XLA compile per distinct round size (the GL003 storm); the
    pad-to-bucket idiom engine.preempt_scan actually uses stays silent."""
    pre_py = os.path.join(PKG_DIR, "ops", "preempt.py")
    bad = tmp_path / "ragged_scan.py"
    bad.write_text(textwrap.dedent("""
        from kubernetes_tpu.ops.preempt import victim_scan_jit

        def scan_rounds(rounds, need_cpu, need_mem, prio, dev):
            out = []
            while rounds:
                n = rounds.pop()
                out.append(victim_scan_jit(need_cpu[:n], need_mem[:n],
                                           prio[:n], dev, dev, dev, dev,
                                           dev, dev, dev, dev))
            return out
    """))
    findings, _sup, errors = run_paths([pre_py, str(bad)],
                                       rules=["GL003"])
    assert not errors, errors
    assert any(f.rule == "GL003" and "scan_rounds" in f.context
               for f in findings), findings
    good = tmp_path / "bucketed_scan.py"
    good.write_text(textwrap.dedent("""
        import numpy as np
        from kubernetes_tpu.ops.preempt import victim_scan_jit

        def scan_rounds(rounds, need_cpu, need_mem, prio, dev, pad):
            out = []
            while rounds:
                n = rounds.pop()
                nc = np.zeros(pad, dtype=np.int32)
                nc[:n] = need_cpu[:n]
                out.append(victim_scan_jit(nc, nc, nc, dev, dev, dev,
                                           dev, dev, dev, dev, dev))
            return out
    """))
    findings, _sup, errors = run_paths([pre_py, str(good)],
                                       rules=["GL003"])
    assert not errors, errors
    assert not [f for f in findings if f.rule == "GL003"
                and "bucketed_scan" in f.path], findings


def test_gl002_fires_on_device_handle_field(tmp_path):
    fs = lint_src(tmp_path, """
        import numpy as np

        def harvest(handle):
            return np.asarray(handle.packed)
    """)
    assert rules_of(fs) == ["GL002"]


# ------------------------------------------------------------------- GL003


def test_gl003_fires_on_jit_in_function(tmp_path):
    fs = lint_src(tmp_path, """
        import jax

        def hot(xs):
            f = jax.jit(lambda a: a + 1)
            return [f(x) for x in xs]
    """)
    assert rules_of(fs) == ["GL003"]


def test_gl003_fires_on_ragged_slice_in_loop(tmp_path):
    fs = lint_src(tmp_path, """
        import jax

        @jax.jit
        def kernel(x):
            return x.sum()

        def drain(queue, arr):
            out = []
            while queue:
                n = queue.pop()
                out.append(kernel(arr[:n]))
            return out
    """)
    assert rules_of(fs) == ["GL003"]


def test_gl003_silent_on_blessed_idioms(tmp_path):
    """Module-level wrap/decorator and bucketed shapes pass."""
    fs = lint_src(tmp_path, """
        import functools
        import jax

        def _impl(x):
            return x + 1

        impl_jit = jax.jit(_impl)

        @functools.partial(jax.jit, static_argnames=("k",))
        def kernel(x, k=1):
            return x * k

        def drain(queue, arr, pad):
            out = []
            while queue:
                queue.pop()
                out.append(kernel(arr))   # constant shape per drain
            return out
    """)
    assert fs == []


# ------------------------------------------------------------------- GL004


def test_gl004_fires_on_attr_store_in_traced_scope(tmp_path):
    fs = lint_src(tmp_path, """
        import jax

        def _impl(holder, x):
            holder.last = x
            return x + 1

        impl_jit = jax.jit(_impl)
    """)
    assert rules_of(fs) == ["GL004"]


def test_gl004_fires_on_global_append(tmp_path):
    fs = lint_src(tmp_path, """
        import jax

        TRACE_LOG = []

        @jax.jit
        def kernel(x):
            TRACE_LOG.append(x)
            return x + 1
    """)
    assert rules_of(fs) == ["GL004"]


def test_gl004_silent_on_pure_kernel_with_local_state(tmp_path):
    fs = lint_src(tmp_path, """
        import jax
        from jax import lax

        @jax.jit
        def loop(x):
            acc = []
            acc.append(x)          # local container: fine

            def body(c):
                s, i = c
                return (s + 1, i + 1)

            def cond(c):
                return c[1] < 4

            return lax.while_loop(cond, body, (x, 0)), acc
    """)
    assert fs == []


# ------------------------------------------------------------------- GL005


GL005_BAD = """
    import numpy as np

    class Snapshot:
        def __init__(self, n):
            self.requested = np.zeros((n, 4), dtype=np.int32)
            self.version = 0
            self.dirty = set()

        def write_row(self, i, row):
            self.requested[i] = row
"""


def test_gl005_fires_on_unannounced_row_write(tmp_path):
    fs = lint_src(tmp_path, GL005_BAD)
    assert rules_of(fs) == ["GL005"]


def test_gl005_silent_when_announced_or_blessed(tmp_path):
    fs = lint_src(tmp_path, GL005_BAD.replace(
        "self.requested[i] = row",
        "self.requested[i] = row\n"
        "            self.dirty.add(\"requested\")\n"
        "            self.version += 1"))
    assert fs == []
    fs = lint_src(tmp_path, GL005_BAD.replace(
        "        def write_row(self, i, row):",
        "        # graftlint: gen-ok — caller owns the dirty note\n"
        "        def write_row(self, i, row):"), name="blessed.py")
    assert fs == []


def test_gl005_silent_on_nonsnapshot_labels(tmp_path):
    """A Pod's labels dict shares the attribute name but carries no
    generation machinery — out of scope by construction."""
    fs = lint_src(tmp_path, """
        def admit(req):
            req.obj.labels["key"] = "value"
    """)
    assert fs == []


def test_gl005_fires_via_local_alias(tmp_path):
    fs = lint_src(tmp_path, """
        import numpy as np

        class Snapshot:
            def __init__(self, n):
                self.requested = np.zeros((n, 4), dtype=np.int32)
                self.dirty = set()

            def write(self, idx, rows):
                requested = self.requested
                requested[idx] = rows
    """)
    assert rules_of(fs) == ["GL005"]


def test_gl005_fires_on_label_delta_patch_without_gen(tmp_path):
    """ISSUE 8: the Protean label-row delta patch is a snapshot
    dynamic-row write like any other — skipping the labels_gen
    announcement would let every consumer keyed on it (the wave
    encoding's topology views) silently go stale. The patch shape
    without the announcement must fire; the shipped shape (gen bump +
    patch-log append) is silent."""
    src = """
        import numpy as np

        class Snapshot:
            def __init__(self, n, l):
                self.labels = np.zeros((n, l), dtype=np.int8)
                self.labels_gen = 0
                self.dirty = set()
                self._labels_log = []

            def patch_row(self, i, row):
                self.labels[i] = row
    """
    fs = lint_src(tmp_path, src)
    assert rules_of(fs) == ["GL005"]
    fs = lint_src(tmp_path, src.replace(
        "self.labels[i] = row",
        "self.labels_gen += 1\n"
        "                self._labels_log.append((self.labels_gen, i))\n"
        "                self.labels[i] = row"))
    assert fs == []


def test_gl001_fires_on_frozen_patch_overlay_mutated_in_place(tmp_path):
    """ISSUE 8: the patched topology views back FROZEN device uploads —
    re-patching them IN PLACE (instead of the shipped copy-on-write:
    fresh array, patch, re-freeze) is exactly the r07 aliasing race with
    a churn trigger. The class-scoped lifetime makes GL001 fire; no new
    jitted entry point was added for the fence/patch paths (they are
    host-side numpy), so the registry needs no new coverage — this
    fixture pins the upload seam discipline instead."""
    fs = lint_src(tmp_path, """
        import numpy as np
        from kubernetes_tpu.analysis.sanitize import upload_frozen

        class Engine:
            def flush(self, enc):
                return upload_frozen(enc.key_node)

            def patch(self, enc, rows, fresh):
                enc.key_node[:, :, rows] = fresh
    """)
    assert rules_of(fs) == ["GL001"]


# ----------------------------------------------- review-hardening guards


def test_pragma_does_not_smear_over_the_function(tmp_path):
    """A sync-ok on one statement must NOT bless a different unblessed
    sync elsewhere in the same function (suppression anchors on the
    smallest enclosing statement; function-wide blessing requires the
    pragma on the def line itself)."""
    fs = lint_src(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            return x + 1

        def hot(x):
            a = kernel(x)
            b = np.asarray(a)  # graftlint: sync-ok (this one is blessed)
            c = kernel(x)
            d = np.asarray(c)
            return b, d
    """)
    assert rules_of(fs) == ["GL002"]
    assert "np.asarray" in fs[0].message


def test_gl001_sees_through_upload_frozen(tmp_path):
    """upload_frozen is jnp.asarray underneath — with GRAFT_SANITIZE unset
    nothing seals the source, so mutating a frozen-seam buffer is the same
    production race and must fire GL001 like the bare spelling."""
    fs = lint_src(tmp_path, """
        import numpy as np
        from kubernetes_tpu.analysis import sanitize

        class Enc:
            def up(self):
                return sanitize.upload_frozen(self.wave_gate)

            def poke(self):
                self.wave_gate[0] = 1
    """)
    assert rules_of(fs) == ["GL001"]
    assert "upload_frozen" in fs[0].message


def test_gl002_survives_same_line_mixed_rebinds(tmp_path):
    """Two same-line rebinds of one name with mixed producers (jitted and
    not) must not crash the taint-event sort (None vs str comparison) —
    a lint-engine TypeError takes down the whole gate, not one rule."""
    fs = lint_src(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            return x + 1

        def hot(x):
            out = kernel(x); out = np.zeros(3)
            return out
    """)
    assert fs == []


def test_empty_collection_fails_the_gate(tmp_path):
    """A typo'd path must fail loudly, not lint zero files and pass."""
    findings, _sup, errors = run_paths([str(tmp_path / "no_such_dir")])
    assert findings == [] and errors, errors
    ok, _report = lint_gate(str(tmp_path / "no_such_dir"))
    assert not ok


def test_bad_path_fails_even_beside_good_paths(tmp_path):
    """A typo'd path must fail the run even when OTHER paths yield files —
    else a CI arg list silently stops covering a renamed subtree."""
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    findings, _sup, errors = run_paths([str(good),
                                        str(tmp_path / "renamed_away")])
    assert findings == []
    assert any("renamed_away" in e for e in errors), errors


# ------------------------------------------------- baseline + CLI plumbing


def test_baseline_suppresses_and_survives_line_drift(tmp_path):
    src = """
        import jax.numpy as jnp
        import numpy as np

        def upload():
            buf = np.zeros(8)
            dev = jnp.asarray(buf)
            buf[0] = 1.0
            return dev
    """
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent(src))
    findings, _s, _e = run_paths([str(f)])
    assert len(findings) == 1
    bpath = tmp_path / "baseline.json"
    write_baseline(str(bpath), findings)
    base = load_baseline(str(bpath))
    # shift every line down: the fingerprint (rule, path, qualname,
    # message) must keep matching
    f.write_text("# a new header comment\n# another\n"
                 + textwrap.dedent(src))
    findings2, sup, _e = run_paths([str(f)], baseline=base)
    assert findings2 == [] and sup == 1


def test_cli_clean_and_failing_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\ndef f(xs):\n"
                   "    g = jax.jit(lambda a: a)\n    return g(xs)\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu.analysis", str(bad)],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(PKG_DIR))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "GL003" in r.stdout
    r2 = subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu.analysis", "--list-rules"],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(PKG_DIR))
    assert r2.returncode == 0 and "GL005" in r2.stdout


def test_baseline_path_form_stable_relative_vs_absolute(tmp_path,
                                                        monkeypatch):
    """A baseline written while linting a RELATIVE path must still
    suppress when the same files are linted via the absolute dir
    (lint_gate's default) — fingerprints must not embed the invocation
    spelling of the path."""
    pkg = tmp_path / "proj" / "pkg"
    pkg.mkdir(parents=True)
    (pkg / "mod.py").write_text(
        "import numpy as np\nimport jax.numpy as jnp\n\n"
        "def f():\n    b = np.zeros(4)\n"
        "    d = jnp.asarray(b)\n    b[0] = 1\n    return d\n")
    monkeypatch.chdir(tmp_path / "proj")
    findings, _s, _e = run_paths(["pkg"])
    assert len(findings) == 1
    bpath = tmp_path / "b.json"
    write_baseline(str(bpath), findings)
    findings2, sup, _e = run_paths([str(pkg)],
                                   baseline=load_baseline(str(bpath)))
    assert findings2 == [] and sup == 1


def test_write_baseline_roundtrip_json(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import numpy as np\nimport jax.numpy as jnp\n\n"
                   "def f():\n    b = np.zeros(4)\n"
                   "    d = jnp.asarray(b)\n    b[0] = 1\n    return d\n")
    bpath = tmp_path / "b.json"
    findings, _s, _e = run_paths([str(bad)])
    write_baseline(str(bpath), findings)
    data = json.loads(bpath.read_text())
    assert len(data["suppressions"]) == 1
    findings2, sup, _e = run_paths([str(bad)],
                                   baseline=load_baseline(str(bpath)))
    assert findings2 == [] and sup == 1


def test_fingerprints_stable_across_cwd(tmp_path, monkeypatch):
    """The same IN-REPO file must fingerprint identically whatever CWD the
    linter runs from — a baseline regenerated by CI at the repo root must
    keep suppressing for a wrapper script running elsewhere."""
    from kubernetes_tpu.analysis.lint import _relpath

    target = os.path.join(PKG_DIR, "engine", "waves.py")
    monkeypatch.chdir(os.path.dirname(PKG_DIR))
    a = _relpath(target)
    monkeypatch.chdir(tmp_path)
    b = _relpath(target)
    assert a == b == os.path.join("kubernetes_tpu", "engine", "waves.py")


def test_write_baseline_reports_parse_errors(tmp_path, capsys):
    """--write-baseline over a tree with an unparseable file must fail
    (exit 1) and say so — a 'successful' regeneration that silently
    shrank coverage resurfaces the broken file's findings unsuppressed
    the moment it is fixed."""
    from kubernetes_tpu.analysis.__main__ import main

    (tmp_path / "ok.py").write_text("x = 1\n")
    (tmp_path / "broken.py").write_text("def broken(:\n")
    bpath = tmp_path / "b.json"
    rc = main(["--write-baseline", str(bpath), str(tmp_path)])
    err = capsys.readouterr().err
    assert rc == 1
    assert "broken.py" in err


def test_write_baseline_regen_keeps_inherited_suppressions(tmp_path,
                                                           capsys):
    """--baseline old --write-baseline new must regenerate from the
    UNFILTERED findings: the old file's suppressions land in the new one
    instead of being silently dropped (which would resurrect them as
    fresh findings on the very next --baseline run)."""
    from kubernetes_tpu.analysis.__main__ import main

    bad = tmp_path / "bad.py"
    bad.write_text("import numpy as np\nimport jax.numpy as jnp\n\n"
                   "def f():\n    b = np.zeros(4)\n"
                   "    d = jnp.asarray(b)\n    b[0] = 1\n    return d\n")
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    assert main(["--write-baseline", str(old), str(bad)]) == 0
    assert main(["--baseline", str(old), "--write-baseline", str(new),
                 str(bad)]) == 0
    capsys.readouterr()
    assert load_baseline(str(new)) == load_baseline(str(old)) != {}
    assert main(["--baseline", str(new), str(bad)]) == 0


# ------------------------------------------------------- the tier-1 gate


def test_tree_lints_clean():
    """THE gate: the whole package carries zero unsuppressed findings.
    Every hazard is either fixed or pragma'd with its justification next
    to the code (the shipped baseline is empty). A new finding here means
    a new hazard entered the hot path — fix it or bless it, don't widen
    the gate."""
    ok, report = lint_gate(PKG_DIR)
    assert ok, f"graftlint gate failed:\n{report}"


def test_gate_is_pure_ast_fast():
    """The gate must stay cheap enough for tier-1 and bench.py
    --lint-gate: pure AST, no device — ~6 s on the idle 2-core CI box.
    The bound is a regression guard against a rule going super-linear,
    not an SLO: 20 s leaves headroom for co-tenant contention (a
    contended full-suite run measured the same gate at 13 s) while any
    complexity blowup still lands far past it."""
    import time
    t0 = time.perf_counter()
    lint_gate(PKG_DIR)
    assert time.perf_counter() - t0 < 20.0


# ------------------------------------------------- ISSUE 12: mesh seams


def test_gl001_fires_on_shard_resident_fold_alias(tmp_path):
    """The shard-resident buffer lifecycle (ISSUE 12): a host array that
    backs a RESIDENT sharded upload while a later fold mutates it in
    place is the committed_nodes race at mesh scale — degrading the
    copying upload (sanitize.upload_copied(..., sharding=...) /
    ResidentMesh.update_rows' per-slice np.array) to a zero-copy
    jnp.asarray must fire; the shipped copying shape stays silent."""
    bad = lint_src(tmp_path, """
        import jax.numpy as jnp
        import numpy as np

        class ResidentEngine:
            def sync_shards(self, enc):
                # sharded residency built over an alias of the live fold
                # target — the regression GL001 exists to reject
                return jnp.asarray(enc.committed_nodes)

            def fold(self, enc, cls, node):
                np.add.at(enc.committed_nodes, (cls, node), 1)
    """)
    assert rules_of(bad) == ["GL001"]
    good = lint_src(tmp_path, """
        import jax
        import numpy as np

        class ResidentEngine:
            def sync_shards(self, enc, sharding):
                # the shipped seam: copy host-side BEFORE placement, so
                # even a zero-copy per-shard device_put aliases only the
                # throwaway copy (sanitize.upload_copied(sharding=...) /
                # mesh.ResidentMesh.update_rows)
                return jax.device_put(np.array(enc.committed_nodes),
                                      sharding)

            def fold(self, enc, cls, node):
                np.add.at(enc.committed_nodes, (cls, node), 1)
    """)
    assert not [f for f in good if f.rule == "GL001"], good


def test_gl003_fires_on_ragged_per_shard_slice_into_reduce(tmp_path):
    """ISSUE 12: the two-stage winner reduce consumes PER-SHARD candidate
    rows — a host loop slicing the candidate table to data-dependent
    per-shard offsets before a registered jitted entry point is the
    recompile storm at mesh scale (one compile per ragged shard width).
    The shipped shape — the whole [D, C] table into ONE program, shard
    ownership resolved inside — stays silent."""
    waves_py = os.path.join(PKG_DIR, "engine", "waves.py")
    bad = tmp_path / "ragged_reduce.py"
    bad.write_text(textwrap.dedent("""
        from kubernetes_tpu.engine.waves import waves_loop

        def combine(shard_offs, cls_arr, nodes, state, pc, ctr, prios):
            out = []
            for d in range(len(shard_offs) - 1):
                lo, hi = shard_offs[d], shard_offs[d + 1]
                out.append(waves_loop(cls_arr, nodes, state, pc[lo:hi],
                                      ctr, prios))
            return out
    """))
    findings, _sup, errors = run_paths([waves_py, str(bad)],
                                       rules=["GL003"])
    assert not errors, errors
    assert any(f.rule == "GL003" and "combine" in f.context
               for f in findings), findings
    good = tmp_path / "whole_table_reduce.py"
    good.write_text(textwrap.dedent("""
        from kubernetes_tpu.engine.waves import waves_loop

        def combine(cls_arr, nodes, state, pc_all, ctr, prios):
            # one program over the WHOLE padded table; shard ownership is
            # the device program's job (waves_loop spmd_mesh), never a
            # host-side ragged slice
            return waves_loop(cls_arr, nodes, state, pc_all, ctr, prios)
    """))
    findings, _sup, errors = run_paths([waves_py, str(good)],
                                       rules=["GL003"])
    assert not errors, errors
    assert not [f for f in findings if f.rule == "GL003"
                and "whole_table_reduce" in f.path], findings


def test_gl002_flight_recorder_stays_host_pure(tmp_path):
    """ISSUE 13: the flight recorder's emit sites record TIMESTAMPS and
    host ints already in hand — they never fetch a device value (one
    unblessed fetch per wave "to log it" would serialize the pipeline at
    the dispatch seam, the exact hazard the overlap story rests on). The
    registry built over the REAL engine sources must produce ZERO GL002
    findings over the observability modules; a recorder-shaped consumer
    that DOES fetch a jitted result to populate an event fires — the
    silence is the recorder's purity, not the rule going blind."""
    import ast

    from kubernetes_tpu.analysis.rules.base import ProjectIndex

    eng_py = os.path.join(PKG_DIR, "engine", "scheduler_engine.py")
    waves_py = os.path.join(PKG_DIR, "engine", "waves.py")
    obs_files = [
        os.path.join(PKG_DIR, "observability", "recorder.py"),
        os.path.join(PKG_DIR, "observability", "registry.py"),
        os.path.join(PKG_DIR, "observability", "perfetto.py"),
    ]
    # scan sanity: an empty jit registry would pass vacuously
    index = ProjectIndex()
    for src in (eng_py, waves_py):
        with open(src, "r", encoding="utf-8") as fh:
            index.scan(ast.parse(fh.read()))
    assert "waves_loop" in index.jitted_names
    findings, _sup, errors = run_paths([eng_py, waves_py] + obs_files,
                                       rules=["GL002"])
    assert not errors, errors
    tainted = [f for f in findings
               if any(os.path.basename(o) in f.path for o in obs_files)]
    assert not tainted, tainted
    # negative control: an event emission that fetches the jitted packed
    # result to fill its fields fires GL002
    bad = tmp_path / "bad_recorder_emit.py"
    bad.write_text(textwrap.dedent("""
        import numpy as np
        from kubernetes_tpu.engine.waves import waves_loop
        from kubernetes_tpu.observability.recorder import HARVEST, RECORDER

        def record_wave(cls_arr, nodes, state, pc, ctr, prios):
            packed, _st = waves_loop(cls_arr, nodes, state, pc, ctr,
                                     prios)
            fetched = np.asarray(packed)
            RECORDER.record(HARVEST, a=int(fetched[0]))
            return fetched
    """))
    findings, _sup, errors = run_paths([waves_py, str(bad)],
                                       rules=["GL002"])
    assert not errors, errors
    assert any(f.rule == "GL002" and "record_wave" in f.context
               for f in findings), findings
    # the shipped shape — timestamps + host ints, no device touch — is
    # silent even when it calls the jitted entry point in the same scope
    good = tmp_path / "good_recorder_emit.py"
    good.write_text(textwrap.dedent("""
        import time
        from kubernetes_tpu.engine.waves import waves_loop
        from kubernetes_tpu.observability.recorder import DISPATCH, RECORDER

        def record_wave(cls_arr, nodes, state, pc, ctr, prios, n):
            t0 = time.monotonic()
            packed, _st = waves_loop(cls_arr, nodes, state, pc, ctr,
                                     prios)
            if RECORDER.enabled:
                RECORDER.record(DISPATCH, t0=t0,
                                dur=time.monotonic() - t0, a=n)
            return packed
    """))
    findings, _sup, errors = run_paths([waves_py, str(good)],
                                       rules=["GL002"])
    assert not errors, errors
    assert not [f for f in findings if "good_recorder_emit" in f.path], \
        findings

def test_gl002_podtrace_slo_seams_stay_host_pure(tmp_path):
    """ISSUE 15: the pod tracer and SLO engine stamp TIMESTAMPS and host
    ints already in hand — a per-pod trace that fetched a device value
    to fill an event would serialize the pipeline at every sampled pod,
    the exact GL002 hazard at per-pod (not per-wave) cadence. The
    registry built over the REAL engine sources produces ZERO GL002
    findings over podtrace/slo/trend; a trace consumer that fetches the
    jitted packed result to stamp a timeline FIRES (the silence is the
    tracer's purity, not the rule going blind)."""
    import ast

    from kubernetes_tpu.analysis.rules.base import ProjectIndex

    eng_py = os.path.join(PKG_DIR, "engine", "scheduler_engine.py")
    waves_py = os.path.join(PKG_DIR, "engine", "waves.py")
    obs_files = [
        os.path.join(PKG_DIR, "observability", "podtrace.py"),
        os.path.join(PKG_DIR, "observability", "slo.py"),
        os.path.join(PKG_DIR, "observability", "trend.py"),
    ]
    # scan sanity: an empty jit registry would pass vacuously
    index = ProjectIndex()
    for src in (eng_py, waves_py):
        with open(src, "r", encoding="utf-8") as fh:
            index.scan(ast.parse(fh.read()))
    assert "waves_loop" in index.jitted_names
    findings, _sup, errors = run_paths([eng_py, waves_py] + obs_files,
                                       rules=["GL002"])
    assert not errors, errors
    tainted = [f for f in findings
               if any(os.path.basename(o) in f.path for o in obs_files)]
    assert not tainted, tainted
    # negative control: a per-pod stamp that fetches the jitted packed
    # result to populate its event fields fires GL002
    bad = tmp_path / "bad_podtrace_emit.py"
    bad.write_text(textwrap.dedent("""
        import numpy as np
        from kubernetes_tpu.engine.waves import waves_loop
        from kubernetes_tpu.observability.podtrace import (
            HARVESTED,
            TRACER,
        )

        def trace_wave(cls_arr, nodes, state, pc, ctr, prios, keys):
            packed, _st = waves_loop(cls_arr, nodes, state, pc, ctr,
                                     prios)
            fetched = np.asarray(packed)
            TRACER.batch_event(HARVESTED, keys, a=int(fetched[0]))
            return fetched
    """))
    findings, _sup, errors = run_paths([waves_py, str(bad)],
                                       rules=["GL002"])
    assert not errors, errors
    assert any(f.rule == "GL002" and "trace_wave" in f.context
               for f in findings), findings
    # the shipped shape — keys + wave id + a host timestamp beside the
    # same jitted call — is silent
    good = tmp_path / "good_podtrace_emit.py"
    good.write_text(textwrap.dedent("""
        import time
        from kubernetes_tpu.engine.waves import waves_loop
        from kubernetes_tpu.observability.podtrace import (
            WAVE_DISPATCHED,
            TRACER,
        )

        def trace_wave(cls_arr, nodes, state, pc, ctr, prios, keys, wid):
            packed, _st = waves_loop(cls_arr, nodes, state, pc, ctr,
                                     prios)
            if TRACER.enabled:
                TRACER.batch_event(WAVE_DISPATCHED, keys, a=wid,
                                   t0=time.monotonic())
            return packed
    """))
    findings, _sup, errors = run_paths([waves_py, str(good)],
                                       rules=["GL002"])
    assert not errors, errors
    assert not [f for f in findings if "good_podtrace_emit" in f.path], \
        findings


# --------------------------------------- ISSUE 17: fast-lane eval seam


def test_gl002_registry_covers_fastlane_sample_eval(tmp_path):
    """ISSUE 17: the fast lane's [1, k] sampled eval is a jitted entry
    point (ops/fastlane.sample_eval) — the project-wide registry must
    pick it up from the REAL source so GL002 taint extends to consumers.
    An unblessed fetch here sits INSIDE the sub-10 ms bind path: one
    accidental sync against a busy device queue is the whole budget."""
    import ast

    from kubernetes_tpu.analysis.rules.base import ProjectIndex

    fl_py = os.path.join(PKG_DIR, "ops", "fastlane.py")
    with open(fl_py, "r", encoding="utf-8") as fh:
        index = ProjectIndex()
        index.scan(ast.parse(fh.read()))
    assert "sample_eval" in index.jitted_names
    fixture = tmp_path / "fast_bind.py"
    fixture.write_text(textwrap.dedent("""
        import numpy as np
        from kubernetes_tpu.ops.fastlane import sample_eval

        def fast_bind(idx, req, nodes):
            out = sample_eval(idx, req, False, False, nodes)
            return np.asarray(out)
    """))
    findings, _sup, errors = run_paths([fl_py, str(fixture)],
                                       rules=["GL002"])
    assert not errors, errors
    assert any(f.rule == "GL002" and "fast_bind" in f.context
               for f in findings), findings
    # the blessed fetch — the lane's documented synchronous consume
    # (device dispatched only when idle, so the wait IS the eval)
    fixture.write_text(fixture.read_text().replace(
        "return np.asarray(out)",
        "return np.asarray(out)  # graftlint: sync-ok"))
    findings, _sup, errors = run_paths([fl_py, str(fixture)],
                                       rules=["GL002"])
    assert not errors, errors
    assert not [f for f in findings if "fast_bind" in f.context], \
        findings


def test_gl003_fires_on_ragged_fastlane_sample(tmp_path):
    """ISSUE 17: a data-dependent k-slice feeding the sampled eval would
    mint one XLA compile per distinct candidate count (the GL003 storm,
    paid on the LATENCY path); the fixed-[1, k] shape the lane actually
    dispatches — resampling re-fills the same width — stays silent."""
    fl_py = os.path.join(PKG_DIR, "ops", "fastlane.py")
    bad = tmp_path / "ragged_sample.py"
    bad.write_text(textwrap.dedent("""
        from kubernetes_tpu.ops.fastlane import sample_eval

        def probe(pods, idx, req, nodes):
            out = []
            while pods:
                k = pods.pop()
                out.append(sample_eval(idx[:k], req, False, False,
                                       nodes))
            return out
    """))
    findings, _sup, errors = run_paths([fl_py, str(bad)],
                                       rules=["GL003"])
    assert not errors, errors
    assert any(f.rule == "GL003" and "probe" in f.context
               for f in findings), findings
    good = tmp_path / "fixed_sample.py"
    good.write_text(textwrap.dedent("""
        import numpy as np
        from kubernetes_tpu.ops.fastlane import sample_eval

        def probe(pods, draw, req, nodes, k):
            out = []
            while pods:
                pods.pop()
                idx = np.zeros(k, dtype=np.int32)
                idx[:] = draw(k)
                out.append(sample_eval(idx, req, False, False, nodes))
            return out
    """))
    findings, _sup, errors = run_paths([fl_py, str(good)],
                                       rules=["GL003"])
    assert not errors, errors
    assert not [f for f in findings if f.rule == "GL003"
                and "fixed_sample" in f.path], findings


# ---------------------- ISSUE 19: the concurrency family (GL006-GL009)


GL006_INVERSION = """
    import threading

    class Cell:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def forward(self):
            with self._a:
                with self._b:
                    pass

        def backward(self):
            with self._b:
                with self._a:
                    pass
"""


def test_gl006_fires_on_two_lock_inversion(tmp_path):
    """The deliberate ABBA reintroduction: both nesting directions exist,
    so BOTH observed edges sit on the cycle and each site fires."""
    fs = lint_src(tmp_path, GL006_INVERSION, rules=["GL006"])
    assert rules_of(fs) == ["GL006", "GL006"]
    assert all("lock-order cycle" in f.message for f in fs)
    assert "'Cell._a'" in fs[0].message and "'Cell._b'" in fs[0].message


def test_gl006_silent_on_consistent_order(tmp_path):
    fs = lint_src(tmp_path, """
        import threading

        class Cell:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a:
                    with self._b:
                        pass

            def also_forward(self):
                with self._a:
                    with self._b:
                        pass
    """, rules=["GL006"])
    assert fs == []


def test_gl006_cycle_across_files(tmp_path):
    """One direction per FILE: the cycle only exists project-wide, which
    is exactly what the prepare() pass-1.5 graph is for."""
    (tmp_path / "fwd.py").write_text(textwrap.dedent("""
        import threading

        class Cell:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a:
                    with self._b:
                        pass
    """))
    (tmp_path / "bwd.py").write_text(textwrap.dedent("""
        import threading

        class Cell:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def backward(self):
                with self._b:
                    with self._a:
                        pass
    """))
    findings, _s, errors = run_paths([str(tmp_path)], rules=["GL006"])
    assert not errors
    assert len(findings) == 2  # one per participating site, per file
    assert {os.path.basename(f.path) for f in findings} == \
        {"fwd.py", "bwd.py"}


def test_gl006_declared_order_catches_lone_inversion(tmp_path):
    """A `lock-order(...)` declaration blesses A->B project-wide, so a
    single B->A nesting fires even though the forward `with` nesting is
    never written anywhere."""
    fs = lint_src(tmp_path, """
        import threading

        # graftlint: lock-order(Cell._a,Cell._b)

        class Cell:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def backward(self):
                with self._b:
                    with self._a:
                        pass
    """, rules=["GL006"])
    assert rules_of(fs) == ["GL006"]
    assert "declared lock-order" in fs[0].message


def test_gl006_fires_on_self_deadlock_and_spares_rlock(tmp_path):
    bad = lint_src(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def work(self):
                with self._lock:
                    with self._lock:
                        pass
    """, rules=["GL006"])
    assert rules_of(bad) == ["GL006"]
    assert "re-acquiring non-reentrant" in bad[0].message
    good = lint_src(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.RLock()

            def work(self):
                with self._lock:
                    with self._lock:
                        pass
    """, name="good.py", rules=["GL006"])
    assert good == []


def test_gl006_lock_ok_pragma_blesses_site(tmp_path):
    fs = lint_src(tmp_path, """
        import threading

        class Cell:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a:
                    with self._b:  # graftlint: lock-ok
                        pass

            def backward(self):
                with self._b:
                    with self._a:  # graftlint: lock-ok
                        pass
    """, rules=["GL006"])
    assert fs == []


# ------------------------------------------------------------------- GL007


GL007_TORN_COUNTER = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._v = 0

        def inc(self):
            with self._lock:
                self._v += 1

        def reset(self):
            self._v = 0

        def peek(self):
            return self._v
"""


def test_gl007_fires_on_torn_counter_regression(tmp_path):
    """The r18 metrics-audit regression, reintroduced deliberately: one
    guarded writer, one STRAY unguarded write and one bare read — the
    stray write must not demote the field (it IS the bug), and both
    unguarded accesses fire."""
    fs = lint_src(tmp_path, GL007_TORN_COUNTER, rules=["GL007"])
    assert rules_of(fs) == ["GL007", "GL007"]
    assert "torn write" in fs[0].message  # reset
    assert "torn read" in fs[1].message   # peek


def test_gl007_silent_when_guarded_or_locked_helper(tmp_path):
    fs = lint_src(tmp_path, """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._v = 0

            def inc(self):
                with self._lock:
                    self._inc_locked()

            def _inc_locked(self):
                self._v += 1

            def peek(self):
                with self._lock:
                    return self._v
    """, rules=["GL007"])
    assert fs == []


def test_gl007_torn_ok_pragma_blesses_stale_read(tmp_path):
    fs = lint_src(tmp_path, """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._v = 0

            def inc(self):
                with self._lock:
                    self._v += 1

            def peek(self):
                # single int, CPython store is atomic; staleness is fine
                # for a monitoring read. graftlint: torn-ok
                return self._v
    """, rules=["GL007"])
    assert fs == []


def test_gl007_ignores_unguarded_fields(tmp_path):
    """A field NEVER written under the lock belongs to some other
    discipline (a loop-owned field, a config constant) — not GL007's."""
    fs = lint_src(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.mode = "idle"

            def flip(self):
                self.mode = "busy"

            def show(self):
                return self.mode
    """, rules=["GL007"])
    assert fs == []


# ------------------------------------------------------------------- GL008


def test_gl008_fires_on_blocking_shapes_in_async_def(tmp_path):
    fs = lint_src(tmp_path, """
        import socket
        import threading
        import time

        class Srv:
            def __init__(self):
                self._lock = threading.Lock()

            async def handle(self, sock):
                time.sleep(0.01)
                with self._lock:
                    pass
                data = sock.recv(4)
                self._lock.acquire()
                return data
    """, rules=["GL008"])
    assert rules_of(fs) == ["GL008"] * 4
    msgs = "\n".join(f.message for f in fs)
    assert "time.sleep" in msgs
    assert "threading lock self._lock" in msgs
    assert ".recv()" in msgs


def test_gl008_fires_on_device_sync_in_async_def(tmp_path):
    fs = lint_src(tmp_path, """
        import jax
        import numpy as np

        @jax.jit
        def kernel(x):
            return x

        class Srv:
            async def pump(self, xs):
                out = kernel(xs)
                return np.asarray(out)
    """, rules=["GL008"])
    assert rules_of(fs) == ["GL008"]
    assert "device->host sync" in fs[0].message


def test_gl008_silent_on_async_twins_and_executor_hop(tmp_path):
    """The blessed shapes: await asyncio.sleep, and blocking work INSIDE
    the lambda handed to run_in_executor — that body runs on a worker
    thread, not the loop (asyncwire's actual idiom)."""
    fs = lint_src(tmp_path, """
        import asyncio
        import threading
        import time

        class Srv:
            def __init__(self):
                self._lock = threading.Lock()

            def _sync_work(self):
                with self._lock:
                    time.sleep(0.001)

            async def handle(self, loop):
                await asyncio.sleep(0.01)
                await loop.run_in_executor(None, lambda: self._sync_work())
    """, rules=["GL008"])
    assert fs == []


def test_gl008_block_ok_pragma_blesses_tiny_section(tmp_path):
    fs = lint_src(tmp_path, """
        import threading

        class Srv:
            def __init__(self):
                self._lock = threading.Lock()

            async def handle(self):
                with self._lock:  # graftlint: block-ok
                    pass
    """, rules=["GL008"])
    assert fs == []


# ------------------------------------------------------------------- GL009


def test_gl009_fires_on_lambda_and_bound_method_targets(tmp_path):
    fs = lint_src(tmp_path, """
        import multiprocessing as mp
        import threading

        class Owner:
            def __init__(self):
                self._lock = threading.Lock()

            def run(self):
                pass

            def boot(self):
                a = mp.Process(target=lambda: None)
                b = mp.Process(target=self.run)
                return a, b
    """, rules=["GL009"])
    assert rules_of(fs) == ["GL009", "GL009"]
    assert "lambda" in fs[0].message
    assert "bound method" in fs[1].message and "_lock" in fs[1].message


def test_gl009_fires_on_module_state_capture_and_global_write(tmp_path):
    fs = lint_src(tmp_path, """
        import multiprocessing as mp
        import threading

        _TABLE = {}
        _LOCK = threading.Lock()
        _TOTAL = 0

        def worker(cfg):
            global _TOTAL
            with _LOCK:
                _TABLE[cfg] = 1
                _TOTAL += 1

        def boot():
            p = mp.Process(target=worker, args=(1,))
            p.start()
    """, rules=["GL009"])
    msgs = "\n".join(f.message for f in fs)
    assert "_TABLE" in msgs and "mutable state" in msgs
    assert "_LOCK" in msgs and "synchronizes nothing" in msgs
    assert "_TOTAL" in msgs and "CHILD's module" in msgs


def test_gl009_silent_on_picklable_config_worker(tmp_path):
    """The multiproc.py discipline: a module-level def handed everything
    through picklable args; module CONSTANTS (ints, strings, compiled
    regexes) are not hazards."""
    fs = lint_src(tmp_path, """
        import multiprocessing as mp
        import re

        _OWNER_RE = re.compile(r"owner=(\\w+)")
        MAX_EVENTS = 4096

        def worker(cfg, queue):
            n = min(cfg["n"], MAX_EVENTS)
            m = _OWNER_RE.match(cfg["line"])
            queue.put((n, m and m.group(1)))

        def boot(q):
            ctx = mp.get_context("spawn")
            p = ctx.Process(target=worker, args=({"n": 1, "line": ""}, q))
            p.start()
            return p
    """, rules=["GL009"])
    assert fs == []


def test_gl009_spawn_ok_pragma_blesses_readonly_table(tmp_path):
    fs = lint_src(tmp_path, """
        import multiprocessing as mp

        _CANNED = {"a": 1}

        def worker(q):
            # import-time-frozen table, mutated nowhere: the child's copy
            # is identical by construction. graftlint: spawn-ok
            q.put(_CANNED["a"])

        def boot(q):
            return mp.Process(target=worker, args=(q,))
    """, rules=["GL009"])
    assert fs == []


# --------------------------------------- concurrency family CLI plumbing


def test_cli_selective_concurrency_rules_exit_codes(tmp_path):
    """`--rules GL006,GL007,GL008,GL009` is the concurrency-only
    invocation: exit 1 on a torn counter, exit 0 once it is clean, and
    the same file keeps exit 0 when only OTHER rules are selected."""
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(GL007_TORN_COUNTER))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    conc = ["--rules", "GL006,GL007,GL008,GL009"]
    r = subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu.analysis", *conc, str(bad)],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(PKG_DIR))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "GL007" in r.stdout
    r2 = subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu.analysis",
         "--rules", "GL001,GL002", str(bad)],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(PKG_DIR))
    assert r2.returncode == 0, r2.stdout + r2.stderr
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    r3 = subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu.analysis", *conc,
         str(good)],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(PKG_DIR))
    assert r3.returncode == 0, r3.stdout + r3.stderr


def test_cli_json_carries_by_rule_counters(tmp_path):
    from kubernetes_tpu.analysis.__main__ import main
    import io
    from contextlib import redirect_stdout

    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(GL007_TORN_COUNTER))
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = main(["--rules", "GL006,GL007", "--json", str(bad)])
    data = json.loads(buf.getvalue())
    assert rc == 1
    assert data["by_rule"] == {"GL006": 0, "GL007": 2}
    full = io.StringIO()
    with redirect_stdout(full):
        main(["--json", str(bad)])
    data = json.loads(full.getvalue())
    assert set(data["by_rule"]) == {f"GL00{i}" for i in range(1, 10)}
    assert data["by_rule"]["GL007"] == 2


def test_list_rules_documents_concurrency_family(tmp_path):
    from kubernetes_tpu.analysis.__main__ import main
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert main(["--list-rules"]) == 0
    out = buf.getvalue()
    for rid in ("GL006", "GL007", "GL008", "GL009"):
        assert rid in out, out


def test_gl007_baseline_fingerprint_survives_line_drift(tmp_path):
    """A baselined GL007 finding keeps suppressing after edits ABOVE it
    shift every line number — fingerprints anchor on qualname+message."""
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent(GL007_TORN_COUNTER))
    findings, _s, _e = run_paths([str(f)], rules=["GL007"])
    assert len(findings) == 2
    bpath = tmp_path / "b.json"
    write_baseline(str(bpath), findings)
    f.write_text("# a new header comment\n# another\n\n"
                 + textwrap.dedent(GL007_TORN_COUNTER))
    findings2, sup, _e = run_paths([str(f)], rules=["GL007"],
                                   baseline=load_baseline(str(bpath)))
    assert findings2 == [] and sup == 2


def test_lint_gate_refuses_concurrency_dirty_tree(tmp_path):
    """`bench --lint-gate` refuses a tree carrying a torn counter or a
    lock-order hazard the same way it refuses an aliasing upload."""
    (tmp_path / "bad.py").write_text(textwrap.dedent(GL007_TORN_COUNTER))
    ok, report = lint_gate(str(tmp_path))
    assert not ok and "GL007" in report


# ----------------------------------------------------- federation (ISSUE 20)


def test_gl002_registry_covers_federation_route_scores(tmp_path):
    """ISSUE 20: the router's fused [C, M] scoring seam
    (ops/federation.route_scores) is a module-level jit bind — the
    project-wide registry must pick it up from the REAL source so GL002
    taint extends to consumers. An unblessed fetch of the routing
    verdict sits on the admission path: one accidental sync per batch
    is the router's whole sub-10 ms budget."""
    import ast

    from kubernetes_tpu.analysis.rules.base import ProjectIndex

    fed_py = os.path.join(PKG_DIR, "ops", "federation.py")
    with open(fed_py, "r", encoding="utf-8") as fh:
        index = ProjectIndex()
        index.scan(ast.parse(fh.read()))
    assert "route_scores" in index.jitted_names, \
        "route_scores missing from the jit registry"
    fixture = tmp_path / "route_batch.py"
    fixture.write_text(textwrap.dedent("""
        import numpy as np
        from kubernetes_tpu.ops.federation import route_scores

        def route_batch(dc, dm, cf, mf, cc, mc, pr, rd, dok):
            out = route_scores(dc, dm, cf, mf, cc, mc, pr, rd, dok)
            return np.asarray(out)
    """))
    findings, _sup, errors = run_paths([fed_py, str(fixture)],
                                       rules=["GL002"])
    assert not errors, errors
    assert any(f.rule == "GL002" and "route_batch" in f.context
               for f in findings), findings
    # the blessed fetch — the ONE routing-verdict transfer per batch
    # (the stacked [2, C] output exists exactly so there is one)
    fixture.write_text(fixture.read_text().replace(
        "return np.asarray(out)",
        "return np.asarray(out)  # graftlint: sync-ok"))
    findings, _sup, errors = run_paths([fed_py, str(fixture)],
                                       rules=["GL002"])
    assert not errors, errors
    assert not [f for f in findings if "route_batch" in f.context], \
        findings


def test_federation_pad_to_bucket_idiom_stays_silent(tmp_path):
    """The router's pad-to-bucket shape bounding (host-side np.pad of
    the C axis BEFORE the dispatch, trim after the blessed fetch) is
    the documented GL003 escape hatch one level up — the full rule set
    must stay silent on it."""
    fed_py = os.path.join(PKG_DIR, "ops", "federation.py")
    fixture = tmp_path / "padded_route.py"
    fixture.write_text(textwrap.dedent("""
        import numpy as np
        from kubernetes_tpu.ops.federation import route_scores
        from kubernetes_tpu.ops.predicates import bucket

        def padded_route(dc, dm, cf, mf, cc, mc, pr, rd, dok):
            c = len(dc)
            cb = bucket(c)
            if cb != c:
                pad = cb - c
                dc = np.pad(dc, (0, pad))
                dm = np.pad(dm, (0, pad))
                dok = np.pad(dok, ((0, pad), (0, 0)),
                             constant_values=True)
            out = route_scores(dc, dm, cf, mf, cc, mc, pr, rd, dok)
            verdict = np.asarray(out)  # graftlint: sync-ok
            return verdict[:, :c]
    """))
    findings, _sup, errors = run_paths([fed_py, str(fixture)])
    assert not errors, errors
    assert not [f for f in findings if "padded_route" in f.context], \
        findings
